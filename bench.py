"""Benchmark: reach-timesteps/sec/chip for the Muskingum-Cunge routing forward pass.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md), so ``vs_baseline`` is
measured against an in-process re-creation of the reference's CPU execution path
(torch + scipy spsolve_triangular per timestep, the same algorithm as
/root/reference/src/ddr/routing/mmc.py:415-441 + utils.py:535-627) on the same
synthetic network, extrapolated per reach-timestep. Run on the TPU chip when present.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _synthetic(n: int, t_hours: int, seed: int = 0):
    from ddr_tpu.geodatazoo.synthetic import make_basin

    basin = make_basin(n_segments=n, n_gauges=8, n_days=max(2, t_hours // 24), seed=seed)
    return basin


def bench_tpu(n: int = 8192, t_hours: int = 720) -> float:
    """Returns reach-timesteps/sec for the jitted forward route."""
    import jax
    import jax.numpy as jnp

    from ddr_tpu.routing.mc import route
    from ddr_tpu.routing.model import prepare_batch
    from ddr_tpu.validation.configs import Config

    cfg = Config(name="bench", geodataset="synthetic", mode="routing", kan={"input_var_names": ["a"]})
    basin = _synthetic(n, t_hours)
    network, channels, gauges = prepare_batch(
        basin.routing_data, cfg.params.attribute_minimums["slope"]
    )
    params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
    q_prime = jnp.asarray(basin.q_prime[:t_hours])

    fn = jax.jit(lambda qp: route(network, channels, params, qp, gauges=gauges).runoff)
    fn(q_prime).block_until_ready()  # compile
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        fn(q_prime).block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    return n * t_hours / dt


def bench_reference_cpu(n: int = 2048, t_hours: int = 24) -> float:
    """Reference-equivalent CPU path: torch elementwise physics + scipy triangular
    solve per timestep (float64, like /root/reference/src/ddr/routing/utils.py:590-596)."""
    import scipy.sparse as sp
    import torch
    from scipy.sparse.linalg import spsolve_triangular

    basin = _synthetic(n, t_hours, seed=1)
    rd = basin.routing_data
    rows, cols = rd.adjacency_rows, rd.adjacency_cols
    N_mat = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
    eye = sp.eye(n, format="csr")

    length = torch.tensor(rd.length)
    slope = torch.tensor(np.maximum(rd.slope, 1e-3))
    x = torch.tensor(rd.x)
    n_mann = torch.tensor(basin.true_params["n"])
    q_sp = torch.tensor(basin.true_params["q_spatial"])
    p_sp = torch.tensor(basin.true_params["p_spatial"])
    q_prime = torch.tensor(basin.q_prime[:t_hours].astype(np.float64))

    def step(q_t):
        qe = q_sp + 1e-6
        depth = torch.clamp(
            ((q_t * n_mann * (qe + 1)) / (p_sp * slope**0.5 + 1e-8)) ** (3.0 / (5.0 + 3.0 * qe)),
            min=0.01,
        )
        tw = p_sp * depth**qe
        ss = torch.clamp(tw * qe / (2 * depth), 0.5, 50.0)
        bw = torch.clamp(tw - 2 * ss * depth, min=0.01)
        area = (tw + bw) * depth / 2
        wp = bw + 2 * depth * torch.sqrt(1 + ss**2)
        v = (1 / n_mann) * (area / wp) ** (2 / 3) * slope**0.5
        c = torch.clamp(v, 0.01, 15.0) * 5 / 3
        k = length / c
        denom = 2 * k * (1 - x) + 3600.0
        c1 = (3600.0 - 2 * k * x) / denom
        c2 = (3600.0 + 2 * k * x) / denom
        c3 = (2 * k * (1 - x) - 3600.0) / denom
        c4 = 2 * 3600.0 / denom
        i_t = torch.tensor(N_mat @ q_t.numpy())
        b = c2 * i_t + c3 * q_t + c4 * torch.clamp(q_prime[0], min=1e-4)
        A = eye - sp.diags(c1.numpy()) @ N_mat
        sol = spsolve_triangular(A.tocsr(), b.numpy(), lower=True)
        return torch.clamp(torch.tensor(sol), min=1e-4)

    q_t = torch.clamp(torch.tensor(np.linalg.norm(basin.q_prime[0]) * np.ones(n)), min=1e-4)
    step(q_t)  # warm
    t0 = time.perf_counter()
    for _ in range(t_hours):
        q_t = step(q_t)
    dt = time.perf_counter() - t0
    return n * t_hours / dt


def main() -> None:
    tpu_rts = bench_tpu()
    ref_rts = bench_reference_cpu()
    print(
        json.dumps(
            {
                "metric": "reach-timesteps/sec/chip (synthetic 8192-reach network, 720h forward route)",
                "value": round(tpu_rts, 1),
                "unit": "reach-timesteps/s",
                "vs_baseline": round(tpu_rts / ref_rts, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
