"""Benchmark: reach-timesteps/sec/chip for the Muskingum-Cunge routing forward pass.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} and ALWAYS exits 0.

Architecture: the parent process never imports jax. Each phase (accelerator probe,
route benchmark, CPU reference baseline) runs in a subprocess with a timeout, so a
wedged TPU tunnel — which *hangs* backend init rather than raising (round-1 failure:
BENCH_r01.json rc=1 "Unable to initialize backend 'axon'") — can never prevent the
JSON payload from being emitted. If the accelerator probe fails or times out, the
route benchmark reruns on CPU (tunnel registration disabled) at reduced shapes.

The reference publishes no throughput numbers (BASELINE.md), so ``vs_baseline`` is
measured against an in-process re-creation of the reference's CPU execution path
(torch elementwise physics + scipy spsolve_triangular per timestep, the same
algorithm as /root/reference/src/ddr/routing/mmc.py:415-441 + utils.py:535-627,
including the PatternMapper values-only CSR update of utils.py:89-102) on the same
synthetic network generator, normalized per reach-timestep.

Round 3 adds the CONUS-realistic topology phase: the headline metric stays on the
legacy shallow generator (cross-round comparability), and a second measurement
(``deep_value``/``deep_metric``) routes a deep network (longest-path depth in the
thousands, like continental MERIT) through whatever engine
``build_routing_network`` auto-selects — the depth-chunked wavefront at these
shapes — so the recorded number exercises the flagship-topology path, not the
shallow best case.

Env knobs: DDR_BENCH_N / DDR_BENCH_T (shapes), DDR_BENCH_DEEP_N /
DDR_BENCH_DEEP_DEPTH (deep-topology phase; 0 disables it), DDR_BENCH_PROBE_TIMEOUT /
DDR_BENCH_TIMEOUT (seconds, accelerator probe / each benchmark subprocess),
DDR_BENCH_KERNEL / DDR_BENCH_DTYPE (the routing wave-scan implementation and
compute dtype — the fused-Pallas-kernel and bf16 axes of
``ddr_tpu.routing.mc.route``; recorded as ``kernel`` / ``compute_dtype`` so
the regression gate pairs records by dtype). JAX_PLATFORMS=cpu skips the
accelerator probe entirely (CPU-only rounds go straight to the fallback
shapes instead of waiting out the probe timeout); the probe timeout now
defaults to 120 s — early driver rounds burned 15 minutes timing out a
wedged tunnel before the CPU fallback — and is recorded as
``probe_timeout_s``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

DEFAULT_N = 8192
DEFAULT_T = 240
CPU_FALLBACK_N = 2048
CPU_FALLBACK_T = 48
# Deep-topology phase defaults (the CONUS-shaped regime: depth in the thousands).
DEEP_N = 262144
DEEP_DEPTH = 2048
# CPU fallback still exercises the depth-chunked path: depth > the single-ring
# cap (1024), so build_routing_network cannot select the single-ring engine.
CPU_DEEP_N = 4096
CPU_DEEP_DEPTH = 1536

#: Accelerator-probe timeout default, seconds. Well under the old 900 s: every
#: driver round so far spent the full probe window on a wedged tunnel before
#: falling back to CPU — 2 minutes is ample for a healthy backend to init.
DEFAULT_PROBE_TIMEOUT = 120.0


def _kernel_dtype() -> tuple[str | None, str]:
    """The routing kernel/dtype axes a bench child runs with
    (DDR_BENCH_KERNEL / DDR_BENCH_DTYPE; None = auto-select)."""
    kernel = os.environ.get("DDR_BENCH_KERNEL") or None
    dtype = os.environ.get("DDR_BENCH_DTYPE") or "fp32"
    return kernel, dtype


def _synthetic(n: int, t_hours: int, seed: int = 0, depth: int | None = None):
    from ddr_tpu.geodatazoo.synthetic import make_basin

    return make_basin(
        n_segments=n, n_gauges=8, n_days=max(2, -(-t_hours // 24)), seed=seed, depth=depth
    )


def _bench_setup(n: int, t_hours: int, depth: int | None = None):
    """Shared benchmark inputs: (network, channels, gauges, params, q_prime)."""
    import jax.numpy as jnp

    from ddr_tpu.routing.model import prepare_batch
    from ddr_tpu.validation.configs import Config

    cfg = Config(name="bench", geodataset="synthetic", mode="routing", kan={"input_var_names": ["a"]})
    basin = _synthetic(n, t_hours, depth=depth)
    network, channels, gauges = prepare_batch(
        basin.routing_data, cfg.params.attribute_minimums["slope"]
    )
    params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
    q_prime = jnp.asarray(basin.q_prime[:t_hours])
    return network, channels, gauges, params, q_prime


def _timed_rate(fn, arg, n: int, t_hours: int) -> float:
    """Compile once, then queue all reps and block once: a blocking sync through
    the axon tunnel costs ~70ms of poll latency, which is device-idle time, not
    device throughput. Reps scale to ~2s of queued device work (measured at
    N=8192/T=240 on the live chip: 5 reps still reads 38% low because the fixed
    poll latency is comparable to the 19ms route itself; 1-ms-route shapes need
    ~50 queued to amortize it, while a 15s deep route needs no amortizing)."""
    import jax

    est0 = time.perf_counter()
    jax.block_until_ready(fn(arg))  # compile + one timed run (upper-bounds est)
    est = time.perf_counter() - est0
    t0 = time.perf_counter()
    jax.block_until_ready(fn(arg))
    est = min(est, time.perf_counter() - t0)  # post-compile single-run estimate
    reps = max(3, min(50, int(2.0 / max(est, 1e-3))))
    t0 = time.perf_counter()
    outs = [fn(arg) for _ in range(reps)]
    jax.block_until_ready(outs)
    dt = (time.perf_counter() - t0) / reps
    return n * t_hours / dt


def _card_suffix(compiled) -> str:
    """`` key=value`` tokens appended to a bench child's output line: the HBM
    peak (device ``memory_stats`` where the backend reports it, the compiled
    program's ``memory_analysis()`` estimate otherwise — so CPU rounds stop
    recording ``peak_hbm_gb: null``) plus the card-derived roofline fields
    (``flops=``, ``bytes=``, ``collectives=<compact json>``)."""
    from ddr_tpu.observability.costs import card_from_compiled, peak_bytes_or_envelope

    kernel, dtype = _kernel_dtype()
    card = None
    try:
        card = card_from_compiled(
            compiled, name="bench", kernel=kernel, compute_dtype=dtype
        )
    except Exception:
        pass
    peak = peak_bytes_or_envelope(compiled=compiled, card=card)
    tokens = []
    if peak is not None:
        tokens.append(f"peak_gb={peak / 2**30:.4f}")
    if card is not None:
        if card.flops is not None:
            tokens.append(f"flops={card.flops:.6g}")
        if card.bytes_accessed is not None:
            tokens.append(f"bytes={card.bytes_accessed:.6g}")
        # always emitted (all-zero included): a record that says "zero
        # collectives" is distinguishable from one with no card at all
        tokens.append(
            "collectives=" + json.dumps(card.collectives, separators=(",", ":"))
        )
    return (" " + " ".join(tokens)) if tokens else ""


def bench_route(n: int, t_hours: int, depth: int | None = None) -> str:
    """``"<rate>[ key=value...]"`` for the jitted forward route on the active
    backend (AOT-compiled, so the same handle yields the cost-card tokens).

    ``depth`` switches the topology to the deep CONUS-realistic generator;
    prepare_batch's auto-selection then routes it through the depth-chunked
    wavefront (ddr_tpu.routing.chunked)."""
    import jax

    from ddr_tpu.routing.mc import route

    network, channels, gauges, params, q_prime = _bench_setup(n, t_hours, depth=depth)
    kernel, dtype = _kernel_dtype()
    fn = jax.jit(lambda qp: route(
        network, channels, params, qp, gauges=gauges, kernel=kernel, dtype=dtype
    ).runoff)
    compiled = fn.lower(q_prime).compile()
    return f"{_timed_rate(compiled, q_prime, n, t_hours)}{_card_suffix(compiled)}"


def _provenance_suffix(engine: str) -> str:
    """`` engine_source=<src> tuned_plan=<engine>`` selection-provenance
    tokens: where the engine decision came from (the auto-tuner's
    ``policy|scored|probed|cached`` vocabulary — ``policy`` for the
    single-device eligibility-driven auto-selection this bench runs) and what
    plan it resolved to, so the regression gate can flag a planner that
    silently walks a record onto a slower engine."""
    try:
        from ddr_tpu.tuning.planner import last_selection

        sel = last_selection()
        if sel:
            return f" engine_source={sel['source']} tuned_plan={sel['engine']}"
    except Exception:  # provenance is best-effort — the rate is the payload
        pass
    return f" engine_source=policy tuned_plan={engine}"


def bench_route_deep(n: int, t_hours: int, depth: int) -> str:
    """Deep-topology route bench; prints ``"<rate> <engine-label>"`` so the record
    names the engine that ACTUALLY ran (auto-selection may pick the single-ring
    wavefront when the requested depth fits its caps)."""
    import jax

    from ddr_tpu.routing.mc import route
    from ddr_tpu.routing.model import engine_label

    network, channels, gauges, params, q_prime = _bench_setup(n, t_hours, depth=depth)
    engine = engine_label(network)
    kernel, dtype = _kernel_dtype()
    fn = jax.jit(lambda qp: route(
        network, channels, params, qp, gauges=gauges, kernel=kernel, dtype=dtype
    ).runoff)
    compiled = fn.lower(q_prime).compile()
    return (
        f"{_timed_rate(compiled, q_prime, n, t_hours)} {engine}"
        f"{_card_suffix(compiled)}{_provenance_suffix(engine)}"
    )


def bench_grad(n: int, t_hours: int, depth: int | None = None) -> str:
    """``"<rate>[ key=value...]"`` for the full VJP (value_and_grad of a
    gauge-loss route) on the active backend — the training-path throughput.
    ``depth`` switches to the deep CONUS-realistic topology (auto-selected
    engine)."""
    import jax

    from ddr_tpu.routing.mc import route

    network, channels, gauges, params, q_prime = _bench_setup(n, t_hours, depth=depth)
    kernel, dtype = _kernel_dtype()

    def loss(p):
        return route(
            network, channels, p, q_prime, gauges=gauges, kernel=kernel, dtype=dtype
        ).runoff.mean()

    fn = jax.jit(jax.value_and_grad(loss))
    compiled = fn.lower(params).compile()
    return f"{_timed_rate(compiled, params, n, t_hours)}{_card_suffix(compiled)}"


def bench_reference_cpu(n: int = 2048, t_hours: int = 24) -> float:
    """Reference-equivalent CPU path: torch elementwise physics + scipy triangular
    solve per timestep (float64, /root/reference/src/ddr/routing/utils.py:590-596),
    with the CSR sparsity pattern built ONCE and only its values refreshed per step —
    the honest analog of the reference's PatternMapper
    (/root/reference/src/ddr/routing/utils.py:25-129)."""
    import numpy as np
    import scipy.sparse as sp
    import torch
    from scipy.sparse.linalg import spsolve_triangular

    basin = _synthetic(n, t_hours, seed=1)
    rd = basin.routing_data
    rows, cols = rd.adjacency_rows, rd.adjacency_cols
    N_mat = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
    eye = sp.eye(n, format="csr")

    # Pattern probe (once): A = I - diag(c1) @ N has diagonal ones plus -c1[row] at
    # each edge; per step only the data vector is rewritten in CSR order.
    A = (eye - N_mat).tocsr()
    A.sort_indices()
    nz_rows, nz_cols = A.nonzero()
    is_diag = nz_rows == nz_cols

    length = torch.tensor(rd.length)
    slope = torch.tensor(np.maximum(rd.slope, 1e-3))
    x = torch.tensor(rd.x)
    n_mann = torch.tensor(basin.true_params["n"])
    q_sp = torch.tensor(basin.true_params["q_spatial"])
    p_sp = torch.tensor(basin.true_params["p_spatial"])
    q_prime = torch.clamp(
        torch.tensor(basin.q_prime[:t_hours].astype(np.float64)), min=1e-4
    )

    def step(q_t, q_prime_t):
        qe = q_sp + 1e-6
        depth = torch.clamp(
            ((q_t * n_mann * (qe + 1)) / (p_sp * slope**0.5 + 1e-8)) ** (3.0 / (5.0 + 3.0 * qe)),
            min=0.01,
        )
        tw = p_sp * depth**qe
        ss = torch.clamp(tw * qe / (2 * depth), 0.5, 50.0)
        bw = torch.clamp(tw - 2 * ss * depth, min=0.01)
        area = (tw + bw) * depth / 2
        wp = bw + 2 * depth * torch.sqrt(1 + ss**2)
        v = (1 / n_mann) * (area / wp) ** (2 / 3) * slope**0.5
        c = torch.clamp(v, 0.01, 15.0) * 5 / 3
        k = length / c
        denom = 2 * k * (1 - x) + 3600.0
        c1 = (3600.0 - 2 * k * x) / denom
        c2 = (3600.0 + 2 * k * x) / denom
        c3 = (2 * k * (1 - x) - 3600.0) / denom
        c4 = 2 * 3600.0 / denom
        i_t = torch.tensor(N_mat @ q_t.numpy())
        b = c2 * i_t + c3 * q_t + c4 * q_prime_t
        c1_np = c1.numpy()
        A.data = np.where(is_diag, 1.0, -c1_np[nz_rows])
        sol = spsolve_triangular(A, b.numpy(), lower=True)
        return torch.clamp(torch.tensor(sol), min=1e-4)

    # Physical cold start: hotstart accumulation (I - N) q0 = q'_0, the reference's
    # compute_hotstart_discharge (/root/reference/src/ddr/routing/mmc.py:25-66).
    # A still holds the I - N values here (first rewritten in the warm step below).
    q0 = spsolve_triangular(A, q_prime[0].numpy(), lower=True)
    q_t = torch.clamp(torch.tensor(q0), min=1e-4)
    step(q_t, q_prime[0])  # warm
    t0 = time.perf_counter()
    for t in range(t_hours):
        q_t = step(q_t, q_prime[t])
    dt = time.perf_counter() - t0
    return n * t_hours / dt


# ---------------------------------------------------------------------------
# Subprocess harness (parent never imports jax; a hung tunnel cannot block it).
# ---------------------------------------------------------------------------

_CPU_ENV = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}


def _run_child(code: str, timeout: float, cpu_only: bool) -> tuple[str | None, str]:
    """Run a python snippet in a subprocess; returns (last stdout line, error)."""
    env = dict(os.environ)
    if cpu_only:
        env.update(_CPU_ENV)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timed out after {timeout:.0f}s"
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if proc.returncode != 0:
        tail = proc.stderr.strip().splitlines()[-1] if proc.stderr.strip() else "no stderr"
        return None, f"rc={proc.returncode}: {tail}"
    return (lines[-1] if lines else None), ""


#: Card tokens a bench child may append (``_card_suffix``) -> record-field
#: suffix in the parent's JSON.
_CARD_TOKEN_FIELDS = {"flops": "flops", "bytes": "bytes_accessed", "collectives": "collectives"}

#: Selection-provenance tokens (``_provenance_suffix``): plain strings.
_STR_TOKENS = ("engine_source", "tuned_plan")


def _split_tokens(val: str) -> tuple[str, dict]:
    """Strip the trailing `` key=value`` tokens a bench child appends
    (``_card_suffix`` / ``_provenance_suffix``); returns ``(rest, tokens)``
    with ``peak_gb``/``flops``/``bytes`` parsed as floats, ``collectives`` as
    its dict, and the provenance tokens as strings. Malformed tokens are
    dropped (best-effort — the rate is the payload)."""
    kept, toks = [], {}
    for t in val.split():
        key, sep, raw = t.partition("=")
        if not sep or key not in ("peak_gb", *_CARD_TOKEN_FIELDS, *_STR_TOKENS):
            kept.append(t)
            continue
        if key in _STR_TOKENS:
            toks[key] = raw
            continue
        try:
            toks[key] = json.loads(raw) if key == "collectives" else float(raw)
        except (ValueError, json.JSONDecodeError):
            pass
    return " ".join(kept), toks


def _store_card_tokens(out: dict, toks: dict, prefix: str = "") -> None:
    """Record the card-derived fields of one phase (``flops``,
    ``bytes_accessed``, ``collectives``; prefixed for non-headline phases)."""
    for token, field in _CARD_TOKEN_FIELDS.items():
        if token in toks:
            out[f"{prefix}{field}"] = toks[token]


def _record_float(out: dict, key: str, code: str, timeout: float, cpu_only: bool,
                  metric_key: str | None = None, metric: str | None = None,
                  peak_key: str | None = None, card_prefix: str | None = None) -> None:
    """Best-effort phase plumbing shared by the grad/deep/deep-grad extras: run
    the child, parse its last line as a float into ``out[key]`` (recording any
    ``peak_gb=`` token under ``peak_key`` and card tokens under
    ``card_prefix``), or record ``out[key + "_error"]`` — never fatal to the
    headline record."""
    val, err = _run_child(code, timeout, cpu_only)
    if val is None:
        out[key + "_error"] = err
        return
    val, toks = _split_tokens(val)
    try:
        out[key] = round(float(val), 1)
    except ValueError:
        out[key + "_error"] = f"unparseable output: {val!r}"
        return
    if peak_key:
        out[peak_key] = toks.get("peak_gb")
    if card_prefix is not None:
        _store_card_tokens(out, toks, prefix=card_prefix)
    if metric_key and metric:
        out[metric_key] = metric


_USAGE = """\
usage: bench.py [-h | --help]

Benchmark reach-timesteps/sec/chip for the Muskingum-Cunge routing forward
pass. Prints ONE JSON line and always exits 0. Configure via env vars:
DDR_BENCH_N / DDR_BENCH_T (shapes), DDR_BENCH_DEEP_N / DDR_BENCH_DEEP_DEPTH
(deep-topology phase; 0 disables), DDR_BENCH_PROBE_TIMEOUT / DDR_BENCH_TIMEOUT
(seconds; probe defaults to 120), DDR_BENCH_KERNEL / DDR_BENCH_DTYPE (routing
wave-scan implementation pallas|xla and compute dtype fp32|bf16 — docs/tpu.md
"Fused Pallas kernel & mixed precision"). JAX_PLATFORMS=cpu skips the
accelerator probe (no probe-timeout stall on CPU-only hosts). Set
DDR_METRICS_DIR to also emit the timings as observability JSONL events
(run_log.bench.jsonl, same schema as training — docs/observability.md).
"""


def _open_bench_recorder():
    """Observability JSONL sink when DDR_METRICS_DIR is set (None otherwise).

    Explicit host=0: the observability package is jax-free, and this parent
    process must never import jax (a wedged tunnel would hang it)."""
    events_dir = os.environ.get("DDR_METRICS_DIR")
    if not events_dir:
        return None
    try:
        from ddr_tpu.observability import Recorder

        return Recorder.open_run(events_dir, cmd="bench", host=0, n_hosts=1)
    except Exception as e:  # telemetry must never break the benchmark record
        print(f"bench: telemetry disabled ({e})", file=sys.stderr)
        return None


def _emit_bench_events(rec, out: dict) -> None:
    """Forward the recorded rates as ``step`` events (same schema as training:
    one event per measured phase, reach_timesteps_per_sec carries the rate)."""
    if rec is None:
        return
    phases = {
        "value": "route",
        "grad_value": "grad",
        "deep_value": "deep-route",
        "deep_grad_value": "deep-grad",
        "train_value": "train-step",
        "baseline_value": "reference-cpu",
    }
    for key, phase in phases.items():
        if out.get(key) is not None:
            rec.emit(
                "step",
                phase=phase,
                reach_timesteps_per_sec=out[key],
                engine=out.get("device"),
            )
    rec.merge_summary(
        "bench", {k: v for k, v in out.items() if not isinstance(v, (dict, list))}
    )
    rec.close(status="ok")


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if any(a in ("-h", "--help") for a in argv):
        print(_USAGE, end="")
        return
    rec = _open_bench_recorder()
    if rec is not None:
        rec.emit("run_start", cmd="bench", n_hosts=1)
    out: dict = {
        "metric": "reach-timesteps/sec/chip (synthetic network, forward route)",
        "value": None,
        "unit": "reach-timesteps/s",
        "vs_baseline": None,
    }
    try:
        probe_timeout = float(
            os.environ.get("DDR_BENCH_PROBE_TIMEOUT", DEFAULT_PROBE_TIMEOUT)
        )
        bench_timeout = float(os.environ.get("DDR_BENCH_TIMEOUT", 2400))
    except ValueError as e:
        out["error"] = f"bad DDR_BENCH_PROBE_TIMEOUT/DDR_BENCH_TIMEOUT override: {e}"
        print(json.dumps(out), flush=True)
        _emit_bench_events(rec, out)
        return
    out["probe_timeout_s"] = probe_timeout
    kernel, dtype = _kernel_dtype()
    out["kernel"] = kernel or "auto"
    out["compute_dtype"] = dtype

    # Phase 1: can an accelerator backend initialize at all? Skipped outright
    # when the environment already pins the host platform (JAX_PLATFORMS=cpu):
    # the probe child would inherit that pin and report "cpu" anyway, after
    # waiting out a possibly-wedged tunnel for up to DDR_BENCH_PROBE_TIMEOUT
    # (900 s default) — the stall that ate whole CPU-only bench rounds
    # (BENCH_r04/r05).
    pinned = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if pinned == "cpu":
        platform, probe_err = "cpu", ""
        out["probe_skipped"] = "JAX_PLATFORMS=cpu pinned in the environment"
    else:
        platform, probe_err = _run_child(
            "import jax; print(jax.devices()[0].platform)", probe_timeout, cpu_only=False
        )
    if platform is None or platform == "cpu":
        out["device"] = "cpu"
        if probe_err:
            out["probe_error"] = f"accelerator probe failed ({probe_err}); CPU fallback"
        n, t_hours = CPU_FALLBACK_N, CPU_FALLBACK_T
        cpu_only = True
    else:
        out["device"] = platform
        n, t_hours = DEFAULT_N, DEFAULT_T
        cpu_only = False

    try:
        n = int(os.environ.get("DDR_BENCH_N", n))
        t_hours = int(os.environ.get("DDR_BENCH_T", t_hours))
    except ValueError as e:
        out["error"] = f"bad DDR_BENCH_N/DDR_BENCH_T override: {e}"
        print(json.dumps(out), flush=True)
        _emit_bench_events(rec, out)
        return
    out["metric"] = (
        f"reach-timesteps/sec/chip (synthetic {n}-reach network, {t_hours}h forward route)"
    )

    # Phase 2: the route benchmark (on the accelerator if the probe passed).
    val, err = _run_child(
        f"import bench; print(bench.bench_route({n}, {t_hours}))", bench_timeout, cpu_only
    )
    if val is None and not cpu_only:
        # Accelerator died mid-benchmark: salvage a CPU number, respecting any
        # explicit shape overrides (they may exist to bound wall-clock).
        out["route_error"] = f"accelerator route bench failed ({err}); retrying on CPU"
        out["device"] = "cpu"
        cpu_only = True  # later phases must not touch the dead accelerator
        n = int(os.environ.get("DDR_BENCH_N", CPU_FALLBACK_N))
        t_hours = int(os.environ.get("DDR_BENCH_T", CPU_FALLBACK_T))
        out["metric"] = (
            f"reach-timesteps/sec/chip (synthetic {n}-reach network, {t_hours}h forward route)"
        )
        val, err = _run_child(
            f"import bench; print(bench.bench_route({n}, {t_hours}))", bench_timeout, True
        )
        if val is None:
            out["route_error"] += f"; CPU retry failed ({err})"
    if val is not None:
        val, toks = _split_tokens(val)
        try:
            out["value"] = round(float(val), 1)
            out["peak_hbm_gb"] = toks.get("peak_gb")
            _store_card_tokens(out, toks)
        except ValueError:
            # Append: a prior accelerator-failure diagnostic must survive.
            prior = out.get("route_error")
            msg = f"unparseable route output: {val!r}"
            out["route_error"] = f"{prior}; {msg}" if prior else msg
    else:
        out.setdefault("route_error", err)

    # Phase 2b (best-effort): training-path throughput — the full VJP. Failure
    # only omits the extra field; the headline metric is already settled.
    if out["value"] is not None:
        _record_float(
            out, "grad_value",
            f"import bench; print(bench.bench_grad({n}, {t_hours}))",
            bench_timeout, cpu_only,
            metric_key="grad_metric",
            metric=(
                "reach-timesteps/sec/chip, full VJP (value_and_grad of the "
                "gauge-loss route), same shapes and unit as the headline"
            ),
            peak_key="grad_peak_hbm_gb",
            card_prefix="grad_",
        )

    # Phase 2c (best-effort): the deep CONUS-shaped topology — depth in the
    # thousands, routed by whatever build_routing_network auto-selects (the
    # depth-chunked wavefront at these shapes).
    try:
        deep_n = int(os.environ.get("DDR_BENCH_DEEP_N", DEEP_N if not cpu_only else CPU_DEEP_N))
        deep_depth = int(
            os.environ.get("DDR_BENCH_DEEP_DEPTH", DEEP_DEPTH if not cpu_only else CPU_DEEP_DEPTH)
        )
    except ValueError as e:
        deep_n, deep_depth = 0, 0
        out["deep_error"] = f"bad DDR_BENCH_DEEP_N/DDR_BENCH_DEEP_DEPTH override: {e}"
    if deep_n > 0 and deep_depth > 0:
        dval, derr = _run_child(
            f"import bench; print(bench.bench_route_deep({deep_n}, {t_hours}, {deep_depth}))",
            bench_timeout,
            cpu_only,
        )
        if dval is not None:
            try:
                dval, dtoks = _split_tokens(dval)
                out["deep_peak_hbm_gb"] = dtoks.get("peak_gb")
                _store_card_tokens(out, dtoks, prefix="deep_")
                rate_str, _, engine = dval.partition(" ")
                # selection provenance: where the engine decision came from
                # (auto-tuner source vocabulary) and the plan it resolved to —
                # check_bench_regression flags a baseline/fresh plan mismatch
                out["engine_source"] = dtoks.get("engine_source", "policy")
                out["tuned_plan"] = dtoks.get("tuned_plan", engine or None)
                out["deep_value"] = round(float(rate_str), 1)
                out["deep_metric"] = (
                    f"reach-timesteps/sec/chip, deep CONUS-shaped topology "
                    f"({deep_n} reaches, longest-path depth {deep_depth}, {t_hours}h "
                    f"forward route, engine={engine or 'unknown'})"
                )
            except ValueError:
                out["deep_error"] = f"unparseable deep output: {dval!r}"
        else:
            out["deep_error"] = derr

        # Phase 2d (best-effort): deep training-path throughput — the full VJP
        # through the auto-selected deep engine.
        if "deep_value" in out:
            _record_float(
                out, "deep_grad_value",
                f"import bench; print(bench.bench_grad({deep_n}, {t_hours}, depth={deep_depth}))",
                bench_timeout, cpu_only,
                metric_key="deep_grad_metric",
                metric=(
                    "reach-timesteps/sec/chip, full VJP on the deep topology, "
                    "same shapes as deep_metric"
                ),
                peak_key="deep_grad_peak_hbm_gb",
                card_prefix="deep_grad_",
            )

        # Phase 2e (best-effort): the COMPLETE train step at the deep shape —
        # KAN forward, routing, daily-aggregated masked L1, backward, Adam —
        # through ddr_tpu.benchmarks.trainbench (the scripts/train.py path).
        if "deep_value" in out:
            tval, terr = _run_child(
                f"import sys; sys.argv = ['trainbench', '{deep_n}', '{t_hours}', "
                f"'{deep_depth}']; "
                "from ddr_tpu.benchmarks import trainbench; trainbench.main()",
                bench_timeout, cpu_only,
            )
            if tval:
                try:
                    trec = json.loads(tval)
                    out["train_value"] = trec["rts"]
                    out["train_peak_hbm_gb"] = trec.get("peak_hbm_gb")
                    out["train_metric"] = (
                        "reach-timesteps/sec/chip, FULL train step (KAN forward + "
                        f"routing + loss + backward + Adam) on the deep topology, "
                        f"engine={trec.get('engine', 'unknown')}, "
                        f"step={trec.get('step_ms', '?')}ms, "
                        f"peak_hbm_gb={trec.get('peak_hbm_gb')}"
                    )
                except (json.JSONDecodeError, KeyError) as e:
                    out["train_error"] = f"unparseable trainbench output: {e}"
            elif terr:
                out["train_error"] = terr

    # Grad-over-forward ratios: how much of the forward schedule's throughput
    # the backward keeps (1.0 = full VJP as fast as the forward route; the
    # analytic reverse-wavefront adjoint exists to push these up —
    # docs/benchmarks.md explains the field).
    if out.get("value") and out.get("grad_value"):
        out["grad_over_forward_ratio"] = round(out["grad_value"] / out["value"], 3)
    if out.get("deep_value") and out.get("deep_grad_value"):
        out["deep_grad_over_forward_ratio"] = round(
            out["deep_grad_value"] / out["deep_value"], 3
        )

    # Phase 3: the reference-equivalent CPU baseline.
    ref, err = _run_child(
        "import bench; print(bench.bench_reference_cpu())", bench_timeout, cpu_only=True
    )
    if ref is not None:
        try:
            out["baseline_value"] = round(float(ref), 1)
            if out["value"] is not None:
                out["vs_baseline"] = round(out["value"] / float(ref), 2)
        except ValueError:
            out["baseline_error"] = f"unparseable baseline output: {ref!r}"
    else:
        out["baseline_error"] = err

    print(json.dumps(out), flush=True)
    _emit_bench_events(rec, out)


if __name__ == "__main__":
    main()
