"""The shipped example must stay runnable: train -> test -> benchmark through the
CLI on examples/synthetic/config.yaml (VERDICT: the one end-to-end artifact was
unguarded). Overrides shrink the run to seconds while exercising the real command
paths, checkpoint handoff, and metric/plot outputs."""

from __future__ import annotations

import shutil
from pathlib import Path

import pytest

from ddr_tpu.cli import main as cli_main

pytestmark = pytest.mark.slow

EXAMPLE = Path(__file__).parent.parent / "examples" / "synthetic" / "config.yaml"


@pytest.fixture(scope="module")
def example_run(tmp_path_factory):
    """One real `ddr train` over the example config; tests share its artifacts."""
    tmp = tmp_path_factory.mktemp("example")
    cfg = tmp / "config.yaml"
    shutil.copy(EXAMPLE, cfg)
    fast = [
        f"params.save_path={tmp / 'output'}",
        "experiment.epochs=1",
        "experiment.end_time=1981/11/15",
        "experiment.rho=8",
        "experiment.warmup=1",
    ]
    rc = cli_main(["train", str(cfg), *fast])
    return tmp, cfg, fast, rc


class TestExampleEndToEnd:
    def test_train_writes_checkpoint(self, example_run):
        tmp, _, _, rc = example_run
        assert rc == 0, f"ddr train exited {rc}"
        assert list((tmp / "output" / "saved_models").glob("*.pkl")), (
            "example training produced no checkpoint"
        )

    def test_test_evaluates_checkpoint(self, example_run):
        tmp, cfg, fast, _ = example_run
        ckpts = list((tmp / "output" / "saved_models").glob("*.pkl"))
        if not ckpts:
            pytest.skip("training produced no checkpoint (covered by the train test)")
        ckpt = max(ckpts, key=lambda p: p.stat().st_mtime)
        rc = cli_main(["test", str(cfg), *fast, f"experiment.checkpoint={ckpt}"])
        assert rc == 0, f"ddr test exited {rc}"
        # ddr test persists predictions/observations as a zarr store
        from ddr_tpu.io import zarrlite

        store = tmp / "output" / "model_test.zarr"
        assert store.exists(), "ddr test wrote no model_test.zarr"
        root = zarrlite.open_group(store)
        assert any(True for _ in root.keys()), "model_test.zarr is empty"
        # and the in-run evaluation figures (round 4: the reference defers
        # these to a notebook; ddr test emits them directly)
        assert (tmp / "output" / "plots" / "test_nse_cdf.png").exists()
        assert (tmp / "output" / "plots" / "test_metric_boxes.png").exists()

    def test_benchmark_compares_against_lti(self, example_run):
        _, cfg, fast, _ = example_run
        rc = cli_main(["benchmark", str(cfg), *fast])
        assert rc == 0, f"ddr benchmark exited {rc}"
