"""Helpers for the ``ddr lint`` analyzer tests: build a throwaway source tree
and run the engine over it in-process."""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from ddr_tpu.analysis.engine import run_lint


def write_tree(root: Path, files: dict[str, str]) -> None:
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))


@pytest.fixture
def lint_tree(tmp_path):
    """``lint_tree({relpath: source, ...}, rules=[...], **run_lint_kwargs)``
    — writes the files under a tmp root and full-scans it (fixture roots lack
    bench.py/examples; the engine skips missing default-surface entries)."""

    def _run(files: dict[str, str], rules: list[str] | None = None, **kw):
        write_tree(tmp_path, files)
        return run_lint(tmp_path, rule_ids=rules, **kw)

    _run.root = tmp_path
    return _run
