"""Suppression-framework and CLI tests: pragmas, the committed baseline,
--changed-only, output formats, and the exit-code contract (0/1/2)."""

from __future__ import annotations

import json
import subprocess
import textwrap

import pytest

from ddr_tpu.analysis.baseline import Baseline, BaselineError
from ddr_tpu.analysis.cli import main as lint_main
from ddr_tpu.analysis.core import all_rules
from ddr_tpu.analysis.engine import LintError, run_lint
from tests.analysis.conftest import write_tree

_BAD_HASH = """\
    def seed_for(name):
        return hash(name) % 2**31
"""


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------

def test_pragma_suppresses_on_its_line(lint_tree):
    result = lint_tree(
        {"ddr_tpu/mod.py": """\
            def seed_for(name):
                return hash(name) % 2**31  # ddr-lint: disable=DDR301
        """},
        rules=["DDR301"],
    )
    assert result.findings == []
    assert result.suppressed_pragma == 1


def test_pragma_is_rule_specific(lint_tree):
    result = lint_tree(
        {"ddr_tpu/mod.py": """\
            def seed_for(name):
                return hash(name) % 2**31  # ddr-lint: disable=DDR999
        """},
        rules=["DDR301"],
    )
    assert [f.rule for f in result.findings] == ["DDR301"]
    assert result.suppressed_pragma == 0


def test_pragma_multiple_rules_one_line(lint_tree):
    result = lint_tree(
        {"ddr_tpu/mod.py": """\
            def order(xs):
                return list(set(xs)), hash(xs[0])  # ddr-lint: disable=DDR301,DDR303
        """},
        rules=["DDR301", "DDR303"],
    )
    assert result.findings == []
    assert result.suppressed_pragma == 2


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def _baseline(entries):
    return json.dumps({"version": 1, "entries": entries})


def test_baseline_suppresses_by_rule_path_context(lint_tree):
    result = lint_tree(
        {
            "ddr_tpu/mod.py": _BAD_HASH,
            "lint_baseline.json": _baseline([{
                "rule": "DDR301", "path": "ddr_tpu/mod.py",
                "context": "seed_for", "justification": "legacy seed format",
            }]),
        },
        rules=["DDR301"],
    )
    assert result.findings == []
    assert result.suppressed_baseline == 1
    assert result.unused_baseline == []


def test_baseline_wildcard_context(lint_tree):
    result = lint_tree(
        {
            "ddr_tpu/mod.py": _BAD_HASH,
            "lint_baseline.json": _baseline([{
                "rule": "DDR301", "path": "ddr_tpu/mod.py",
                "context": "*", "justification": "whole-file accepted",
            }]),
        },
        rules=["DDR301"],
    )
    assert result.findings == []
    assert result.suppressed_baseline == 1


def test_baseline_survives_line_churn_but_not_context_change(lint_tree):
    # same finding pushed 20 lines down still matches (keyed on context,
    # never line); a different enclosing function does not.
    pad = "# pad\n" * 20
    result = lint_tree(
        {
            "ddr_tpu/mod.py": pad + textwrap.dedent(_BAD_HASH),
            "lint_baseline.json": _baseline([
                {"rule": "DDR301", "path": "ddr_tpu/mod.py",
                 "context": "seed_for", "justification": "legacy"},
                {"rule": "DDR301", "path": "ddr_tpu/mod.py",
                 "context": "other_fn", "justification": "stale"},
            ]),
        },
        rules=["DDR301"],
    )
    assert result.findings == []
    assert result.suppressed_baseline == 1
    assert [e["context"] for e in result.unused_baseline] == ["other_fn"]


def test_no_baseline_strict_mode(lint_tree):
    result = lint_tree(
        {
            "ddr_tpu/mod.py": _BAD_HASH,
            "lint_baseline.json": _baseline([{
                "rule": "DDR301", "path": "ddr_tpu/mod.py",
                "context": "*", "justification": "accepted",
            }]),
        },
        rules=["DDR301"],
        use_baseline=False,
    )
    assert [f.rule for f in result.findings] == ["DDR301"]


def test_baseline_requires_justification(tmp_path):
    write_tree(tmp_path, {
        "ddr_tpu/mod.py": _BAD_HASH,
        "lint_baseline.json": _baseline(
            [{"rule": "DDR301", "path": "ddr_tpu/mod.py", "justification": "  "}]
        ),
    })
    with pytest.raises(BaselineError, match="empty justification"):
        run_lint(tmp_path, rule_ids=["DDR301"])


def test_baseline_rejects_malformed_json(tmp_path):
    write_tree(tmp_path, {"ddr_tpu/mod.py": "X = 1\n", "lint_baseline.json": "{nope"})
    with pytest.raises(BaselineError, match="unparseable"):
        run_lint(tmp_path, rule_ids=["DDR301"])


def test_write_baseline_dedupes_and_marks_todo(tmp_path):
    write_tree(tmp_path, {"ddr_tpu/mod.py": _BAD_HASH})
    result = run_lint(tmp_path, rule_ids=["DDR301"], use_baseline=False)
    out = tmp_path / "lint_baseline.json"
    Baseline.write(out, result.findings)
    doc = json.loads(out.read_text())
    assert doc["entries"] == [{
        "rule": "DDR301", "path": "ddr_tpu/mod.py", "context": "seed_for",
        "justification": "TODO: justify or fix",
    }]


# ---------------------------------------------------------------------------
# engine behaviors
# ---------------------------------------------------------------------------

def test_unknown_rule_id_is_internal_error(tmp_path):
    write_tree(tmp_path, {"ddr_tpu/mod.py": "X = 1\n"})
    with pytest.raises(LintError, match="unknown rule id"):
        run_lint(tmp_path, rule_ids=["DDR999"])


def test_explicit_missing_path_is_internal_error(tmp_path):
    write_tree(tmp_path, {"ddr_tpu/mod.py": "X = 1\n"})
    with pytest.raises(LintError, match="no such file"):
        run_lint(tmp_path, paths=[tmp_path / "nope.py"])


def test_parse_error_reported_not_crashed(lint_tree):
    result = lint_tree({"ddr_tpu/broken.py": "def f(:\n"}, rules=["DDR301"])
    assert result.findings == []
    assert len(result.parse_errors) == 1
    assert "ddr_tpu/broken.py" in result.parse_errors[0]


def test_finalize_rules_skipped_on_partial_scan(tmp_path):
    """Cross-file registry checks only run on full-tree scans — judging
    EVENT_TYPES coverage against one file would fire the broken-matcher
    guard on every clean single-file lint."""
    write_tree(tmp_path, {
        "ddr_tpu/observability/events.py":
            'SCHEMA_VERSION = 2\nEVENT_TYPES = ("epoch",)\n',
        "ddr_tpu/mod.py": "X = 1\n",
    })
    partial = run_lint(tmp_path, paths=[tmp_path / "ddr_tpu/mod.py"], rule_ids=["DDR501"])
    assert partial.findings == []
    full = run_lint(tmp_path, rule_ids=["DDR501"])
    assert [f.rule for f in full.findings] == ["DDR501"]  # zero-sites guard


def _git(root, *args):
    subprocess.run(
        ["git", "-C", str(root), *args], check=True, capture_output=True,
        env={"PATH": "/usr/bin:/bin", "HOME": str(root),
             "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
             "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
    )


def test_changed_only_filters_to_touched_files(tmp_path):
    write_tree(tmp_path, {
        "ddr_tpu/committed.py": _BAD_HASH,
        "lint_baseline.json": _baseline([{
            "rule": "DDR301", "path": "ddr_tpu/committed.py",
            "context": "*", "justification": "accepted",
        }]),
    })
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    # committed finding is filtered out; a new untracked bad file reports
    write_tree(tmp_path, {"ddr_tpu/fresh.py": _BAD_HASH})
    result = run_lint(tmp_path, rule_ids=["DDR301"], changed_only=True)
    assert [(f.rule, f.path) for f in result.findings] == [("DDR301", "ddr_tpu/fresh.py")]
    # the committed file's baseline entry had no chance to match under the
    # changed-only filter — it must NOT be reported stale
    assert result.unused_baseline == []


def test_changed_only_outside_git_is_internal_error(tmp_path):
    write_tree(tmp_path, {"ddr_tpu/mod.py": _BAD_HASH})
    with pytest.raises(LintError, match="--changed-only"):
        run_lint(tmp_path, rule_ids=["DDR301"], changed_only=True)


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_clean_exit_0(tmp_path, capsys):
    write_tree(tmp_path, {"ddr_tpu/mod.py": "X = 1\n"})
    assert lint_main(["--root", str(tmp_path)]) == 0
    assert "ddr lint: clean" in capsys.readouterr().out


def test_cli_findings_exit_1_text(tmp_path, capsys):
    write_tree(tmp_path, {"ddr_tpu/mod.py": _BAD_HASH})
    assert lint_main(["--root", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "ddr_tpu/mod.py:2: DDR301 error:" in out
    assert "[seed_for]" in out
    assert "1 finding(s)" in out


def test_cli_parse_error_exit_2(tmp_path, capsys):
    write_tree(tmp_path, {"ddr_tpu/broken.py": "def f(:\n"})
    assert lint_main(["--root", str(tmp_path)]) == 2
    assert "could not parse" in capsys.readouterr().err


def test_cli_bad_baseline_exit_2(tmp_path, capsys):
    write_tree(tmp_path, {"ddr_tpu/mod.py": "X = 1\n", "lint_baseline.json": "{nope"})
    assert lint_main(["--root", str(tmp_path)]) == 2
    assert "internal error" in capsys.readouterr().err


def test_cli_json_format(tmp_path, capsys):
    write_tree(tmp_path, {"ddr_tpu/mod.py": _BAD_HASH})
    assert lint_main(["--root", str(tmp_path), "--format", "json", "--rules", "DDR301"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["errors"] == 1
    (finding,) = doc["findings"]
    assert finding["rule"] == "DDR301"
    assert finding["path"] == "ddr_tpu/mod.py"
    assert finding["context"] == "seed_for"


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    write_tree(tmp_path, {"ddr_tpu/mod.py": _BAD_HASH})
    assert lint_main(["--root", str(tmp_path), "--write-baseline"]) == 0
    baseline = tmp_path / "lint_baseline.json"
    assert "TODO: justify or fix" in baseline.read_text()
    capsys.readouterr()
    # the written baseline suppresses the finding on the next run...
    assert lint_main(["--root", str(tmp_path)]) == 0
    assert "1 suppressed (1 baseline)" in capsys.readouterr().out
    # ...but blanking a justification is an internal error, not a pass
    doc = json.loads(baseline.read_text())
    doc["entries"][0]["justification"] = ""
    baseline.write_text(json.dumps(doc))
    assert lint_main(["--root", str(tmp_path)]) == 2


def test_cli_unused_baseline_note(tmp_path, capsys):
    write_tree(tmp_path, {
        "ddr_tpu/mod.py": "X = 1\n",
        "lint_baseline.json": _baseline([{
            "rule": "DDR301", "path": "ddr_tpu/gone.py",
            "context": "*", "justification": "was accepted",
        }]),
    })
    assert lint_main(["--root", str(tmp_path)]) == 0
    assert "unused baseline entry DDR301 ddr_tpu/gone.py" in capsys.readouterr().err


def test_cli_list_rules_covers_catalog(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in all_rules():
        assert rule_id in out


def test_rule_catalog_shape():
    rules = all_rules()
    assert len(rules) == 13
    families = {rid[:4] for rid in rules}
    assert families == {"DDR1", "DDR2", "DDR3", "DDR4", "DDR5"}
    for rule in rules.values():
        assert rule.severity in ("error", "warning")
        assert rule.rationale
