"""Fixture-pair tests: every rule ID fires on its bad fixture and stays quiet
on the corresponding good one. One pair per rule, matching the catalog in
docs/static_analysis.md."""

from __future__ import annotations


def _ids(result):
    return [(f.rule, f.path) for f in result.findings]


# ---------------------------------------------------------------------------
# DDR1xx — trace safety
# ---------------------------------------------------------------------------

def test_ddr101_host_effect_in_jit(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import time
            import jax

            @jax.jit
            def step(x):
                t0 = time.time()
                return x + t0
        """},
        rules=["DDR101"],
    )
    assert _ids(bad) == [("DDR101", "ddr_tpu/mod.py")]
    assert "time.time" in bad.findings[0].message
    assert bad.findings[0].context == "step"


def test_ddr101_good_host_effect_outside_trace(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import time
            import jax

            def train(x):
                t0 = time.time()   # host code: fine
                return jax.jit(lambda y: y + 1)(x), time.time() - t0
        """},
        rules=["DDR101"],
    )
    assert good.findings == []


def test_ddr101_propagates_through_local_call_graph(lint_tree):
    """A helper only ever called from a scan body is itself traced."""
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import time
            import jax

            def _inner(carry, x):
                time.sleep(0.1)
                return carry, x

            def run(xs):
                return jax.lax.scan(_inner, 0.0, xs)
        """},
        rules=["DDR101"],
    )
    assert [f.rule for f in bad.findings] == ["DDR101"]
    assert bad.findings[0].context == "_inner"


def test_ddr102_item_and_param_coercion(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            @jax.jit
            def step(x):
                a = x.item()
                b = float(x)
                return a + b
        """},
        rules=["DDR102"],
    )
    assert [f.rule for f in bad.findings] == ["DDR102", "DDR102"]


def test_ddr102_good_no_coercion(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            @jax.jit
            def step(x):
                return x * 2.0

            def host(x):
                return float(x)   # untraced: fine
        """},
        rules=["DDR102"],
    )
    assert good.findings == []


def test_ddr103_env_read_in_traced_body(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import os
            import jax

            @jax.jit
            def step(x):
                fixed = float(os.environ.get("DDR_WAVE_FIXED_US", "7.0"))
                return x + fixed
        """},
        rules=["DDR103"],
    )
    assert [f.rule for f in bad.findings] == ["DDR103"]
    assert "trace-time constant" in bad.findings[0].message


def test_ddr103_good_env_read_at_planning_time(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import os
            import jax

            def make_step():
                fixed = float(os.environ.get("DDR_WAVE_FIXED_US", "7.0"))

                @jax.jit
                def step(x):
                    return x + fixed
                return step
        """},
        rules=["DDR103"],
    )
    assert good.findings == []


# ---------------------------------------------------------------------------
# DDR2xx — recompile hazards
# ---------------------------------------------------------------------------

def test_ddr201_jit_of_lambda_in_loop(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            fns = []
            for i in range(4):
                fns.append(jax.jit(lambda x: x + i))
        """},
        rules=["DDR201"],
    )
    assert [f.rule for f in bad.findings] == ["DDR201"]


def test_ddr201_good_jit_hoisted(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            step = jax.jit(lambda x: x + 1)

            def run(xs):
                out = []
                for x in xs:
                    out.append(step(x))   # calling a jitted fn in a loop: fine
                return out
        """},
        rules=["DDR201"],
    )
    assert good.findings == []


def test_ddr202_unhashable_static_default(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            @jax.jit(static_argnames="names")
            def gather(x, names=["n", "q"]):
                return x
        """},
        rules=["DDR202"],
    )
    assert [f.rule for f in bad.findings] == ["DDR202"]
    assert "'names'" in bad.findings[0].message


def test_ddr202_good_tuple_default(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            @jax.jit(static_argnames="names")
            def gather(x, names=("n", "q")):
                return x
        """},
        rules=["DDR202"],
    )
    assert good.findings == []


def test_ddr203_unaudited_jit_in_product_module(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            def build(fn):
                return jax.jit(fn)
        """},
        rules=["DDR203"],
    )
    assert [f.rule for f in bad.findings] == ["DDR203"]
    assert "track_jit" in bad.findings[0].message


def test_ddr203_good_module_references_tracker(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import jax

            def build(fn, tracker):
                compiled = jax.jit(fn)
                tracker.track_jit("engine", compiled)
                return compiled
        """},
        rules=["DDR203"],
    )
    assert good.findings == []


def test_ddr203_ignores_non_product_paths(lint_tree):
    """The auditing discipline applies to ddr_tpu/ only (bench/examples
    measure compiles on purpose)."""
    good = lint_tree(
        {"ddr_tpu/ok.py": "X = 1\n",
         "bench.py": "import jax\nstep = jax.jit(lambda x: x)\n"},
        rules=["DDR203"],
    )
    assert good.findings == []


# ---------------------------------------------------------------------------
# DDR3xx — determinism / resume safety
# ---------------------------------------------------------------------------

def test_ddr301_salted_hash(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            def seed_for(name):
                return hash(name) % 2**31
        """},
        rules=["DDR301"],
    )
    assert [f.rule for f in bad.findings] == ["DDR301"]
    assert bad.findings[0].context == "seed_for"


def test_ddr301_good_crc32(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import zlib

            def seed_for(name):
                return zlib.crc32(name.encode()) % 2**31
        """},
        rules=["DDR301"],
    )
    assert good.findings == []


def test_ddr302_wallclock_defaults(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            import dataclasses
            import time

            @dataclasses.dataclass
            class Meta:
                stamp: float = time.time()
                created: float = dataclasses.field(default_factory=time.time)
        """},
        rules=["DDR302"],
    )
    assert [f.rule for f in bad.findings] == ["DDR302", "DDR302"]


def test_ddr302_good_explicit_timestamp(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import dataclasses

            @dataclasses.dataclass
            class Meta:
                stamp: float   # caller passes the timestamp explicitly
        """},
        rules=["DDR302"],
    )
    assert good.findings == []


def test_ddr303_set_materialization(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": """\
            def order(xs, ys):
                a = list(set(xs))
                b = tuple(set(xs) - set(ys))
                return a, b
        """},
        rules=["DDR303"],
    )
    assert [f.rule for f in bad.findings] == ["DDR303", "DDR303"]


def test_ddr303_good_sorted(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            def order(xs, ys):
                a = sorted(set(xs))
                b = tuple(sorted(set(xs) - set(ys)))
                return a, b
        """},
        rules=["DDR303"],
    )
    assert good.findings == []


# ---------------------------------------------------------------------------
# DDR4xx — lock discipline
# ---------------------------------------------------------------------------

_WRITER = """\
    import threading

    class Writer:
        def __init__(self):
            self._lock = threading.Lock()
            self._pending = []
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

        def _run(self):
            with self._lock:
                self._pending.append(1)

        def flush(self):
            {flush_body}
"""


def test_ddr401_write_outside_lock(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/mod.py": _WRITER.format(flush_body="self._pending = []")},
        rules=["DDR401"],
    )
    assert [f.rule for f in bad.findings] == ["DDR401"]
    assert "flush()" in bad.findings[0].message
    assert bad.findings[0].context == "Writer.flush"


def test_ddr401_good_guarded_everywhere(lint_tree):
    good = lint_tree(
        {"ddr_tpu/mod.py": _WRITER.format(
            flush_body="with self._lock:\n            self._pending = []")},
        rules=["DDR401"],
    )
    assert good.findings == []


def test_ddr401_init_exempt_and_unthreaded_module_skipped(lint_tree):
    # __init__ writes happen-before thread start; a module with no Thread
    # reference is out of scope entirely even with the same write pattern.
    good = lint_tree(
        {"ddr_tpu/mod.py": """\
            import threading

            class Plain:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0   # construction: exempt
        """},
        rules=["DDR401"],
    )
    assert good.findings == []


# ---------------------------------------------------------------------------
# DDR5xx — consistency gates (need registry files in the fixture tree)
# ---------------------------------------------------------------------------

_EVENTS_PY = 'SCHEMA_VERSION = 2\nEVENT_TYPES = ("epoch", "route")\n'
_FAULTS_PY = 'FAULT_SITES = ("data.load", "device.step")\n'


def test_ddr501_unregistered_event(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/observability/events.py": _EVENTS_PY,
         "ddr_tpu/mod.py": """\
            def report(rec):
                rec.emit("epoch", t=1.0)
                rec.emit("epohc", t=2.0)
        """},
        rules=["DDR501"],
    )
    assert [f.rule for f in bad.findings] == ["DDR501"]
    assert "'epohc'" in bad.findings[0].message


def test_ddr501_good_all_registered(lint_tree):
    good = lint_tree(
        {"ddr_tpu/observability/events.py": _EVENTS_PY,
         "ddr_tpu/mod.py": 'def report(rec):\n    rec.emit("epoch")\n'},
        rules=["DDR501"],
    )
    assert good.findings == []


def test_ddr501_missing_schema_version_flagged(lint_tree):
    """Dropping the run_start version stamp breaks mixed-version readers
    silently — losing the constant is a lint error in its own right."""
    result = lint_tree(
        {"ddr_tpu/observability/events.py": 'EVENT_TYPES = ("epoch",)\n',
         "ddr_tpu/mod.py": 'def report(rec):\n    rec.emit("epoch")\n'},
        rules=["DDR501"],
    )
    assert [f.rule for f in result.findings] == ["DDR501"]
    assert "SCHEMA_VERSION" in result.findings[0].message


def test_ddr501_zero_sites_means_broken_matcher(lint_tree):
    broken = lint_tree(
        {"ddr_tpu/observability/events.py": _EVENTS_PY,
         "ddr_tpu/mod.py": "X = 1\n"},
        rules=["DDR501"],
    )
    assert [f.rule for f in broken.findings] == ["DDR501"]
    assert "matcher broken" in broken.findings[0].message


_DOCS_MD = """\
    # Configuration reference
    - `DDR_FOO` — a documented knob.
    - `DDR_FAM_*` — a documented family.
    - `DDR_STALE` — documented but never read.
"""


def test_ddr502_both_directions(lint_tree):
    result = lint_tree(
        {"docs/config_reference.md": _DOCS_MD,
         "ddr_tpu/mod.py": """\
            import os
            A = os.environ.get("DDR_FOO", "")
            B = os.getenv("DDR_FAM_X")
            C = os.environ["DDR_BAR"]
        """},
        rules=["DDR502"],
    )
    msgs = sorted(f.message for f in result.findings)
    assert len(msgs) == 2
    assert any("DDR_BAR" in m and "not documented" in m for m in msgs)
    assert any("DDR_STALE" in m and "never read" in m for m in msgs)
    # the stale-docs finding anchors at the docs file, not a source file
    assert {f.path for f in result.findings} == {"ddr_tpu/mod.py", "docs/config_reference.md"}


def test_ddr502_good_parity(lint_tree):
    good = lint_tree(
        {"docs/config_reference.md": "- `DDR_FOO`\n- `DDR_FAM_*`\n",
         "ddr_tpu/mod.py": """\
            import os
            A = os.environ.get("DDR_FOO", "")
            B = os.getenv("DDR_FAM_X")
        """},
        rules=["DDR502"],
    )
    assert good.findings == []


def test_ddr503_unknown_fault_site(lint_tree):
    bad = lint_tree(
        {"ddr_tpu/observability/faults.py": _FAULTS_PY,
         "ddr_tpu/mod.py": """\
            def load(faults):
                faults.maybe_inject("data.laod")
        """},
        rules=["DDR503"],
    )
    assert [f.rule for f in bad.findings] == ["DDR503"]
    assert "data.laod" in bad.findings[0].message


def test_ddr503_good_registered_site(lint_tree):
    good = lint_tree(
        {"ddr_tpu/observability/faults.py": _FAULTS_PY,
         "ddr_tpu/mod.py": """\
            def load(faults):
                faults.maybe_inject("data.load")
        """},
        rules=["DDR503"],
    )
    assert good.findings == []
