"""Sharded analytic adjoint: grad parity on the virtual 8-device mesh.

Both multi-chip routers (sharded wavefront, stacked-sharded) now accept
``adjoint="analytic"`` — the transposed-table reverse sweep whose boundary
exchange is the forward's psum with publisher/consumer roles swapped and the
adjoint flowing toward LOWER shards. These tests pin the contract the routers
sell: the analytic backward is a drop-in for AD — parameter gradients match
sharded AD and the single-chip analytic kernels to ≤1e-5 relative (scale-
relative: float32 through a T-step recurrence), including under ACTIVE clamp
bounds (the subgradient is chosen by the same outer-AD ``max`` as the AD path,
so the two must agree exactly there too) and composed with ``remat_bands``.

seed=3 throughout the gradient tests: the seed-0 basin's loss is near-flat
(|g| ~1e-6, pure float32 noise), where a relative comparison is vacuous; the
seed-3 basin has measurable gradients (leaf scales ~1e0..1e3).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.parallel import (
    build_sharded_wavefront,
    make_mesh,
    permute_routing_data,
    sharded_wavefront_route,
    topological_range_partition,
)
from ddr_tpu.parallel.stacked import build_stacked_sharded, route_stacked_sharded
from ddr_tpu.routing.mc import Bounds, route
from ddr_tpu.routing.model import prepare_batch

N_DEV = 8

#: Scale-relative gradient tolerance (the acceptance bar): per leaf,
#: max|a-b| / max(|a|_inf, |b|_inf, 1e-8) <= 1e-5.
GRAD_RTOL = 1e-5


def _assert_grads_close(ga, gb, tol=GRAD_RTOL):
    fa, _ = jax.tree_util.tree_flatten(ga)
    fb, _ = jax.tree_util.tree_flatten(gb)
    assert len(fa) == len(fb)
    for i, (a, b) in enumerate(zip(fa, fb)):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.max(np.abs(a)), np.max(np.abs(b)), 1e-8)
        rel = np.max(np.abs(a - b)) / scale
        assert rel < tol, f"leaf {i}: maxdiff/scale={rel:.3e} (scale={scale:.3e})"


def _wf_setup(n=256, t=24, seed=3):
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    basin = make_basin(n_segments=n, n_gauges=4, n_days=max(2, -(-t // 24)), seed=seed)
    rd = basin.routing_data
    part = topological_range_partition(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    rd = permute_routing_data(rd, part)
    sched = build_sharded_wavefront(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    _, channels, _ = prepare_batch(rd, 1e-4)
    params = {
        k: jnp.asarray(np.asarray(v)[part.perm], jnp.float32)
        for k, v in basin.true_params.items()
    }
    q_prime = jnp.asarray(basin.q_prime[:t, part.perm])
    return make_mesh(N_DEV), sched, rd, channels, params, q_prime


def _stacked_setup(n=256, t=24, seed=3):
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    basin = make_basin(n_segments=n, n_gauges=4, n_days=max(2, -(-t // 24)), seed=seed)
    rd = basin.routing_data
    # ORIGINAL node order: the stacked layout carries its own permutations.
    layout = build_stacked_sharded(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    _, channels, _ = prepare_batch(rd, 1e-4)
    params = {
        k: jnp.asarray(np.asarray(v), jnp.float32)
        for k, v in basin.true_params.items()
    }
    q_prime = jnp.asarray(basin.q_prime[:t])
    return make_mesh(N_DEV), layout, rd, channels, params, q_prime


class TestWavefrontAnalytic:
    def test_transposed_table_is_edge_transpose(self):
        """Pure-host tier-1 invariant, no compiles: decoding ``t_idx`` (the
        analytic adjoint's successor gather) must yield exactly the same
        same-shard (src, tgt, gap) edge set as decoding ``pred_idx`` — the
        transposed table IS the forward table, transposed."""
        mesh, sched, rd, channels, params, q_prime = _wf_setup(n=64, t=8)
        assert sched.t_idx is not None and sched.t_width >= 1
        nl = sched.n_local
        pred = np.asarray(sched.pred_idx).reshape(sched.n_shards, nl, -1)
        tidx = np.asarray(sched.t_idx).reshape(sched.n_shards, nl, -1)

        def decode(table, local_is_source):
            edges = set()
            for s in range(sched.n_shards):
                for i in range(nl):
                    for v in table[s, i]:
                        v = int(v)
                        other, gap = v % (nl + 1), v // (nl + 1) + 1
                        if other == nl:
                            continue  # sentinel pad slot
                        edges.add(
                            (s, i, other, gap) if local_is_source
                            else (s, other, i, gap)
                        )
            return edges

        fwd_edges = decode(pred, local_is_source=False)
        rev_edges = decode(tidx, local_is_source=True)
        assert fwd_edges, "expected same-shard edges in a 64-reach basin"
        assert fwd_edges == rev_edges

    @pytest.mark.slow
    def test_forward_and_grad_parity_quick(self):
        """Small case: analytic forward bit-matches the AD-path forward (same
        primal program) and gradients agree to the bar."""
        mesh, sched, rd, channels, params, q_prime = _wf_setup(n=64, t=24)

        def loss(p, adj):
            with mesh:
                runoff, _ = sharded_wavefront_route(
                    mesh, sched, channels, p, q_prime, adjoint=adj
                )
            return jnp.mean(runoff**2)

        with mesh:
            r_an, _ = sharded_wavefront_route(
                mesh, sched, channels, params, q_prime, adjoint="analytic"
            )
            r_ad, _ = sharded_wavefront_route(
                mesh, sched, channels, params, q_prime, adjoint="ad"
            )
        np.testing.assert_allclose(
            np.asarray(r_an), np.asarray(r_ad), rtol=1e-6, atol=1e-6
        )
        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )

    def test_unknown_adjoint_rejected(self):
        mesh, sched, rd, channels, params, q_prime = _wf_setup(n=64, t=8)
        with pytest.raises(ValueError, match="adjoint"):
            with mesh:
                sharded_wavefront_route(
                    mesh, sched, channels, params, q_prime, adjoint="bogus"
                )

    def test_stale_schedule_rejected(self):
        """A schedule without transposed tables (pre-PR pickle) must fail
        loudly for analytic, not silently produce wrong gradients."""
        mesh, sched, rd, channels, params, q_prime = _wf_setup(n=64, t=8)
        stale = dataclasses.replace(sched, t_idx=None, t_width=0)
        with pytest.raises(ValueError, match="transposed"):
            with mesh:
                sharded_wavefront_route(
                    mesh, stale, channels, params, q_prime, adjoint="analytic"
                )

    @pytest.mark.slow
    def test_grad_matches_sharded_ad(self):
        mesh, sched, rd, channels, params, q_prime = _wf_setup()

        def loss(p, adj):
            with mesh:
                runoff, _ = sharded_wavefront_route(
                    mesh, sched, channels, p, q_prime, adjoint=adj
                )
            return jnp.mean(runoff**2)

        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )

    @pytest.mark.slow
    def test_grad_matches_single_chip_analytic(self):
        """Transposed tables + reversed psum reproduce the single-chip
        reverse-wavefront kernel's gradients (which are FD-pinned in
        tests/routing) across the shard boundaries."""
        from ddr_tpu.routing.network import build_network

        mesh, sched, rd, channels, params, q_prime = _wf_setup()
        network = build_network(
            rd.adjacency_rows, rd.adjacency_cols, rd.n_segments,
            fused=False, wavefront=True,
        )

        def loss_sh(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(
                    mesh, sched, channels, p, q_prime, adjoint="analytic"
                )
            return jnp.mean(runoff**2)

        def loss_sc(p):
            out = route(network, channels, p, q_prime, adjoint="analytic")
            return jnp.mean(out.runoff**2)

        _assert_grads_close(jax.grad(loss_sh)(params), jax.grad(loss_sc)(params))

    @pytest.mark.slow
    def test_grad_parity_under_active_clamp(self):
        """Raise the discharge floor until a sizable fraction of outputs sit
        ON the clamp boundary: the analytic backward must pick the same
        max-subgradient as AD (it does by construction — the clamp lives
        outside the custom_vjp, on the shared outer-AD path)."""
        mesh, sched, rd, channels, params, q_prime = _wf_setup()
        with mesh:
            r0, _ = sharded_wavefront_route(mesh, sched, channels, params, q_prime)
        lb = float(np.quantile(np.asarray(r0), 0.5))
        bounds = Bounds(discharge=lb)
        with mesh:
            r1, _ = sharded_wavefront_route(
                mesh, sched, channels, params, q_prime, bounds=bounds,
                adjoint="analytic",
            )
        clamped = float(np.mean(np.asarray(r1) <= lb * (1 + 1e-6)))
        assert clamped > 0.2, f"clamp inactive ({clamped:.0%}) — test is vacuous"

        def loss(p, adj):
            with mesh:
                runoff, _ = sharded_wavefront_route(
                    mesh, sched, channels, p, q_prime, bounds=bounds, adjoint=adj
                )
            return jnp.mean(runoff**2)

        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )


class TestStackedAnalytic:
    def test_layout_carries_transposed_tables(self):
        """Pure-host tier-1 invariant, no compiles: a freshly built stacked
        layout carries the analytic band adjoint's transposed tables, shaped
        per slot (the stale-layout error branch pins the converse)."""
        mesh, layout, rd, channels, params, q_prime = _stacked_setup(n=64, t=8)
        assert layout.t_idx is not None and layout.t_width >= 1
        tidx = np.asarray(layout.t_idx)
        assert tidx.ndim == 3 and tidx.shape[-1] % layout.t_width == 0
        n_cap = tidx.shape[-1] // layout.t_width
        # every entry is a valid flat ring slot or the sentinel column
        other, gap = tidx % (n_cap + 1), tidx // (n_cap + 1)
        assert (other <= n_cap).all() and (gap >= 0).all()
        # at 64 reaches over 8 shards some same-shard successor edges exist
        assert (other < n_cap).any()

    @pytest.mark.slow
    def test_forward_and_grad_parity_quick(self):
        mesh, layout, rd, channels, params, q_prime = _stacked_setup(n=64, t=24)

        def loss(p, adj):
            with mesh:
                runoff, _ = route_stacked_sharded(
                    mesh, layout, channels, p, q_prime, adjoint=adj
                )
            return jnp.mean(runoff**2)

        with mesh:
            r_an, _ = route_stacked_sharded(
                mesh, layout, channels, params, q_prime, adjoint="analytic"
            )
            r_ad, _ = route_stacked_sharded(
                mesh, layout, channels, params, q_prime, adjoint="ad"
            )
        np.testing.assert_allclose(
            np.asarray(r_an), np.asarray(r_ad), rtol=1e-6, atol=1e-6
        )
        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )

    def test_unknown_adjoint_rejected(self):
        mesh, layout, rd, channels, params, q_prime = _stacked_setup(n=64, t=8)
        with pytest.raises(ValueError, match="adjoint"):
            with mesh:
                route_stacked_sharded(
                    mesh, layout, channels, params, q_prime, adjoint="bogus"
                )

    def test_stale_layout_rejected(self):
        mesh, layout, rd, channels, params, q_prime = _stacked_setup(n=64, t=8)
        stale = dataclasses.replace(layout, t_idx=None, t_width=0)
        with pytest.raises(ValueError, match="transposed"):
            with mesh:
                route_stacked_sharded(
                    mesh, stale, channels, params, q_prime, adjoint="analytic"
                )

    @pytest.mark.slow
    def test_grad_matches_sharded_ad(self):
        mesh, layout, rd, channels, params, q_prime = _stacked_setup()

        def loss(p, adj):
            with mesh:
                runoff, _ = route_stacked_sharded(
                    mesh, layout, channels, p, q_prime, adjoint=adj
                )
            return jnp.mean(runoff**2)

        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )

    @pytest.mark.slow
    def test_grad_matches_single_chip_stacked_analytic(self):
        """Band-frame transposed tables reproduce routing/stacked's
        ``_band_analytic`` gradients through the cross-band AND cross-shard
        hand-offs (the x_ext/s_ext external-inflow contract on outer AD)."""
        from ddr_tpu.routing.stacked import build_stacked_chunked, route_stacked

        mesh, layout, rd, channels, params, q_prime = _stacked_setup()
        sc = build_stacked_chunked(rd.adjacency_rows, rd.adjacency_cols, rd.n_segments)

        def loss_sh(p):
            with mesh:
                runoff, _ = route_stacked_sharded(
                    mesh, layout, channels, p, q_prime, adjoint="analytic"
                )
            return jnp.mean(runoff**2)

        def loss_sc(p):
            res = route_stacked(sc, channels, p, q_prime, adjoint="analytic")
            runoff = res.runoff if hasattr(res, "runoff") else res[0]
            return jnp.mean(runoff**2)

        _assert_grads_close(jax.grad(loss_sh)(params), jax.grad(loss_sc)(params))

    @pytest.mark.slow
    def test_grad_parity_with_carried_state(self):
        mesh, layout, rd, channels, params, q_prime = _stacked_setup()
        q_init = jnp.asarray(
            np.random.default_rng(0).uniform(0.1, 5.0, rd.n_segments), jnp.float32
        )

        def loss(p, adj):
            with mesh:
                runoff, _ = route_stacked_sharded(
                    mesh, layout, channels, p, q_prime, q_init=q_init, adjoint=adj
                )
            return jnp.mean(runoff**2)

        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )

    @pytest.mark.slow
    def test_remat_bands_composes_with_analytic(self):
        """Band-level rematerialization re-runs the analytic forward inside
        the backward; gradients must be unchanged from the default path."""
        mesh, layout, rd, channels, params, q_prime = _stacked_setup()

        def loss(p, adj, rb=False):
            with mesh:
                runoff, _ = route_stacked_sharded(
                    mesh, layout, channels, p, q_prime, adjoint=adj, remat_bands=rb
                )
            return jnp.mean(runoff**2)

        g_rb = jax.jit(
            jax.grad(lambda p: loss(p, "analytic", rb=True))
        )(params)
        _assert_grads_close(g_rb, jax.grad(lambda p: loss(p, "ad"))(params))

    @pytest.mark.slow
    def test_grad_parity_under_active_clamp(self):
        mesh, layout, rd, channels, params, q_prime = _stacked_setup()
        with mesh:
            r0, _ = route_stacked_sharded(mesh, layout, channels, params, q_prime)
        lb = float(np.quantile(np.asarray(r0), 0.5))
        bounds = Bounds(discharge=lb)

        def loss(p, adj):
            with mesh:
                runoff, _ = route_stacked_sharded(
                    mesh, layout, channels, p, q_prime, bounds=bounds, adjoint=adj
                )
            return jnp.mean(runoff**2)

        _assert_grads_close(
            jax.grad(lambda p: loss(p, "analytic"))(params),
            jax.grad(lambda p: loss(p, "ad"))(params),
        )
