"""Two-process execution: the GSPMD train step must be process-count-agnostic.

Every other multi-chip result in the suite runs on a single-process
8-virtual-device mesh; this is the SURVEY §5 "DCN for multi-slice" proof that
the code is actually mesh-shape-agnostic: two OS processes x 4 virtual CPU
devices each, wired by ``jax.distributed`` through
:func:`ddr_tpu.parallel.distributed.maybe_initialize` (the DDR_* env
contract), run ONE global 8-device GSPMD train step on the same synthetic
problem and must produce the single-process loss.

Unit tests for the env-var parsing live here too (fast); the subprocess pair
is marked slow (two CPU jit compiles of the train step).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from ddr_tpu.parallel.distributed import distributed_env

REPO = Path(__file__).resolve().parents[2]

WORKER = r"""
import json, os, sys

from ddr_tpu.parallel.distributed import maybe_initialize

assert maybe_initialize() is True
import jax
import jax.numpy as jnp
import numpy as np

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 4, len(jax.local_devices())

from ddr_tpu.geodatazoo.synthetic import make_basin, observe
from ddr_tpu.nn.kan import Kan
from ddr_tpu.parallel import make_mesh, reach_sharding, shard_channels, shard_network
from ddr_tpu.routing.mc import Bounds
from ddr_tpu.routing.model import prepare_batch
from ddr_tpu.training import make_batch_train_step, make_optimizer
from ddr_tpu.validation.configs import Config

cfg = Config(
    name="multiprocess_test",
    geodataset="synthetic",
    mode="training",
    kan={"input_var_names": [f"a{i}" for i in range(10)]},
    experiment={"start_time": "1981/10/01", "end_time": "1981/10/08", "rho": 6, "warmup": 1},
    params={"save_path": "/tmp"},
)
basin = observe(make_basin(n_segments=96, n_gauges=4, n_days=8, seed=3), cfg)
rd = basin.routing_data
network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
kan_model = Kan(
    input_var_names=tuple(cfg.kan.input_var_names),
    learnable_parameters=tuple(cfg.kan.learnable_parameters),
    hidden_size=cfg.kan.hidden_size,
    num_hidden_layers=cfg.kan.num_hidden_layers,
    grid=cfg.kan.grid,
    k=cfg.kan.k,
)
attrs = jnp.asarray(rd.normalized_spatial_attributes)
params = kan_model.init(jax.random.key(0), attrs)
optimizer = make_optimizer(1e-3)
opt_state = optimizer.init(params)
step = make_batch_train_step(
    kan_model,
    Bounds.from_config(cfg.params.attribute_minimums),
    cfg.params.parameter_ranges,
    cfg.params.log_space_parameters,
    cfg.params.defaults,
    tau=cfg.params.tau,
    warmup=1,
    optimizer=optimizer,
)
obs = jnp.asarray(basin.obs_daily)
mask = jnp.ones_like(obs, dtype=bool)
q_prime = jnp.asarray(basin.q_prime)

mesh = make_mesh(8)  # global mesh: spans both processes
with mesh:
    params2, _, loss, _ = step(
        params, opt_state,
        shard_network(mesh, network), shard_channels(mesh, channels), gauges,
        jax.device_put(attrs, reach_sharding(mesh, 0, 2)),
        jax.device_put(q_prime, reach_sharding(mesh, 1, 2)),
        obs, mask,
    )

# loss is replicated; the updated KAN params are replicated too — digest them
# so the parent can assert both processes computed the same update.
leaves = jax.tree_util.tree_leaves(params2)
digest = float(sum(np.abs(np.asarray(x)).sum() for x in leaves))
print("RESULT " + json.dumps({
    "process": jax.process_index(),
    "loss": float(loss),
    "param_digest": digest,
}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestDistributedEnv:
    def test_unset_is_single_process(self):
        assert distributed_env({}) is None

    def test_autodetect_flag(self):
        assert distributed_env({"DDR_DISTRIBUTED": "1"}) == {}
        assert distributed_env({"DDR_DISTRIBUTED": "0"}) is None

    def test_explicit_triple(self):
        spec = distributed_env(
            {
                "DDR_COORDINATOR": "10.0.0.1:1234",
                "DDR_NUM_PROCESSES": "4",
                "DDR_PROCESS_ID": "2",
            }
        )
        assert spec == {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }

    def test_partial_configuration_raises(self):
        with pytest.raises(ValueError, match="partial multi-process configuration"):
            distributed_env({"DDR_COORDINATOR": "10.0.0.1:1234"})

    def test_rank_out_of_range_raises(self):
        with pytest.raises(ValueError, match="out of range"):
            distributed_env(
                {
                    "DDR_COORDINATOR": "h:1",
                    "DDR_NUM_PROCESSES": "2",
                    "DDR_PROCESS_ID": "2",
                }
            )


@pytest.mark.slow
def test_two_process_gspmd_train_step_matches_single_process():
    """2 processes x 4 devices == 1 process x 8 devices, same loss and update."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PALLAS_AXON_POOL_IPS="",
            DDR_COORDINATOR=f"127.0.0.1:{port}",
            DDR_NUM_PROCESSES="2",
            DDR_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"process {pid} failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
        results[pid] = json.loads(line[len("RESULT "):])

    assert results[0]["process"] == 0 and results[1]["process"] == 1
    # both processes see the identical replicated loss and parameter update
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], rel=1e-12)
    assert results[0]["param_digest"] == pytest.approx(
        results[1]["param_digest"], rel=1e-12
    )

    # and the two-process result matches this (single-process, 8-device) process
    # running the identical problem — the in-suite GSPMD test already pins that
    # loss against the unsharded step, so transitively all three agree.
    import jax
    import jax.numpy as jnp

    from ddr_tpu.geodatazoo.synthetic import make_basin, observe
    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.parallel import make_mesh, reach_sharding, shard_channels, shard_network
    from ddr_tpu.routing.mc import Bounds
    from ddr_tpu.routing.model import prepare_batch
    from ddr_tpu.training import make_batch_train_step, make_optimizer
    from ddr_tpu.validation.configs import Config

    cfg = Config(
        name="multiprocess_test",
        geodataset="synthetic",
        mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"start_time": "1981/10/01", "end_time": "1981/10/08", "rho": 6, "warmup": 1},
        params={"save_path": "/tmp"},
    )
    basin = observe(make_basin(n_segments=96, n_gauges=4, n_days=8, seed=3), cfg)
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
        hidden_size=cfg.kan.hidden_size,
        num_hidden_layers=cfg.kan.num_hidden_layers,
        grid=cfg.kan.grid,
        k=cfg.kan.k,
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    optimizer = make_optimizer(1e-3)
    opt_state = optimizer.init(params)
    step = make_batch_train_step(
        kan_model,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges,
        cfg.params.log_space_parameters,
        cfg.params.defaults,
        tau=cfg.params.tau,
        warmup=1,
        optimizer=optimizer,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    q_prime = jnp.asarray(basin.q_prime)
    mesh = make_mesh(8)
    with mesh:
        params2, _, loss, _ = step(
            params, opt_state,
            shard_network(mesh, network), shard_channels(mesh, channels), gauges,
            jax.device_put(attrs, reach_sharding(mesh, 0, 2)),
            jax.device_put(q_prime, reach_sharding(mesh, 1, 2)),
            obs, mask,
        )
    leaves = jax.tree_util.tree_leaves(params2)
    digest = float(sum(np.abs(np.asarray(x)).sum() for x in leaves))
    assert results[0]["loss"] == pytest.approx(float(loss), rel=1e-5)
    assert results[0]["param_digest"] == pytest.approx(digest, rel=1e-6)


class TestDistributedFlagParsing:
    def test_case_insensitive_truthy(self):
        for v in ("1", "true", "True", "YES", "on"):
            assert distributed_env({"DDR_DISTRIBUTED": v}) == {}, v

    def test_falsy_values(self):
        for v in ("", "0", "false", "False", "no", "OFF"):
            assert distributed_env({"DDR_DISTRIBUTED": v}) is None, v

    def test_unrecognized_value_raises(self):
        with pytest.raises(ValueError, match="unrecognized DDR_DISTRIBUTED"):
            distributed_env({"DDR_DISTRIBUTED": "maybe"})
