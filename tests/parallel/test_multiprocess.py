"""Two-process execution: the GSPMD train step must be process-count-agnostic.

Every other multi-chip result in the suite runs on a single-process
8-virtual-device mesh; this is the SURVEY §5 "DCN for multi-slice" proof that
the code is actually mesh-shape-agnostic: two OS processes x 4 virtual CPU
devices each, wired by ``jax.distributed`` through
:func:`ddr_tpu.parallel.distributed.maybe_initialize` (the DDR_* env
contract), run ONE global 8-device GSPMD train step on the same synthetic
problem and must produce the single-process loss.

Unit tests for the env-var parsing live here too (fast); the subprocess pair
is marked slow (two CPU jit compiles of the train step).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

from ddr_tpu.parallel.distributed import distributed_env

REPO = Path(__file__).resolve().parents[2]

WORKER = r"""
import json

from ddr_tpu.parallel.distributed import maybe_initialize

assert maybe_initialize() is True
import jax

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 4, len(jax.local_devices())

# cwd is the repo root, so the SHARED problem definition is importable — the
# single-process comparison in the parent test runs this exact function.
from tests.parallel._mp_problem import run_gspmd_step

result = run_gspmd_step(8)  # global mesh: spans both processes
print("RESULT " + json.dumps({"process": jax.process_index(), **result}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]



def _run_two_process(worker_src: str, *argv: str, timeout: int = 900) -> dict[int, dict]:
    """Launch two jax.distributed worker processes (4 virtual CPU devices each,
    DDR_* env contract) running ``worker_src`` and collect each one's
    ``RESULT {json}`` line. The ONE launch recipe for every test here."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PALLAS_AXON_POOL_IPS="",
            DDR_COORDINATOR=f"127.0.0.1:{port}",
            DDR_NUM_PROCESSES="2",
            DDR_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", worker_src, *argv],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    results = {}
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"process {pid} failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
        results[pid] = json.loads(line[len("RESULT "):])
    return results


class TestDistributedEnv:
    def test_unset_is_single_process(self):
        assert distributed_env({}) is None

    def test_autodetect_flag(self):
        assert distributed_env({"DDR_DISTRIBUTED": "1"}) == {}
        assert distributed_env({"DDR_DISTRIBUTED": "0"}) is None

    def test_explicit_triple(self):
        spec = distributed_env(
            {
                "DDR_COORDINATOR": "10.0.0.1:1234",
                "DDR_NUM_PROCESSES": "4",
                "DDR_PROCESS_ID": "2",
            }
        )
        assert spec == {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }

    def test_partial_configuration_raises(self):
        with pytest.raises(ValueError, match="partial multi-process configuration"):
            distributed_env({"DDR_COORDINATOR": "10.0.0.1:1234"})

    def test_rank_out_of_range_raises(self):
        with pytest.raises(ValueError, match="out of range"):
            distributed_env(
                {
                    "DDR_COORDINATOR": "h:1",
                    "DDR_NUM_PROCESSES": "2",
                    "DDR_PROCESS_ID": "2",
                }
            )


@pytest.mark.slow
def test_two_process_gspmd_train_step_matches_single_process():
    """2 processes x 4 devices == 1 process x 8 devices, same loss and update."""
    results = _run_two_process(WORKER)

    assert results[0]["process"] == 0 and results[1]["process"] == 1
    # both processes see the identical replicated loss and parameter update
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], rel=1e-12)
    assert results[0]["param_digest"] == pytest.approx(
        results[1]["param_digest"], rel=1e-12
    )

    # and the two-process result matches this (single-process, 8-device) process
    # running the IDENTICAL problem (same shared builder the workers import) —
    # the in-suite GSPMD test already pins that loss against the unsharded
    # step, so transitively all three agree.
    from tests.parallel._mp_problem import run_gspmd_step

    single = run_gspmd_step(8)
    assert results[0]["loss"] == pytest.approx(single["loss"], rel=1e-5)
    assert results[0]["param_digest"] == pytest.approx(single["param_digest"], rel=1e-6)


class TestDistributedFlagParsing:
    def test_case_insensitive_truthy(self):
        for v in ("1", "true", "True", "YES", "on"):
            assert distributed_env({"DDR_DISTRIBUTED": v}) == {}, v

    def test_falsy_values(self):
        for v in ("", "0", "false", "False", "no", "OFF"):
            assert distributed_env({"DDR_DISTRIBUTED": v}) is None, v

    def test_unrecognized_value_raises(self):
        with pytest.raises(ValueError, match="unrecognized DDR_DISTRIBUTED"):
            distributed_env({"DDR_DISTRIBUTED": "maybe"})


class TestCpuCountDivisibility:
    """advisor r5: cpu:N under a multi-process launch must not silently
    ceil-divide — ceil(n/p)*p > n would build a larger global device set than
    `device` names and every mesh sized from it mis-shards."""

    def _launch_env(self, monkeypatch, n_procs: int):
        monkeypatch.setenv("DDR_COORDINATOR", "127.0.0.1:9999")
        monkeypatch.setenv("DDR_NUM_PROCESSES", str(n_procs))
        monkeypatch.setenv("DDR_PROCESS_ID", "0")

    def test_indivisible_count_raises(self, monkeypatch):
        from ddr_tpu.parallel.train import ensure_device_platform

        self._launch_env(monkeypatch, 2)
        with pytest.raises(ValueError, match="not divisible by the process count"):
            ensure_device_platform("cpu:7")

    def test_error_names_nearest_valid_counts(self, monkeypatch):
        from ddr_tpu.parallel.train import ensure_device_platform

        self._launch_env(monkeypatch, 4)
        with pytest.raises(ValueError, match=r"cpu:4 or cpu:8"):
            ensure_device_platform("cpu:6")

    def test_divisible_count_accepted(self, monkeypatch):
        from ddr_tpu.parallel.train import ensure_device_platform

        self._launch_env(monkeypatch, 2)
        ensure_device_platform("cpu:8")  # 4 per process: no raise


ORBAX_WORKER = r"""
import json, sys

from ddr_tpu.parallel.distributed import maybe_initialize

assert maybe_initialize() is True
import jax
import jax.numpy as jnp
import numpy as np

from ddr_tpu.training import load_state_orbax, make_optimizer, peek_orbax_meta, save_state_orbax

out_dir = sys.argv[1]
params = {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones(4)}
opt = make_optimizer(1e-3)
opt_state = opt.init(params)
# every process calls save (collective array write); only process 0 writes meta,
# and the barrier guarantees BOTH processes see a complete checkpoint afterwards
path = save_state_orbax(out_dir, "mp", epoch=4, mini_batch=1,
                        params=params, opt_state=opt_state, arch={"grid": 3})
meta = peek_orbax_meta(path, expected_arch={"grid": 3})
blob = load_state_orbax(path, target={"params": params, "opt_state": opt_state})
digest = float(sum(np.abs(np.asarray(x)).sum() for x in jax.tree_util.tree_leaves(blob["params"])))
print("RESULT " + json.dumps({
    "process": jax.process_index(), "epoch": meta["epoch"], "digest": digest,
}))
"""


@pytest.mark.slow
def test_two_process_orbax_save_and_load(tmp_path):
    """The multi-host orbax path end to end: collective save, process-0 meta
    write, post-meta barrier, and a collective targeted restore — both
    processes must see the complete checkpoint and identical state."""
    results = _run_two_process(ORBAX_WORKER, str(tmp_path), timeout=600)
    assert results[0]["epoch"] == results[1]["epoch"] == 4
    assert results[0]["digest"] == pytest.approx(results[1]["digest"], rel=1e-12)
    assert results[0]["digest"] == pytest.approx(70.0)  # sum(arange(12)) + sum(ones(4))


SWF_WORKER = r"""
import json

from ddr_tpu.parallel.distributed import maybe_initialize

assert maybe_initialize() is True
import jax

from tests.parallel._mp_problem import run_sharded_wavefront_step

result = run_sharded_wavefront_step(8)
print("RESULT " + json.dumps({"process": jax.process_index(), **result}))
"""


@pytest.mark.slow
def test_two_process_sharded_wavefront_step_matches_single_process():
    """The EXPLICIT-COLLECTIVE train step (shard_map, one psum per wave) is
    process-count-agnostic too: 2 processes x 4 devices reproduce this
    process's single-process 8-device loss and update."""
    results = _run_two_process(SWF_WORKER)

    assert results[0]["loss"] == pytest.approx(results[1]["loss"], rel=1e-12)
    # BOTH processes must hold the identical post-step parameters (a missed
    # psum in the backward could diverge the update while losses agree)
    assert results[0]["param_digest"] == pytest.approx(
        results[1]["param_digest"], rel=1e-12
    )
    from tests.parallel._mp_problem import run_sharded_wavefront_step

    single = run_sharded_wavefront_step(8)
    assert results[0]["loss"] == pytest.approx(single["loss"], rel=1e-5)
    assert results[0]["param_digest"] == pytest.approx(single["param_digest"], rel=1e-6)


CLI_TRAIN_WORKER = r"""
import json, sys

import jax
import numpy as np

from ddr_tpu.validation.configs import Config

# setup_run -> maybe_initialize wires jax.distributed from the DDR_* env —
# the EXACT path `ddr train` takes on a multi-host launch.
from ddr_tpu.scripts.common import setup_run
from ddr_tpu.scripts.train import train

out_dir = sys.argv[1]
cfg = setup_run(Config(
    name="mp_cli",
    geodataset="synthetic",
    mode="training",
    device="cpu:8",
    kan={"input_var_names": [f"a{i}" for i in range(10)]},
    experiment={
        "start_time": "1981/10/01", "end_time": "1981/10/16",
        "rho": 6, "batch_size": 2, "epochs": 1, "warmup": 1,
        "parallel": "gspmd",
    },
    params={"save_path": out_dir},
))
assert jax.process_count() == 2
params, _ = train(cfg, max_batches=1)
digest = float(sum(np.abs(np.asarray(x)).sum() for x in jax.tree_util.tree_leaves(params)))
print("RESULT " + json.dumps({"process": jax.process_index(), "param_digest": digest}))
"""


@pytest.mark.slow
def test_two_process_cli_train_collective_checkpoint(tmp_path):
    """The USER-FACING multi-host path: `ddr train` semantics (setup_run ->
    ParallelTrainer gspmd) across 2 processes x 4 devices sharing ONE save dir —
    both processes finish with identical parameters, the checkpoint is the
    COLLECTIVE orbax form (complete: meta.json present; no racing .pkl writes),
    and it restores."""
    results = _run_two_process(CLI_TRAIN_WORKER, str(tmp_path))
    # identical replicated post-step parameters on both hosts
    assert results[0]["param_digest"] == pytest.approx(
        results[1]["param_digest"], rel=1e-12
    )
    saved = tmp_path / "saved_models"
    orbax_dirs = list(saved.glob("*.orbax"))
    assert len(orbax_dirs) == 1, orbax_dirs
    assert (orbax_dirs[0] / "meta.json").exists()  # completeness marker
    assert not list(saved.glob("*.pkl"))  # no host-0 pickle racing the collective
    assert list((tmp_path / "plots").glob("*.png"))  # process-0 plot
    from ddr_tpu.training import load_state

    blob = load_state(orbax_dirs[0])
    assert blob["epoch"] == 1 and blob["mini_batch"] == 0
