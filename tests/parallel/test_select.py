"""Multi-chip engine selection policy (ddr_tpu.parallel.select) — VERDICT r4
item 5: one documented function arbitrating gspmd / sharded-wavefront /
stacked-sharded, shared by the forward router and the training CLI."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.parallel.select import route_parallel, select_parallel_engine

N_DEV = 8


class TestPolicy:
    def test_cpu_always_gspmd(self):
        """Host meshes invert the explicit engines (MULTICHIP_r04 scale rows:
        gspmd 210ms vs wavefront 5060ms) — gspmd regardless of shape."""
        assert select_parallel_engine("cpu", 8192, 40, 8) == "gspmd"
        assert select_parallel_engine("cpu", 2_900_000, 4000, 256) == "gspmd"

    def test_tpu_shallow_is_sharded_wavefront(self):
        assert select_parallel_engine("tpu", 65536, 200, 8) == "sharded-wavefront"

    def test_tpu_deep_is_stacked_sharded(self):
        """Past the per-shard ring feasibility (single_ring_eligible on
        (depth+2)*(n/S+1)) the banded scan engine takes over."""
        assert select_parallel_engine("tpu", 2_900_000, 4000, 8) == "stacked-sharded"
        # sharding CAN rescue feasibility: same depth, many more shards
        n, depth = 65536, 1000
        assert select_parallel_engine("tpu", n, depth, 8) == "sharded-wavefront"
        assert select_parallel_engine("tpu", n, 1200, 8) == "stacked-sharded"


class TestRouteParallel:
    def _problem(self, n, depth, T, seed=0):
        """ORIGINAL-order inputs — route_parallel pads/partitions internally
        (the engine, and with it the layout, is only decided inside)."""
        from ddr_tpu.geodatazoo.synthetic import make_basin
        from ddr_tpu.parallel import make_mesh
        from ddr_tpu.routing.model import prepare_channels

        if len(jax.devices()) < N_DEV:
            pytest.skip(f"needs {N_DEV} devices")
        basin = make_basin(n_segments=n, n_gauges=2, n_days=1, seed=seed, depth=depth)
        rd = basin.routing_data
        channels, _ = prepare_channels(rd, 0.001)
        spatial = {
            "n": jnp.full(n, 0.05),
            "q_spatial": jnp.full(n, 0.4),
            "p_spatial": jnp.full(n, 21.0),
        }
        qp = jnp.asarray(basin.q_prime[:T])
        return make_mesh(N_DEV), rd, channels, spatial, qp

    def _reference(self, rd, channels, spatial, qp):
        from ddr_tpu.routing.mc import route
        from ddr_tpu.routing.network import build_network

        return route(
            build_network(rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, fused=False),
            channels, spatial, qp, engine="step",
        ).runoff

    def test_policy_engine_matches_reference(self):
        """route_parallel on the virtual CPU mesh: policy picks gspmd, and the
        ORIGINAL-order result matches the single-program step engine."""
        mesh, rd, channels, spatial, qp = self._problem(n=256, depth=None, T=6)
        res = route_parallel(mesh, rd, channels, spatial, qp)
        assert res.engine == "gspmd"  # cpu platform -> policy row 1
        runoff = res.runoff
        ref = self._reference(rd, channels, spatial, qp)
        np.testing.assert_allclose(np.asarray(runoff), np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_forced_engine_overrides_policy(self):
        mesh, rd, channels, spatial, qp = self._problem(n=128, depth=None, T=3)
        res = route_parallel(
            mesh, rd, channels, spatial, qp, engine="sharded-wavefront"
        )
        assert res.engine == "sharded-wavefront"
        runoff = res.runoff
        ref = self._reference(rd, channels, spatial, qp)
        np.testing.assert_allclose(np.asarray(runoff), np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_non_shard_multiple_batch(self):
        """n not divisible by the mesh: the internal pad/partition must make
        every engine work on an arbitrary batch size and return original order."""
        mesh, rd, channels, spatial, qp = self._problem(n=93, depth=None, T=3)
        ref = self._reference(rd, channels, spatial, qp)
        for engine in ("gspmd", "sharded-wavefront", "stacked-sharded"):
            res = route_parallel(mesh, rd, channels, spatial, qp, engine=engine)
            assert res.engine == engine
            assert res.runoff.shape == (3, 93)
            assert res.final_discharge.shape == (93,)
            np.testing.assert_allclose(
                np.asarray(res.runoff), np.asarray(ref), rtol=1e-4, atol=1e-5,
                err_msg=engine,
            )

    def test_unknown_engine_raises(self):
        mesh, rd, channels, spatial, qp = self._problem(n=64, depth=None, T=2)
        with pytest.raises(ValueError, match="unknown parallel engine"):
            route_parallel(mesh, rd, channels, spatial, qp, engine="bogus")

    def test_plan_cache_verifies_mesh_identity(self):
        """advisor r5: the plan cache keys on id(mesh); a recycled address
        (new mesh object inheriting a dead mesh's id) must NOT hit the stale
        plan. Entries store (mesh, plan) and a hit verifies `is` identity —
        simulated here by planting a poisoned entry under the live mesh's key."""
        from ddr_tpu.parallel.select import _plan_cache, _topology_key

        mesh, rd, channels, spatial, qp = self._problem(n=64, depth=None, T=2)
        from ddr_tpu.routing.mc import Bounds

        key = _topology_key(rd, N_DEV, "gspmd", Bounds(), mesh, "auto", "fp32")

        def poisoned_plan(*a, **k):
            raise AssertionError("stale plan from a recycled mesh id was executed")

        # the cached mesh is a DIFFERENT object that (by simulation) produced
        # the same key — exactly what id() reuse after GC looks like
        other_mesh = object()
        _plan_cache()[key] = (other_mesh, poisoned_plan)
        res = route_parallel(mesh, rd, channels, spatial, qp, engine="gspmd")
        assert res.runoff.shape == (2, 64)  # rebuilt, not poisoned
        cached_mesh, _ = _plan_cache()[key]
        assert cached_mesh is mesh  # the rebuild replaced the stale entry

    def test_plan_cache_reuses_plan_for_same_mesh(self):
        """Sanity check on the fix: identity verification must not defeat the
        cache — a repeat call with the SAME mesh reuses the entry."""
        from ddr_tpu.parallel.select import _plan_cache

        mesh, rd, channels, spatial, qp = self._problem(n=64, depth=None, T=2)
        route_parallel(mesh, rd, channels, spatial, qp, engine="gspmd")
        size = len(_plan_cache())
        route_parallel(mesh, rd, channels, spatial, qp, engine="gspmd")
        assert len(_plan_cache()) == size


def test_auto_mode_resolves_per_policy(tmp_path):
    """experiment.parallel=auto through ParallelTrainer: on the CPU mesh the
    policy resolves gspmd and the prepared batch says so."""
    from ddr_tpu.geodatazoo.synthetic import make_basin, observe
    from ddr_tpu.parallel.train import ParallelTrainer
    from ddr_tpu.scripts.common import build_kan
    from ddr_tpu.training import make_optimizer
    from ddr_tpu.validation.configs import Config

    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    cfg = Config(
        name="auto_run",
        geodataset="synthetic",
        mode="training",
        device=f"cpu:{N_DEV}",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"rho": 4, "warmup": 1, "parallel": "auto"},
        params={"save_path": str(tmp_path)},
    )
    basin = make_basin(n_segments=64, n_gauges=2, n_days=3, seed=1)
    basin = observe(basin, cfg)
    kan_model, params = build_kan(cfg)
    optimizer = make_optimizer(1e-3)
    par = ParallelTrainer(cfg, kan_model, optimizer)
    prep = par.prepare(basin.routing_data, np.asarray(basin.q_prime, np.float32))
    assert prep.mode == "gspmd"
    obs = np.asarray(basin.obs_daily, np.float32)
    mask = np.ones_like(obs, dtype=bool)
    _, _, loss, _ = par.step(prep, params, optimizer.init(params), obs, mask)
    assert np.isfinite(float(loss))


class TestParallelInference:
    """dmc with experiment.parallel set: `ddr route`/`ddr test` chunked
    inference rides route_parallel — including carried state — and must match
    the single-device wrapper exactly."""

    def _cfgs(self, tmp_path, mode):
        from ddr_tpu.validation.configs import Config

        def mk(parallel):
            return Config(
                name="par_inf",
                geodataset="synthetic",
                mode="testing",
                device=f"cpu:{N_DEV}" if parallel != "none" else "cpu",
                kan={"input_var_names": [f"a{i}" for i in range(10)]},
                experiment={"rho": 4, "parallel": parallel},
                params={"save_path": str(tmp_path)},
            )

        return mk("none"), mk(mode)

    @pytest.mark.parametrize("mode", ["auto", "stacked-sharded"])
    def test_chunked_inference_matches_single_device(self, tmp_path, mode):
        from ddr_tpu.geodatazoo.synthetic import make_basin
        from ddr_tpu.routing.model import dmc

        if len(jax.devices()) < N_DEV:
            pytest.skip(f"needs {N_DEV} devices")
        cfg_ref, cfg_par = self._cfgs(tmp_path, mode)
        basin = make_basin(n_segments=61, n_gauges=3, n_days=3, seed=4)
        rd = basin.routing_data
        raw = {
            "n": jnp.full(61, 0.4),
            "q_spatial": jnp.full(61, 0.5),
        }
        qp = np.asarray(basin.q_prime, np.float32)
        h = qp.shape[0] // 2
        ref_m, par_m = dmc(cfg_ref), dmc(cfg_par)
        # two sequential chunks with carried state through both wrappers
        ref_a = ref_m.forward(rd, qp[:h], raw)["runoff"]
        ref_b = ref_m.forward(rd, qp[h:], raw, carry_state=True)["runoff"]
        par_a = par_m.forward(rd, qp[:h], raw)["runoff"]
        par_b = par_m.forward(rd, qp[h:], raw, carry_state=True)["runoff"]
        np.testing.assert_allclose(np.asarray(par_a), np.asarray(ref_a), rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(par_b), np.asarray(ref_b), rtol=2e-4, atol=1e-5)


def test_route_parallel_accepts_scalar_spatial(tmp_path):
    """route()'s contract allows scalar parameters; the parallel dispatcher
    must broadcast them instead of crashing in the pad/permute machinery."""
    from ddr_tpu.geodatazoo.synthetic import make_basin
    from ddr_tpu.parallel import make_mesh
    from ddr_tpu.routing.model import prepare_channels

    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    basin = make_basin(n_segments=21, n_gauges=2, n_days=1, seed=2)
    rd = basin.routing_data
    channels, _ = prepare_channels(rd, 0.001)
    spatial = {
        "n": jnp.full(21, 0.05),
        "q_spatial": jnp.full(21, 0.4),
        "p_spatial": jnp.float32(21.0),  # scalar — allowed by route()
    }
    qp = jnp.asarray(basin.q_prime[:2])
    res = route_parallel(make_mesh(N_DEV), rd, channels, spatial, qp, engine="gspmd")
    assert res.runoff.shape == (2, 21)
    assert np.isfinite(np.asarray(res.runoff)).all()


class TestEngineAxes:
    """The policy's kernel/dtype axes (resolve_engine_axes): honored on gspmd,
    auto-fallback on the shard_map engines, explicit pallas/bf16 raises there."""

    def test_gspmd_passes_kernel_through_unresolved(self):
        """gspmd defers resolution to the route itself: whether pallas is
        usable depends on the engine the built network actually runs (a
        non-wavefront-eligible topology routes via the step engine, where
        auto must stay a no-op)."""
        from ddr_tpu.parallel.select import resolve_engine_axes

        assert resolve_engine_axes("gspmd", None, "fp32") == (None, "fp32")
        assert resolve_engine_axes("gspmd", "xla", "bf16") == ("xla", "bf16")
        assert resolve_engine_axes("gspmd", "pallas", "fp32") == ("pallas", "fp32")
        import pytest

        with pytest.raises(ValueError, match="kernel"):
            resolve_engine_axes("gspmd", "cuda", "fp32")

    def test_shard_map_engines_auto_fall_back(self):
        from ddr_tpu.parallel.select import resolve_engine_axes

        for engine in ("sharded-wavefront", "stacked-sharded"):
            assert resolve_engine_axes(engine, None, "fp32") == ("xla", "fp32")

    def test_shard_map_engines_reject_explicit_pallas_and_bf16(self):
        import pytest

        from ddr_tpu.parallel.select import resolve_engine_axes

        with pytest.raises(NotImplementedError, match="pallas"):
            resolve_engine_axes("sharded-wavefront", "pallas", "fp32")
        with pytest.raises(NotImplementedError, match="bf16"):
            resolve_engine_axes("stacked-sharded", None, "bf16")

    def test_bad_dtype_rejected(self):
        import pytest

        from ddr_tpu.parallel.select import resolve_engine_axes

        with pytest.raises(ValueError, match="dtype"):
            resolve_engine_axes("gspmd", None, "fp16")


class TestTopologyStatsMemo:
    """The derived-stat memo (topology_stats): chunked inference asks once per
    time chunk of the same reach set — the O(E) Kahn layering must run once."""

    def test_memoized_by_cache_key(self, monkeypatch):
        from ddr_tpu.parallel.select import topology_stats
        from ddr_tpu.routing import network

        calls = []
        orig = network.compute_levels

        def spy(rows, cols, n):
            calls.append(int(n))
            return orig(rows, cols, n)

        monkeypatch.setattr(network, "compute_levels", spy)
        rows = np.arange(1, 9, dtype=np.int64)
        cols = np.arange(0, 8, dtype=np.int64)
        s1 = topology_stats(rows, cols, 9, cache_key="memo-chain-9")
        s2 = topology_stats(rows, cols, 9, cache_key="memo-chain-9")
        assert s1 == s2 == (9, 8, 8, 1)
        assert len(calls) == 1, "repeat selection re-ran the O(E) layering"
        topology_stats(rows, cols, 9)  # no key -> nothing to memo under
        assert len(calls) == 2

    def test_cpu_short_circuit_never_layers(self, monkeypatch):
        """select_for_topology's cpu row answers without deriving stats at all
        (the policy doesn't consult depth there)."""
        from ddr_tpu.parallel.select import select_for_topology
        from ddr_tpu.routing import network

        def boom(rows, cols, n):  # pragma: no cover - must not run
            raise AssertionError("cpu row derived topology stats")

        monkeypatch.setattr(network, "compute_levels", boom)
        rows = np.arange(1, 9, dtype=np.int64)
        cols = np.arange(0, 8, dtype=np.int64)
        assert select_for_topology("cpu", rows, cols, 9, 8) == "gspmd"
