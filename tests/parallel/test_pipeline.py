"""Pipelined wavefront router tests: exact agreement with the single-device engine
on an 8-virtual-device CPU mesh (the multi-chip analog of the reference's CPU-only
CI, SURVEY.md §4)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.parallel import (
    make_mesh,
    permute_routing_data,
    topological_range_partition,
)
from ddr_tpu.parallel.pipeline import build_pipeline_schedule, pipelined_route
from ddr_tpu.routing.mc import route
from ddr_tpu.routing.model import prepare_batch
from ddr_tpu.routing.network import build_network as build_network_for

N, S, T_DAYS = 64, 8, 4


@pytest.fixture(scope="module")
def partitioned():
    basin = make_basin(n_segments=N, n_gauges=4, n_days=T_DAYS, seed=3)
    rd = basin.routing_data
    part = topological_range_partition(rd.adjacency_rows, rd.adjacency_cols, N, S)
    rd = permute_routing_data(rd, part)
    network, channels, _ = prepare_batch(rd, 0.001)
    params = {
        k: jnp.asarray(np.asarray(v)[part.perm], jnp.float32)
        for k, v in basin.true_params.items()
    }
    q_prime = jnp.asarray(basin.q_prime[:, part.perm])
    return rd, network, channels, params, q_prime


class TestScheduleBuilder:
    def test_rejects_indivisible_n(self):
        with pytest.raises(ValueError, match="divisible"):
            build_pipeline_schedule(np.array([1]), np.array([0]), 10, 4)

    def test_rejects_backward_edges(self):
        # Edge from shard 1 (node 3) down to shard 0 (node 0): not partitioned order.
        with pytest.raises(ValueError, match="lower shards"):
            build_pipeline_schedule(np.array([0]), np.array([3]), 4, 2)

    def test_boundary_accounting(self, partitioned):
        rd, *_ = partitioned
        sched = build_pipeline_schedule(rd.adjacency_rows, rd.adjacency_cols, N, S)
        n_local = N // S
        cross = (
            np.asarray(rd.adjacency_cols) // n_local
            != np.asarray(rd.adjacency_rows) // n_local
        ).sum()
        assert sched.n_boundary == max(1, cross)
        assert int((np.asarray(sched.delay) >= 1).sum()) == sched.n_boundary


class TestPipelinedRoute:
    def test_matches_single_device_route(self, partitioned):
        rd, network, channels, params, q_prime = partitioned
        want = route(network, channels, params, q_prime, gauges=None)

        mesh = make_mesh(S)
        sched = build_pipeline_schedule(rd.adjacency_rows, rd.adjacency_cols, N, S)
        runoff, q_fin = pipelined_route(mesh, sched, channels, params, q_prime)

        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(want.runoff), rtol=2e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(q_fin), np.asarray(want.final_discharge), rtol=2e-5, atol=1e-5
        )

    def test_hotstart_with_dry_reaches(self, partitioned):
        # Regression: q_prime[0] entries below the discharge floor (dry reaches)
        # must reach the hotstart solve RAW — hotstart_discharge clamps only the
        # result, and the pre-clamp error accumulates downstream.
        rd, network, channels, params, q_prime = partitioned
        q_prime = q_prime.at[0].set(0.0)
        want = route(network, channels, params, q_prime, gauges=None)
        mesh = make_mesh(S)
        sched = build_pipeline_schedule(rd.adjacency_rows, rd.adjacency_cols, N, S)
        runoff, _ = pipelined_route(mesh, sched, channels, params, q_prime)
        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(want.runoff), rtol=2e-5, atol=1e-5
        )

    def test_matches_with_carry_state(self, partitioned):
        rd, network, channels, params, q_prime = partitioned
        q_init = jnp.asarray(np.random.default_rng(1).uniform(0.5, 3.0, N), jnp.float32)
        want = route(network, channels, params, q_prime, q_init=q_init, gauges=None)

        mesh = make_mesh(S)
        sched = build_pipeline_schedule(rd.adjacency_rows, rd.adjacency_cols, N, S)
        runoff, q_fin = pipelined_route(mesh, sched, channels, params, q_prime, q_init=q_init)

        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(want.runoff), rtol=2e-5, atol=1e-5
        )

    @pytest.mark.parametrize(
        ("name", "rows", "cols", "n"),
        [
            ("star", np.full(7, 7), np.arange(7), 8),  # delays 1..7 into one sink
            ("skip", np.array([2, 4, 6, 3, 5, 7]), np.array([0, 2, 4, 1, 3, 5]), 8),
        ],
    )
    def test_multi_hop_delays_with_carry_state(self, name, rows, cols, n):
        # Regression: the boundary stream must carry RAW solve outputs — clamped
        # values diverge whenever an upstream solve goes below the discharge floor
        # (caught on exactly these topologies).
        from ddr_tpu.routing.mc import ChannelState

        rng = np.random.default_rng(0)
        network = build_network_for(rows, cols, n)
        channels = ChannelState(
            length=jnp.asarray(rng.uniform(1000, 3000, n), jnp.float32),
            slope=jnp.asarray(rng.uniform(0.001, 0.01, n), jnp.float32),
            x_storage=jnp.full(n, 0.3, jnp.float32),
        )
        params = {
            "n": jnp.full(n, 0.05),
            "p_spatial": jnp.full(n, 21.0),
            "q_spatial": jnp.full(n, 0.5),
        }
        q_prime = jnp.asarray(rng.uniform(0.1, 1.0, (4, n)), jnp.float32)
        q_init = jnp.asarray(rng.uniform(0.5, 3.0, n), jnp.float32)
        want = route(network, channels, params, q_prime, q_init=q_init, gauges=None)
        sched = build_pipeline_schedule(rows, cols, n, n)
        got, _ = pipelined_route(
            make_mesh(n), sched, channels, params, q_prime, q_init=q_init
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want.runoff), rtol=2e-5, atol=1e-5
        )

    def test_single_shard_degenerates_to_route(self):
        basin = make_basin(n_segments=32, n_gauges=2, n_days=3, seed=5)
        rd = basin.routing_data
        network, channels, _ = prepare_batch(rd, 0.001)
        params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
        q_prime = jnp.asarray(basin.q_prime)
        want = route(network, channels, params, q_prime, gauges=None)

        mesh = make_mesh(1)
        sched = build_pipeline_schedule(rd.adjacency_rows, rd.adjacency_cols, 32, 1)
        runoff, _ = pipelined_route(mesh, sched, channels, params, q_prime)
        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(want.runoff), rtol=2e-5, atol=1e-5
        )
