"""Sharded depth-chunked wavefront: the engine composition that fits CONUS depth
in per-chip HBM (bands bound the per-shard ring; shards parallelize each band).
Every configuration must match the single-program step engine — the in-repo
oracle — to float32-reassociation tolerance, forward and backward."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_deep_network
from ddr_tpu.parallel import build_sharded_chunked, make_mesh, route_chunked_sharded
from ddr_tpu.routing.mc import ChannelState, route
from ddr_tpu.routing.network import build_network

N_DEV = 8


def _setup(n, depth, T, seed=2):
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    rows, cols = make_deep_network(n, depth, seed=seed)
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.full(n, 0.5),
        "p_spatial": jnp.full(n, 21.0),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
    net = build_network(rows, cols, n, fused=False)
    return rows, cols, net, channels, params, qp


def _rel(a, b):
    return float(jnp.max(jnp.abs(a - b) / (jnp.abs(b) + 1e-6)))


@pytest.mark.parametrize("cell_budget", [200_000, 3_000])
def test_matches_step_engine(cell_budget):
    n, depth, T = 600, 150, 10
    rows, cols, net, channels, params, qp = _setup(n, depth, T)
    ref = route(net, channels, params, qp, engine="step")
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=cell_budget)
    with make_mesh(N_DEV):
        runoff, final = route_chunked_sharded(make_mesh(N_DEV), layout, channels, params, qp)
    assert _rel(runoff, ref.runoff) < 1e-4
    assert _rel(final, ref.final_discharge) < 1e-4


def test_multi_band_with_shard_padding():
    """Band sizes not divisible by the shard count force sentinel pad slots —
    outputs must still be exact and pad values must never leak."""
    n, depth, T = 500, 120, 8  # odd band populations under a tiny budget
    rows, cols, net, channels, params, qp = _setup(n, depth, T, seed=5)
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=1_500)
    assert layout.n_bands > 1
    assert any(int(g.shape[0]) % N_DEV == 0 for g in layout.gidx)
    ref = route(net, channels, params, qp, engine="step")
    with make_mesh(N_DEV):
        runoff, _ = route_chunked_sharded(make_mesh(N_DEV), layout, channels, params, qp)
    assert runoff.shape == (T, n)  # pad slots dropped on reassembly
    assert _rel(runoff, ref.runoff) < 1e-4


def test_carry_state_parity():
    n, depth, T = 400, 100, 8
    rows, cols, net, channels, params, qp = _setup(n, depth, T, seed=4)
    qi = jnp.asarray(np.random.default_rng(0).uniform(0.1, 2.0, n), jnp.float32)
    ref = route(net, channels, params, qp, q_init=qi, engine="step")
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=2_000)
    with make_mesh(N_DEV):
        runoff, final = route_chunked_sharded(
            make_mesh(N_DEV), layout, channels, params, qp, q_init=qi
        )
    assert _rel(runoff, ref.runoff) < 1e-4
    assert _rel(final, ref.final_discharge) < 1e-4


def test_gradient_parity_with_step_engine():
    n, depth, T = 400, 100, 8
    rows, cols, net, channels, params, qp = _setup(n, depth, T, seed=4)
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=2_000)
    assert layout.n_bands > 1
    mesh = make_mesh(N_DEV)

    def mk(nm):
        return dict(params, n=nm)

    nm0 = params["n"]
    g_ref = jax.grad(lambda nm: jnp.mean(route(net, channels, mk(nm), qp, engine="step").runoff ** 2))(nm0)
    with mesh:
        g_sc = jax.grad(
            lambda nm: jnp.mean(route_chunked_sharded(mesh, layout, channels, mk(nm), qp)[0] ** 2)
        )(nm0)
    # same math, different reassociation (measured f64 agreement ~1e-12 for the
    # engine family); float32 noise bounded like the other engines' grad tests
    assert float(jnp.max(jnp.abs(g_ref - g_sc) / (jnp.abs(g_ref) + 1e-5))) < 2e-2


def test_per_shard_ring_budget_honored():
    """Every band's per-shard ring (depth+2)x(n_local+1) stays within budget."""
    n, depth = 600, 150
    rows, cols, *_ = _setup(n, depth, 4)
    budget = 1_500
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=budget)
    for sched in layout.bands:
        assert (sched.depth + 2) * (sched.n_local + 1) <= budget or sched.depth == 0
