"""Sharded depth-chunked wavefront: the engine composition that fits CONUS depth
in per-chip HBM (bands bound the per-shard ring; shards parallelize each band).
Every configuration must match the single-program step engine — the in-repo
oracle — to float32-reassociation tolerance, forward and backward."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_deep_network
from ddr_tpu.parallel import build_sharded_chunked, make_mesh, route_chunked_sharded
from ddr_tpu.routing.mc import ChannelState, route
from ddr_tpu.routing.network import build_network

N_DEV = 8


def _setup(n, depth, T, seed=2):
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    rows, cols = make_deep_network(n, depth, seed=seed)
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.full(n, 0.5),
        "p_spatial": jnp.full(n, 21.0),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
    net = build_network(rows, cols, n, fused=False)
    return rows, cols, net, channels, params, qp


def _rel(a, b):
    return float(jnp.max(jnp.abs(a - b) / (jnp.abs(b) + 1e-6)))


@pytest.mark.parametrize(
    "cell_budget",
    [200_000, pytest.param(3_000, marks=pytest.mark.slow)],
)
def test_matches_step_engine(cell_budget):
    n, depth, T = 240, 60, 6
    rows, cols, net, channels, params, qp = _setup(n, depth, T)
    ref = route(net, channels, params, qp, engine="step")
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=cell_budget)
    with make_mesh(N_DEV):
        runoff, final = route_chunked_sharded(make_mesh(N_DEV), layout, channels, params, qp)
    assert _rel(runoff, ref.runoff) < 1e-4
    assert _rel(final, ref.final_discharge) < 1e-4


@pytest.mark.slow
def test_multi_band_with_shard_padding():
    """Band sizes not divisible by the shard count force sentinel pad slots —
    outputs must still be exact and pad values must never leak."""
    n, depth, T = 500, 120, 8  # odd band populations under a tiny budget
    rows, cols, net, channels, params, qp = _setup(n, depth, T, seed=5)
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=1_500)
    assert layout.n_bands > 1
    assert any(int(g.shape[0]) % N_DEV == 0 for g in layout.gidx)
    ref = route(net, channels, params, qp, engine="step")
    with make_mesh(N_DEV):
        runoff, _ = route_chunked_sharded(make_mesh(N_DEV), layout, channels, params, qp)
    assert runoff.shape == (T, n)  # pad slots dropped on reassembly
    assert _rel(runoff, ref.runoff) < 1e-4


@pytest.mark.slow
def test_carry_state_parity():
    n, depth, T = 400, 100, 8
    rows, cols, net, channels, params, qp = _setup(n, depth, T, seed=4)
    qi = jnp.asarray(np.random.default_rng(0).uniform(0.1, 2.0, n), jnp.float32)
    ref = route(net, channels, params, qp, q_init=qi, engine="step")
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=2_000)
    with make_mesh(N_DEV):
        runoff, final = route_chunked_sharded(
            make_mesh(N_DEV), layout, channels, params, qp, q_init=qi
        )
    assert _rel(runoff, ref.runoff) < 1e-4
    assert _rel(final, ref.final_discharge) < 1e-4


@pytest.mark.slow
def test_gradient_parity_with_step_engine():
    n, depth, T = 400, 100, 8
    rows, cols, net, channels, params, qp = _setup(n, depth, T, seed=4)
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=2_000)
    assert layout.n_bands > 1
    mesh = make_mesh(N_DEV)

    def mk(nm):
        return dict(params, n=nm)

    nm0 = params["n"]
    g_ref = jax.grad(lambda nm: jnp.mean(route(net, channels, mk(nm), qp, engine="step").runoff ** 2))(nm0)
    with mesh:
        g_sc = jax.grad(
            lambda nm: jnp.mean(route_chunked_sharded(mesh, layout, channels, mk(nm), qp)[0] ** 2)
        )(nm0)
    # same math, different reassociation (measured f64 agreement ~1e-12 for the
    # engine family); float32 noise bounded like the other engines' grad tests
    assert float(jnp.max(jnp.abs(g_ref - g_sc) / (jnp.abs(g_ref) + 1e-5))) < 2e-2


def test_per_shard_ring_budget_honored():
    """Every band's per-shard ring (depth+2)x(n_local+1) stays within budget."""
    n, depth = 600, 150
    rows, cols, *_ = _setup(n, depth, 4)
    budget = 1_500
    layout = build_sharded_chunked(rows, cols, n, N_DEV, cell_budget=budget)
    for sched in layout.bands:
        assert (sched.depth + 2) * (sched.n_local + 1) <= budget or sched.depth == 0


@pytest.mark.slow
def test_train_step_descends_at_depth():
    """Full training step over the composed engine on a DEEP twin experiment:
    KAN -> sharded-chunked route -> masked L1 -> backward -> optimizer, loss
    descending — the builder multi-chip training uses at continental depth."""
    from ddr_tpu.geodatazoo.synthetic import make_basin, observe
    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.routing.mc import Bounds, GaugeIndex
    from ddr_tpu.routing.model import prepare_channels
    from ddr_tpu.training import make_optimizer, make_sharded_chunked_train_step
    from ddr_tpu.validation.configs import Config

    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    cfg = Config(
        name="t", geodataset="synthetic", mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"rho": 3, "warmup": 1},
    )
    basin = observe(make_basin(n_segments=256, n_gauges=4, n_days=3, seed=0, depth=96), cfg)
    rd = basin.routing_data
    channels, gauges = prepare_channels(rd, 1e-4)
    if gauges is None:
        gauges = GaugeIndex.from_ragged(rd.outflow_idx)
    layout = build_sharded_chunked(
        rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, N_DEV, cell_budget=3_000
    )
    assert layout.n_bands > 1
    kan = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
        hidden_size=cfg.kan.hidden_size,
        num_hidden_layers=cfg.kan.num_hidden_layers,
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan.init(jax.random.PRNGKey(0), attrs)
    opt = make_optimizer(1e-3)
    step = make_sharded_chunked_train_step(
        kan, make_mesh(N_DEV), layout, channels, gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges, cfg.params.log_space_parameters,
        cfg.params.defaults, tau=cfg.params.tau, warmup=1, optimizer=opt,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    qp = jnp.asarray(basin.q_prime)
    state = opt.init(params)
    losses = []
    for _ in range(3):
        params, state, loss, _ = step(params, state, attrs, qp, obs, mask)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
