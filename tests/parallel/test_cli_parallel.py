"""CLI-reachable multi-chip training (ddr_tpu.parallel.train): every
``experiment.parallel`` mode runs end-to-end through ``scripts.train.train`` on
the virtual 8-device mesh, and each mode's single step matches the single-device
batch step's loss on the same batch (the objective is shared, so only the
schedule may differ — VERDICT r4 item 2)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.parallel.train import PARALLEL_MODES, ParallelTrainer, parse_device
from ddr_tpu.validation.configs import Config

N_DEV = 8

ENGINE_MODES = [m for m in PARALLEL_MODES if m != "none"]
# Fast-leg parity rungs: gspmd + stacked-sharded. "auto" resolves to gspmd on
# the CPU mesh (identical engine; selection itself is pinned in test_select),
# and the sharded-wavefront step has its own train-step tests — both stay on
# the slow leg here.
PARITY_MODES = [
    "gspmd",
    "stacked-sharded",
    pytest.param("auto", marks=pytest.mark.slow),
    pytest.param("sharded-wavefront", marks=pytest.mark.slow),
]


def _need_devices():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")


class TestParseDevice:
    def test_forms(self):
        assert parse_device("tpu") == ("tpu", None)
        assert parse_device("cpu") == ("cpu", None)
        assert parse_device("cpu:8") == ("cpu", 8)
        assert parse_device("tpu:4") == ("tpu", 4)

    def test_rejects_bad_count(self):
        with pytest.raises(ValueError, match="integer"):
            parse_device("cpu:eight")
        with pytest.raises(ValueError, match=">= 1"):
            parse_device("cpu:0")


class TestPadRoutingData:
    def test_pad_preserves_routing(self):
        """Padded batch routes identically at every real reach."""
        from ddr_tpu.geodatazoo.synthetic import make_basin
        from ddr_tpu.parallel.partition import pad_routing_data
        from ddr_tpu.routing.mc import route
        from ddr_tpu.routing.model import prepare_batch

        basin = make_basin(n_segments=21, n_gauges=2, n_days=2, seed=3)
        rd = basin.routing_data
        rd_pad = pad_routing_data(rd, N_DEV)
        assert rd_pad.n_segments == 24
        assert rd_pad.n_segments % N_DEV == 0
        # multiple-already: identity
        assert pad_routing_data(rd_pad, N_DEV) is rd_pad

        qp = jnp.asarray(basin.q_prime)
        qp_pad = jnp.concatenate([qp, jnp.zeros((qp.shape[0], 3))], axis=1)
        spatial = {
            "n": jnp.full(21, 0.03),
            "q_spatial": jnp.full(21, 0.5),
            "p_spatial": jnp.full(21, 21.0),
        }
        spatial_pad = {k: jnp.concatenate([v, jnp.full(3, 0.5)]) for k, v in spatial.items()}
        net, ch, _ = prepare_batch(rd, 0.001)
        net_p, ch_p, _ = prepare_batch(rd_pad, 0.001)
        out = route(net, ch, spatial, qp).runoff
        out_p = route(net_p, ch_p, spatial_pad, qp_pad).runoff
        np.testing.assert_allclose(np.asarray(out_p[:, :21]), np.asarray(out), rtol=1e-6)


def _synthetic_cfg(tmp_path, **exp):
    return Config(
        name="par_run",
        geodataset="synthetic",
        mode="training",
        device=f"cpu:{N_DEV}",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={
            "start_time": "1981/10/01",
            "end_time": "1981/10/20",
            "rho": 8,
            "batch_size": 2,
            "epochs": 1,
            "warmup": 1,
            "learning_rate": {1: 0.01},
            **exp,
        },
        params={"save_path": str(tmp_path)},
    )


@pytest.mark.slow
@pytest.mark.parametrize("mode", ENGINE_MODES)
def test_train_cli_end_to_end(tmp_path, mode):
    """`ddr train ... experiment.parallel=<mode> device=cpu:8` equivalent: two
    mini-batches through the real training loop, checkpoints + plots written."""
    from ddr_tpu.scripts.train import train

    _need_devices()
    cfg = _synthetic_cfg(tmp_path, parallel=mode)
    params, opt_state = train(cfg, max_batches=2)
    assert params is not None
    assert list((tmp_path / "saved_models").glob("*.pkl")), "no checkpoint written"


class TestStepParity:
    """One ParallelTrainer step vs the single-device batch step on the SAME
    batch: identical loss/daily (fresh params+optimizer both sides)."""

    def _setup(self, tmp_path, mode):
        from ddr_tpu.geodatazoo.synthetic import make_basin, observe
        from ddr_tpu.routing.mc import Bounds
        from ddr_tpu.routing.model import prepare_batch
        from ddr_tpu.scripts.common import build_kan
        from ddr_tpu.training import make_batch_train_step, make_optimizer

        _need_devices()
        cfg = _synthetic_cfg(tmp_path, parallel=mode)
        basin = make_basin(n_segments=93, n_gauges=4, n_days=6, seed=7)
        basin = observe(basin, cfg)
        rd = basin.routing_data
        kan_model, params = build_kan(cfg)
        optimizer = make_optimizer(1e-3)
        opt_state = optimizer.init(params)
        par = ParallelTrainer(cfg, kan_model, optimizer)
        q_prime = np.asarray(basin.q_prime, dtype=np.float32)
        # full-period q_prime pairs with observe()'s full-period daily targets
        # (the loader's per-window batches instead pair with
        # daily_observation_targets — exercised by the end-to-end test above)
        obs_daily = np.asarray(basin.obs_daily, dtype=np.float32)
        obs_mask = np.ones_like(obs_daily, dtype=bool)

        ref_step = make_batch_train_step(
            kan_model,
            Bounds.from_config(cfg.params.attribute_minimums),
            cfg.params.parameter_ranges,
            cfg.params.log_space_parameters,
            cfg.params.defaults,
            tau=cfg.params.tau,
            warmup=cfg.experiment.warmup,
            optimizer=optimizer,
            donate=False,  # the same params/opt_state go into par.step next
        )
        network, channels, gauges = prepare_batch(
            rd, cfg.params.attribute_minimums["slope"]
        )
        _, _, ref_loss, ref_daily = ref_step(
            params,
            opt_state,
            network,
            channels,
            gauges,
            jnp.asarray(rd.normalized_spatial_attributes),
            jnp.asarray(q_prime),
            jnp.asarray(obs_daily),
            jnp.asarray(obs_mask),
        )
        prep = par.prepare(rd, q_prime)
        _, _, loss, daily = par.step(prep, params, opt_state, obs_daily, obs_mask)
        return float(ref_loss), np.asarray(ref_daily), float(loss), np.asarray(daily), par, prep

    @pytest.mark.parametrize("mode", PARITY_MODES)
    def test_loss_matches_single_device(self, tmp_path, mode):
        ref_loss, ref_daily, loss, daily, _, _ = self._setup(tmp_path, mode)
        assert np.isfinite(loss)
        np.testing.assert_allclose(loss, ref_loss, rtol=2e-4)
        np.testing.assert_allclose(daily, ref_daily, rtol=2e-3, atol=1e-4)

    def test_step_cache_reused_on_repeat_batch(self, tmp_path):
        """The sampler cycles a fixed gauge list; a recurring batch topology must
        hit the built-step cache, not rebuild (recompile churn)."""
        *_, par, prep = self._setup(tmp_path, "sharded-wavefront")
        assert len(par._step_cache) == 1
        step_before = next(iter(par._step_cache.values()))
        from ddr_tpu.geodatazoo.synthetic import make_basin, observe

        basin = make_basin(n_segments=93, n_gauges=4, n_days=6, seed=7)
        basin = observe(basin, _synthetic_cfg(tmp_path, parallel="sharded-wavefront"))
        prep2 = par.prepare(basin.routing_data, np.asarray(basin.q_prime, np.float32))
        assert len(par._step_cache) == 1
        assert prep2.step_fn is step_before


def test_parallel_config_validates():
    with pytest.raises(ValueError, match="experiment.parallel"):
        Config(
            name="x",
            geodataset="synthetic",
            mode="training",
            kan={"input_var_names": ["a"]},
            experiment={"parallel": "bogus"},
        )


def test_multiprocess_requires_parallel_mode(tmp_path, monkeypatch):
    """P independent single-device loops all writing one save dir is never what
    a distributed launch means — train() must refuse parallel='none' there."""
    import jax as _jax

    from ddr_tpu.scripts.train import train

    monkeypatch.setattr(_jax, "process_count", lambda: 2)
    cfg = _synthetic_cfg(tmp_path, parallel="none")
    cfg.device = "cpu"
    with pytest.raises(ValueError, match="experiment.parallel"):
        train(cfg, max_batches=1)
