"""Multi-device tests on the 8-virtual-CPU mesh (tests/conftest.py): partition
invariants, sharded-vs-single-device numerical equivalence, sharded training step."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin, observe
from ddr_tpu.parallel import (
    make_mesh,
    permute_routing_data,
    sharded_route,
    topological_range_partition,
)
from ddr_tpu.routing.mc import Bounds, route
from ddr_tpu.routing.model import prepare_batch
from ddr_tpu.validation.configs import Config


@pytest.fixture(scope="module")
def basin_cfg():
    cfg = Config(
        name="parallel_test",
        geodataset="synthetic",
        mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"start_time": "1981/10/01", "end_time": "1981/10/08", "rho": 6, "warmup": 1},
        params={"save_path": "/tmp"},
    )
    basin = make_basin(n_segments=96, n_gauges=4, n_days=8, seed=3)
    return basin, cfg


class TestPartition:
    def test_partition_invariants(self, basin_cfg):
        basin, _ = basin_cfg
        rd = basin.routing_data
        part = topological_range_partition(
            rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, 8
        )
        n = rd.n_segments
        # permutation is a bijection
        assert sorted(part.perm.tolist()) == list(range(n))
        # still lower-triangular: every edge src < tgt in new order
        new_rows = part.inv[rd.adjacency_rows]
        new_cols = part.inv[rd.adjacency_cols]
        assert (new_cols < new_rows).all()
        # cross-shard edges only point to higher shards
        shard_src = part.shard_of(new_cols)
        shard_tgt = part.shard_of(new_rows)
        assert (shard_src <= shard_tgt).all()
        # balanced ranges
        sizes = np.diff(part.bounds)
        assert sizes.max() - sizes.min() <= 1

    def test_permuted_route_equivalent(self, basin_cfg):
        basin, cfg = basin_cfg
        rd = basin.routing_data
        slope_min = cfg.params.attribute_minimums["slope"]
        params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}

        network, channels, gauges = prepare_batch(rd, slope_min)
        base = route(network, channels, params, jnp.asarray(basin.q_prime), gauges=gauges)

        part = topological_range_partition(
            rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, 8
        )
        rd_p = permute_routing_data(rd, part)
        network_p, channels_p, gauges_p = prepare_batch(rd_p, slope_min)
        params_p = {k: v[part.perm] for k, v in params.items()}
        q_prime_p = jnp.asarray(basin.q_prime[:, part.perm])
        out_p = route(network_p, channels_p, params_p, q_prime_p, gauges=gauges_p)

        np.testing.assert_allclose(
            np.asarray(base.runoff), np.asarray(out_p.runoff), rtol=1e-5, atol=1e-5
        )


class TestShardedRoute:
    def test_matches_single_device(self, basin_cfg):
        basin, cfg = basin_cfg
        rd = basin.routing_data
        slope_min = cfg.params.attribute_minimums["slope"]
        params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
        network, channels, gauges = prepare_batch(rd, slope_min)
        q_prime = jnp.asarray(basin.q_prime)

        base = route(network, channels, params, q_prime, gauges=gauges)

        mesh = make_mesh(8)
        out = sharded_route(mesh, network, channels, params, q_prime, gauges=gauges)
        np.testing.assert_allclose(
            np.asarray(base.runoff), np.asarray(out.runoff), rtol=1e-5, atol=1e-5
        )
        # carry state stays reach-sharded for sequential chunking
        assert out.final_discharge.shape == (rd.n_segments,)

    def test_carry_state_across_sharded_chunks(self, basin_cfg):
        basin, cfg = basin_cfg
        rd = basin.routing_data
        params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
        network, channels, gauges = prepare_batch(
            rd, cfg.params.attribute_minimums["slope"]
        )
        q_prime = jnp.asarray(basin.q_prime)
        mesh = make_mesh(8)

        full = sharded_route(mesh, network, channels, params, q_prime, gauges=gauges)
        T = q_prime.shape[0]
        a = sharded_route(mesh, network, channels, params, q_prime[: T // 2], gauges=gauges)
        b = sharded_route(
            mesh, network, channels, params, q_prime[T // 2 - 1 :],
            q_init=a.final_discharge, gauges=gauges,
        )
        stitched = np.concatenate([np.asarray(a.runoff), np.asarray(b.runoff)[1:]], axis=0)
        np.testing.assert_allclose(
            np.asarray(full.runoff), stitched, rtol=1e-4, atol=1e-4
        )


class TestShardedTraining:
    def test_sharded_train_step_matches_loss(self, basin_cfg):
        from ddr_tpu.nn.kan import Kan
        from ddr_tpu.training import make_batch_train_step, make_optimizer

        basin, cfg = basin_cfg
        basin = observe(basin, cfg)
        rd = basin.routing_data
        network, channels, gauges = prepare_batch(
            rd, cfg.params.attribute_minimums["slope"]
        )
        kan_model = Kan(
            input_var_names=tuple(cfg.kan.input_var_names),
            learnable_parameters=tuple(cfg.kan.learnable_parameters),
            hidden_size=cfg.kan.hidden_size,
            num_hidden_layers=cfg.kan.num_hidden_layers,
            grid=cfg.kan.grid,
            k=cfg.kan.k,
        )
        attrs = jnp.asarray(rd.normalized_spatial_attributes)
        params = kan_model.init(jax.random.key(0), attrs)
        optimizer = make_optimizer(1e-3)
        opt_state = optimizer.init(params)
        step = make_batch_train_step(
            kan_model,
            Bounds.from_config(cfg.params.attribute_minimums),
            cfg.params.parameter_ranges,
            cfg.params.log_space_parameters,
            cfg.params.defaults,
            tau=cfg.params.tau,
            warmup=1,
            optimizer=optimizer,
            donate=False,  # the same state feeds the sharded step below
        )
        obs = jnp.asarray(basin.obs_daily)
        mask = jnp.ones_like(obs, dtype=bool)
        q_prime = jnp.asarray(basin.q_prime)

        _, _, loss_single, _ = step(
            params, opt_state, network, channels, gauges, attrs, q_prime, obs, mask
        )

        from ddr_tpu.parallel import make_mesh, reach_sharding, shard_channels, shard_network

        mesh = make_mesh(8)
        s1 = reach_sharding(mesh)
        s2 = reach_sharding(mesh, rank_1_axis=1, ndim=2)
        attrs_sh = jax.device_put(attrs, reach_sharding(mesh, 0, 2))
        q_sh = jax.device_put(q_prime, s2)
        with mesh:
            _, _, loss_sharded, _ = step(
                params, opt_state,
                shard_network(mesh, network), shard_channels(mesh, channels), gauges,
                attrs_sh, q_sh, obs, mask,
            )
        np.testing.assert_allclose(float(loss_single), float(loss_sharded), rtol=1e-4)
