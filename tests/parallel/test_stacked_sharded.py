"""Stacked sharded router: ONE scanned band program under shard_map.

Parity discipline matches tests/parallel/test_sharded_chunked.py: every
configuration must match the single-program step engine (the in-repo oracle,
itself pinned to the scipy float64 solve) to float32-reassociation tolerance —
forward, carry-free hotstart, gradients — regardless of band count or how the
shard blocks split each band."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_deep_network
from ddr_tpu.parallel import make_mesh
from ddr_tpu.parallel.stacked import (
    StackedSharded,
    build_stacked_sharded,
    route_stacked_sharded,
)
from ddr_tpu.routing.mc import ChannelState, route
from ddr_tpu.routing.network import build_network

N_DEV = 8


def _setup(n, depth, T, seed=2):
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    rows, cols = make_deep_network(n, depth, seed=seed)
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
    return rows, cols, channels, params, qp


def _rel(a, b):
    return float(jnp.max(jnp.abs(a - b) / (jnp.abs(b) + 1e-6)))


def test_matches_step_engine():
    n, depth, T = 400, 100, 6
    rows, cols, channels, params, qp = _setup(n, depth, T)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    assert isinstance(layout, StackedSharded)
    mesh = make_mesh(N_DEV)
    with mesh:
        runoff, final = route_stacked_sharded(mesh, layout, channels, params, qp)
    assert _rel(runoff, ref.runoff) < 1e-4
    assert _rel(final, ref.final_discharge) < 1e-4


@pytest.mark.slow
def test_matches_single_chip_stacked():
    """The sharded frame reorders slots but must agree with the single-chip
    stacked router to reassociation tolerance."""
    from ddr_tpu.routing.stacked import build_stacked_chunked

    n, depth, T = 480, 120, 8
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=7)
    sn = build_stacked_chunked(rows, cols, n)
    single = route(sn, channels, params, qp)
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    mesh = make_mesh(N_DEV)
    with mesh:
        runoff, _ = route_stacked_sharded(mesh, layout, channels, params, qp)
    assert _rel(runoff, single.runoff) < 1e-5


@pytest.mark.slow
def test_carry_state_handoff():
    n, depth, T = 400, 100, 10
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=4)
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    mesh = make_mesh(N_DEV)
    h = T // 2
    with mesh:
        _, final_a = route_stacked_sharded(mesh, layout, channels, params, qp[:h])
        runoff_b, _ = route_stacked_sharded(
            mesh, layout, channels, params, qp[h:], q_init=final_a
        )
    ref2 = route(
        build_network(rows, cols, n, fused=False), channels, params, qp[h:],
        q_init=final_a, engine="step",
    )
    assert _rel(runoff_b, ref2.runoff) < 1e-4


@pytest.mark.slow
def test_gradients_match_step_engine():
    n, depth, T = 320, 80, 6
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=6)
    net_s = build_network(rows, cols, n, fused=False)
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    mesh = make_mesh(N_DEV)

    def loss_ref(p):
        return route(net_s, channels, p, qp, engine="step").runoff.mean()

    def loss_sh(p):
        with mesh:
            runoff, _ = route_stacked_sharded(mesh, layout, channels, p, qp)
        return runoff.mean()

    g_ref = jax.grad(loss_ref)(params)
    g_sh = jax.grad(loss_sh)(params)
    # same math, different reassociation — float32 noise bounded like the
    # other sharded engines' grad tests (test_sharded_chunked.py:102-104)
    for k in params:
        denom = jnp.abs(g_ref[k]) + 1e-5
        assert float(jnp.max(jnp.abs(g_sh[k] - g_ref[k]) / denom)) < 2e-2, k


def test_multi_band_forced():
    """Deep enough that the model packs several bands; every node appears in
    exactly one slot and the frame bounds hold."""
    n, depth, T = 400, 170, 4
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=9)
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    assert layout.n_bands > 1
    assert int((np.asarray(layout.gidx) < n).sum()) == n
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    mesh = make_mesh(N_DEV)
    with mesh:
        runoff, _ = route_stacked_sharded(mesh, layout, channels, params, qp)
    assert _rel(runoff, ref.runoff) < 1e-4


@pytest.mark.slow
def test_fuzz_random_dags_match_step():
    """Seeded mini-fuzz over irregular DAGs (multi-root, wide confluences,
    uneven bands after balanced packing) — the stacked-sharded frame has the
    most sentinel wiring in the repo (local gather + hist + pub + ext, each
    per shard); random topologies corner it cheaply. Seeded loop rather than
    hypothesis: each example compiles a shard_map program, so example count
    is the budget."""
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    from ddr_tpu.routing.stacked import build_stacked_chunked

    mesh = make_mesh(N_DEV)
    for seed in range(6):
        rng = np.random.default_rng(100 + seed)
        n = int(rng.integers(24, 120))
        edges = []
        for i in range(1, n):
            for u in rng.choice(i, size=int(rng.integers(0, min(i, 3) + 1)), replace=False):
                edges.append((i, int(u)))
        rows = np.array([e[0] for e in edges], dtype=np.int64)
        cols = np.array([e[1] for e in edges], dtype=np.int64)
        T = int(rng.integers(2, 6))
        channels = ChannelState(
            length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
            slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
            x_storage=jnp.full(n, 0.3, jnp.float32),
        )
        params = {
            "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
            "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
            "p_spatial": jnp.full(n, 21.0, jnp.float32),
        }
        qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
        ref = route(
            build_network(rows, cols, n, fused=False), channels, params, qp, engine="step"
        )
        layout = build_stacked_sharded(rows, cols, n, N_DEV)
        with mesh:
            runoff, _ = route_stacked_sharded(mesh, layout, channels, params, qp)
        rel = _rel(runoff, ref.runoff)
        assert rel < 1e-4, f"seed={seed} n={n} E={len(edges)} bands={layout.n_bands} rel={rel}"
        # and the single-chip stacked on the same topology
        sn = build_stacked_chunked(rows, cols, n, cell_budget=max(60, 6 * n))
        res = route(sn, channels, params, qp)
        rel_s = _rel(res.runoff, ref.runoff)
        assert rel_s < 1e-4, f"seed={seed} single-chip stacked rel={rel_s}"


def test_train_step_descends():
    """Full training step over the stacked-sharded engine on a deep twin
    experiment: KAN -> stacked-sharded route -> masked L1 -> backward ->
    optimizer, loss descending — make_sharded_chunked_train_step dispatches on
    the layout type, so the O(1)-compile multi-chip path is trainable."""
    from ddr_tpu.geodatazoo.synthetic import make_basin, observe
    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.routing.mc import Bounds, GaugeIndex
    from ddr_tpu.routing.model import prepare_channels
    from ddr_tpu.training import make_optimizer, make_sharded_chunked_train_step
    from ddr_tpu.validation.configs import Config

    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    cfg = Config(
        name="t", geodataset="synthetic", mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"rho": 3, "warmup": 1},
    )
    basin = observe(make_basin(n_segments=256, n_gauges=4, n_days=3, seed=0, depth=96), cfg)
    rd = basin.routing_data
    channels, gauges = prepare_channels(rd, 1e-4)
    if gauges is None:
        gauges = GaugeIndex.from_ragged(rd.outflow_idx)
    layout = build_stacked_sharded(rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, N_DEV)
    kan = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
        hidden_size=cfg.kan.hidden_size,
        num_hidden_layers=cfg.kan.num_hidden_layers,
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan.init(jax.random.PRNGKey(0), attrs)
    opt = make_optimizer(1e-3)
    step = make_sharded_chunked_train_step(
        kan, make_mesh(N_DEV), layout, channels, gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges, cfg.params.log_space_parameters,
        cfg.params.defaults, tau=cfg.params.tau, warmup=1, optimizer=opt,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    qp = jnp.asarray(basin.q_prime)
    state = opt.init(params)
    losses = []
    for _ in range(3):
        params, state, loss, _ = step(params, state, attrs, qp, obs, mask)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_remat_bands_gradients_match_default():
    """Band-level checkpointing on the SHARDED stacked engine: values and
    gradients must match the default path (the backward replays each band's
    wave scan + boundary psum instead of storing residuals)."""
    n, depth, T = 256, 60, 8
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=13)
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    mesh = make_mesh(N_DEV)

    def loss(p, rb):
        with mesh:
            r, _ = route_stacked_sharded(mesh, layout, channels, p, qp, remat_bands=rb)
        return r.mean()

    # jitted, as every real caller is (train steps are @jax.jit)
    v0, g0 = jax.jit(jax.value_and_grad(lambda p: loss(p, False)))(params)
    v1, g1 = jax.jit(jax.value_and_grad(lambda p: loss(p, True)))(params)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g0[k]), np.asarray(g1[k]), rtol=1e-5, atol=1e-8, err_msg=k
        )


def test_builder_rejects_remat_bands_on_chunked_layout():
    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.parallel.chunked import build_sharded_chunked
    from ddr_tpu.routing.mc import Bounds, GaugeIndex
    from ddr_tpu.training import make_optimizer, make_sharded_chunked_train_step
    from ddr_tpu.validation.configs import Config

    n, depth, T = 128, 30, 4
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=5)
    layout = build_sharded_chunked(rows, cols, n, N_DEV)
    cfg = Config(
        name="x", geodataset="synthetic", mode="training",
        kan={"input_var_names": ["a"]}, params={"save_path": "/tmp"},
    )
    kan_model = Kan(input_var_names=("a",), learnable_parameters=("n", "q_spatial"))
    gauges = GaugeIndex.from_ragged([np.array([0])])
    with pytest.raises(ValueError, match="StackedSharded"):
        make_sharded_chunked_train_step(
            kan_model, make_mesh(N_DEV), layout, channels, gauges,
            Bounds.from_config(cfg.params.attribute_minimums),
            cfg.params.parameter_ranges, cfg.params.log_space_parameters,
            cfg.params.defaults, tau=3, warmup=1,
            optimizer=make_optimizer(1e-3), remat_bands=True,
        )


def test_repeat_eager_remat_bands_warns_once(caplog, monkeypatch):
    """Eager remat_bands re-jits the band program per call (the closure is
    rebuilt); a repeated eager call on the same layout must warn exactly once,
    and trace-time executions inside a jitted caller must not."""
    import logging
    import weakref

    import ddr_tpu.parallel.stacked as stacked_mod

    # the warn-once registry is process-global: reset so this test is
    # order-independent and repeatable
    monkeypatch.setattr(stacked_mod, "_EAGER_REMAT_WARNED", False)
    monkeypatch.setattr(stacked_mod, "_EAGER_REMAT_SEEN", weakref.WeakValueDictionary())

    n, depth, T = 48, 12, 2
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=9)
    layout = build_stacked_sharded(rows, cols, n, N_DEV)
    mesh = make_mesh(N_DEV)
    with caplog.at_level(logging.WARNING, logger="ddr_tpu.parallel.stacked"):
        with mesh:
            route_stacked_sharded(mesh, layout, channels, params, qp, remat_bands=True)
            assert not [r for r in caplog.records if "re-jits" in r.message]
            route_stacked_sharded(mesh, layout, channels, params, qp, remat_bands=True)
            route_stacked_sharded(mesh, layout, channels, params, qp, remat_bands=True)
    assert len([r for r in caplog.records if "re-jits" in r.message]) == 1
