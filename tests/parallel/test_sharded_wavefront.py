"""Sharded wavefront engine: parity + differentiability on the virtual 8-device mesh.

The engine must match the single-program route() reach-for-reach (forward) AND
gradient-for-gradient (its selling point over the forward-only pipelined router):
gradients flow through the boundary psum, the history ring, and the in-band
hotstart diagonal with standard AD. A finite-difference probe guards against a
plausible-but-wrong VJP from any custom-ish op in the chain."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.parallel import (
    build_sharded_wavefront,
    make_mesh,
    permute_routing_data,
    sharded_wavefront_route,
    topological_range_partition,
)
from ddr_tpu.routing.mc import route
from ddr_tpu.routing.model import prepare_batch

N_DEV = 8


def _setup(n=512, t=36, seed=0):
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    basin = make_basin(n_segments=n, n_gauges=4, n_days=max(2, -(-t // 24)), seed=seed)
    rd = basin.routing_data
    part = topological_range_partition(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    rd = permute_routing_data(rd, part)
    network, channels, _ = prepare_batch(rd, 1e-4)
    sched = build_sharded_wavefront(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    params = {
        k: jnp.asarray(np.asarray(v)[part.perm], jnp.float32)
        for k, v in basin.true_params.items()
    }
    q_prime = jnp.asarray(basin.q_prime[:t, part.perm])
    mesh = make_mesh(N_DEV)
    return mesh, sched, network, channels, params, q_prime


class TestForwardParity:
    def test_matches_single_program_route(self):
        mesh, sched, network, channels, params, q_prime = _setup()
        with mesh:
            runoff, final = sharded_wavefront_route(mesh, sched, channels, params, q_prime)
        ref = route(network, channels, params, q_prime, engine="step")
        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(ref.runoff), rtol=2e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(final), np.asarray(ref.final_discharge), rtol=2e-4, atol=1e-4
        )

    def test_with_carried_state(self):
        mesh, sched, network, channels, params, q_prime = _setup(seed=1)
        q_init = jnp.asarray(
            np.random.default_rng(0).uniform(0.1, 5.0, network.n), jnp.float32
        )
        with mesh:
            runoff, _ = sharded_wavefront_route(
                mesh, sched, channels, params, q_prime, q_init=q_init
            )
        ref = route(network, channels, params, q_prime, q_init=q_init, engine="step")
        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(ref.runoff), rtol=2e-4, atol=1e-4
        )

    def test_boundary_edges_actually_exist(self):
        """The parity above must exercise cross-shard traffic, not a trivial split."""
        _, sched, *_ = _setup()
        real_boundary = int((np.asarray(sched.bnd_gap) >= 1).sum())
        assert sched.n_boundary >= 8, f"only {sched.n_boundary} boundary edges"
        assert real_boundary == sched.n_boundary


class TestGradients:
    def test_grad_matches_single_program(self):
        """Parameter gradients through psum + ring must equal the single-program
        route's gradients (which themselves are pinned against finite differences
        in tests/routing)."""
        mesh, sched, network, channels, params, q_prime = _setup(n=256, t=24, seed=2)

        def loss_sharded(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(mesh, sched, channels, p, q_prime)
            return jnp.mean(runoff**2)

        def loss_ref(p):
            return jnp.mean(route(network, channels, p, q_prime, engine="step").runoff ** 2)

        g_sh = jax.grad(loss_sharded)(params)
        g_ref = jax.grad(loss_ref)(params)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g_sh[k]), np.asarray(g_ref[k]), rtol=2e-3, atol=1e-5
            )

    def test_grad_finite_difference_probe(self):
        """Directional FD check directly on the sharded engine."""
        mesh, sched, network, channels, params, q_prime = _setup(n=128, t=12, seed=3)

        def loss(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(mesh, sched, channels, p, q_prime)
            return float(jnp.mean(runoff**2))

        def loss_j(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(mesh, sched, channels, p, q_prime)
            return jnp.mean(runoff**2)

        g = jax.grad(loss_j)(params)
        rng = np.random.default_rng(0)
        direction = {
            k: jnp.asarray(rng.normal(size=v.shape), jnp.float32) for k, v in params.items()
        }
        eps = 1e-3
        plus = {k: params[k] + eps * direction[k] for k in params}
        minus = {k: params[k] - eps * direction[k] for k in params}
        fd = (loss(plus) - loss(minus)) / (2 * eps)
        analytic = float(sum(jnp.vdot(g[k], direction[k]) for k in params))
        # float32 central differences through a 24-step recurrence carry a few
        # percent of noise; the tight check is grad-vs-single-program above.
        assert abs(fd - analytic) <= 5e-2 * max(abs(fd), abs(analytic), 1e-6), (fd, analytic)


class TestBuildValidation:
    def test_indivisible_n_rejected(self):
        with pytest.raises(ValueError, match="divisible"):
            build_sharded_wavefront(np.array([1]), np.array([0]), 9, N_DEV)

    def test_backward_edge_rejected(self):
        # edge from shard 1 to shard 0 (not partitioned order)
        with pytest.raises(ValueError, match="lower shards"):
            build_sharded_wavefront(np.array([0]), np.array([15]), 16, N_DEV)
