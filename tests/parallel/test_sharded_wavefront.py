"""Sharded wavefront engine: parity + differentiability on the virtual 8-device mesh.

The engine must match the single-program route() reach-for-reach (forward) AND
gradient-for-gradient (its selling point over the forward-only pipelined router):
gradients flow through the boundary psum, the history ring, and the in-band
hotstart diagonal with standard AD. A finite-difference probe guards against a
plausible-but-wrong VJP from any custom-ish op in the chain."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.parallel import (
    build_sharded_wavefront,
    make_mesh,
    permute_routing_data,
    sharded_wavefront_route,
    topological_range_partition,
)
from ddr_tpu.routing.mc import route
from ddr_tpu.routing.model import prepare_batch

N_DEV = 8


def _setup(n=256, t=24, seed=0):
    # ONE shared topology per (n, t) — distinct seeds would recompile the
    # shard_map program per test; topology variety lives in the fuzz batteries.
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    basin = make_basin(n_segments=n, n_gauges=4, n_days=max(2, -(-t // 24)), seed=seed)
    rd = basin.routing_data
    part = topological_range_partition(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    rd = permute_routing_data(rd, part)
    network, channels, _ = prepare_batch(rd, 1e-4)
    sched = build_sharded_wavefront(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
    params = {
        k: jnp.asarray(np.asarray(v)[part.perm], jnp.float32)
        for k, v in basin.true_params.items()
    }
    q_prime = jnp.asarray(basin.q_prime[:t, part.perm])
    mesh = make_mesh(N_DEV)
    return mesh, sched, network, channels, params, q_prime


class TestForwardParity:
    def test_matches_single_program_route(self):
        mesh, sched, network, channels, params, q_prime = _setup()
        with mesh:
            runoff, final = sharded_wavefront_route(mesh, sched, channels, params, q_prime)
        ref = route(network, channels, params, q_prime, engine="step")
        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(ref.runoff), rtol=2e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(final), np.asarray(ref.final_discharge), rtol=2e-4, atol=1e-4
        )

    def test_with_carried_state(self):
        mesh, sched, network, channels, params, q_prime = _setup()
        q_init = jnp.asarray(
            np.random.default_rng(0).uniform(0.1, 5.0, network.n), jnp.float32
        )
        with mesh:
            runoff, _ = sharded_wavefront_route(
                mesh, sched, channels, params, q_prime, q_init=q_init
            )
        ref = route(network, channels, params, q_prime, q_init=q_init, engine="step")
        np.testing.assert_allclose(
            np.asarray(runoff), np.asarray(ref.runoff), rtol=2e-4, atol=1e-4
        )

    def test_boundary_edges_actually_exist(self):
        """The parity above must exercise cross-shard traffic, not a trivial split."""
        _, sched, *_ = _setup()
        real_boundary = int((np.asarray(sched.bnd_gap) >= 1).sum())
        assert sched.n_boundary >= 8, f"only {sched.n_boundary} boundary edges"
        assert real_boundary == sched.n_boundary


@pytest.mark.slow
class TestGradients:
    def test_grad_matches_single_program(self):
        """Parameter gradients through psum + ring must equal the single-program
        route's gradients (which themselves are pinned against finite differences
        in tests/routing)."""
        mesh, sched, network, channels, params, q_prime = _setup()

        def loss_sharded(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(mesh, sched, channels, p, q_prime)
            return jnp.mean(runoff**2)

        def loss_ref(p):
            return jnp.mean(route(network, channels, p, q_prime, engine="step").runoff ** 2)

        g_sh = jax.grad(loss_sharded)(params)
        g_ref = jax.grad(loss_ref)(params)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g_sh[k]), np.asarray(g_ref[k]), rtol=2e-3, atol=1e-5
            )

    def test_grad_finite_difference_probe(self):
        """Directional FD check directly on the sharded engine.

        seed=3 deliberately: the probe needs a topology whose loss has a
        float32-measurable gradient (|g| ~1e-3); the file-default seed-0 basin
        is near-flat here (|g| ~1e-6), where eps*|g| sits below float32's loss
        resolution and the central difference is identically zero."""
        mesh, sched, network, channels, params, q_prime = _setup(n=128, t=12, seed=3)

        def loss(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(mesh, sched, channels, p, q_prime)
            return float(jnp.mean(runoff**2))

        def loss_j(p):
            with mesh:
                runoff, _ = sharded_wavefront_route(mesh, sched, channels, p, q_prime)
            return jnp.mean(runoff**2)

        g = jax.grad(loss_j)(params)
        # Probe ALONG the gradient: a random direction can land nearly orthogonal
        # to a small gradient (measured: analytic ~1.7e-6 at this shape/seed),
        # where the float32 central difference underflows to 0 and the relative
        # check is vacuous noise-vs-noise. Along g/|g| the directional
        # derivative is |g| > 0 by construction.
        norm = float(jnp.sqrt(sum(jnp.vdot(g[k], g[k]) for k in params)))
        assert norm > 0, "gradient identically zero"
        direction = {k: g[k] / norm for k in params}
        eps = 1e-3
        plus = {k: params[k] + eps * direction[k] for k in params}
        minus = {k: params[k] - eps * direction[k] for k in params}
        fd = (loss(plus) - loss(minus)) / (2 * eps)
        analytic = float(sum(jnp.vdot(g[k], direction[k]) for k in params))
        # float32 central differences through a 24-step recurrence carry a few
        # percent of noise; the tight check is grad-vs-single-program above.
        assert abs(fd - analytic) <= 5e-2 * max(abs(fd), abs(analytic), 1e-6), (fd, analytic)


class TestBuildValidation:
    def test_indivisible_n_rejected(self):
        with pytest.raises(ValueError, match="divisible"):
            build_sharded_wavefront(np.array([1]), np.array([0]), 9, N_DEV)

    def test_backward_edge_rejected(self):
        # edge from shard 1 to shard 0 (not partitioned order)
        with pytest.raises(ValueError, match="lower shards"):
            build_sharded_wavefront(np.array([0]), np.array([15]), 16, N_DEV)


class TestShardedTrainStep:
    """make_sharded_train_step: the full distributed training step (KAN forward ->
    sharded wavefront -> masked L1 -> backward -> optimizer) in one SPMD program."""

    def _train_setup(self, n=256, n_days=3, seed=0):
        if len(jax.devices()) < N_DEV:
            pytest.skip(f"needs {N_DEV} devices")
        from ddr_tpu.geodatazoo.synthetic import observe
        from ddr_tpu.nn.kan import Kan
        from ddr_tpu.routing.mc import Bounds
        from ddr_tpu.training import make_optimizer, make_sharded_train_step
        from ddr_tpu.validation.configs import Config

        cfg = Config(
            name="t", geodataset="synthetic", mode="training",
            kan={"input_var_names": [f"a{i}" for i in range(10)]},
            experiment={"rho": n_days, "warmup": 1},
        )
        basin = observe(
            make_basin(n_segments=n, n_gauges=4, n_days=n_days, seed=seed), cfg
        )
        rd = basin.routing_data
        part = topological_range_partition(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
        rd = permute_routing_data(rd, part)
        network, channels, gauges = prepare_batch(rd, 1e-4)
        sched = build_sharded_wavefront(rd.adjacency_rows, rd.adjacency_cols, n, N_DEV)
        kan = Kan(
            input_var_names=tuple(cfg.kan.input_var_names),
            learnable_parameters=tuple(cfg.kan.learnable_parameters),
            hidden_size=cfg.kan.hidden_size,
            num_hidden_layers=cfg.kan.num_hidden_layers,
        )
        attrs = jnp.asarray(rd.normalized_spatial_attributes)
        kan_params = kan.init(jax.random.PRNGKey(0), attrs)
        optimizer = make_optimizer(1e-3)
        step = make_sharded_train_step(
            kan, make_mesh(N_DEV), sched, channels, gauges,
            Bounds.from_config(cfg.params.attribute_minimums),
            cfg.params.parameter_ranges, cfg.params.log_space_parameters,
            cfg.params.defaults, tau=cfg.params.tau, warmup=1, optimizer=optimizer,
            donate=False,  # A/B tests below feed the same state into two steps
        )
        q_prime = jnp.asarray(basin.q_prime[:, part.perm])
        obs = jnp.asarray(basin.obs_daily)
        mask = jnp.ones_like(obs, dtype=bool)
        return step, optimizer, kan, kan_params, attrs, q_prime, obs, mask, (
            network, channels, gauges, cfg
        )

    def test_step_runs_and_descends(self):
        step, optimizer, kan, params, attrs, q_prime, obs, mask, _ = self._train_setup()
        opt_state = optimizer.init(params)
        losses = []
        for _ in range(3):
            params, opt_state, loss, daily = step(params, opt_state, attrs, q_prime, obs, mask)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # twin-experiment loss must descend

    def test_step_loss_matches_single_program_step(self):
        """Same batch through make_train_step (single-program route) and
        make_sharded_train_step must produce the same loss and daily output."""
        from ddr_tpu.routing.mc import Bounds
        from ddr_tpu.training import make_optimizer, make_train_step

        step, optimizer, kan, params, attrs, q_prime, obs, mask, (
            network, channels, gauges, cfg
        ) = self._train_setup()
        ref_step = make_train_step(
            kan, network, channels, gauges,
            Bounds.from_config(cfg.params.attribute_minimums),
            cfg.params.parameter_ranges, cfg.params.log_space_parameters,
            cfg.params.defaults, tau=cfg.params.tau, warmup=1,
            optimizer=make_optimizer(1e-3), donate=False,
        )
        opt_state = optimizer.init(params)
        _, _, loss_swf, daily_swf = step(params, opt_state, attrs, q_prime, obs, mask)
        _, _, loss_ref, daily_ref = ref_step(params, opt_state, attrs, q_prime, obs, mask)
        # abs floor matches the daily tolerance below: near-zero losses (the
        # twin setup routes to ~machine-eps L1) differ by summation order
        # between the sharded and single-program schedules
        assert float(loss_swf) == pytest.approx(float(loss_ref), rel=1e-4, abs=1e-6)
        np.testing.assert_allclose(
            np.asarray(daily_swf), np.asarray(daily_ref), rtol=2e-4, atol=1e-4
        )
