"""The shared synthetic GSPMD train-step problem for the multi-process tests.

One definition, imported by BOTH the in-test single-process comparison and the
two worker subprocesses (which run with cwd=repo root, so ``tests.parallel``
is importable) — the comparison is only meaningful if all three processes
construct the identical problem, and a hand-synchronized copy would drift.
"""

from __future__ import annotations

import numpy as np


def _make_problem():
    """The FIXED seed-3 synthetic training problem both step runners share —
    one construction site so the GSPMD and explicit-collective tests can never
    drift onto different problems."""
    import jax
    import jax.numpy as jnp

    from ddr_tpu.geodatazoo.synthetic import make_basin, observe
    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.training import make_optimizer
    from ddr_tpu.validation.configs import Config

    cfg = Config(
        name="multiprocess_test",
        geodataset="synthetic",
        mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={
            "start_time": "1981/10/01",
            "end_time": "1981/10/08",
            "rho": 6,
            "warmup": 1,
        },
        params={"save_path": "/tmp"},
    )
    basin = observe(make_basin(n_segments=96, n_gauges=4, n_days=8, seed=3), cfg)
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
        hidden_size=cfg.kan.hidden_size,
        num_hidden_layers=cfg.kan.num_hidden_layers,
        grid=cfg.kan.grid,
        k=cfg.kan.k,
    )
    optimizer = make_optimizer(1e-3)
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    return cfg, basin, kan_model, optimizer, obs, mask


def _digest(params) -> float:
    import jax

    return float(sum(np.abs(np.asarray(x)).sum() for x in jax.tree_util.tree_leaves(params)))


def run_gspmd_step(n_mesh_devices: int = 8) -> dict:
    """Build the fixed seed-3 synthetic basin, run ONE GSPMD train step over an
    ``n_mesh_devices``-device mesh, and return {loss, param_digest}."""
    import jax
    import jax.numpy as jnp

    from ddr_tpu.parallel import make_mesh, reach_sharding, shard_channels, shard_network
    from ddr_tpu.routing.mc import Bounds
    from ddr_tpu.routing.model import prepare_batch
    from ddr_tpu.training import make_batch_train_step

    cfg, basin, kan_model, optimizer, obs, mask = _make_problem()
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    opt_state = optimizer.init(params)
    step = make_batch_train_step(
        kan_model,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges,
        cfg.params.log_space_parameters,
        cfg.params.defaults,
        tau=cfg.params.tau,
        warmup=1,
        optimizer=optimizer,
    )
    q_prime = jnp.asarray(basin.q_prime)

    mesh = make_mesh(n_mesh_devices)
    with mesh:
        params2, _, loss, _ = step(
            params, opt_state,
            shard_network(mesh, network), shard_channels(mesh, channels), gauges,
            jax.device_put(attrs, reach_sharding(mesh, 0, 2)),
            jax.device_put(q_prime, reach_sharding(mesh, 1, 2)),
            obs, mask,
        )
    return {"loss": float(loss), "param_digest": _digest(params2)}


def run_sharded_wavefront_step(n_mesh_devices: int = 8) -> dict:
    """ONE explicit-collective (shard_map, 1 psum/wave) train step on the fixed
    seed-3 problem over an ``n_mesh_devices``-device mesh; {loss, param_digest}.

    The multi-process analog of the GSPMD step above — proves the
    explicit-collective stack is process-count-agnostic too, not just XLA's
    partitioner."""
    import jax
    import jax.numpy as jnp

    from ddr_tpu.parallel import (
        build_sharded_wavefront,
        make_mesh,
        permute_routing_data,
        topological_range_partition,
    )
    from ddr_tpu.routing.mc import Bounds
    from ddr_tpu.routing.model import prepare_batch
    from ddr_tpu.training import make_sharded_train_step

    cfg, basin, kan_model, optimizer, obs, mask = _make_problem()
    rd = basin.routing_data
    n = rd.n_segments
    part = topological_range_partition(rd.adjacency_rows, rd.adjacency_cols, n, n_mesh_devices)
    rd = permute_routing_data(rd, part)
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    sched = build_sharded_wavefront(rd.adjacency_rows, rd.adjacency_cols, n, n_mesh_devices)
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    opt_state = optimizer.init(params)
    step = make_sharded_train_step(
        kan_model, make_mesh(n_mesh_devices), sched, channels, gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges,
        cfg.params.log_space_parameters,
        cfg.params.defaults,
        tau=cfg.params.tau,
        warmup=1,
        optimizer=optimizer,
    )
    q_prime = jnp.asarray(basin.q_prime[:, part.perm])
    params2, _, loss, _ = step(params, opt_state, attrs, q_prime, obs, mask)
    return {"loss": float(loss), "param_digest": _digest(params2)}
