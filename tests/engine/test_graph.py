"""Native graph core tests: native vs NumPy fallback parity, cycle handling, scale."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.engine import graph as G


@pytest.fixture()
def diamond():
    # 0 -> 1 -> 3, 0 -> 2 -> 3, 4 isolated
    src = np.array([0, 1, 0, 2])
    dst = np.array([1, 3, 2, 3])
    return src, dst, 5


def test_native_compiles():
    assert G.native_available(), "C++ graph core failed to compile/load"


def test_topo_sort_deterministic(diamond):
    src, dst, n = diamond
    order = G.topological_sort(src, dst, n)
    pos = np.empty(n, dtype=int)
    pos[order] = np.arange(n)
    for s, d in zip(src, dst):
        assert pos[s] < pos[d]
    # lexicographic Kahn: 0 first, then 1 and 2 before 4? 4 has indeg 0 too ->
    # ready set {0, 4}: 0 pops first; after 0, ready {1, 2, 4} -> 1, 2, then 3 vs 4.
    assert order.tolist() == [0, 1, 2, 3, 4]


def test_topo_sort_cycle_raises():
    src = np.array([0, 1, 2])
    dst = np.array([1, 2, 0])
    with pytest.raises(ValueError, match="cycle"):
        G.topological_sort(src, dst, 3)


def test_levels(diamond):
    src, dst, n = diamond
    levels = G.longest_path_levels(src, dst, n)
    assert levels.tolist() == [0, 1, 1, 2, 0]


def test_cycle_nodes_found():
    # 0 -> 1 -> 2 -> 1 (cycle {1,2}), 2 -> 3
    src = np.array([0, 1, 2, 2])
    dst = np.array([1, 2, 1, 3])
    cyc = G.cycle_nodes(src, dst, 4)
    assert cyc.tolist() == [1, 2]


def test_cycle_nodes_empty_on_dag(diamond):
    src, dst, n = diamond
    assert G.cycle_nodes(src, dst, n).size == 0


def test_ancestors(diamond):
    src, dst, n = diamond
    mask = G.ancestors_mask(src, dst, n, np.array([3]))
    assert mask.tolist() == [True, True, True, True, False]
    mask1 = G.ancestors_mask(src, dst, n, np.array([1]))
    assert mask1.tolist() == [True, True, False, False, False]


def test_native_matches_fallback():
    rng = np.random.default_rng(0)
    n = 500
    # random DAG: edges i -> j with i < j
    src = rng.integers(0, n - 1, size=2000)
    dst = src + rng.integers(1, 20, size=2000)
    keep = dst < n
    src, dst = src[keep], dst[keep]
    # dedupe
    pairs = np.unique(np.stack([src, dst], 1), axis=0)
    src, dst = pairs[:, 0], pairs[:, 1]

    native_order = G.topological_sort(src, dst, n)
    native_levels = G.longest_path_levels(src, dst, n)
    native_anc = G.ancestors_mask(src, dst, n, np.array([n - 1]))

    lib, tried = G._NATIVE, G._NATIVE_TRIED
    try:
        G._NATIVE = None  # force fallback paths
        fb_order = G.topological_sort(src, dst, n)
        fb_levels = G.longest_path_levels(src, dst, n)
        fb_anc = G.ancestors_mask(src, dst, n, np.array([n - 1]))
    finally:
        G._NATIVE, G._NATIVE_TRIED = lib, tried

    np.testing.assert_array_equal(native_order, fb_order)
    np.testing.assert_array_equal(native_levels, fb_levels)
    np.testing.assert_array_equal(native_anc, fb_anc)


def test_scale_smoke():
    """200k-node chain+branches completes fast through the native path."""
    n = 200_000
    src = np.arange(n - 1)
    dst = src + 1
    order = G.topological_sort(src, dst, n)
    assert order[0] == 0 and order[-1] == n - 1
    levels = G.longest_path_levels(src, dst, n)
    assert levels[-1] == n - 1
