"""Upstream-subset semantics and builder edge cases at the reference's granularity
(/root/reference/tests/engine/merit/test_graph.py TestSubsetUpstream,
test_integration.py TestGaugeIntegration/TestEdgeCases)."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.engine import graph as G
from ddr_tpu.engine.core import coo_from_zarr, list_geodatasets
from ddr_tpu.engine.merit import (
    build_gauge_adjacencies,
    build_merit_adjacency,
    build_upstream_dict,
    create_adjacency_matrix,
)
from ddr_tpu.geodatazoo.dataclasses import GaugeSet, MERITGauge
from ddr_tpu.io import zarrlite

# Sandbox-shaped chain-with-branches: 10 -> 30 <- 20, 30 -> 50 <- 40 (outlet 50).
SANDBOX = pd.DataFrame(
    {
        "COMID": [10, 20, 30, 40, 50],
        "NextDownID": [30, 30, 50, 50, 0],
        "up1": [0, 0, 10, 0, 30],
        "up2": [0, 0, 20, 0, 40],
    }
)


def _subset(origin: int) -> set[int]:
    """Upstream closure of ``origin`` (inclusive) via the native ancestors mask."""
    upstream = build_upstream_dict(SANDBOX)
    ids = sorted({c for dn, ups in upstream.items() for c in (dn, *ups)})
    idx = {c: i for i, c in enumerate(ids)}
    src, dst = [], []
    for dn in upstream:
        for up in upstream[dn]:
            src.append(idx[up])
            dst.append(idx[dn])
    mask = G.ancestors_mask(
        np.asarray(src, np.int64), np.asarray(dst, np.int64), len(ids),
        np.array([idx[origin]]),
    )
    return {ids[i] for i in np.flatnonzero(mask)}


class TestUpstreamSubsets:
    def test_outlet_returns_all_nodes(self):
        assert _subset(50) == {10, 20, 30, 40, 50}

    def test_intermediate_node(self):
        assert _subset(30) == {10, 20, 30}

    def test_headwater_returns_self(self):
        assert _subset(10) == {10}

    def test_subsets_are_nested(self):
        assert _subset(30) < _subset(50)
        assert _subset(10) < _subset(30)

    def test_node_30_upstreams(self):
        d = build_upstream_dict(SANDBOX)
        assert d[30] == [10, 20]

    def test_node_50_upstreams(self):
        d = build_upstream_dict(SANDBOX)
        assert d[50] == [30, 40]

    def test_headwaters_not_keys(self):
        d = build_upstream_dict(SANDBOX)
        for hw in (10, 20, 40):
            assert hw not in d


class TestSandboxMatrix:
    def test_shape_and_nnz(self):
        coo, order = create_adjacency_matrix(SANDBOX)
        assert coo.shape == (5, 5)
        assert coo.nnz == 4
        assert len(order) == 5

    def test_encodes_correct_edges(self):
        coo, order = create_adjacency_matrix(SANDBOX)
        pos = {c: i for i, c in enumerate(order)}
        edges = {(order[r], order[c]) for r, c in zip(coo.row, coo.col)}
        assert edges == {(30, 10), (30, 20), (50, 30), (50, 40)}

    def test_outlet_has_no_outgoing_edges(self):
        coo, order = create_adjacency_matrix(SANDBOX)
        outlet_idx = order.index(50)
        assert outlet_idx not in set(coo.col.tolist())

    def test_order_is_topological(self):
        _, order = create_adjacency_matrix(SANDBOX)
        pos = {c: i for i, c in enumerate(order)}
        assert pos[10] < pos[30] < pos[50]
        assert pos[20] < pos[30]
        assert pos[40] < pos[50]


class TestBuilderEdgeCases:
    def test_empty_dataframe_raises(self):
        empty = pd.DataFrame(columns=["COMID", "NextDownID", "up1", "up2"])
        with pytest.raises(ValueError, match="No upstream connections"):
            create_adjacency_matrix(empty)

    def test_two_node_network(self, tmp_path):
        fp = pd.DataFrame({"COMID": [1, 2], "NextDownID": [2, 0], "up1": [0, 1]})
        out = build_merit_adjacency(fp, tmp_path / "two.zarr")
        coo, order = coo_from_zarr(out)
        assert order == [1, 2]
        assert coo.nnz == 1

    def test_deep_parent_dirs_created(self, tmp_path):
        out = build_merit_adjacency(SANDBOX, tmp_path / "a" / "b" / "c" / "conus.zarr")
        assert out.exists()
        _, order = coo_from_zarr(out)
        assert len(order) == 5

    def test_gauge_store_existing_raises(self, tmp_path):
        conus = build_merit_adjacency(SANDBOX, tmp_path / "conus.zarr")
        gs = GaugeSet(gauges=[MERITGauge(STAID="1", STANAME="a", DRAIN_SQKM=1, COMID=50)])
        build_gauge_adjacencies(SANDBOX, conus, gs, tmp_path / "g.zarr")
        with pytest.raises(FileExistsError):
            build_gauge_adjacencies(SANDBOX, conus, gs, tmp_path / "g.zarr")

    def test_gauge_groups_cover_requested_set(self, tmp_path):
        conus = build_merit_adjacency(SANDBOX, tmp_path / "conus.zarr")
        gs = GaugeSet(
            gauges=[
                MERITGauge(STAID="1", STANAME="a", DRAIN_SQKM=1, COMID=10),  # headwater
                MERITGauge(STAID="2", STANAME="b", DRAIN_SQKM=2, COMID=30),
                MERITGauge(STAID="3", STANAME="c", DRAIN_SQKM=3, COMID=50),  # outlet
            ]
        )
        out = build_gauge_adjacencies(SANDBOX, conus, gs, tmp_path / "g.zarr")
        root = zarrlite.open_group(out)
        for staid in ("00000001", "00000002", "00000003"):
            assert staid in root
        # nested sizes: headwater 1, mid 3, outlet 5
        assert len(root["00000001"]["order"].read()) == 1
        assert len(root["00000002"]["order"].read()) == 3
        assert len(root["00000003"]["order"].read()) == 5

    def test_headwater_gauge_has_empty_coo(self, tmp_path):
        conus = build_merit_adjacency(SANDBOX, tmp_path / "conus.zarr")
        gs = GaugeSet(gauges=[MERITGauge(STAID="1", STANAME="a", DRAIN_SQKM=1, COMID=20)])
        out = build_gauge_adjacencies(SANDBOX, conus, gs, tmp_path / "g.zarr")
        sub = zarrlite.open_group(out)["00000001"]
        assert sub["indices_0"].shape[0] == 0
        assert sub["order"].read().tolist() == [20]


class TestRegistry:
    def test_list_geodatasets_sorted(self):
        names = list_geodatasets()
        assert names == sorted(names)
        assert "merit" in names and "lynker" in names
