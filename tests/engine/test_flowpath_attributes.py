"""Flowpath-attribute writers at the reference suite's granularity
(/root/reference/tests/engine/merit/test_flowpath_attributes.py,
lynker_hydrofabric/test_flowpath_attributes.py): dtype contracts, order
alignment, NaN for unmatched ids, namespace separation between datasets."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.engine.lynker import (
    build_lynker_hydrofabric_adjacency,
    write_flowpath_attributes,
)
from ddr_tpu.engine.merit import (
    build_merit_adjacency,
    write_merit_flowpath_attributes,
)
from ddr_tpu.io import zarrlite

MERIT_FP = pd.DataFrame(
    {
        "COMID": [1, 2, 3, 4],
        "NextDownID": [3, 3, 4, 0],
        "up1": [0, 0, 1, 3],
        "up2": [0, 0, 2, 0],
        "lengthkm": [1.5, 2.0, 3.0, 4.5],
        "slope": [0.01, 0.02, 0.005, 0.001],
    }
)

LYNKER_FP = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3"],
        "toid": ["nex-10", "nex-10", "nex-11"],
        "tot_drainage_areasqkm": [10.0, 12.0, 30.0],
    }
)
LYNKER_NET = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3", "nex-10", "nex-11"],
        "toid": ["nex-10", "nex-10", "nex-11", "wb-3", None],
        "hl_uri": [None] * 5,
    }
)
LYNKER_ATTRS = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3"],
        "Length_m": [1000.0, 1500.0, 2000.0],
        "So": [0.01, 0.012, 0.007],
        "TopWdth": [5.0, 6.0, 12.0],
        "ChSlp": [1.0, 1.2, 2.0],
        "MusX": [0.25, 0.3, 0.28],
    }
)

MERIT_ATTR_ARRAYS = ("length_m", "slope")
LYNKER_ATTR_ARRAYS = ("length_m", "slope", "top_width", "side_slope", "muskingum_x", "toid")


class TestMeritFlowpathAttributes:
    @pytest.fixture()
    def store(self, tmp_path):
        return build_merit_adjacency(MERIT_FP, tmp_path / "conus.zarr")

    def test_arrays_exist(self, store):
        root = zarrlite.open_group(store)
        for name in MERIT_ATTR_ARRAYS:
            assert name in root, name

    def test_arrays_same_length_as_order(self, store):
        root = zarrlite.open_group(store)
        n = len(root["order"].read())
        for name in MERIT_ATTR_ARRAYS:
            assert root[name].read().shape == (n,)

    def test_float32_dtypes(self, store):
        root = zarrlite.open_group(store)
        for name in MERIT_ATTR_ARRAYS:
            assert root[name].read().dtype == np.float32, name

    def test_length_converted_to_meters(self, store):
        root = zarrlite.open_group(store)
        order = root["order"].read().tolist()
        length_m = root["length_m"].read()
        assert length_m[order.index(1)] == pytest.approx(1500.0)
        assert length_m[order.index(4)] == pytest.approx(4500.0)

    def test_slope_values_aligned(self, store):
        root = zarrlite.open_group(store)
        order = root["order"].read().tolist()
        slope = root["slope"].read()
        for comid, want in zip(MERIT_FP["COMID"], MERIT_FP["slope"]):
            assert slope[order.index(comid)] == pytest.approx(want, abs=1e-7)

    def test_nan_for_missing_comids(self, tmp_path):
        """Attributes written from a table missing some COMIDs leave NaN there."""
        store = build_merit_adjacency(MERIT_FP[["COMID", "NextDownID", "up1", "up2"]],
                                      tmp_path / "bare.zarr")
        write_merit_flowpath_attributes(MERIT_FP[MERIT_FP["COMID"] != 2], store)
        root = zarrlite.open_group(store)
        order = root["order"].read().tolist()
        length_m = root["length_m"].read()
        assert np.isnan(length_m[order.index(2)])
        assert length_m[order.index(1)] == pytest.approx(1500.0)

    def test_no_extra_lynker_arrays(self, store):
        """MERIT stores must not grow Lynker-only arrays (top_width etc.)."""
        root = zarrlite.open_group(store)
        for name in ("top_width", "side_slope", "muskingum_x", "toid"):
            assert name not in root, name

    def test_attributeless_table_skips_write(self, tmp_path, caplog):
        store = build_merit_adjacency(MERIT_FP[["COMID", "NextDownID", "up1", "up2"]],
                                      tmp_path / "noattr.zarr")
        root = zarrlite.open_group(store)
        assert "length_m" not in root
        assert "slope" not in root


class TestLynkerFlowpathAttributes:
    @pytest.fixture()
    def store(self, tmp_path):
        out = build_lynker_hydrofabric_adjacency(LYNKER_FP, LYNKER_NET, tmp_path / "conus.zarr")
        write_flowpath_attributes(
            {
                "flowpath-attributes-ml": LYNKER_ATTRS,
                "flowpaths": LYNKER_FP[["id", "toid"]],
                "network": LYNKER_NET[["id", "toid"]],
            },
            out,
        )
        return out

    def test_all_arrays_exist(self, store):
        root = zarrlite.open_group(store)
        for name in LYNKER_ATTR_ARRAYS:
            assert name in root, name

    def test_float_arrays_float32(self, store):
        root = zarrlite.open_group(store)
        for name in LYNKER_ATTR_ARRAYS[:-1]:
            assert root[name].read().dtype == np.float32, name

    def test_toid_int32(self, store):
        assert zarrlite.open_group(store)["toid"].read().dtype == np.int32

    def test_values_aligned_to_order(self, store):
        # On disk the order array stores the numeric waterbody parts (int32).
        root = zarrlite.open_group(store)
        order = root["order"].read().tolist()
        tw = root["top_width"].read()
        assert tw[order.index(3)] == pytest.approx(12.0)
        assert root["muskingum_x"].read()[order.index(1)] == pytest.approx(0.25)

    def test_toid_resolves_nexus_hop(self, store):
        """wb-1 -> nex-10 -> wb-3: stored toid is the downstream waterbody number."""
        root = zarrlite.open_group(store)
        order = root["order"].read().tolist()
        toid = root["toid"].read()
        assert toid[order.index(1)] == 3
        assert toid[order.index(2)] == 3

    def test_terminal_toid_zero(self, store):
        """wb-3 drains to an unmapped nexus: toid stays 0."""
        root = zarrlite.open_group(store)
        order = root["order"].read().tolist()
        assert root["toid"].read()[order.index(3)] == 0

    def test_nan_for_missing_attribute_ids(self, tmp_path):
        out = build_lynker_hydrofabric_adjacency(LYNKER_FP, LYNKER_NET, tmp_path / "c2.zarr")
        write_flowpath_attributes(
            {
                "flowpath-attributes-ml": LYNKER_ATTRS[LYNKER_ATTRS["id"] != "wb-2"],
                "flowpaths": LYNKER_FP[["id", "toid"]],
            },
            out,
        )
        root = zarrlite.open_group(out)
        order = root["order"].read().tolist()
        assert np.isnan(root["length_m"].read()[order.index(2)])
        assert root["length_m"].read()[order.index(1)] == pytest.approx(1000.0)

    def test_without_network_table_toid_skips_nexus(self, tmp_path):
        """No network table: nexus toids cannot resolve -> 0 (documented fallback)."""
        out = build_lynker_hydrofabric_adjacency(LYNKER_FP, LYNKER_NET, tmp_path / "c3.zarr")
        write_flowpath_attributes(
            {
                "flowpath-attributes-ml": LYNKER_ATTRS,
                "flowpaths": LYNKER_FP[["id", "toid"]],
            },
            out,
        )
        root = zarrlite.open_group(out)
        assert (root["toid"].read() == 0).all()
