"""Lynker graph-prep behaviors at the reference suite's granularity
(/root/reference/tests/engine/lynker_hydrofabric/test_graph.py: 17 tests over
preprocess/find_origin/subset; test_determinism.py: build invariance)."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.engine.core import coo_from_zarr
from ddr_tpu.engine.lynker import (
    build_lynker_hydrofabric_adjacency,
    create_matrix,
    find_origin,
    preprocess_river_network,
    subset,
)
from ddr_tpu.geodatazoo.dataclasses import Gauge

# Deeper fixture than test_lynker_build's: a 7-waterbody, two-confluence network
# (every flowpath toid is a nexus, as in the real hydrofabric — the reference's
# create_matrix resolves strictly through the nexus hop, io.py:97-116).
#   wb-1, wb-2, wb-3 -> nex-10 -> wb-4;
#   wb-4, wb-5 -> nex-11 -> wb-6;  wb-6 -> nex-12 -> wb-7; wb-7 -> nex-13 (terminal)
FP = pd.DataFrame(
    {
        "id": [f"wb-{i}" for i in range(1, 8)],
        "toid": ["nex-10", "nex-10", "nex-10", "nex-11", "nex-11", "nex-12", "nex-13"],
        "tot_drainage_areasqkm": [5.0, 6.0, 4.0, 20.0, 7.0, 30.0, 40.0],
    }
)
NET = pd.DataFrame(
    {
        "id": [f"wb-{i}" for i in range(1, 8)] + ["nex-10", "nex-11", "nex-12", "nex-13"],
        "toid": ["nex-10", "nex-10", "nex-10", "nex-11", "nex-11", "nex-12", "nex-13",
                 "wb-4", "wb-6", "wb-7", None],
        "hl_uri": [None, None, None, "gages-00000004", None, None, "gages-00000007",
                   None, None, None, None],
    }
)


class TestPreprocess:
    def test_collapses_nexus_chains(self):
        d = preprocess_river_network(NET)
        assert d["wb-4"] == ["wb-1", "wb-2", "wb-3"]
        assert d["wb-6"] == ["wb-4", "wb-5"]
        assert d["wb-7"] == ["wb-6"]

    def test_three_way_confluence(self):
        d = preprocess_river_network(NET)
        assert "wb-3" in d["wb-4"]

    def test_headwaters_absent(self):
        d = preprocess_river_network(NET)
        for hw in ("wb-1", "wb-2", "wb-3", "wb-5"):
            assert hw not in d

    def test_terminal_nexus_dropped(self):
        """wb-7 -> nex-13 -> None produces no connection: wb-7 is never an
        upstream, and no phantom downstream node appears for nex-13."""
        d = preprocess_river_network(NET)
        all_ups = {u for ups in d.values() for u in ups}
        assert "wb-7" not in all_ups
        assert set(d) == {"wb-4", "wb-6", "wb-7"}  # exactly the real confluences

    def test_duplicate_rows_collapse(self):
        doubled = pd.concat([NET, NET], ignore_index=True)
        assert preprocess_river_network(doubled) == preprocess_river_network(NET)

    def test_upstreams_sorted(self):
        d = preprocess_river_network(NET)
        for ups in d.values():
            assert ups == sorted(ups)


class TestSubsetTraversal:
    def test_outlet_covers_all(self):
        d = preprocess_river_network(NET)
        conns = subset("wb-7", d)
        nodes = {n for pair in conns for n in pair}
        assert nodes == {f"wb-{i}" for i in range(1, 8)}
        assert len(conns) == 6  # tree edges

    def test_intermediate(self):
        d = preprocess_river_network(NET)
        conns = subset("wb-4", d)
        nodes = {n for pair in conns for n in pair}
        assert nodes == {"wb-1", "wb-2", "wb-3", "wb-4"}

    def test_headwater_empty(self):
        d = preprocess_river_network(NET)
        assert subset("wb-2", d) == []

    def test_unknown_origin_empty(self):
        d = preprocess_river_network(NET)
        assert subset("wb-999", d) == []

    def test_connection_orientation(self):
        """Pairs are (downstream, upstream)."""
        d = preprocess_river_network(NET)
        conns = subset("wb-6", d)
        assert ("wb-6", "wb-4") in conns
        assert ("wb-4", "wb-6") not in conns

    def test_deep_chain_beyond_recursion_limit(self):
        """The iterative traversal survives chains longer than Python's default
        recursion limit (reference hit this at CONUS scale)."""
        n = 5000
        d = {f"wb-{i}": [f"wb-{i-1}"] for i in range(1, n)}
        conns = subset(f"wb-{n-1}", d)
        assert len(conns) == n - 1


class TestFindOriginHlUri:
    def test_match_on_hl_uri(self):
        g = Gauge(STAID="00000004", STANAME="x", DRAIN_SQKM=20.0)
        assert find_origin(g, FP, NET) == "wb-4"

    def test_staid_zero_fill_respected(self):
        """Gauge STAIDs validate zero-filled; hl_uri entries match exactly."""
        g = Gauge(STAID="00000007", STANAME="x", DRAIN_SQKM=40.0)
        assert find_origin(g, FP, NET) == "wb-7"

    def test_closest_drainage_area_wins(self):
        net = NET.copy()
        net.loc[net["id"] == "wb-5", "hl_uri"] = "gages-00000009"
        net.loc[net["id"] == "wb-6", "hl_uri"] = "gages-00000009"
        g = Gauge(STAID="00000009", STANAME="x", DRAIN_SQKM=8.0)
        assert find_origin(g, FP, net) == "wb-5"  # |7-8| < |30-8|


class TestMatrixStructure:
    def test_nexus_hop_resolved_to_edge(self):
        coo, order = create_matrix(FP, NET)
        edges = {(order[r], order[c]) for r, c in zip(coo.row, coo.col)}
        assert ("wb-4", "wb-3") in edges  # wb-3 -> nex-10 -> wb-4

    def test_nnz_matches_tree(self):
        coo, order = create_matrix(FP, NET)
        assert coo.nnz == 6
        assert len(order) == 7

    def test_topological_invariant(self):
        _, order = create_matrix(FP, NET)
        pos = {w: i for i, w in enumerate(order)}
        assert pos["wb-1"] < pos["wb-4"] < pos["wb-6"] < pos["wb-7"]

    def test_row_permutation_invariant(self, tmp_path):
        """Build is deterministic under input row shuffling (reference
        test_determinism.py)."""
        fp_shuf = FP.sample(frac=1.0, random_state=7).reset_index(drop=True)
        net_shuf = NET.sample(frac=1.0, random_state=9).reset_index(drop=True)
        a = build_lynker_hydrofabric_adjacency(FP, NET, tmp_path / "a.zarr")
        b = build_lynker_hydrofabric_adjacency(fp_shuf, net_shuf, tmp_path / "b.zarr")
        ca, oa = coo_from_zarr(a)
        cb, ob = coo_from_zarr(b)
        # Same edge set in conus space regardless of input ordering.
        ea = {(oa[r], oa[c]) for r, c in zip(ca.row, ca.col)}
        eb = {(ob[r], ob[c]) for r, c in zip(cb.row, cb.col)}
        assert ea == eb
