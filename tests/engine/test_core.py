"""Binsparse COO engine-core tests (reference tests/engine parity)."""

import numpy as np
import pytest
from scipy import sparse

from ddr_tpu.engine.core import (
    LynkerOrderConverter,
    MeritOrderConverter,
    coo_from_zarr,
    coo_from_zarr_group,
    coo_to_zarr,
    coo_to_zarr_group,
    get_converter,
    list_geodatasets,
    register_converter,
)
from ddr_tpu.io import zarrlite


def _chain_coo(n=5):
    rows = np.arange(1, n)
    cols = np.arange(0, n - 1)
    return sparse.coo_matrix((np.ones(n - 1, dtype=np.uint8), (rows, cols)), shape=(n, n))


def test_merit_roundtrip(tmp_path):
    coo = _chain_coo()
    comids = [71000001, 71000002, 71000003, 71000004, 71000005]
    coo_to_zarr(coo, comids, tmp_path / "adj.zarr", "merit")
    coo2, order = coo_from_zarr(tmp_path / "adj.zarr")
    assert order == comids
    np.testing.assert_array_equal(coo2.toarray(), coo.toarray())


def test_lynker_roundtrip(tmp_path):
    coo = _chain_coo(3)
    wb = ["wb-10", "wb-22", "wb-31"]
    coo_to_zarr(coo, wb, tmp_path / "adj.zarr", "lynker")
    coo2, order = coo_from_zarr(tmp_path / "adj.zarr")
    assert order == wb
    np.testing.assert_array_equal(coo2.row, coo.row)


def test_hydrofabric_alias():
    assert isinstance(get_converter("hydrofabric_v2.2"), LynkerOrderConverter)
    assert isinstance(get_converter("merit"), MeritOrderConverter)
    assert "lynker" in list_geodatasets()


def test_lynker_converter_rejects_bad_ids():
    with pytest.raises(ValueError):
        LynkerOrderConverter().to_zarr(["no_dash_id"])


def test_unknown_geodataset_raises():
    with pytest.raises(ValueError, match="unknown geodataset"):
        get_converter("nope")


def test_register_converter():
    class Custom:
        def to_zarr(self, ids):
            return np.asarray(ids, dtype=np.int32) * 2

        def from_zarr(self, order):
            return [int(v) // 2 for v in order]

    register_converter("custom_test", Custom())
    conv = get_converter("custom_test")
    assert conv.from_zarr(conv.to_zarr([1, 2])) == [1, 2]


def test_gauge_subset_groups(tmp_path):
    root = zarrlite.create_group(tmp_path / "gages.zarr")
    coo = _chain_coo(4)
    coo_to_zarr_group(
        root, "01234567", coo, [5, 6, 7, 8], "merit", gage_catchment=8, gage_idx=42
    )
    root2 = zarrlite.open_group(tmp_path / "gages.zarr")
    sub = root2["01234567"]
    assert sub.attrs["gage_catchment"] == 8
    assert sub.attrs["gage_idx"] == 42
    coo2, order = coo_from_zarr_group(sub)
    assert order == [5, 6, 7, 8]
    assert coo2.shape == (4, 4)
    assert sub.attrs["format"] == "COO"
    assert sub.attrs["data_types"]["values"] == "uint8"


def test_missing_geodataset_metadata_raises(tmp_path):
    root = zarrlite.create_group(tmp_path / "x.zarr")
    coo = _chain_coo(3)
    root.create_array("indices_0", coo.row.astype(np.int32))
    root.create_array("indices_1", coo.col.astype(np.int32))
    root.create_array("values", coo.data.astype(np.uint8))
    root.create_array("order", np.arange(3, dtype=np.int32))
    root.attrs.update({"format": "COO", "shape": [3, 3]})
    with pytest.raises(ValueError, match="geodataset"):
        coo_from_zarr(tmp_path / "x.zarr")


def test_lynker_converter_ghost_and_float_ids():
    """Reference accepts 'ghost-N' terminals and float-formatted ids (converters.py:61-117)."""
    conv = LynkerOrderConverter()
    np.testing.assert_array_equal(
        conv.to_zarr(["wb-123", "ghost-0", "wb-45.0"]), np.array([123, 0, 45], dtype=np.int32)
    )
    assert conv.from_zarr(np.array([123, 0], dtype=np.int32)) == ["wb-123", "wb-0"]


def test_empty_adjacency_roundtrip(tmp_path):
    """A headwater-only subset (no edges) must round-trip."""
    coo = sparse.coo_matrix((1, 1), dtype=np.uint8)
    coo_to_zarr(coo, [42], tmp_path / "e.zarr", "merit")
    coo2, order = coo_from_zarr(tmp_path / "e.zarr")
    assert order == [42] and coo2.nnz == 0 and coo2.shape == (1, 1)
