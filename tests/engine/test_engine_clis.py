"""Engine CLI drivers end to end: flowpath table / GeoPackage in, binsparse
stores out (reference python -m ddr_engine.{merit,lynker_hydrofabric} and
engine/scripts/build_hydrofabric_v2.2_matrices.py)."""

from __future__ import annotations

import sqlite3

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.engine.core import coo_from_zarr
from ddr_tpu.engine.lynker_cli import main as lynker_main
from ddr_tpu.engine.merit_cli import main as merit_main
from ddr_tpu.io import zarrlite

MERIT_FP = pd.DataFrame(
    {
        "COMID": [11, 12, 13, 14],
        "NextDownID": [13, 13, 14, 0],
        "up1": [0, 0, 11, 13],
        "up2": [0, 0, 12, 0],
        "lengthkm": [1.0, 2.0, 3.0, 4.0],
        "slope": [0.01, 0.02, 0.005, 0.001],
    }
)


@pytest.fixture()
def merit_csv(tmp_path):
    p = tmp_path / "flowpaths.csv"
    MERIT_FP.to_csv(p, index=False)
    return p


@pytest.fixture()
def merit_gages_csv(tmp_path):
    p = tmp_path / "gages.csv"
    p.write_text(
        "STAID,STANAME,DRAIN_SQKM,LAT_GAGE,LNG_GAGE,COMID\n"
        "00000001,outlet,100,40.0,-75.0,14\n"
        "00000002,mid,40,40.1,-75.1,13\n"
    )
    return p


class TestMeritCli:
    def test_builds_conus_store(self, merit_csv, tmp_path):
        out_dir = tmp_path / "out"
        assert merit_main([str(merit_csv), "--path", str(out_dir)]) == 0
        coo, order = coo_from_zarr(out_dir / "merit_conus_adjacency.zarr")
        assert sorted(order) == [11, 12, 13, 14]
        assert coo.nnz == 3

    def test_attributes_written(self, merit_csv, tmp_path):
        out_dir = tmp_path / "out"
        merit_main([str(merit_csv), "--path", str(out_dir)])
        root = zarrlite.open_group(out_dir / "merit_conus_adjacency.zarr")
        order = root["order"].read().tolist()
        assert root["length_m"].read()[order.index(14)] == pytest.approx(4000.0)

    def test_gages_store_built(self, merit_csv, merit_gages_csv, tmp_path):
        out_dir = tmp_path / "out"
        assert merit_main([str(merit_csv), "--path", str(out_dir), "--gages", str(merit_gages_csv)]) == 0
        root = zarrlite.open_group(out_dir / "merit_gages_conus_adjacency.zarr")
        assert "00000001" in root and "00000002" in root
        assert len(root["00000001"]["order"].read()) == 4  # outlet closure
        assert len(root["00000002"]["order"].read()) == 3

    def test_parquet_input(self, tmp_path):
        p = tmp_path / "flowpaths.parquet"
        MERIT_FP.to_parquet(p)
        out_dir = tmp_path / "out"
        assert merit_main([str(p), "--path", str(out_dir)]) == 0
        _, order = coo_from_zarr(out_dir / "merit_conus_adjacency.zarr")
        assert len(order) == 4


LYNKER_FP = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3"],
        "toid": ["nex-10", "nex-10", "nex-11"],
        "tot_drainage_areasqkm": [10.0, 12.0, 30.0],
    }
)
LYNKER_NET = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3", "nex-10", "nex-11"],
        "toid": ["nex-10", "nex-10", "nex-11", "wb-3", None],
        "hl_uri": [None, None, "gages-00000009", None, None],
    }
)


@pytest.fixture()
def gpkg(tmp_path):
    path = tmp_path / "hydrofabric.gpkg"
    with sqlite3.connect(path) as conn:
        LYNKER_FP[["id", "toid"]].to_sql("flowpaths", conn, index=False)
        LYNKER_FP.to_sql("fp_full", conn, index=False)  # unused extra table
        LYNKER_NET.to_sql("network", conn, index=False)
        pd.DataFrame(
            {
                "id": ["wb-1", "wb-2", "wb-3"],
                "Length_m": [1000.0, 1500.0, 2000.0],
                "So": [0.01, 0.012, 0.007],
                "TopWdth": [5.0, 6.0, 12.0],
                "ChSlp": [1.0, 1.2, 2.0],
                "MusX": [0.25, 0.3, 0.28],
            }
        ).to_sql("flowpath-attributes-ml", conn, index=False)
    return path


class TestLynkerCli:
    def test_builds_conus_store_with_attributes(self, gpkg, tmp_path):
        out_dir = tmp_path / "out"
        assert lynker_main([str(gpkg), "--path", str(out_dir)]) == 0
        store = out_dir / "hydrofabric_v2.2_conus_adjacency.zarr"
        coo, order = coo_from_zarr(store)
        assert len(order) == 3 and coo.nnz == 2
        root = zarrlite.open_group(store)
        num_order = root["order"].read().tolist()
        assert root["top_width"].read()[num_order.index(3)] == pytest.approx(12.0)

    def test_gages_store_built(self, gpkg, tmp_path):
        gages = tmp_path / "gages.csv"
        gages.write_text(
            "STAID,STANAME,DRAIN_SQKM,LAT_GAGE,LNG_GAGE\n00000009,out,30.0,40.0,-75.0\n"
        )
        out_dir = tmp_path / "out"
        assert lynker_main([str(gpkg), "--path", str(out_dir), "--gages", str(gages)]) == 0
        root = zarrlite.open_group(out_dir / "hydrofabric_v2.2_gages_conus_adjacency.zarr")
        assert "00000009" in root
        assert len(root["00000009"]["order"].read()) == 3  # full closure of wb-3

    def test_ghost_flag(self, gpkg, tmp_path):
        out_dir = tmp_path / "out"
        assert lynker_main([str(gpkg), "--path", str(out_dir), "--ghost"]) == 0
        coo, order = coo_from_zarr(out_dir / "hydrofabric_v2.2_conus_adjacency.zarr")
        # The ghost terminal adds a node + edge; its id round-trips lossily
        # through the numeric converter (ghost-0 -> 0 -> wb-0, the documented
        # behavior pinned by test_core's ghost tests).
        assert len(order) == 4 and coo.nnz == 3
        assert order[-1] == "wb-0"
