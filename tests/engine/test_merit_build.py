"""MERIT engine build pipeline: flowpath table -> zarr stores -> dataset -> routing
(the reference's engine integration strategy, tests/engine/merit/test_integration.py)."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.engine.core import coo_from_zarr
from ddr_tpu.engine.merit import (
    build_gauge_adjacencies,
    build_merit_adjacency,
    build_upstream_dict,
    create_adjacency_matrix,
)
from ddr_tpu.geodatazoo.dataclasses import GaugeSet, MERITGauge
from ddr_tpu.io import zarrlite


def _merit_table() -> pd.DataFrame:
    """11-reach dendritic basin + 1 isolated reach.

    Topology (COMID -> NextDownID): two 3-reach branches joining at 107, a side
    branch at 108, trunk 107 -> 108 -> 109 -> 110 (outlet). 199 is isolated.
    """
    rows = [
        # COMID, NextDownID, up1..up4, lengthkm, slope
        (101, 103, 0, 0, 0, 0, 1.2, 0.010),
        (102, 103, 0, 0, 0, 0, 2.0, 0.012),
        (103, 107, 101, 102, 0, 0, 1.8, 0.008),
        (104, 106, 0, 0, 0, 0, 1.1, 0.015),
        (105, 106, 0, 0, 0, 0, 0.9, 0.014),
        (106, 107, 104, 105, 0, 0, 2.2, 0.007),
        (107, 108, 103, 106, 0, 0, 3.0, 0.005),
        (108, 109, 107, 111, 0, 0, 2.5, 0.004),
        (109, 110, 108, 0, 0, 0, 4.0, 0.003),
        (110, 0, 109, 0, 0, 0, 5.0, 0.002),
        (111, 108, 0, 0, 0, 0, 1.5, 0.02),
        (199, 0, 0, 0, 0, 0, 0.7, 0.03),  # isolated
    ]
    return pd.DataFrame(
        rows, columns=["COMID", "NextDownID", "up1", "up2", "up3", "up4", "lengthkm", "slope"]
    )


class TestMeritBuild:
    def test_upstream_dict(self):
        d = build_upstream_dict(_merit_table())
        assert d[103] == [101, 102]
        assert d[108] == [107, 111]
        assert 199 not in d

    def test_adjacency_lower_triangular_and_complete(self):
        coo, order = create_adjacency_matrix(_merit_table())
        assert len(order) == 12  # 11 connected + isolated appended
        assert order[-1] == 199
        assert (coo.row > coo.col).all()
        pos = {c: i for i, c in enumerate(order)}
        # each edge upstream index < downstream index in topo order
        for r, c in zip(coo.row, coo.col):
            assert pos[order[c]] < pos[order[r]]
        assert coo.nnz == 10  # 11 connected reaches in a tree -> 10 edges

    def test_cycle_removed_and_rebuilt(self):
        fp = _merit_table()
        # introduce a cycle: 110 -> 104 (via up columns on 104)
        fp.loc[fp["COMID"] == 104, "up1"] = 110
        coo, order = create_adjacency_matrix(fp)
        # the whole trunk 104..110 participates in the cycle and is removed
        assert 199 in order
        assert (coo.row > coo.col).all() if coo.nnz else True

    def test_full_store_roundtrip(self, tmp_path):
        out = build_merit_adjacency(_merit_table(), tmp_path / "conus.zarr")
        coo, order = coo_from_zarr(out)
        assert len(order) == 12
        g = zarrlite.open_group(out)
        length_m = g["length_m"].read()
        assert length_m.shape == (12,)
        # aligned: outlet 110 has 5.0 km
        assert length_m[order.index(110)] == pytest.approx(5000.0)
        assert g["slope"].read()[order.index(110)] == pytest.approx(0.002, abs=1e-6)

    def test_existing_store_raises(self, tmp_path):
        build_merit_adjacency(_merit_table(), tmp_path / "conus.zarr")
        with pytest.raises(FileExistsError):
            build_merit_adjacency(_merit_table(), tmp_path / "conus.zarr")


class TestGaugeAdjacencies:
    @pytest.fixture()
    def stores(self, tmp_path):
        fp = _merit_table()
        conus = build_merit_adjacency(fp, tmp_path / "conus.zarr")
        gauges = GaugeSet(
            gauges=[
                MERITGauge(STAID="1", STANAME="a", DRAIN_SQKM=10, COMID=107),
                MERITGauge(STAID="2", STANAME="b", DRAIN_SQKM=50, COMID=110),
                MERITGauge(STAID="3", STANAME="c", DRAIN_SQKM=5, COMID=199),  # isolated
                MERITGauge(STAID="4", STANAME="d", DRAIN_SQKM=5, COMID=999),  # absent
            ]
        )
        gages = build_gauge_adjacencies(fp, conus, gauges, tmp_path / "gages.zarr")
        return conus, gages

    def test_subset_contents(self, stores):
        conus, gages = stores
        root = zarrlite.open_group(gages)
        sub = root["00000001"]
        order = sub["order"].read().tolist()
        # closure of 107: {101..107}
        assert sorted(order) == [101, 102, 103, 104, 105, 106, 107]
        assert sub.attrs["gage_catchment"] == 107
        conus_order = zarrlite.open_group(conus)["order"].read().tolist()
        assert sub.attrs["gage_idx"] == conus_order.index(107)
        # edges are conus-indexed, lower triangular
        assert (sub["indices_0"].read() > sub["indices_1"].read()).all()

    def test_headwater_subset_is_empty_matrix(self, stores):
        _, gages = stores
        root = zarrlite.open_group(gages)
        sub = root["00000003"]
        assert sub["indices_0"].shape[0] == 0
        assert sub["order"].read().tolist() == [199]

    def test_absent_comid_skipped(self, stores):
        _, gages = stores
        assert "00000004" not in zarrlite.open_group(gages)

    def test_determinism(self, stores, tmp_path):
        fp = _merit_table()
        conus2 = build_merit_adjacency(fp, tmp_path / "conus2.zarr")
        gauges = GaugeSet(
            gauges=[MERITGauge(STAID="1", STANAME="a", DRAIN_SQKM=10, COMID=107)]
        )
        gages2 = build_gauge_adjacencies(fp, conus2, gauges, tmp_path / "gages2.zarr")
        a = zarrlite.open_group(stores[1])["00000001"]
        b = zarrlite.open_group(gages2)["00000001"]
        np.testing.assert_array_equal(a["order"].read(), b["order"].read())
        np.testing.assert_array_equal(
            np.sort(a["indices_0"].read()), np.sort(b["indices_0"].read())
        )


class TestEndToEnd:
    def test_built_stores_drive_dataset_and_routing(self, tmp_path):
        """Engine output -> Merit dataset -> routed discharge, no hand-built zarr."""
        from ddr_tpu.geodatazoo.merit import Merit
        from ddr_tpu.io.stores import write_attribute_store, write_hydro_store
        from ddr_tpu.scripts.train import train
        from ddr_tpu.validation.configs import Config

        fp = _merit_table()
        conus = build_merit_adjacency(fp, tmp_path / "conus.zarr")
        gauges = GaugeSet(
            gauges=[
                MERITGauge(STAID="11111111", STANAME="a", DRAIN_SQKM=100, COMID=107),
                MERITGauge(STAID="22222222", STANAME="b", DRAIN_SQKM=400, COMID=110),
            ]
        )
        gages = build_gauge_adjacencies(fp, conus, gauges, tmp_path / "gages.zarr")

        rng = np.random.default_rng(0)
        comids = fp["COMID"].tolist()
        attr_names = [f"a{i}" for i in range(4)]
        write_attribute_store(
            tmp_path / "attrs.zarr",
            comids,
            {n: rng.normal(size=len(comids)).astype(np.float32) for n in attr_names},
        )
        write_hydro_store(
            tmp_path / "flow.zarr", comids, "1981/09/25", "D",
            {"Qr": rng.uniform(0.1, 2.0, (len(comids), 40)).astype(np.float32)},
        )
        write_hydro_store(
            tmp_path / "obs.zarr", ["11111111", "22222222"], "1981/09/25", "D",
            {"streamflow": rng.uniform(1, 20, (2, 40)).astype(np.float32)},
            id_dim="gage_id",
        )
        (tmp_path / "gages.csv").write_text(
            "STAID,STANAME,DRAIN_SQKM,LAT_GAGE,LNG_GAGE,COMID,DA_VALID\n"
            "11111111,a,100,40,-75,107,True\n22222222,b,400,40,-75,110,True\n"
        )

        cfg = Config(
            name="engine_e2e",
            geodataset="merit",
            mode="training",
            kan={"input_var_names": attr_names},
            experiment={
                "start_time": "1981/10/01", "end_time": "1981/10/20",
                "rho": 8, "batch_size": 2, "epochs": 1, "learning_rate": {1: 0.01},
                "warmup": 1,
            },
            data_sources={
                "attributes": str(tmp_path / "attrs.zarr"),
                "conus_adjacency": str(conus),
                "streamflow": str(tmp_path / "flow.zarr"),
                "observations": str(tmp_path / "obs.zarr"),
                "gages": str(tmp_path / "gages.csv"),
                "gages_adjacency": str(gages),
                "statistics": str(tmp_path / "stats"),
            },
            params={"save_path": str(tmp_path)},
        )
        dataset = Merit(cfg)
        params, _ = train(cfg, dataset=dataset, max_batches=1)
        assert params is not None
