"""Field-level binsparse on-disk contract + MERIT pipeline edge cases.

Mirrors the reference engine suite's granular coverage
(/root/reference/tests/engine/core/test_zarr_io.py,
/root/reference/tests/engine/merit/test_{graph,build,io}.py): every metadata
attribute and array the binsparse spec promises, plus the degenerate networks
(isolated COMIDs, single nodes, headwater-only gauges) that real MERIT extracts
contain."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest
from scipy import sparse

from ddr_tpu.engine.core import (
    coo_from_zarr,
    coo_from_zarr_group,
    coo_to_zarr,
    coo_to_zarr_group,
    read_coo_arrays,
)
from ddr_tpu.engine.merit import build_upstream_dict, create_adjacency_matrix
from ddr_tpu.io import zarrlite


def _fp(rows):
    """Flowpath table from (COMID, up1..up4, lengthkm, slope) tuples."""
    return pd.DataFrame(
        rows, columns=["COMID", "up1", "up2", "up3", "up4", "lengthkm", "slope"]
    )


@pytest.fixture()
def y_network():
    """10, 20 -> 30 -> 40, plus isolated COMID 99."""
    return _fp(
        [
            (10, 0, 0, 0, 0, 1.0, 0.001),
            (20, 0, 0, 0, 0, 2.0, 0.002),
            (30, 10, 20, 0, 0, 3.0, 0.003),
            (40, 30, 0, 0, 0, 4.0, 0.004),
            (99, 0, 0, 0, 0, 9.0, 0.009),
        ]
    )


class TestBinsparseOnDiskContract:
    @pytest.fixture()
    def store(self, tmp_path, y_network):
        coo, order = create_adjacency_matrix(y_network)
        path = tmp_path / "adj.zarr"
        coo_to_zarr(coo, order, path, "merit")
        return path, coo, order

    def test_required_arrays_exist(self, store):
        path, _, _ = store
        root = zarrlite.open_group(path)
        for name in ("indices_0", "indices_1", "values", "order"):
            assert name in root, name

    def test_format_attr(self, store):
        root = zarrlite.open_group(store[0])
        assert root.attrs["format"] == "COO"

    def test_shape_attr_matches_matrix(self, store):
        path, coo, _ = store
        root = zarrlite.open_group(path)
        assert tuple(root.attrs["shape"]) == coo.shape == (5, 5)

    def test_geodataset_attr(self, store):
        assert zarrlite.open_group(store[0]).attrs["geodataset"] == "merit"

    def test_data_types_attr_matches_arrays(self, store):
        root = zarrlite.open_group(store[0])
        dt = root.attrs["data_types"]
        for name in ("indices_0", "indices_1", "values"):
            assert root[name].read().dtype == np.dtype(dt[name])

    def test_indices_are_int32(self, store):
        root = zarrlite.open_group(store[0])
        assert root["indices_0"].read().dtype == np.int32
        assert root["indices_1"].read().dtype == np.int32

    def test_values_all_ones_uint8(self, store):
        vals = zarrlite.open_group(store[0])["values"].read()
        assert vals.dtype == np.uint8
        np.testing.assert_array_equal(vals, 1)

    def test_coo_is_lower_triangular(self, store):
        root = zarrlite.open_group(store[0])
        assert np.all(root["indices_0"].read() > root["indices_1"].read())

    def test_order_roundtrips_comids(self, store):
        path, _, order = store
        _, back = coo_from_zarr(path)
        assert back == order
        assert all(isinstance(c, (int, np.integer)) for c in back)

    def test_matrix_roundtrips_exactly(self, store):
        path, coo, _ = store
        back, _ = coo_from_zarr(path)
        np.testing.assert_array_equal(back.toarray(), coo.toarray())

    def test_read_coo_arrays_matches_memory(self, store):
        path, coo, order = store
        root = zarrlite.open_group(path)
        back, raw_order = read_coo_arrays(root)
        np.testing.assert_array_equal(back.toarray(), coo.toarray())
        np.testing.assert_array_equal(raw_order, np.asarray(order, dtype=np.int64))

    def test_subgroup_carries_gauge_attrs(self, tmp_path, y_network):
        coo, order = create_adjacency_matrix(y_network)
        root = zarrlite.create_group(tmp_path / "gauges.zarr")
        sub = coo_to_zarr_group(
            root, "01013500", coo, order, "merit", gage_catchment=30, gage_idx=2
        )
        assert sub.attrs["gage_catchment"] == 30
        assert sub.attrs["gage_idx"] == 2
        back, back_order = coo_from_zarr_group(root["01013500"])
        assert back_order == order
        np.testing.assert_array_equal(back.toarray(), coo.toarray())


class TestMeritEdgeCases:
    def test_isolated_comid_appended_after_connected_order(self, y_network):
        coo, order = create_adjacency_matrix(y_network)
        assert order[-1] == 99
        assert set(order[:-1]) == {10, 20, 30, 40}

    def test_isolated_comid_has_no_edges(self, y_network):
        coo, order = create_adjacency_matrix(y_network)
        iso = order.index(99)
        assert iso not in set(coo.row.tolist()) | set(coo.col.tolist())

    def test_edge_count_matches_connections(self, y_network):
        coo, _ = create_adjacency_matrix(y_network)
        assert coo.nnz == 3  # 10->30, 20->30, 30->40

    def test_matrix_encodes_expected_edges(self, y_network):
        coo, order = create_adjacency_matrix(y_network)
        pos = {c: i for i, c in enumerate(order)}
        edges = set(zip(coo.row.tolist(), coo.col.tolist()))
        assert edges == {
            (pos[30], pos[10]),
            (pos[30], pos[20]),
            (pos[40], pos[30]),
        }

    def test_topological_order_valid(self, y_network):
        coo, order = create_adjacency_matrix(y_network)
        pos = {c: i for i, c in enumerate(order)}
        assert pos[10] < pos[30] < pos[40]
        assert pos[20] < pos[30]

    def test_single_connection_network(self):
        coo, order = create_adjacency_matrix(
            _fp([(1, 0, 0, 0, 0, 1.0, 0.001), (2, 1, 0, 0, 0, 1.0, 0.001)])
        )
        assert order == [1, 2]
        assert coo.nnz == 1

    def test_all_isolated_raises(self):
        with pytest.raises(ValueError, match="No upstream connections"):
            create_adjacency_matrix(
                _fp([(1, 0, 0, 0, 0, 1.0, 0.001), (2, 0, 0, 0, 0, 1.0, 0.001)])
            )

    def test_upstream_dict_ignores_nonpositive_and_nan(self):
        fp = _fp(
            [
                (10, 0, -1, 0, 0, 1.0, 0.001),
                (20, 10, np.nan, 0, 0, 1.0, 0.001),
            ]
        )
        assert build_upstream_dict(fp) == {20: [10]}

    def test_upstream_dict_sorts_upstreams(self):
        fp = _fp([(30, 20, 10, 0, 0, 1.0, 0.001)])
        assert build_upstream_dict(fp) == {30: [10, 20]}

    def test_non_dendritic_rejected(self):
        # 10 drains into BOTH 20 and 30
        fp = _fp(
            [
                (20, 10, 0, 0, 0, 1.0, 0.001),
                (30, 10, 0, 0, 0, 1.0, 0.001),
            ]
        )
        with pytest.raises(AssertionError, match="multiple successors"):
            create_adjacency_matrix(fp)

    def test_missing_up_columns_tolerated(self):
        fp = pd.DataFrame({"COMID": [1, 2], "up1": [0, 1]})
        assert build_upstream_dict(fp) == {2: [1]}

    def test_self_loop_is_removed_as_cycle(self):
        fp = _fp(
            [
                (10, 10, 0, 0, 0, 1.0, 0.001),  # self-cycle
                (20, 0, 0, 0, 0, 1.0, 0.001),
                (30, 20, 0, 0, 0, 1.0, 0.001),
            ]
        )
        coo, order = create_adjacency_matrix(fp)
        assert 10 not in order  # cycle flowpath dropped, rest rebuilt
        assert set(order) == {20, 30}

    def test_two_cycle_removed(self):
        fp = _fp(
            [
                (10, 20, 0, 0, 0, 1.0, 0.001),
                (20, 10, 0, 0, 0, 1.0, 0.001),
                (30, 0, 0, 0, 0, 1.0, 0.001),
                (40, 30, 0, 0, 0, 1.0, 0.001),
            ]
        )
        coo, order = create_adjacency_matrix(fp)
        assert set(order) == {30, 40}
        assert coo.nnz == 1
