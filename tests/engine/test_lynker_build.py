"""Lynker engine build pipeline: network collapse, origin lookup, matrix build,
sqlite (GeoPackage) attribute extraction, per-gauge subsets, determinism
(reference tests/engine/lynker_hydrofabric/*)."""

from __future__ import annotations

import sqlite3

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.engine.core import coo_from_zarr
from ddr_tpu.engine.lynker import (
    build_gauge_adjacencies,
    build_lynker_hydrofabric_adjacency,
    create_matrix,
    find_origin,
    preprocess_river_network,
    subset,
    write_flowpath_attributes,
)
from ddr_tpu.geodatazoo.dataclasses import Gauge, GaugeSet
from ddr_tpu.io import zarrlite

# wb-1, wb-2 -> nex-10 -> wb-3; wb-3, wb-4 -> nex-11 -> wb-5; wb-5 -> nex-12 (terminal)
FLOWPATHS = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3", "wb-4", "wb-5"],
        "toid": ["nex-10", "nex-10", "nex-11", "nex-11", "nex-12"],
        "tot_drainage_areasqkm": [10.0, 12.0, 30.0, 8.0, 55.0],
    }
)
NETWORK = pd.DataFrame(
    {
        "id": ["wb-1", "wb-2", "wb-3", "wb-4", "wb-5", "nex-10", "nex-11", "nex-12"],
        "toid": ["nex-10", "nex-10", "nex-11", "nex-11", "nex-12", "wb-3", "wb-5", None],
        "hl_uri": [None, None, "gages-11111111", None, "gages-22222222", None, None, None],
    }
)


class TestNetworkCollapse:
    def test_wb_to_wb_collapse(self):
        d = preprocess_river_network(NETWORK)
        assert d["wb-3"] == ["wb-1", "wb-2"]
        assert d["wb-5"] == ["wb-3", "wb-4"]

    def test_subset_traversal(self):
        d = preprocess_river_network(NETWORK)
        conns = subset("wb-5", d)
        assert ("wb-5", "wb-3") in conns and ("wb-3", "wb-1") in conns
        assert len(conns) == 4
        assert subset("wb-1", d) == []  # headwater


class TestFindOrigin:
    def test_simple_match(self):
        g = Gauge(STAID="22222222", STANAME="x", DRAIN_SQKM=50.0)
        assert find_origin(g, FLOWPATHS, NETWORK) == "wb-5"

    def test_no_match_raises(self):
        g = Gauge(STAID="99999999", STANAME="x", DRAIN_SQKM=50.0)
        with pytest.raises(ValueError):
            find_origin(g, FLOWPATHS, NETWORK)

    def test_tie_break_on_drainage_area(self):
        network = NETWORK.copy()
        network.loc[network["id"] == "wb-4", "hl_uri"] = "gages-33333333"
        network.loc[network["id"] == "wb-3", "hl_uri"] = "gages-33333333"
        g = Gauge(STAID="33333333", STANAME="x", DRAIN_SQKM=9.0)
        assert find_origin(g, FLOWPATHS, network) == "wb-4"  # |8-9| < |30-9|


class TestCreateMatrix:
    def test_lower_triangular_dendritic(self):
        coo, order = create_matrix(FLOWPATHS, NETWORK)
        assert len(order) == 5
        assert (coo.row > coo.col).all()
        assert coo.nnz == 4
        pos = {w: i for i, w in enumerate(order)}
        assert pos["wb-1"] < pos["wb-3"] < pos["wb-5"]

    def test_ghost_nodes(self):
        coo, order = create_matrix(FLOWPATHS, NETWORK, ghost=True)
        assert any(w.startswith("ghost-") for w in order)
        assert coo.nnz == 5  # wb-5 -> ghost edge added

    def test_non_dendritic_raises(self):
        fp = pd.concat(
            [FLOWPATHS, pd.DataFrame({"id": ["wb-1"], "toid": ["nex-11"], "tot_drainage_areasqkm": [1.0]})]
        )
        with pytest.raises(AssertionError, match="not dendritic"):
            create_matrix(fp, NETWORK)


class TestStoresAndAttributes:
    @pytest.fixture()
    def gpkg(self, tmp_path):
        """GeoPackage-style sqlite with flowpaths + flowpath-attributes-ml tables."""
        path = tmp_path / "hydrofabric.gpkg"
        with sqlite3.connect(path) as conn:
            FLOWPATHS[["id", "toid"]].to_sql("flowpaths", conn, index=False)
            NETWORK[["id", "toid"]].to_sql("network", conn, index=False)
            pd.DataFrame(
                {
                    "id": ["wb-1", "wb-2", "wb-3", "wb-4", "wb-5"],
                    "Length_m": [1000.0, 1500.0, 2000.0, 900.0, 3000.0],
                    "So": [0.01, 0.012, 0.007, 0.02, 0.004],
                    "TopWdth": [5.0, 6.0, 12.0, 4.0, 20.0],
                    "ChSlp": [1.0, 1.2, 2.0, 0.8, 2.5],
                    "MusX": [0.25, 0.3, 0.28, 0.22, 0.35],
                }
            ).to_sql("flowpath-attributes-ml", conn, index=False)
        return path

    def test_build_with_gpkg_attributes(self, gpkg, tmp_path):
        out = build_lynker_hydrofabric_adjacency(
            FLOWPATHS, NETWORK, tmp_path / "conus.zarr", attributes=gpkg
        )
        coo, order = coo_from_zarr(out)
        assert order == [o for o in order]  # wb strings round-trip
        g = zarrlite.open_group(out)
        tw = g["top_width"].read()
        idx5 = order.index("wb-5")
        assert tw[idx5] == pytest.approx(20.0)
        assert g["muskingum_x"].read()[idx5] == pytest.approx(0.35)
        # toid stores the numeric downstream wb (wb-3 drains to wb-5)
        assert g["toid"].read()[order.index("wb-3")] == 5

    def test_gauge_subsets(self, gpkg, tmp_path):
        conus = build_lynker_hydrofabric_adjacency(
            FLOWPATHS, NETWORK, tmp_path / "conus.zarr", attributes=gpkg
        )
        gauges = GaugeSet(
            gauges=[
                Gauge(STAID="11111111", STANAME="a", DRAIN_SQKM=30.0),
                Gauge(STAID="22222222", STANAME="b", DRAIN_SQKM=55.0),
            ]
        )
        out = build_gauge_adjacencies(
            FLOWPATHS, NETWORK, conus, gauges, tmp_path / "gages.zarr"
        )
        root = zarrlite.open_group(out)
        sub = root["22222222"]
        # closure of wb-5 = all five reaches
        assert len(sub["order"].read()) == 5
        assert sub.attrs["gage_catchment"] == "wb-5"
        assert (sub["indices_0"].read() > sub["indices_1"].read()).all()
        sub1 = root["11111111"]
        assert len(sub1["order"].read()) == 3  # wb-3 closure: {1, 2, 3}

    def test_determinism(self, tmp_path):
        a = build_lynker_hydrofabric_adjacency(FLOWPATHS, NETWORK, tmp_path / "a.zarr")
        b = build_lynker_hydrofabric_adjacency(FLOWPATHS, NETWORK, tmp_path / "b.zarr")
        ca, oa = coo_from_zarr(a)
        cb, ob = coo_from_zarr(b)
        assert oa == ob
        np.testing.assert_array_equal(ca.row, cb.row)
        np.testing.assert_array_equal(ca.col, cb.col)
