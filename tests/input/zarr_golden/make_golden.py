"""Generate the committed zarr-v3 golden store WITHOUT ddr_tpu.io.zarrlite.

Every byte below is derived directly from the zarr v3 core spec
(https://zarr-specs.readthedocs.io/en/latest/v3/core/v3.0.html): metadata documents
are hand-built JSON, chunk payloads are C-order ``struct``-packed scalars (not numpy
``tobytes`` of the arrays under test), and the gzip chunk is compressed with
``mtime=0`` for reproducibility. ``tests/io/test_zarrlite_interop.py`` then asserts
that zarrlite reads these bytes to the expected values and writes byte-identical
chunks for the uncompressed cases — interop evidence that does not depend on the
implementation it is testing.

Run from the repo root to regenerate:  python tests/input/zarr_golden/make_golden.py
"""

from __future__ import annotations

import gzip
import json
import struct
from pathlib import Path

HERE = Path(__file__).parent
STORE = HERE / "store"


def write_json(path: Path, doc: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=2))


def array_meta(shape, dtype, chunks, fill, codecs, attributes=None) -> dict:
    return {
        "zarr_format": 3,
        "node_type": "array",
        "shape": list(shape),
        "data_type": dtype,
        "chunk_grid": {"name": "regular", "configuration": {"chunk_shape": list(chunks)}},
        "chunk_key_encoding": {"name": "default", "configuration": {"separator": "/"}},
        "fill_value": fill,
        "codecs": codecs,
        "attributes": attributes or {},
    }


BYTES_LE = [{"name": "bytes", "configuration": {"endian": "little"}}]
BYTES_BE = [{"name": "bytes", "configuration": {"endian": "big"}}]
GZIP5 = BYTES_LE + [{"name": "gzip", "configuration": {"level": 5}}]


def main() -> None:
    write_json(
        STORE / "zarr.json",
        {
            "zarr_format": 3,
            "node_type": "group",
            "attributes": {"title": "zarrlite interop golden store", "answer": 42},
        },
    )

    # ints: (5, 3) int32 = arange(15) row-major, chunks (2, 2) -> 3x2 chunk grid with
    # edge chunks padded by fill_value=-1. Chunk (i, j) holds rows 2i..2i+1, cols
    # 2j..2j+1 of the logical array; payload is C-order over the CHUNK shape.
    write_json(
        STORE / "ints" / "zarr.json",
        array_meta((5, 3), "int32", (2, 2), -1, BYTES_LE, {"role": "edge-chunk case"}),
    )

    def int_chunk(values):
        return b"".join(struct.pack("<i", v) for v in values)

    chunks_ints = {
        (0, 0): [0, 1, 3, 4],
        (0, 1): [2, -1, 5, -1],
        (1, 0): [6, 7, 9, 10],
        (1, 1): [8, -1, 11, -1],
        (2, 0): [12, 13, -1, -1],
        (2, 1): [14, -1, -1, -1],
    }
    for (i, j), vals in chunks_ints.items():
        p = STORE / "ints" / "c" / str(i) / str(j)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(int_chunk(vals))

    # floats: (7,) float64 [0.5, -1.5, nan, 3.25, 10.0, -0.125, 2**-40], chunks (4,),
    # fill NaN, bytes+gzip(level=5). Edge chunk padded with NaN.
    write_json(
        STORE / "floats" / "zarr.json",
        array_meta((7,), "float64", (4,), "NaN", GZIP5),
    )
    f_vals = [0.5, -1.5, float("nan"), 3.25, 10.0, -0.125, 2.0**-40]

    def f64_chunk(values):
        return b"".join(struct.pack("<d", v) for v in values)

    (STORE / "floats" / "c").mkdir(parents=True, exist_ok=True)
    for i, vals in enumerate([f_vals[:4], f_vals[4:] + [float("nan")]]):
        payload = gzip.compress(f64_chunk(vals), compresslevel=5, mtime=0)
        (STORE / "floats" / "c" / str(i)).write_bytes(payload)

    # bige: (3,) int16 BIG-endian bytes codec — legal v3 that a little-endian-only
    # reader decodes to garbage. Values [1, -2, 300].
    write_json(STORE / "bige" / "zarr.json", array_meta((3,), "int16", (3,), 0, BYTES_BE))
    (STORE / "bige" / "c").mkdir(parents=True, exist_ok=True)
    (STORE / "bige" / "c" / "0").write_bytes(
        b"".join(struct.pack(">h", v) for v in [1, -2, 300])
    )

    # flags: (4,) bool [True, False, False, True], one chunk, raw.
    write_json(STORE / "flags" / "zarr.json", array_meta((4,), "bool", (4,), False, BYTES_LE))
    (STORE / "flags" / "c").mkdir(parents=True, exist_ok=True)
    (STORE / "flags" / "c" / "0").write_bytes(bytes([1, 0, 0, 1]))

    # scalar: rank-0 float32 = 6.5; chunk key for rank 0 is just "c".
    write_json(STORE / "scalar" / "zarr.json", array_meta((), "float32", (), 0.0, BYTES_LE))
    (STORE / "scalar" / "c").write_bytes(struct.pack("<f", 6.5))

    # sub/missing_chunks: (4,) int64 with NO chunk files -> reads as all fill (=7).
    write_json(
        STORE / "sub" / "zarr.json",
        {"zarr_format": 3, "node_type": "group", "attributes": {}},
    )
    write_json(
        STORE / "sub" / "missing_chunks" / "zarr.json",
        array_meta((4,), "int64", (4,), 7, BYTES_LE),
    )

    # Unsupported-but-legal v3 metadata: zarrlite must refuse LOUDLY, never return fill.
    write_json(
        STORE / "zstd_codec" / "zarr.json",
        array_meta(
            (2,), "int32", (2,), 0,
            BYTES_LE + [{"name": "zstd", "configuration": {"level": 0, "checksum": False}}],
        ),
    )
    dot = array_meta((2,), "int32", (2,), 0, BYTES_LE)
    dot["chunk_key_encoding"] = {"name": "default", "configuration": {"separator": "."}}
    write_json(STORE / "dot_separator" / "zarr.json", dot)

    print(f"golden store written under {STORE}")


if __name__ == "__main__":
    main()
