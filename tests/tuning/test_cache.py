"""The persistent tuning cache (ddr_tpu/tuning/cache.py): key stability,
round-trips, version invalidation, and corruption tolerance. Jax-free by
package contract — this module must import and run without jax."""

from __future__ import annotations

import json

import pytest

from ddr_tpu.tuning import cache


MESH = {"axes": ["reach"], "shape": [8], "platform": "cpu", "n_devices": 8}


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DDR_TUNE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("DDR_COMPILE_CACHE_DIR", raising=False)
    return tmp_path


class TestCacheDir:
    def test_explicit_dir_wins(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DDR_TUNE_CACHE_DIR", str(tmp_path / "t"))
        monkeypatch.setenv("DDR_COMPILE_CACHE_DIR", str(tmp_path / "c"))
        assert cache.tuning_cache_dir() == tmp_path / "t"

    def test_compile_cache_fallback_is_a_subdir(self, tmp_path, monkeypatch):
        """The planner rides the same persistent volume as the XLA executable
        cache — a fleet that warms one warms both."""
        monkeypatch.delenv("DDR_TUNE_CACHE_DIR", raising=False)
        monkeypatch.setenv("DDR_COMPILE_CACHE_DIR", str(tmp_path))
        assert cache.tuning_cache_dir() == tmp_path / "tuning"

    def test_unconfigured_means_no_persistence(self, monkeypatch):
        monkeypatch.delenv("DDR_TUNE_CACHE_DIR", raising=False)
        monkeypatch.delenv("DDR_COMPILE_CACHE_DIR", raising=False)
        assert cache.tuning_cache_dir() is None
        assert cache.load_plan("deadbeef") is None
        assert cache.store_plan("deadbeef", {"engine": "gspmd"}) is None

    def test_resolving_creates_nothing(self, tmp_path, monkeypatch):
        """Read-only callers must not mkdir (side-effect-free resolution)."""
        target = tmp_path / "never-created"
        monkeypatch.setenv("DDR_TUNE_CACHE_DIR", str(target))
        cache.tuning_cache_dir()
        assert cache.load_plan("deadbeef") is None
        assert not target.exists()


class TestPlanKey:
    def test_mesh_identity_fields_only(self):
        """The key uses the mesh's content identity (axes/shape/platform/
        device count), never process identity: the same fleet shape on
        different device ids — a restarted replica — must hit the cache."""
        extra = dict(MESH, topology="abc123", process_count=2, device_ids=[3, 1])
        assert cache.plan_key("t", MESH, "fp32", None) == cache.plan_key(
            "t", extra, "fp32", None
        )

    @pytest.mark.parametrize(
        "other",
        [
            dict(MESH, shape=[4], n_devices=4),
            dict(MESH, platform="tpu"),
            dict(MESH, axes=["band"]),
        ],
    )
    def test_mesh_shape_changes_the_key(self, other):
        assert cache.plan_key("t", MESH, "fp32", None) != cache.plan_key(
            "t", other, "fp32", None
        )

    def test_every_query_axis_participates(self):
        base = cache.plan_key("topo-a", MESH, "fp32", None)
        assert cache.plan_key("topo-b", MESH, "fp32", None) != base
        assert cache.plan_key("topo-a", MESH, "bf16", None) != base
        assert cache.plan_key("topo-a", MESH, "fp32", "pallas") != base
        assert cache.plan_key("topo-a", MESH, "fp32", None, version=99) != base

    def test_kernel_none_is_auto(self):
        """None and "auto" are the same kernel axis value (route_parallel's
        contract) — they must not fork the cache."""
        assert cache.plan_key("t", MESH, "fp32", None) == cache.plan_key(
            "t", MESH, "fp32", "auto"
        )


class TestPlanRoundTrip:
    def test_store_then_load(self, cache_dir):
        key = cache.plan_key("topo", MESH, "fp32", None)
        path = cache.store_plan(key, {"engine": "sharded-wavefront", "n": 64})
        assert path is not None and path.exists()
        rec = cache.load_plan(key)
        assert rec["engine"] == "sharded-wavefront"
        assert rec["n"] == 64
        assert rec["planner_version"] == cache.PLANNER_VERSION
        assert "wall" in rec

    def test_version_mismatch_invalidates(self, cache_dir):
        """A scoring-model bump must orphan every stale entry at once."""
        key = cache.plan_key("topo", MESH, "fp32", None)
        cache.store_plan(key, {"engine": "gspmd", "planner_version": cache.PLANNER_VERSION + 1})
        assert cache.load_plan(key) is None

    def test_corrupt_entry_tolerated(self, cache_dir):
        key = cache.plan_key("topo", MESH, "fp32", None)
        (cache_dir / f"plan_{key}.json").write_text("{not json")
        assert cache.load_plan(key) is None

    def test_non_dict_and_engineless_entries_rejected(self, cache_dir):
        key = cache.plan_key("topo", MESH, "fp32", None)
        (cache_dir / f"plan_{key}.json").write_text(json.dumps([1, 2]))
        assert cache.load_plan(key) is None
        cache.store_plan(key, {"engine": 7})
        assert cache.load_plan(key) is None

    def test_unwritable_dir_never_raises(self, monkeypatch, tmp_path):
        """Best-effort persistence: a read-only cache volume degrades to the
        in-process memo, never to a crash."""
        blocker = tmp_path / "file"
        blocker.write_text("")
        monkeypatch.setenv("DDR_TUNE_CACHE_DIR", str(blocker / "sub"))
        assert cache.store_plan("k", {"engine": "gspmd"}) is None


class TestCalibrationRoundTrip:
    def test_store_then_load_per_platform(self, cache_dir):
        cache.store_calibration("tpu", {"wave_fixed_s": 3.1e-5})
        assert cache.load_calibration("tpu")["wave_fixed_s"] == 3.1e-5
        assert cache.load_calibration("cpu") is None

    def test_version_mismatch_invalidates(self, cache_dir):
        cache.store_calibration(
            "tpu", {"wave_fixed_s": 3.1e-5, "planner_version": cache.PLANNER_VERSION + 1}
        )
        assert cache.load_calibration("tpu") is None
