"""The cost-model engine planner (ddr_tpu/tuning/planner.py).

The load-bearing claims, each pinned here:

- score mode REPRODUCES every recorded MULTICHIP_r04 regime from synthetic
  ProgramCards (the cost model earns the policy table, it doesn't contradict
  it);
- ``DDR_AUTOTUNE=off`` is byte-identical to the hand policy and builds
  nothing;
- the decision ladder degrades memo -> persistent cache -> scoring -> policy,
  with the persistent hit card-build-free (the warm-replica contract);
- the physics card is AOT — scoring leaves every jit dispatch cache it
  creates EMPTY (what keeps serving warmup's compile set exactly its own);
- eligibility pruning mirrors the engines' own predicates (per-shard ring,
  kernel/dtype axes, HBM envelope).
"""

from __future__ import annotations

import json
from types import SimpleNamespace

import numpy as np
import pytest

from ddr_tpu.parallel.select import (
    select_engine_tuned,
    select_for_topology,
    select_parallel_engine,
)
from ddr_tpu.tuning import cache as tcache
from ddr_tpu.tuning import planner


def synthetic_card(n: int, t: int, peak_per_reach: float = 64.0):
    """A ProgramCard stand-in with the measured order of the route physics
    (a few hundred flops / ~hundred bytes per reach-step)."""
    return SimpleNamespace(
        flops=260.0 * n * t, bytes_accessed=120.0 * n * t, peak_bytes=peak_per_reach * n
    )


def _chain(depth: int):
    n = depth + 1
    return np.arange(1, n, dtype=np.int64), np.arange(0, n - 1, dtype=np.int64), n


@pytest.fixture()
def tune_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("DDR_TUNE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("DDR_COMPILE_CACHE_DIR", raising=False)
    monkeypatch.delenv("DDR_AUTOTUNE", raising=False)
    planner.reset_tune_memo()
    yield tmp_path
    planner.reset_tune_memo()


class TestMode:
    def test_default_is_score(self, monkeypatch):
        monkeypatch.delenv("DDR_AUTOTUNE", raising=False)
        assert planner.autotune_mode() == "score"

    def test_malformed_warns_to_score(self, monkeypatch, caplog):
        """A tuning knob must never abort a run."""
        monkeypatch.setenv("DDR_AUTOTUNE", "fastest")
        with caplog.at_level("WARNING"):
            assert planner.autotune_mode() == "score"
        assert "DDR_AUTOTUNE" in caplog.text


class TestRegimeParity:
    """THE acceptance claim: the default score mode reproduces the engine of
    every recorded MULTICHIP_r04 regime — same winner as the hand policy,
    reached from the cost model instead of the table."""

    REGIMES = [
        # (platform, n, depth, max_in, n_shards, t) — the recorded rows:
        # host-mesh scale row (gspmd 210ms vs wavefront 5060ms inversion)
        ("cpu", 8192, 120, 4, 8, 48),
        # accelerator shallow: T+depth waves beat the T*depth rectangle
        ("tpu", 65536, 200, 4, 8, 240),
        # continental depth: per-shard ring infeasible, bands take over
        ("tpu", 2_900_000, 4000, 4, 8, 240),
        # accelerator small-N sanity
        ("tpu", 8192, 30, 4, 2, 240),
    ]

    @pytest.mark.parametrize("platform,n,depth,max_in,shards,t", REGIMES)
    def test_score_reproduces_the_policy_regime(
        self, platform, n, depth, max_in, shards, t
    ):
        prior = select_parallel_engine(platform, n, depth, shards, max_in)
        cands = planner.score_candidates(
            platform=platform, n=n, depth=depth, max_in=max_in, n_shards=shards,
            t_steps=t, card=synthetic_card(n, t), card_t=t,
        )
        winner, _ = planner._pick(cands, prior)
        assert winner is not None and winner.engine == prior

    def test_continental_wavefront_pruned_not_outscored(self):
        """At depth 4000 the sharded wavefront must be INFEASIBLE (the
        per-shard ring bound), not merely slower — the same predicate the
        engine itself enforces."""
        cands = planner.score_candidates(
            platform="tpu", n=2_900_000, depth=4000, max_in=4, n_shards=8,
            t_steps=240, card=synthetic_card(2_900_000, 240), card_t=240,
        )
        wf = next(c for c in cands if c.engine == "sharded-wavefront")
        assert not wf.feasible
        assert "ring infeasible" in wf.reason

    def test_cardless_scoring_still_ranks_structurally(self):
        """No card (e.g. a build failure upstream) degrades to the structural
        terms alone — the wave counts still order the engines."""
        cands = planner.score_candidates(
            platform="cpu", n=8192, depth=120, max_in=4, n_shards=8, t_steps=48
        )
        assert cands[0].engine == "gspmd"
        assert all(c.est_s is not None for c in cands)


class TestPruning:
    @pytest.mark.parametrize("dtype,kernel", [("bf16", None), ("fp32", "pallas")])
    def test_axes_prune_shard_map_engines(self, dtype, kernel):
        """resolve_engine_axes raises for explicit pallas/bf16 on the shard_map
        engines; the planner must never nominate a candidate the router would
        refuse to run."""
        cands = planner.score_candidates(
            platform="tpu", n=65536, depth=200, max_in=4, n_shards=8, t_steps=240,
            card=synthetic_card(65536, 240), card_t=240, dtype=dtype, kernel=kernel,
        )
        by = {c.engine: c for c in cands}
        assert by["gspmd"].feasible
        assert not by["sharded-wavefront"].feasible
        assert not by["stacked-sharded"].feasible
        assert "gspmd" in by["sharded-wavefront"].reason

    def test_hbm_prunes_all_but_stacked(self):
        """A per-shard peak above 92% of HBM prunes the whole-network-resident
        engines; the banded engine is exempt by construction (the band budget
        is what bounds its residency)."""
        n, t = 65536, 240
        card = synthetic_card(n, t, peak_per_reach=1e6)  # ~7.6 GiB/shard at S=8
        cands = planner.score_candidates(
            platform="tpu", n=n, depth=200, max_in=4, n_shards=8, t_steps=t,
            card=card, card_t=t, hbm_bytes=4 * 2**30,
        )
        by = {c.engine: c for c in cands}
        assert not by["gspmd"].feasible and "HBM" in by["gspmd"].reason
        assert not by["sharded-wavefront"].feasible
        assert by["stacked-sharded"].feasible

    def test_no_hbm_limit_skips_the_prune(self):
        cands = planner.score_candidates(
            platform="tpu", n=65536, depth=200, max_in=4, n_shards=8, t_steps=240,
            card=synthetic_card(65536, 240, peak_per_reach=1e6), card_t=240,
            hbm_bytes=None,
        )
        assert all(c.feasible for c in cands)


class TestPriorMargin:
    def _cands(self, prior_s: float, challenger_s: float):
        return [
            planner.Candidate("sharded-wavefront", True, est_s=challenger_s),
            planner.Candidate("gspmd", True, est_s=prior_s),
        ]

    def test_near_tie_retains_the_prior(self):
        """A challenger inside PRIOR_MARGIN must not flap the fleet off the
        measured table on calibration noise."""
        winner, is_prior = planner._pick(self._cands(1.0, 0.99), "gspmd")
        assert winner.engine == "gspmd" and is_prior

    def test_decisive_challenger_overrides(self):
        winner, is_prior = planner._pick(self._cands(1.0, 0.9), "gspmd")
        assert winner.engine == "sharded-wavefront" and not is_prior

    def test_infeasible_prior_concedes(self):
        """When the policy's own pick is pruned, the best feasible candidate
        wins without a margin contest."""
        cands = [
            planner.Candidate("gspmd", False, est_s=0.1, reason="HBM"),
            planner.Candidate("stacked-sharded", True, est_s=5.0),
        ]
        winner, is_prior = planner._pick(cands, "gspmd")
        assert winner.engine == "stacked-sharded" and not is_prior

    def test_nothing_feasible_returns_none(self):
        winner, _ = planner._pick(
            [planner.Candidate("gspmd", False, est_s=1.0)], "gspmd"
        )
        assert winner is None


class TestOffModeParity:
    """DDR_AUTOTUNE=off must be byte-identical to the pre-planner behavior:
    the hand policy's pick, source 'policy', zero cards built."""

    GRID = [
        ("cpu", 40, 8),
        ("cpu", 2000, 8),
        ("tpu", 200, 8),
        ("tpu", 2000, 8),
        ("gpu", 60, 4),
    ]

    @pytest.mark.parametrize("platform,depth,shards", GRID)
    def test_off_matches_select_for_topology(
        self, platform, depth, shards, monkeypatch
    ):
        monkeypatch.setenv("DDR_AUTOTUNE", "off")
        rows, cols, n = _chain(depth)
        builds = planner.card_build_count()
        engine, source = select_engine_tuned(
            platform, rows, cols, n, shards, cache_key=f"off-{platform}-{depth}"
        )
        assert source == "policy"
        assert engine == select_for_topology(
            platform, rows, cols, n, shards, cache_key=f"off-{platform}-{depth}"
        )
        assert planner.card_build_count() == builds


class TestTuneEngineLadder:
    """memo -> persistent cache -> scoring -> policy, on a real (tiny) topology
    on the CPU backend."""

    def _query(self, tune_cache, depth=4, **kw):
        rows, cols, n = _chain(depth)
        args = dict(
            topo_sha=f"ladder-{depth}",
            mesh_desc={"axes": ["reach"], "shape": [1], "platform": "cpu",
                       "n_devices": 1},
            t_steps=6,
        )
        args.update(kw)
        return planner.tune_engine("cpu", rows, cols, n, depth, 1, 1, **args)

    def test_scored_then_memo_then_cached(self, tune_cache):
        builds = planner.card_build_count()
        res = self._query(tune_cache)
        assert res.source == "scored"
        assert res.engine == "gspmd"  # the cpu regime
        assert planner.card_build_count() == builds + 1
        assert res.candidates, "a scored decision carries its candidate table"

        # same process, same query: the in-process memo answers
        res2 = self._query(tune_cache)
        assert res2 is res
        assert planner.card_build_count() == builds + 1

        # "fresh process": memos cleared, the persistent cache answers with
        # zero new card builds — the warm-replica contract
        planner.reset_tune_memo()
        res3 = self._query(tune_cache)
        assert res3.source == "cached"
        assert res3.engine == res.engine
        assert planner.card_build_count() == builds + 1

    def test_persisted_record_is_complete(self, tune_cache):
        res = self._query(tune_cache)
        rec = json.loads((tune_cache / f"plan_{res.key}.json").read_text())
        for field in ("engine", "source", "topology", "mesh", "platform",
                      "dtype", "n", "depth", "n_shards", "candidates",
                      "planner_version"):
            assert field in rec, field
        assert rec["engine"] == res.engine

    def test_injected_card_skips_the_build(self, tune_cache):
        builds = planner.card_build_count()
        res = self._query(
            tune_cache, topo_sha="ladder-injected", card=synthetic_card(5, 6)
        )
        assert res.source == "scored"
        assert planner.card_build_count() == builds

    def test_scoring_failure_degrades_to_policy(self, tune_cache, monkeypatch):
        """Any scoring exception falls back to exactly the hand policy — the
        planner can misestimate, it can never error a run."""
        monkeypatch.setattr(
            planner, "score_candidates",
            lambda **kw: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        res = self._query(tune_cache, topo_sha="ladder-broken",
                          card=synthetic_card(5, 6))
        assert res.source == "policy"
        assert res.engine == "gspmd"

    def test_emits_one_tune_event(self, tune_cache, tmp_path, monkeypatch):
        from ddr_tpu.observability import events

        rec = events.Recorder(tmp_path / "events.jsonl")
        monkeypatch.setattr(events, "_ACTIVE", rec)
        self._query(tune_cache, topo_sha="ladder-evt", card=synthetic_card(5, 6))
        self._query(tune_cache, topo_sha="ladder-evt", card=synthetic_card(5, 6))
        rec.close()
        evts = [
            json.loads(ln)
            for ln in (tmp_path / "events.jsonl").read_text().splitlines()
        ]
        tunes = [e for e in evts if e.get("event") == "tune"]
        assert len(tunes) == 1, "memo hits must not re-emit"
        e = tunes[0]
        assert e["engine"] == "gspmd" and e["source"] == "scored"
        assert e["mode"] == "score" and e["platform"] == "cpu"
        assert e["candidates"] and all("engine" in c for c in e["candidates"])


class TestCardIsAOT:
    def test_scoring_leaves_every_jit_dispatch_cache_empty(
        self, tune_cache, monkeypatch
    ):
        """The physics card is built with lower().compile() — ahead-of-time —
        so the jit callables the planner wraps must end the build with EMPTY
        dispatch caches. This is what keeps serving warmup's compiled-program
        set exactly its own: a register_network that consults the planner
        adds no jit cache entries beyond the serving programs it warms."""
        import jax

        captured = []
        orig_jit = jax.jit

        def spy_jit(fn, *a, **kw):
            j = orig_jit(fn, *a, **kw)
            captured.append(j)
            return j

        monkeypatch.setattr(jax, "jit", spy_jit)
        rows, cols, n = _chain(3)
        planner._physics_card(rows, cols, n, 4, "fp32", "aot-probe")
        assert captured, "the card build wraps its analog in jax.jit"
        if not hasattr(captured[0], "_cache_size"):
            pytest.skip("this jax version exposes no _cache_size")
        assert all(int(j._cache_size()) == 0 for j in captured)


class TestCalibration:
    def test_stored_calibration_overrides_defaults(self, tune_cache):
        tcache.store_calibration("tpu", {"wave_fixed_s": 7e-5, "flops_per_s": 1e13})
        cal = planner.calibration("tpu")
        assert cal["wave_s"] == 7e-5
        assert cal["flops_per_s"] == 1e13
        # untouched constants keep their defaults
        assert cal["step_s"] == planner._CALIBRATION_DEFAULTS["tpu"]["step_s"]

    def test_wave_cost_constants_prefer_stored_calibration(self, tune_cache):
        """Satellite contract: routing.chunked.wave_cost_constants consults the
        calibration record before the committed v5e literals — and the env
        knobs still override everything."""
        from ddr_tpu.routing.chunked import wave_cost_constants

        tcache.store_calibration(
            "cpu",
            {"wave_fixed_s": 9e-5, "ring_bytes_per_s": 5e9,
             "ring_bw_inherited": False},
        )
        fixed, bw = wave_cost_constants()
        assert fixed == pytest.approx(9e-5)
        assert bw == pytest.approx(5e9)

    def test_inherited_ring_bw_is_not_applied(self, tune_cache):
        """A calibrate run whose comb residual was below noise records
        ring_bw_inherited — the prior bandwidth must survive."""
        from ddr_tpu.routing.chunked import wave_cost_constants

        _, prior_bw = wave_cost_constants()
        tcache.store_calibration(
            "cpu",
            {"wave_fixed_s": 9e-5, "ring_bytes_per_s": 1.0,
             "ring_bw_inherited": True},
        )
        fixed, bw = wave_cost_constants()
        assert fixed == pytest.approx(9e-5)
        assert bw == pytest.approx(prior_bw)

    def test_env_knobs_override_stored_calibration(self, tune_cache, monkeypatch):
        from ddr_tpu.routing.chunked import wave_cost_constants

        tcache.store_calibration(
            "cpu", {"wave_fixed_s": 9e-5, "ring_bytes_per_s": 5e9,
                    "ring_bw_inherited": False},
        )
        monkeypatch.setenv("DDR_WAVE_FIXED_US", "11")
        monkeypatch.setenv("DDR_WAVE_RING_GBPS", "123")
        fixed, bw = wave_cost_constants()
        assert fixed == pytest.approx(11e-6)
        assert bw == pytest.approx(123e9)


class TestSingleDeviceReport:
    def test_table_covers_the_schedule_space(self, tune_cache):
        cands = planner.tune_single_device(4096, 2000, 4, t_steps=48, platform="cpu")
        engines = {c.engine for c in cands}
        assert "step" in engines
        assert "wavefront" in engines
        assert any(e.startswith("stacked[") for e in engines)
        wf = next(c for c in cands if c.engine == "wavefront")
        assert not wf.feasible, "depth 2000 exceeds the single-ring bound"
        assert cands == sorted(
            cands, key=lambda c: (not c.feasible, c.est_s)
        )
