"""Solver parity vs SciPy float64 oracle + gradient checks.

Mirrors the reference's solver unit tests
(/root/reference/tests/routing/test_routing_utils.py:122-170): identity systems, known
triangular systems, and finite backward gradients — plus finite-difference VJP checks
the reference does not have.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp
from scipy.sparse.linalg import spsolve_triangular

from ddr_tpu.routing.network import build_network, compute_levels
from ddr_tpu.routing.solver import solve_lower_triangular, solve_transposed


@pytest.fixture(params=[None, False], ids=["auto", "rect"])
def schedule(request):
    """Run each solve test under the auto-selected (fused where eligible) and the
    forced rectangle-scan schedule — both are production paths (the rectangle one
    backs distributed execution and deep networks)."""
    return request.param


def _random_dag(rng, n, max_up=3):
    """Random topologically-ordered DAG: each node picks 0..max_up upstream nodes."""
    rows, cols = [], []
    for i in range(1, n):
        k = rng.integers(0, min(i, max_up) + 1)
        for j in rng.choice(i, size=k, replace=False):
            rows.append(i)
            cols.append(int(j))
    return np.array(rows, dtype=np.int64), np.array(cols, dtype=np.int64)


def _scipy_solve(rows, cols, n, c1, b):
    """Oracle: A = I - diag(c1) @ N solved in float64."""
    N = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
    A = sp.eye(n, format="csr") - sp.diags(c1.astype(np.float64)) @ N
    return spsolve_triangular(A.tocsr(), b.astype(np.float64), lower=True)


class TestLevels:
    def test_chain_levels(self, chain_coo):
        rows, cols = chain_coo(6)
        lv = compute_levels(rows, cols, 6)
        np.testing.assert_array_equal(lv, np.arange(6))

    def test_tree_levels(self, tree_coo):
        rows, cols, n = tree_coo(3)
        lv = compute_levels(rows, cols, n)
        assert lv.max() == 3
        assert (lv[:8] == 0).all()

    def test_cycle_raises(self):
        rows = np.array([1, 0])
        cols = np.array([0, 1])
        with pytest.raises(ValueError, match="cycle"):
            compute_levels(rows, cols, 2)

    def test_headwaters_only(self):
        net = build_network(np.zeros(0, np.int64), np.zeros(0, np.int64), 5)
        assert net.depth == 0
        x = solve_lower_triangular(net, jnp.ones(5), jnp.arange(5.0))
        np.testing.assert_allclose(np.asarray(x), np.arange(5.0))


class TestSolve:
    def test_identity_when_c1_zero(self, rng, schedule):
        rows, cols = _random_dag(rng, 50)
        net = build_network(rows, cols, 50, fused=schedule)
        b = jnp.asarray(rng.normal(size=50).astype(np.float32))
        x = solve_lower_triangular(net, jnp.zeros(50), b)
        np.testing.assert_allclose(np.asarray(x), np.asarray(b), rtol=1e-6)

    @pytest.mark.parametrize("n", [2, 17, 200])
    def test_chain_vs_scipy(self, chain_coo, rng, n, schedule):
        rows, cols = chain_coo(n)
        net = build_network(rows, cols, n, fused=schedule)
        c1 = rng.uniform(-0.9, 0.95, n).astype(np.float32)
        b = rng.uniform(0.1, 5.0, n).astype(np.float32)
        x = solve_lower_triangular(net, jnp.asarray(c1), jnp.asarray(b))
        ref = _scipy_solve(rows, cols, n, c1, b)
        np.testing.assert_allclose(np.asarray(x), ref, rtol=2e-5, atol=1e-5)

    def test_tree_vs_scipy(self, tree_coo, rng, schedule):
        rows, cols, n = tree_coo(4)
        net = build_network(rows, cols, n, fused=schedule)
        c1 = rng.uniform(0.0, 0.99, n).astype(np.float32)
        b = rng.uniform(0.1, 5.0, n).astype(np.float32)
        x = solve_lower_triangular(net, jnp.asarray(c1), jnp.asarray(b))
        ref = _scipy_solve(rows, cols, n, c1, b)
        np.testing.assert_allclose(np.asarray(x), ref, rtol=2e-5, atol=1e-5)

    def test_random_dag_vs_scipy(self, rng, schedule):
        n = 300
        rows, cols = _random_dag(rng, n)
        net = build_network(rows, cols, n, fused=schedule)
        c1 = rng.uniform(-0.5, 0.9, n).astype(np.float32)
        b = rng.uniform(0.1, 5.0, n).astype(np.float32)
        x = solve_lower_triangular(net, jnp.asarray(c1), jnp.asarray(b))
        ref = _scipy_solve(rows, cols, n, c1, b)
        np.testing.assert_allclose(np.asarray(x), ref, rtol=5e-5, atol=5e-5)

    def test_transposed_vs_scipy(self, rng, schedule):
        n = 120
        rows, cols = _random_dag(rng, n)
        net = build_network(rows, cols, n, fused=schedule)
        c1 = rng.uniform(-0.5, 0.9, n).astype(np.float32)
        g = rng.normal(size=n).astype(np.float32)
        y = solve_transposed(net, jnp.asarray(c1), jnp.asarray(g))
        N = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
        A = sp.eye(n, format="csr") - sp.diags(c1.astype(np.float64)) @ N
        ref = spsolve_triangular(A.T.tocsr(), g.astype(np.float64), lower=False)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=5e-5, atol=5e-5)

    def test_jit_compatible(self, rng, schedule):
        n = 64
        rows, cols = _random_dag(rng, n)
        net = build_network(rows, cols, n, fused=schedule)
        f = jax.jit(lambda c1, b: solve_lower_triangular(net, c1, b))
        c1 = jnp.asarray(rng.uniform(0, 0.9, n).astype(np.float32))
        b = jnp.asarray(rng.uniform(0.1, 5, n).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(f(c1, b)),
            np.asarray(solve_lower_triangular(net, c1, b)),
            rtol=1e-6,
        )


class TestGradients:
    def _setup(self, rng, schedule, n=60):
        rows, cols = _random_dag(rng, n)
        net = build_network(rows, cols, n, fused=schedule)
        c1 = jnp.asarray(rng.uniform(0.05, 0.9, n).astype(np.float32))
        b = jnp.asarray(rng.uniform(0.5, 5.0, n).astype(np.float32))
        w = jnp.asarray(rng.normal(size=n).astype(np.float32))
        return net, c1, b, w

    def test_grad_b_finite_difference(self, rng, schedule):
        net, c1, b, w = self._setup(rng, schedule)

        def loss(b_):
            return jnp.sum(w * solve_lower_triangular(net, c1, b_))

        g = jax.grad(loss)(b)
        eps = 1e-3
        for i in [0, 10, 30, 59]:
            bp = b.at[i].add(eps)
            bm = b.at[i].add(-eps)
            fd = (loss(bp) - loss(bm)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(g[i]), np.asarray(fd), rtol=5e-2, atol=1e-3)

    def test_grad_c1_finite_difference(self, rng, schedule):
        net, c1, b, w = self._setup(rng, schedule)

        def loss(c1_):
            return jnp.sum(w * solve_lower_triangular(net, c1_, b))

        g = jax.grad(loss)(c1)
        eps = 1e-3
        for i in [0, 10, 30, 59]:
            cp = c1.at[i].add(eps)
            cm = c1.at[i].add(-eps)
            fd = (loss(cp) - loss(cm)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(g[i]), np.asarray(fd), rtol=5e-2, atol=1e-3)

    def test_grads_flow_through_jit(self, rng, schedule):
        net, c1, b, w = self._setup(rng, schedule)
        g = jax.jit(jax.grad(lambda c: jnp.sum(solve_lower_triangular(net, c, b))))(c1)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0


class TestScheduleEquivalence:
    """The fused (scatter-free permuted) and rectangle scan schedules are two
    lowerings of the same solve; they must agree in values and gradients."""

    def _nets(self, rng, n=120):
        # Dendritic chain-with-confluences: in/out degrees within fused limits.
        rows = np.array([int(rng.integers(i + 1, min(n, i + 40))) for i in range(n - 1)])
        cols = np.arange(n - 1, dtype=np.int64)
        nf = build_network(rows, cols, n, fused=True)
        nr = build_network(rows, cols, n, fused=False)
        assert nf.fused and not nr.fused
        return nf, nr

    def test_solve_agrees(self, rng):
        nf, nr = self._nets(rng)
        n = nf.n
        c1 = jnp.asarray(rng.uniform(-0.5, 0.9, n).astype(np.float32))
        b = jnp.asarray(rng.uniform(0.1, 5.0, n).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(solve_lower_triangular(nf, c1, b)),
            np.asarray(solve_lower_triangular(nr, c1, b)),
            rtol=1e-5, atol=1e-5,
        )

    def test_transposed_agrees(self, rng):
        nf, nr = self._nets(rng)
        n = nf.n
        c1 = jnp.asarray(rng.uniform(-0.5, 0.9, n).astype(np.float32))
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(solve_transposed(nf, c1, g)),
            np.asarray(solve_transposed(nr, c1, g)),
            rtol=1e-5, atol=1e-5,
        )

    def test_gradients_agree(self, rng):
        nf, nr = self._nets(rng)
        n = nf.n
        c1 = jnp.asarray(rng.uniform(-0.5, 0.9, n).astype(np.float32))
        b = jnp.asarray(rng.uniform(0.5, 2.0, n).astype(np.float32))
        w = jnp.asarray(rng.normal(size=n).astype(np.float32))

        def loss(net):
            return lambda c, bb: jnp.sum(w * solve_lower_triangular(net, c, bb))

        gf = jax.grad(loss(nf), argnums=(0, 1))(c1, b)
        gr = jax.grad(loss(nr), argnums=(0, 1))(c1, b)
        np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gr[0]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gr[1]), rtol=1e-4, atol=1e-5)

    def test_fused_ineligible_raises(self, rng):
        # Out-degree beyond the fused limit must refuse fused=True explicitly.
        n = 20
        rows = np.arange(1, n, dtype=np.int64)
        cols = np.zeros(n - 1, dtype=np.int64)  # node 0 feeds everyone
        with pytest.raises(ValueError, match="fused-schedule limits"):
            build_network(rows, cols, n, fused=True)
        net = build_network(rows, cols, n)  # auto falls back
        assert not net.fused


class TestChunkedSchedule:
    """level_schedule caps the padded rectangle at O(n_edges) by splitting
    oversized levels into chunk rows (safe: within-level edges are independent)."""

    def _skewed(self, n_chain=100, n_wide=5000):
        # n_wide headwaters (ids 0..n_wide-1) all drain into the chain head
        # (id n_wide), then a chain of n_chain further reaches: one level is
        # ~50x wider than every other. Ids are topologically ordered
        # (downstream > upstream), as the binsparse stores guarantee.
        head = n_wide
        n = n_wide + n_chain + 1
        rows = [head] * n_wide + list(range(head + 1, n))
        cols = list(range(n_wide)) + list(range(head, n - 1))
        return np.asarray(rows), np.asarray(cols), n

    def test_rectangle_is_capped(self):
        from ddr_tpu.routing.network import level_schedule

        rows, cols, n = self._skewed()
        lvl_src, lvl_tgt, depth = level_schedule(rows, cols, n)
        assert lvl_src.shape[1] <= 1024
        assert lvl_src.shape[0] > depth  # chunk rows added
        # Every real edge appears exactly once.
        real = lvl_tgt[lvl_tgt < n]
        assert real.size == len(rows)

    def test_chunked_solve_matches_scipy(self, rng):
        import scipy.sparse as sp
        from scipy.sparse.linalg import spsolve_triangular

        from ddr_tpu.routing.network import build_network
        from ddr_tpu.routing.solver import solve_lower_triangular, solve_transposed

        rows, cols, n = self._skewed(n_chain=60, n_wide=3000)
        net = build_network(rows, cols, n, fused=False)
        assert net.lvl_src.shape[0] > net.depth  # chunking active
        c1 = jnp.asarray(rng.uniform(0.05, 0.9, n), jnp.float32)
        b = jnp.asarray(rng.uniform(0.1, 2.0, n), jnp.float32)
        A = sp.eye(n) - sp.diags(np.asarray(c1, np.float64)) @ sp.coo_matrix(
            (np.ones(len(rows)), (rows, cols)), shape=(n, n)
        )
        want = spsolve_triangular(A.tocsr().astype(np.float64), np.asarray(b, np.float64), lower=True)
        got = solve_lower_triangular(net, c1, b)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=1e-5)
        # Transposed sweep (the backward path) under chunking:
        want_t = spsolve_triangular(
            A.tocsr().T.tocsr().astype(np.float64), np.asarray(b, np.float64), lower=False
        )
        got_t = solve_transposed(net, c1, b)
        np.testing.assert_allclose(np.asarray(got_t), want_t, rtol=2e-4, atol=1e-5)

    def test_chunked_gradients_finite_difference(self, rng):
        from ddr_tpu.routing.network import build_network
        from ddr_tpu.routing.solver import solve_lower_triangular

        rows, cols, n = self._skewed(n_chain=20, n_wide=1500)
        net = build_network(rows, cols, n, fused=False)
        c1 = jnp.asarray(rng.uniform(0.1, 0.8, n), jnp.float32)
        b = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)

        def loss(c):
            return jnp.sum(solve_lower_triangular(net, c, b) ** 2)

        g = jax.grad(loss)(c1)
        # Headwater c1 values are never used (a headwater is no edge's target).
        assert np.asarray(g[0]) == 0.0
        # The confluence head concentrates the signal, so the finite difference
        # stays well above float32 resolution of the million-scale loss.
        head = n - 21  # n_chain=20 chain reaches after the confluence
        eps = 1e-3
        e = jnp.zeros(n).at[head].set(eps)
        fd = (loss(c1 + e) - loss(c1 - e)) / (2 * eps)
        assert np.asarray(g[head]) == pytest.approx(float(fd), rel=0.01)

    def test_rectangle_bounded_at_scale(self):
        """The padded schedule stays O(E + 1024*depth) on a 200k-reach graph with
        skewed level widths (regression: this build previously allocated
        depth x e_max — e_max set by the single widest level — and took
        >10 minutes at 131k reaches)."""
        from ddr_tpu.routing.network import build_network

        n = 200_000
        rng = np.random.default_rng(0)
        cols = np.arange(n - 1)
        rows = np.minimum(cols + rng.integers(1, 64, size=n - 1), n - 1)
        net = build_network(rows, cols, n)
        rows_n, width = net.lvl_src.shape
        assert width <= 1024
        # Chunk rows beyond the topological depth are bounded by E / width.
        assert rows_n <= net.depth + (n - 1) // width + 1

    def test_pipeline_shards_share_cap(self):
        """Stacked per-shard schedules chunk against one shared width: a
        wide-flat shard must not dictate an unchunked e_max that multiplies
        against a deep shard's row count."""
        from ddr_tpu.parallel.pipeline import build_pipeline_schedule

        # shard 0 (ids 0..4095): 4000 headwaters into one confluence (wide, flat)
        # shard 1 (ids 4096..8191): one long chain (deep, thin)
        half = 4096
        rows = [4000] * 4000 + list(range(half + 1, 2 * half))
        cols = list(range(4000)) + list(range(half, 2 * half - 1))
        sched = build_pipeline_schedule(
            np.asarray(rows), np.asarray(cols), 2 * half, n_shards=2
        )
        s, d, e = sched.lvl_src.shape
        assert s == 2
        assert e <= 1024  # the 4000-wide level was chunked, not taken whole
        assert d <= half + 4000 // e + 1
