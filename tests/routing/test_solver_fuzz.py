"""Property-based solver fuzzing: random DAG topologies x random coefficients
against the scipy float64 oracle, both solve schedules, values and gradients.

Complements the fixed-topology suites (test_solver.py) the way the reference's
randomized MockRoutingDataclass scenarios do
(/root/reference/tests/routing/test_utils.py:75-120), but with
hypothesis-driven topology search and shrinking."""

from __future__ import annotations

import pytest

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from hypothesis import given, settings
from hypothesis import strategies as st
from scipy.sparse.linalg import spsolve_triangular

from ddr_tpu.routing.network import build_network
from ddr_tpu.routing.solver import solve_lower_triangular, solve_transposed

pytestmark = pytest.mark.slow

@st.composite
def dag_cases(draw):
    """A topologically-ordered random DAG + coefficients/forcings."""
    n = draw(st.integers(min_value=1, max_value=28))
    edges = []
    for i in range(1, n):
        k = draw(st.integers(min_value=0, max_value=min(i, 3)))
        ups = draw(
            st.lists(
                st.integers(min_value=0, max_value=i - 1),
                min_size=k, max_size=k, unique=True,
            )
        )
        edges.extend((i, u) for u in ups)
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    rng = np.random.default_rng(seed)
    c1 = rng.uniform(-0.9, 0.95, n).astype(np.float32)
    b = rng.uniform(-2.0, 5.0, n).astype(np.float32)
    return n, edges, c1, b


def _oracle(rows, cols, n, c1, b, transposed=False):
    N = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
    A = sp.eye(n, format="csr") - sp.diags(c1.astype(np.float64)) @ N
    if transposed:
        return spsolve_triangular(A.T.tocsr(), b.astype(np.float64), lower=False)
    return spsolve_triangular(A.tocsr(), b.astype(np.float64), lower=True)


@settings(max_examples=40, deadline=None)
@given(dag_cases())
def test_solve_matches_scipy_on_random_dags(case):
    n, edges, c1, b = case
    rows = np.array([e[0] for e in edges], dtype=np.int64)
    cols = np.array([e[1] for e in edges], dtype=np.int64)
    for fused in (None, False):
        net = build_network(rows, cols, n, fused=fused)
        x = np.asarray(solve_lower_triangular(net, jnp.asarray(c1), jnp.asarray(b)))
        want = _oracle(rows, cols, n, c1, b)
        np.testing.assert_allclose(x, want, rtol=5e-4, atol=5e-4)
        y = np.asarray(solve_transposed(net, jnp.asarray(c1), jnp.asarray(b)))
        want_t = _oracle(rows, cols, n, c1, b, transposed=True)
        np.testing.assert_allclose(y, want_t, rtol=5e-4, atol=5e-4)


@settings(max_examples=15, deadline=None)
@given(dag_cases())
def test_vjp_matches_oracle_identity(case):
    """A^T grad_b = grad_x: the custom VJP's grad_b must satisfy the transposed
    system (checked against the scipy transposed solve), and grad_c1 must equal
    grad_b * (N @ x) — the implicit-function backward identities."""
    n, edges, c1, b = case
    rows = np.array([e[0] for e in edges], dtype=np.int64)
    cols = np.array([e[1] for e in edges], dtype=np.int64)
    net = build_network(rows, cols, n)
    seed_w = np.random.default_rng(1).normal(size=n).astype(np.float32)

    def loss(c, bb):
        return jnp.sum(jnp.asarray(seed_w) * solve_lower_triangular(net, c, bb))

    gc, gb = jax.grad(loss, argnums=(0, 1))(jnp.asarray(c1), jnp.asarray(b))
    want_gb = _oracle(rows, cols, n, c1, seed_w, transposed=True)
    np.testing.assert_allclose(np.asarray(gb), want_gb, rtol=5e-4, atol=5e-4)

    x = _oracle(rows, cols, n, c1, b)
    N = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
    want_gc = want_gb * (N @ x)
    np.testing.assert_allclose(np.asarray(gc), want_gc, rtol=5e-4, atol=5e-4)
