"""Muskingum-Cunge engine tests vs a NumPy float64 oracle.

The oracle re-implements the documented physics equations
(/root/reference/src/ddr/routing/mmc.py:460-485,487-559 and
/root/reference/src/ddr/geometry/trapezoidal.py:62-108) directly in float64 NumPy,
mirroring the reference test strategy of CPU-oracle parity
(/root/reference/tests/routing/test_mmc.py:38-200).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp
from scipy.sparse.linalg import spsolve_triangular

from ddr_tpu.routing.mc import (
    Bounds,
    ChannelState,
    GaugeIndex,
    denormalize,
    hotstart_discharge,
    muskingum_coefficients,
    route,
)
from ddr_tpu.routing.network import build_network

DT = 3600.0


def _mock_net(rng, n=24):
    """Random dendritic (single-downstream) network: node i drains to one node > i."""
    rows, cols = [], []
    for i in range(n - 1):
        tgt = int(rng.integers(i + 1, n))
        rows.append(tgt)
        cols.append(i)
    return np.asarray(rows), np.asarray(cols)


def _mock_channels(rng, n):
    return dict(
        length=rng.uniform(500, 5000, n),
        slope=np.clip(rng.uniform(1e-4, 0.02, n), 1e-4, None),
        x=np.full(n, 0.3),
        n_mann=rng.uniform(0.02, 0.2, n),
        q_spatial=rng.uniform(0.1, 0.9, n),
        p_spatial=np.full(n, 21.0),
    )


def _oracle_route(rows, cols, n, ch, q_prime, bounds, T):
    """Float64 reference implementation of the documented MC loop."""
    N = sp.coo_matrix((np.ones(len(rows)), (rows, cols)), shape=(n, n)).tocsr()
    eye = sp.eye(n, format="csr")

    def solve(c1, b):
        A = eye - sp.diags(c1) @ N
        return spsolve_triangular(A.tocsr(), b, lower=True)

    def geometry_velocity(q):
        qe = ch["q_spatial"] + 1e-6
        num = q * ch["n_mann"] * (qe + 1)
        den = ch["p_spatial"] * np.sqrt(ch["slope"])
        depth = np.maximum((num / (den + 1e-8)) ** (3.0 / (5.0 + 3.0 * qe)), bounds.depth)
        tw = ch["p_spatial"] * depth**qe
        ss = np.clip(tw * qe / (2 * depth), 0.5, 50.0)
        bw = np.maximum(tw - 2 * ss * depth, bounds.bottom_width)
        area = (tw + bw) * depth / 2
        wp = bw + 2 * depth * np.sqrt(1 + ss**2)
        v = (1 / ch["n_mann"]) * (area / wp) ** (2 / 3) * np.sqrt(ch["slope"])
        return np.clip(v, bounds.velocity, 15.0) * 5 / 3

    q0 = np.maximum(solve(np.ones(n), np.maximum(q_prime[0], 0.0)), bounds.discharge)
    out = np.zeros((T, n))
    out[0] = q0
    q_t = q0
    for t in range(1, T):
        c = geometry_velocity(q_t)
        k = ch["length"] / c
        denom = 2 * k * (1 - ch["x"]) + DT
        c1 = (DT - 2 * k * ch["x"]) / denom
        c2 = (DT + 2 * k * ch["x"]) / denom
        c3 = (2 * k * (1 - ch["x"]) - DT) / denom
        c4 = 2 * DT / denom
        qp = np.maximum(q_prime[t - 1], bounds.discharge)
        b = c2 * (N @ q_t) + c3 * q_t + c4 * qp
        q_t = np.maximum(solve(c1, b), bounds.discharge)
        out[t] = q_t
    return out


@pytest.fixture
def setup(rng):
    n = 24
    rows, cols = _mock_net(rng, n)
    net = build_network(rows, cols, n)
    ch = _mock_channels(rng, n)
    T = 48
    q_prime = rng.uniform(0.01, 2.0, (T, n))
    channels = ChannelState(
        length=jnp.asarray(ch["length"], jnp.float32),
        slope=jnp.asarray(ch["slope"], jnp.float32),
        x_storage=jnp.asarray(ch["x"], jnp.float32),
    )
    params = {
        "n": jnp.asarray(ch["n_mann"], jnp.float32),
        "q_spatial": jnp.asarray(ch["q_spatial"], jnp.float32),
        "p_spatial": jnp.asarray(ch["p_spatial"], jnp.float32),
    }
    return n, rows, cols, net, ch, channels, params, q_prime, T


class TestRouteParity:
    def test_full_domain_vs_oracle(self, setup):
        n, rows, cols, net, ch, channels, params, q_prime, T = setup
        bounds = Bounds()
        res = route(net, channels, params, jnp.asarray(q_prime, jnp.float32), bounds=bounds)
        oracle = _oracle_route(rows, cols, n, ch, q_prime, bounds, T)
        np.testing.assert_allclose(np.asarray(res.runoff), oracle, rtol=2e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(res.final_discharge), oracle[-1], rtol=2e-3, atol=1e-4)

    def test_gauge_aggregation(self, setup):
        n, rows, cols, net, ch, channels, params, q_prime, T = setup
        gauges = GaugeIndex.from_ragged([np.array([0, 3]), np.array([5])])
        res = route(net, channels, params, jnp.asarray(q_prime, jnp.float32), gauges=gauges)
        full = route(net, channels, params, jnp.asarray(q_prime, jnp.float32))
        np.testing.assert_allclose(
            np.asarray(res.runoff[:, 0]),
            np.asarray(full.runoff[:, 0] + full.runoff[:, 3]),
            rtol=1e-5,
        )
        np.testing.assert_allclose(np.asarray(res.runoff[:, 1]), np.asarray(full.runoff[:, 5]), rtol=1e-5)

    def test_carry_state_continuity(self, setup):
        """Sequential chunks with carried state == one long route
        (/root/reference/src/ddr/routing/mmc.py:330-342 semantics)."""
        n, rows, cols, net, ch, channels, params, q_prime, T = setup
        qp = jnp.asarray(q_prime, jnp.float32)
        full = route(net, channels, params, qp)
        half = T // 2
        r1 = route(net, channels, params, qp[:half])
        # Chunk 2 starts from chunk 1's final state; its q_prime window must overlap by
        # one step, mirroring the reference collate's day-1 prepend for continuity.
        r2 = route(net, channels, params, qp[half - 1 :], q_init=r1.final_discharge)
        np.testing.assert_allclose(
            np.asarray(r2.runoff[1:]), np.asarray(full.runoff[half:]), rtol=1e-4, atol=1e-5
        )

    def test_hotstart_headwater_equals_local_inflow(self, setup):
        n, rows, cols, net, ch, channels, params, q_prime, T = setup
        q0 = hotstart_discharge(net, jnp.asarray(q_prime[0], jnp.float32), 1e-4)
        headwaters = np.setdiff1d(np.arange(n), np.asarray(rows))
        np.testing.assert_allclose(
            np.asarray(q0)[headwaters], q_prime[0][headwaters].astype(np.float32), rtol=1e-6
        )
        # Everywhere: accumulated >= local inflow (mmc.py:38-122 invariant).
        assert (np.asarray(q0) >= q_prime[0].astype(np.float32) - 1e-6).all()

    def test_jit_and_grad(self, setup):
        n, rows, cols, net, ch, channels, params, q_prime, T = setup
        qp = jnp.asarray(q_prime, jnp.float32)

        @jax.jit
        def loss(p):
            res = route(net, channels, p, qp)
            return jnp.mean(res.runoff)

        g = jax.grad(loss)(params)
        for k in ("n", "q_spatial"):
            arr = np.asarray(g[k])
            assert np.isfinite(arr).all()
            assert np.abs(arr).sum() > 0, f"no gradient signal for {k}"


class TestPieces:
    def test_muskingum_coefficients_sum(self, rng):
        """c1 + c2 + c3 == 1 identically (mass-consistency of the MC scheme)."""
        length = jnp.asarray(rng.uniform(100, 10000, 50), jnp.float32)
        vel = jnp.asarray(rng.uniform(0.3, 15, 50), jnp.float32)
        x = jnp.full(50, 0.3)
        c1, c2, c3, c4 = muskingum_coefficients(length, vel, x)
        np.testing.assert_allclose(np.asarray(c1 + c2 + c3), np.ones(50), rtol=1e-5)

    def test_denormalize_linear_and_log(self):
        v = jnp.array([0.0, 0.5, 1.0])
        lin = denormalize(v, (0.015, 0.25))
        np.testing.assert_allclose(np.asarray(lin), [0.015, 0.1325, 0.25], rtol=1e-6)
        logd = denormalize(v, (1.0, 200.0), log_space=True)
        np.testing.assert_allclose(np.asarray(logd[0]), 1.0, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(logd[2]), 200.0, rtol=1e-3)
        assert np.asarray(logd[1]) == pytest.approx(np.sqrt(200.0), rel=1e-2)

    def test_data_override_blend(self, setup):
        n, rows, cols, net, ch, channels, params, q_prime, T = setup
        tw_data = np.full(n, np.nan)
        tw_data[::2] = 42.0
        ch2 = ChannelState(
            length=channels.length,
            slope=channels.slope,
            x_storage=channels.x_storage,
            top_width_data=jnp.asarray(tw_data, jnp.float32),
        )
        from ddr_tpu.routing.mc import celerity

        c_a, tw, ss = celerity(
            jnp.ones(n), params["n"], params["p_spatial"], params["q_spatial"], ch2, Bounds()
        )
        assert (np.asarray(tw)[::2] == 42.0).all()
        assert np.isfinite(np.asarray(tw)[1::2]).all()
        assert not (np.asarray(tw)[1::2] == 42.0).any()
