"""Spatial health attribution: per-band equivalence across engines.

The band reductions (``route(collect_health=True, health_bands=B)``) must be
an ENGINE-INDEPENDENT property of the topology + inputs: the step engine's
scan-carry accumulators, the single-ring wavefront's wf-order reductions, and
the chunked/stacked engines' band-concat reductions all attribute to the SAME
level bands (``ddr_tpu.routing.mc.band_ids``) and must agree to float
associativity — on randomized DAGs, with gauges (the scan-carry path) and
without, under kernel=pallas|xla, and in bf16 (overflow/ulp-drift band
counters). Plus the PR contract: band health adds ZERO new jit-cache entries
to a train step (the knobs are build-time statics of the one program).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.routing.mc import GaugeIndex, band_ids, route
from ddr_tpu.routing.network import build_network
from tests.routing.test_adjoint import _build, _random_dag, _random_inputs

ENGINES = ("wavefront", "chunked", "stacked")


def _spatial_health(network, channels, params, q_prime, **kw):
    r = route(
        network, channels, params, q_prime,
        collect_health=True, health_bands=4, health_topk=5, **kw,
    )
    assert r.reach_stats is None, "route must strip the ReachStats intermediate"
    return r.health


def _assert_band_equal(a, b, label):
    np.testing.assert_array_equal(
        np.asarray(a.band_nonfinite), np.asarray(b.band_nonfinite), err_msg=label
    )
    for field in ("band_residual", "band_q_min", "band_q_max"):
        x, y = np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        scale = max(np.max(np.abs(x)), 1e-8)
        np.testing.assert_allclose(
            x, y, rtol=1e-5, atol=1e-5 * scale, err_msg=f"{label}: {field}"
        )
    np.testing.assert_array_equal(
        np.asarray(a.worst_idx), np.asarray(b.worst_idx), err_msg=f"{label}: worst"
    )


class TestBandEquivalenceAcrossEngines:
    @pytest.mark.parametrize("seed", (0, 1))
    def test_all_engines_agree_full_domain(self, seed):
        rng = np.random.default_rng(seed)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        ref = None
        for engine in ENGINES:
            net = _build(engine, rows, cols, n)
            h = _spatial_health(net, channels, params, q_prime)
            if ref is None:
                ref = h
            else:
                _assert_band_equal(h, ref, engine)
        # the step engine attributes to the same bands
        net = build_network(rows, cols, n)
        h = _spatial_health(net, channels, params, q_prime, engine="step")
        _assert_band_equal(h, ref, "step")

    def test_step_gauge_carry_path_matches(self):
        """With gauges, the step engine's per-reach stats ride the scan carry
        — they must equal the wavefront engine's materialized reductions."""
        rng = np.random.default_rng(2)
        n, t = 40, 6
        rows, cols = _random_dag(rng, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        gauges = GaugeIndex.from_ragged(
            [np.array([n - 1]), np.array([n - 2, n - 3])]
        )
        net = build_network(rows, cols, n)
        h_wf = _spatial_health(net, channels, params, q_prime, gauges=gauges)
        h_step = _spatial_health(
            net, channels, params, q_prime, gauges=gauges, engine="step"
        )
        _assert_band_equal(h_step, h_wf, "step+gauges vs wavefront+gauges")

    @pytest.mark.parametrize("engine", ("wavefront", "stacked"))
    def test_pallas_matches_xla(self, engine):
        rng = np.random.default_rng(3)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        net = _build(engine, rows, cols, n)
        h_x = _spatial_health(
            net, channels, params, q_prime, kernel="xla", adjoint="analytic"
        )
        h_p = _spatial_health(
            net, channels, params, q_prime, kernel="pallas", adjoint="analytic"
        )
        _assert_band_equal(h_p, h_x, f"{engine}: pallas vs xla")

    @pytest.mark.parametrize("engine", ("wavefront", "stacked"))
    def test_bf16_band_counters(self, engine):
        rng = np.random.default_rng(4)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        net = _build(engine, rows, cols, n)
        h = _spatial_health(net, channels, params, q_prime, dtype="bf16")
        assert h.band_overflow is not None and h.band_ulp_drift is not None
        assert np.asarray(h.band_overflow).sum() == 0  # healthy inputs
        assert np.all(np.isfinite(np.asarray(h.band_ulp_drift)))
        # fp32 leaves the mixed-precision band fields empty
        h32 = _spatial_health(net, channels, params, q_prime)
        assert h32.band_overflow is None and h32.band_ulp_drift is None


class TestLocalization:
    def test_nan_injection_localizes(self):
        rng = np.random.default_rng(5)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        net = build_network(rows, cols, n)
        bad = 17
        qp = np.asarray(q_prime).copy()
        qp[:, bad] = np.nan
        h = _spatial_health(net, channels, params, jnp.asarray(qp))
        ids, nb = band_ids(net.level, net.depth, 4)
        bad_band = int(np.asarray(ids)[bad])
        band_nf = np.asarray(h.band_nonfinite)
        assert band_nf[bad_band] > 0
        assert bad in np.asarray(h.worst_idx)
        # global stats see the non-finites too (per-reach view)
        assert int(h.nonfinite) > 0

    def test_band_ids_partition(self):
        level = jnp.asarray(np.arange(11), jnp.int32)
        ids, nb = band_ids(level, 10, 4)
        ids = np.asarray(ids)
        assert nb == 4
        assert ids.min() == 0 and ids.max() == nb - 1
        assert np.all(np.diff(ids) >= 0)  # monotone in level
        # more bands than levels: one band per level
        ids2, nb2 = band_ids(level, 10, 64)
        assert nb2 == 11
        np.testing.assert_array_equal(np.asarray(ids2), np.arange(11))


class TestNoNewJitCacheEntries:
    def test_train_step_band_health_single_program(self):
        """The e2e pin: a batch train step built with band health compiles
        ONCE and repeat batches (same topology) hit the cache — spatial
        attribution changes what the program computes, never how many
        programs there are."""
        import optax

        from ddr_tpu.routing.mc import Bounds, ChannelState
        from ddr_tpu.training import make_batch_train_step

        rng = np.random.default_rng(6)
        n, t = 32, 48
        rows, cols = _random_dag(rng, n)
        net = build_network(rows, cols, n)
        channels = ChannelState(
            length=jnp.asarray(rng.uniform(500, 5000, n), jnp.float32),
            slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
            x_storage=jnp.asarray(rng.uniform(0.1, 0.4, n), jnp.float32),
        )
        gauges = GaugeIndex.from_ragged([np.array([n - 1])])

        import flax.linen as nn

        class TinyKan(nn.Module):
            @nn.compact
            def __call__(self, x):
                out = jax.nn.sigmoid(nn.Dense(2)(x))
                return {"n": out[:, 0], "q_spatial": out[:, 1]}

        kan = TinyKan()
        attrs = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
        params = kan.init(jax.random.PRNGKey(0), attrs)
        optimizer = optax.adam(1e-3)
        opt_state = optimizer.init(params)
        step = make_batch_train_step(
            kan,
            Bounds(),
            {"n": [0.01, 0.3], "q_spatial": [0.0, 1.0]},
            [],
            {"p_spatial": 21.0},
            tau=3,
            warmup=0,
            optimizer=optimizer,
            collect_health=True,
            health_bands=4,
            health_topk=5,
            donate=False,
        )
        q_prime = jnp.asarray(rng.uniform(0.1, 2.0, (t, n)), jnp.float32)
        days = t // 24
        obs = jnp.asarray(rng.uniform(0.5, 2.0, (days - 2 + 1, 1)), jnp.float32)
        mask = jnp.ones_like(obs, bool)
        out = step(params, opt_state, net, channels, gauges, attrs, q_prime, obs, mask)
        assert step._cache_size() == 1
        assert out[4].band_residual is not None
        out = step(params, opt_state, net, channels, gauges, attrs, q_prime, obs, mask)
        assert step._cache_size() == 1, "band health re-traced a repeat batch"
        assert np.asarray(out[4].worst_idx).shape == (5,)
