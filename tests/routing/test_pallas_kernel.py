"""Fused Pallas wavefront kernel vs the XLA scan: forward + analytic-VJP
equivalence on randomized DAGs.

The ``kernel="pallas"`` axis must be a pure implementation change: identical
raw solve values (the interpret-mode kernel executes the same op sequence as
the ``lax.scan`` body, so fp32 forwards agree exactly — to solver tolerance
in the asserts below), gradients matching the XLA analytic adjoint to float
associativity, across all three wavefront engines (single-ring, depth-chunked
bands, stacked band-scan), both state paths (in-band hotstart and carried
``q_init``), with clamp-active inputs (zero inflows drive raw values below
the discharge bound) and the T=1 degenerate window.

bf16 (``dtype="bf16"``): pallas and xla implement the same
bf16-ring/fp32-accumulate scheme, so they agree exactly with EACH OTHER; vs
the fp32 ring the documented bound is bf16's ~3 significant digits compounded
along the longest path — asserted as max relative error <= 0.3 and mean
relative error <= 0.02 on these shapes (measured ~0.11 max / ~0.002 mean).

Runs entirely on CPU: ``kernel="pallas"`` off-TPU executes the REAL kernel
body under ``pl.pallas_call(interpret=True)`` (the tier-1 contract —
docs/tpu.md "Fused Pallas kernel & mixed precision").
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.routing.mc import route
from tests.routing.test_adjoint import (
    _build,
    _random_dag,
    _random_inputs,
)

ENGINES = ("wavefront", "chunked", "stacked")


def _loss(network, channels, w, wf, kernel, dtype, q_init):
    def loss(params, q_prime, length):
        ch = dataclasses.replace(channels, length=length)
        res = route(
            network, ch, params, q_prime, q_init=q_init,
            adjoint="analytic", kernel=kernel, dtype=dtype,
        )
        return (res.runoff * w).sum() + (res.final_discharge * wf).sum()

    return loss


def _forward(network, channels, params, q_prime, kernel, dtype, q_init=None):
    return route(
        network, channels, params, q_prime, q_init=q_init,
        adjoint="analytic", kernel=kernel, dtype=dtype,
    )


def _assert_close(a, b, label, rtol=1e-5, atol_scale=1e-5):
    a, b = np.asarray(a), np.asarray(b)
    scale = max(np.max(np.abs(a)), np.max(np.abs(b)), 1e-8)
    np.testing.assert_allclose(
        a, b, rtol=rtol, atol=atol_scale * scale, err_msg=label
    )


class TestPallasMatchesXla:
    @pytest.mark.parametrize("engine", ENGINES)
    @pytest.mark.parametrize("init_path", ("hotstart", "q_init"))
    def test_forward_and_vjp_match(self, engine, init_path):
        # deterministic per-case seed (hash() is salted per process)
        seed = sum(ord(c) for c in f"pallas/{engine}/{init_path}")
        rng = np.random.default_rng(seed)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        network = _build(engine, rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
        q_init = (
            None if init_path == "hotstart"
            else jnp.asarray(rng.uniform(0.0, 3.0, n), jnp.float32)
        )

        r_x = _forward(network, channels, params, q_prime, "xla", "fp32", q_init)
        r_p = _forward(network, channels, params, q_prime, "pallas", "fp32", q_init)
        # fp32: the interpreted kernel replays the scan body op for op —
        # exact to solver tolerance
        _assert_close(r_x.runoff, r_p.runoff, f"{engine}/{init_path}: forward",
                      rtol=1e-6, atol_scale=1e-7)
        _assert_close(r_x.final_discharge, r_p.final_discharge,
                      f"{engine}/{init_path}: final", rtol=1e-6, atol_scale=1e-7)

        g_x = jax.grad(_loss(network, channels, w, wf, "xla", "fp32", q_init),
                       argnums=(0, 1, 2))(params, q_prime, channels.length)
        g_p = jax.grad(_loss(network, channels, w, wf, "pallas", "fp32", q_init),
                       argnums=(0, 1, 2))(params, q_prime, channels.length)
        for i, (a, b) in enumerate(zip(
            jax.tree_util.tree_leaves(g_x), jax.tree_util.tree_leaves(g_p)
        )):
            _assert_close(a, b, f"{engine}/{init_path}: grad leaf {i}")

    def test_single_timestep_window(self):
        """T=1: only the hotstart diagonal exists."""
        rng = np.random.default_rng(31)
        n = 40
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, 1)
        r_x = _forward(network, channels, params, q_prime, "xla", "fp32")
        r_p = _forward(network, channels, params, q_prime, "pallas", "fp32")
        _assert_close(r_x.runoff, r_p.runoff, "T=1 forward", rtol=1e-6, atol_scale=1e-7)
        g_x = jax.grad(_loss(network, channels, w, wf, "xla", "fp32", None),
                       argnums=(0, 1, 2))(params, q_prime, channels.length)
        g_p = jax.grad(_loss(network, channels, w, wf, "pallas", "fp32", None),
                       argnums=(0, 1, 2))(params, q_prime, channels.length)
        for a, b in zip(jax.tree_util.tree_leaves(g_x), jax.tree_util.tree_leaves(g_p)):
            _assert_close(a, b, "T=1 grad")


class TestBf16:
    def test_bf16_pallas_matches_xla_and_stays_near_fp32(self):
        """Both implementations share the bf16-ring/fp32-accumulate scheme, so
        they agree with each other exactly; vs fp32 the documented bound is
        bf16 rounding compounded along the longest path (module docstring)."""
        rng = np.random.default_rng(57)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        r32 = _forward(network, channels, params, q_prime, "xla", "fp32")
        rb_x = _forward(network, channels, params, q_prime, "xla", "bf16")
        rb_p = _forward(network, channels, params, q_prime, "pallas", "bf16")
        _assert_close(rb_x.runoff, rb_p.runoff, "bf16 pallas-vs-xla",
                      rtol=1e-6, atol_scale=1e-7)
        rel = np.abs(np.asarray(rb_x.runoff) - np.asarray(r32.runoff)) / (
            np.abs(np.asarray(r32.runoff)) + 1e-6
        )
        assert rel.max() <= 0.3, f"bf16 max rel err {rel.max()} out of bound"
        assert rel.mean() <= 0.02, f"bf16 mean rel err {rel.mean()} out of bound"

    def test_bf16_health_counters_ride_route(self):
        """route(dtype='bf16', collect_health=True) fills the mixed-precision
        overflow/ulp_drift counters the training watchdog gates on; fp32
        leaves them None."""
        rng = np.random.default_rng(3)
        n, t = 32, 6
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, t)
        res32 = route(network, channels, params, q_prime, collect_health=True)
        assert res32.health.overflow is None and res32.health.ulp_drift is None
        res16 = route(network, channels, params, q_prime, dtype="bf16",
                      collect_health=True)
        assert int(res16.health.overflow) == 0
        assert np.isfinite(float(res16.health.ulp_drift))


class TestValidation:
    def test_pallas_requires_analytic_adjoint(self):
        rng = np.random.default_rng(5)
        n = 16
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, 4)
        with pytest.raises(ValueError, match="analytic"):
            route(network, channels, params, q_prime, adjoint="ad", kernel="pallas")

    def test_auto_kernel_falls_back_to_xla_for_ad_adjoint(self, monkeypatch):
        """On a TPU backend, kernel=None auto-resolves to pallas — but with
        adjoint='ad' (the A/B escape hatch) auto must silently keep the XLA
        scan, not raise: only an EXPLICIT pallas request errors."""
        from ddr_tpu.routing import pallas_kernel

        monkeypatch.setattr(pallas_kernel, "_on_tpu", lambda: True)
        assert pallas_kernel.resolve_kernel(None) == "pallas"  # simulated TPU
        rng = np.random.default_rng(8)
        n = 16
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, 4)
        res = route(network, channels, params, q_prime, adjoint="ad", kernel=None)
        assert np.isfinite(np.asarray(res.runoff)).all()

    def test_unknown_kernel_and_dtype_rejected(self):
        rng = np.random.default_rng(6)
        n = 16
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, 4)
        with pytest.raises(ValueError, match="kernel"):
            route(network, channels, params, q_prime, kernel="cuda")
        with pytest.raises(ValueError, match="dtype"):
            route(network, channels, params, q_prime, dtype="fp16")

    def test_step_engine_rejects_pallas_and_bf16(self):
        rng = np.random.default_rng(7)
        n = 16
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, 4)
        with pytest.raises(ValueError, match="step engine"):
            route(network, channels, params, q_prime, engine="step", kernel="pallas")
        with pytest.raises(ValueError, match="step engine"):
            route(network, channels, params, q_prime, engine="step", dtype="bf16")
        # "xla" is a no-op on the step engine (it IS a plain XLA schedule)
        route(network, channels, params, q_prime, engine="step", kernel="xla")


class TestJitCacheDiscipline:
    def test_pallas_path_adds_no_jit_cache_entries(self):
        """ONE jitted value_and_grad on the pallas path compiles exactly one
        program and repeat same-shape calls never re-trace — the fused kernel
        must not smuggle per-call retraces into the train step."""
        rng = np.random.default_rng(9)
        n, t = 40, 6
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
        loss = _loss(network, channels, w, wf, "pallas", "fp32", None)
        step = jax.jit(jax.value_and_grad(loss))
        step(params, q_prime, channels.length)
        assert step._cache_size() == 1
        params2 = {k: v + 0.001 for k, v in params.items()}
        step(params2, q_prime * 1.1, channels.length + 1.0)
        assert step._cache_size() == 1, "pallas path re-traced on a repeat batch"


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_fuzz_random_dags_all_engines(seed):
    """Wider randomized battery: per seed, one DAG through all three engines,
    alternating init paths, pallas vs xla, forward + analytic VJP."""
    rng = np.random.default_rng(4000 + seed)
    n, t = int(rng.integers(36, 96)), int(rng.integers(4, 14))
    rows, cols = _random_dag(rng, n, max_in=int(rng.integers(1, 6)))
    channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
    q_init = (
        None if seed % 2 == 0
        else jnp.asarray(rng.uniform(0.0, 3.0, n), jnp.float32)
    )
    for engine in ENGINES:
        network = _build(engine, rows, cols, n)
        r_x = _forward(network, channels, params, q_prime, "xla", "fp32", q_init)
        r_p = _forward(network, channels, params, q_prime, "pallas", "fp32", q_init)
        _assert_close(r_x.runoff, r_p.runoff, f"seed={seed}/{engine}: forward",
                      rtol=1e-6, atol_scale=1e-7)
        g_x = jax.grad(_loss(network, channels, w, wf, "xla", "fp32", q_init),
                       argnums=(0, 1, 2))(params, q_prime, channels.length)
        g_p = jax.grad(_loss(network, channels, w, wf, "pallas", "fp32", q_init),
                       argnums=(0, 1, 2))(params, q_prime, channels.length)
        for i, (a, b) in enumerate(zip(
            jax.tree_util.tree_leaves(g_x), jax.tree_util.tree_leaves(g_p)
        )):
            _assert_close(a, b, f"seed={seed}/{engine}: grad leaf {i}")
