"""Property-based fuzzing of the stacked band-scan router: random DAG
topologies x random band budgets against the step engine (itself pinned to the
scipy float64 oracle in tests/routing/test_solver.py).

The stacked frame has the most padding-sensitive host logic in the routing
layer (degree-rank slots, cross-band max width profiles, sentinel wiring for
gather/publish/external edges), so hypothesis shrinking over topologies is the
cheapest way to corner it: multi-root DAGs, isolated nodes, single-node bands,
wide confluences, and budget-forced degenerate bandings."""

from __future__ import annotations

import pytest

import jax.numpy as jnp
import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from ddr_tpu.routing.mc import ChannelState, route
from ddr_tpu.routing.network import build_network
from ddr_tpu.routing.stacked import build_stacked_chunked

pytestmark = pytest.mark.slow

@st.composite
def routed_dag_cases(draw):
    n = draw(st.integers(min_value=2, max_value=40))
    edges = []
    for i in range(1, n):
        k = draw(st.integers(min_value=0, max_value=min(i, 4)))
        ups = draw(
            st.lists(
                st.integers(min_value=0, max_value=i - 1),
                min_size=k, max_size=k, unique=True,
            )
        )
        edges.extend((i, u) for u in ups)
    t_hours = draw(st.integers(min_value=1, max_value=6))
    budget = draw(st.integers(min_value=6, max_value=4000))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    return n, edges, t_hours, budget, seed


@settings(max_examples=40, deadline=None)
@given(routed_dag_cases())
def test_stacked_route_matches_step_on_random_dags(case):
    n, edges, t_hours, budget, seed = case
    rows = np.array([e[0] for e in edges], dtype=np.int64)
    cols = np.array([e[1] for e in edges], dtype=np.int64)
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (t_hours, n)), jnp.float32)

    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    sn = build_stacked_chunked(rows, cols, n, cell_budget=budget)
    res = route(sn, channels, params, qp)

    rel = float(jnp.max(jnp.abs(res.runoff - ref.runoff) / (jnp.abs(ref.runoff) + 1e-6)))
    assert rel < 1e-4, f"n={n} edges={len(edges)} bands={sn.n_chunks} rel={rel}"
    relf = float(
        jnp.max(
            jnp.abs(res.final_discharge - ref.final_discharge)
            / (jnp.abs(ref.final_discharge) + 1e-6)
        )
    )
    assert relf < 1e-4
