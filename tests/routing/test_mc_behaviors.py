"""Behavioral Muskingum-Cunge tests at the reference suite's granularity.

Mirrors the behavior matrix of /root/reference/tests/routing/test_mmc.py,
test_flow_scaling.py:33-166 and test_routing_utils.py: hotstart variants,
coefficient edge cases, clamping, flow-scale routing effects, reproducibility,
and error handling — against this repo's functional engine.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.routing.mc import (
    Bounds,
    ChannelState,
    GaugeIndex,
    celerity,
    denormalize,
    hotstart_discharge,
    muskingum_coefficients,
    route,
)
from ddr_tpu.routing.network import build_network

DT = 3600.0


def _channels(n, rng=None, length=2000.0, slope=1e-3):
    if rng is None:
        return ChannelState(
            length=jnp.full(n, length, jnp.float32),
            slope=jnp.full(n, slope, jnp.float32),
            x_storage=jnp.full(n, 0.3, jnp.float32),
        )
    return ChannelState(
        length=jnp.asarray(rng.uniform(500, 5000, n), jnp.float32),
        slope=jnp.asarray(np.clip(rng.uniform(1e-4, 0.02, n), 1e-4, None), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )


def _params(n, rng=None):
    if rng is None:
        return {
            "n": jnp.full(n, 0.05, jnp.float32),
            "q_spatial": jnp.full(n, 0.5, jnp.float32),
            "p_spatial": jnp.full(n, 21.0, jnp.float32),
        }
    return {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }


def _chain(n):
    rows = np.arange(1, n, dtype=np.int64)
    cols = np.arange(0, n - 1, dtype=np.int64)
    return build_network(rows, cols, n)


class TestHotstart:
    """compute_hotstart_discharge behaviors
    (/root/reference/tests/routing/test_mmc.py TestComputeHotstartDischarge)."""

    def test_linear_chain_uniform_inflow(self):
        """On a chain with inflow 1 everywhere, Q0 is the cumulative count."""
        n = 6
        net = _chain(n)
        q0 = hotstart_discharge(net, jnp.ones(n, jnp.float32), 1e-4)
        np.testing.assert_allclose(np.asarray(q0), np.arange(1, n + 1, dtype=np.float32), rtol=1e-6)

    def test_linear_chain_nonuniform_inflow(self):
        n = 5
        net = _chain(n)
        inflow = np.array([2.0, 0.5, 1.0, 0.25, 3.0], np.float32)
        q0 = hotstart_discharge(net, jnp.asarray(inflow), 1e-4)
        np.testing.assert_allclose(np.asarray(q0), np.cumsum(inflow), rtol=1e-6)

    def test_single_reach(self):
        net = build_network(np.array([], np.int64), np.array([], np.int64), 1)
        q0 = hotstart_discharge(net, jnp.array([0.7], jnp.float32), 1e-4)
        np.testing.assert_allclose(np.asarray(q0), [0.7], rtol=1e-6)

    def test_clamping_to_discharge_lb(self):
        """Negative/zero lateral inflows clamp to the discharge lower bound."""
        n = 4
        net = _chain(n)
        q0 = hotstart_discharge(net, jnp.asarray([-1.0, 0.0, -5.0, 0.0], jnp.float32), 1e-4)
        assert (np.asarray(q0) >= 1e-4).all()

    def test_confluence_sums_branches(self):
        """Two headwaters joining: downstream = sum of branches + local."""
        rows = np.array([2, 2], np.int64)
        cols = np.array([0, 1], np.int64)
        net = build_network(rows, cols, 3)
        q0 = hotstart_discharge(net, jnp.asarray([1.0, 2.0, 0.5], jnp.float32), 1e-4)
        np.testing.assert_allclose(np.asarray(q0), [1.0, 2.0, 3.5], rtol=1e-6)

    def test_route_with_q_init_skips_hotstart(self):
        """carry_state semantics: output[0] is the clamped q_init, not a hotstart
        (/root/reference/src/ddr/routing/mmc.py:330-342)."""
        n = 8
        net = _chain(n)
        qp = jnp.ones((12, n), jnp.float32)
        q_init = jnp.full(n, 123.0, jnp.float32)
        res = route(net, _channels(n), _params(n), qp, q_init=q_init)
        np.testing.assert_allclose(np.asarray(res.runoff[0]), np.full(n, 123.0), rtol=1e-6)

    def test_differentiable_through_hotstart(self):
        n = 6
        net = _chain(n)

        def loss(qp0):
            return jnp.sum(hotstart_discharge(net, qp0, 1e-4))

        g = jax.grad(loss)(jnp.ones(n, jnp.float32))
        assert np.isfinite(np.asarray(g)).all()
        # d(sum of cumsums)/d(inflow_i) = n - i reaches downstream of i (chain).
        np.testing.assert_allclose(np.asarray(g), np.arange(n, 0, -1, dtype=np.float32), rtol=1e-5)


class TestCoefficients:
    """calculate_muskingum_coefficients edge cases
    (/root/reference/tests/routing/test_mmc.py TestMuskingumCungeCoefficients)."""

    def test_fast_wave_limits(self):
        """k << dt (short reach, fast wave): c4 -> 2, c3 -> -1... verify signs/ranges."""
        c1, c2, c3, c4 = muskingum_coefficients(
            jnp.array([10.0]), jnp.array([15.0]), jnp.array([0.3])
        )
        # k = 10/15 s, tiny vs dt=3600: c1,c2 ~ 1, c3 ~ -1, c4 ~ 2.
        assert np.asarray(c1)[0] == pytest.approx(1.0, abs=1e-3)
        assert np.asarray(c2)[0] == pytest.approx(1.0, abs=1e-3)
        assert np.asarray(c3)[0] == pytest.approx(-1.0, abs=1e-3)
        assert np.asarray(c4)[0] == pytest.approx(2.0, abs=1e-2)

    def test_slow_wave_limits(self):
        """k >> dt (long reach, slow wave): c4 -> 0, c1 -> negative, c3 -> +1."""
        c1, c2, c3, c4 = muskingum_coefficients(
            jnp.array([500_000.0]), jnp.array([0.3]), jnp.array([0.3])
        )
        assert np.asarray(c4)[0] == pytest.approx(0.0, abs=1e-2)
        assert np.asarray(c3)[0] > 0.9
        assert np.asarray(c1)[0] < 0.0

    def test_x_zero_reservoir(self):
        """x = 0 (pure reservoir): c1 == c2 == dt/denom, c4 == 2*c1."""
        c1, c2, c3, c4 = muskingum_coefficients(
            jnp.array([3600.0]), jnp.array([1.0]), jnp.array([0.0])
        )
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(c4), 2 * np.asarray(c1), rtol=1e-6)

    def test_sum_identity_random(self, rng):
        length = jnp.asarray(rng.uniform(10, 1e6, 200), jnp.float32)
        vel = jnp.asarray(rng.uniform(0.3, 15, 200), jnp.float32)
        x = jnp.asarray(rng.uniform(0.0, 0.5, 200), jnp.float32)
        c1, c2, c3, c4 = muskingum_coefficients(length, vel, x)
        np.testing.assert_allclose(np.asarray(c1 + c2 + c3), np.ones(200), rtol=1e-4)
        assert (np.asarray(c4) > 0).all() and (np.asarray(c4) <= 2.0 + 1e-6).all()

    def test_custom_dt(self):
        """Halving dt halves c4's numerator scale relationship: coefficients remain
        consistent (c1+c2+c3 == 1) at any dt (BMI sub-stepping uses dt != 3600)."""
        for dt in (300.0, 900.0, 7200.0):
            c1, c2, c3, c4 = muskingum_coefficients(
                jnp.array([2000.0]), jnp.array([1.5]), jnp.array([0.3]), dt=dt
            )
            np.testing.assert_allclose(np.asarray(c1 + c2 + c3), [1.0], rtol=1e-6)


class TestDenormalize:
    """Reference TestDenormalize (test_routing_utils.py:18-57)."""

    def test_linear_midpoint_and_bounds(self):
        v = denormalize(jnp.array([0.0, 0.5, 1.0]), (10.0, 20.0))
        np.testing.assert_allclose(np.asarray(v), [10.0, 15.0, 20.0], rtol=1e-6)

    def test_log_space_geometric_midpoint(self):
        v = denormalize(jnp.array([0.5]), (1.0, 100.0), log_space=True)
        assert np.asarray(v)[0] == pytest.approx(10.0, rel=1e-2)

    def test_preserves_gradient(self):
        g = jax.grad(lambda x: denormalize(x, (0.015, 0.25)).sum())(jnp.array([0.4]))
        np.testing.assert_allclose(np.asarray(g), [0.25 - 0.015], rtol=1e-6)

    def test_log_space_gradient_finite_positive(self):
        g = jax.grad(lambda x: denormalize(x, (1.0, 200.0), log_space=True).sum())(
            jnp.array([0.1, 0.5, 0.9])
        )
        arr = np.asarray(g)
        assert np.isfinite(arr).all() and (arr > 0).all()

    def test_matrix_input(self):
        v = denormalize(jnp.full((3, 4), 0.5), (0.0, 2.0))
        np.testing.assert_allclose(np.asarray(v), np.ones((3, 4)), rtol=1e-6)


class TestClamping:
    def test_discharge_never_below_lb(self):
        """Zero inflow everywhere: discharge pinned at the lower bound, never 0/NaN
        (reference test_route_timestep_discharge_clamping)."""
        n = 10
        net = _chain(n)
        qp = jnp.zeros((24, n), jnp.float32)
        res = route(net, _channels(n), _params(n), qp)
        out = np.asarray(res.runoff)
        assert np.isfinite(out).all()
        assert (out >= Bounds().discharge - 1e-9).all()

    def test_velocity_cap_limits_celerity(self):
        """Huge discharge: velocity clamps at 15 m/s -> celerity == 25 m/s."""
        n = 4
        c, _, _ = celerity(
            jnp.full(n, 1e9, jnp.float32),
            jnp.full(n, 0.02, jnp.float32),
            jnp.full(n, 21.0, jnp.float32),
            jnp.full(n, 0.5, jnp.float32),
            _channels(n),
            Bounds(),
        )
        np.testing.assert_allclose(np.asarray(c), np.full(n, 25.0), rtol=1e-5)

    def test_velocity_floor_limits_celerity(self):
        """Tiny discharge: velocity clamps at the 0.3 m/s floor -> celerity 0.5."""
        n = 4
        c, _, _ = celerity(
            jnp.full(n, 1e-6, jnp.float32),
            jnp.full(n, 0.2, jnp.float32),
            jnp.full(n, 21.0, jnp.float32),
            jnp.full(n, 0.5, jnp.float32),
            _channels(n),
            Bounds(),
        )
        np.testing.assert_allclose(np.asarray(c), np.full(n, 0.5), rtol=1e-5)


class TestFlowScaleRouting:
    """Routing-level flow scaling behavior
    (/root/reference/tests/routing/test_flow_scaling.py:33-166). In this design
    q_prime arrives pre-scaled (route() docstring), so scaling is applied to the
    forcing and its effect verified at the gauge."""

    def _route_gauge(self, scale):
        n = 8
        net = _chain(n)
        rng = np.random.default_rng(7)
        qp = rng.uniform(0.5, 2.0, (24, n)).astype(np.float32)
        qp_scaled = qp * np.asarray(scale, np.float32)[None, :]
        gauges = GaugeIndex.from_ragged([np.array([n - 1])])
        res = route(net, _channels(n), _params(n), jnp.asarray(qp_scaled), gauges=gauges)
        return np.asarray(res.runoff[:, 0])

    def test_scale_one_is_identity(self):
        base = self._route_gauge(np.ones(8))
        again = self._route_gauge(np.ones(8))
        np.testing.assert_array_equal(base, again)

    def test_scale_reduces_discharge_at_gauge(self):
        base = self._route_gauge(np.ones(8))
        scaled = self._route_gauge(np.full(8, 0.5))
        # After the hotstart row, every gauge value strictly decreases.
        assert (scaled[1:] < base[1:]).all()

    def test_near_zero_fraction_stays_finite(self):
        out = self._route_gauge(np.full(8, 1e-6))
        assert np.isfinite(out).all()
        assert (out >= Bounds().discharge - 1e-9).all()

    def test_partial_scale_only_upstream_half(self):
        """Scaling only the upstream half reduces the gauge, less than scaling all."""
        scale_half = np.ones(8)
        scale_half[:4] = 0.5
        base = self._route_gauge(np.ones(8))
        part = self._route_gauge(scale_half)
        full = self._route_gauge(np.full(8, 0.5))
        assert (part[1:] < base[1:]).all()
        assert (part[1:] > full[1:]).all()


class TestGaugeIndex:
    def test_empty_upstream_set_contributes_zero(self):
        gi = GaugeIndex.from_ragged([np.array([], np.int64), np.array([2])])
        out = gi.aggregate(jnp.array([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(np.asarray(out), [0.0, 3.0], rtol=1e-6)

    def test_duplicate_indices_sum(self):
        gi = GaugeIndex.from_ragged([np.array([1, 1])])
        out = gi.aggregate(jnp.array([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out), [4.0], rtol=1e-6)

    def test_shared_segment_across_gauges(self):
        """Two gauges can reference the same segment (reference
        test_two_gages_same_segment)."""
        gi = GaugeIndex.from_ragged([np.array([0, 2]), np.array([2])])
        out = gi.aggregate(jnp.array([1.0, 5.0, 7.0]))
        np.testing.assert_allclose(np.asarray(out), [8.0, 7.0], rtol=1e-6)


class TestRouteContract:
    def test_reproducibility_bitwise(self, rng):
        """Same inputs -> bitwise-identical outputs (reference test_reproducibility;
        the TPU design's stronger guarantee: pure function, no RNG)."""
        n = 16
        net = _chain(n)
        ch = _channels(n, rng)
        p = _params(n, rng)
        qp = jnp.asarray(rng.uniform(0.1, 2.0, (24, n)), jnp.float32)
        a = route(net, ch, p, qp)
        b = route(net, ch, p, qp)
        np.testing.assert_array_equal(np.asarray(a.runoff), np.asarray(b.runoff))

    @pytest.mark.parametrize("n", [1, 2, 3, 17, 64])
    def test_network_sizes(self, n, rng):
        """route() handles degenerate through mid sizes (reference
        test_different_network_sizes)."""
        net = _chain(n) if n > 1 else build_network(np.array([], np.int64), np.array([], np.int64), 1)
        qp = jnp.asarray(rng.uniform(0.1, 2.0, (6, n)), jnp.float32)
        res = route(net, _channels(n, rng), _params(n, rng), qp)
        assert res.runoff.shape == (6, n)
        assert np.isfinite(np.asarray(res.runoff)).all()

    def test_unknown_engine_raises(self):
        n = 4
        net = _chain(n)
        qp = jnp.ones((3, n), jnp.float32)
        with pytest.raises(ValueError, match="unknown engine"):
            route(net, _channels(n), _params(n), qp, engine="bogus")

    def test_q_prime_permuted_requires_wavefront(self):
        n = 4
        net = _chain(n)
        qp = jnp.ones((3, n), jnp.float32)
        with pytest.raises(ValueError, match="q_prime_permuted"):
            route(net, _channels(n), _params(n), qp, engine="step", q_prime_permuted=True)

    def test_scalar_p_spatial_broadcasts(self, rng):
        """p_spatial may be a scalar (reference default p=21 for MERIT)."""
        n = 8
        net = _chain(n)
        p = _params(n, rng)
        p_scalar = dict(p, p_spatial=jnp.float32(21.0))
        qp = jnp.asarray(rng.uniform(0.1, 2.0, (6, n)), jnp.float32)
        a = route(net, _channels(n), p, qp)
        b = route(net, _channels(n), p_scalar, qp)
        np.testing.assert_allclose(np.asarray(a.runoff), np.asarray(b.runoff), rtol=1e-6)

    def test_mass_conservation_steady_state(self):
        """Constant inflow long enough -> outlet discharge approaches total basin
        inflow (steady state of the MC scheme conserves mass)."""
        n = 6
        net = _chain(n)
        qp = jnp.full((200, n), 1.0, jnp.float32)
        res = route(net, _channels(n), _params(n), qp)
        # Outlet sees n units of inflow at steady state.
        assert np.asarray(res.runoff[-1, -1]) == pytest.approx(float(n), rel=1e-3)


class TestBounds:
    def test_from_config_subset(self):
        b = Bounds.from_config({"velocity": 0.5, "depth": 0.02, "unknown_key": 9.0})
        assert b.velocity == 0.5
        assert b.depth == 0.02
        assert b.discharge == Bounds().discharge  # untouched default

    def test_frozen(self):
        with pytest.raises(dataclasses.FrozenInstanceError):
            Bounds().velocity = 1.0
