"""Analytic reverse-wavefront adjoint vs standard AD: gradient equivalence.

The wavefront family's ``adjoint="analytic"`` custom VJP must be a pure
backward-schedule change — identical forward values, gradients matching AD to
float associativity — across every engine (single-ring wavefront, depth-chunked,
stacked), both state paths (in-band hotstart and carried ``q_init``), with and
without ``remat_physics`` on the AD side, on randomized small DAGs whose inputs
deliberately drive reaches INTO the discharge clamp (zero inflows -> raw solve
values below the lower bound), so the clamp subgradient path is exercised.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.routing.chunked import build_chunked_network
from ddr_tpu.routing.mc import Bounds, ChannelState, GaugeIndex, route
from ddr_tpu.routing.network import build_network
from ddr_tpu.routing.stacked import build_stacked_chunked


def _random_dag(rng, n, max_in=4, p_edge=0.8):
    """Topologically-ordered random DAG with bounded in-degree; returns
    (rows, cols) with rows = downstream targets."""
    rows, cols = [], []
    for i in range(1, n):
        if rng.random() > p_edge:
            continue  # occasional headwater mid-sequence
        k = int(rng.integers(1, max_in + 1))
        preds = rng.choice(i, size=min(k, i), replace=False)
        for p in np.atleast_1d(preds):
            rows.append(i)
            cols.append(int(p))
    return np.asarray(rows, np.int64), np.asarray(cols, np.int64)


def _random_inputs(rng, n, t):
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(500.0, 5000.0, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.asarray(rng.uniform(0.1, 0.4, n), jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.06, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.2, 0.8, n), jnp.float32),
        "p_spatial": jnp.asarray(rng.uniform(5.0, 30.0, n), jnp.float32),
    }
    # CLAMP-ACTIVE by construction: ~1/4 of inflow entries are exactly zero, so
    # headwater hotstart values (raw = q'_0) sit BELOW the discharge bound and
    # downstream raw values cross it — the backward's dmax path is exercised.
    q_prime = rng.uniform(0.0, 2.0, (t, n)).astype(np.float32)
    q_prime[rng.random((t, n)) < 0.25] = 0.0
    # loss weights: dense, sign-mixed, so every reach-timestep contributes a
    # distinct cotangent (a mean would make many backward bugs self-cancel)
    w = jnp.asarray(rng.normal(size=(t, n)), jnp.float32)
    wf = jnp.asarray(rng.normal(size=n), jnp.float32)
    return channels, params, jnp.asarray(q_prime), w, wf


def _loss_fn(network, channels, w, wf, adjoint, remat_physics, q_init, gauges=None):
    """Loss over (params, q_prime, length): covers the spatial-parameter,
    inflow, AND channel-state gradient paths, plus both outputs (runoff and
    final_discharge)."""
    if gauges is not None:
        w = w[:, : gauges.n_gauges]

    def loss(params, q_prime, length):
        ch = dataclasses.replace(channels, length=length)
        res = route(
            network, ch, params, q_prime, q_init=q_init,
            gauges=gauges, adjoint=adjoint, remat_physics=remat_physics,
        )
        return (res.runoff * w).sum() + (res.final_discharge * wf).sum()

    return loss


def _grads(network, channels, params, q_prime, w, wf, adjoint, remat, q_init, gauges=None):
    loss = _loss_fn(network, channels, w, wf, adjoint, remat, q_init, gauges)
    val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
        params, q_prime, channels.length
    )
    return val, grads


def _assert_grads_match(ga, gb, label):
    """rtol 1e-5 in the acceptance sense: componentwise rtol with an absolute
    floor scaled to each array's gradient magnitude (float32 accumulation
    noise on near-zero components must not mask real mismatches elsewhere)."""
    flat_a, _ = jax.tree_util.tree_flatten(ga)
    flat_b, _ = jax.tree_util.tree_flatten(gb)
    assert len(flat_a) == len(flat_b)
    for i, (a, b) in enumerate(zip(flat_a, flat_b)):
        a, b = np.asarray(a), np.asarray(b)
        scale = max(np.max(np.abs(a)), np.max(np.abs(b)), 1e-8)
        np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-5 * scale,
            err_msg=f"{label}: gradient leaf {i} diverges (scale={scale})",
        )


def _build(engine, rows, cols, n):
    if engine == "wavefront":
        net = build_network(rows, cols, n)
        assert net.wavefront and net.wf_t_width > 0
        return net
    if engine == "chunked":
        net = build_chunked_network(rows, cols, n, cell_budget=160)
        assert net.n_chunks >= 2, "banding too coarse to exercise cross-band adjoints"
        return net
    net = build_stacked_chunked(rows, cols, n, cell_budget=160)
    assert net.n_chunks >= 2 and net.t_width > 0
    return net


ENGINES = ("wavefront", "chunked", "stacked")


class TestAnalyticMatchesAD:
    @pytest.mark.parametrize("engine", ENGINES)
    @pytest.mark.parametrize("init_path", ("hotstart", "q_init"))
    def test_gradients_match_both_remat_modes(self, engine, init_path):
        rng = np.random.default_rng(hash((engine, init_path)) % 2**32)
        n, t = 72, 12
        rows, cols = _random_dag(rng, n)
        network = _build(engine, rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
        q_init = (
            None if init_path == "hotstart"
            else jnp.asarray(rng.uniform(0.0, 3.0, n), jnp.float32)
        )

        v_an, g_an = _grads(network, channels, params, q_prime, w, wf,
                            "analytic", True, q_init)
        for remat in (True, False):
            v_ad, g_ad = _grads(network, channels, params, q_prime, w, wf,
                                "ad", remat, q_init)
            # identical forward program -> identical value, bit for bit
            assert float(v_an) == float(v_ad), f"{engine}/{init_path}: forward diverged"
            _assert_grads_match(g_an, g_ad, f"{engine}/{init_path}/remat={remat}")

    def test_gauge_aggregated_gradients_match(self):
        """The gauge segment-sum path composes with the custom VJP."""
        rng = np.random.default_rng(11)
        n, t = 64, 10
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
        gauges = GaugeIndex.from_ragged(
            [rng.choice(n, size=3, replace=False) for _ in range(4)]
        )
        _, g_an = _grads(network, channels, params, q_prime, w, wf,
                         "analytic", True, None, gauges=gauges)
        _, g_ad = _grads(network, channels, params, q_prime, w, wf,
                         "ad", True, None, gauges=gauges)
        _assert_grads_match(g_an, g_ad, "gauges")

    def test_single_timestep_window(self):
        """T=1: only the hotstart diagonal exists; the q'-adjoint reduces to
        the transposed hotstart solve alone."""
        rng = np.random.default_rng(3)
        n = 40
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, 1)
        _, g_an = _grads(network, channels, params, q_prime, w, wf, "analytic", True, None)
        _, g_ad = _grads(network, channels, params, q_prime, w, wf, "ad", True, None)
        _assert_grads_match(g_an, g_ad, "T=1")

    def test_step_engine_rejects_adjoint(self):
        rng = np.random.default_rng(5)
        n = 16
        rows, cols = _random_dag(rng, n)
        network = build_network(rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, 4)
        with pytest.raises(ValueError, match="wavefront routing family"):
            route(network, channels, params, q_prime, engine="step", adjoint="analytic")

    def test_unknown_adjoint_rejected(self):
        rng = np.random.default_rng(6)
        n = 16
        rows, cols = _random_dag(rng, n)
        network = build_network(rows, cols, n)
        channels, params, q_prime, _, _ = _random_inputs(rng, n, 4)
        with pytest.raises(ValueError, match="adjoint"):
            route(network, channels, params, q_prime, adjoint="bogus")


class TestJitCacheDiscipline:
    def test_analytic_path_adds_no_jit_cache_entries(self):
        """ONE jitted value_and_grad on the analytic path compiles exactly one
        program, and repeat calls (fresh arrays, same shapes) never re-trace —
        the custom VJP must not smuggle extra cache entries or per-call
        retraces into the train step."""
        rng = np.random.default_rng(7)
        n, t = 48, 8
        rows, cols = _random_dag(rng, n)
        network = _build("wavefront", rows, cols, n)
        channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
        loss = _loss_fn(network, channels, w, wf, "analytic", True, None)
        step = jax.jit(jax.value_and_grad(loss))
        step(params, q_prime, channels.length)
        assert step._cache_size() == 1
        params2 = {k: v + 0.001 for k, v in params.items()}
        step(params2, q_prime * 1.1, channels.length + 1.0)
        assert step._cache_size() == 1, "analytic adjoint re-traced on a repeat batch"


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_fuzz_random_dags_all_engines(seed):
    """Wider randomized battery: per seed, one DAG through all three engines,
    alternating init paths, analytic vs AD."""
    rng = np.random.default_rng(1000 + seed)
    n, t = int(rng.integers(40, 120)), int(rng.integers(6, 20))
    rows, cols = _random_dag(rng, n, max_in=int(rng.integers(1, 6)))
    channels, params, q_prime, w, wf = _random_inputs(rng, n, t)
    q_init = (
        None if seed % 2 == 0
        else jnp.asarray(rng.uniform(0.0, 3.0, n), jnp.float32)
    )
    for engine in ENGINES:
        if engine == "wavefront":
            network = build_network(rows, cols, n)
        elif engine == "chunked":
            network = build_chunked_network(rows, cols, n, cell_budget=200)
        else:
            network = build_stacked_chunked(rows, cols, n, cell_budget=200)
        _, g_an = _grads(network, channels, params, q_prime, w, wf,
                         "analytic", True, q_init)
        _, g_ad = _grads(network, channels, params, q_prime, w, wf,
                         "ad", True, q_init)
        _assert_grads_match(g_an, g_ad, f"seed={seed}/{engine}")
