"""dmc wrapper contract: forward through prepare_batch + carry-state handoff,
and the state_dict round-trip the reference's module carries
(/root/reference/src/ddr/routing/torch_mc.py:297-339)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.routing.model import dmc
from ddr_tpu.validation.configs import Config


def _cfg():
    return Config(
        name="dmc_state",
        geodataset="synthetic",
        mode="routing",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"start_time": "1981/10/01", "end_time": "1981/10/08", "rho": 6},
        params={"save_path": "/tmp"},
    )


def _forward_once(model, basin):
    rd = basin.routing_data
    # dmc takes the KAN's NORMALIZED (0,1) outputs and denormalizes internally
    n = rd.n_segments
    spatial = {
        "n": jnp.full(n, 0.4, jnp.float32),
        "q_spatial": jnp.full(n, 0.5, jnp.float32),
        "p_spatial": jnp.full(n, 0.6, jnp.float32),
    }
    return model.forward(rd, basin.q_prime[:24], spatial, carry_state=True)


def test_state_dict_round_trips_progress_and_carry():
    cfg = _cfg()
    basin = make_basin(n_segments=48, n_gauges=3, n_days=3, seed=4)
    model = dmc(cfg)
    model.set_progress_info(epoch=3, mini_batch=9)
    out = _forward_once(model, basin)
    assert np.isfinite(np.asarray(out["runoff"])).all()

    state = model.state_dict()
    assert state["epoch"] == 3 and state["mini_batch"] == 9
    assert state["discharge_t"] is not None and state["discharge_t"].shape == (48,)

    fresh = dmc(cfg)
    fresh.load_state_dict(state)
    assert fresh.epoch == 3 and fresh.mini_batch == 9
    np.testing.assert_array_equal(
        np.asarray(fresh._discharge_t), np.asarray(model._discharge_t)
    )
    # the restored carry drives the next chunk exactly like the original's
    out_a = _forward_once(model, basin)
    out_b = _forward_once(fresh, basin)
    np.testing.assert_allclose(
        np.asarray(out_a["runoff"]), np.asarray(out_b["runoff"]), rtol=1e-6
    )


def test_load_state_dict_defaults_missing_fields():
    cfg = _cfg()
    model = dmc(cfg)
    model.load_state_dict({"cfg": cfg})
    assert model.epoch == 0 and model.mini_batch == 0 and model._discharge_t is None
