"""Wavefront (time-skewed) engine: parity against the per-timestep engine.

The wavefront schedule must be a pure re-ordering of route_step's arithmetic —
identical physics, identical predecessor sums — so every test here pins it against
engine="step" on the same inputs, including gradients (standard AD through the
wave scan vs the step engine's custom-VJP solver)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.routing.mc import Bounds, route
from ddr_tpu.routing.model import prepare_batch
from ddr_tpu.routing.network import build_network


def _setup(n=256, t=24, seed=0):
    # ONE shared topology per (n, t): distinct seeds would recompile both
    # engines per test (depth/n_edges are compile-time static); topology
    # variety lives in the fuzz batteries, not here.
    basin = make_basin(n_segments=n, n_gauges=4, n_days=max(2, -(-t // 24)), seed=seed)
    network, channels, gauges = prepare_batch(basin.routing_data, 1e-4)
    params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
    q_prime = jnp.asarray(basin.q_prime[:t])
    return network, channels, gauges, params, q_prime


def _assert_close(a, b, rtol=2e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


class TestForwardParity:
    def test_full_domain(self):
        network, channels, _, params, q_prime = _setup()
        assert network.wavefront, "synthetic basin should carry wavefront tables"
        wf = route(network, channels, params, q_prime, engine="wavefront")
        st = route(network, channels, params, q_prime, engine="step")
        _assert_close(wf.runoff, st.runoff)
        _assert_close(wf.final_discharge, st.final_discharge)

    def test_gauge_aggregated(self):
        network, channels, gauges, params, q_prime = _setup()
        wf = route(network, channels, params, q_prime, gauges=gauges, engine="wavefront")
        st = route(network, channels, params, q_prime, gauges=gauges, engine="step")
        _assert_close(wf.runoff, st.runoff)

    def test_with_carried_state(self):
        network, channels, _, params, q_prime = _setup()
        q_init = jnp.asarray(
            np.random.default_rng(0).uniform(0.1, 5.0, network.n), jnp.float32
        )
        wf = route(network, channels, params, q_prime, q_init=q_init, engine="wavefront")
        st = route(network, channels, params, q_prime, q_init=q_init, engine="step")
        _assert_close(wf.runoff, st.runoff)

    def test_chunked_carry_equivalence(self):
        """Sequential chunked inference (carry final_discharge) matches one pass."""
        network, channels, _, params, q_prime = _setup(t=48)
        full = route(network, channels, params, q_prime, engine="wavefront")
        a = route(network, channels, params, q_prime[:24], engine="wavefront")
        # chunk 2 overlaps one input row (step t consumes q_prime[t-1]) and its
        # row 0 re-emits the carried state — the ddr test chunking convention.
        b = route(
            network, channels, params, q_prime[23:], q_init=a.final_discharge,
            engine="wavefront",
        )
        _assert_close(
            jnp.concatenate([a.runoff, b.runoff[1:]], axis=0), full.runoff
        )

    def test_deep_chain(self):
        """A pure chain (depth = n - 1) is the wavefront's worst case for skew."""
        n, t = 150, 12
        rows, cols = np.arange(1, n), np.arange(n - 1)
        network = build_network(rows, cols, n)
        assert network.wavefront and network.depth == n - 1
        rng = np.random.default_rng(4)
        from ddr_tpu.routing.mc import ChannelState

        channels = ChannelState(
            length=jnp.asarray(rng.uniform(1e3, 1e4, n), jnp.float32),
            slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
            x_storage=jnp.full(n, 0.3, jnp.float32),
        )
        params = {
            "n": jnp.full(n, 0.03, jnp.float32),
            "q_spatial": jnp.full(n, 0.4, jnp.float32),
            "p_spatial": jnp.full(n, 21.0, jnp.float32),
        }
        q_prime = jnp.asarray(rng.uniform(0.0, 2.0, (t, n)), jnp.float32)
        wf = route(network, channels, params, q_prime, engine="wavefront")
        st = route(network, channels, params, q_prime, engine="step")
        _assert_close(wf.runoff, st.runoff, rtol=5e-4, atol=1e-4)

    def test_host_permuted_inflow_fast_path(self):
        """q_prime_permuted=True with host-pre-permuted columns must match the
        in-jit permute exactly (the documented hoist contract), and the flag must
        refuse on the step engine."""
        network, channels, gauges, params, q_prime = _setup()
        qp_host = jnp.asarray(
            np.asarray(q_prime)[:, np.asarray(network.wf_perm)]
        )
        a = route(network, channels, params, q_prime, gauges=gauges, engine="wavefront")
        b = route(
            network, channels, params, qp_host, gauges=gauges,
            engine="wavefront", q_prime_permuted=True,
        )
        np.testing.assert_array_equal(np.asarray(a.runoff), np.asarray(b.runoff))
        np.testing.assert_array_equal(
            np.asarray(a.final_discharge), np.asarray(b.final_discharge)
        )
        with pytest.raises(ValueError, match="q_prime_permuted"):
            route(network, channels, params, qp_host, engine="step", q_prime_permuted=True)

    def test_single_timestep(self):
        """T=1 runs the wave scan with only the in-band hotstart diagonal active:
        runoff is a single row equal to the clamped hotstart state."""
        network, channels, _, params, q_prime = _setup(t=24)
        wf = route(network, channels, params, q_prime[:1], engine="wavefront")
        st = route(network, channels, params, q_prime[:1], engine="step")
        assert wf.runoff.shape == st.runoff.shape == (1, network.n)
        _assert_close(wf.runoff, st.runoff)
        _assert_close(wf.final_discharge, st.final_discharge)


class TestGradientParity:
    def test_grad_matches_step_engine(self):
        network, channels, gauges, params, q_prime = _setup()

        def loss(p, engine):
            r = route(network, channels, p, q_prime, gauges=gauges, engine=engine)
            return jnp.mean(r.runoff ** 2)

        g_wf = jax.grad(lambda p: loss(p, "wavefront"))(params)
        g_st = jax.grad(lambda p: loss(p, "step"))(params)
        for k in params:
            _assert_close(g_wf[k], g_st[k], rtol=1e-3, atol=1e-5)

    @pytest.mark.slow
    def test_grad_wrt_inflow(self):
        network, channels, _, params, q_prime = _setup()

        def loss(qp, engine):
            return jnp.sum(route(network, channels, params, qp, engine=engine).runoff)

        g_wf = jax.grad(lambda qp: loss(qp, "wavefront"))(q_prime)
        g_st = jax.grad(lambda qp: loss(qp, "step"))(q_prime)
        _assert_close(g_wf, g_st, rtol=1e-3, atol=1e-5)


class TestEligibility:
    def test_edgeless_network_has_no_wavefront(self):
        network = build_network(np.zeros(0, np.int64), np.zeros(0, np.int64), 8)
        assert not network.wavefront
        # auto-select must quietly use the step engine
        channels_n = 8
        from ddr_tpu.routing.mc import ChannelState

        channels = ChannelState(
            length=jnp.full(channels_n, 1e3), slope=jnp.full(channels_n, 1e-3),
            x_storage=jnp.full(channels_n, 0.3),
        )
        params = {
            "n": jnp.full(channels_n, 0.03),
            "q_spatial": jnp.full(channels_n, 0.4),
            "p_spatial": jnp.full(channels_n, 21.0),
        }
        qp = jnp.ones((4, channels_n))
        out = route(network, channels, params, qp)
        assert out.runoff.shape == (4, channels_n)

    def test_forcing_wavefront_without_tables_raises(self):
        network = build_network(np.zeros(0, np.int64), np.zeros(0, np.int64), 4)
        from ddr_tpu.routing.mc import ChannelState

        channels = ChannelState(
            length=jnp.full(4, 1e3), slope=jnp.full(4, 1e-3), x_storage=jnp.full(4, 0.3)
        )
        params = {
            "n": jnp.full(4, 0.03), "q_spatial": jnp.full(4, 0.4),
            "p_spatial": jnp.full(4, 21.0),
        }
        with pytest.raises(ValueError, match="wavefront tables"):
            route(network, channels, params, jnp.ones((4, 4)), engine="wavefront")

    def test_bucket_tables_decode_to_the_edge_list(self):
        """wf_idx/wf_mask/wf_buckets must be a lossless re-encoding of the edges:
        decoding every real slot recovers exactly the (src, gap) multiset per node."""
        network, *_ = _setup(n=256)
        n = network.n
        lvl = np.asarray(network.level)
        perm = np.asarray(network.wf_perm)
        idx = np.asarray(network.wf_idx)
        mask = np.asarray(network.wf_mask)
        row_len = n + 1

        decoded = []  # (tgt_original, src_original, gap)
        off = 0
        for node_start, node_end, width in network.wf_buckets:
            cnt = (node_end - node_start) * width
            tbl = idx[off : off + cnt].reshape(node_end - node_start, width)
            msk = mask[off : off + cnt].reshape(tbl.shape)
            for j in range(node_end - node_start):
                tgt = perm[node_start + j]
                for k in range(width):
                    if msk[j, k]:
                        gap = tbl[j, k] // row_len + 1
                        src = perm[tbl[j, k] % row_len]
                        decoded.append((tgt, src, gap))
                    else:
                        assert tbl[j, k] == row_len - 1  # sentinel: ring[0, n]
            off += cnt
        assert off == len(idx)

        rows = np.asarray(network.edge_tgt)
        cols = np.asarray(network.edge_src)
        expected = sorted((t, s, lvl[t] - lvl[s]) for t, s in zip(rows, cols))
        assert sorted(decoded) == expected
        # gathered index count stays within 2x the edge count (pow2 bucket padding)
        assert len(idx) <= 2 * network.n_edges


class TestRematPhysics:
    """remat_physics replays the same physics in the backward; forward results are
    bitwise-equal, gradients agree to float-reassociation tolerance."""

    def test_forward_identical(self):
        network, channels, _, params, qp = _setup(n=64, seed=5)
        a = route(network, channels, params, qp, engine="wavefront", remat_physics=True)
        b = route(network, channels, params, qp, engine="wavefront", remat_physics=False)
        np.testing.assert_array_equal(np.asarray(a.runoff), np.asarray(b.runoff))

    def test_gradients_identical(self):
        network, channels, gauges, params, qp = _setup(n=64, seed=5)

        def loss(p, remat):
            return route(
                network, channels, p, qp, gauges=gauges,
                engine="wavefront", remat_physics=remat,
            ).runoff.mean()

        g_on = jax.grad(lambda p: loss(p, True))(params)
        g_off = jax.grad(lambda p: loss(p, False))(params)
        for k in g_on:
            # XLA fuses the two programs differently; parity is float-reassociation
            # level, not bitwise.
            np.testing.assert_allclose(
                np.asarray(g_on[k]), np.asarray(g_off[k]), rtol=2e-4, atol=1e-7
            )


class TestSkewImplementations:
    def test_gather_skew_matches_slice_skew(self, monkeypatch):
        """Deep networks compile the time-skews as one gather (op count O(1))
        instead of per-level-run static slices (op count O(depth), measured
        4+ min of XLA compile at depth 1200). Forcing the gather path on a
        shallow network must reproduce the slice path bitwise."""
        import ddr_tpu.routing.wavefront as wf
        from ddr_tpu.geodatazoo.synthetic import make_deep_network
        from ddr_tpu.routing.mc import ChannelState, route
        from ddr_tpu.routing.network import build_network

        n, depth, T = 400, 60, 12
        rows, cols = make_deep_network(n, depth, seed=6)
        rng = np.random.default_rng(0)
        channels = ChannelState(
            length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
            slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
            x_storage=jnp.full(n, 0.3, jnp.float32),
        )
        params = {
            "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
            "q_spatial": jnp.full(n, 0.5),
            "p_spatial": jnp.full(n, 21.0),
        }
        qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
        net = build_network(rows, cols, n)
        assert net.wavefront
        # the reference run must actually take the slice path, or this becomes
        # a vacuous gather-vs-gather comparison
        assert len(net.wf_level_runs) <= wf.SKEW_SLICE_MAX_RUNS
        ref = route(net, channels, params, qp, engine="wavefront").runoff
        monkeypatch.setattr(wf, "SKEW_SLICE_MAX_RUNS", 0)  # force gather path
        got = route(net, channels, params, qp, engine="wavefront").runoff
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
