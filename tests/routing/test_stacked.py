"""Stacked (scan-over-bands) chunked router: parity with the step engine across
band counts, gauges, carry state, gradients, and irregular topologies.

Same oracle discipline as tests/routing/test_chunked.py: the step engine is
pinned to the scipy float64 forward-substitution oracle, and every stacked
result must match it to float32-reassociation tolerance regardless of how many
bands the cell budget forces or how unequal the bands are (sentinel padding)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_deep_network
from ddr_tpu.routing.chunked import build_chunked_network
from ddr_tpu.routing.mc import ChannelState, GaugeIndex, route
from ddr_tpu.routing.network import build_network
from ddr_tpu.routing.stacked import StackedChunked, build_stacked_chunked, route_stacked


def _setup(n, depth, T, seed=2):
    rows, cols = make_deep_network(n, depth, seed=seed)
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
    return rows, cols, channels, params, qp


def _rel(a, b):
    return float(jnp.max(jnp.abs(a - b) / (jnp.abs(b) + 1e-6)))


@pytest.mark.parametrize("cell_budget", [200_000, 20_000, 4_000])
def test_matches_step_engine(cell_budget):
    n, depth, T = 600, 150, 16
    rows, cols, channels, params, qp = _setup(n, depth, T)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    sn = build_stacked_chunked(rows, cols, n, cell_budget=cell_budget)
    res = route(sn, channels, params, qp)  # via the route() dispatch
    assert _rel(res.runoff, ref.runoff) < 1e-4
    assert _rel(res.final_discharge, ref.final_discharge) < 1e-4


@pytest.mark.slow
def test_matches_unrolled_chunked_bitwise_frame():
    """Same budget => same banding as the unrolled router; results agree to
    float32 reassociation (the stacked frame reorders slots within bands)."""
    n, depth, T = 500, 120, 12
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=5)
    cn = build_chunked_network(rows, cols, n, cell_budget=6_000)
    sn = build_stacked_chunked(rows, cols, n, cell_budget=6_000)
    assert sn.n_chunks == cn.n_chunks > 1
    a = route(cn, channels, params, qp)
    b = route(sn, channels, params, qp)
    assert _rel(b.runoff, a.runoff) < 1e-5


def test_gauges_aggregate_identically():
    n, depth, T = 400, 100, 10
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=3)
    rng = np.random.default_rng(3)
    flat = rng.choice(n, size=6, replace=False)
    gauges = GaugeIndex.from_ragged([flat[:2], flat[2:4], flat[4:]])
    ref = route(
        build_network(rows, cols, n, fused=False), channels, params, qp,
        gauges=gauges, engine="step",
    )
    sn = build_stacked_chunked(rows, cols, n, cell_budget=5_000)
    assert sn.n_chunks > 1
    res = route(sn, channels, params, qp, gauges=gauges)
    assert res.runoff.shape == (T, 3)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_carry_state_chunked_inference():
    """Two half-window routes with q_init handoff == one full-window route."""
    n, depth, T = 400, 100, 12
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=4)
    sn = build_stacked_chunked(rows, cols, n, cell_budget=5_000)
    full = route(sn, channels, params, qp)
    h = T // 2
    a = route(sn, channels, params, qp[:h])
    b = route(sn, channels, params, qp[h:], q_init=a.final_discharge)
    # Reference semantics: window 2's output[0] re-emits the carried state
    # (clamped), then steps consume q_prime[t-1] of the new window — matching
    # the step engine's carry contract, which test_chunked pins the same way.
    ref2 = route(
        build_network(rows, cols, n, fused=False), channels, params, qp[h:],
        q_init=a.final_discharge, engine="step",
    )
    assert _rel(b.runoff, ref2.runoff) < 1e-4
    assert _rel(full.runoff[:h], a.runoff) < 1e-4


def test_gradients_match_step_engine():
    n, depth, T = 200, 50, 6
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=6)
    net_s = build_network(rows, cols, n, fused=False)
    sn = build_stacked_chunked(rows, cols, n, cell_budget=4_000)
    assert sn.n_chunks > 1

    def loss_ref(p):
        return route(net_s, channels, p, qp, engine="step").runoff.mean()

    def loss_stk(p):
        return route(sn, channels, p, qp).runoff.mean()

    g_ref = jax.grad(loss_ref)(params)
    g_stk = jax.grad(loss_stk)(params)
    for k in params:
        # 1e-6 denominator floor (near-zero leaves carry pure float32 noise) and
        # the same 2e-2 reassociation bound as the chunked engine's grad parity
        denom = jnp.abs(g_ref[k]) + 1e-6
        assert float(jnp.max(jnp.abs(g_stk[k] - g_ref[k]) / denom)) < 2e-2, k


def test_braided_divergence_matches_step():
    chain = 300
    n = 4 + chain
    rows = np.concatenate([[1, 2, 3, 3], np.arange(4, n)])
    cols = np.concatenate([[0, 0, 1, 2], np.arange(3, n - 1)])
    rng = np.random.default_rng(1)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (5, n)), jnp.float32)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    sn = build_stacked_chunked(rows, cols, n, cell_budget=2000)
    assert sn.n_chunks > 1
    res = route(sn, channels, params, qp)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_high_in_degree_confluence():
    """A 40-way confluence lands in a high bucket; unified-bucket padding must
    stay consistent when other bands lack that bucket entirely."""
    fan = 40
    tail = 200
    n = fan + 1 + tail
    rows = np.concatenate([np.full(fan, fan), np.arange(fan + 1, n)])
    cols = np.concatenate([np.arange(fan), np.arange(fan, n - 1)])
    rng = np.random.default_rng(7)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (6, n)), jnp.float32)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    sn = build_stacked_chunked(rows, cols, n, cell_budget=1500)
    assert sn.n_chunks > 1
    res = route(sn, channels, params, qp)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_auto_budget_default_and_jit():
    n, depth, T = 600, 150, 10
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=8)
    sn = build_stacked_chunked(rows, cols, n)  # auto budget
    assert isinstance(sn, StackedChunked)
    fn = jax.jit(lambda q: route(sn, channels, params, q).runoff)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    assert _rel(fn(qp), ref.runoff) < 1e-4


def test_single_timestep_route():
    """T=1 exercises the skew frame's degenerate right-edge branch."""
    n, depth = 300, 80
    rows, cols, channels, params, qp = _setup(n, depth, 1, seed=11)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    sn = build_stacked_chunked(rows, cols, n, cell_budget=4_000)
    res = route(sn, channels, params, qp)
    assert res.runoff.shape == (1, n)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_empty_graph_builds():
    """n=0 must not crash the public builder (the width profile is size-0, so
    the bucket comprehension would index wp[0]); the frame is trivial."""
    empty = np.zeros(0, dtype=np.int64)
    sn = build_stacked_chunked(empty, empty, 0)
    assert isinstance(sn, StackedChunked)
    assert sn.n == 0 and sn.n_cap == 0 and sn.buckets == ()


def test_dispatch_error_names_actual_type():
    """route()'s validation errors name the concrete network type (a
    StackedChunked error must not claim to be about a ChunkedNetwork)."""
    n, depth, T = 120, 30, 4
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=3)
    sn = build_stacked_chunked(rows, cols, n, cell_budget=2_000)
    with pytest.raises(ValueError, match="StackedChunked"):
        route(sn, channels, params, qp, engine="fused")
    with pytest.raises(ValueError, match="StackedChunked"):
        route(sn, channels, params, qp, q_prime_permuted=True)


def test_remat_bands_gradients_identical():
    """Band-level checkpointing recomputes instead of storing — values AND
    gradients must be identical to the default path (same math, same order)."""
    import jax

    n, depth, T = 200, 50, 6
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=6)
    sn = build_stacked_chunked(rows, cols, n, cell_budget=2_500)
    assert sn.n_chunks > 1

    def loss(p, **kw):
        return route(sn, channels, p, qp, **kw).runoff.mean()

    v0, g0 = jax.value_and_grad(loss)(params)
    v1, g1 = jax.value_and_grad(lambda p: loss(p, remat_bands=True))(params)
    assert float(v0) == float(v1)
    for k in params:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]), rtol=1e-6)


def test_remat_bands_rejected_off_stacked():
    n, depth, T = 120, 10, 4
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=3)
    plain = build_network(rows, cols, n, fused=False)
    with pytest.raises(ValueError, match="remat_bands"):
        route(plain, channels, params, qp, remat_bands=True)
