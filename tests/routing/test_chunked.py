"""Depth-chunked wavefront router: parity with the step engine, differentiability,
band-packing invariants, and deep-regime auto-selection.

The step engine is the in-repo oracle (itself pinned bitwise-level to the scipy
float64 forward-substitution oracle in tests/routing/test_solver.py); every
chunked result here must match it to float32-reassociation tolerance regardless
of how many bands the cell budget forces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_deep_network
from ddr_tpu.routing.chunked import (
    ChunkedNetwork,
    build_chunked_network,
    build_routing_network,
)
from ddr_tpu.routing.mc import ChannelState, GaugeIndex, route
from ddr_tpu.routing.network import (
    WAVEFRONT_MAX_DEPTH,
    WAVEFRONT_MAX_IN_DEGREE,
    RiverNetwork,
    build_network,
    compute_levels,
)


def _setup(n, depth, T, seed=2):
    rows, cols = make_deep_network(n, depth, seed=seed)
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {
        "n": jnp.asarray(rng.uniform(0.02, 0.2, n), jnp.float32),
        "q_spatial": jnp.asarray(rng.uniform(0.1, 0.9, n), jnp.float32),
        "p_spatial": jnp.full(n, 21.0, jnp.float32),
    }
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
    return rows, cols, channels, params, qp


def _rel(a, b):
    return float(jnp.max(jnp.abs(a - b) / (jnp.abs(b) + 1e-6)))


@pytest.mark.parametrize(
    "cell_budget",
    [60_000, 8_000, pytest.param(2_500, marks=pytest.mark.slow)],
)
def test_chunked_matches_step_engine(cell_budget):
    # budgets span the 1-band / few-band / many-band regimes at this shape
    # (the old 600x150 shape lives on in the slow-leg scale tests)
    n, depth, T = 320, 80, 8
    rows, cols, channels, params, qp = _setup(n, depth, T)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    cn = build_chunked_network(rows, cols, n, cell_budget=cell_budget)
    res = route(cn, channels, params, qp)
    assert _rel(res.runoff, ref.runoff) < 1e-4
    assert _rel(res.final_discharge, ref.final_discharge) < 1e-4


def test_chunked_multi_band_actually_splits():
    n, depth = 600, 150
    rows, cols, *_ = _setup(n, depth, 4)
    cn = build_chunked_network(rows, cols, n, cell_budget=4_000)
    assert cn.n_chunks > 1
    assert sum(net.n for net in cn.chunks) == n
    # every band ring respects the budget: (local_depth + 2) * (n_c + 1) cells
    for net in cn.chunks:
        assert (net.depth + 2) * (net.n + 1) <= 4_000 or net.depth == 0


def test_chunked_gauges_and_carry_state():
    n, depth, T = 320, 80, 8
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=5)
    gauges = GaugeIndex.from_ragged([np.array([n - 1]), np.array([5, 17, 200])])
    qi = jnp.asarray(np.random.default_rng(0).uniform(0.1, 2.0, n), jnp.float32)
    ref = route(
        build_network(rows, cols, n, fused=False), channels, params, qp,
        q_init=qi, gauges=gauges, engine="step",
    )
    cn = build_chunked_network(rows, cols, n, cell_budget=9_000)  # 2-3 bands
    res = route(cn, channels, params, qp, q_init=qi, gauges=gauges)
    assert res.runoff.shape == (T, 2)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_chunked_differentiable_matches_step_grad():
    n, depth, T = 160, 40, 6
    rows, cols, channels, params, qp = _setup(n, depth, T, seed=7)
    net_step = build_network(rows, cols, n, fused=False)
    cn = build_chunked_network(rows, cols, n, cell_budget=3_500)  # 2-3 bands: band-program compiles are the cost
    assert cn.n_chunks > 1

    def loss(nm, network, **kw):
        p = dict(params, n=nm)
        return jnp.mean(route(network, channels, p, qp, **kw).runoff ** 2)

    g_step = jax.grad(lambda nm: loss(nm, net_step, engine="step"))(params["n"])
    g_chk = jax.grad(lambda nm: loss(nm, cn))(params["n"])
    # identical math, different reassociation: float64 agreement is ~1e-12 (see
    # module docstring); float32 noise stays under ~2%
    denom = jnp.abs(g_step) + 1e-6
    assert float(jnp.max(jnp.abs(g_step - g_chk) / denom)) < 2e-2


def test_chunked_deep_chain_worst_case():
    """Pure mainstem (depth = n - 1): every band boundary is a single edge."""
    n = 32
    rows = np.arange(1, n, dtype=np.int64)
    cols = np.arange(n - 1, dtype=np.int64)
    channels, params, qp = _state(n, 6, seed=3)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    cn = build_chunked_network(rows, cols, n, cell_budget=120)  # tiny: many bands
    assert cn.n_chunks >= 4
    res = route(cn, channels, params, qp)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_auto_selection_deep_vs_shallow():
    from ddr_tpu.routing.stacked import StackedChunked

    rows, cols = make_deep_network(8000, 1500, seed=0)  # depth > single-ring cap
    assert isinstance(build_routing_network(rows, cols, 8000), StackedChunked)
    # an explicit budget keeps the unrolled chunked router (ablation/debug path)
    assert isinstance(
        build_routing_network(rows, cols, 8000, cell_budget=100_000), ChunkedNetwork
    )
    rows, cols = make_deep_network(2000, 200, seed=0)
    net = build_routing_network(rows, cols, 2000)
    assert isinstance(net, RiverNetwork) and net.wavefront


def test_route_rejects_bad_args_on_chunked():
    rows, cols, channels, params, qp = _setup(300, 80, 4)
    cn = build_chunked_network(rows, cols, 300, cell_budget=4_000)
    with pytest.raises(ValueError):
        route(cn, channels, params, qp, engine="step")
    with pytest.raises(ValueError):
        route(cn, channels, params, qp, q_prime_permuted=True)


def test_forced_wavefront_int32_guard():
    """(depth + 2) * (n + 1) >= 2^31 must refuse forced wavefront tables. A deep
    chain violates the cap at modest n ((n + 1)^2 ~ 2.6e9 at n = 51k) without
    allocating gigabyte-scale host arrays."""
    n = 51_000
    rows = np.arange(1, n, dtype=np.int64)
    cols = np.arange(n - 1, dtype=np.int64)
    with pytest.raises(ValueError, match="int32"):
        build_network(rows, cols, n, wavefront=True)


def test_conus_scale_preprocessing_stays_linear():
    """The full host-side build path at continental scale — 2.9M reaches, depth
    4000 (the global-MERIT shape, /root/reference/scripts/geometry_predictor.py:80)
    — must stay O(E): generate + compute_levels + level_schedule + 8-way
    topological partition + chunked build, with every schedule artifact bounded
    by edges, not depth x width. Measured on the build machine: ~4s wall, <1GB
    peak RSS for the whole chain (docs/tpu.md 'Continental depth'). The in-suite
    shape is scaled to 1/8 (still deep regime) to keep the suite fast; the sizes
    asserted are the scale-invariant O(E) contracts."""
    import time

    from ddr_tpu.geodatazoo.synthetic import make_deep_network
    from ddr_tpu.parallel.partition import topological_range_partition
    from ddr_tpu.routing.network import level_schedule

    n, depth = 362_500, 2000
    t0 = time.time()
    rows, cols = make_deep_network(n, depth, seed=0)
    level = compute_levels(rows, cols, n)
    lvl_src, _, _ = level_schedule(rows, cols, n, level=level)
    topological_range_partition(rows, cols, n, 8)
    cn = build_chunked_network(rows, cols, n, level=level)
    elapsed = time.time() - t0
    # O(E) contracts: rectangle cells bounded by E + cap*depth; every band ring
    # within budget; bands partition the nodes; all edges accounted for.
    assert lvl_src.size <= len(rows) + 1024 * depth + lvl_src.shape[1]
    assert sum(net.n for net in cn.chunks) == n
    for net in cn.chunks:
        assert (net.depth + 2) * (net.n + 1) <= 1 << 26
    assert sum(net.n_edges for net in cn.chunks) + sum(
        int(e.shape[0]) for e in cn.ext_cols
    ) == len(rows)
    # Generous wall guard (shared CI boxes): the 2.9M build measured ~4s alone.
    assert elapsed < 120, f"host preprocessing took {elapsed:.0f}s — no longer O(E)?"

    # The stacked frame at the same scale: vectorized build, bounded padding.
    from ddr_tpu.routing.stacked import build_stacked_chunked

    t0 = time.time()
    sn = build_stacked_chunked(rows, cols, n, level=level)
    stacked_s = time.time() - t0
    n_real = int((np.asarray(sn.gidx) < n).sum())
    assert n_real == n  # every node exactly one slot
    assert sn.n_chunks * sn.n_cap <= 2 * n + sn.n_chunks * depth  # padding bounded
    assert (sn.span_max + 2) * (sn.n_cap + 1) < 2**31
    assert stacked_s < 120, f"stacked build took {stacked_s:.0f}s — no longer O(E)?"


def test_chunk_local_levels_bounded_by_band_span():
    """Local (band-subgraph) depth never exceeds the global span of its band."""
    n, depth = 2000, 600
    rows, cols = make_deep_network(n, depth, seed=9)
    level = compute_levels(rows, cols, n)
    cn = build_chunked_network(rows, cols, n, cell_budget=30_000, level=level)
    assert cn.n_chunks > 1
    assert sum(net.n_edges for net in cn.chunks) + sum(
        int(e.shape[0]) for e in cn.ext_cols
    ) == len(rows)
    for net in cn.chunks:
        assert net.depth <= depth


def _state(n, T, seed):
    """Physics state for hand-built topologies (deterministic, shared by the
    extreme-topology tests; _setup draws from the deep generator instead)."""
    rng = np.random.default_rng(seed)
    channels = ChannelState(
        length=jnp.asarray(rng.uniform(1000, 5000, n), jnp.float32),
        slope=jnp.asarray(rng.uniform(1e-3, 1e-2, n), jnp.float32),
        x_storage=jnp.full(n, 0.3, jnp.float32),
    )
    params = {"n": jnp.full(n, 0.05), "q_spatial": jnp.full(n, 0.5),
              "p_spatial": jnp.full(n, 21.0)}
    qp = jnp.asarray(rng.uniform(0.01, 1.0, (T, n)), jnp.float32)
    return channels, params, qp


def test_high_in_degree_confluence_routes_via_chunked():
    """A reservoir-like node with in-degree far past the single-ring cap (64)
    must fall to the chunked router and still match the step engine — the
    bucketed gather tables carry arbitrary degree. chain stays BELOW the depth
    cap (1024) so in-degree is the SOLE selection trigger."""
    n_up, chain = 100, 200
    n = n_up + chain
    rows = np.concatenate([np.full(n_up, n_up), np.arange(n_up + 1, n)])
    cols = np.concatenate([np.arange(n_up), np.arange(n_up, n - 1)])
    level = compute_levels(rows, cols, n)
    assert int(level.max()) == chain <= WAVEFRONT_MAX_DEPTH  # depth alone stays single-ring
    assert n_up > WAVEFRONT_MAX_IN_DEGREE  # the load-bearing trigger
    from ddr_tpu.routing.stacked import StackedChunked

    net = build_routing_network(rows, cols, n)
    assert isinstance(net, StackedChunked)

    channels, params, qp = _state(n, 4, seed=0)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    res = route(net, channels, params, qp)
    assert _rel(res.runoff, ref.runoff) < 1e-4


def test_braided_divergence_matches_step():
    """Out-degree 2 (braided channel: one reach feeding two downstream branches)
    is outside the dendritic assumption but inside the lower-triangular solve
    semantics; the chunked router must match the step engine there too."""
    # 0 -> {1, 2}; 1 -> 3; 2 -> 3; 3 -> 4; then a chain 4 -> 5 -> ... -> n-1
    chain = 120
    n = 4 + chain
    rows = np.concatenate([[1, 2, 3, 3], np.arange(4, n)])
    cols = np.concatenate([[0, 0, 1, 2], np.arange(3, n - 1)])
    channels, params, qp = _state(n, 4, seed=1)
    ref = route(build_network(rows, cols, n, fused=False), channels, params, qp, engine="step")
    cn = build_chunked_network(rows, cols, n, cell_budget=6_000)  # 2-3 bands
    assert cn.n_chunks > 1
    res = route(cn, channels, params, qp)
    assert _rel(res.runoff, ref.runoff) < 1e-4


class TestAutoCellBudget:
    """auto_cell_budget: the measured-TPU-cost-model band sizing (docs/tpu.md).

    On-chip measurement that motivated it (N=65536, depth=1024, T=240): the
    2^26 memory cap packs 2 bands and routes at 7.4M rt/s; budget 2^18 packs
    16 bands and routes at 99.7M rt/s — per-wave cost is dominated by XLA's
    ring-carry copy, so small rings win until the C*T extra waves' fixed cost
    takes over.
    """

    def test_prefers_small_rings_on_deep_networks(self):
        from ddr_tpu.routing.chunked import CHUNK_CELL_BUDGET, auto_cell_budget

        b = auto_cell_budget(65536, 1024)
        # The optimum sits orders below the memory cap (C ~ 8-16 bands).
        assert b < CHUNK_CELL_BUDGET // 16
        assert b >= 2

    def test_respects_memory_cap(self):
        from ddr_tpu.routing.chunked import CHUNK_CELL_BUDGET, auto_cell_budget

        for n, d in [(65536, 1024), (2_900_000, 4000), (8192, 30), (16, 4)]:
            assert 2 <= auto_cell_budget(n, d) <= CHUNK_CELL_BUDGET

    def test_degenerate_shapes(self):
        from ddr_tpu.routing.chunked import CHUNK_CELL_BUDGET, auto_cell_budget

        assert auto_cell_budget(0, 0) == CHUNK_CELL_BUDGET
        assert auto_cell_budget(100, 0) == CHUNK_CELL_BUDGET

    def test_ring_divisor_shifts_optimum_to_wider_bands(self):
        """Per-shard cost model (ring_divisor=S): each shard pays ~1/S of the
        band's ring-copy tax per wave, so the optimum moves to fewer, wider
        bands — the per-shard budget must never imply MORE bands than the
        single-chip budget does at the same shape."""
        from ddr_tpu.routing.chunked import CHUNK_CELL_BUDGET, auto_cell_budget

        n, depth = 262_144, 2048
        rho = n / depth

        def implied_bands(budget, div):
            # invert ring(C) = (span+1)(span*rho/div+1) <= budget over C=2^k
            c = 1
            while c <= 64:
                span = max(1, -(-depth // c))
                if (span + 1) * (int(span * rho / div) + 1) <= budget:
                    return c
                c *= 2
            return 64

        b1 = auto_cell_budget(n, depth)
        b8 = auto_cell_budget(n, depth, ring_divisor=8)
        assert 2 <= b8 <= CHUNK_CELL_BUDGET
        assert implied_bands(b8, 8) <= implied_bands(b1, 1)
        # divisor=1 stays the exact legacy model
        assert b1 == auto_cell_budget(n, depth, ring_divisor=1)

    def test_default_build_uses_auto(self):
        n, depth, T = 320, 80, 8  # same shape+seed as the parity sweep: the step
        # reference hits the in-process jit cache
        rows, cols, channels, params, qp = _setup(n, depth, T)
        cn = build_chunked_network(rows, cols, n)  # cell_budget=None -> auto
        ref = route(
            build_network(rows, cols, n, fused=False), channels, params, qp, engine="step"
        )
        res = route(cn, channels, params, qp)
        assert _rel(res.runoff, ref.runoff) < 1e-4
