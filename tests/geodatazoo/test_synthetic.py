"""Generator contracts for the synthetic basins, focused on the deep
CONUS-realistic topology (round-3 requirement: the bench/ablation networks must
carry mainstem-scale longest-path depth, not the ~30 the shallow tree tops out at).
"""

import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin, make_deep_network
from ddr_tpu.routing.network import compute_levels


@pytest.mark.parametrize("n,depth", [(64, 10), (500, 120), (5000, 1500)])
def test_deep_network_exact_depth(n, depth):
    rows, cols = make_deep_network(n, depth, seed=3)
    level = compute_levels(rows, cols, n)
    assert int(level.max()) == depth


def test_deep_network_is_sorted_dendritic():
    n, depth = 2000, 400
    rows, cols = make_deep_network(n, depth, seed=7)
    # topologically sorted lower-triangular: src strictly below tgt
    assert (cols < rows).all()
    out_deg = np.bincount(cols, minlength=n)
    assert out_deg.max() == 1  # dendritic: every reach drains to one downstream
    # every non-outlet reach drains somewhere; outlets are the last level only
    level = compute_levels(rows, cols, n)
    no_out = np.flatnonzero(out_deg == 0)
    assert (level[no_out] == depth).all()


def test_deep_network_headwater_heavy():
    """Level populations decay: more headwaters than deep mainstem reaches."""
    n, depth = 20000, 2000
    rows, cols = make_deep_network(n, depth, seed=0)
    level = compute_levels(rows, cols, n)
    counts = np.bincount(level, minlength=depth + 1)
    assert (counts >= 1).all()  # mainstem threads every level
    assert counts[0] > 4 * counts[depth]


def test_deep_network_determinism():
    a = make_deep_network(300, 50, seed=11)
    b = make_deep_network(300, 50, seed=11)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    c = make_deep_network(300, 50, seed=12)
    assert not np.array_equal(a[0], c[0])


@pytest.mark.parametrize("n,depth", [(102, 100), (11, 10)])
def test_deep_network_near_pure_mainstem(n, depth):
    """Minimal-width networks (n barely above depth+1) must terminate and hit
    the exact depth — regression for the count-shave loop spinning when only
    level 0 had shaveable population."""
    rows, cols = make_deep_network(n, depth, seed=1)
    level = compute_levels(rows, cols, n)
    assert int(level.max()) == depth


def test_deep_network_rejects_infeasible():
    with pytest.raises(ValueError):
        make_deep_network(5, 10)


def test_make_basin_deep_topology_end_to_end():
    basin = make_basin(n_segments=256, n_gauges=2, n_days=2, seed=0, depth=60)
    rd = basin.routing_data
    level = compute_levels(rd.adjacency_rows, rd.adjacency_cols, rd.n_segments)
    assert int(level.max()) == 60
    assert basin.q_prime.shape == (48, 256)


def test_synthetic_dataset_config_knobs():
    """synthetic_segments / synthetic_depth are REAL config fields now (the
    getattr-only read was unreachable from YAML under extra=forbid)."""
    from ddr_tpu.geodatazoo.synthetic import Synthetic
    from ddr_tpu.validation.configs import Config

    cfg = Config(
        name="t", geodataset="synthetic", mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"start_time": "1981/10/01", "end_time": "1981/10/04", "rho": 3},
        synthetic_segments=200, synthetic_depth=50,
    )
    ds = Synthetic(cfg)
    rd = ds.routing_data
    assert rd.n_segments == 200
    level = compute_levels(rd.adjacency_rows, rd.adjacency_cols, 200)
    assert int(level.max()) == 50


class TestPrefetch:
    """prefetch(): order, exhaustion, exception propagation, bounded lookahead."""

    def test_preserves_order_and_maps(self):
        from ddr_tpu.geodatazoo.loader import prefetch

        out = list(prefetch(range(7), lambda x: x * 10, ahead=2))
        assert out == [0, 10, 20, 30, 40, 50, 60]

    def test_empty_iterable(self):
        from ddr_tpu.geodatazoo.loader import prefetch

        assert list(prefetch([], lambda x: x)) == []

    def test_exception_surfaces_at_consumer(self):
        from ddr_tpu.geodatazoo.loader import prefetch

        def boom(x):
            if x == 2:
                raise RuntimeError("prep failed")
            return x

        it = prefetch(range(5), boom, ahead=1)
        assert next(it) == 0
        assert next(it) == 1
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="prep failed"):
            list(it)

    def test_lookahead_is_bounded(self):
        """The worker never runs more than `ahead` items past the consumer."""
        import time

        from ddr_tpu.geodatazoo.loader import prefetch

        prepared = []

        def prep(x):
            prepared.append(x)
            return x

        it = prefetch(range(10), prep, ahead=1)
        next(it)
        time.sleep(0.2)  # give the worker time to overrun if it were unbounded
        assert len(prepared) <= 3  # consumed 1 + ahead 1 + one in flight

    def test_early_exit_is_prompt(self):
        """Abandoning the iterator (train.py's max_batches cutoff) must not
        block on queued prepares of batches nobody will consume."""
        import time

        from ddr_tpu.geodatazoo.loader import prefetch

        def slow_prep(x):
            time.sleep(1.5)
            return x

        it = prefetch(range(10), slow_prep, ahead=1)
        next(it)  # ~1.5s: first item must complete
        t0 = time.perf_counter()
        it.close()  # GeneratorExit -> shutdown(wait=False, cancel_futures=True)
        # the QUEUED prepare is cancelled; only an already-running one may
        # finish in its thread, and close() must not wait for it
        assert time.perf_counter() - t0 < 1.0


class TestCollatePurity:
    """collate_fn must hand each batch an INDEPENDENT window: collating batch
    k+1 cannot move batch k's dates or observations (the prefetch invariant;
    round-4 review caught the shared-Dates mutation)."""

    def test_later_collate_does_not_shift_earlier_batch(self):
        from ddr_tpu.geodatazoo.loader import DataLoader
        from ddr_tpu.validation.configs import Config

        cfg = Config(
            name="collate_purity",
            geodataset="synthetic",
            mode="training",
            kan={"input_var_names": [f"a{i}" for i in range(10)]},
            experiment={
                "start_time": "1981/10/01", "end_time": "1981/10/20",
                "rho": 5, "warmup": 1, "batch_size": 2,
            },
            params={"save_path": "/tmp"},
        )
        ds = cfg.geodataset.get_dataset_class(cfg)
        loader = DataLoader(ds, batch_size=2, shuffle=True, rng=np.random.default_rng(0))
        it = iter(loader)
        rd_a = next(it)
        win_a = np.asarray(rd_a.dates.batch_daily_time_range).copy()
        obs_a = np.asarray(rd_a.observations.streamflow).copy()
        hrs_a = np.asarray(rd_a.dates.hourly_indices).copy()
        # draw several more batches (each re-windows the dataset's shared Dates)
        for _ in range(3):
            rd_b = next(it, None)
            if rd_b is None:
                break
        np.testing.assert_array_equal(np.asarray(rd_a.dates.batch_daily_time_range), win_a)
        np.testing.assert_array_equal(np.asarray(rd_a.dates.hourly_indices), hrs_a)
        np.testing.assert_array_equal(np.asarray(rd_a.observations.streamflow), obs_a)
        assert rd_a is not rd_b  # distinct batch objects, not a shared mutable
