"""On-disk fixtures for the geodataset layer: a hand-built 10-reach MERIT-style
hydrofabric persisted through the real engine writers (the reference tests the same
way — tiny fixtures through the true build->zarr->load pipeline,
/root/reference/tests/conftest.py:28-338, tests/benchmarks/conftest.py:44-98).

Network (reach index: downstream id), COMIDs are 100+idx:

    0 -> 2, 1 -> 2, 2 -> 4, 3 -> 4, 4 -> 6, 5 -> 6, 6 -> 8, 7 -> 8, 8 -> 9

Gauges: 11111111 at reach 4 (upstream closure 0-4), 22222222 at reach 8
(closure 0-8), 33333333 at headwater reach 5 (no upstream — filtered).
"""

from __future__ import annotations

import numpy as np
import pytest
from scipy import sparse

from ddr_tpu.engine.core import coo_to_zarr, coo_to_zarr_group
from ddr_tpu.io import zarrlite
from ddr_tpu.io.stores import write_attribute_store, write_hydro_store

N_REACH = 10
COMIDS = [100 + i for i in range(N_REACH)]
EDGES = [(2, 0), (2, 1), (4, 2), (4, 3), (6, 4), (6, 5), (8, 6), (8, 7), (9, 8)]
GAGE_SEGMENTS = {"11111111": 4, "22222222": 8, "33333333": 5}
ATTR_NAMES = [f"attr{i}" for i in range(4)]
START, END = "1981/10/01", "1981/10/20"  # 20 days
N_DAYS_STORE = 40


@pytest.fixture(scope="session")
def fabric_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("fabric")
    rng = np.random.default_rng(7)

    rows = np.array([e[0] for e in EDGES])
    cols = np.array([e[1] for e in EDGES])
    coo = sparse.coo_matrix(
        (np.ones(len(EDGES), dtype=np.uint8), (rows, cols)), shape=(N_REACH, N_REACH)
    )

    # conus adjacency + flowpath attribute arrays (as the engine builder writes them)
    conus = root / "conus_adjacency.zarr"
    coo_to_zarr(coo, COMIDS, conus, "merit")
    g = zarrlite.open_group(conus)
    length = rng.uniform(1000, 5000, N_REACH)
    slope = rng.uniform(1e-3, 0.02, N_REACH)
    length[3] = np.nan  # exercise the NaN -> store-mean fill
    g.create_array("length_m", length)
    g.create_array("slope", slope)

    # per-gauge subsets (conus index space) with the binsparse subset convention:
    # ``order`` holds ONLY the subset's ids, ``gage_catchment`` the origin id
    # (reference core/zarr_io.py coo_to_zarr_group_generic).
    gages = root / "gages_adjacency.zarr"
    sub_root = zarrlite.create_group(gages)
    for staid, seg in GAGE_SEGMENTS.items():
        keep = _upstream_edges(seg)
        sub = sparse.coo_matrix(
            (np.ones(len(keep), dtype=np.uint8), ([e[0] for e in keep], [e[1] for e in keep])),
            shape=(N_REACH, N_REACH),
        )
        members = sorted({seg} | {i for e in keep for i in e})
        coo_to_zarr_group(
            sub_root, staid, sub, [COMIDS[i] for i in members], "merit",
            gage_catchment=COMIDS[seg], gage_idx=seg,
        )

    # attribute store over the COMIDs (one COMID deliberately missing)
    attrs = {name: rng.normal(size=N_REACH).astype(np.float32) for name in ATTR_NAMES}
    write_attribute_store(root / "attributes.zarr", COMIDS, attrs)

    # daily lateral-inflow store + observation store, origin-aligned
    q = rng.uniform(0.1, 2.0, size=(N_REACH, N_DAYS_STORE)).astype(np.float32)
    write_hydro_store(
        root / "streamflow.zarr", COMIDS, "1981/09/25", "D", {"Qr": q}, id_dim="divide_id"
    )
    obs = rng.uniform(1.0, 30.0, size=(3, N_DAYS_STORE)).astype(np.float32)
    obs[0, 5] = np.nan  # observation gap
    write_hydro_store(
        root / "observations.zarr",
        list(GAGE_SEGMENTS),
        "1981/09/25",
        "D",
        {"streamflow": obs},
        id_dim="gage_id",
    )

    # gauge CSV
    csv = root / "gages.csv"
    csv.write_text(
        "STAID,STANAME,DRAIN_SQKM,LAT_GAGE,LNG_GAGE,COMID,DA_VALID\n"
        + "\n".join(
            f"{staid},site {staid},{100.0 * (i + 1)},40.0,-75.0,{COMIDS[seg]},True"
            for i, (staid, seg) in enumerate(GAGE_SEGMENTS.items())
        )
        + "\n"
    )
    return root


def _upstream_edges(seg: int) -> list[tuple[int, int]]:
    keep, frontier = [], {seg}
    while frontier:
        new = set()
        for r, c in EDGES:
            if r in frontier and (r, c) not in keep:
                keep.append((r, c))
                new.add(c)
        frontier = new
    return keep


@pytest.fixture()
def merit_cfg(fabric_dir, tmp_path):
    from ddr_tpu.validation.configs import Config

    return Config(
        name="merit_test",
        geodataset="merit",
        mode="training",
        kan={"input_var_names": ATTR_NAMES},
        experiment={
            "start_time": START,
            "end_time": END,
            "rho": 8,
            "batch_size": 2,
            "warmup": 1,
        },
        data_sources={
            "attributes": str(fabric_dir / "attributes.zarr"),
            "conus_adjacency": str(fabric_dir / "conus_adjacency.zarr"),
            "streamflow": str(fabric_dir / "streamflow.zarr"),
            "observations": str(fabric_dir / "observations.zarr"),
            "gages": str(fabric_dir / "gages.csv"),
            "gages_adjacency": str(fabric_dir / "gages_adjacency.zarr"),
            "statistics": str(tmp_path / "stats"),
        },
        params={"save_path": str(tmp_path)},
    )
