"""Merit dataset behavior: filtering chain, subgraph compression, collate contract,
inference modes (modeled on the reference's dataset tests; the network fixture is in
conftest.py)."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.geodatazoo.dataclasses import RoutingData
from ddr_tpu.geodatazoo.loader import DataLoader
from ddr_tpu.geodatazoo.merit import Merit
from tests.geodatazoo.conftest import ATTR_NAMES, COMIDS, GAGE_SEGMENTS, N_REACH


@pytest.fixture()
def merit_train(merit_cfg):
    return Merit(merit_cfg)


class TestTraining:
    def test_headwater_gage_filtered(self, merit_train):
        # 33333333 sits on a headwater reach (empty subset) and must be dropped.
        assert sorted(merit_train.gage_ids) == ["11111111", "22222222"]

    def test_len_is_n_gages(self, merit_train):
        assert len(merit_train) == 2

    def test_collate_builds_compressed_subgraph(self, merit_train):
        rd = merit_train.collate_fn(["11111111"])
        assert isinstance(rd, RoutingData)
        # Upstream closure of reach 4 = reaches {0,1,2,3,4}.
        assert rd.n_segments == 5
        assert sorted(rd.divide_ids.tolist()) == [COMIDS[i] for i in range(5)]
        # Edges stay lower-triangular in compressed space (src < tgt).
        assert (rd.adjacency_cols < rd.adjacency_rows).all()
        # Gauge inflow columns: reaches draining into reach 4 are {2, 3}.
        assert len(rd.outflow_idx) == 1
        got = sorted(rd.divide_ids[rd.outflow_idx[0]].tolist())
        assert got == [COMIDS[2], COMIDS[3]]

    def test_collate_union_of_two_gages(self, merit_train):
        rd = merit_train.collate_fn(["11111111", "22222222"])
        assert rd.n_segments == 9  # union closure of reach 8: reaches 0-8
        assert len(rd.outflow_idx) == 2
        assert rd.gage_catchment == [COMIDS[4], COMIDS[8]]
        assert rd.flow_scale.shape == (9,)
        np.testing.assert_allclose(rd.flow_scale, 1.0)

    def test_collate_randomizes_window(self, merit_train):
        merit_train.collate_fn(["11111111"])
        w1 = merit_train.dates.batch_daily_time_range
        for _ in range(10):
            merit_train.collate_fn(["11111111"])
            if not w1.equals(merit_train.dates.batch_daily_time_range):
                break
        else:
            pytest.fail("rho window never re-randomized")
        assert len(merit_train.dates.batch_daily_time_range) == 8

    def test_attributes_normalized_shape(self, merit_train):
        rd = merit_train.collate_fn(["11111111"])
        assert rd.spatial_attributes.shape == (len(ATTR_NAMES), 5)
        assert rd.normalized_spatial_attributes.shape == (5, len(ATTR_NAMES))
        assert np.isfinite(rd.normalized_spatial_attributes).all()

    def test_nan_length_filled(self, merit_train):
        rd = merit_train.collate_fn(["11111111"])  # reach 3 has NaN length in store
        assert np.isfinite(rd.length).all()
        assert rd.x.shape == (5,)
        np.testing.assert_allclose(rd.x, 0.3)
        assert rd.top_width is None and rd.side_slope is None

    def test_observations_subset(self, merit_train):
        rd = merit_train.collate_fn(["11111111", "22222222"])
        assert rd.observations.streamflow.shape == (2, 8)

    def test_loader_epoch(self, merit_train):
        loader = DataLoader(merit_train, batch_size=2, shuffle=True, rng=np.random.default_rng(0))
        batches = list(loader)
        assert len(batches) == 1
        assert batches[0].n_segments == 9


class TestInference:
    def test_all_segments_mode(self, merit_cfg):
        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "testing"
        cfg.data_sources.gages = None
        cfg.data_sources.gages_adjacency = None
        ds = Merit(cfg)
        rd = ds.routing_data
        assert rd.n_segments == N_REACH
        assert rd.outflow_idx is None
        assert len(ds) == len(ds.dates.daily_time_range)

    def test_gages_mode(self, merit_cfg):
        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "testing"
        ds = Merit(cfg)
        assert ds.routing_data.n_segments == 9
        assert len(ds.routing_data.outflow_idx) == 2

    def test_target_catchments_mode(self, merit_cfg):
        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "routing"
        cfg.data_sources.target_catchments = [str(COMIDS[4])]
        ds = Merit(cfg)
        rd = ds.routing_data
        assert rd.n_segments == 5  # closure of reach 4
        # every active segment is its own output
        assert len(rd.outflow_idx) == 5

    def test_inference_collate_prepends_previous_day(self, merit_cfg):
        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "testing"
        ds = Merit(cfg)
        ds.collate_fn([3, 4, 5])
        assert ds.dates.batch_daily_time_range[0] == ds.dates.daily_time_range[2]

    def test_streamflow_reader_integration(self, merit_cfg, merit_train):
        from ddr_tpu.io.readers import StreamflowReader

        rd = merit_train.collate_fn(["11111111"])
        flow = StreamflowReader(merit_cfg)
        q = flow(routing_dataclass=rd)
        assert q.shape == (len(rd.dates.batch_hourly_time_range), rd.n_segments)
        assert (q > 0).all()
