"""Dates time-window machinery tests (reference dataclasses.py:69-187 behavior)."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.geodatazoo.dataclasses import Dates


@pytest.fixture
def dates():
    return Dates(start_time="1981/10/01", end_time="1981/10/31", rho=5)


class TestRanges:
    def test_daily_range_inclusive(self, dates):
        assert len(dates.daily_time_range) == 31
        assert str(dates.daily_time_range[0].date()) == "1981-10-01"
        assert str(dates.daily_time_range[-1].date()) == "1981-10-31"

    def test_hourly_range_left_inclusive(self, dates):
        # D days span (D-1)*24 hours with the left-inclusive convention.
        assert len(dates.hourly_time_range) == 30 * 24

    def test_numerical_time_range_origin_1980(self):
        d = Dates(start_time="1980/01/01", end_time="1980/01/03")
        np.testing.assert_array_equal(d.numerical_time_range, [0, 1, 2])

    def test_initial_batch_is_full_period(self, dates):
        assert len(dates.batch_daily_time_range) == 31
        np.testing.assert_array_equal(dates.daily_indices, np.arange(31))

    def test_rho_larger_than_period_rejected(self):
        with pytest.raises(ValueError, match="rho"):
            Dates(start_time="1981/10/01", end_time="1981/10/05", rho=10)

    def test_rho_equal_to_period_yields_full_window(self):
        d = Dates(start_time="1981/10/01", end_time="1981/10/05", rho=5)
        d.calculate_time_period(np.random.default_rng(0))
        assert len(d.batch_daily_time_range) == 5


class TestTrainingWindows:
    def test_random_window_has_rho_days(self, dates):
        dates.calculate_time_period(np.random.default_rng(0))
        assert len(dates.batch_daily_time_range) == 5
        assert len(dates.batch_hourly_time_range) == 4 * 24

    def test_window_stays_inside_period(self, dates):
        for seed in range(10):
            dates.calculate_time_period(np.random.default_rng(seed))
            assert dates.batch_daily_time_range[0] >= dates.daily_time_range[0]
            assert dates.batch_daily_time_range[-1] <= dates.daily_time_range[-1]

    def test_indices_map_into_full_ranges(self, dates):
        dates.calculate_time_period(np.random.default_rng(3))
        i0 = dates.daily_indices[0]
        assert dates.daily_time_range[i0] == dates.batch_daily_time_range[0]
        h0 = dates.hourly_indices[0]
        assert dates.hourly_time_range[h0] == dates.batch_hourly_time_range[0]
        assert len(dates.hourly_indices) == len(dates.batch_hourly_time_range)

    def test_every_day_sampleable(self, dates):
        # The final window [len-rho, len-1] must be drawable, or the period's last
        # days never appear in training.
        seen_last = False
        for seed in range(200):
            dates.calculate_time_period(np.random.default_rng(seed))
            if dates.batch_daily_time_range[-1] == dates.daily_time_range[-1]:
                seen_last = True
                break
        assert seen_last

    def test_no_rho_is_noop(self):
        d = Dates(start_time="1981/10/01", end_time="1981/10/10")
        d.calculate_time_period(np.random.default_rng(0))
        assert len(d.batch_daily_time_range) == 10

    def test_reproducible_with_seeded_rng(self, dates):
        dates.calculate_time_period(np.random.default_rng(7))
        first = dates.batch_daily_time_range.copy()
        dates.calculate_time_period(np.random.default_rng(7))
        assert (dates.batch_daily_time_range == first).all()


class TestInferenceChunks:
    def test_set_date_range_selects_chunk(self, dates):
        dates.set_date_range(np.array([2, 3, 4]))
        assert len(dates.batch_daily_time_range) == 3
        np.testing.assert_array_equal(dates.daily_indices, [2, 3, 4])

    def test_create_time_windows_partitions_period(self, dates):
        windows = dates.create_time_windows()
        assert windows.shape == (6, 5)  # 31 // 5 windows
        np.testing.assert_array_equal(windows.ravel(), np.arange(30))

    def test_create_time_windows_requires_rho(self):
        d = Dates(start_time="1981/10/01", end_time="1981/10/10")
        with pytest.raises(ValueError, match="rho"):
            d.create_time_windows()

    def test_numerical_range_follows_batch(self, dates):
        dates.set_date_range(np.array([0, 1]))
        origin_offset = dates.numerical_time_range[0]
        d2 = Dates(start_time="1981/10/01", end_time="1981/10/02")
        assert origin_offset == d2.numerical_time_range[0]
        assert len(dates.numerical_time_range) == 2
