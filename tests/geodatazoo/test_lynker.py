"""LynkerHydrofabric dataset behavior: string divide ids, observed channel geometry,
toid consistency assertion (reference lynker_hydrofabric tests)."""

from __future__ import annotations

import numpy as np
import pytest
from scipy import sparse

from ddr_tpu.engine.core import coo_to_zarr, coo_to_zarr_group
from ddr_tpu.geodatazoo.lynker import LynkerHydrofabric
from ddr_tpu.io import zarrlite
from ddr_tpu.io.stores import write_attribute_store, write_hydro_store
from tests.geodatazoo.conftest import EDGES, GAGE_SEGMENTS, N_REACH, START, END, _upstream_edges

WBIDS = [1000 + i for i in range(N_REACH)]
WB_ORDER = [f"wb-{w}" for w in WBIDS]
ATTRS = ["mean_elevation", "impervious_frac", "forest_frac"]


@pytest.fixture(scope="session")
def lynker_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("lynker_fabric")
    rng = np.random.default_rng(11)

    rows = np.array([e[0] for e in EDGES])
    cols = np.array([e[1] for e in EDGES])
    coo = sparse.coo_matrix(
        (np.ones(len(EDGES), dtype=np.uint8), (rows, cols)), shape=(N_REACH, N_REACH)
    )
    conus = root / "conus_adjacency.zarr"
    coo_to_zarr(coo, WB_ORDER, conus, "lynker")
    g = zarrlite.open_group(conus)
    g.create_array("length_m", rng.uniform(1000, 5000, N_REACH))
    g.create_array("slope", rng.uniform(1e-3, 0.02, N_REACH))
    g.create_array("top_width", rng.uniform(2, 40, N_REACH))
    g.create_array("side_slope", rng.uniform(0.5, 4.0, N_REACH))
    g.create_array("muskingum_x", rng.uniform(0.1, 0.45, N_REACH))
    # toid: numeric part of the downstream waterbody (terminal reaches -> ghost 0)
    downstream = {c: r for r, c in EDGES}
    toid = np.array(
        [WBIDS[downstream[i]] if i in downstream else 0 for i in range(N_REACH)],
        dtype=np.int32,
    )
    g.create_array("toid", toid)

    gages = root / "gages_adjacency.zarr"
    sub_root = zarrlite.create_group(gages)
    for staid, seg in GAGE_SEGMENTS.items():
        keep = _upstream_edges(seg)
        sub = sparse.coo_matrix(
            (np.ones(len(keep), dtype=np.uint8), ([e[0] for e in keep], [e[1] for e in keep])),
            shape=(N_REACH, N_REACH),
        )
        members = sorted({seg} | {i for e in keep for i in e})
        coo_to_zarr_group(
            sub_root, staid, sub, [WB_ORDER[i] for i in members], "lynker",
            gage_catchment=f"wb-{WBIDS[seg]}", gage_idx=seg,
        )

    cat_ids = [f"cat-{w}" for w in WBIDS]
    write_attribute_store(
        root / "attributes.zarr",
        cat_ids,
        {name: rng.normal(size=N_REACH).astype(np.float32) for name in ATTRS},
    )
    q = rng.uniform(0.1, 2.0, size=(N_REACH, 40)).astype(np.float32)
    write_hydro_store(root / "streamflow.zarr", cat_ids, "1981/09/25", "D", {"Qr": q})
    obs = rng.uniform(1.0, 30.0, size=(3, 40)).astype(np.float32)
    write_hydro_store(
        root / "observations.zarr", list(GAGE_SEGMENTS), "1981/09/25", "D",
        {"streamflow": obs}, id_dim="gage_id",
    )
    csv = root / "gages.csv"
    csv.write_text(
        "STAID,STANAME,DRAIN_SQKM,LAT_GAGE,LNG_GAGE\n"
        + "\n".join(
            f"{staid},site {staid},{100.0 * (i + 1)},40.0,-75.0"
            for i, staid in enumerate(GAGE_SEGMENTS)
        )
        + "\n"
    )
    return root


@pytest.fixture()
def lynker_cfg(lynker_dir, tmp_path):
    from ddr_tpu.validation.configs import Config

    return Config(
        name="lynker_test",
        geodataset="lynker_hydrofabric",
        mode="training",
        kan={"input_var_names": ATTRS},
        experiment={
            "start_time": START,
            "end_time": END,
            "rho": 8,
            "max_area_diff_sqkm": None,
        },
        data_sources={
            "attributes": str(lynker_dir / "attributes.zarr"),
            "conus_adjacency": str(lynker_dir / "conus_adjacency.zarr"),
            "streamflow": str(lynker_dir / "streamflow.zarr"),
            "observations": str(lynker_dir / "observations.zarr"),
            "gages": str(lynker_dir / "gages.csv"),
            "gages_adjacency": str(lynker_dir / "gages_adjacency.zarr"),
            "statistics": str(tmp_path / "stats"),
        },
        params={"save_path": str(tmp_path)},
    )


class TestLynker:
    def test_divide_ids_are_cat_strings(self, lynker_cfg):
        ds = LynkerHydrofabric(lynker_cfg)
        rd = ds.collate_fn(["11111111"])
        assert all(str(d).startswith("cat-") for d in rd.divide_ids)

    def test_real_channel_geometry_carried(self, lynker_cfg):
        ds = LynkerHydrofabric(lynker_cfg)
        rd = ds.collate_fn(["11111111"])
        assert rd.top_width is not None and rd.top_width.shape == (5,)
        assert rd.side_slope is not None
        assert rd.x is not None and not np.allclose(rd.x, 0.3)

    def test_toid_validation_passes_on_consistent_fabric(self, lynker_cfg):
        ds = LynkerHydrofabric(lynker_cfg)
        rd = ds.collate_fn(["11111111", "22222222"])
        assert rd.n_segments == 9

    def test_toid_validation_catches_mismatch(self, lynker_cfg):
        ds = LynkerHydrofabric(lynker_cfg)
        toid = ds._toid().copy()
        toid[2] = 9999  # reach 2 drains into gauge reach 4; corrupt its toid
        ds._toid_cache = toid
        with pytest.raises(AssertionError, match="Gage WB"):
            ds.collate_fn(["11111111"])

    def test_streamflow_reader_with_cat_ids(self, lynker_cfg):
        from ddr_tpu.io.readers import StreamflowReader

        ds = LynkerHydrofabric(lynker_cfg)
        rd = ds.collate_fn(["11111111"])
        q = StreamflowReader(lynker_cfg)(routing_dataclass=rd)
        assert q.shape == (len(rd.dates.batch_hourly_time_range), 5)
