"""Self-healing training e2e (synthetic basin, real train loop): an injected
NaN batch is skipped by the recovery supervisor, the loss trajectory rejoins,
and quarantined forcings never reach the device."""

from __future__ import annotations

import json
import math

import pytest

from ddr_tpu.observability import faults


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.configure(None)


def _cfg(tmp_path, **exp):
    from ddr_tpu.validation.configs import Config

    return Config(**{
        "name": "heal",
        "geodataset": "synthetic",
        "mode": "training",
        "kan": {"input_var_names": [f"a{i}" for i in range(10)]},
        "experiment": {
            "start_time": "1981/10/01",
            "end_time": "1981/10/20",
            "rho": 8,
            "batch_size": 1,
            "epochs": 1,
            "warmup": 1,
            "learning_rate": {1: 0.01},
            "shuffle": False,
            **exp,
        },
        "params": {"save_path": str(tmp_path)},
    })


def _events(run_dir):
    return [
        json.loads(line)
        for line in (run_dir / "run_log.train.jsonl").read_text().splitlines()
    ]


@pytest.mark.slow
def test_nan_batch_is_skipped_and_loss_rejoins(tmp_path, monkeypatch):
    """nan@device.step poisons one step payload; the supervisor skips the
    batch (restoring the pre-step snapshot), the step event carries the
    ``recovered`` marker, training finishes on a finite loss near the
    fault-free trajectory, and the run_end rollup records the quarantine."""
    from ddr_tpu.observability import run_telemetry
    from ddr_tpu.scripts.train import train

    monkeypatch.setenv("DDR_HEALTH_ENABLED", "1")
    monkeypatch.setenv("DDR_RECOVERY_ENABLED", "1")
    monkeypatch.setenv("DDR_CKPT_ASYNC", "0")

    golden = tmp_path / "golden"
    with run_telemetry(_cfg(golden, epochs=2), "train", base_dir=str(golden)):
        g_params, _ = train(_cfg(golden, epochs=2))
    golden_losses = [
        e["loss"] for e in _events(golden) if e["event"] == "step"
    ]
    assert all(math.isfinite(v) for v in golden_losses)

    run = tmp_path / "faulted"
    faults.configure("nan@device.step=1:n=1")
    try:
        with run_telemetry(_cfg(run, epochs=2), "train", base_dir=str(run)):
            f_params, _ = train(_cfg(run, epochs=2))
    finally:
        faults.configure(None)
    events = _events(run)

    recoveries = [e for e in events if e["event"] == "recovery"]
    assert [e["stage"] for e in recoveries] == ["skip"]
    assert recoveries[0]["batch"] == 1

    steps = [e for e in events if e["event"] == "step"]
    assert len(steps) == len(golden_losses)
    assert steps[1].get("recovered") == "skip"
    # the skipped batch reports no usable loss; every executed one is finite
    assert not math.isfinite(steps[1]["loss"])
    others = [e["loss"] for i, e in enumerate(steps) if i != 1]
    assert all(math.isfinite(v) for v in others)
    # rejoin: one dropped update, then a clean epoch — the run lands back in
    # the golden basin (the same gate the chaos drill applies to its params)
    import jax
    import numpy as np

    deltas = [
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree_util.tree_leaves(g_params), jax.tree_util.tree_leaves(f_params)
        )
    ]
    assert max(deltas) < 0.1

    (run_end,) = [e for e in events if e["event"] == "run_end"]
    assert run_end["summary"]["recovery"]["counts"]["skip"] == 1
    assert run_end["summary"]["recovery"]["quarantined"] == [
        {"epoch": 1, "batch": 1}
    ]


@pytest.mark.slow
def test_quarantined_forcings_never_reach_the_device(tmp_path, monkeypatch):
    """nan@data.forcings + DDR_DATA_VALIDATE=quarantine: the poisoned batch is
    dropped at the data_load phase — one data_anomaly event, one skip
    recovery, one FEWER executed step, and no health violation (the device
    never saw the poison)."""
    from ddr_tpu.observability import run_telemetry
    from ddr_tpu.scripts.train import train

    monkeypatch.setenv("DDR_HEALTH_ENABLED", "1")
    monkeypatch.setenv("DDR_RECOVERY_ENABLED", "1")
    monkeypatch.setenv("DDR_DATA_VALIDATE", "quarantine")
    monkeypatch.setenv("DDR_CKPT_ASYNC", "0")

    run = tmp_path / "run"
    faults.configure("nan@data.forcings=1:n=1")
    try:
        with run_telemetry(_cfg(run), "train", base_dir=str(run)):
            train(_cfg(run))
    finally:
        faults.configure(None)
    events = _events(run)

    anomalies = [e for e in events if e["event"] == "data_anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["nonfinite"] > 0
    assert [e["stage"] for e in events if e["event"] == "recovery"] == ["skip"]
    # the device never executed the poisoned batch: no health violation, and
    # the epoch is one step short
    assert not [e for e in events if e["event"] == "health"]
    steps = [e for e in events if e["event"] == "step"]
    assert all(math.isfinite(e["loss"]) for e in steps)

    (run_end,) = [e for e in events if e["event"] == "run_end"]
    assert run_end["summary"]["data_validate"]["policy"] == "quarantine"
    assert run_end["summary"]["data_validate"]["quarantined"] == 1
    assert run_end["summary"]["data_validate"]["anomalies"] == 1
