"""Supplementary BMI metadata/grid contract tests toward the reference's 84-test
granularity (/root/reference/tests/bmi/test_ddr_bmi.py: TestBmiInitConfig,
TestVariableInfo itemsize/location, TestTime per-method, TestGrid counts,
TestColdStart retrigger)."""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from ddr_tpu.bmi import BmiInitConfig, DdrBmi
from tests.bmi.test_ddr_bmi import bmi, bmi_config_file, fresh_bmi  # noqa: F401

ALL_OUTPUTS = (
    "channel_exit_water_x-section__volume_flow_rate",
    "channel_water_flow__speed",
    "channel_water__mean_depth",
    "channel_water__id",
)
ALL_INPUTS = (
    "land_surface_water_source__volume_flow_rate",
    "land_surface_water_source__id",
    "ngen_dt",
)


class TestInitConfigDefaults:
    def test_defaults(self, bmi_config_file):
        raw = yaml.safe_load(bmi_config_file.read_text())
        cfg = BmiInitConfig(ddr_config=raw["ddr_config"], kan_checkpoint=raw["kan_checkpoint"])
        assert cfg.timestep_seconds == 3600.0
        assert cfg.interpolation == "constant"

    def test_custom_values(self, bmi_config_file):
        raw = yaml.safe_load(bmi_config_file.read_text())
        cfg = BmiInitConfig(
            ddr_config=raw["ddr_config"],
            kan_checkpoint=raw["kan_checkpoint"],
            timestep_seconds=300.0,
            interpolation="linear",
        )
        assert cfg.timestep_seconds == 300.0
        assert cfg.interpolation == "linear"

    def test_missing_checkpoint_rejected(self, bmi_config_file, tmp_path):
        raw = yaml.safe_load(bmi_config_file.read_text())
        with pytest.raises(ValueError):
            BmiInitConfig(ddr_config=raw["ddr_config"], kan_checkpoint=tmp_path / "nope.ckpt")


class TestVariableMetadata:
    @pytest.mark.parametrize("name", ALL_OUTPUTS + ALL_INPUTS)
    def test_all_vars_have_units(self, bmi, name):
        assert isinstance(bmi.get_var_units(name), str)
        assert len(bmi.get_var_units(name)) > 0

    @pytest.mark.parametrize("name", ALL_OUTPUTS + ALL_INPUTS)
    def test_all_vars_have_types(self, bmi, name):
        np.dtype(bmi.get_var_type(name))  # resolvable numpy dtype

    @pytest.mark.parametrize("name", ALL_OUTPUTS)
    def test_itemsize_matches_dtype(self, bmi, name):
        assert bmi.get_var_itemsize(name) == np.dtype(bmi.get_var_type(name)).itemsize

    @pytest.mark.parametrize("name", ALL_OUTPUTS + ALL_INPUTS)
    def test_location_is_node(self, bmi, name):
        assert bmi.get_var_location(name) == "node"

    def test_nbytes_raises_for_input_vars(self, bmi):
        with pytest.raises(NotImplementedError):
            bmi.get_var_nbytes("land_surface_water_source__volume_flow_rate")

    def test_input_names_are_tuple(self, bmi):
        assert isinstance(bmi.get_input_var_names(), tuple)
        assert len(bmi.get_input_var_names()) == bmi.get_input_item_count()

    def test_output_names_are_tuple(self, bmi):
        assert isinstance(bmi.get_output_var_names(), tuple)
        assert len(bmi.get_output_var_names()) == bmi.get_output_item_count()


class TestTimeMethods:
    def test_start_time_zero(self, bmi):
        assert bmi.get_start_time() == 0.0

    def test_end_time_unbounded(self, bmi):
        assert bmi.get_end_time() == float("inf")

    def test_time_step_matches_config(self, bmi):
        assert bmi.get_time_step() == 3600.0

    def test_current_time_starts_at_zero(self, bmi_config_file):
        model = DdrBmi()
        model.initialize(str(bmi_config_file))
        assert model.get_current_time() == 0.0


class TestGridMethods:
    def test_grid_rank(self, bmi):
        assert bmi.get_grid_rank(0) == 1

    def test_grid_type_unstructured(self, bmi):
        assert bmi.get_grid_type(0) == "unstructured"

    def test_grid_size_equals_segments(self, bmi):
        n = bmi.get_grid_size(0)
        assert n > 0
        assert bmi.get_grid_node_count(0) == n

    def test_grid_edge_count_dendritic(self, bmi):
        """A dendritic network has fewer edges than nodes."""
        assert 0 < bmi.get_grid_edge_count(0) < bmi.get_grid_node_count(0)

    def test_grid_face_count_zero(self, bmi):
        assert bmi.get_grid_face_count(0) == 0

    def test_grid_spacing_raises(self, bmi):
        with pytest.raises(NotImplementedError):
            bmi.get_grid_spacing(0, np.zeros(1))

    def test_grid_origin_raises(self, bmi):
        with pytest.raises(NotImplementedError):
            bmi.get_grid_origin(0, np.zeros(1))

    @pytest.mark.parametrize("method", ["get_grid_x", "get_grid_y", "get_grid_z"])
    def test_grid_coordinates_raise(self, bmi, method):
        with pytest.raises(NotImplementedError):
            getattr(bmi, method)(0, np.zeros(4))


class TestColdStartRetrigger:
    def test_cold_start_does_not_retrigger(self, fresh_bmi):
        """The hotstart solve runs once; later updates step from carried state
        (reference TestColdStart.test_cold_start_does_not_retrigger)."""
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value(
            "land_surface_water_source__volume_flow_rate", np.full(n, 2.0)
        )
        fresh_bmi.update()
        q_after_first = fresh_bmi.get_value_ptr(
            "channel_exit_water_x-section__volume_flow_rate"
        ).copy()
        # Second update with zero inflow must CONTINUE (recession), not re-hotstart
        # to the zero-inflow accumulation (which would floor everything).
        fresh_bmi.set_value(
            "land_surface_water_source__volume_flow_rate", np.zeros(n)
        )
        fresh_bmi.update()
        q_after_second = fresh_bmi.get_value_ptr(
            "channel_exit_water_x-section__volume_flow_rate"
        )
        assert (q_after_second <= q_after_first + 1e-6).all()
        assert q_after_second.max() > 0.01  # state carried, not re-initialized


class TestGetValueSemantics:
    def test_get_value_fills_dest(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        dest = np.zeros(n)
        out = fresh_bmi.get_value("channel_water__id", dest)
        assert out is dest
        assert (dest == fresh_bmi.get_value_ptr("channel_water__id")).all()

    def test_get_value_ptr_unknown_raises(self, bmi):
        with pytest.raises(ValueError, match="Unknown output"):
            bmi.get_value_ptr("not_a_variable")

    def test_get_value_at_indices_out_of_order(self, fresh_bmi):
        ids = fresh_bmi.get_value_ptr("channel_water__id")
        dest = np.zeros(2)
        fresh_bmi.get_value_at_indices("channel_water__id", dest, np.array([3, 1]))
        assert dest[0] == ids[3] and dest[1] == ids[1]
