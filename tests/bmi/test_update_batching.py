"""update_until must be ONE compiled dispatch per coupling interval (not one per
sub-step), and the batched program must reproduce the per-step semantics exactly
— constant and linear interpolation, cold start, and carry across intervals."""

import numpy as np
import pytest
import yaml

from ddr_tpu.bmi.ddr_bmi import DdrBmi

N_ATTRS = 10


@pytest.fixture(scope="module")
def cfg_file(tmp_path_factory):
    import jax

    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.training import save_state

    tmp = tmp_path_factory.mktemp("bmi_batch")
    ddr_cfg = {
        "name": "bmi_batch",
        "geodataset": "synthetic",
        "mode": "routing",
        "kan": {"input_var_names": [f"a{i}" for i in range(N_ATTRS)]},
        "experiment": {"start_time": "1981/10/01", "end_time": "1981/10/04"},
        "params": {"save_path": str(tmp)},
    }
    cfg_path = tmp / "ddr_config.yaml"
    cfg_path.write_text(yaml.safe_dump(ddr_cfg))
    kan_model = Kan(
        input_var_names=tuple(ddr_cfg["kan"]["input_var_names"]),
        learnable_parameters=("n", "q_spatial"),
        hidden_size=11, num_hidden_layers=1, grid=3, k=3,
    )
    params = kan_model.init(jax.random.key(0), jax.numpy.zeros((4, N_ATTRS)))
    ckpt = save_state(tmp, "bmi_batch", epoch=1, mini_batch=0, params=params, opt_state=None)

    def write(interp):
        p = tmp / f"bmi_{interp}.yaml"
        p.write_text(yaml.safe_dump({
            "ddr_config": str(cfg_path), "kan_checkpoint": str(ckpt),
            "device": "cpu", "timestep_seconds": 900.0, "interpolation": interp,
        }))
        return p

    return {"constant": write("constant"), "linear": write("linear")}


def _feed(model, scale=1.0):
    n = model._num_segments
    inflow = scale * (0.1 + 0.01 * np.arange(n, dtype=np.float64))
    model._lateral_inflow[:] = inflow
    return inflow


def test_one_dispatch_per_update_until(cfg_file):
    model = DdrBmi()
    model.initialize(str(cfg_file["constant"]))
    calls = []
    inner = model._multi_step_fn
    model._multi_step_fn = lambda *a: (calls.append(a), inner(*a))[1]
    _feed(model)
    model.update_until(4 * 3600.0)  # 16 sub-steps at dt=900s
    assert len(calls) == 1, f"{len(calls)} dispatches for one coupling interval"
    assert calls[0][3] == 16  # n_steps
    _feed(model)
    model.update_until(8 * 3600.0)
    assert len(calls) == 2


@pytest.mark.parametrize("interp", ["constant", "linear"])
def test_batched_matches_per_step_reference(cfg_file, interp):
    """The scan program equals the old per-sub-step loop (run via _step_fn)."""
    import jax.numpy as jnp

    batched = DdrBmi()
    batched.initialize(str(cfg_file[interp]))
    loop = DdrBmi()
    loop.initialize(str(cfg_file[interp]))

    for interval, scale in enumerate([1.0, 2.5, 0.3]):
        _feed(batched, scale)
        inflow = _feed(loop, scale)
        t_end = (interval + 1) * 2 * 3600.0
        batched.update_until(t_end)

        # reference: the pre-batching per-step loop, replicated verbatim
        n_steps = round((t_end - loop._current_time) / loop._timestep)
        use_linear = interp == "linear" and loop._has_prev_inflow and n_steps > 1
        for step in range(n_steps):
            if use_linear:
                alpha = (step + 1) / n_steps
                q = (1 - alpha) * loop._prev_lateral_inflow + alpha * loop._lateral_inflow
            else:
                q = loop._lateral_inflow
            qp = jnp.asarray(q, jnp.float32)
            if not loop._cold_started:
                loop._q_t = loop._hotstart_fn(qp)
                loop._cold_started = True
            loop._q_t, vel, dep = loop._step_fn(loop._q_t, qp)
            loop._current_time += loop._timestep
        loop._discharge[:] = np.asarray(loop._q_t, dtype=np.float32)
        loop._prev_lateral_inflow[:] = loop._lateral_inflow
        loop._has_prev_inflow = True
        loop._lateral_inflow[:] = 0.0

        np.testing.assert_allclose(
            batched._discharge, loop._discharge, rtol=1e-5, atol=1e-6,
            err_msg=f"interval {interval} ({interp})",
        )
        assert batched._current_time == loop._current_time


def test_diagnostics_match_final_state(cfg_file):
    """Velocity/depth surfaced by BMI equal the geometry of the final discharge."""
    model = DdrBmi()
    model.initialize(str(cfg_file["constant"]))
    _feed(model)
    model.update_until(3 * 3600.0)
    dst = np.zeros(model._num_segments, dtype=np.float32)
    v = model.get_value("channel_water_flow__speed", dst.copy())
    assert np.isfinite(v).all() and (v >= 0).all()
    d = model.get_value("channel_water__mean_depth", dst.copy())
    assert np.isfinite(d).all() and (d > 0).all()
