"""BMI contract tests (reference /root/reference/tests/bmi/test_ddr_bmi.py).

Same strategy as the reference suite: exercise the full BMI v2.0 surface — pre-init
guards, variable metadata, time/grid semantics, set/get value plumbing, sub-stepping,
interpolation — without external data. Where the reference assembles MagicMock torch
engines, here the synthetic geodataset gives a REAL end-to-end initialize()/update()
path (network build + KAN inference + compiled routing step) at 64-segment scale.
"""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from ddr_tpu.bmi import BmiInitConfig, DdrBmi

N_ATTRS = 10


@pytest.fixture(scope="module")
def bmi_config_file(tmp_path_factory):
    """A BMI init YAML + framework config + trained-shape KAN checkpoint on disk."""
    import jax

    from ddr_tpu.nn.kan import Kan
    from ddr_tpu.training import save_state

    tmp = tmp_path_factory.mktemp("bmi")
    ddr_cfg = {
        "name": "bmi_test",
        "geodataset": "synthetic",
        "mode": "routing",
        "kan": {"input_var_names": [f"a{i}" for i in range(N_ATTRS)]},
        "experiment": {"start_time": "1981/10/01", "end_time": "1981/10/04"},
        "params": {"save_path": str(tmp)},
    }
    cfg_path = tmp / "ddr_config.yaml"
    cfg_path.write_text(yaml.safe_dump(ddr_cfg))

    kan_model = Kan(
        input_var_names=tuple(ddr_cfg["kan"]["input_var_names"]),
        learnable_parameters=("n", "q_spatial"),
        hidden_size=11,
        num_hidden_layers=1,
        grid=3,
        k=3,
    )
    params = kan_model.init(jax.random.key(0), jax.numpy.zeros((4, N_ATTRS)))
    ckpt = save_state(tmp, "bmi_test", epoch=1, mini_batch=0, params=params, opt_state=None)

    bmi_yaml = tmp / "bmi_config.yaml"
    bmi_yaml.write_text(
        yaml.safe_dump(
            {
                "ddr_config": str(cfg_path),
                "kan_checkpoint": str(ckpt),
                "device": "cpu",
                "timestep_seconds": 3600.0,
                "interpolation": "constant",
            }
        )
    )
    return bmi_yaml


@pytest.fixture(scope="module")
def bmi(bmi_config_file):
    model = DdrBmi()
    model.initialize(str(bmi_config_file))
    return model


@pytest.fixture()
def fresh_bmi(bmi_config_file):
    """Function-scoped instance for tests that mutate time/state."""
    model = DdrBmi()
    model.initialize(str(bmi_config_file))
    return model


class TestPreInitGuards:
    def test_update_before_initialize_raises(self):
        with pytest.raises(RuntimeError, match="not initialized"):
            DdrBmi().update()

    def test_update_until_before_initialize_raises(self):
        with pytest.raises(RuntimeError, match="not initialized"):
            DdrBmi().update_until(3600.0)

    def test_metadata_available_before_initialize(self):
        model = DdrBmi()
        assert model.get_input_item_count() == 3
        assert model.get_output_item_count() == 4
        assert model.get_time_units() == "s"


class TestInitConfig:
    def test_missing_ddr_config_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="does not exist"):
            BmiInitConfig(ddr_config=tmp_path / "nope.yaml")

    def test_bad_interpolation_rejected(self, bmi_config_file):
        raw = yaml.safe_load(bmi_config_file.read_text())
        raw["interpolation"] = "cubic"
        with pytest.raises(ValueError):
            BmiInitConfig(**raw)

    def test_extra_keys_rejected(self, bmi_config_file):
        raw = yaml.safe_load(bmi_config_file.read_text())
        raw["unknown_knob"] = 1
        with pytest.raises(ValueError):
            BmiInitConfig(**raw)


class TestVariableInfo:
    def test_component_name(self, bmi):
        assert "MuskingumCunge" in bmi.get_component_name()

    def test_var_names_match_troute(self, bmi):
        assert "land_surface_water_source__volume_flow_rate" in bmi.get_input_var_names()
        assert "channel_exit_water_x-section__volume_flow_rate" in bmi.get_output_var_names()
        assert len(bmi.get_input_var_names()) == bmi.get_input_item_count()
        assert len(bmi.get_output_var_names()) == bmi.get_output_item_count()

    @pytest.mark.parametrize(
        ("name", "units", "dtype"),
        [
            ("land_surface_water_source__volume_flow_rate", "m3 s-1", "float64"),
            ("channel_exit_water_x-section__volume_flow_rate", "m3 s-1", "float32"),
            ("channel_water_flow__speed", "m s-1", "float32"),
            ("channel_water__mean_depth", "m", "float32"),
            ("channel_water__id", "-", "int64"),
            ("ngen_dt", "s", "int32"),
        ],
    )
    def test_units_and_types(self, bmi, name, units, dtype):
        assert bmi.get_var_units(name) == units
        assert bmi.get_var_type(name) == dtype
        assert bmi.get_var_itemsize(name) == np.dtype(dtype).itemsize

    def test_var_nbytes_outputs(self, bmi):
        n = bmi.get_grid_size(0)
        assert bmi.get_var_nbytes("channel_water__mean_depth") == 4 * n
        with pytest.raises(NotImplementedError):
            bmi.get_var_nbytes("ngen_dt")

    def test_var_grid_and_location(self, bmi):
        assert bmi.get_var_grid("channel_water__id") == 0
        assert bmi.get_var_location("channel_water__id") == "node"


class TestTime:
    def test_time_semantics(self, bmi):
        assert bmi.get_start_time() == 0.0
        assert bmi.get_end_time() == float("inf")
        assert bmi.get_time_step() == 3600.0
        assert bmi.get_time_units() == "s"

    def test_update_advances_time(self, fresh_bmi):
        assert fresh_bmi.get_current_time() == 0.0
        fresh_bmi.update()
        assert fresh_bmi.get_current_time() == 3600.0

    def test_update_until_substeps(self, fresh_bmi):
        fresh_bmi.update_until(4 * 3600.0)
        assert fresh_bmi.get_current_time() == pytest.approx(4 * 3600.0)

    def test_update_until_past_time_is_noop(self, fresh_bmi):
        fresh_bmi.update()
        t = fresh_bmi.get_current_time()
        fresh_bmi.update_until(t - 3600.0)
        assert fresh_bmi.get_current_time() == t

    def test_update_until_below_half_step_defers(self, fresh_bmi):
        # Advancing a whole 3600 s step for a 900 s request would overshoot and
        # desynchronize from ngen's clock; the model defers until enough time
        # accumulates, keeping queued inflows.
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update_until(900.0)
        assert fresh_bmi.get_current_time() == 0.0
        assert fresh_bmi._lateral_inflow.sum() > 0  # inflows not consumed
        fresh_bmi.update_until(3600.0)
        assert fresh_bmi.get_current_time() == 3600.0


class TestGrid:
    def test_grid_shape(self, bmi):
        assert bmi.get_grid_rank(0) == 1
        assert bmi.get_grid_type(0) == "unstructured"
        assert bmi.get_grid_size(0) == 64  # synthetic default
        assert bmi.get_grid_node_count(0) == 64
        assert bmi.get_grid_edge_count(0) == 63  # dendritic tree: N-1 edges
        assert bmi.get_grid_face_count(0) == 0
        shape = np.zeros(1, dtype=np.int64)
        assert bmi.get_grid_shape(0, shape)[0] == 64

    @pytest.mark.parametrize(
        "method", ["get_grid_spacing", "get_grid_origin", "get_grid_x", "get_grid_y", "get_grid_z"]
    )
    def test_unsupported_grid_methods_raise(self, bmi, method):
        with pytest.raises(NotImplementedError):
            getattr(bmi, method)(0, np.zeros(1))


class TestValues:
    def test_set_value_direct_array(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        inflow = np.full(n, 0.5)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", inflow)
        np.testing.assert_allclose(fresh_bmi._lateral_inflow, 0.5)

    def test_set_value_nexus_remap(self, fresh_bmi):
        # nexus mapping falls back to identity (no GeoPackage for synthetic)
        fresh_bmi.set_value("land_surface_water_source__id", np.array([3, 5], dtype=np.int32))
        fresh_bmi.set_value(
            "land_surface_water_source__volume_flow_rate", np.array([1.5, 2.5])
        )
        assert fresh_bmi._lateral_inflow[3] == 1.5
        assert fresh_bmi._lateral_inflow[5] == 2.5
        assert fresh_bmi._lateral_inflow.sum() == 4.0

    def test_set_value_at_indices(self, fresh_bmi):
        fresh_bmi.set_value_at_indices(
            "land_surface_water_source__volume_flow_rate",
            np.array([0, 2]),
            np.array([7.0, 9.0]),
        )
        assert fresh_bmi._lateral_inflow[0] == 7.0
        assert fresh_bmi._lateral_inflow[2] == 9.0

    def test_set_unknown_variable_does_not_crash(self, fresh_bmi):
        fresh_bmi.set_value("not_a_variable", np.zeros(3))

    def test_set_value_shorter_than_nexus_ids(self, fresh_bmi):
        fresh_bmi.set_value(
            "land_surface_water_source__id", np.array([1, 2, 3, 4, 5], dtype=np.int32)
        )
        fresh_bmi.set_value(
            "land_surface_water_source__volume_flow_rate", np.array([1.0, 2.0, 3.0])
        )
        assert fresh_bmi._lateral_inflow[3] == 3.0  # identity map: nexus 3 -> seg 3
        assert fresh_bmi._lateral_inflow[4] == 0.0  # unsent entries untouched

    def test_set_ngen_dt(self, fresh_bmi):
        fresh_bmi.set_value("ngen_dt", np.array([900], dtype=np.int32))
        assert fresh_bmi._ngen_dt == 900

    def test_get_value_copies(self, fresh_bmi):
        fresh_bmi.update()
        n = fresh_bmi.get_grid_size(0)
        dest = np.zeros(n, dtype=np.float32)
        out = fresh_bmi.get_value("channel_exit_water_x-section__volume_flow_rate", dest)
        assert out is dest
        assert not np.shares_memory(
            dest, fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        )

    def test_get_value_ptr_stable_across_updates(self, fresh_bmi):
        ptr = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        fresh_bmi.update()
        assert fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate") is ptr

    def test_get_value_at_indices(self, fresh_bmi):
        fresh_bmi.update()
        dest = np.zeros(2, dtype=np.float32)
        full = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        out = fresh_bmi.get_value_at_indices(
            "channel_exit_water_x-section__volume_flow_rate", dest, np.array([1, 4])
        )
        np.testing.assert_allclose(out, full[[1, 4]])

    def test_get_unknown_output_raises(self, bmi):
        with pytest.raises(ValueError, match="Unknown output"):
            bmi.get_value_ptr("not_a_variable")

    def test_segment_ids_exposed(self, bmi):
        ids = bmi.get_value_ptr("channel_water__id")
        assert ids.dtype == np.int64
        assert len(ids) == bmi.get_grid_size(0)


class TestRoutingBehavior:
    def test_inflow_produces_positive_discharge(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update()
        q = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        assert (q > 0).all()
        assert np.isfinite(q).all()
        # Downstream segments accumulate upstream flow: max discharge well above the
        # per-segment inflow.
        assert q.max() > 2.0

    def test_velocity_and_depth_physical(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update()
        v = fresh_bmi.get_value_ptr("channel_water_flow__speed")
        d = fresh_bmi.get_value_ptr("channel_water__mean_depth")
        assert (v >= 0).all() and (v <= 15.0).all()
        assert (d >= 0.01).all()
        assert np.isfinite(v).all() and np.isfinite(d).all()

    def test_cold_start_uses_first_inflow(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        assert not fresh_bmi._cold_started
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 2.0))
        fresh_bmi.update()
        assert fresh_bmi._cold_started
        # Hotstart solves (I-N) Q0 = q'; after one step discharge stays near that
        # steady state rather than spinning up from ~0.
        q = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        assert q.max() > 2.0

    def test_inflows_cleared_after_update(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update()
        assert fresh_bmi._lateral_inflow.sum() == 0.0

    def test_steady_inflow_approaches_steady_state(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        for _ in range(6):
            fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
            fresh_bmi.update()
        q1 = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate").copy()
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update()
        q2 = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        np.testing.assert_allclose(q1, q2, rtol=0.05)


class TestInterpolation:
    def _run(self, bmi_config_file, tmp_path, interpolation):
        raw = yaml.safe_load(bmi_config_file.read_text())
        raw["interpolation"] = interpolation
        cfg = tmp_path / f"bmi_{interpolation}.yaml"
        cfg.write_text(yaml.safe_dump(raw))
        model = DdrBmi()
        model.initialize(str(cfg))
        n = model.get_grid_size(0)
        # interval 1: low inflow; interval 2: high inflow, 4 sub-steps
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 0.1))
        model.update_until(4 * 3600.0)
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 2.0))
        model.update_until(8 * 3600.0)
        return model.get_value_ptr("channel_exit_water_x-section__volume_flow_rate").copy()

    def test_linear_lags_constant_on_rising_inflow(self, bmi_config_file, tmp_path):
        q_const = self._run(bmi_config_file, tmp_path, "constant")
        q_lin = self._run(bmi_config_file, tmp_path, "linear")
        # Linear ramps from 0.1 up to 2.0 across the interval, so it injects less
        # total volume than constant-at-2.0 and ends with lower discharge.
        assert q_lin.sum() < q_const.sum()
        assert (q_lin > 0).all()


class TestFinalize:
    def test_finalize_releases_engine(self, bmi_config_file):
        model = DdrBmi()
        model.initialize(str(bmi_config_file))
        model.update()
        model.finalize()
        assert model._step_fn is None
        with pytest.raises(RuntimeError):
            model.update()


def record_substep_inflows(model, until):
    """Capture each sub-step's effective q_prime from the batched interval call
    (update_until is ONE dispatch now; the per-step series comes from the shared
    production ramp, ddr_bmi.interval_inflows), then restore."""
    from ddr_tpu.bmi.ddr_bmi import interval_inflows

    seen = []
    real = model._multi_step_fn

    def wrapper(q_t, cur, prev, n_steps, linear, cold):
        seen.extend(np.asarray(interval_inflows(cur, prev, n_steps, linear)))
        return real(q_t, cur, prev, n_steps, linear, cold)

    model._multi_step_fn = wrapper
    try:
        model.update_until(until)
    finally:
        model._multi_step_fn = real
    return seen


class TestSubStepping:
    def test_update_until_runs_expected_substeps(self, fresh_bmi):
        calls = record_substep_inflows(fresh_bmi, 4 * 3600.0)
        assert len(calls) == 4
        assert fresh_bmi.get_current_time() == 4 * 3600.0

    def test_no_substep_when_dt_matches(self, fresh_bmi):
        assert len(record_substep_inflows(fresh_bmi, 3600.0)) == 1

    def test_multi_coupling_intervals(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        for k in range(1, 4):
            fresh_bmi.set_value(
                "land_surface_water_source__volume_flow_rate", np.full(n, float(k))
            )
            fresh_bmi.update_until(k * 2 * 3600.0)
            assert fresh_bmi.get_current_time() == k * 2 * 3600.0

    def test_constant_equals_per_step_updates(self, bmi_config_file):
        """Constant interpolation over one 4h interval must reproduce 4 single
        updates with the same inflow re-sent each step (ngen's usual pattern)."""
        n_models = []
        for _ in range(2):
            m = DdrBmi()
            m.initialize(str(bmi_config_file))
            n_models.append(m)
        a, b = n_models
        n = a.get_grid_size(0)
        inflow = np.full(n, 1.25)
        a.set_value("land_surface_water_source__volume_flow_rate", inflow)
        a.update_until(4 * 3600.0)
        for _ in range(4):
            b.set_value("land_surface_water_source__volume_flow_rate", inflow)
            b.update()
        np.testing.assert_allclose(
            a.get_value_ptr("channel_exit_water_x-section__volume_flow_rate"),
            b.get_value_ptr("channel_exit_water_x-section__volume_flow_rate"),
            rtol=1e-6,
        )


class TestUpdateUntilBoundaries:
    """The deferral semantics VERDICT flagged as documented-but-untested: requests
    are rounded to whole routing steps; below half a step the model defers (a
    deviation from the reference's max(1, round(...)), kept deliberately so ngen's
    clock never desynchronizes)."""

    def test_exactly_half_step_defers(self, fresh_bmi):
        fresh_bmi.update_until(1800.0)  # round(0.5) == 0: banker's rounding
        assert fresh_bmi.get_current_time() == 0.0

    def test_just_above_half_step_advances_full_step(self, fresh_bmi):
        fresh_bmi.update_until(1801.0)
        assert fresh_bmi.get_current_time() == 3600.0  # snapped to the routing grid

    def test_one_and_a_half_steps_rounds_to_two(self, fresh_bmi):
        fresh_bmi.update_until(5400.0)
        assert fresh_bmi.get_current_time() == 7200.0

    def test_deferral_preserves_queued_inflows_and_state(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 2.0))
        fresh_bmi.update()
        q_before = fresh_bmi.get_value_ptr(
            "channel_exit_water_x-section__volume_flow_rate"
        ).copy()
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 9.0))
        fresh_bmi.update_until(fresh_bmi.get_current_time() + 900.0)  # defers
        np.testing.assert_array_equal(
            q_before,
            fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate"),
        )
        assert fresh_bmi._lateral_inflow.sum() == pytest.approx(9.0 * n)

    def test_backward_time_is_noop(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.update()
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 3.0))
        fresh_bmi.update_until(0.0)
        assert fresh_bmi.get_current_time() == 3600.0
        assert fresh_bmi._lateral_inflow.sum() == pytest.approx(3.0 * n)


class TestInterpolationRampValues:
    """Pin the exact per-substep inflows the engine receives (VERDICT: ramp values
    were untested). The step function is wrapped to record its q_prime argument."""

    def _linear_model(self, bmi_config_file, tmp_path):
        raw = yaml.safe_load(bmi_config_file.read_text())
        raw["interpolation"] = "linear"
        cfg = tmp_path / "bmi_linear_ramp.yaml"
        cfg.write_text(yaml.safe_dump(raw))
        model = DdrBmi()
        model.initialize(str(cfg))
        return model

    def test_linear_ramps_between_intervals(self, bmi_config_file, tmp_path):
        model = self._linear_model(bmi_config_file, tmp_path)
        n = model.get_grid_size(0)
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        model.update_until(4 * 3600.0)  # first interval: constant fallback
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 3.0))
        seen = record_substep_inflows(model, 8 * 3600.0)
        # alpha = (step+1)/4: inflows 1.5, 2.0, 2.5, 3.0
        assert len(seen) == 4
        for got, want in zip(seen, (1.5, 2.0, 2.5, 3.0)):
            np.testing.assert_allclose(got, np.full(n, want), rtol=1e-6)

    def test_linear_first_interval_falls_back_to_constant(self, bmi_config_file, tmp_path):
        model = self._linear_model(bmi_config_file, tmp_path)
        n = model.get_grid_size(0)
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 2.0))
        seen = record_substep_inflows(model, 3 * 3600.0)
        assert len(seen) == 3
        for got in seen:
            np.testing.assert_allclose(got, np.full(n, 2.0), rtol=1e-6)

    def test_linear_single_substep_uses_current(self, bmi_config_file, tmp_path):
        model = self._linear_model(bmi_config_file, tmp_path)
        n = model.get_grid_size(0)
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        model.update_until(3600.0)
        model.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 5.0))
        seen = record_substep_inflows(model, 2 * 3600.0)  # n_steps == 1: no ramp possible
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], np.full(n, 5.0), rtol=1e-6)

    def test_constant_holds_inflow_every_substep(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update()
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 4.0))
        seen = record_substep_inflows(fresh_bmi, fresh_bmi.get_current_time() + 3 * 3600.0)
        assert len(seen) == 3
        for got in seen:
            np.testing.assert_allclose(got, np.full(n, 4.0), rtol=1e-6)


class TestPrevInflowIndependence:
    def test_prev_inflow_stored_after_update(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 2.5))
        fresh_bmi.update()
        assert fresh_bmi._has_prev_inflow
        np.testing.assert_allclose(fresh_bmi._prev_lateral_inflow, 2.5)

    def test_prev_and_current_are_different_objects(self, fresh_bmi):
        assert fresh_bmi._prev_lateral_inflow is not fresh_bmi._lateral_inflow
        assert not np.shares_memory(
            fresh_bmi._prev_lateral_inflow, fresh_bmi._lateral_inflow
        )

    def test_zeroing_current_does_not_affect_prev(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.5))
        fresh_bmi.update()
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.zeros(n))
        np.testing.assert_allclose(fresh_bmi._prev_lateral_inflow, 1.5)


class TestPointerStability:
    @pytest.mark.parametrize(
        "name",
        [
            "channel_exit_water_x-section__volume_flow_rate",
            "channel_water_flow__speed",
            "channel_water__mean_depth",
            "channel_water__id",
        ],
    )
    def test_all_output_ptrs_stable_across_update(self, fresh_bmi, name):
        ptr = fresh_bmi.get_value_ptr(name)
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 1.0))
        fresh_bmi.update()
        assert fresh_bmi.get_value_ptr(name) is ptr

    def test_update_mutates_in_place(self, fresh_bmi):
        q_ptr = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        before = q_ptr.copy()
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, 2.0))
        fresh_bmi.update()
        assert not np.array_equal(q_ptr, before)  # same buffer, new values


class TestValueEdgeCases:
    def test_zero_inflow_floors_at_discharge_bound(self, fresh_bmi):
        fresh_bmi.update()  # no inflow set at all
        q = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        assert np.isfinite(q).all()
        assert (q >= 1e-4 - 1e-9).all()

    def test_negative_inflow_stays_finite_and_bounded(self, fresh_bmi):
        n = fresh_bmi.get_grid_size(0)
        fresh_bmi.set_value("land_surface_water_source__volume_flow_rate", np.full(n, -5.0))
        fresh_bmi.update()
        q = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        assert np.isfinite(q).all()
        assert (q >= 1e-4 - 1e-9).all()

    def test_get_value_at_indices_repeated_indices(self, fresh_bmi):
        fresh_bmi.update()
        full = fresh_bmi.get_value_ptr("channel_exit_water_x-section__volume_flow_rate")
        dest = np.zeros(3, dtype=np.float32)
        out = fresh_bmi.get_value_at_indices(
            "channel_exit_water_x-section__volume_flow_rate", dest, np.array([2, 2, 0])
        )
        np.testing.assert_allclose(out, full[[2, 2, 0]])

    def test_set_value_at_indices_accumulates_nothing(self, fresh_bmi):
        """Repeated set_value_at_indices overwrites, never accumulates."""
        fresh_bmi.set_value_at_indices(
            "land_surface_water_source__volume_flow_rate", np.array([1]), np.array([2.0])
        )
        fresh_bmi.set_value_at_indices(
            "land_surface_water_source__volume_flow_rate", np.array([1]), np.array([3.0])
        )
        assert fresh_bmi._lateral_inflow[1] == 3.0

    def test_segment_id_values_match_grid(self, bmi):
        ids = bmi.get_value_ptr("channel_water__id")
        assert len(np.unique(ids)) == len(ids)  # unique per segment


class TestFinalizeLifecycle:
    def test_finalize_then_update_raises(self, bmi_config_file):
        model = DdrBmi()
        model.initialize(str(bmi_config_file))
        model.finalize()
        with pytest.raises(RuntimeError):
            model.update()

    def test_finalize_is_idempotent(self, bmi_config_file):
        model = DdrBmi()
        model.initialize(str(bmi_config_file))
        model.finalize()
        model.finalize()

    def test_reinitialize_after_finalize(self, bmi_config_file):
        model = DdrBmi()
        model.initialize(str(bmi_config_file))
        model.finalize()
        model.initialize(str(bmi_config_file))
        model.update()
        assert model.get_current_time() == 3600.0
