"""Ensemble numerics: the fused E-member program must agree with an offline
loop of single-member forecasts, members must be deterministic per request id,
and E must stay ONE compiled program however many requests ride it."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.fleet.ensemble import (
    DEFAULT_PERCENTILES,
    member_forcing,
    percentile_bands,
    perturbation_seed,
)

MEMBERS = 5
RID = "ens-numerics-1"


def _ensemble_misses(svc) -> dict[str, int]:
    return {
        label: eng["misses"]
        for label, eng in svc.tracker.engines.items()
        if ":ensemble" in label
    }


class TestPerturbations:
    def test_seed_is_stable_and_31_bit(self):
        a = perturbation_seed("req-1", 0)
        assert a == perturbation_seed("req-1", 0)
        assert 0 <= a < 2**31
        assert a != perturbation_seed("req-2", 0)
        assert a != perturbation_seed("req-1", 1)

    def test_member_forcing_deterministic_and_distinct(self):
        qp = np.ones((6, 4), np.float32)
        m0 = member_forcing(qp, "req-1", 0, member=0, sigma=0.1)
        assert np.array_equal(m0, member_forcing(qp, "req-1", 0, 0, 0.1))
        m1 = member_forcing(qp, "req-1", 0, member=1, sigma=0.1)
        assert not np.array_equal(m0, m1)

    def test_sigma_zero_is_identity(self):
        qp = np.random.default_rng(0).random((6, 4)).astype(np.float32)
        assert np.array_equal(member_forcing(qp, "r", 0, 3, 0.0), qp)


class TestEnsembleNumerics:
    def test_percentiles_match_offline_member_loop(self, service_factory):
        """The fused program's bands == np.percentile over members routed one
        at a time through the PLAIN serve path with member_forcing windows."""
        svc = service_factory()
        out = svc.ensemble_forecast(
            network="default", t0=0, members=MEMBERS, request_id=RID,
            return_members=True,
        )
        sigma = svc._ensembles.fleet_cfg.ensemble_sigma
        net = svc.networks()["default"]
        window = np.asarray(net.forcing[: net.horizon])
        offline = np.stack([
            svc.forecast(
                network="default",
                q_prime=member_forcing(window, RID, 0, m, sigma),
                request_id=f"{RID}-offline-{m}",
            )["runoff"]
            for m in range(MEMBERS)
        ])  # (E, T, G)
        assert np.max(np.abs(np.asarray(out["member_runoff"]) - offline)) < 1e-6
        bands = np.percentile(offline, out["percentiles"], axis=0)
        assert np.max(np.abs(np.asarray(out["runoff"]) - bands)) < 1e-6
        assert np.max(np.abs(np.asarray(out["mean"]) - offline.mean(axis=0))) < 1e-6

    def test_same_request_id_reproduces_members(self, service_factory):
        svc = service_factory()
        a = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="rep",
            return_members=True,
        )
        b = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="rep",
            return_members=True,
        )
        assert np.array_equal(a["member_runoff"], b["member_runoff"])
        c = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="other",
            return_members=True,
        )
        assert not np.array_equal(a["member_runoff"], c["member_runoff"])

    def test_result_surface(self, service_factory):
        svc = service_factory()
        out = svc.ensemble_forecast(network="default", t0=0, members=3)
        assert out["percentiles"] == list(DEFAULT_PERCENTILES)
        runoff = np.asarray(out["runoff"])
        assert runoff.shape[0] == len(DEFAULT_PERCENTILES)
        assert np.all(np.diff(runoff, axis=0) >= -1e-6)  # bands are ordered
        assert out["engine"].endswith(":ensemble3")
        assert len(out["worst"]["gauges"]) == len(out["worst"]["scores"])
        assert "member_runoff" not in out  # only on return_members=True


class TestCompilePin:
    def test_one_program_per_network_model_E(self, service_factory):
        """The e2e pin: N requests at one E = exactly one compile; a second E
        adds exactly one more; reuse counts hits."""
        svc = service_factory()
        for i in range(3):
            svc.ensemble_forecast(
                network="default", t0=0, members=4, request_id=f"pin-{i}"
            )
        misses = _ensemble_misses(svc)
        assert sum(misses.values()) == 1, misses
        svc.ensemble_forecast(network="default", t0=0, members=8, request_id="pin-8")
        misses = _ensemble_misses(svc)
        assert sum(misses.values()) == 2, misses
        pair4 = "default/default:ensemble4"
        assert svc.tracker.engines[pair4]["hits"] >= 2

    def test_members_cap_enforced(self, service_factory, monkeypatch):
        monkeypatch.delenv("DDR_FLEET_ENSEMBLE_MAX_MEMBERS", raising=False)
        svc = service_factory()
        with pytest.raises(ValueError, match="members"):
            svc.ensemble_forecast(network="default", t0=0, members=65)
        with pytest.raises(ValueError, match="members"):
            svc.ensemble_forecast(network="default", t0=0, members=0)

    def test_validation_mirrors_submit(self, service_factory):
        svc = service_factory()
        with pytest.raises(ValueError, match="unknown network"):
            svc.ensemble_forecast(network="nope", t0=0, members=2)
        with pytest.raises(KeyError):
            svc.ensemble_forecast(network="default", model="nope", t0=0, members=2)
        with pytest.raises(ValueError, match="percentiles"):
            svc.ensemble_forecast(
                network="default", t0=0, members=2, percentiles=[150.0]
            )


class TestNanAwareBands:
    """percentile_bands: one broken member degrades one member — it must
    never poison every band the way plain np.percentile does."""

    def test_clean_stack_matches_plain_percentile(self):
        rng = np.random.default_rng(2)
        stack = rng.gamma(2.0, 1.0, size=(5, 4, 3))
        bands, n_bad = percentile_bands(stack, (10.0, 50.0, 90.0))
        assert n_bad == 0
        np.testing.assert_allclose(
            bands, np.percentile(stack, (10.0, 50.0, 90.0), axis=0)
        )

    def test_one_nan_member_is_masked_not_poisonous(self):
        rng = np.random.default_rng(3)
        stack = rng.gamma(2.0, 1.0, size=(5, 4, 3))
        stack[2, 1, 1] = np.nan  # ONE cell of ONE member
        bands, n_bad = percentile_bands(stack, (50.0,))
        assert n_bad == 1
        assert np.isfinite(bands).all()  # survivors carry every cell
        # untouched cells still use all five members
        np.testing.assert_allclose(
            bands[0, 0, 0], np.percentile(stack[:, 0, 0], 50.0)
        )
        # the poisoned cell falls back to the four finite members
        np.testing.assert_allclose(
            bands[0, 1, 1],
            np.percentile(np.delete(stack[:, 1, 1], 2), 50.0),
        )

    def test_inf_counts_like_nan(self):
        stack = np.ones((3, 2, 2))
        stack[0, 0, 0] = np.inf
        stack[1, 1, 1] = -np.inf
        _, n_bad = percentile_bands(stack, (50.0,))
        assert n_bad == 2

    def test_all_members_broken_cell_yields_nan_band(self):
        stack = np.ones((2, 1, 2))
        stack[:, 0, 0] = np.nan  # every member broke at this cell
        bands, n_bad = percentile_bands(stack, (50.0,))
        assert n_bad == 2
        assert np.isnan(bands[0, 0, 0]) and bands[0, 0, 1] == 1.0

    def test_nonfinite_count_rides_response_and_event(self, service_factory,
                                                      monkeypatch):
        svc = service_factory()
        runner_out = svc.ensemble_forecast(network="default", t0=0, members=3)
        assert runner_out["ensemble_nonfinite_members"] == 0
        # break one member's device output and re-serve
        import ddr_tpu.fleet.ensemble as ens_mod

        real = ens_mod.percentile_bands

        def poisoned(stack, qs):
            stack = np.asarray(stack).copy()
            stack[0, 0, 0] = np.nan
            return real(stack, qs)

        monkeypatch.setattr(ens_mod, "percentile_bands", poisoned)
        out = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="nan-ens"
        )
        assert out["ensemble_nonfinite_members"] == 1
        assert np.isfinite(np.asarray(out["runoff"])).all()
