"""Ensemble numerics: the fused E-member program must agree with an offline
loop of single-member forecasts, members must be deterministic per request id,
and E must stay ONE compiled program however many requests ride it."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.fleet.ensemble import (
    DEFAULT_PERCENTILES,
    member_forcing,
    perturbation_seed,
)

MEMBERS = 5
RID = "ens-numerics-1"


def _ensemble_misses(svc) -> dict[str, int]:
    return {
        label: eng["misses"]
        for label, eng in svc.tracker.engines.items()
        if ":ensemble" in label
    }


class TestPerturbations:
    def test_seed_is_stable_and_31_bit(self):
        a = perturbation_seed("req-1", 0)
        assert a == perturbation_seed("req-1", 0)
        assert 0 <= a < 2**31
        assert a != perturbation_seed("req-2", 0)
        assert a != perturbation_seed("req-1", 1)

    def test_member_forcing_deterministic_and_distinct(self):
        qp = np.ones((6, 4), np.float32)
        m0 = member_forcing(qp, "req-1", 0, member=0, sigma=0.1)
        assert np.array_equal(m0, member_forcing(qp, "req-1", 0, 0, 0.1))
        m1 = member_forcing(qp, "req-1", 0, member=1, sigma=0.1)
        assert not np.array_equal(m0, m1)

    def test_sigma_zero_is_identity(self):
        qp = np.random.default_rng(0).random((6, 4)).astype(np.float32)
        assert np.array_equal(member_forcing(qp, "r", 0, 3, 0.0), qp)


class TestEnsembleNumerics:
    def test_percentiles_match_offline_member_loop(self, service_factory):
        """The fused program's bands == np.percentile over members routed one
        at a time through the PLAIN serve path with member_forcing windows."""
        svc = service_factory()
        out = svc.ensemble_forecast(
            network="default", t0=0, members=MEMBERS, request_id=RID,
            return_members=True,
        )
        sigma = svc._ensembles.fleet_cfg.ensemble_sigma
        net = svc.networks()["default"]
        window = np.asarray(net.forcing[: net.horizon])
        offline = np.stack([
            svc.forecast(
                network="default",
                q_prime=member_forcing(window, RID, 0, m, sigma),
                request_id=f"{RID}-offline-{m}",
            )["runoff"]
            for m in range(MEMBERS)
        ])  # (E, T, G)
        assert np.max(np.abs(np.asarray(out["member_runoff"]) - offline)) < 1e-6
        bands = np.percentile(offline, out["percentiles"], axis=0)
        assert np.max(np.abs(np.asarray(out["runoff"]) - bands)) < 1e-6
        assert np.max(np.abs(np.asarray(out["mean"]) - offline.mean(axis=0))) < 1e-6

    def test_same_request_id_reproduces_members(self, service_factory):
        svc = service_factory()
        a = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="rep",
            return_members=True,
        )
        b = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="rep",
            return_members=True,
        )
        assert np.array_equal(a["member_runoff"], b["member_runoff"])
        c = svc.ensemble_forecast(
            network="default", t0=0, members=3, request_id="other",
            return_members=True,
        )
        assert not np.array_equal(a["member_runoff"], c["member_runoff"])

    def test_result_surface(self, service_factory):
        svc = service_factory()
        out = svc.ensemble_forecast(network="default", t0=0, members=3)
        assert out["percentiles"] == list(DEFAULT_PERCENTILES)
        runoff = np.asarray(out["runoff"])
        assert runoff.shape[0] == len(DEFAULT_PERCENTILES)
        assert np.all(np.diff(runoff, axis=0) >= -1e-6)  # bands are ordered
        assert out["engine"].endswith(":ensemble3")
        assert len(out["worst"]["gauges"]) == len(out["worst"]["scores"])
        assert "member_runoff" not in out  # only on return_members=True


class TestCompilePin:
    def test_one_program_per_network_model_E(self, service_factory):
        """The e2e pin: N requests at one E = exactly one compile; a second E
        adds exactly one more; reuse counts hits."""
        svc = service_factory()
        for i in range(3):
            svc.ensemble_forecast(
                network="default", t0=0, members=4, request_id=f"pin-{i}"
            )
        misses = _ensemble_misses(svc)
        assert sum(misses.values()) == 1, misses
        svc.ensemble_forecast(network="default", t0=0, members=8, request_id="pin-8")
        misses = _ensemble_misses(svc)
        assert sum(misses.values()) == 2, misses
        pair4 = "default/default:ensemble4"
        assert svc.tracker.engines[pair4]["hits"] >= 2

    def test_members_cap_enforced(self, service_factory, monkeypatch):
        monkeypatch.delenv("DDR_FLEET_ENSEMBLE_MAX_MEMBERS", raising=False)
        svc = service_factory()
        with pytest.raises(ValueError, match="members"):
            svc.ensemble_forecast(network="default", t0=0, members=65)
        with pytest.raises(ValueError, match="members"):
            svc.ensemble_forecast(network="default", t0=0, members=0)

    def test_validation_mirrors_submit(self, service_factory):
        svc = service_factory()
        with pytest.raises(ValueError, match="unknown network"):
            svc.ensemble_forecast(network="nope", t0=0, members=2)
        with pytest.raises(KeyError):
            svc.ensemble_forecast(network="default", model="nope", t0=0, members=2)
        with pytest.raises(ValueError, match="percentiles"):
            svc.ensemble_forecast(
                network="default", t0=0, members=2, percentiles=[150.0]
            )
