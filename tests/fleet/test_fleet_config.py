"""FleetConfig env parsing/validation and the fleet_identity contract."""

from __future__ import annotations

import pytest

from ddr_tpu.fleet.config import FLEET_MODES, FleetConfig, fleet_identity


class TestFromEnv:
    def test_defaults(self):
        cfg = FleetConfig.from_env(environ={})
        assert cfg.replicas == 2
        assert cfg.mode == "inprocess"
        assert cfg.group == "fleet"
        assert cfg.probe_s == 1.0
        assert cfg.eject_after == 2

    def test_env_overrides_defaults(self):
        cfg = FleetConfig.from_env(environ={
            "DDR_FLEET_REPLICAS": "4",
            "DDR_FLEET_MODE": "subprocess",
            "DDR_FLEET_PROBE_MS": "250",
            "DDR_FLEET_ENSEMBLE_SIGMA": "0.3",
        })
        assert cfg.replicas == 4
        assert cfg.mode == "subprocess"
        assert cfg.probe_s == pytest.approx(0.25)  # PROBE_MS is milliseconds
        assert cfg.ensemble_sigma == pytest.approx(0.3)

    def test_explicit_overrides_beat_env(self):
        cfg = FleetConfig.from_env(
            environ={"DDR_FLEET_REPLICAS": "4"}, replicas=3
        )
        assert cfg.replicas == 3

    def test_bad_env_value_names_the_variable(self):
        with pytest.raises(ValueError, match="DDR_FLEET_REPLICAS"):
            FleetConfig.from_env(environ={"DDR_FLEET_REPLICAS": "many"})

    @pytest.mark.parametrize("kw", [
        {"mode": "threads"},
        {"replicas": 0},
        {"eject_after": 0},
        {"probe_s": 0.0},
        {"ensemble_max_members": 0},
        {"ensemble_sigma": -0.1},
        {"canary_weight": 0.0},
        {"canary_weight": 1.5},
        {"canary_min_obs": 0},
    ])
    def test_validation_rejects(self, kw):
        with pytest.raises(ValueError):
            FleetConfig(**kw)

    def test_modes_vocabulary(self):
        assert FLEET_MODES == ("inprocess", "subprocess")


class TestFleetIdentity:
    def test_absent_outside_a_fleet(self):
        assert fleet_identity(environ={}) is None

    def test_full_identity(self):
        ident = fleet_identity(environ={
            "DDR_FLEET_GROUP": "prod",
            "DDR_FLEET_REPLICA": "3",
            "DDR_FLEET_ROUTER": "local:123",
        })
        assert ident == {"group": "prod", "replica": 3, "router": "local:123"}

    def test_non_integer_replica_kept_verbatim(self):
        ident = fleet_identity(environ={
            "DDR_FLEET_GROUP": "g", "DDR_FLEET_REPLICA": "blue",
        })
        assert ident["replica"] == "blue"
