"""Canary state machine: deterministic arm routing, auto-promotion on skill
parity, auto-rollback on skill regression and watchdog degradation, and the
``canary`` event emitted on every transition."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.fleet.canary import STATES, CanaryController, _arm_fraction
from ddr_tpu.fleet.config import FleetConfig
from tests.fleet.conftest import events_of


def _cfg(**kw) -> FleetConfig:
    kw.setdefault("canary_min_obs", 2)
    kw.setdefault("canary_weight", 1.0)
    return FleetConfig.from_env(environ={}, **kw)


def _controller(service_factory, **kw) -> CanaryController:
    svc = service_factory(candidate=True)
    return CanaryController(svc, fleet_cfg=_cfg(), **kw)


def _obs_like(svc) -> np.ndarray:
    return np.asarray(
        svc.forecast(network="default", t0=0, request_id="canary-ref")["runoff"]
    )


class TestRouting:
    def test_arm_fraction_is_stable(self):
        assert _arm_fraction("req-1") == _arm_fraction("req-1")
        assert 0.0 <= _arm_fraction("req-1") < 1.0

    def test_states_vocabulary(self):
        assert STATES == ("shadow", "canary", "promoted", "rolled-back")

    def test_shadow_serves_stable(self, service_factory):
        c = _controller(service_factory)
        assert c.arm_for("any-id") == "stable"

    def test_validation(self, service_factory):
        svc = service_factory(candidate=True)
        with pytest.raises(ValueError, match="different"):
            CanaryController(svc, stable="default", candidate="default")
        with pytest.raises(KeyError):
            CanaryController(svc, candidate="missing")


class TestPromotion:
    def test_skill_par_candidate_promotes(self, service_factory, recorder):
        """The happy path: shadow evidence at parity -> canary, confirmation
        window under weighted traffic -> promoted; one canary event per edge."""
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        states_seen = []
        for i in range(8):
            out = c.handle(
                network="default", t0=0, request_id=f"p-{i}", observations=obs
            )
            states_seen.append(out["canary_state"])
            if out["canary_state"] == "promoted":
                break
        assert c.state == "promoted"
        assert c.arm_for("whatever") == "candidate"
        reasons = [t["reason"] for t in c.status()["transitions"]]
        assert reasons == ["skill-parity", "skill-confirmed"]
        events = events_of(recorder, "canary")
        assert [(e["state_from"], e["state_to"]) for e in events] == [
            ("shadow", "canary"), ("canary", "promoted"),
        ]
        for e in events:  # every transition carries its per-arm evidence
            assert e["stable_obs"] >= 2 and e["candidate_obs"] >= 2
            assert e["candidate_nse"] is not None

    def test_promotion_needs_fresh_canary_evidence(self, service_factory):
        """Shadow evidence alone never promotes: entering canary snapshots the
        candidate's count and demands min_obs MORE under weighted traffic."""
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        for i in range(2):
            c.handle(network="default", t0=0, request_id=f"f-{i}", observations=obs)
        assert c.state == "canary"  # parity reached, not yet promoted
        assert c.evaluate() == "canary"  # re-evaluating without traffic: no edge


class TestRollback:
    def test_skill_regression_rolls_back(self, service_factory, recorder):
        """A candidate scoring far below stable on the same observations must
        roll back from shadow — before ever taking user traffic."""
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        good = obs
        bad = obs + 10.0 * (1.0 + np.abs(obs))  # hopeless predictions
        for _ in range(2):
            c.observe("stable", good, obs)
            c.observe("candidate", bad, obs)
        assert c.evaluate() == "rolled-back"
        assert c.arm_for("any") == "stable"
        (event,) = events_of(recorder, "canary")
        assert event["reason"] == "skill-regression"
        assert event["candidate_nse"] < event["stable_nse"]

    def test_watchdog_degradation_rolls_back(self, service_factory, monkeypatch):
        c = _controller(service_factory)
        monkeypatch.setattr(
            type(c._svc.watchdog), "degraded", property(lambda self: True)
        )
        assert c.evaluate() == "rolled-back"
        assert c.status()["transitions"][0]["reason"] == "watchdog-degraded"

    def test_terminal_states_are_sticky(self, service_factory):
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        bad = obs + 10.0 * (1.0 + np.abs(obs))
        for _ in range(2):
            c.observe("stable", obs, obs)
            c.observe("candidate", bad, obs)
        assert c.evaluate() == "rolled-back"
        # more (now excellent) evidence cannot resurrect a rolled-back canary
        for _ in range(4):
            c.observe("candidate", obs, obs)
        assert c.evaluate() == "rolled-back"
        assert len(c.status()["transitions"]) == 1


class TestShadowFailures:
    def test_shadow_failure_keeps_stable_answer(self, service_factory,
                                                monkeypatch):
        """Shadow traffic is invisible to the caller INCLUDING its failures:
        a shed/rejected shadow forecast costs the candidate one observation,
        never the stable answer the caller already earned."""
        from ddr_tpu.serving.batcher import QueueFullError

        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        real_forecast = c._svc.forecast

        def overloaded(**kw):
            if str(kw.get("request_id", "")).endswith("-shadow"):
                raise QueueFullError("queue at capacity; request rejected")
            return real_forecast(**kw)

        monkeypatch.setattr(c._svc, "forecast", overloaded)
        out = c.handle(
            network="default", t0=0, request_id="sf-1", observations=obs
        )
        assert out["arm"] == "stable" and "runoff" in out
        status = c.status()
        assert status["shadow_failures"] == 1
        assert status["arms"]["stable"]["observations"] == 1
        assert status["arms"]["candidate"]["observations"] == 0
        counter = c._svc.metrics.get("ddr_canary_shadow_failures_total")
        assert counter.value(model="candidate") == 1.0


class TestWeightedSplit:
    def test_canary_weight_splits_traffic_deterministically(self, service_factory):
        svc = service_factory(candidate=True)
        c = CanaryController(
            svc, fleet_cfg=FleetConfig.from_env(
                environ={}, canary_weight=0.5, canary_min_obs=2
            )
        )
        obs = _obs_like(svc)
        for i in range(2):  # parity -> canary
            c.handle(network="default", t0=0, request_id=f"w-{i}", observations=obs)
        assert c.state == "canary"
        arms = {rid: c.arm_for(rid) for rid in (f"split-{i}" for i in range(64))}
        assert set(arms.values()) == {"stable", "candidate"}  # both arms live
        assert all(c.arm_for(rid) == arm for rid, arm in arms.items())  # sticky


class TestVerificationEvidence:
    """The CRPS evidence path: the `DDR_CANARY_MIN_SAMPLES` floor gates every
    evidence-based transition, and once both arms hold enough MATCHED
    verification samples the decision compares proper scores, not NSE."""

    def _ensemble_evidence(self, c, obs, sharp=True, arm="candidate"):
        # (E, T, G) members around the truth: sharp = tight, degraded = biased
        rng = np.random.default_rng(0 if sharp else 1)
        spread = 0.01 if sharp else 0.0
        bias = 1.0 if sharp else 2.0
        members = obs[None, :, :] * bias + rng.normal(
            0.0, spread, size=(4,) + obs.shape
        )
        c.observe_ensemble(arm, members, obs)

    def test_min_samples_floor_holds_transitions(self, service_factory):
        svc = service_factory(candidate=True)
        c = CanaryController(svc, fleet_cfg=_cfg(), min_samples=1000)
        obs = _obs_like(svc)
        for arm in ("stable", "candidate"):
            c.observe(arm, obs, obs)
            c.observe(arm, obs, obs)  # 64 samples/arm: parity, but < floor
        assert c.evaluate() == "shadow"
        assert c.status()["min_samples"] == 1000
        # the identical evidence clears a realistic floor immediately
        c2 = CanaryController(svc, fleet_cfg=_cfg(), min_samples=8)
        for arm in ("stable", "candidate"):
            c2.observe(arm, obs, obs)
            c2.observe(arm, obs, obs)
        assert c2.evaluate() == "canary"

    def test_watchdog_rollback_ignores_sample_floor(self, service_factory,
                                                    monkeypatch):
        svc = service_factory(candidate=True)
        c = CanaryController(svc, fleet_cfg=_cfg(), min_samples=1000)
        monkeypatch.setattr(type(svc.watchdog), "degraded", property(
            lambda self: True
        ))
        assert c.evaluate() == "rolled-back"  # safety beats statistics

    def test_crps_regression_rolls_back(self, service_factory, recorder):
        svc = service_factory(candidate=True)
        c = CanaryController(svc, fleet_cfg=_cfg(), min_samples=8)
        obs = _obs_like(svc)
        self._ensemble_evidence(c, obs, sharp=True, arm="stable")
        self._ensemble_evidence(c, obs, sharp=False, arm="candidate")
        for arm in ("stable", "candidate"):  # satisfy the min_obs cadence
            c.observe(arm, obs, obs)
        assert c.evaluate() == "rolled-back"
        (t,) = c.status()["transitions"]
        assert t["reason"] == "crps-regression"
        assert t["candidate_crps"] > t["stable_crps"]
        assert t["stable_matched"] == t["candidate_matched"] == obs.size
        (e,) = events_of(recorder, "canary")
        assert e["reason"] == "crps-regression"
        assert e["candidate_crps"] is not None

    def test_crps_parity_promotes_with_crps_reasons(self, service_factory):
        svc = service_factory(candidate=True)
        c = CanaryController(svc, fleet_cfg=_cfg(), min_samples=8)
        obs = _obs_like(svc)
        for arm in ("stable", "candidate"):
            self._ensemble_evidence(c, obs, sharp=True, arm=arm)
            c.observe(arm, obs, obs)
        assert c.evaluate() == "canary"
        # fresh canary-state evidence for the confirmation window
        self._ensemble_evidence(c, obs, sharp=True, arm="candidate")
        self._ensemble_evidence(c, obs, sharp=True, arm="candidate")
        assert c.evaluate() == "promoted"
        reasons = [t["reason"] for t in c.status()["transitions"]]
        assert reasons == ["crps-parity", "crps-confirmed"]

    def test_status_reports_per_arm_matched_counts(self, service_factory):
        svc = service_factory(candidate=True)
        c = CanaryController(svc, fleet_cfg=_cfg())
        obs = _obs_like(svc)
        self._ensemble_evidence(c, obs, arm="candidate")
        arms = c.status()["arms"]
        assert arms["candidate"]["matched_samples"] == obs.size
        assert arms["candidate"]["observations"] == 1  # the ensemble join
        assert arms["stable"]["matched_samples"] == 0
        assert arms["stable"]["crps_mean"] is None
