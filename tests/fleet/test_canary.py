"""Canary state machine: deterministic arm routing, auto-promotion on skill
parity, auto-rollback on skill regression and watchdog degradation, and the
``canary`` event emitted on every transition."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.fleet.canary import STATES, CanaryController, _arm_fraction
from ddr_tpu.fleet.config import FleetConfig
from tests.fleet.conftest import events_of


def _cfg(**kw) -> FleetConfig:
    kw.setdefault("canary_min_obs", 2)
    kw.setdefault("canary_weight", 1.0)
    return FleetConfig.from_env(environ={}, **kw)


def _controller(service_factory, **kw) -> CanaryController:
    svc = service_factory(candidate=True)
    return CanaryController(svc, fleet_cfg=_cfg(), **kw)


def _obs_like(svc) -> np.ndarray:
    return np.asarray(
        svc.forecast(network="default", t0=0, request_id="canary-ref")["runoff"]
    )


class TestRouting:
    def test_arm_fraction_is_stable(self):
        assert _arm_fraction("req-1") == _arm_fraction("req-1")
        assert 0.0 <= _arm_fraction("req-1") < 1.0

    def test_states_vocabulary(self):
        assert STATES == ("shadow", "canary", "promoted", "rolled-back")

    def test_shadow_serves_stable(self, service_factory):
        c = _controller(service_factory)
        assert c.arm_for("any-id") == "stable"

    def test_validation(self, service_factory):
        svc = service_factory(candidate=True)
        with pytest.raises(ValueError, match="different"):
            CanaryController(svc, stable="default", candidate="default")
        with pytest.raises(KeyError):
            CanaryController(svc, candidate="missing")


class TestPromotion:
    def test_skill_par_candidate_promotes(self, service_factory, recorder):
        """The happy path: shadow evidence at parity -> canary, confirmation
        window under weighted traffic -> promoted; one canary event per edge."""
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        states_seen = []
        for i in range(8):
            out = c.handle(
                network="default", t0=0, request_id=f"p-{i}", observations=obs
            )
            states_seen.append(out["canary_state"])
            if out["canary_state"] == "promoted":
                break
        assert c.state == "promoted"
        assert c.arm_for("whatever") == "candidate"
        reasons = [t["reason"] for t in c.status()["transitions"]]
        assert reasons == ["skill-parity", "skill-confirmed"]
        events = events_of(recorder, "canary")
        assert [(e["state_from"], e["state_to"]) for e in events] == [
            ("shadow", "canary"), ("canary", "promoted"),
        ]
        for e in events:  # every transition carries its per-arm evidence
            assert e["stable_obs"] >= 2 and e["candidate_obs"] >= 2
            assert e["candidate_nse"] is not None

    def test_promotion_needs_fresh_canary_evidence(self, service_factory):
        """Shadow evidence alone never promotes: entering canary snapshots the
        candidate's count and demands min_obs MORE under weighted traffic."""
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        for i in range(2):
            c.handle(network="default", t0=0, request_id=f"f-{i}", observations=obs)
        assert c.state == "canary"  # parity reached, not yet promoted
        assert c.evaluate() == "canary"  # re-evaluating without traffic: no edge


class TestRollback:
    def test_skill_regression_rolls_back(self, service_factory, recorder):
        """A candidate scoring far below stable on the same observations must
        roll back from shadow — before ever taking user traffic."""
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        good = obs
        bad = obs + 10.0 * (1.0 + np.abs(obs))  # hopeless predictions
        for _ in range(2):
            c.observe("stable", good, obs)
            c.observe("candidate", bad, obs)
        assert c.evaluate() == "rolled-back"
        assert c.arm_for("any") == "stable"
        (event,) = events_of(recorder, "canary")
        assert event["reason"] == "skill-regression"
        assert event["candidate_nse"] < event["stable_nse"]

    def test_watchdog_degradation_rolls_back(self, service_factory, monkeypatch):
        c = _controller(service_factory)
        monkeypatch.setattr(
            type(c._svc.watchdog), "degraded", property(lambda self: True)
        )
        assert c.evaluate() == "rolled-back"
        assert c.status()["transitions"][0]["reason"] == "watchdog-degraded"

    def test_terminal_states_are_sticky(self, service_factory):
        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        bad = obs + 10.0 * (1.0 + np.abs(obs))
        for _ in range(2):
            c.observe("stable", obs, obs)
            c.observe("candidate", bad, obs)
        assert c.evaluate() == "rolled-back"
        # more (now excellent) evidence cannot resurrect a rolled-back canary
        for _ in range(4):
            c.observe("candidate", obs, obs)
        assert c.evaluate() == "rolled-back"
        assert len(c.status()["transitions"]) == 1


class TestShadowFailures:
    def test_shadow_failure_keeps_stable_answer(self, service_factory,
                                                monkeypatch):
        """Shadow traffic is invisible to the caller INCLUDING its failures:
        a shed/rejected shadow forecast costs the candidate one observation,
        never the stable answer the caller already earned."""
        from ddr_tpu.serving.batcher import QueueFullError

        c = _controller(service_factory)
        obs = _obs_like(c._svc)
        real_forecast = c._svc.forecast

        def overloaded(**kw):
            if str(kw.get("request_id", "")).endswith("-shadow"):
                raise QueueFullError("queue at capacity; request rejected")
            return real_forecast(**kw)

        monkeypatch.setattr(c._svc, "forecast", overloaded)
        out = c.handle(
            network="default", t0=0, request_id="sf-1", observations=obs
        )
        assert out["arm"] == "stable" and "runoff" in out
        status = c.status()
        assert status["shadow_failures"] == 1
        assert status["arms"]["stable"]["observations"] == 1
        assert status["arms"]["candidate"]["observations"] == 0
        counter = c._svc.metrics.get("ddr_canary_shadow_failures_total")
        assert counter.value(model="candidate") == 1.0


class TestWeightedSplit:
    def test_canary_weight_splits_traffic_deterministically(self, service_factory):
        svc = service_factory(candidate=True)
        c = CanaryController(
            svc, fleet_cfg=FleetConfig.from_env(
                environ={}, canary_weight=0.5, canary_min_obs=2
            )
        )
        obs = _obs_like(svc)
        for i in range(2):  # parity -> canary
            c.handle(network="default", t0=0, request_id=f"w-{i}", observations=obs)
        assert c.state == "canary"
        arms = {rid: c.arm_for(rid) for rid in (f"split-{i}" for i in range(64))}
        assert set(arms.values()) == {"stable", "candidate"}  # both arms live
        assert all(c.arm_for(rid) == arm for rid, arm in arms.items())  # sticky
