"""Router dispatch/ejection semantics on fake replicas — no jax, no service:
the router is duck-typed, so these pin the health state machine in isolation."""

from __future__ import annotations

import time

import pytest

from ddr_tpu.fleet.router import NoHealthyReplicaError, Router


class FakeReplica:
    """Scriptable stand-in: set ``up=False`` for transport death, ``depth``
    for queue pressure, ``app_error`` to raise an application error."""

    def __init__(self, index: int, depth: int = 0):
        self.index = index
        self.name = f"r{index}"
        self.url = None
        self.up = True
        self.queue_depth = depth
        self.app_error: Exception | None = None
        self.calls = 0

    def ready(self) -> bool:
        return self.up

    def depth(self) -> int:
        if not self.up:
            raise ConnectionError(f"{self.name} is down")
        return self.queue_depth

    def forecast(self, **kw) -> dict:
        self.calls += 1
        if not self.up:
            raise ConnectionError(f"{self.name} is down")
        if self.app_error is not None:
            raise self.app_error
        return {"replica": self.name, **kw}

    def ensemble(self, **kw) -> dict:
        return self.forecast(**kw)


def make_router(*replicas, probe_s: float = 30.0, eject_after: int = 2):
    """probe_s defaults long so dispatch-path behavior is tested without the
    prober racing the assertions."""
    return Router(list(replicas), probe_s=probe_s, eject_after=eject_after)


class TestDispatch:
    def test_picks_least_loaded(self):
        a, b = FakeReplica(0, depth=5), FakeReplica(1, depth=0)
        r = make_router(a, b)
        try:
            # the prober has not run: seed probed depth by hand
            r._probed_depth["r0"], r._probed_depth["r1"] = 5, 0
            out = r.forecast(x=1)
            assert out["replica"] == "r1"
            assert b.calls == 1 and a.calls == 0
        finally:
            r.close()

    def test_ties_break_by_index(self):
        a, b = FakeReplica(0), FakeReplica(1)
        r = make_router(a, b)
        try:
            assert r.forecast()["replica"] == "r0"
        finally:
            r.close()

    def test_transport_failure_reroutes_and_ejects(self):
        a, b = FakeReplica(0), FakeReplica(1)
        a.up = False
        r = make_router(a, b, eject_after=1)
        try:
            out = r.forecast()
            assert out["replica"] == "r1"  # caller never saw the death
            assert r.healthy() == ["r1"]
        finally:
            r.close()

    def test_application_errors_propagate(self):
        a, b = FakeReplica(0), FakeReplica(1)
        a.app_error = ValueError("unknown network 'x'")
        r = make_router(a, b)
        try:
            with pytest.raises(ValueError, match="unknown network"):
                r.forecast()
            # an application error is the caller's answer, not health signal
            assert r.healthy() == ["r0", "r1"]
        finally:
            r.close()

    def test_all_dead_raises_unroutable(self):
        a, b = FakeReplica(0), FakeReplica(1)
        a.up = b.up = False
        r = make_router(a, b, eject_after=1)
        try:
            with pytest.raises(NoHealthyReplicaError):
                r.forecast()
            assert r.status()["unroutable_errors"] == 1
        finally:
            r.close()

    def test_ejection_needs_consecutive_failures(self):
        a, b = FakeReplica(0), FakeReplica(1)
        r = make_router(a, b, eject_after=2)
        try:
            a.up = False
            r.forecast()  # failure 1 -> rerouted, not ejected yet
            assert r.healthy() == ["r0", "r1"]
            a.up = True
            r._probed_depth["r0"] = 0  # make r0 preferred again
            r.forecast()  # success resets the streak
            a.up = False
            r.forecast()
            assert r.healthy() == ["r0", "r1"]  # streak is 1 again, not 2
        finally:
            r.close()


class TestProber:
    def test_probe_ejects_and_readmits(self):
        a, b = FakeReplica(0), FakeReplica(1)
        r = make_router(a, b, probe_s=0.02, eject_after=2)
        try:
            a.up = False
            deadline = time.monotonic() + 5.0
            while "r0" in r.healthy() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert r.healthy() == ["r1"]
            a.up = True
            deadline = time.monotonic() + 5.0
            while "r0" not in r.healthy() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert r.healthy() == ["r0", "r1"]
            row = r.status()["replicas"][0]
            assert row["consecutive_failures"] == 0
        finally:
            r.close()

    def test_probe_updates_depth(self):
        a = FakeReplica(0, depth=7)
        r = make_router(a, probe_s=0.02)
        try:
            deadline = time.monotonic() + 5.0
            while (
                r.status()["replicas"][0]["last_probed_depth"] != 7
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert r.status()["replicas"][0]["last_probed_depth"] == 7
        finally:
            r.close()


class TestLifecycle:
    def test_needs_at_least_one_replica(self):
        with pytest.raises(ValueError):
            Router([])

    def test_status_shape_and_dispatch_counts(self):
        a = FakeReplica(0)
        r = make_router(a)
        try:
            r.forecast()
            r.ensemble()
            row = r.status()["replicas"][0]
            assert row["name"] == "r0"
            assert row["dispatched"] == 2
            assert row["inflight"] == 0
            assert row["ejected"] is False
        finally:
            r.close()

    def test_close_stops_prober(self):
        r = make_router(FakeReplica(0), probe_s=0.02)
        r.close()
        assert not r._prober.is_alive()


class TestSentinelWiring:
    """The fleet-scoped performance sentinel: one queue-depth detector per
    replica, fed at probe cadence, surfaced on status()."""

    def test_status_carries_per_replica_depth_detectors(self, monkeypatch):
        monkeypatch.setenv("DDR_SENTINEL_WARMUP", "3")
        a, b = FakeReplica(0, depth=1), FakeReplica(1, depth=1)
        r = make_router(a, b, probe_s=0.02)
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                anomalies = r.status()["anomalies"]
                if anomalies and {"r0.queue_depth", "r1.queue_depth"} <= set(
                    anomalies["signals"]
                ):
                    break
                time.sleep(0.01)
            anomalies = r.status()["anomalies"]
            assert anomalies is not None and anomalies["scope"] == "fleet"
            assert {"r0.queue_depth", "r1.queue_depth"} <= set(
                anomalies["signals"]
            )
        finally:
            r.close()

    def test_sentinel_disabled_yields_none(self, monkeypatch):
        monkeypatch.setenv("DDR_SENTINEL_ENABLED", "0")
        r = make_router(FakeReplica(0))
        try:
            assert r.status()["anomalies"] is None
        finally:
            r.close()
