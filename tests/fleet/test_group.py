"""ReplicaGroup lifecycle: in-process boot behind the router, federation
publish/restore, the kill/restart chaos surface, and construction guards."""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from ddr_tpu.fleet.config import FleetConfig
from ddr_tpu.fleet.group import ReplicaGroup


def _cfg(**kw) -> FleetConfig:
    kw.setdefault("replicas", 2)
    kw.setdefault("mode", "inprocess")
    kw.setdefault("probe_s", 0.05)
    return FleetConfig.from_env(environ={}, **kw)


def _wait(predicate, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


class TestConstruction:
    def test_inprocess_requires_builder(self):
        with pytest.raises(ValueError, match="builder"):
            ReplicaGroup(_cfg())

    def test_subprocess_requires_serve_args(self):
        with pytest.raises(ValueError, match="serve_args"):
            ReplicaGroup(_cfg(mode="subprocess"))

    def test_dispatch_before_boot_raises(self, service_factory):
        group = ReplicaGroup(_cfg(replicas=1), builder=lambda i: service_factory())
        with pytest.raises(RuntimeError, match="boot"):
            group.forecast(network="default", t0=0)


class TestInProcessGroup:
    def test_boot_dispatch_kill_restart(self, service_factory, tmp_path):
        group = ReplicaGroup(
            _cfg(), builder=lambda i: service_factory(), workdir=tmp_path
        )
        group.boot()
        try:
            for i in range(4):
                out = group.forecast(network="default", t0=i, request_id=f"g-{i}")
                assert "runoff" in out
            ens = group.ensemble(network="default", t0=0, members=3)
            assert len(ens["percentiles"]) == 3

            group.kill_replica(1)
            assert _wait(lambda: group.router.healthy() == ["fleet-r0"])
            # traffic keeps flowing through the survivor
            group.forecast(network="default", t0=0, request_id="g-post")
            group.restart_replica(1)
            assert _wait(
                lambda: group.router.healthy() == ["fleet-r0", "fleet-r1"]
            )
            desc = group.describe()
            assert desc["mode"] == "inprocess"
            assert desc["replicas"] == 2
        finally:
            group.close()

    def test_no_federation_without_http_fronts(self, service_factory, tmp_path,
                                               monkeypatch):
        monkeypatch.delenv("DDR_FEDERATE_REPLICAS", raising=False)
        group = ReplicaGroup(
            _cfg(replicas=1), builder=lambda i: service_factory(),
            workdir=tmp_path,
        )
        group.boot()
        try:
            # in-process replicas with no HTTP front have no scrape URL:
            # nothing to federate, env stays untouched
            assert "DDR_FEDERATE_REPLICAS" not in os.environ
        finally:
            group.close()

    def test_ensemble_through_http_replica(self, service_factory):
        """The subprocess-group dispatch shape: an ensemble request routed to
        an :class:`HttpReplica` must cross the wire as the scalar body plus an
        ``"ensemble"`` object and come back as (P, T, G) percentile bands — a
        scalar (T, G) response here is the silent-downgrade bug."""
        from ddr_tpu.fleet.router import HttpReplica, Router
        from ddr_tpu.serving.http_api import serve_http

        svc = service_factory()
        server = serve_http(svc, host="127.0.0.1", port=0)
        router = None
        try:
            router = Router([HttpReplica(server.url, 0)], probe_s=30.0)
            out = router.ensemble(
                network="default", t0=0, members=3,
                percentiles=[10, 50, 90], seed=7,
            )
            assert out["members"] == 3
            assert out["percentiles"] == [10.0, 50.0, 90.0]
            runoff = np.asarray(out["runoff"])
            assert runoff.ndim == 3 and runoff.shape[0] == 3  # (P, T, G)
            # numeric parity with the in-process path on the same request id
            local = svc.ensemble_forecast(
                network="default", t0=0, members=3,
                percentiles=[10, 50, 90], seed=7,
                request_id=out["request_id"],
            )
            np.testing.assert_allclose(
                runoff, np.asarray(local["runoff"]), rtol=1e-6
            )
        finally:
            if router is not None:
                router.close()
            server.shutdown()

    def test_http_fronts_publish_and_restore_federation(
        self, service_factory, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("DDR_FEDERATE_REPLICAS", "prior=http://x/metrics")
        group = ReplicaGroup(
            _cfg(replicas=1), builder=lambda i: service_factory(),
            workdir=tmp_path, http=True,
        )
        group.boot()
        try:
            published = os.environ["DDR_FEDERATE_REPLICAS"]
            assert published != "prior=http://x/metrics"
            assert published.startswith("fleet-r0=http://")
            assert published.endswith("/metrics")
            assert group.replicas[0].url is not None
        finally:
            group.close()
        # the pre-boot federation view is restored on close
        assert os.environ["DDR_FEDERATE_REPLICAS"] == "prior=http://x/metrics"
