"""Fleet-tier fixtures: synthetic-basin services (reusing the serving-layer
helpers) plus an active telemetry recorder for canary-event read-back."""

from __future__ import annotations

import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.observability import Recorder, activate, deactivate
from ddr_tpu.serving import ForecastService, ServeConfig
from tests.serving.conftest import events_of, make_cfg  # noqa: F401 (re-export)


@pytest.fixture
def service_factory(tmp_path):
    """Build a warmed ForecastService over a fresh synthetic basin; closed at
    teardown regardless of test outcome. ``candidate=True`` additionally
    registers the default model under the name ``"candidate"`` (the canary
    tests' second arm), warmed alongside the stable pair."""
    created: list[ForecastService] = []

    def make(
        n_segments: int = 24,
        horizon: int = 8,
        n_days: int = 3,
        warmup: bool = True,
        candidate: bool = False,
        **serve_kw,
    ) -> ForecastService:
        from ddr_tpu.scripts.common import build_kan, kan_arch

        cfg = make_cfg(tmp_path)
        basin = make_basin(n_segments=n_segments, n_gauges=4, n_days=n_days, seed=1)
        kan_model, params = build_kan(cfg)
        serve_kw.setdefault("max_batch", 4)
        serve_kw.setdefault("batch_wait_s", 0.002)
        svc = ForecastService(cfg, ServeConfig(horizon_hours=horizon, **serve_kw))
        svc.register_network("default", basin.routing_data, forcing=basin.q_prime)
        svc.register_model("default", kan_model, params, arch=kan_arch(cfg))
        if candidate:
            svc.register_model("candidate", kan_model, params, arch=kan_arch(cfg))
        if warmup:
            svc.warmup()
        created.append(svc)
        return svc

    yield make
    for svc in created:
        svc.close(drain=False)


@pytest.fixture
def recorder(tmp_path):
    """An ACTIVE Recorder; yields the log path for read-back via events_of."""
    path = tmp_path / "run_log.fleet.jsonl"
    rec = Recorder(path)
    activate(rec)
    yield path
    deactivate(rec)
    rec.close()
