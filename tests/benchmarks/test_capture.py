"""Session-capture resume contract: errored entries re-run, successes skip."""

import json

from ddr_tpu.benchmarks.capture import PLAN, _key, load_done


def test_load_done_skips_errors(tmp_path):
    session = tmp_path / "s.jsonl"
    lines = [
        {"_key": "ablate:65536,240,chunked,1024", "rts": 1.0},
        {"_key": "ablate:262144,240,stacked,2048", "error": "timed out after 2400s"},
        {"_key": "trainbench:262144,240,2048", "rts": 2.0},
        "not json at all",
        {"no_key": True},
    ]
    session.write_text(
        "\n".join(json.dumps(x) if isinstance(x, dict) else x for x in lines) + "\n"
    )
    done = load_done(str(session))
    assert done == {"ablate:65536,240,chunked,1024", "trainbench:262144,240,2048"}


def test_load_done_missing_file(tmp_path):
    assert load_done(str(tmp_path / "absent.jsonl")) == set()


def test_plan_keys_unique():
    keys = [_key(m, a) for m, a, _ in PLAN]
    assert len(keys) == len(set(keys))
    # the plan covers both deep engines, both modes, and the train step
    joined = " ".join(keys)
    assert "stacked,2048,--grad" in joined
    assert "chunked,2048,--grad" in joined
    assert "--no-remat" in joined
    assert any(k.startswith("trainbench:") for k in keys)


def test_ablate_rejects_unknown_flags(monkeypatch, capsys):
    """A typo'd flag must exit non-zero (capture records an error row), never
    silently measure the default variant under an official-looking JSON."""
    import sys

    import pytest as _pytest

    from ddr_tpu.benchmarks import ablate

    monkeypatch.setattr(sys, "argv", ["ablate", "8", "2", "rect", "--gard"])
    with _pytest.raises(SystemExit) as e:
        ablate.main()
    assert e.value.code == 2
