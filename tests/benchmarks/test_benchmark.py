"""Benchmark harness tests: config layouts, headwater masking, ΣQ' alignment, and the
end-to-end two-phase run on the synthetic dataset (the reference exercises the true
build→route pipeline on the RAPID Sandbox the same way,
/root/reference/tests/benchmarks/)."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.benchmarks import (
    BenchmarkConfig,
    benchmark,
    build_headwater_mask,
    load_summed_q_prime,
    validate_benchmark_config,
)
from ddr_tpu.geodatazoo.synthetic import make_basin
from ddr_tpu.io import zarrlite

N_ATTRS = 10


def _raw_cfg(tmp_path, **extra):
    raw = {
        "name": "bench_test",
        "geodataset": "synthetic",
        "mode": "testing",
        "kan": {"input_var_names": [f"a{i}" for i in range(N_ATTRS)]},
        "experiment": {"start_time": "1981/10/01", "end_time": "1981/10/10", "warmup": 1},
        "params": {"save_path": str(tmp_path)},
    }
    raw.update(extra)
    return raw


class TestConfig:
    def test_flat_layout(self, tmp_path):
        cfg = validate_benchmark_config(
            _raw_cfg(tmp_path, lti={"irf_fn": "hayami", "max_delay": 50})
        )
        assert isinstance(cfg, BenchmarkConfig)
        assert cfg.ddr.name == "bench_test"
        assert cfg.lti.irf_fn == "hayami"
        assert cfg.lti.max_delay == 50

    def test_legacy_diffroute_key(self, tmp_path):
        cfg = validate_benchmark_config(
            _raw_cfg(tmp_path, diffroute={"irf_fn": "pure_lag"})
        )
        assert cfg.lti.irf_fn == "pure_lag"

    def test_bad_irf_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="irf_fn"):
            validate_benchmark_config(_raw_cfg(tmp_path, lti={"irf_fn": "quantum"}))

    def test_summed_q_prime_path(self, tmp_path):
        cfg = validate_benchmark_config(
            _raw_cfg(tmp_path, summed_q_prime=str(tmp_path / "sqp.zarr"))
        )
        assert cfg.summed_q_prime is not None


class TestHeadwaterMask:
    def test_synthetic_gauges_have_upstream(self):
        basin = make_basin(n_segments=32, n_gauges=4, n_days=3, seed=0)
        mask = build_headwater_mask(basin.routing_data)
        assert mask.shape == (4,)
        assert mask.any()

    def test_headwater_gauge_masked(self):
        basin = make_basin(n_segments=32, n_gauges=2, n_days=3, seed=0)
        rd = basin.routing_data
        # Point one gauge's outflow at segment 0: a source reach with no upstream.
        rd.outflow_idx = [rd.outflow_idx[0], np.array([0])]
        mask = build_headwater_mask(rd)
        assert mask[0] and not mask[1]


class TestSummedQPrime:
    def _store(self, tmp_path, gage_ids, preds):
        root = zarrlite.create_group(tmp_path / "sqp.zarr")
        root.create_array("predictions", preds.astype(np.float32))
        root.attrs.update({"gage_ids": [str(g) for g in gage_ids]})
        return tmp_path / "sqp.zarr"

    def test_alignment_and_metrics(self, tmp_path, rng):
        preds = rng.uniform(1, 5, (3, 20)).astype(np.float32)
        path = self._store(tmp_path, ["0001", "0002", "0003"], preds)
        daily_obs = rng.uniform(1, 5, (2, 20))
        out = load_summed_q_prime(path, np.array(["0003", "0001"]), daily_obs, warmup=2)
        assert out is not None
        metrics, aligned, common = out
        assert common.all()
        np.testing.assert_allclose(aligned, preds[[2, 0]])
        assert np.asarray(metrics.nse).shape == (2,)

    def test_missing_store_returns_none(self, tmp_path):
        assert (
            load_summed_q_prime(tmp_path / "nope.zarr", np.array(["1"]), np.ones((1, 5)), 0)
            is None
        )

    def test_disjoint_gauges_returns_none(self, tmp_path, rng):
        path = self._store(tmp_path, ["0009"], rng.uniform(1, 2, (1, 5)))
        assert load_summed_q_prime(path, np.array(["0001"]), np.ones((1, 5)), 0) is None


class TestEndToEnd:
    @pytest.mark.slow
    def test_two_phase_benchmark_on_synthetic(self, tmp_path):
        bench_cfg = validate_benchmark_config(
            _raw_cfg(tmp_path, lti={"irf_fn": "muskingum", "max_delay": 48})
        )
        results = benchmark(bench_cfg)
        assert set(results) == {"mc", "lti"}
        for m in results.values():
            nse = np.asarray(m.nse)
            assert np.isfinite(nse).any()
        # Observations are MC-generated (twin experiment): both routers track the
        # inflow-dominated signal, but they are distinct models, not copies.
        mc_nse, lti_nse = np.asarray(results["mc"].nse), np.asarray(results["lti"].nse)
        assert np.nanmedian(mc_nse) > 0.9
        assert not np.allclose(mc_nse, lti_nse)
        out = zarrlite.open_group(tmp_path / "benchmark_results.zarr")
        assert out["mc_predictions"][:].shape == out["observations"][:].shape
        assert (tmp_path / "plots" / "benchmark_nse_cdf.png").exists()
        assert (tmp_path / "plots" / "benchmark_nse_box.png").exists()

    def test_lti_disabled(self, tmp_path):
        bench_cfg = validate_benchmark_config(_raw_cfg(tmp_path, lti={"enabled": False}))
        results = benchmark(bench_cfg)
        assert set(results) == {"mc"}

    def test_cli_nested_layout(self, tmp_path):
        import yaml

        from ddr_tpu.benchmarks.benchmark import main

        ddr = _raw_cfg(tmp_path)
        del ddr["mode"]  # main() must default mode inside the nested section
        cfg_path = tmp_path / "nested.yaml"
        cfg_path.write_text(yaml.safe_dump({"ddr": ddr, "lti": {"enabled": False}}))
        assert main([str(cfg_path)]) == 0
