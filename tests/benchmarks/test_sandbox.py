"""RAPID Sandbox golden-fixture parity tests.

The only externally-published ground truth in the test suite: RAPID2's Qout/Qfinal
for the 5-reach Sandbox network (tests/input/Sandbox/README.md). Three layers:

1. Engine round-trip: the Sandbox builds through the real MERIT engine into a
   zarrlite store and loads back with the exact topology.
2. Bit-level parity: our solver + Muskingum coefficients reproduce RAPID2's
   published Qout to float32 storage precision and Qfinal to float64 round-off,
   using RAPID2's discretization (k=9000s, x=0.25, dt=900s, Qext constant per
   3-hour window, output = mean of the 12 window-start states).
3. Full-pipeline route: the physics-based ``route()`` (Manning celerity, not fixed
   k) over the engine-built network tracks the published outlet hydrograph — the
   reference's tolerance-based check (/root/reference/tests/benchmarks/test_diffroute.py:137-183).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.routing.mc import muskingum_coefficients, route
from ddr_tpu.routing.model import prepare_batch
from ddr_tpu.routing.network import build_network
from ddr_tpu.routing.solver import solve_lower_triangular

from .conftest import (
    QEXT_WINDOW,
    RAPID2_REACH_IDS,
    SANDBOX_DT,
    SANDBOX_K,
    SANDBOX_X,
)


@pytest.fixture(scope="session")
def sandbox_network_from_store(sandbox_zarr_path):
    """(RiverNetwork, order) loaded back from the engine-built zarrlite store."""
    from ddr_tpu.engine.core import read_coo_arrays
    from ddr_tpu.io import zarrlite

    root = zarrlite.open_group(sandbox_zarr_path)
    coo, order = read_coo_arrays(root)
    return build_network(coo.row, coo.col, coo.shape[0]), list(order)


class TestEngineRoundTrip:
    def test_store_order_is_topological(self, sandbox_network_from_store):
        _, order = sandbox_network_from_store
        assert sorted(order) == RAPID2_REACH_IDS
        pos = {c: i for i, c in enumerate(order)}
        # 10, 20 drain into 30; 30, 40 drain into 50.
        assert pos[30] > pos[10] and pos[30] > pos[20]
        assert pos[50] > pos[30] and pos[50] > pos[40]

    def test_network_edges_match_connectivity(self, sandbox_network_from_store):
        net, order = sandbox_network_from_store
        pos = {c: i for i, c in enumerate(order)}
        expected = {(pos[10], pos[30]), (pos[20], pos[30]), (pos[30], pos[50]), (pos[40], pos[50])}
        got = set(zip(np.asarray(net.edge_src).tolist(), np.asarray(net.edge_tgt).tolist()))
        assert got == expected
        assert net.n == 5 and net.depth == 2


def _rapid2_recurrence(net, order, qext, qinit):
    """RAPID2's exact discretization through our solver: 12 substeps of 900 s per
    3-hourly Qext window; returns (window-mean Qout, final state) in RAPID2 order."""
    import jax
    from jax import enable_x64

    perm = np.array([RAPID2_REACH_IDS.index(c) for c in order])  # rapid2 -> store order
    inv = np.argsort(perm)
    n_sub = int(QEXT_WINDOW / SANDBOX_DT)

    # RAPID2 computes in float64; match it (scoped, not a global config flip).
    with enable_x64():
        c1, c2, c3, c4 = muskingum_coefficients(
            jnp.full(5, SANDBOX_K, jnp.float64),
            jnp.ones(5, jnp.float64),
            jnp.full(5, SANDBOX_X, jnp.float64),
            dt=SANDBOX_DT,
        )

        @jax.jit
        def run(q0, qe_windows):
            def substep(q, _, qe):
                b = c2 * net.upstream_sum(q) + c3 * q + c4 * qe
                return solve_lower_triangular(net, c1, b), q  # emit window-start state

            def window(q, qe):
                q_next, starts = jax.lax.scan(
                    lambda q, x: substep(q, x, qe), q, None, length=n_sub
                )
                return q_next, starts.mean(axis=0)  # RAPID2 writes the window mean

            return jax.lax.scan(window, q0, qe_windows)

        q_final, qout = run(
            jnp.asarray(qinit[perm], jnp.float64), jnp.asarray(qext[:, perm], jnp.float64)
        )
        return np.asarray(qout)[:, inv], np.asarray(q_final)[inv]


@pytest.fixture(scope="session")
def rapid2_recurrence_result(sandbox_network_from_store, sandbox_qext, sandbox_qinit):
    net, order = sandbox_network_from_store
    return _rapid2_recurrence(net, order, sandbox_qext, sandbox_qinit)


class TestRapid2Parity:
    def test_qout_bit_parity(self, rapid2_recurrence_result, sandbox_expected_qout):
        qout, _ = rapid2_recurrence_result
        rel = np.max(np.abs(qout - sandbox_expected_qout) / (np.abs(sandbox_expected_qout) + 1e-6))
        # Published Qout is float32; 1e-6 is its storage precision.
        assert rel < 1e-6, f"Qout parity broken: max rel err {rel:.2e}"

    def test_qfinal_parity(self, rapid2_recurrence_result, sandbox_expected_qfinal):
        _, qfinal = rapid2_recurrence_result
        rel = np.max(np.abs(qfinal - sandbox_expected_qfinal) / np.abs(sandbox_expected_qfinal))
        assert rel < 1e-9, f"Qfinal parity broken: max rel err {rel:.2e}"

    def test_mass_balance_against_qext(self, rapid2_recurrence_result, sandbox_qext):
        """Near steady state, outlet discharge approaches the basin-total Qext."""
        qout, _ = rapid2_recurrence_result
        steady_in = sandbox_qext[-10:].sum(axis=1).mean()
        steady_out = qout[-10:, 4].mean()
        assert abs(steady_out - steady_in) / steady_in < 0.05


class TestFullPipelineRoute:
    """The reference-style tolerance check: the physics-based route() (Manning
    celerity from channel geometry, not the Sandbox's fixed k) must still track
    RAPID2's outlet hydrograph on the engine-built network."""

    @pytest.fixture(scope="class")
    def routed(self, sandbox_zarr_path, sandbox_hourly_qprime, sandbox_qinit):
        from ddr_tpu.engine.core import read_coo_arrays
        from ddr_tpu.geodatazoo.dataclasses import RoutingData
        from ddr_tpu.io import zarrlite

        root = zarrlite.open_group(sandbox_zarr_path)
        coo, order = read_coo_arrays(root)
        n = coo.shape[0]
        rd = RoutingData(
            n_segments=n,
            adjacency_rows=coo.row,
            adjacency_cols=coo.col,
            length=np.asarray(root["length_m"].read()),
            slope=np.asarray(root["slope"].read()),
            x=np.full(n, SANDBOX_X),
            divide_ids=np.asarray(order),
        )
        network, channels, gauges = prepare_batch(rd, slope_min=1e-4)
        assert gauges is None  # full-domain output
        perm = np.array([RAPID2_REACH_IDS.index(c) for c in order])
        params = {
            "n": jnp.full(n, 0.03),
            "q_spatial": jnp.full(n, 0.5),
            "p_spatial": jnp.full(n, 21.0),
        }
        res = route(
            network,
            channels,
            params,
            jnp.asarray(sandbox_hourly_qprime[:, perm]),
            q_init=jnp.asarray(sandbox_qinit[perm], jnp.float32),
        )
        inv = np.argsort(perm)
        return np.asarray(res.runoff)[:, inv]  # (238, 5) in RAPID2 order

    def test_outlet_tracks_rapid2(self, routed, sandbox_expected_qout):
        # Compare at 3-hourly points after the reference's 20-window spin-up
        # (/root/reference/tests/benchmarks/test_diffroute.py:166-175).
        ours = routed[::3, 4][20:80]
        rapid2 = sandbox_expected_qout[20:, 4]
        corr = np.corrcoef(ours, rapid2)[0, 1]
        assert corr > 0.8, f"outlet correlation vs RAPID2 too low: {corr:.3f}"

    def test_steady_state_convergence(self, routed, sandbox_expected_qout):
        end_ours = routed[-30:, 4].mean()
        end_rapid2 = sandbox_expected_qout[-10:, 4].mean()
        rel = abs(end_ours - end_rapid2) / end_rapid2
        assert rel < 0.10, f"steady-state divergence vs RAPID2: {rel:.3f}"

    def test_confluence_accumulation(self, routed):
        """Downstream of a confluence, steady discharge exceeds each upstream."""
        steady = routed[-10:].mean(axis=0)
        assert steady[2] > steady[0] and steady[2] > steady[1]
        assert steady[4] > steady[2] and steady[4] > steady[3]
