"""Benchmark results-store contract + LTI config defaults, at the reference
suite's granularity (/root/reference/tests/benchmarks/ TestSaveResults,
TestDiffRouteConfig, TestBenchmarkConfig)."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.benchmarks import benchmark, validate_benchmark_config
from ddr_tpu.benchmarks.configs import BenchmarkConfig, LTIRouteConfig
from ddr_tpu.io import zarrlite

N_ATTRS = 10


@pytest.fixture(scope="module")
def bench_run(tmp_path_factory):
    """One full two-phase benchmark run shared by every store-contract test."""
    tmp = tmp_path_factory.mktemp("bench_store")
    cfg = validate_benchmark_config(
        {
            "name": "store_test",
            "geodataset": "synthetic",
            "mode": "testing",
            "kan": {"input_var_names": [f"a{i}" for i in range(N_ATTRS)]},
            "experiment": {
                "start_time": "1981/10/01", "end_time": "1981/10/10", "warmup": 1,
            },
            "params": {"save_path": str(tmp)},
            "lti": {"irf_fn": "muskingum", "max_delay": 48},
        }
    )
    results = benchmark(cfg)
    return tmp, results


class TestResultsStore:
    def test_creates_zarr(self, bench_run):
        tmp, _ = bench_run
        assert (tmp / "benchmark_results.zarr").exists()

    def test_has_data_vars(self, bench_run):
        tmp, _ = bench_run
        root = zarrlite.open_group(tmp / "benchmark_results.zarr")
        for name in ("mc_predictions", "lti_predictions", "observations"):
            assert name in root, name

    def test_shapes_match(self, bench_run):
        tmp, _ = bench_run
        root = zarrlite.open_group(tmp / "benchmark_results.zarr")
        mc = root["mc_predictions"].read()
        lti = root["lti_predictions"].read()
        obs = root["observations"].read()
        assert mc.shape == lti.shape == obs.shape

    def test_attrs_include_version_and_provenance(self, bench_run):
        tmp, _ = bench_run
        root = zarrlite.open_group(tmp / "benchmark_results.zarr")
        assert "version" in root.attrs
        assert root.attrs["irf_fn"] == "muskingum"
        assert "model_checkpoint" in root.attrs

    def test_gage_ids_attr_matches_rows(self, bench_run):
        tmp, _ = bench_run
        root = zarrlite.open_group(tmp / "benchmark_results.zarr")
        assert len(root.attrs["gage_ids"]) == root["mc_predictions"].read().shape[0]

    def test_predictions_finite_where_observed(self, bench_run):
        tmp, _ = bench_run
        root = zarrlite.open_group(tmp / "benchmark_results.zarr")
        assert np.isfinite(root["mc_predictions"].read()).all()

    def test_metrics_keys(self, bench_run):
        _, results = bench_run
        assert set(results) == {"mc", "lti"}


class TestLTIRouteConfigDefaults:
    """Reference TestDiffRouteConfig (validation/diffroute.py defaults)."""

    def test_defaults(self):
        cfg = LTIRouteConfig()
        assert cfg.enabled is True
        assert cfg.irf_fn == "muskingum"
        assert cfg.max_delay == 100
        assert cfg.dt == pytest.approx(1.0 / 24.0)
        assert cfg.k is None  # resolved to the RAPID 9000 s default downstream
        assert cfg.x == pytest.approx(0.3)

    def test_custom_values(self):
        cfg = LTIRouteConfig(irf_fn="hayami", max_delay=50, k=0.25, x=0.1)
        assert cfg.irf_fn == "hayami"
        assert cfg.k == 0.25

    def test_extra_field_rejected(self):
        with pytest.raises(ValueError):
            LTIRouteConfig(unknown=1)

    def test_x_upper_bound(self):
        with pytest.raises(ValueError):
            LTIRouteConfig(x=0.5)

    def test_nash_n_lower_bound(self):
        with pytest.raises(ValueError):
            LTIRouteConfig(nash_n=0)


class TestBenchmarkConfigShape:
    def _ddr(self, tmp_path):
        return {
            "name": "b",
            "geodataset": "synthetic",
            "mode": "testing",
            "kan": {"input_var_names": ["a0"]},
            "params": {"save_path": str(tmp_path)},
        }

    def test_construction_nested(self, tmp_path):
        cfg = BenchmarkConfig(ddr=self._ddr(tmp_path))
        assert cfg.lti.enabled is True
        assert cfg.summed_q_prime is None

    def test_extra_field_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            BenchmarkConfig(ddr=self._ddr(tmp_path), bogus=1)

    def test_summed_q_prime_optional_path(self, tmp_path):
        cfg = BenchmarkConfig(
            ddr=self._ddr(tmp_path), summed_q_prime=tmp_path / "sqp.zarr"
        )
        assert cfg.summed_q_prime == tmp_path / "sqp.zarr"
