"""RAPID Sandbox fixtures: the externally-published golden oracle.

Adapts the published 5-reach RAPID Sandbox network (tests/input/Sandbox/README.md;
David 2025, CC-BY-4.0) into MERIT format and builds it through the repo's real
engine -> zarrlite -> loader pipeline, the same adaptation the reference performs in
/root/reference/tests/benchmarks/conftest.py:44-98. The .nc4 files are NetCDF4/HDF5,
read via h5py (no netCDF4 package in this environment).
"""

from __future__ import annotations

from pathlib import Path

import h5py
import numpy as np
import pandas as pd
import pytest

TESTS_DIR = Path(__file__).parent.parent
SANDBOX_IN = TESTS_DIR / "input" / "Sandbox"
SANDBOX_OUT = TESTS_DIR / "output" / "Sandbox"

# RAPID2 reach ordering and Muskingum parameters (k_Sandbox.csv, x_Sandbox.csv,
# namelist_Sandbox.yml IS_dtR).
RAPID2_REACH_IDS = [10, 20, 30, 40, 50]
SANDBOX_K = 9000.0  # seconds
SANDBOX_X = 0.25
SANDBOX_DT = 900.0  # RAPID2 routing substep
QEXT_WINDOW = 10800.0  # Qext is 3-hourly


def read_nc_var(path: Path, name: str) -> np.ndarray:
    with h5py.File(path, "r") as f:
        return np.asarray(f[name][:])


@pytest.fixture(scope="session")
def sandbox_connectivity() -> pd.DataFrame:
    """rapid_connect CSV: columns [COMID, NextDownID] (0 = outlet)."""
    df = pd.read_csv(SANDBOX_IN / "rapid_connect_Sandbox.csv", header=None)
    df.columns = ["COMID", "NextDownID"]
    return df


@pytest.fixture(scope="session")
def sandbox_merit_fp(sandbox_connectivity: pd.DataFrame) -> pd.DataFrame:
    """Sandbox connectivity in MERIT flowpath format (COMID, NextDownID, up1-up4)."""
    up: dict[int, list[int]] = {}
    for comid, nxt in sandbox_connectivity.itertuples(index=False):
        if int(nxt) != 0:
            up.setdefault(int(nxt), []).append(int(comid))
    records = []
    for comid, nxt in sandbox_connectivity.itertuples(index=False):
        ups = up.get(int(comid), [])
        records.append(
            {
                "COMID": int(comid),
                "NextDownID": int(nxt),
                **{f"up{i + 1}": (ups[i] if i < len(ups) else 0) for i in range(4)},
                # 5 km reaches at 0.1% slope: the same nominal channel the reference
                # assigns the Sandbox (/root/reference/tests/benchmarks/conftest.py).
                "lengthkm": 5.0,
                "slope": 0.001,
            }
        )
    return pd.DataFrame(records)


@pytest.fixture(scope="session")
def sandbox_zarr_path(tmp_path_factory: pytest.TempPathFactory, sandbox_merit_fp) -> Path:
    """Sandbox adjacency built through the real engine into a zarrlite store."""
    from ddr_tpu.engine.merit import build_merit_adjacency

    out = tmp_path_factory.mktemp("sandbox_zarr") / "sandbox_adjacency.zarr"
    return build_merit_adjacency(sandbox_merit_fp, out)


@pytest.fixture(scope="session")
def sandbox_qext() -> np.ndarray:
    """(80, 5) 3-hourly lateral inflow, RAPID2 reach order."""
    return read_nc_var(SANDBOX_IN / "Qext_Sandbox_19700101_19700110.nc4", "Qext")


@pytest.fixture(scope="session")
def sandbox_qinit() -> np.ndarray:
    """(5,) initial discharge [9, 9, 27, 18, 63] m3/s."""
    return read_nc_var(SANDBOX_IN / "Qinit_Sandbox_19700101_19700110.nc4", "Qout").squeeze()


@pytest.fixture(scope="session")
def sandbox_expected_qout() -> np.ndarray:
    """(80, 5) RAPID2 published discharge (window means)."""
    return read_nc_var(SANDBOX_OUT / "Qout_Sandbox_19700101_19700110.nc4", "Qout")


@pytest.fixture(scope="session")
def sandbox_expected_qfinal() -> np.ndarray:
    """(5,) RAPID2 published final state."""
    return read_nc_var(SANDBOX_OUT / "Qfinal_Sandbox_19700101_19700110.nc4", "Qout").squeeze()


@pytest.fixture(scope="session")
def sandbox_hourly_qprime(sandbox_qext: np.ndarray) -> np.ndarray:
    """Qext linearly interpolated from 3-hourly (80 pts) to hourly (238 pts),
    mirroring the reference's sandbox_hourly_qprime fixture."""
    t3 = np.arange(sandbox_qext.shape[0]) * 3.0
    t1 = np.arange(t3[-1] + 1)
    return np.stack(
        [np.interp(t1, t3, sandbox_qext[:, i]) for i in range(sandbox_qext.shape[1])], axis=1
    ).astype(np.float32)
