"""Error-budget harness: the measurement itself must stay runnable and the
engine-vs-oracle contract must hold at a small deep shape (subprocess because
x64 is a process-global jax switch the shared test process must not flip)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow


def test_measure_engine_errors_contract():
    code = """
import json, jax
jax.config.update("jax_enable_x64", True)
from ddr_tpu.benchmarks.numerics import measure_engine_errors

res = measure_engine_errors(600, 150, 24, seed=3)
print(json.dumps({k: list(v) for k, v in res.items()}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=600, env=env
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    res = json.loads(proc.stdout.strip().splitlines()[-1])
    assert any(k.startswith("chunked-f32") for k in res)
    for engine, (rel_max, one_nse) in res.items():
        assert rel_max < 1e-3, (engine, rel_max)   # flat-in-depth contract
        assert one_nse < 1e-6, (engine, one_nse)   # NSE-identical at f32 tolerance


def test_requires_x64():
    import pytest

    from ddr_tpu.benchmarks.numerics import measure_engine_errors

    with pytest.raises(RuntimeError, match="x64"):
        measure_engine_errors(64, 8, 4)
