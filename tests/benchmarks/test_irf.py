"""LTI IRF router tests: kernel families + frequency-domain routing vs a plain
time-domain convolution oracle (the role the reference's DiffRoute adapter round-trip
tests play, /root/reference/tests/benchmarks/test_diffroute_adapter.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.benchmarks.irf import IRF_FAMILIES, irf_kernels, route_lti
from ddr_tpu.routing.network import build_network

L = 96
DT = 1.0 / 24.0  # hourly, in days


@pytest.mark.parametrize("family", IRF_FAMILIES)
class TestKernels:
    def test_unit_mass_and_shape(self, family):
        k = np.array([0.05, 0.1042, 0.5])
        x = np.array([0.0, 0.3, 0.45])
        h = irf_kernels(family, k, x, DT, L)
        assert h.shape == (3, L)
        np.testing.assert_allclose(h.sum(axis=1), 1.0, atol=1e-6)
        assert np.isfinite(h).all()

    def test_longer_k_delays_mass(self, family):
        h = irf_kernels(family, np.array([0.05, 0.5]), np.array([0.2, 0.2]), DT, L)
        t = np.arange(L)
        # First temporal moment increases with travel time for every family.
        assert (h[1] * t).sum() > (h[0] * t).sum()


class TestKernelSpecifics:
    def test_pure_lag_is_spike_at_k(self):
        h = irf_kernels("pure_lag", np.array([0.25]), np.array([0.3]), DT, L)
        assert h[0, 6] == 1.0  # 0.25 d = 6 h
        assert h[0].sum() == 1.0

    def test_linear_storage_monotone_decay(self):
        h = irf_kernels("linear_storage", np.array([0.2]), np.array([0.3]), DT, L)
        assert (np.diff(h[0]) < 0).all()

    def test_nash_cascade_mean_near_k(self):
        k = 0.3
        h = irf_kernels("nash_cascade", np.array([k]), np.array([0.3]), DT, 400)
        t = (np.arange(400) + 0.5) * DT
        assert (h[0] * t).sum() == pytest.approx(k, rel=0.05)

    def test_muskingum_initial_dip_for_slow_reaches(self):
        # Bin 0 nets the -x/(1-x) spike against the exponential's first-bin mass
        # (1-e^{-dt/K(1-x)})/(1-x): negative (the classic Muskingum dip) when the
        # reach is slow vs dt, positive when fast (all mass lands in bin 0).
        h_slow = irf_kernels("muskingum", np.array([0.2]), np.array([0.3]), DT, L)
        h_fast = irf_kernels("muskingum", np.array([0.002]), np.array([0.3]), DT, L)
        assert h_slow[0, 0] < 0
        assert h_fast[0, 0] == pytest.approx(1.0, abs=1e-6)
        assert abs(h_fast[0, 1:]).max() < 1e-9

    def test_hayami_peak_near_k_for_low_dispersion(self):
        # Inverse-Gaussian mode -> mean as x -> 0 (pure translation limit).
        h = irf_kernels("hayami", np.array([0.25]), np.array([0.01]), DT, L)
        assert abs(int(h[0].argmax()) - 6) <= 1  # 0.25 d = bin ~6

    @pytest.mark.parametrize("family", IRF_FAMILIES)
    def test_degenerate_fast_reach_becomes_spike(self, family):
        # k << dt must never yield a zero (flow-annihilating) kernel.
        h = irf_kernels(family, np.array([1e-4]), np.array([0.3]), DT, L)
        np.testing.assert_allclose(h.sum(axis=1), 1.0, atol=1e-6)
        assert h[0, 0] == pytest.approx(1.0)

    def test_unknown_family_rejected(self):
        with pytest.raises(ValueError, match="irf_fn"):
            irf_kernels("spectral", np.ones(1), np.zeros(1), DT, L)


def _oracle(rows, cols, n, kernels, q_prime):
    """Time-domain reference: topological sweep of truncated-kernel convolutions."""
    T = q_prime.shape[0]
    q = np.zeros((T, n))
    for i in range(n):  # nodes are topologically sorted
        inflow = q_prime[:, i].astype(np.float64).copy()
        for e in range(len(rows)):
            if rows[e] == i:
                inflow += q[:, cols[e]]
        q[:, i] = np.convolve(inflow, kernels[i].astype(np.float64))[:T]
    return q


class TestRouteLti:
    @pytest.mark.parametrize("family", ["muskingum", "linear_storage", "pure_lag"])
    def test_matches_time_domain_oracle(self, family, rng):
        # Y-network plus a chain: 0,1 -> 2 -> 3 -> 4
        rows = np.array([2, 2, 3, 4])
        cols = np.array([0, 1, 2, 3])
        n, T = 5, 240
        network = build_network(rows, cols, n)
        k = rng.uniform(0.05, 0.3, n)
        x = rng.uniform(0.05, 0.4, n)
        kernels = irf_kernels(family, k, x, DT, L)
        q_prime = rng.uniform(0.0, 2.0, (T, n)).astype(np.float32)

        got = np.asarray(route_lti(network, kernels, jnp.asarray(q_prime)))
        want = _oracle(rows, cols, n, kernels, q_prime)
        np.testing.assert_allclose(got, want, atol=2e-3, rtol=1e-3)

    def test_mass_conservation_at_outlet(self, rng):
        # Chain of 4; impulse inflow only at the head; all mass must exit reach 3.
        rows, cols = np.array([1, 2, 3]), np.array([0, 1, 2])
        network = build_network(rows, cols, 4)
        kernels = irf_kernels("linear_storage", np.full(4, 0.05), np.full(4, 0.3), DT, L)
        T = 2048  # long window so the composed response fully decays
        q_prime = np.zeros((T, 4), np.float32)
        q_prime[0, 0] = 1.0
        q = np.asarray(route_lti(network, kernels, jnp.asarray(q_prime)))
        assert q[:, 3].sum() == pytest.approx(1.0, abs=1e-4)

    def test_shape_validation(self):
        network = build_network(np.array([1]), np.array([0]), 2)
        kernels = irf_kernels("linear_storage", np.ones(2), np.zeros(2), DT, L)
        with pytest.raises(ValueError, match="reaches"):
            route_lti(network, kernels, jnp.zeros((10, 3)))
