"""End-to-end twin-experiment training test: KAN -> routing -> loss -> gradients.

The analog of the reference's TestParameterTraining
(/root/reference/tests/routing/test_torch_mc.py:514+): run forward+backward on a mock
scenario and assert the parameters actually receive gradients and the loss drops.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin, observe
from ddr_tpu.nn.kan import Kan
from ddr_tpu.routing.mc import Bounds
from ddr_tpu.routing.model import prepare_batch
from ddr_tpu.training import make_optimizer, make_train_step, set_learning_rate
from ddr_tpu.validation.configs import Config


def _cfg():
    return Config(
        name="twin_test",
        geodataset="synthetic",
        mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"rho": 6, "warmup": 1},
    )


def test_twin_experiment_training_reduces_loss():
    cfg = _cfg()
    basin = observe(make_basin(n_segments=48, n_gauges=4, n_days=6, seed=1), cfg)
    rd = basin.routing_data

    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
        hidden_size=cfg.kan.hidden_size,
        num_hidden_layers=cfg.kan.num_hidden_layers,
        grid=cfg.kan.grid,
        k=cfg.kan.k,
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(cfg.seed), attrs)
    optimizer = make_optimizer(learning_rate=0.01)
    opt_state = optimizer.init(params)

    step = make_train_step(
        kan_model,
        network,
        channels,
        gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges,
        cfg.params.log_space_parameters,
        cfg.params.defaults,
        tau=cfg.params.tau,
        warmup=cfg.experiment.warmup,
        optimizer=optimizer,
    )

    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    q_prime = jnp.asarray(basin.q_prime)

    losses = []
    for _ in range(8):
        params, opt_state, loss, daily = step(params, opt_state, attrs, q_prime, obs, mask)
        losses.append(float(loss))

    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, f"loss did not decrease: {losses}"

    # LR schedule injection works.
    opt_state = set_learning_rate(opt_state, 1e-4)
    params2, opt_state, loss2, _ = step(params, opt_state, attrs, q_prime, obs, mask)
    assert np.isfinite(float(loss2))


def test_batch_step_host_permuted_q_prime_matches():
    """The wf-hoist fast path (`ddr train`'s contract): a step built with
    q_prime_wf_permuted=True fed HOST-permuted inflow columns must produce the
    same loss/daily as the plain step on original-order inflows — and leave
    non-single-ring batches untouched (same predicate on both sides)."""
    from ddr_tpu.routing.model import single_ring_wavefront
    from ddr_tpu.training import make_batch_train_step

    cfg = _cfg()
    basin = observe(make_basin(n_segments=48, n_gauges=4, n_days=6, seed=3), cfg)
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    assert single_ring_wavefront(network)
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    optimizer = make_optimizer(1e-3)
    opt_state = optimizer.init(params)
    kw = dict(
        bounds=Bounds.from_config(cfg.params.attribute_minimums),
        parameter_ranges=cfg.params.parameter_ranges,
        log_space_parameters=cfg.params.log_space_parameters,
        defaults=cfg.params.defaults, tau=cfg.params.tau, warmup=1,
        optimizer=optimizer, donate=False,
    )
    step_plain = make_batch_train_step(kan_model, **kw)
    step_hoist = make_batch_train_step(kan_model, **kw, q_prime_wf_permuted=True)
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    qp = np.asarray(basin.q_prime, np.float32)
    qp_perm = jnp.asarray(qp[:, np.asarray(network.wf_perm)])

    _, _, l0, d0 = step_plain(
        params, opt_state, network, channels, gauges, attrs, jnp.asarray(qp), obs, mask
    )
    _, _, l1, d1 = step_hoist(
        params, opt_state, network, channels, gauges, attrs, qp_perm, obs, mask
    )
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-5, atol=1e-6)


def test_nan_observations_are_masked():
    cfg = _cfg()
    basin = observe(make_basin(n_segments=32, n_gauges=3, n_days=6, seed=2), cfg)
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=("n", "q_spatial"),
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    optimizer = make_optimizer(0.005)
    opt_state = optimizer.init(params)
    step = make_train_step(
        kan_model, network, channels, gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges, cfg.params.log_space_parameters,
        cfg.params.defaults, tau=cfg.params.tau, warmup=1, optimizer=optimizer,
    )
    obs = np.asarray(basin.obs_daily).copy()
    obs[:, 0] = np.nan  # dead gauge
    mask = ~np.isnan(obs)
    _, _, loss, _ = step(
        params, opt_state, attrs, jnp.asarray(basin.q_prime),
        jnp.asarray(np.nan_to_num(obs)), jnp.asarray(mask),
    )
    assert np.isfinite(float(loss)), "NaN observations leaked into the loss"


class TestCheckpointSchema:
    """Version/schema guard on checkpoint blobs (pre-versioning blobs and corrupt
    files must fail with a clear ValueError, not a cryptic KeyError mid-restore)."""

    def _save(self, tmp_path):
        from ddr_tpu.training import save_state

        return save_state(tmp_path, "t", epoch=1, mini_batch=2, params={"w": 1.0}, opt_state={})

    def test_round_trip(self, tmp_path):
        from ddr_tpu.training import load_state

        blob = load_state(self._save(tmp_path))
        assert blob["epoch"] == 1 and blob["mini_batch"] == 2
        assert blob["params"] == {"w": 1.0}

    def test_corrupt_blob_raises(self, tmp_path):
        import pytest

        from ddr_tpu.training import load_state

        p = tmp_path / "bad.pkl"
        p.write_bytes(b"\x80\x04 this is not a pickle")
        with pytest.raises(ValueError, match="corrupt checkpoint"):
            load_state(p)

    def test_pre_versioning_blob_raises(self, tmp_path):
        import pickle

        import pytest

        from ddr_tpu.training import load_state

        p = tmp_path / "old.pkl"
        with p.open("wb") as f:
            pickle.dump({"epoch": 0, "params": {}}, f)  # round-1 layout: no marker
        with pytest.raises(ValueError, match="not a ddr-tpu checkpoint"):
            load_state(p)

    def test_version_mismatch_raises(self, tmp_path):
        import pickle

        import pytest

        from ddr_tpu.training import CHECKPOINT_FORMAT, load_state

        p = tmp_path / "future.pkl"
        with p.open("wb") as f:
            pickle.dump({"format": CHECKPOINT_FORMAT, "version": 999}, f)
        with pytest.raises(ValueError, match="version 999"):
            load_state(p)

    def test_missing_fields_raises(self, tmp_path):
        import pickle

        import pytest

        from ddr_tpu.training import CHECKPOINT_FORMAT, CHECKPOINT_VERSION, load_state

        p = tmp_path / "partial.pkl"
        with p.open("wb") as f:
            pickle.dump({"format": CHECKPOINT_FORMAT, "version": CHECKPOINT_VERSION}, f)
        with pytest.raises(ValueError, match="missing fields"):
            load_state(p)

    def test_arch_mismatch_raises(self, tmp_path):
        """Same param shapes under a different grid_range compute a different
        function; the blob's arch fingerprint must refuse the cross-load."""
        import pytest

        from ddr_tpu.training import load_state, save_state

        arch = {"model": "kan", "grid_range": [-1.0, 1.0], "grid": 3}
        p = save_state(
            tmp_path, "t", epoch=1, mini_batch=0, params={"w": 1.0}, opt_state={}, arch=arch
        )
        # same arch loads fine
        assert load_state(p, expected_arch=dict(arch))["params"] == {"w": 1.0}
        # and with no expectation stated, loads fine (inference-only tools)
        assert load_state(p)["arch"] == arch
        with pytest.raises(ValueError, match="grid_range"):
            load_state(
                p, expected_arch={"model": "kan", "grid_range": [-2.0, 2.0], "grid": 3}
            )

    def test_v1_blob_loads_archless_but_not_with_expectation(self, tmp_path):
        """Round-1 (v1) blobs stay loadable by arch-agnostic tools (geometry
        predictor, plain inference) but are refused when the caller states an
        architecture — v1 predates the fingerprint, so nothing can verify it."""
        import pickle

        import pytest

        from ddr_tpu.training import CHECKPOINT_FORMAT, load_state

        p = tmp_path / "v1.pkl"
        with p.open("wb") as f:
            pickle.dump(
                {
                    "format": CHECKPOINT_FORMAT, "version": 1,
                    "epoch": 2, "mini_batch": 5, "params": {"w": 2.0}, "opt_state": {},
                },
                f,
            )
        assert load_state(p)["params"] == {"w": 2.0}
        with pytest.raises(ValueError, match="version 1"):
            load_state(p, expected_arch={"model": "kan"})

    def test_archless_blob_loads_with_expectation(self, tmp_path):
        """A v2 blob saved without arch (non-KAN producers) never hard-fails."""
        from ddr_tpu.training import load_state, save_state

        p = save_state(tmp_path, "t", epoch=1, mini_batch=0, params={}, opt_state={})
        assert load_state(p, expected_arch={"model": "kan"})["arch"] is None

    def test_train_checkpoints_carry_kan_arch(self, tmp_path):
        """End-to-end: ddr train writes blobs whose arch matches the config, and
        resuming under an edited grid_range refuses."""
        import pytest

        from ddr_tpu.scripts.common import kan_arch
        from ddr_tpu.scripts.train import train
        from ddr_tpu.training import load_state
        from ddr_tpu.validation.configs import Config

        cfg = Config(
            name="archck", geodataset="synthetic", mode="training",
            kan={"input_var_names": [f"a{i}" for i in range(10)]},
            experiment={
                "start_time": "1981/10/01", "end_time": "1981/11/30",
                "epochs": 1, "batch_size": 2, "rho": 5, "warmup": 1,
            },
            params={"save_path": tmp_path},
        )
        train(cfg, max_batches=1)
        ckpts = sorted((tmp_path / "saved_models").glob("*.pkl"))
        assert ckpts, "training wrote no checkpoint"
        assert load_state(ckpts[0])["arch"] == kan_arch(cfg)

        cfg2 = cfg.model_copy(deep=True)
        cfg2.kan.grid_range = [-3.0, 3.0]
        cfg2.experiment.checkpoint = ckpts[0]
        with pytest.raises(ValueError, match="different architecture"):
            train(cfg2, max_batches=1)


@pytest.mark.slow
def test_twin_experiment_with_adaptive_grid_refit():
    """Adaptive-grid training end to end on the twin experiment: a mid-training
    grid refit (pykan-style) must not break descent — loss keeps falling after
    the refit and ends below the start (the recovery-evidence extension VERDICT
    round-2 asked for alongside the static-grid justification)."""
    from ddr_tpu.nn.kan import update_grid_from_samples

    cfg = _cfg()
    basin = observe(make_basin(n_segments=48, n_gauges=4, n_days=6, seed=1), cfg)
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
        hidden_size=cfg.kan.hidden_size,
        num_hidden_layers=cfg.kan.num_hidden_layers,
        grid=cfg.kan.grid,
        k=cfg.kan.k,
        adaptive_grid=True,
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(cfg.seed), attrs)
    optimizer = make_optimizer(learning_rate=0.01)
    opt_state = optimizer.init(params)
    step = make_train_step(
        kan_model, network, channels, gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges, cfg.params.log_space_parameters,
        cfg.params.defaults, tau=cfg.params.tau, warmup=cfg.experiment.warmup,
        optimizer=optimizer,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    q_prime = jnp.asarray(basin.q_prime)

    losses = []
    for k in range(10):
        if k == 4:
            params = update_grid_from_samples(kan_model, params, attrs)
        params, opt_state, loss, _ = step(params, opt_state, attrs, q_prime, obs, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    # the refit is function-preserving: no loss explosion at the boundary
    assert losses[4] < losses[0]
    assert losses[-1] < losses[0] * 0.9, f"loss did not decrease: {losses}"


@pytest.mark.slow
def test_twin_experiment_on_deep_stacked_topology():
    """The CONUS-shaped training path: a deep network whose prepare_batch
    auto-selection routes through the STACKED chunked engine (the
    lax.scan-over-bands router) — gradients must flow through the band scan,
    the boundary-buffer carry, and the rotating ring, and the loss must drop."""
    from ddr_tpu.routing.stacked import StackedChunked

    cfg = _cfg()
    basin = observe(
        make_basin(n_segments=256, n_gauges=3, n_days=4, seed=9, depth=96), cfg
    )
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    # Force the stacked router even though this test-sized depth fits the
    # single-ring caps (the real trigger needs depth > 1024 — too slow for CI).
    from ddr_tpu.routing.stacked import build_stacked_chunked

    network = build_stacked_chunked(
        rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, cell_budget=3_000
    )
    assert isinstance(network, StackedChunked) and network.n_chunks > 1
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(cfg.seed), attrs)
    optimizer = make_optimizer(learning_rate=0.01)
    opt_state = optimizer.init(params)
    step = make_train_step(
        kan_model, network, channels, gauges,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges, cfg.params.log_space_parameters,
        cfg.params.defaults, tau=cfg.params.tau, warmup=cfg.experiment.warmup,
        optimizer=optimizer,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    q_prime = jnp.asarray(basin.q_prime)
    losses = []
    for _ in range(6):
        params, opt_state, loss, _ = step(params, opt_state, attrs, q_prime, obs, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.95, f"loss did not decrease: {losses}"


@pytest.mark.slow
def test_deep_batch_train_step_auto_selects_stacked():
    """VERDICT r3 item 3: at genuinely deep shape (depth > the single-ring cap),
    prepare_batch must hand make_batch_train_step the STACKED band-scan engine
    — the path the CONUS training run rides — and one full step must produce a
    finite loss through it."""
    from ddr_tpu.routing.stacked import StackedChunked
    from ddr_tpu.training import make_batch_train_step

    cfg = _cfg()
    basin = observe(
        make_basin(n_segments=2048, n_gauges=4, n_days=3, seed=2, depth=1100), cfg
    )
    rd = basin.routing_data
    network, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    assert isinstance(network, StackedChunked), type(network).__name__
    assert network.depth >= 1100

    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    optimizer = make_optimizer(learning_rate=0.01)
    opt_state = optimizer.init(params)
    step = make_batch_train_step(
        kan_model,
        Bounds.from_config(cfg.params.attribute_minimums),
        cfg.params.parameter_ranges, cfg.params.log_space_parameters,
        cfg.params.defaults, tau=cfg.params.tau, warmup=cfg.experiment.warmup,
        optimizer=optimizer,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    q_prime = jnp.asarray(basin.q_prime)
    _, _, loss, _ = step(params, opt_state, network, channels, gauges, attrs, q_prime, obs, mask)
    assert np.isfinite(float(loss))


class TestOrbaxCheckpoints:
    """Orbax-backed checkpoint directories: same schema contract as the pickle
    blobs, auto-detected by load_state, structural optax restore via target."""

    def _save(self, tmp_path, arch=None):
        from ddr_tpu.training import make_optimizer, save_state_orbax

        params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}
        opt = make_optimizer(1e-3)
        opt_state = opt.init(params)
        rng_state = {"bit_generator": np.random.default_rng(5).bit_generator.state}
        path = save_state_orbax(
            tmp_path, "ob", epoch=3, mini_batch=7, params=params,
            opt_state=opt_state, rng_state=rng_state, arch=arch,
        )
        return path, params, opt, opt_state

    def test_round_trip_via_autodetect(self, tmp_path):
        from ddr_tpu.training import load_state

        path, params, _, _ = self._save(tmp_path, arch={"grid": 3})
        assert path.is_dir() and path.suffix == ".orbax"
        blob = load_state(path, expected_arch={"grid": 3})
        assert blob["epoch"] == 3 and blob["mini_batch"] == 7
        np.testing.assert_array_equal(np.asarray(blob["params"]["w"]), np.asarray(params["w"]))
        assert blob["rng_state"]["bit_generator"]["bit_generator"] == "PCG64"

    def test_arch_mismatch_raises(self, tmp_path):
        from ddr_tpu.training import load_state_orbax

        path, *_ = self._save(tmp_path, arch={"grid": 3})
        with pytest.raises(ValueError, match="different architecture"):
            load_state_orbax(path, expected_arch={"grid": 50})

    def test_non_json_plain_rng_state_fails_at_save(self, tmp_path):
        """JSON silently rewrites tuples/ndarrays to lists, so an rng blob that
        would restore structurally different from the pickle path must fail AT
        SAVE TIME, not corrupt a later resume."""
        from ddr_tpu.training import make_optimizer, save_state_orbax

        params = {"w": jnp.ones(3)}
        opt_state = make_optimizer(1e-3).init(params)
        for bad, pattern in [
            ({"key": (1, 2)}, "rng_state.key is tuple"),
            ({"deep": {"inner": [1, (2,)]}}, r"rng_state.deep.inner\[1\] is tuple"),
            ({"o": np.array([(1, 2)], dtype=object)}, "object-dtype"),
        ]:
            with pytest.raises(TypeError, match=pattern):
                save_state_orbax(
                    tmp_path, "bad", epoch=1, mini_batch=0, params=params,
                    opt_state=opt_state, rng_state=bad,
                )
        # the real loader blob (dict of ints/strs) still saves, and so does an
        # MT19937-style state whose ndarray 'key' leaf round-trips through JSON
        # bit-identically (numpy state setters accept the list form)
        rng_state = {"bit_generator": np.random.default_rng(5).bit_generator.state}
        save_state_orbax(
            tmp_path, "ok", epoch=1, mini_batch=0, params=params,
            opt_state=opt_state, rng_state=rng_state,
        )
        mt_state = {"bit_generator": np.random.Generator(np.random.MT19937(3)).bit_generator.state}
        path = save_state_orbax(
            tmp_path, "mt", epoch=1, mini_batch=0, params=params,
            opt_state=opt_state, rng_state=mt_state,
        )
        from ddr_tpu.training import peek_orbax_meta

        restored = peek_orbax_meta(path)["rng_state"]["bit_generator"]
        g = np.random.Generator(np.random.MT19937(99))
        g.bit_generator.state = restored
        g2 = np.random.Generator(np.random.MT19937(3))
        assert g.standard_normal(4).tolist() == g2.standard_normal(4).tolist()

    def test_target_restores_optax_structure(self, tmp_path):
        """With a target exemplar the restored opt_state is a REAL optax state
        (the optimizer can consume it directly), not nested dicts."""
        from ddr_tpu.training import load_state_orbax

        path, params, opt, opt_state = self._save(tmp_path)
        blob = load_state_orbax(path, target={"params": params, "opt_state": opt_state})
        grads = jax.tree_util.tree_map(jnp.ones_like, blob["params"])
        updates, _ = opt.update(grads, blob["opt_state"], blob["params"])
        assert jax.tree_util.tree_structure(updates) == jax.tree_util.tree_structure(params)

    def test_not_an_orbax_checkpoint_raises(self, tmp_path):
        from ddr_tpu.training import load_state_orbax

        (tmp_path / "empty").mkdir()
        with pytest.raises(ValueError, match="no meta.json"):
            load_state_orbax(tmp_path / "empty")

    def test_preempted_save_raises_clear_error(self, tmp_path):
        """A dir with state/ but no meta.json (crash between the array save and
        the meta rename) must raise the module's ValueError, not leak
        IsADirectoryError through the pickle branch."""
        from ddr_tpu.training import load_state

        path, *_ = self._save(tmp_path)
        (path / "meta.json").unlink()
        with pytest.raises(ValueError, match="corrupt checkpoint"):
            load_state(path)

    def test_latest_checkpoint_sees_orbax_dirs(self, tmp_path):
        from ddr_tpu.training import latest_checkpoint, save_state

        save_state(tmp_path, "ob", epoch=1, mini_batch=0, params={"w": 1.0}, opt_state={})
        import time as _time

        _time.sleep(0.05)
        path, *_ = self._save(tmp_path)  # newer orbax dir
        assert latest_checkpoint(tmp_path) == path

    def test_preempted_save_invisible_to_latest_checkpoint(self, tmp_path):
        """An incomplete .orbax dir must not shadow the previous good checkpoint
        in auto-resume discovery."""
        from ddr_tpu.training import latest_checkpoint, save_state

        good = save_state(tmp_path, "g", epoch=1, mini_batch=0, params={"w": 1.0}, opt_state={})
        import time as _time

        _time.sleep(0.05)
        path, *_ = self._save(tmp_path)  # newer
        (path / "meta.json").unlink()  # preempted: no completeness marker
        assert latest_checkpoint(tmp_path) == good

    def test_peek_meta_reads_no_arrays(self, tmp_path):
        from ddr_tpu.training import peek_orbax_meta

        path, *_ = self._save(tmp_path, arch={"grid": 3})
        meta = peek_orbax_meta(path)
        assert meta["epoch"] == 3 and meta["mini_batch"] == 7
        assert "params" not in meta and "opt_state" not in meta

    def test_peek_validates_arch(self, tmp_path):
        """Arch mismatches must fail at the metadata peek (clear ValueError),
        BEFORE any tensorstore array I/O could die on a shape error."""
        from ddr_tpu.training import peek_orbax_meta

        path, *_ = self._save(tmp_path, arch={"grid": 3})
        with pytest.raises(ValueError, match="different architecture"):
            peek_orbax_meta(path, expected_arch={"grid": 50})


@pytest.mark.slow
def test_batch_step_remat_bands_matches_default_on_deep_topology():
    """experiment.remat_bands plumbs through make_batch_train_step: identical
    loss on a stacked deep batch, and silently ignored on a shallow batch."""
    from ddr_tpu.routing.stacked import build_stacked_chunked
    from ddr_tpu.training import make_batch_train_step

    cfg = _cfg()
    basin = observe(make_basin(n_segments=256, n_gauges=3, n_days=4, seed=9, depth=96), cfg)
    rd = basin.routing_data
    _, channels, gauges = prepare_batch(rd, cfg.params.attribute_minimums["slope"])
    network = build_stacked_chunked(
        rd.adjacency_rows, rd.adjacency_cols, rd.n_segments, cell_budget=3_000
    )
    kan_model = Kan(
        input_var_names=tuple(cfg.kan.input_var_names),
        learnable_parameters=tuple(cfg.kan.learnable_parameters),
    )
    attrs = jnp.asarray(rd.normalized_spatial_attributes)
    params = kan_model.init(jax.random.key(0), attrs)
    optimizer = make_optimizer(0.01)
    opt_state = optimizer.init(params)
    kw = dict(
        bounds=Bounds.from_config(cfg.params.attribute_minimums),
        parameter_ranges=cfg.params.parameter_ranges,
        log_space_parameters=cfg.params.log_space_parameters,
        defaults=cfg.params.defaults, tau=cfg.params.tau, warmup=1,
        optimizer=optimizer,
    )
    obs = jnp.asarray(basin.obs_daily)
    mask = jnp.ones_like(obs, dtype=bool)
    qp = jnp.asarray(basin.q_prime)

    # donate=False: the same params/opt_state feed all three calls below
    step0 = make_batch_train_step(kan_model, **kw, donate=False)
    step1 = make_batch_train_step(kan_model, **kw, remat_bands=True, donate=False)
    _, _, l0, _ = step0(params, opt_state, network, channels, gauges, attrs, qp, obs, mask)
    _, _, l1, _ = step1(params, opt_state, network, channels, gauges, attrs, qp, obs, mask)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)

    # shallow batch: plain network, flag must be a no-op, not an error
    net_p, ch_p, g_p = prepare_batch(rd, cfg.params.attribute_minimums["slope"], chunked=False)
    _, _, l2, _ = step1(params, opt_state, net_p, ch_p, g_p, attrs, qp, obs, mask)
    assert np.isfinite(float(l2))
