"""Geometry subsystem tests: adapters, statistics, predictor round-trip through a real
training checkpoint (reference tests/geometry/*)."""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.geometry.adapters import (
    HYDROATLAS_TO_MERIT,
    MERIT_ATTRIBUTE_NAMES,
    adapt_attributes,
    detect_source,
)
from ddr_tpu.geometry.statistics import compute_geometry_statistics


class TestAdapters:
    def _merit(self, n=5):
        rng = np.random.default_rng(0)
        return {name: rng.uniform(1, 10, n) for name in MERIT_ATTRIBUTE_NAMES}

    def _hydroatlas(self, n=5):
        rng = np.random.default_rng(0)
        return {name: rng.uniform(1, 10, n) for name in HYDROATLAS_TO_MERIT}

    def test_detect_merit(self):
        assert detect_source(self._merit()) == "merit"

    def test_detect_hydroatlas(self):
        assert detect_source(self._hydroatlas()) == "hydroatlas"

    def test_detect_unknown(self):
        assert detect_source({"foo": np.zeros(3)}) is None

    def test_adapt_merit_noop_ordered(self):
        out = adapt_attributes(self._merit())
        assert list(out) == list(MERIT_ATTRIBUTE_NAMES)

    def test_adapt_hydroatlas_log_transform(self):
        src = self._hydroatlas()
        src["upa_sk_smx"] = np.array([1.0, 10.0, 100.0, 1000.0, 10000.0])
        out = adapt_attributes(src)
        np.testing.assert_allclose(out["log10_uparea"], [0, 1, 2, 3, 4], atol=1e-9)
        np.testing.assert_allclose(out["SoilGrids1km_clay"], src["cly_pc_sav"])

    def test_adapt_missing_raises(self):
        src = self._hydroatlas()
        del src["cly_pc_sav"]
        with pytest.raises(ValueError, match="Cannot auto-detect"):
            adapt_attributes(src)
        with pytest.raises(ValueError, match="Missing hydroatlas"):
            adapt_attributes(src, source="hydroatlas")

    def test_unknown_source_raises(self):
        with pytest.raises(ValueError, match="Unknown attribute source"):
            adapt_attributes(self._merit(), source="nonsense")


class TestStatistics:
    def test_shapes_and_monotonicity(self):
        rng = np.random.default_rng(1)
        n_reach, n_days = 12, 30
        q = rng.uniform(0.5, 50, size=(n_days, n_reach))
        stats = compute_geometry_statistics(
            n=np.full(n_reach, 0.05),
            p_spatial=np.full(n_reach, 21.0),
            q_spatial=np.full(n_reach, 0.4),
            slope=rng.uniform(1e-3, 0.02, n_reach),
            daily_accumulated_discharge=q,
        )
        assert stats["depth_min"].shape == (n_reach,)
        assert (stats["depth_min"] <= stats["depth_median"]).all()
        assert (stats["depth_median"] <= stats["depth_max"]).all()
        assert (stats["top_width_min"] > 0).all()
        np.testing.assert_allclose(stats["discharge_mean"], q.mean(0), rtol=1e-6)

    def test_more_discharge_more_depth(self):
        n_reach = 4
        base = dict(
            n=np.full(n_reach, 0.05),
            p_spatial=np.full(n_reach, 21.0),
            q_spatial=np.full(n_reach, 0.4),
            slope=np.full(n_reach, 0.005),
        )
        lo = compute_geometry_statistics(
            **base, daily_accumulated_discharge=np.full((5, n_reach), 1.0)
        )
        hi = compute_geometry_statistics(
            **base, daily_accumulated_discharge=np.full((5, n_reach), 100.0)
        )
        assert (hi["depth_mean"] > lo["depth_mean"]).all()
        assert (hi["top_width_mean"] > lo["top_width_mean"]).all()


class TestPredictor:
    @pytest.fixture(scope="class")
    def trained_run(self, tmp_path_factory):
        """Train one synthetic mini-batch so a real checkpoint + stats JSON exist.

        Class-scoped: the three predictor tests read the SAME checkpoint (none
        mutates it), so the ~7s train+compile runs once, not per test."""
        import json

        import yaml

        from ddr_tpu.scripts.train import train
        from ddr_tpu.training import latest_checkpoint
        from ddr_tpu.validation.configs import Config

        tmp_path = tmp_path_factory.mktemp("geom_predictor")

        cfg_dict = {
            "name": "geom_test",
            "geodataset": "synthetic",
            "mode": "training",
            "kan": {"input_var_names": list(MERIT_ATTRIBUTE_NAMES)},
            "experiment": {
                "start_time": "1981/10/01",
                "end_time": "1981/10/15",
                "rho": 6,
                "batch_size": 4,
                "epochs": 1,
                "learning_rate": {1: 0.01},
                "warmup": 1,
            },
            "params": {"save_path": str(tmp_path)},
            "data_sources": {
                "attributes": "synthetic_attrs",
                "statistics": str(tmp_path / "stats"),
            },
        }
        cfg = Config(**cfg_dict)
        train(cfg, max_batches=1)
        ckpt = latest_checkpoint(tmp_path / "saved_models")

        # Stats JSON in the cache location the predictor auto-detects.
        stats_dir = tmp_path / "stats"
        stats_dir.mkdir(exist_ok=True)
        rng = np.random.default_rng(2)
        stats = {
            name: {
                "min": 0.0, "max": 10.0, "mean": 5.0, "std": 2.0, "p10": 1.0, "p90": 9.0,
            }
            for name in MERIT_ATTRIBUTE_NAMES
        }
        (stats_dir / "synthetic_attribute_statistics_synthetic_attrs.json").write_text(
            json.dumps(stats)
        )
        cfg_path = tmp_path / "config.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg_dict))
        return cfg_path, ckpt

    def test_from_checkpoint_and_predict(self, trained_run, caplog):
        from ddr_tpu.geometry.predictor import GeometryPredictor

        cfg_path, ckpt = trained_run
        predictor = GeometryPredictor.from_checkpoint(ckpt, cfg_path)
        rng = np.random.default_rng(3)
        n = 20
        attrs = {name: rng.uniform(2, 8, n) for name in MERIT_ATTRIBUTE_NAMES}
        result = predictor.predict(attrs, discharge=rng.uniform(1, 50, n), slope=rng.uniform(1e-3, 0.02, n))
        for key in ("top_width", "depth", "velocity", "n", "p_spatial", "q_spatial"):
            assert result[key].shape == (n,)
            assert np.isfinite(result[key]).all()
        lo, hi = predictor._parameter_ranges["n"]
        assert (result["n"] >= lo - 1e-6).all() and (result["n"] <= hi + 1e-6).all()

    def test_ood_warning(self, trained_run, caplog):
        from ddr_tpu.geometry.predictor import GeometryPredictor

        cfg_path, ckpt = trained_run
        predictor = GeometryPredictor.from_checkpoint(ckpt, cfg_path)
        n = 10
        attrs = {name: np.full(n, 100.0) for name in MERIT_ATTRIBUTE_NAMES}  # way above p90
        with caplog.at_level("WARNING"):
            predictor.predict(attrs, discharge=np.ones(n), slope=np.full(n, 0.01))
        assert "above training p90" in caplog.text

    def test_nan_filled_with_training_mean(self, trained_run, caplog):
        from ddr_tpu.geometry.predictor import GeometryPredictor

        cfg_path, ckpt = trained_run
        predictor = GeometryPredictor.from_checkpoint(ckpt, cfg_path)
        n = 10
        attrs = {name: np.full(n, 5.0) for name in MERIT_ATTRIBUTE_NAMES}
        attrs["aridity"][3] = np.nan
        result = predictor.predict(attrs, discharge=np.ones(n), slope=np.full(n, 0.01))
        assert np.isfinite(result["n"]).all()


class TestGeometryScript:
    def test_script_on_merit_fixture(self, merit_cfg, tmp_path):
        from ddr_tpu.io import zarrlite
        from ddr_tpu.scripts.geometry_predictor import generate_geometry_dataset

        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "routing"
        cfg.experiment.rho = None
        cfg.data_sources.gages = None
        cfg.data_sources.gages_adjacency = None
        cfg.params.save_path = tmp_path
        out = generate_geometry_dataset(cfg)
        root = zarrlite.open_group(out)
        depth_med = root["depth_median"].read()
        assert depth_med.shape == (10,)
        assert np.isfinite(depth_med).all()
        # Downstream-most reaches accumulate more discharge.
        q_mean = root["discharge_mean"].read()
        assert q_mean[9] > q_mean[0]


class TestTrapezoidPhysics:
    """Physical-consistency battery mirroring the reference's trapezoid suite
    (/root/reference/tests/geometry): internal consistency of the returned
    geometry, bound enforcement, and monotone responses to each driver."""

    def _geom(self, **over):
        import jax.numpy as jnp

        from ddr_tpu.geometry.trapezoidal import trapezoidal_geometry

        base = dict(
            n=jnp.full(6, 0.035),
            p_spatial=jnp.full(6, 21.0),
            q_spatial=jnp.full(6, 0.45),
            discharge=jnp.asarray([0.5, 1.0, 5.0, 20.0, 100.0, 500.0]),
            slope=jnp.full(6, 2e-3),
        )
        base.update(over)
        return {k: np.asarray(v) for k, v in trapezoidal_geometry(**base).items()}

    def test_returns_all_expected_keys(self):
        g = self._geom()
        assert set(g) == {
            "depth", "top_width", "bottom_width", "side_slope",
            "cross_sectional_area", "wetted_perimeter", "hydraulic_radius",
            "velocity",
        }

    def test_all_values_positive_and_finite(self):
        for name, v in self._geom().items():
            assert np.all(np.isfinite(v)), name
            assert np.all(v > 0), name

    def test_area_consistent_with_trapezoid_formula(self):
        g = self._geom()
        want = (g["top_width"] + g["bottom_width"]) * g["depth"] / 2.0
        np.testing.assert_allclose(g["cross_sectional_area"], want, rtol=1e-5)

    def test_hydraulic_radius_consistent(self):
        g = self._geom()
        np.testing.assert_allclose(
            g["hydraulic_radius"],
            g["cross_sectional_area"] / g["wetted_perimeter"],
            rtol=1e-5,
        )

    def test_top_width_follows_leopold_maddock(self):
        g = self._geom()
        np.testing.assert_allclose(
            g["top_width"], 21.0 * g["depth"] ** (0.45 + 1e-6), rtol=1e-5
        )

    def test_depth_lower_bound_applied(self):
        import jax.numpy as jnp

        g = self._geom(discharge=jnp.full(6, 1e-9), depth_lb=0.05)
        np.testing.assert_allclose(g["depth"], 0.05, rtol=1e-6)

    def test_bottom_width_lower_bound_applied(self):
        import jax.numpy as jnp

        # q -> 1 (triangular): bottom width collapses onto its floor
        g = self._geom(q_spatial=jnp.full(6, 0.999), bottom_width_lb=0.2)
        assert np.all(g["bottom_width"] >= 0.2 - 1e-6)

    def test_higher_roughness_gives_greater_depth(self):
        import jax.numpy as jnp

        lo = self._geom(n=jnp.full(6, 0.02))
        hi = self._geom(n=jnp.full(6, 0.08))
        assert np.all(hi["depth"] > lo["depth"])

    def test_steeper_slope_gives_lower_depth_higher_velocity(self):
        import jax.numpy as jnp

        mild = self._geom(slope=jnp.full(6, 1e-4))
        steep = self._geom(slope=jnp.full(6, 1e-2))
        assert np.all(steep["depth"] < mild["depth"])
        assert np.all(steep["velocity"] > mild["velocity"])

    def test_q_near_zero_hits_side_slope_floor(self):
        import jax.numpy as jnp

        g = self._geom(q_spatial=jnp.full(6, 1e-6))
        # q -> 0 drives the raw side slope to ~0; the clamp floor (0.5, the
        # reference's physical band) takes over, leaving top - bottom = depth.
        np.testing.assert_allclose(g["side_slope"], 0.5, rtol=1e-5)
        np.testing.assert_allclose(
            g["top_width"] - g["bottom_width"], g["depth"], rtol=1e-4
        )

    def test_velocity_satisfies_manning(self):
        g = self._geom()
        v_manning = (1.0 / 0.035) * g["hydraulic_radius"] ** (2.0 / 3.0) * np.sqrt(2e-3)
        np.testing.assert_allclose(g["velocity"], v_manning, rtol=1e-4)

    def test_discharge_closure_approximately_recovered(self):
        """v * A should reproduce the driving discharge (the Manning inversion is
        exact for the wide-channel closure; tolerance covers the trapezoid
        correction)."""
        g = self._geom()
        q_back = g["velocity"] * g["cross_sectional_area"]
        driving = np.array([0.5, 1.0, 5.0, 20.0, 100.0, 500.0])
        np.testing.assert_allclose(q_back, driving, rtol=0.35)

    def test_output_shapes_match_input(self):
        for v in self._geom().values():
            assert v.shape == (6,)
