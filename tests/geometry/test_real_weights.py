"""The REAL published MERIT geometry weights through the full geometry pipeline.

Every other geometry/import test runs on synthetic state dicts or the Lynker
routing blob; this exercises the actual product path of the reference's
geometry workflow (/root/reference/scripts/geometry_predictor.py:45-309):
ddr-v0.5.2-merit-geometry-weights.pt -> torch import ->
GeometryPredictor.from_reference_checkpoint -> predict() on MERIT-named
attributes — pinning the architecture the blob was trained under
(/root/reference/examples/merit/geometry_config.yaml), a golden forward
against the independent scipy BSpline oracle, and physical-range contracts on
the trapezoidal outputs.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geometry.predictor import GeometryPredictor
from ddr_tpu.nn.torch_import import load_reference_checkpoint
from tests.nn.test_torch_import import _oracle_forward

MERIT_PT = "/root/reference/examples/merit/ddr-v0.5.2-merit-geometry-weights.pt"

# /root/reference/examples/merit/geometry_config.yaml kan: block
MERIT_INPUTS = (
    "SoilGrids1km_clay", "aridity", "meanelevation", "meanP", "NDVI",
    "meanslope", "log10_uparea", "SoilGrids1km_sand", "ETPOT_Hargr", "Porosity",
)
MERIT_PARAMS = ("n", "q_spatial", "p_spatial")

pytestmark = pytest.mark.skipif(
    not os.path.exists(MERIT_PT), reason="reference MERIT weights not mounted"
)


def test_merit_blob_architecture_pins():
    imported = load_reference_checkpoint(MERIT_PT, MERIT_INPUTS, MERIT_PARAMS)
    assert imported.hidden_size == 21
    assert imported.num_hidden_layers == 2
    assert (imported.grid, imported.k) == (50, 2)
    assert (imported.epoch, imported.mini_batch) == (5, 35)


def test_merit_blob_matches_scipy_oracle():
    """Golden forward: the imported flax model on the REAL trained weights must
    match the scipy-BSpline pykan oracle (previously only ever evaluated on
    synthetic state dicts)."""
    import torch

    blob = torch.load(MERIT_PT, map_location="cpu", weights_only=False)
    sd = {k: v.detach().numpy() for k, v in blob["model_state_dict"].items()}
    imported = load_reference_checkpoint(MERIT_PT, MERIT_INPUTS, MERIT_PARAMS)

    x = np.random.default_rng(0).uniform(-0.5, 0.5, (16, len(MERIT_INPUTS))).astype(np.float32)
    got = imported.model.apply(imported.params, jnp.asarray(x))
    want = _oracle_forward(sd, x.astype(np.float64), k=2, n_layers=2)
    for i, name in enumerate(MERIT_PARAMS):
        np.testing.assert_allclose(np.asarray(got[name]), want[:, i], rtol=2e-4, atol=2e-5)


def test_geometry_pipeline_on_real_weights():
    """from_reference_checkpoint -> predict() end to end on MERIT-named
    attributes: all trapezoidal outputs finite and inside their physical
    ranges, learned parameters inside the training parameter_ranges."""
    pred = GeometryPredictor.from_reference_checkpoint(
        MERIT_PT, list(MERIT_INPUTS), list(MERIT_PARAMS)
    )
    rng = np.random.default_rng(1)
    n_reach = 64
    # identity normalization (no stats file in this environment): attributes
    # arrive on the z-scored scale the KAN was trained on
    attrs = {name: rng.normal(0, 0.5, n_reach) for name in MERIT_INPUTS}
    discharge = np.abs(rng.normal(30, 20, n_reach)) + 0.1
    slope = np.abs(rng.normal(5e-3, 2e-3, n_reach)) + 1e-4

    out = pred.predict(attrs, discharge, slope, source="merit")

    for key in (
        "top_width", "depth", "bottom_width", "side_slope", "cross_sectional_area",
        "wetted_perimeter", "hydraulic_radius", "velocity", "n", "p_spatial", "q_spatial",
    ):
        assert key in out, key
        arr = out[key]
        assert arr.shape == (n_reach,), key
        assert np.all(np.isfinite(arr)), key
    # parameter_ranges from the training config (the schema defaults)
    assert np.all((out["n"] >= 0.015) & (out["n"] <= 0.25))
    assert np.all((out["q_spatial"] >= 0.0) & (out["q_spatial"] <= 1.0))
    assert np.all((out["p_spatial"] >= 1.0) & (out["p_spatial"] <= 200.0))
    # physical positivity of the cross-section
    for key in ("top_width", "depth", "bottom_width", "cross_sectional_area",
                "wetted_perimeter", "hydraulic_radius", "velocity"):
        assert np.all(out[key] > 0), key
    # trapezoid consistency: top width >= bottom width
    assert np.all(out["top_width"] >= out["bottom_width"] - 1e-6)
    # trained weights vary across reaches (not a constant predictor)
    assert out["n"].std() > 1e-5
