from tests.geodatazoo.conftest import fabric_dir, merit_cfg  # noqa: F401  (shared fixtures)
