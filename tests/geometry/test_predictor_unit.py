"""GeometryPredictor unit tests with a stub KAN — the reference's mock strategy
(/root/reference/tests/geometry/ TestGeometryPredictor,
TestAdaptAttributes, TestComputeGeometryStatistics) without checkpoint round trips."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geometry.adapters import (
    HYDROATLAS_TO_MERIT,
    MERIT_ATTRIBUTE_NAMES,
    adapt_attributes,
    detect_source,
)
from ddr_tpu.geometry.predictor import GeometryPredictor
from ddr_tpu.geometry.statistics import GEOMETRY_VARS, compute_geometry_statistics

PARAM_RANGES = {"n": [0.015, 0.25], "q_spatial": [0.0, 1.0], "p_spatial": [1.0, 200.0]}


class _StubKan:
    """Deterministic stand-in for the flax KAN: constant sigmoid outputs."""

    def __init__(self, outputs=("n", "q_spatial", "p_spatial"), value=0.5):
        self.outputs = outputs
        self.value = value

    def apply(self, params, x):
        return {k: jnp.full(x.shape[0], self.value, jnp.float32) for k in self.outputs}


def _predictor(outputs=("n", "q_spatial", "p_spatial"), stats_ranges=None):
    a = len(MERIT_ATTRIBUTE_NAMES)
    return GeometryPredictor(
        kan_model=_StubKan(outputs),
        kan_params={},
        attribute_names=list(MERIT_ATTRIBUTE_NAMES),
        means=np.full(a, 5.0),
        stds=np.full(a, 2.0),
        parameter_ranges={k: PARAM_RANGES[k] for k in PARAM_RANGES if k in outputs or k != "p_spatial"},
        log_space_parameters=["p_spatial"],
        defaults={"p_spatial": 21.0},
        attribute_minimums={"depth": 0.01, "bottom_width": 0.01, "slope": 0.001},
        stats_ranges=stats_ranges,
    )


def _attrs(n=8, value=5.0):
    return {name: np.full(n, value) for name in MERIT_ATTRIBUTE_NAMES}


class TestPredictOutputs:
    def test_returns_all_geometry_vars(self):
        out = _predictor().predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        for var in GEOMETRY_VARS + ("velocity", "cross_sectional_area", "wetted_perimeter"):
            assert var in out, var
        for p in ("n", "p_spatial", "q_spatial"):
            assert p in out, p

    def test_output_shape(self):
        out = _predictor().predict(_attrs(12), discharge=np.ones(12), slope=np.full(12, 0.01))
        for v in out.values():
            assert v.shape == (12,)

    def test_all_values_positive(self):
        out = _predictor().predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        for name, v in out.items():
            assert (v > 0).all(), name

    def test_n_within_configured_bounds(self):
        out = _predictor().predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        lo, hi = PARAM_RANGES["n"]
        assert (out["n"] >= lo).all() and (out["n"] <= hi).all()

    def test_q_spatial_within_bounds(self):
        out = _predictor().predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        assert (out["q_spatial"] >= 0).all() and (out["q_spatial"] <= 1).all()

    def test_p_spatial_log_space_midpoint(self):
        """sigmoid 0.5 through log-space [1, 200] lands at sqrt(200), not 100.5."""
        out = _predictor().predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        np.testing.assert_allclose(out["p_spatial"], np.sqrt(200.0), rtol=2e-2)

    def test_p_spatial_default_when_not_learned(self):
        """A KAN trained without p_spatial falls back to the config default
        (reference predictor behavior for MERIT-era checkpoints)."""
        pred = _predictor(outputs=("n", "q_spatial"))
        out = pred.predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        np.testing.assert_allclose(out["p_spatial"], 21.0, rtol=1e-6)

    def test_deterministic(self):
        p = _predictor()
        a = p.predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        b = p.predict(_attrs(), discharge=np.ones(8), slope=np.full(8, 0.01))
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])

    def test_accepts_hydroatlas_names(self):
        """source='auto' converts HydroATLAS attributes before normalization."""
        n = 6
        attrs = {name: np.full(n, 5.0) for name in HYDROATLAS_TO_MERIT}
        out = _predictor().predict(attrs, discharge=np.ones(n), slope=np.full(n, 0.01))
        assert out["depth"].shape == (n,)
        assert np.isfinite(out["depth"]).all()

    def test_discharge_slope_floors_applied(self):
        """Zero discharge / zero slope are floored by attribute_minimums, not NaN."""
        out = _predictor().predict(
            _attrs(), discharge=np.zeros(8), slope=np.zeros(8)
        )
        for name, v in out.items():
            assert np.isfinite(v).all(), name

    def test_predict_parameters_batched_path(self):
        pred = _predictor()
        params = pred.predict_parameters(np.zeros((16, len(MERIT_ATTRIBUTE_NAMES)), np.float32))
        assert set(params) == {"n", "q_spatial", "p_spatial"}
        assert params["n"].shape == (16,)

    def test_ood_below_p10_warns(self, caplog):
        pred = _predictor(
            stats_ranges={name: {"p10": 1.0, "p90": 9.0} for name in MERIT_ATTRIBUTE_NAMES}
        )
        with caplog.at_level("WARNING"):
            pred.predict(_attrs(value=-50.0), discharge=np.ones(8), slope=np.full(8, 0.01))
        assert "below training p10" in caplog.text


class TestDetectSourcePrecedence:
    def test_merit_takes_precedence_over_extra_vars(self):
        """A dataset carrying BOTH name sets detects as MERIT (reference
        test_merit_takes_precedence_over_extra_vars)."""
        attrs = {name: np.zeros(3) for name in MERIT_ATTRIBUTE_NAMES}
        attrs.update({name: np.zeros(3) for name in HYDROATLAS_TO_MERIT})
        assert detect_source(attrs) == "merit"

    def test_partial_merit_is_not_detected(self):
        attrs = {name: np.zeros(3) for name in MERIT_ATTRIBUTE_NAMES[:5]}
        assert detect_source(attrs) is None

    def test_extra_unknown_vars_ignored(self):
        attrs = {name: np.zeros(3) for name in MERIT_ATTRIBUTE_NAMES}
        attrs["extra_junk"] = np.zeros(3)
        assert detect_source(attrs) == "merit"
        out = adapt_attributes(attrs)
        assert "extra_junk" not in out

    def test_explicit_merit_source_skips_detection(self):
        attrs = {name: np.arange(3.0) for name in MERIT_ATTRIBUTE_NAMES}
        out = adapt_attributes(attrs, source="merit")
        assert list(out) == list(MERIT_ATTRIBUTE_NAMES)

    def test_missing_merit_attribute_raises(self):
        attrs = {name: np.zeros(3) for name in MERIT_ATTRIBUTE_NAMES[:-1]}
        with pytest.raises(ValueError, match="Missing MERIT"):
            adapt_attributes(attrs, source="merit")


class TestStatisticsBehaviors:
    def _stats(self, q):
        n_reach = q.shape[1]
        return compute_geometry_statistics(
            n=np.full(n_reach, 0.05),
            p_spatial=np.full(n_reach, 21.0),
            q_spatial=np.full(n_reach, 0.4),
            slope=np.full(n_reach, 0.005),
            daily_accumulated_discharge=q,
        )

    def test_constant_discharge_gives_equal_stats(self):
        stats = self._stats(np.full((10, 4), 7.0))
        for var in GEOMETRY_VARS:
            np.testing.assert_allclose(stats[f"{var}_min"], stats[f"{var}_max"], rtol=1e-6)
            np.testing.assert_allclose(stats[f"{var}_mean"], stats[f"{var}_median"], rtol=1e-6)

    def test_attribute_minimums_forwarded(self):
        n_reach = 3
        stats = compute_geometry_statistics(
            n=np.full(n_reach, 0.05),
            p_spatial=np.full(n_reach, 21.0),
            q_spatial=np.full(n_reach, 0.4),
            slope=np.full(n_reach, 0.005),
            daily_accumulated_discharge=np.full((4, n_reach), 1e-9),
            attribute_minimums={"depth": 0.42},
        )
        np.testing.assert_allclose(stats["depth_min"], 0.42, rtol=1e-6)

    def test_nan_days_ignored(self):
        q = np.full((6, 3), 5.0)
        q[2, :] = np.nan
        stats = self._stats(q)
        assert np.isfinite(stats["discharge_mean"]).all()
        np.testing.assert_allclose(stats["discharge_mean"], 5.0, rtol=1e-6)

    def test_median_reflects_distribution(self):
        q = np.concatenate([np.full((9, 2), 1.0), np.full((1, 2), 100.0)])
        stats = self._stats(q)
        np.testing.assert_allclose(stats["discharge_median"], 1.0, rtol=1e-6)
        assert (stats["discharge_mean"] > 10.0).all()
