"""Elastic mid-epoch resume: mesh/sharding provenance in checkpoints, the
any-mesh -> any-mesh reshard-load matrix, torn sharded (orbax) writes, the
serving watcher's half-committed-dir discipline, and the CompileTracker-pinned
cross-mesh resume e2e (docs/robustness.md "Elastic resume & resharding")."""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddr_tpu.observability import faults
from ddr_tpu.parallel.sharding import (
    make_mesh,
    mesh_descriptor,
    mesh_mismatch,
    reach_sharding,
    reshard_state,
    state_sharding_specs,
)
from ddr_tpu.training import (
    AsyncCheckpointWriter,
    checkpoint_candidates,
    latest_checkpoint,
    load_state,
    save_state,
    save_state_orbax,
)

PARAMS = {"w": np.ones((3, 3), np.float32)}
OPT = {"m": np.zeros(3, np.float32)}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.configure(None)


def _need(n: int) -> None:
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _sharded_state(mesh):
    """params + opt state with one genuinely reach-sharded leaf each (dim 0
    sized 8: divisible by every mesh width the matrix uses) plus replicated
    leaves, so both placement classes cross every mesh transition."""
    rng = np.random.default_rng(7)
    sh = reach_sharding(mesh, rank_1_axis=0, ndim=2)
    params = {
        "w": jax.device_put(
            jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32)), sh
        ),
        "b": jnp.asarray(rng.normal(size=(3,)).astype(np.float32)),
    }
    opt_state = {
        "mu": jax.device_put(
            jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32)), sh
        ),
        "count": jnp.asarray(4, jnp.int32),
    }
    return params, opt_state


class TestProvenance:
    def test_pickle_blob_records_mesh_and_sharding(self, tmp_path):
        _need(4)
        mesh = make_mesh(4)
        p = save_state(tmp_path, "t", 1, 0, PARAMS, OPT, mesh=mesh)
        blob = load_state(p)
        assert blob["mesh"]["n_devices"] == 4
        assert blob["mesh"]["axes"] == ["reach"]
        assert blob["mesh"]["topology"]
        assert len(blob["sharding"]["leaves"]) == len(blob["sharding"]["paths"])
        # the manifest sidecar carries the same descriptor (scanners can read
        # provenance without unpickling the blob)
        manifest = json.loads(
            p.with_name(p.name + ".manifest.json").read_text()
        )
        assert manifest["mesh"]["n_devices"] == 4

    def test_mesh_mismatch_semantics(self):
        _need(4)
        d4, d2 = mesh_descriptor(make_mesh(4)), mesh_descriptor(make_mesh(2))
        assert mesh_mismatch(d4, d2)
        assert not mesh_mismatch(d4, mesh_descriptor(make_mesh(4)))
        # pre-provenance checkpoints (no mesh recorded) never mismatch
        assert not mesh_mismatch(None, d2)
        assert not mesh_mismatch({}, d2)

    def test_sharding_specs_record_live_layout(self):
        _need(2)
        mesh = make_mesh(2)
        params, opt_state = _sharded_state(mesh)
        specs = state_sharding_specs({"params": params, "opt_state": opt_state})
        by_path = dict(zip(specs["paths"], specs["leaves"]))
        sharded = [s for s in specs["leaves"] if s is not None]
        assert len(sharded) == 2  # w and mu
        assert all(s[0] == "reach" for s in sharded)
        # replicated leaves record None, truthfully
        assert sum(1 for s in specs["leaves"] if s is None) == 2
        assert len(by_path) == 4


class TestReshardMatrix:
    """Save on mesh A (orbax, sharded leaves), restore untargeted, reshard
    onto mesh B: sharded->smaller, sharded->single-device, single->sharded,
    grown meshes — params, opt state, and rng state all bitwise intact."""

    @pytest.mark.parametrize("src,dst", [(4, 2), (4, 1), (1, 4), (2, 4)])
    def test_round_trip_bitwise(self, tmp_path, src, dst):
        _need(max(src, dst))
        mesh_src = make_mesh(src)
        params, opt_state = _sharded_state(mesh_src)
        rng_state = {"bit_generator": "MT19937", "pos": 3}
        ckpt = save_state_orbax(
            tmp_path, "m", 2, 5, params, opt_state,
            rng_state=rng_state, mesh=mesh_src,
        )
        blob = load_state(ckpt)
        assert blob["mesh"]["n_devices"] == src
        assert blob["rng_state"] == rng_state
        restored = reshard_state(
            {"params": blob["params"], "opt_state": blob["opt_state"]},
            make_mesh(dst),
            plan=blob.get("sharding"),
        )
        saved = jax.tree_util.tree_leaves(
            {"params": params, "opt_state": opt_state}
        )
        fresh = jax.tree_util.tree_leaves(restored)
        assert len(saved) == len(fresh)
        for a, b in zip(saved, fresh):
            assert len(b.sharding.device_set) <= dst
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_plan_misalignment_degrades_to_replication(self, tmp_path):
        """A plan whose leaf count no longer matches the restored tree (an
        orbax untargeted restore can rewrite container types) must never
        misplace leaves by position — everything replicates, values intact."""
        _need(2)
        mesh = make_mesh(2)
        params, opt_state = _sharded_state(mesh)
        state = {"params": params, "opt_state": opt_state}
        bad_plan = {"paths": ["a"], "leaves": [["reach"]]}  # wrong length
        out = reshard_state(state, mesh, plan=bad_plan)
        for a, b in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(out)
        ):
            assert b.sharding.is_fully_replicated
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestTornShardedWrite:
    def test_torn_meta_quarantines_whole_step(self, tmp_path):
        """A crash between the orbax array commit and the meta.json marker
        (the torn SHARDED write) leaves a meta-less dir that every scan skips
        — the whole step is quarantined, the previous checkpoint wins, and
        the async writer surfaces the failure on drain."""
        _need(2)
        mesh = make_mesh(2)
        params, opt_state = _sharded_state(mesh)
        good = save_state(tmp_path, "t", 1, 0, PARAMS, OPT)
        faults.configure("crash@checkpoint.write")
        w = AsyncCheckpointWriter()
        try:
            w.save_orbax(tmp_path, "t", 1, 1, params, opt_state, mesh=mesh)
            with pytest.raises(RuntimeError, match="checkpoint write failed"):
                w.drain(timeout=30.0)
        finally:
            try:
                w.close()
            except RuntimeError:
                pass
        faults.configure(None)
        torn = tmp_path / "_t_epoch_1_mb_1.orbax"
        assert torn.is_dir() and not (torn / "meta.json").exists()
        assert checkpoint_candidates(tmp_path) == [good]
        assert latest_checkpoint(tmp_path) == good

    def test_async_save_orbax_lands_with_provenance(self, tmp_path):
        _need(2)
        mesh = make_mesh(2)
        params, opt_state = _sharded_state(mesh)
        w = AsyncCheckpointWriter()
        try:
            w.save_orbax(
                tmp_path, "t", 1, 0, params, opt_state,
                rng_state={"x": 1}, mesh=mesh,
            )
            assert w.drain(timeout=30.0)
        finally:
            w.close()
        p = latest_checkpoint(tmp_path)
        assert p is not None and p.suffix == ".orbax"
        blob = load_state(p)
        assert blob["mesh"]["n_devices"] == 2
        # specs were captured from the LIVE leaves on the loop thread, so the
        # sharded layout survives into provenance despite the host snapshot
        assert any(s is not None for s in blob["sharding"]["leaves"])
        assert blob["rng_state"] == {"x": 1}

    def test_snapshot_owns_its_bytes(self):
        """On the CPU backend ``jax.device_get`` can return ZERO-COPY views of
        the live XLA buffer; buffer donation or teardown then frees the memory
        under the writer thread mid-serialization (seen as 1e32 garbage in a
        chaos-drill checkpoint). The snapshot must own every leaf outright."""
        from ddr_tpu.training import _owned_host_snapshot

        x = jnp.arange(8, dtype=jnp.float32)
        # the raw device_get really is the hazard on this backend...
        raw = jax.device_get({"x": x})["x"]
        if raw.flags.owndata:
            pytest.skip("device_get copies on this backend; nothing to pin")
        # ...and the snapshot helper removes it
        snap = _owned_host_snapshot({"x": x, "n": 3})
        assert snap["x"].flags.owndata
        assert snap["n"] == 3
        np.testing.assert_array_equal(snap["x"], np.arange(8, dtype=np.float32))

    def test_save_orbax_refuses_multiprocess(self, tmp_path, monkeypatch):
        w = AsyncCheckpointWriter()
        try:
            monkeypatch.setattr(jax, "process_count", lambda: 2)
            with pytest.raises(RuntimeError, match="single-controller"):
                w.save_orbax(tmp_path, "t", 1, 0, PARAMS, OPT)
        finally:
            monkeypatch.undo()
            w.close()


class TestWatcherShardedSkip:
    def test_half_committed_sharded_checkpoint_is_skipped(self, tmp_path):
        """The serving watcher must treat a meta-less orbax dir (a writer
        killed between array commit and marker) exactly like a torn pickle:
        invisible — the previous good checkpoint swaps in instead."""
        from ddr_tpu.serving.registry import ModelRegistry

        _need(2)
        reg = ModelRegistry()
        reg.register("m", kan_model=object(), params={"w": np.zeros(2)})
        save_state(tmp_path, "t", 1, 0, PARAMS, OPT)
        mesh = make_mesh(2)
        params, opt_state = _sharded_state(mesh)
        ob = save_state_orbax(tmp_path, "t", 1, 1, params, opt_state, mesh=mesh)
        (ob / "meta.json").unlink()  # the preempted-save shape
        from ddr_tpu.serving.registry import CheckpointWatcher

        watcher = CheckpointWatcher(
            registry=reg, name="m", directory=tmp_path, expected_arch=None
        )
        assert watcher.check_now() is True
        entry = reg.get("m")
        assert entry.version == 2
        np.testing.assert_array_equal(np.asarray(entry.params["w"]), PARAMS["w"])

    def test_watcher_loads_cross_mesh_checkpoint(self, tmp_path):
        """A checkpoint saved under a training mesh loads into a serving
        process on a different layout: device_params collapses the restored
        leaves to replicated jit arguments."""
        from ddr_tpu.serving.registry import ModelRegistry

        _need(4)
        reg = ModelRegistry()
        reg.register("m", kan_model=object(), params={"w": np.zeros((8, 3))})
        mesh = make_mesh(4)
        params, opt_state = _sharded_state(mesh)
        save_state_orbax(tmp_path, "t", 1, 0, params, opt_state, mesh=mesh)
        from ddr_tpu.serving.registry import CheckpointWatcher

        watcher = CheckpointWatcher(
            registry=reg, name="m", directory=tmp_path, expected_arch=None
        )
        assert watcher.check_now() is True
        entry = reg.get("m")
        for leaf in jax.tree_util.tree_leaves(entry.params):
            assert len(leaf.sharding.device_set) == 1
        np.testing.assert_array_equal(
            np.asarray(entry.params["w"]), np.asarray(params["w"])
        )


# ---------------------------------------------------------------------------
# e2e: cross-mesh resume through the real training loop.
# ---------------------------------------------------------------------------


def _cfg(tmp_path, device, **exp):
    from ddr_tpu.validation.configs import Config

    return Config(**{
        "name": "elastic",
        "geodataset": "synthetic",
        "mode": "training",
        "device": device,
        "kan": {"input_var_names": [f"a{i}" for i in range(10)]},
        "experiment": {
            "start_time": "1981/10/01",
            "end_time": "1981/10/20",
            "rho": 8,
            "batch_size": 1,
            "epochs": 1,
            "warmup": 1,
            "learning_rate": {1: 0.01},
            "shuffle": False,
            "parallel": "auto",
            **exp,
        },
        "params": {"save_path": str(tmp_path)},
    })


@pytest.mark.slow
def test_train_resume_across_meshes_emits_reshard_event(tmp_path, monkeypatch):
    """THE elastic-resume acceptance: train on a cpu:4 mesh, resume the same
    run on cpu:2 — the trainer detects the mesh change, reshard-loads the
    checkpoint, logs exactly one `reshard` event, keeps training, and pays no
    jit-cache growth beyond the expected new-mesh recompile (no more `compile`
    events than the cold run of equal length)."""
    from ddr_tpu.observability import run_telemetry
    from ddr_tpu.scripts.train import train

    _need(4)
    monkeypatch.setenv("DDR_CKPT_ASYNC", "0")  # deterministic write ordering
    run1 = tmp_path / "r1"
    with run_telemetry(_cfg(run1, "cpu:4"), "train", base_dir=str(run1)):
        train(_cfg(run1, "cpu:4"), max_batches=2)
    events1 = [
        json.loads(line)
        for line in (run1 / "run_log.train.jsonl").read_text().splitlines()
    ]
    compiles1 = [e for e in events1 if e["event"] == "compile"]
    saved = run1 / "saved_models"
    blob = load_state(latest_checkpoint(saved))
    assert blob["mesh"]["n_devices"] == 4

    cfg2 = _cfg(run1, "cpu:2")
    cfg2.experiment.checkpoint = saved
    run2 = tmp_path / "r2"
    with run_telemetry(cfg2, "train", base_dir=str(run2)):
        params, _ = train(cfg2, max_batches=2)
    assert params is not None
    events = [
        json.loads(line)
        for line in (run2 / "run_log.train.jsonl").read_text().splitlines()
    ]
    reshards = [e for e in events if e["event"] == "reshard"]
    assert len(reshards) == 1
    assert reshards[0]["from_mesh"]["n_devices"] == 4
    assert reshards[0]["to_mesh"]["n_devices"] == 2
    steps = [i for i, e in enumerate(events) if e["event"] == "step"]
    assert len(steps) >= 2, "resume made no progress"
    # the mesh change buys exactly the expected new-mesh recompile set: one
    # compile per batch topology, same as the cold run of equal length —
    # resharded state must not force extra per-step cache entries (a stale
    # layout would double-compile every batch)
    compiles2 = [e for e in events if e["event"] == "compile"]
    assert len(compiles2) <= max(len(compiles1), len(steps)), (
        f"jit cache grew beyond the new-mesh recompile: {compiles2}"
    )
    # and the new mesh's checkpoints carry the NEW provenance
    blob2 = load_state(latest_checkpoint(saved))
    assert blob2["mesh"]["n_devices"] == 2
