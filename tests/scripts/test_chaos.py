"""``ddr chaos`` harness: log-harvest units, CLI plumbing, and the slow
kill-and-resume acceptance e2es (train SIGKILL x2 + serve kill/restart under
load, both gated by check_bench_regression)."""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from ddr_tpu.scripts import chaos


class TestUnits:
    def test_read_jsonl_tolerates_torn_tail(self, tmp_path):
        p = tmp_path / "log.jsonl"
        p.write_text('{"event": "step", "loss": 1.0}\n{"event": "st')
        events = chaos._read_jsonl(p)
        assert len(events) == 1
        assert chaos._read_jsonl(tmp_path / "missing.jsonl") == []

    def test_step_losses_keyed_by_epoch_batch(self):
        events = [
            {"event": "step", "epoch": 1, "batch": 0, "loss": 2.0},
            {"event": "step", "epoch": 1, "batch": 1, "loss": 1.5},
            {"event": "heartbeat", "epoch": 1},
            {"event": "step", "epoch": 1, "batch": 1, "loss": 1.4},  # last wins
        ]
        assert chaos._step_losses(events) == {(1, 0): 2.0, (1, 1): 1.4}

    def test_train_cfg_resumes_from_own_saved_models(self, tmp_path):
        class A:
            segments, epochs = 32, 1

        cfg = chaos._train_cfg_dict(tmp_path / "run", tmp_path / "run/saved_models", A)
        assert cfg["experiment"]["checkpoint"] == str(tmp_path / "run/saved_models")
        assert cfg["experiment"]["shuffle"] is False  # resume determinism
        cfg2 = chaos._train_cfg_dict(tmp_path / "g", None, A)
        assert "checkpoint" not in cfg2["experiment"]

    def test_parse_reshard(self):
        assert chaos._parse_reshard(None) is None
        assert chaos._parse_reshard("") is None
        assert chaos._parse_reshard("4:2") == (4, 2)
        assert chaos._parse_reshard("1:8") == (1, 8)
        for bad in ("4", "4:2:1", "a:b", "4:0", "0:2", "-1:2"):
            with pytest.raises(SystemExit):
                chaos._parse_reshard(bad)

    def test_train_cfg_reshard_adds_device_and_parallel(self, tmp_path):
        class A:
            segments, epochs = 32, 1

        cfg = chaos._train_cfg_dict(tmp_path / "r", None, A, device="cpu:4")
        assert cfg["device"] == "cpu:4"
        assert cfg["experiment"]["parallel"] == "auto"
        # without --reshard the config is exactly what it always was
        cfg2 = chaos._train_cfg_dict(tmp_path / "r", None, A)
        assert "device" not in cfg2
        assert "parallel" not in cfg2["experiment"]

    def test_subprocess_env_defaults_compile_cache(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DDR_COMPILE_CACHE_DIR", raising=False)
        monkeypatch.setenv("DDR_METRICS_DIR", "/nope")
        env = chaos._subprocess_env(tmp_path)
        assert env["DDR_COMPILE_CACHE_DIR"] == str(tmp_path / "xla_cache")
        assert "DDR_METRICS_DIR" not in env
        monkeypatch.setenv("DDR_COMPILE_CACHE_DIR", "/pinned")
        assert chaos._subprocess_env(tmp_path)["DDR_COMPILE_CACHE_DIR"] == "/pinned"

    def test_render_summary_both_modes(self):
        train_rep = {
            "mode": "train", "label": "x", "passed": True, "kills": [1, 2],
            "signal": "kill", "steps_chaos": 4, "steps_golden": 4,
            "steps_missing": 0, "loss_delta": 0.0, "params_max_abs_delta": 0.0,
            "tolerance": 1e-4, "recovery_s": 3.2,
        }
        out = chaos.render_summary(train_rep)
        assert "PASSED" in out and "kills" in out
        serve_rep = {
            "mode": "serve", "label": "y", "passed": False, "recovery_s": 9.9,
            "kill_after_s": 3.0, "requests": 10, "ok": 2, "errors": 8,
            "error_rate": 0.8, "post_restart_attainment": None,
            "post_restart_requests": 0,
        }
        out = chaos.render_summary(serve_rep)
        assert "FAILED" in out and "recovery 9.9s" in out

    def test_render_summary_fleet_branch(self):
        fleet_rep = {
            "mode": "serve", "fleet": True, "label": "z", "passed": True,
            "killed_replica": 1, "replicas": 2, "eject_s": 0.4,
            "recovery_s": 6.2, "federation_saw_dead": True,
            "federate_up": {"chaos-r0": "1", "chaos-r1": "0"},
            "requests": 85, "ok": 85, "errors": 0, "error_rate": 0.0,
            "post_restart_attainment": 1.0, "post_restart_requests": 12,
        }
        out = chaos.render_summary(fleet_rep)
        assert "PASSED" in out
        assert "killed replica 1 of 2" in out
        assert "re-admitted in 6.2s" in out
        assert "survivor scrape saw the dead member: True" in out
        assert "post-restart attainment 100.00%" in out

    def test_cli_requires_mode_and_serve_requires_synthetic(self, capsys, tmp_path):
        assert chaos.main([]) == 2
        with pytest.raises(SystemExit):
            chaos.run_chaos_serve(
                type("A", (), {"synthetic": False, "url": None})()
            )

    def test_chaos_command_is_dispatchable(self):
        from ddr_tpu.cli import _COMMANDS

        assert _COMMANDS["chaos"] == "ddr_tpu.scripts.chaos"


def _shared_cache_env(monkeypatch):
    """Point subprocess XLA caches at the test harness's warm cache so the
    e2es replay compiles instead of re-paying them per subprocess."""
    import jax

    cache = jax.config.jax_compilation_cache_dir
    if cache:
        monkeypatch.setenv("DDR_COMPILE_CACHE_DIR", cache)


@pytest.mark.slow
def test_chaos_train_sigkill_resume_matches_golden(tmp_path, monkeypatch):
    """THE kill-and-resume acceptance: a real training subprocess SIGKILLed at
    two distinct mini-batches resumes each time, and the full loss trajectory
    + final params match the uninterrupted golden run within tolerance."""
    _shared_cache_env(monkeypatch)
    rc = chaos.main([
        "train", "--kills", "1,2", "--label", "e2e", "--out", str(tmp_path),
        "--timeout", "240",
    ])
    assert rc == 0
    report = json.loads((tmp_path / "CHAOS_e2e.json").read_text())
    assert report["passed"] is True
    assert report["kills"] == [1, 2]
    assert report["steps_missing"] == 0
    assert report["steps_chaos"] == report["steps_golden"] >= 4
    assert report["loss_delta"] <= report["tolerance"]
    assert report["params_max_abs_delta"] <= report["tolerance"]
    assert report["recovery_s"] > 0
    # the harness's own telemetry recorded the kills and resumes
    log = tmp_path / "run_log.chaos.jsonl"
    events = chaos._read_jsonl(log)
    actions = [e["action"] for e in events if e["event"] == "chaos"]
    assert actions.count("kill") == 2 and actions.count("resume") == 2


@pytest.mark.slow
def test_chaos_serve_synthetic_recovers_and_passes_gate(tmp_path, monkeypatch):
    """`ddr chaos serve --synthetic` completes: the replica is SIGKILLed under
    open-loop load, restarts, recovers, and the CHAOS record passes the
    check_bench_regression gate."""
    _shared_cache_env(monkeypatch)
    rc = chaos.main([
        "serve", "--synthetic", "--rps", "8", "--duration", "8",
        "--kill-after", "2.5", "--label", "se2e", "--out", str(tmp_path),
        "--boot-timeout", "240",
    ])
    assert rc == 0
    record = tmp_path / "CHAOS_se2e.json"
    report = json.loads(record.read_text())
    assert report["recovered"] is True and report["passed"] is True
    assert report["recovery_s"] > 0
    assert report["post_restart_requests"] > 0
    assert report["post_restart_attainment"] > 0.5
    # the outage is visible in the storm's error accounting
    assert report["errors"] > 0

    # and the new regression gate accepts it (self-compare: no regressions)
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "cbr", Path(__file__).resolve().parents[2] / "scripts/check_bench_regression.py"
    )
    cbr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cbr)
    assert cbr.main([str(record), "--baseline", str(record), "--strict"]) == 0
