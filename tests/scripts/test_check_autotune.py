"""scripts/check_autotune.py: the auto-tuner CI gate must pass on a clean
tree (score -> persist -> warm-cache card-build-free reselect -> off-mode
policy parity) and actually catch a cold cache."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_autotune.py"


def _run(tmp_path, **env_overrides):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        DDR_TUNE_CACHE_DIR=str(tmp_path / "tune-cache"),
        **env_overrides,
    )
    return subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300, env=env,
    )


def test_repo_autotune_gate_passes(tmp_path):
    """THE CI gate: score a tiny topology, persist the winner, and prove the
    second (memo-cleared) invocation is a cache hit with zero card builds."""
    proc = _run(tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "card-build-free" in proc.stdout
    plans = list((tmp_path / "tune-cache").glob("plan_*.json"))
    assert len(plans) == 1
    rec = json.loads(plans[0].read_text())
    assert rec["engine"] == "gspmd"


def test_gate_fails_on_a_poisoned_cache(tmp_path):
    """A cache entry whose engine contradicts the scorer must fail stage 2
    (cached winner != scored winner) — the gate is a real check, not a
    tautology. Poison by pre-seeding the exact plan key the gate queries."""
    from ddr_tpu.tuning.cache import plan_key

    cache_dir = tmp_path / "tune-cache"
    cache_dir.mkdir(parents=True)
    key = plan_key(
        "check-autotune-topology",
        {"axes": ["reach"], "shape": [1], "platform": "cpu", "n_devices": 1},
        "fp32",
        None,
    )
    # planner_version must match or the entry is (correctly) ignored
    from ddr_tpu.tuning.cache import PLANNER_VERSION

    (cache_dir / f"plan_{key}.json").write_text(json.dumps({
        "engine": "stacked-sharded", "planner_version": PLANNER_VERSION,
    }))
    proc = _run(tmp_path)
    assert proc.returncode == 1
    assert "source" in proc.stderr or "winner" in proc.stderr
