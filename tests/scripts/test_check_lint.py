"""``scripts/check_lint.py`` gate tests: the committed tree lints clean, in
seconds, without importing jax — and a planted hazard flips the exit code.

All subprocess-based (like the other check_* gate tests): the contract under
test is the CLI's, not the library's."""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_lint.py"

_BAD = "def seed_for(name):\n    return hash(name) % 2**31\n"


def _run(*args, cwd=REPO, timeout=60):
    return subprocess.run(
        [sys.executable, str(SCRIPT), *args],
        capture_output=True, text=True, cwd=cwd, timeout=timeout,
    )


def test_committed_tree_is_clean_and_fast():
    """The acceptance pin: exit 0 on the repo as committed, well under 10s
    (the analyzer parses ~130 files; jax import alone would blow the wall)."""
    t0 = time.monotonic()
    proc = _run()
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ddr lint: clean" in proc.stdout
    assert elapsed < 10.0, f"gate took {elapsed:.1f}s — the <10s contract broke"


def test_analyzer_never_imports_jax():
    """Pure-AST contract, asserted via sys.modules in a fresh interpreter:
    after a full-tree run, jax must be absent (ddr_tpu/__init__.py is empty
    and ddr_tpu.analysis is stdlib-only)."""
    code = (
        "import sys; sys.path.insert(0, '.')\n"
        "from ddr_tpu.analysis.cli import main\n"
        "rc = main(['--root', '.'])\n"
        "print('JAX_IMPORTED' if 'jax' in sys.modules else 'JAX_ABSENT')\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-S", "-c", code],  # -S: skip any jax-preloading sitecustomize
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "JAX_ABSENT" in proc.stdout


def test_planted_hazard_exits_1(tmp_path):
    (tmp_path / "ddr_tpu").mkdir()
    (tmp_path / "ddr_tpu" / "bad.py").write_text(_BAD)
    proc = _run("--root", str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "DDR301" in proc.stdout


def test_malformed_baseline_exits_2(tmp_path):
    (tmp_path / "ddr_tpu").mkdir()
    (tmp_path / "ddr_tpu" / "ok.py").write_text("X = 1\n")
    (tmp_path / "lint_baseline.json").write_text("{nope")
    proc = _run("--root", str(tmp_path))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "internal error" in proc.stderr


def test_forwarded_args_json_strict():
    """check_lint forwards lint args; strict mode over the committed tree
    must report only findings the committed baseline justifies."""
    proc = _run("--no-baseline", "--format", "json")
    doc = json.loads(proc.stdout)
    if proc.returncode == 0:
        assert doc["summary"]["findings"] == 0
    else:
        assert proc.returncode == 1
        baseline = json.loads((REPO / "lint_baseline.json").read_text())
        allowed = {(e["rule"], e["path"]) for e in baseline["entries"]}
        for f in doc["findings"]:
            assert (f["rule"], f["path"]) in allowed, f
