"""scripts/check_recovery.py: the self-healing smoke gate must pass on a
clean tree (so recovery-ladder bit-rot fails tier-1 fast) and actually catch
breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_recovery.py"


def test_repo_recovery_smokes_clean():
    """THE CI gate: a nan fault clause through the real watchdog + supervisor
    yields one recovery event, a bitwise restore, and a bounded give-up."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bounded give-up" in proc.stdout


def test_gate_fails_on_broken_recovery_module(tmp_path):
    """A tree whose recovery module cannot import must fail the gate — copy
    the script next to a stub package with a broken observability.recovery."""
    pkg = tmp_path / "ddr_tpu" / "observability"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_recovery.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_recovery.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
