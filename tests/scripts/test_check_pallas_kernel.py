"""scripts/check_pallas_kernel.py: the fused-kernel smoke gate must pass on a
clean tree (so Pallas bit-rot fails tier-1 fast) and actually catch breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_pallas_kernel.py"


def test_repo_kernel_smokes_clean():
    """THE CI gate: the Pallas module imports and one interpreted wave scan on
    CPU matches the XLA reference."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "matches the XLA reference" in proc.stdout


def test_gate_fails_on_broken_kernel(tmp_path):
    """A tree whose pallas module cannot import must fail the gate — copy the
    script next to a stub package with a broken pallas_kernel."""
    pkg = tmp_path / "ddr_tpu" / "routing"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "pallas_kernel.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_pallas_kernel.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_pallas_kernel.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
