"""scripts/check_audit.py: the spatial-attribution smoke gate must pass on a
clean tree (so a localization regression fails tier-1 fast) and actually
catch breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_audit.py"


def test_repo_audit_smokes_clean():
    """THE CI gate: one tiny synthetic audit on CPU localizes its injected
    anomaly to the right band and reach."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "localizes the injected anomaly" in proc.stdout


def test_gate_fails_on_broken_audit(tmp_path):
    """A tree whose audit module cannot import must fail the gate — copy the
    script next to a stub package with a broken scripts/audit.py."""
    pkg = tmp_path / "ddr_tpu" / "scripts"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "audit.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_audit.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_audit.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
