"""scripts/check_trace.py: the fleet-trace smoke gate must pass on a clean
tree (so cross-host id/export bit-rot fails tier-1 fast) and actually catch
breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_trace.py"


def test_repo_trace_gate_clean():
    """THE CI gate: a 2-process synthetic run merges into a Perfetto export
    with one step trace on both host tracks and fully resolvable parents."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "both host tracks" in proc.stdout
    assert "all parent ids resolve" in proc.stdout


def test_gate_fails_on_broken_observability_module(tmp_path):
    """A tree whose observability package cannot import must fail the gate —
    copy the script next to a stub package with a broken __init__."""
    pkg = tmp_path / "ddr_tpu" / "observability"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_trace.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_trace.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
