"""scripts/check_verify.py: the verification-plane smoke gate must pass on a
clean tree (so ledger/scorer/HTTP-join bit-rot fails tier-1 fast) and
actually catch breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_verify.py"


def test_repo_verify_gate_clean():
    """THE CI gate: forecasts ledgered over HTTP, /v1/observe joins + scores
    them (streaming == offline CRPS, sharp < degraded), the verify event /
    stats slice / ddr_verify_* series appear, and zero programs compile."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "verification plane holds" in proc.stdout
    assert "streaming CRPS == offline reference" in proc.stdout
    assert "zero new jit-cache entries" in proc.stdout


def test_gate_fails_on_broken_verification_module(tmp_path):
    """A tree whose verification module cannot import must fail the gate —
    copy the script next to a stub package with a broken module."""
    pkg = tmp_path / "ddr_tpu" / "observability"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_verify.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_verify.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
