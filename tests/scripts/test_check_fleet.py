"""scripts/check_fleet.py: the fleet-tier smoke gate must pass on a clean
tree (so router/ensemble/canary bit-rot fails tier-1 fast) and actually
catch breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_fleet.py"


def test_repo_fleet_gate_clean():
    """THE CI gate: a 2-replica in-process group serves routed + ensemble
    traffic, survives a replica kill, and promotes a skill-par canary."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ejection + re-admission" in proc.stdout
    assert "one compiled 4-member ensemble program" in proc.stdout
    assert "canary promoted shadow->canary->promoted" in proc.stdout


def test_gate_fails_on_broken_fleet_module(tmp_path):
    """A tree whose fleet package cannot import must fail the gate — copy the
    script next to a stub package with a broken __init__."""
    pkg = tmp_path / "ddr_tpu" / "fleet"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_fleet.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_fleet.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
