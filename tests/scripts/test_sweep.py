"""``ddr sweep`` (hydra --multirun analog) and config ``include:`` composition
(hydra defaults-list analog) — VERDICT r4 item 8."""

from __future__ import annotations

import json

import pytest
import yaml

from ddr_tpu.scripts.sweep import expand_sweep, main as sweep_main
from ddr_tpu.validation.configs import load_config


class TestExpandSweep:
    def test_cartesian_product(self):
        combos, fixed = expand_sweep(["a=1,2", "b.c=x,y", "d=7"])
        assert fixed == ["d=7"]
        assert combos == [
            ["a=1", "b.c=x"],
            ["a=1", "b.c=y"],
            ["a=2", "b.c=x"],
            ["a=2", "b.c=y"],
        ]

    def test_no_axes_is_single_run(self):
        combos, fixed = expand_sweep(["a=1"])
        assert combos == [[]] and fixed == ["a=1"]

    def test_bracketed_lists_are_not_axes(self):
        combos, fixed = expand_sweep(["a=[1,2]", "b={x: 1, y: 2}"])
        assert combos == [[]]
        assert fixed == ["a=[1,2]", "b={x: 1, y: 2}"]

    def test_malformed_override_raises(self):
        with pytest.raises(ValueError, match="key.subkey=value"):
            expand_sweep(["nonsense"])


class TestIncludeComposition:
    def test_include_merges_with_file_winning(self, tmp_path):
        (tmp_path / "base.yaml").write_text(yaml.safe_dump({
            "name": "base",
            "geodataset": "synthetic",
            "mode": "training",
            "kan": {"input_var_names": ["a"], "hidden_size": 7},
            "params": {"save_path": str(tmp_path)},
        }))
        (tmp_path / "exp.yaml").write_text(yaml.safe_dump({
            "include": ["base.yaml"],
            "name": "exp",
            "kan": {"hidden_size": 13},
        }))
        cfg = load_config(tmp_path / "exp.yaml", save_config=False)
        assert cfg.name == "exp"
        assert cfg.kan.hidden_size == 13
        assert cfg.kan.input_var_names == ["a"]  # inherited from base

    def test_include_chain_and_overrides(self, tmp_path):
        (tmp_path / "a.yaml").write_text(yaml.safe_dump({
            "name": "a", "geodataset": "synthetic", "mode": "training",
            "kan": {"input_var_names": ["x"]}, "params": {"save_path": str(tmp_path)},
        }))
        (tmp_path / "b.yaml").write_text(yaml.safe_dump({"include": "a.yaml", "seed": 5}))
        (tmp_path / "c.yaml").write_text(yaml.safe_dump({"include": "b.yaml"}))
        cfg = load_config(tmp_path / "c.yaml", ["seed=9"], save_config=False)
        assert cfg.seed == 9  # CLI override beats the whole chain

    def test_circular_include_raises(self, tmp_path):
        (tmp_path / "x.yaml").write_text(yaml.safe_dump({"include": "y.yaml"}))
        (tmp_path / "y.yaml").write_text(yaml.safe_dump({"include": "x.yaml"}))
        with pytest.raises(ValueError, match="circular config include"):
            load_config(tmp_path / "x.yaml", save_config=False)


class TestSweepCli:
    def test_usage_and_unknown_command(self, capsys):
        assert sweep_main([]) == 2
        assert sweep_main(["--help"]) == 0
        assert sweep_main(["bogus"]) == 2
        assert "unknown command" in capsys.readouterr().err

    @pytest.mark.slow
    def test_sweep_train_product_run_dirs(self, tmp_path, capsys):
        """One invocation -> N run dirs + summary.json (the VERDICT item's
        done-condition)."""
        cfg = {
            "name": "sweep_run",
            "geodataset": "synthetic",
            "mode": "training",
            "kan": {"input_var_names": [f"a{i}" for i in range(10)]},
            "experiment": {
                "start_time": "1981/10/01",
                "end_time": "1981/10/13",
                "rho": 6,
                "batch_size": 4,
                "epochs": 1,
                "warmup": 1,
            },
            "params": {"save_path": str(tmp_path)},
        }
        cfg_path = tmp_path / "config.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg))
        rc = sweep_main(["train", str(cfg_path), "seed=0,1", "experiment.epochs=1"])
        assert rc == 0
        sweep_root = (tmp_path / "multirun").iterdir().__next__()
        summary = json.loads((sweep_root / "summary.json").read_text())
        assert len(summary) == 2
        assert {tuple(r["overrides"]) for r in summary} == {("seed=0",), ("seed=1",)}
        for r in summary:
            assert r["exit_code"] == 0
            run_dir = sweep_root / r["overrides"][0]
            assert (run_dir / "saved_models").exists(), f"no checkpoint dir in {run_dir}"


def test_combo_dirname_sanitizes_path_separators():
    from ddr_tpu.scripts.sweep import _combo_dirname

    assert _combo_dirname([]) == "default"
    assert _combo_dirname(["a=1", "b=2"]) == "a=1,b=2"
    # a path-valued axis must stay ONE directory component under the root
    d = _combo_dirname(["data_sources.streamflow=/data/a"])
    assert "/" not in d and "\\" not in d
    assert _combo_dirname(["p=../escape"]) == "p=.._escape"
