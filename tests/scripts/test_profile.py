"""``ddr profile`` end-to-end: the --synthetic smoke run (the acceptance
surface — report JSON/markdown with ProgramCards for forward route, full VJP,
and train step), plus CLI registration and the markdown renderer."""

from __future__ import annotations

import json

import pytest


class TestProfileSynthetic:
    @pytest.fixture(scope="class")
    def report_dir(self, tmp_path_factory):
        """One tiny profile run shared by every assertion below (three AOT
        compiles is the expensive part)."""
        out = tmp_path_factory.mktemp("profile_out")
        from ddr_tpu.scripts.profile import main

        rc = main([
            "--synthetic", "--n", "64", "--t-hours", "48",
            "--reps", "1", "--out", str(out),
        ])
        assert rc == 0
        return out

    def test_report_files_written(self, report_dir):
        assert (report_dir / "profile_report.json").exists()
        md = (report_dir / "profile_report.md").read_text()
        assert "forward-route" in md and "full-vjp" in md and "train-step" in md

    def test_cards_cover_all_three_programs(self, report_dir):
        report = json.loads((report_dir / "profile_report.json").read_text())
        assert set(report["programs"]) == {"forward-route", "full-vjp", "train-step"}
        for name, rec in report["programs"].items():
            card = rec["card"]
            assert card["flops"] and card["flops"] > 0, name
            assert card["peak_bytes"] is not None, name
            assert set(card["collectives"]) == {
                "all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all",
            }, name
            assert rec["seconds_per_iter"] > 0, name
            assert rec["reach_timesteps_per_sec"] > 0, name
            assert rec["achieved_flops_per_sec"] > 0, name

    def test_run_log_carries_program_cards(self, report_dir):
        log = report_dir / "run_log.profile.jsonl"
        events = [json.loads(l) for l in log.read_text().splitlines()]
        cards = [e for e in events if e["event"] == "program_card"]
        assert {e["name"] for e in cards} == {"forward-route", "full-vjp", "train-step"}
        assert events[-1]["event"] == "run_end"
        assert events[-1]["status"] == "ok"

    def test_summarize_renders_program_table(self, report_dir, capsys):
        from ddr_tpu.observability.metrics_cli import main as metrics_main

        assert metrics_main(["summarize", str(report_dir / "run_log.profile.jsonl")]) == 0
        out = capsys.readouterr().out
        assert "programs :" in out
        assert "train-step" in out


class TestProfileCli:
    def test_registered_in_ddr_cli(self, capsys):
        from ddr_tpu.cli import main

        assert main([]) == 0
        assert "profile" in capsys.readouterr().out

    def test_help_exits_zero(self):
        from ddr_tpu.scripts.profile import main

        assert main(["--help"]) == 0


class TestRenderMarkdown:
    def test_peak_flops_column(self):
        from ddr_tpu.scripts.profile import render_markdown

        report = {
            "device": "cpu", "n": 8, "t_hours": 48, "depth": None, "reps": 1,
            "peak_flops": 1e9,
            "programs": {
                "forward-route": {
                    "card": {"engine": "step", "flops": 5e8, "bytes_accessed": 1e6,
                             "arithmetic_intensity": 500.0, "peak_bytes": 2**20,
                             "n_collectives": 0, "compile_seconds": 0.1,
                             "collectives": {"all-reduce": 0}},
                    "seconds_per_iter": 0.5,
                    "achieved_flops_per_sec": 1e9,
                },
            },
        }
        md = render_markdown(report)
        assert "% peak" in md
        assert "100.0%" in md

    def test_nonzero_collective_mix_listed(self):
        from ddr_tpu.scripts.profile import render_markdown

        report = {
            "device": "tpu", "n": 8, "t_hours": 48, "depth": None, "reps": 1,
            "peak_flops": None,
            "programs": {
                "train-step": {
                    "card": {"engine": "gspmd", "flops": 1.0, "bytes_accessed": 1.0,
                             "arithmetic_intensity": 1.0, "peak_bytes": 1,
                             "n_collectives": 3, "compile_seconds": 0.1,
                             "collectives": {"all-reduce": 3, "all-gather": 0}},
                    "seconds_per_iter": 0.5,
                    "achieved_flops_per_sec": 2.0,
                },
            },
        }
        md = render_markdown(report)
        assert "collective mix" in md
        assert "'all-reduce': 3" in md
        assert "all-gather" not in md.split("collective mix")[1]  # zeros hidden
