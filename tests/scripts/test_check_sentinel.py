"""scripts/check_sentinel.py: the performance-sentinel smoke gate must pass
on a clean tree (so detector/attribution bit-rot fails tier-1 fast) and
actually catch breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_sentinel.py"


def test_repo_sentinel_smokes_clean():
    """THE CI gate: an injected slow@data.load fires a data_load anomaly
    within a bounded number of steps and flips the pipeline verdict to
    data_bound, while the clean twin stays silent — all without importing
    jax (the zero-jit-cache-entries proof)."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean twin silent" in proc.stdout
    assert "jax never imported" in proc.stdout


def test_gate_fails_on_broken_sentinel_module(tmp_path):
    """A tree whose observability package cannot import must fail the gate —
    copy the script next to a stub package with a broken __init__."""
    pkg = tmp_path / "ddr_tpu" / "observability"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_sentinel.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_sentinel.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
