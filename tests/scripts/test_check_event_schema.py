"""scripts/check_event_schema.py: the tree's literal emit() names must all be
registered EVENT_TYPES — and the checker must actually catch offenders."""

from __future__ import annotations

import importlib.util
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_event_schema.py"


def _load():
    spec = importlib.util.spec_from_file_location("check_event_schema", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_tree_is_clean():
    """THE CI gate: a new emit() with an unregistered name fails the suite."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)], capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all registered" in proc.stdout


def test_registered_events_matches_runtime():
    mod = _load()
    from ddr_tpu.observability.events import EVENT_TYPES

    assert mod.registered_events(REPO / "ddr_tpu/observability/events.py") == EVENT_TYPES


def test_catches_unregistered_emit(tmp_path):
    mod = _load()
    root = tmp_path
    (root / "ddr_tpu/observability").mkdir(parents=True)
    shutil.copy(
        REPO / "ddr_tpu/observability/events.py",
        root / "ddr_tpu/observability/events.py",
    )
    (root / "ddr_tpu/rogue.py").write_text(
        "def f(rec):\n"
        "    rec.emit('step', loss=1.0)\n"          # fine
        "    rec.emit('totally_new_event', x=1)\n"  # offender
        "    rec.emit(variable_name, x=1)\n"        # non-literal: skipped
    )
    (root / "bench.py").write_text("")
    (root / "examples").mkdir()
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "--root", str(root)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "totally_new_event" in proc.stderr
    assert "rogue.py:3" in proc.stderr
    assert "step" not in proc.stderr.replace("totally_new_event", "")


def test_zero_sites_is_an_error(tmp_path):
    """An empty scan means the matcher rotted — that must fail, not pass."""
    root = tmp_path
    (root / "ddr_tpu/observability").mkdir(parents=True)
    shutil.copy(
        REPO / "ddr_tpu/observability/events.py",
        root / "ddr_tpu/observability/events.py",
    )
    # strip every emit() call events.py itself contains
    src = (root / "ddr_tpu/observability/events.py").read_text()
    (root / "ddr_tpu/observability/events.py").write_text(
        src.replace(".emit(", ".no_emit(")
    )
    (root / "bench.py").write_text("")
    (root / "examples").mkdir()
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "--root", str(root)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "no emit() call sites" in proc.stderr
