"""Unit tests for the shared script utilities, at the reference's granularity
(/root/reference/tests/scripts/test_scripts_utils.py: TestComputeDailyRunoff,
TestResolveLearningRate, TestSafePercentile, TestSafeMean) plus the routing
terminal summary (TestPrintRoutingSummary)."""

from pathlib import Path

import numpy as np
import pytest

from ddr_tpu.scripts.router import print_routing_summary
from ddr_tpu.scripts_utils import (
    compute_daily_runoff,
    resolve_learning_rate,
    safe_mean,
    safe_percentile,
)


class TestComputeDailyRunoff:
    def test_shape(self):
        """D-day window of (D-1)*24 hourly steps -> D-2 daily values."""
        d = 10
        hourly = np.random.default_rng(0).uniform(0, 5, (3, (d - 1) * 24))
        daily = compute_daily_runoff(hourly, tau=3)
        assert daily.shape == (3, d - 2)

    def test_known_values(self):
        """Constant signal survives trim + block mean exactly."""
        hourly = np.full((2, 9 * 24), 7.5)
        daily = compute_daily_runoff(hourly, tau=3)
        np.testing.assert_allclose(daily, np.full((2, 8), 7.5), rtol=1e-12)

    def test_block_mean_of_step_signal(self):
        """A signal constant within each post-trim 24h block reproduces the block
        values exactly (downsample is an exact block mean)."""
        tau = 3
        t_total = 6 * 24
        hourly = np.zeros((1, t_total))
        sliced_len = t_total - (13 + tau) - (11 - tau)
        n_days = sliced_len // 24
        vals = np.arange(1.0, n_days + 1)
        start = 13 + tau
        for i, v in enumerate(vals):
            hourly[0, start + 24 * i : start + 24 * (i + 1)] = v
        daily = compute_daily_runoff(hourly, tau=tau)
        np.testing.assert_allclose(daily[0], vals, rtol=1e-12)

    def test_different_tau_shifts_window(self):
        rng = np.random.default_rng(1)
        hourly = rng.uniform(0, 5, (1, 8 * 24))
        d3 = compute_daily_runoff(hourly, tau=3)
        d5 = compute_daily_runoff(hourly, tau=5)
        assert d3.shape == d5.shape
        assert not np.allclose(d3, d5)

    def test_tau_window_matches_manual_slice(self):
        tau = 4
        hourly = np.random.default_rng(2).uniform(0, 5, (2, 7 * 24))
        daily = compute_daily_runoff(hourly, tau=tau)
        sliced = hourly[:, 13 + tau : -11 + tau]
        nd = sliced.shape[1] // 24
        manual = sliced[:, : nd * 24].reshape(2, nd, 24).mean(axis=2)
        np.testing.assert_allclose(daily, manual, rtol=1e-6)


class TestResolveLearningRate:
    def test_exact_match(self):
        assert resolve_learning_rate({1: 0.01, 3: 0.001}, 3) == 0.001

    def test_fallback_to_latest_before(self):
        assert resolve_learning_rate({1: 0.01, 3: 0.001}, 2) == 0.01
        assert resolve_learning_rate({1: 0.01, 3: 0.001}, 10) == 0.001

    def test_before_first_entry_uses_first(self):
        assert resolve_learning_rate({5: 0.1}, 1) == 0.1

    def test_single_entry(self):
        assert resolve_learning_rate({1: 0.02}, 100) == 0.02


class TestSafePercentile:
    def test_with_nans(self):
        vals = np.array([1.0, np.nan, 3.0, np.nan, 5.0])
        assert safe_percentile(vals, 50) == pytest.approx(3.0)

    def test_all_nan(self):
        assert np.isnan(safe_percentile(np.array([np.nan, np.nan]), 50))

    def test_empty(self):
        assert np.isnan(safe_percentile(np.array([]), 50))

    def test_no_nan(self):
        assert safe_percentile(np.arange(101.0), 90) == pytest.approx(90.0)

    def test_inf_excluded(self):
        vals = np.array([1.0, np.inf, 2.0, -np.inf, 3.0])
        assert safe_percentile(vals, 50) == pytest.approx(2.0)


class TestSafeMean:
    def test_with_nans(self):
        assert safe_mean(np.array([1.0, np.nan, 3.0])) == pytest.approx(2.0)

    def test_all_nan(self):
        assert np.isnan(safe_mean(np.array([np.nan])))

    def test_no_nan(self):
        assert safe_mean(np.array([2.0, 4.0])) == pytest.approx(3.0)


class TestPrintRoutingSummary:
    """Reference /root/reference/tests/scripts/test_router.py TestPrintRoutingSummary."""

    def _capture(self, capsys, discharge=None, runtime=12.34, out="chrout.zarr"):
        if discharge is None:
            discharge = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
        print_routing_summary(discharge, ["a", "b"], runtime, Path(out))
        return capsys.readouterr().out

    def test_prints_to_stdout(self, capsys):
        assert len(self._capture(capsys)) > 0

    def test_contains_segment_count(self, capsys):
        assert "2" in self._capture(capsys)
        out = self._capture(capsys, discharge=np.ones((7, 4)))
        assert "7" in out

    def test_contains_timestep_count(self, capsys):
        out = self._capture(capsys, discharge=np.ones((2, 48)))
        assert "48" in out

    def test_contains_runtime(self, capsys):
        assert "12.34" in self._capture(capsys, runtime=12.34)

    def test_contains_discharge_stats(self, capsys):
        out = self._capture(capsys, discharge=np.full((2, 3), 5.0))
        assert "5.000" in out  # mean and peaks all 5

    def test_contains_output_path(self, capsys):
        assert "chrout.zarr" in self._capture(capsys, out="chrout.zarr")

    def test_single_segment_single_timestep(self, capsys):
        out = self._capture(capsys, discharge=np.array([[1.5]]))
        assert "1" in out and "1.500" in out

    def test_nan_robust(self, capsys):
        disch = np.array([[1.0, np.nan], [np.nan, 3.0]])
        out = self._capture(capsys, discharge=disch)
        assert "nan" not in out.split("mean discharge")[1].splitlines()[0]
