"""scripts/check_bench_regression.py: the bench-history regression gate.

Fast tests pin the comparison semantics (threshold, device-mismatch downgrade,
ratio fields informational, strict exit code); the slow test runs the real
bench.py at tiny shapes and feeds its record through the script end to end.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_bench_regression.py"


def _load():
    spec = importlib.util.spec_from_file_location("check_bench_regression", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCompare:
    def test_flags_only_drops_past_threshold(self):
        mod = _load()
        fresh = {"device": "cpu", "value": 79.0, "grad_value": 85.0}
        base = {"device": "cpu", "value": 100.0, "grad_value": 100.0}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["value"]["status"] == "regression"  # 79% < 80%
        assert by_key["grad_value"]["status"] == "ok"  # 85% >= 80%

    def test_improvements_are_ok(self):
        mod = _load()
        out = mod.compare({"device": "cpu", "value": 250.0}, {"device": "cpu", "value": 100.0})
        assert out == [
            {"key": "value", "fresh": 250.0, "baseline": 100.0, "ratio": 2.5, "status": "ok"}
        ]

    def test_device_mismatch_downgrades_to_info(self):
        """A CPU fallback round vs a TPU round says nothing about the code."""
        mod = _load()
        out = mod.compare({"device": "cpu", "value": 1.0}, {"device": "tpu", "value": 1e6})
        assert all(f["status"] == "info" for f in out)
        assert out[0]["key"] == "device"

    def test_ratio_fields_are_informational(self):
        mod = _load()
        fresh = {"device": "cpu", "grad_over_forward_ratio": 0.1}
        base = {"device": "cpu", "grad_over_forward_ratio": 0.9}
        (f,) = mod.compare(fresh, base)
        assert f["status"] == "info"

    def test_missing_and_null_fields_are_skipped(self):
        mod = _load()
        fresh = {"device": "cpu", "value": 10.0, "deep_value": None}
        base = {"device": "cpu", "grad_value": 5.0, "deep_value": 3.0}
        assert mod.compare(fresh, base) == []

    def test_tuned_plan_drift_is_informational(self):
        """The auto-tuner picking a different engine than the baseline round is
        CONTEXT for any throughput movement, never itself a regression."""
        mod = _load()
        fresh = {"device": "cpu", "value": 100.0, "tuned_plan": "sharded-wavefront"}
        base = {"device": "cpu", "value": 100.0, "tuned_plan": "gspmd"}
        by_key = {f["key"]: f for f in mod.compare(fresh, base)}
        assert by_key["tuned_plan"]["status"] == "info"
        assert by_key["tuned_plan"]["fresh"] == "sharded-wavefront"
        # same plan (or a record predating the field): no finding at all
        same = mod.compare(
            {"device": "cpu", "value": 1.0, "tuned_plan": "gspmd"},
            {"device": "cpu", "value": 1.0, "tuned_plan": "gspmd"},
        )
        assert all(f["key"] != "tuned_plan" for f in same)
        legacy = mod.compare(
            {"device": "cpu", "value": 1.0, "tuned_plan": "gspmd"},
            {"device": "cpu", "value": 1.0},
        )
        assert all(f["key"] != "tuned_plan" for f in legacy)


class TestCostGrowth:
    """The cost-card direction: peak memory and collective counts growing past
    the threshold warn; shrinking (or equal) is ok."""

    def test_peak_memory_growth_flags(self):
        mod = _load()
        fresh = {"device": "cpu", "peak_hbm_gb": 1.3, "deep_peak_hbm_gb": 1.1}
        base = {"device": "cpu", "peak_hbm_gb": 1.0, "deep_peak_hbm_gb": 1.0}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["peak_hbm_gb"]["status"] == "regression"  # +30% > +20%
        assert by_key["deep_peak_hbm_gb"]["status"] == "ok"  # +10% <= +20%

    def test_peak_memory_shrink_is_ok(self):
        mod = _load()
        (f,) = mod.compare(
            {"device": "cpu", "peak_hbm_gb": 0.4},
            {"device": "cpu", "peak_hbm_gb": 1.0},
        )
        assert f["status"] == "ok"  # memory going DOWN is the good direction

    def test_collective_count_growth_flags(self):
        mod = _load()
        fresh = {"device": "tpu", "deep_collectives": {"all-reduce": 14, "all-gather": 0}}
        base = {"device": "tpu", "deep_collectives": {"all-reduce": 10, "all-gather": 0}}
        by_key = {f["key"]: f for f in mod.compare(fresh, base)}
        assert by_key["deep_collectives.all-reduce"]["status"] == "regression"  # +40%
        assert "deep_collectives.all-gather" not in by_key  # all-zero rows are noise

    def test_collective_growth_within_threshold_is_ok(self):
        """The threshold applies to collectives like every other field: a +10%
        count bump under the default 20% threshold reports but doesn't warn."""
        mod = _load()
        fresh = {"device": "tpu", "collectives": {"all-reduce": 11}}
        base = {"device": "tpu", "collectives": {"all-reduce": 10}}
        (f,) = mod.compare(fresh, base)
        assert f["status"] == "ok"

    def test_collective_appearing_from_zero_flags(self):
        mod = _load()
        fresh = {"device": "tpu", "collectives": {"all-to-all": 2}}
        base = {"device": "tpu", "collectives": {"all-to-all": 0}}
        (f,) = mod.compare(fresh, base)
        assert f["status"] == "regression"
        assert f["ratio"] is None  # no finite ratio from a zero baseline

    def test_device_mismatch_downgrades_cost_fields(self):
        mod = _load()
        out = mod.compare(
            {"device": "cpu", "peak_hbm_gb": 9.0, "collectives": {"all-reduce": 5}},
            {"device": "tpu", "peak_hbm_gb": 1.0, "collectives": {"all-reduce": 1}},
        )
        assert all(f["status"] == "info" for f in out)

    def test_strict_exit_on_memory_growth(self, tmp_path):
        mod = _load()
        fresh = tmp_path / "fresh.json"
        base = tmp_path / "base.json"
        fresh.write_text(json.dumps({"device": "cpu", "value": 100.0, "peak_hbm_gb": 2.0}))
        base.write_text(json.dumps({"device": "cpu", "value": 100.0, "peak_hbm_gb": 1.0}))
        assert mod.main([str(fresh), "--baseline", str(base), "--strict"]) == 1
        assert mod.main([str(fresh), "--baseline", str(base)]) == 0  # warn only


class TestDtypePairing:
    """Records pair by routing compute dtype: a bf16 round never gates against
    an fp32 baseline (and vice versa); records predating the field are fp32."""

    def test_record_dtype_defaults_to_fp32(self):
        mod = _load()
        assert mod.record_dtype({}) == "fp32"
        assert mod.record_dtype({"compute_dtype": None}) == "fp32"
        assert mod.record_dtype({"compute_dtype": "bf16"}) == "bf16"

    def test_latest_bench_baseline_pairs_by_dtype(self, tmp_path):
        mod = _load()
        r1 = tmp_path / "BENCH_r01.json"
        r2 = tmp_path / "BENCH_r02.json"
        r3 = tmp_path / "BENCH_r03.json"
        r1.write_text(json.dumps({"device": "cpu", "value": 1.0}))  # pre-dtype = fp32
        r2.write_text(json.dumps(
            {"device": "cpu", "value": 2.0, "compute_dtype": "bf16"}
        ))
        r3.write_text(json.dumps(
            {"device": "cpu", "value": 3.0, "compute_dtype": "fp32"}
        ))
        assert mod.latest_bench_baseline(tmp_path, dtype="fp32") == r3
        assert mod.latest_bench_baseline(tmp_path, dtype="bf16") == r2
        # an fp32 fresh record skips the newer bf16 round when r3 is excluded
        assert mod.latest_bench_baseline(tmp_path, dtype="fp32", exclude=r3) == r1
        assert mod.latest_bench_baseline(tmp_path, dtype="int8") is None

    def test_latest_bench_baseline_skips_unparseable(self, tmp_path):
        mod = _load()
        (tmp_path / "BENCH_r09.json").write_text("not json at all")
        good = tmp_path / "BENCH_r08.json"
        good.write_text(json.dumps({"value": 1.0}))
        assert mod.latest_bench_baseline(tmp_path, dtype="fp32") == good

    def test_dtype_mismatch_downgrades_to_info(self):
        """An explicit --baseline across dtypes measures the precision knob,
        not the code — every finding downgrades like a device mismatch."""
        mod = _load()
        out = mod.compare(
            {"device": "cpu", "value": 50.0, "compute_dtype": "bf16"},
            {"device": "cpu", "value": 100.0},  # implicit fp32
        )
        assert all(f["status"] == "info" for f in out)
        assert out[0]["key"] == "compute_dtype"

    def test_same_dtype_compares_normally(self):
        mod = _load()
        out = mod.compare(
            {"device": "cpu", "value": 50.0, "compute_dtype": "bf16"},
            {"device": "cpu", "value": 100.0, "compute_dtype": "bf16"},
        )
        (f,) = out
        assert f["key"] == "value" and f["status"] == "regression"

    def test_cli_auto_baseline_selects_by_fresh_dtype(self, tmp_path, monkeypatch):
        """main() asks the bench-baseline picker for the FRESH record's dtype."""
        mod = _load()
        base = tmp_path / "BENCH_r01.json"
        base.write_text(json.dumps(
            {"device": "cpu", "value": 100.0, "compute_dtype": "bf16"}
        ))
        calls: dict = {}

        def stub(dtype, exclude=None):
            calls["dtype"] = dtype
            return base

        monkeypatch.setattr(mod, "latest_bench_baseline", stub)
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(
            {"device": "cpu", "value": 99.0, "compute_dtype": "bf16"}
        ))
        assert mod.main([str(fresh)]) == 0
        assert calls["dtype"] == "bf16"


class TestLoadtestRecords:
    """Serving-latency gating: ``ddr loadtest`` reports compare with the
    opposite polarities (latency/rates warn on GROWTH, throughput/attainment
    on DROP) and against the LOADTEST_* history, never a bench round."""

    def test_is_loadtest_record(self):
        mod = _load()
        assert mod.is_loadtest_record({"kind": "loadtest"})
        assert mod.is_loadtest_record({"p50_ms": 12.0})  # pre-kind records
        assert not mod.is_loadtest_record({"value": 100.0})

    def test_latency_growth_flags(self):
        mod = _load()
        fresh = {"device": "cpu", "p99_ms": 65.0, "queue_p99_ms": 11.0}
        base = {"device": "cpu", "p99_ms": 50.0, "queue_p99_ms": 10.0}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["p99_ms"]["status"] == "regression"  # +30% > +20%
        assert by_key["queue_p99_ms"]["status"] == "ok"  # +10% <= +20%

    def test_latency_shrink_is_ok(self):
        mod = _load()
        (f,) = mod.compare(
            {"device": "cpu", "p50_ms": 8.0}, {"device": "cpu", "p50_ms": 20.0}
        )
        assert f["status"] == "ok"  # faster is the good direction

    def test_throughput_and_attainment_drop_flags(self):
        mod = _load()
        fresh = {"device": "cpu", "throughput_rps": 70.0, "slo_attainment": 0.70}
        base = {"device": "cpu", "throughput_rps": 100.0, "slo_attainment": 0.99}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["throughput_rps"]["status"] == "regression"
        assert by_key["slo_attainment"]["status"] == "regression"  # -29%

    def test_drop_rate_appearing_from_clean_baseline_flags(self):
        mod = _load()
        fresh = {"device": "cpu", "shed_rate": 0.25, "reject_rate": 0.01}
        base = {"device": "cpu", "shed_rate": 0.0, "reject_rate": 0.0}
        by_key = {f["key"]: f for f in mod.compare(fresh, base)}
        assert by_key["shed_rate"]["status"] == "regression"
        assert by_key["shed_rate"]["ratio"] is None  # no finite ratio from 0
        # one unlucky shed in a tiny run stays under the absolute floor
        assert by_key["reject_rate"]["status"] == "ok"

    def test_rate_growth_over_nonzero_baseline_uses_threshold(self):
        mod = _load()
        fresh = {"device": "cpu", "shed_rate": 0.15}
        base = {"device": "cpu", "shed_rate": 0.10}
        (f,) = mod.compare(fresh, base, threshold=0.2)
        assert f["status"] == "regression"  # +50% > +20%

    def test_device_mismatch_downgrades_loadtest_fields(self):
        mod = _load()
        out = mod.compare(
            {"device": "cpu", "p99_ms": 500.0, "shed_rate": 0.5,
             "throughput_rps": 1.0},
            {"device": "tpu", "p99_ms": 5.0, "shed_rate": 0.0,
             "throughput_rps": 100.0},
        )
        assert all(f["status"] == "info" for f in out)

    def test_latest_loadtest_baseline_by_mtime(self, tmp_path):
        """LOADTEST labels are free-form, so recency is mtime, not name — a
        one-off `--label smoke` must not lexically outrank every later
        timestamped record forever."""
        import os

        mod = _load()
        for i, name in enumerate((
            "LOADTEST_smoke.json",  # lexically LAST, but oldest by mtime
            "LOADTEST_20260801-1200.json",
            "LOADTEST_20260803-0900.json",
        )):
            p = tmp_path / name
            p.write_text("{}")
            os.utime(p, (1000 + i, 1000 + i))
        (tmp_path / "BENCH_r99.json").write_text("{}")
        picked = mod.latest_baseline(tmp_path, pattern="LOADTEST_*.json")
        assert picked.name == "LOADTEST_20260803-0900.json"

    def test_fresh_record_is_never_its_own_baseline(self, tmp_path):
        """A timestamp-named fresh LOADTEST in the baseline dir sorts newest;
        excluding it must fall back to the real history (or None)."""
        mod = _load()
        old = tmp_path / "LOADTEST_20260801-1200.json"
        fresh = tmp_path / "LOADTEST_20260804-1500.json"
        old.write_text("{}")
        fresh.write_text("{}")
        picked = mod.latest_baseline(
            tmp_path, pattern="LOADTEST_*.json", exclude=fresh
        )
        assert picked == old
        assert mod.latest_baseline(
            tmp_path, pattern="LOADTEST_*.json", exclude=old
        ) == fresh
        old.unlink()
        assert mod.latest_baseline(
            tmp_path, pattern="LOADTEST_*.json", exclude=fresh
        ) is None

    def test_cli_gates_loadtest_record(self, tmp_path):
        rec = {"kind": "loadtest", "device": "cpu", "p99_ms": 50.0,
               "throughput_rps": 100.0, "shed_rate": 0.0,
               "slo_attainment": 0.995}
        fresh = tmp_path / "LOADTEST_fresh.json"
        fresh.write_text(json.dumps(
            dict(rec, p99_ms=90.0, throughput_rps=60.0)) + "\n")
        base = tmp_path / "LOADTEST_base.json"
        base.write_text(json.dumps(rec) + "\n")
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1
        assert "WARNING" in proc.stderr
        # self-comparison is always clean
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(fresh),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestVerifyRecords:
    """Verification gating: ``ddr verify`` reports pair against the VERIFY_*
    history by mode — the scores (crps/brier) warn on GROWTH, the matched
    evidence count on DROP, and the degraded control arm is never flagged."""

    def test_is_verify_record(self):
        mod = _load()
        assert mod.is_verify_record({"kind": "verify"})
        assert not mod.is_verify_record({"kind": "loadtest"})
        assert not mod.is_verify_record({"value": 100.0})

    def test_score_growth_flags(self):
        mod = _load()
        fresh = {"device": "cpu", "crps": 0.30, "brier": 0.055}
        base = {"device": "cpu", "crps": 0.20, "brier": 0.050}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["crps"]["status"] == "regression"  # +50% > +20%
        assert by_key["brier"]["status"] == "ok"  # +10% <= +20%

    def test_score_shrink_is_ok(self):
        mod = _load()
        (f,) = mod.compare(
            {"device": "cpu", "crps": 0.10}, {"device": "cpu", "crps": 0.20}
        )
        assert f["status"] == "ok"  # sharper is the good direction

    def test_matched_sample_drop_flags(self):
        mod = _load()
        fresh = {"device": "cpu", "matched_samples": 60}
        base = {"device": "cpu", "matched_samples": 128}
        (f,) = mod.compare(fresh, base, threshold=0.2)
        assert f["status"] == "regression"  # less evidence, -53%

    def test_control_arm_and_spread_skill_never_flag(self):
        mod = _load()
        fresh = {"device": "cpu", "crps_degraded": 9.0, "spread_skill": 2.0}
        base = {"device": "cpu", "crps_degraded": 1.0, "spread_skill": 1.0}
        assert mod.compare(fresh, base, threshold=0.2) == []

    def test_latest_verify_baseline_pairs_by_mode(self, tmp_path):
        mod = _load()
        old = tmp_path / "VERIFY_syn.json"
        old.write_text(json.dumps({"kind": "verify", "mode": "synthetic"}))
        newer = tmp_path / "VERIFY_live.json"
        newer.write_text(json.dumps({"kind": "verify", "mode": "live"}))
        os.utime(old, (1_000_000, 1_000_000))
        os.utime(newer, (2_000_000, 2_000_000))
        assert mod.latest_verify_baseline(tmp_path, mode="synthetic") == old
        assert mod.latest_verify_baseline(tmp_path, mode="live") == newer
        assert mod.latest_verify_baseline(tmp_path) == newer  # plain newest
        # a fresh record never self-selects as its own baseline
        assert mod.latest_verify_baseline(
            tmp_path, mode="live", exclude=newer
        ) is None

    def test_cli_gates_verify_record(self, tmp_path):
        rec = {"kind": "verify", "mode": "synthetic", "device": "cpu",
               "crps": 0.02, "brier": 0.04, "matched_samples": 128}
        fresh = tmp_path / "VERIFY_fresh.json"
        fresh.write_text(json.dumps(
            dict(rec, crps=0.09, matched_samples=40)) + "\n")
        base = tmp_path / "VERIFY_base.json"
        base.write_text(json.dumps(rec) + "\n")
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1
        assert "WARNING" in proc.stderr
        # self-comparison is always clean
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(fresh),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestMultichipRecords:
    """Multichip dryrun gating: MULTICHIP_r* wrappers parse their numbers out
    of the dryrun's stdout ``tail``; the analytic-beats-AD train-step gate and
    the grad-parity ceiling hold intra-record (no baseline needed), while the
    per-entry step times gate against the previous round like latency."""

    TAIL = (
        "dryrun_multichip OK: 8 devices, N=128 reaches in topological-range "
        "shards, one GSPMD train step (loss=0.9108) + sharded-wavefront train "
        "step (loss=0.9108, grad parity 1.81e-07 vs single-program, analytic "
        "adjoint grad parity 2.75e-07 vs AD) + pipelined wavefront route\n"
        "scale dryrun N=8192 T=48 on 8 virtual devices: gspmd_step=212ms "
        "(1.9M rt/s), sharded_wavefront_train=402ms (1.0M rt/s), "
        "sharded_wavefront_train_analytic=171ms (2.3M rt/s)\n"
    )

    def test_is_multichip_record(self):
        mod = _load()
        assert mod.is_multichip_record({"n_devices": 8, "tail": "..."})
        assert mod.is_multichip_record({"kind": "multichip"})
        assert not mod.is_multichip_record({"kind": "loadtest"})
        assert not mod.is_multichip_record({"value": 100.0})

    def test_parse_multichip_extracts_timings_and_parity(self):
        parsed = _load().parse_multichip({"n_devices": 8, "tail": self.TAIL})
        assert parsed["gspmd_step_ms"] == 212.0
        assert parsed["sharded_wavefront_train_ms"] == 402.0
        assert parsed["sharded_wavefront_train_analytic_ms"] == 171.0
        assert parsed["analytic_grad_parity"] == pytest.approx(2.75e-07)

    def test_analytic_beating_ad_is_ok(self):
        mod = _load()
        by_key = {f["key"]: f for f in mod.multichip_self_check(
            {"sharded_wavefront_train_ms": 402.0,
             "sharded_wavefront_train_analytic_ms": 171.0,
             "analytic_grad_parity": 2.75e-07})}
        assert by_key["analytic_vs_ad_train_step"]["status"] == "ok"
        assert by_key["analytic_grad_parity"]["status"] == "ok"

    def test_analytic_slower_than_ad_flags(self):
        """The whole point of the transposed-table backward: a round where
        the analytic step stops beating AD regresses with NO baseline."""
        mod = _load()
        by_key = {f["key"]: f for f in mod.multichip_self_check(
            {"sharded_wavefront_train_ms": 402.0,
             "sharded_wavefront_train_analytic_ms": 450.0})}
        assert by_key["analytic_vs_ad_train_step"]["status"] == "regression"

    def test_grad_parity_past_tolerance_flags(self):
        mod = _load()
        by_key = {f["key"]: f for f in mod.multichip_self_check(
            {"analytic_grad_parity": 3e-05})}
        assert by_key["analytic_grad_parity"]["status"] == "regression"

    def test_step_time_growth_gates_against_previous_round(self):
        mod = _load()
        fresh = {"sharded_wavefront_train_analytic_ms": 300.0,
                 "gspmd_step_ms": 215.0}
        base = {"sharded_wavefront_train_analytic_ms": 171.0,
                "gspmd_step_ms": 212.0}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["sharded_wavefront_train_analytic_ms"]["status"] == "regression"
        assert by_key["gspmd_step_ms"]["status"] == "ok"

    def test_latest_multichip_baseline_picks_highest_round(self, tmp_path):
        mod = _load()
        for name in ("MULTICHIP_r01.json", "MULTICHIP_r06.json", "MULTICHIP_r03.json"):
            (tmp_path / name).write_text("{}")
        picked = mod.latest_multichip_baseline(tmp_path)
        assert picked.name == "MULTICHIP_r06.json"
        # a fresh record never self-selects as its own baseline
        assert mod.latest_multichip_baseline(
            tmp_path, exclude=picked
        ).name == "MULTICHIP_r03.json"

    def test_repo_multichip_round_passes_own_gates(self):
        """The committed latest MULTICHIP round must parse and hold its own
        intra-record gates — the acceptance shape this kind exists for."""
        mod = _load()
        latest = mod.latest_multichip_baseline()
        assert latest is not None
        parsed = mod.parse_multichip(mod.load_record(latest))
        assert parsed.get("sharded_wavefront_train_ms")
        checks = mod.multichip_self_check(parsed)
        assert all(f["status"] == "ok" for f in checks)

    def test_host_size_mismatch_downgrades_step_times(self, tmp_path):
        """A 1-core host's wall times vs an undeclared (driver) host measure
        the machine, not the code — times go informational, but the
        intra-record analytic-vs-AD gate still holds (it never leaves the
        fresh record)."""
        rec = {"n_devices": 8, "host_nproc": 1, "rc": 0, "ok": True,
               "tail": self.TAIL}
        fresh = tmp_path / "MULTICHIP_r07.json"
        fresh.write_text(json.dumps(rec, indent=2))
        base = tmp_path / "MULTICHIP_r06.json"
        slow_tail = self.TAIL.replace("gspmd_step=212ms", "gspmd_step=20ms")
        base.write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True, "tail": slow_tail},
            indent=2))
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "undeclared-host" in proc.stdout
        assert "analytic_vs_ad_train_step" in proc.stdout

    def test_cli_gates_multichip_record(self, tmp_path):
        rec = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
               "tail": self.TAIL}
        fresh = tmp_path / "MULTICHIP_r07.json"
        fresh.write_text(json.dumps(rec, indent=2))
        base = tmp_path / "MULTICHIP_r06.json"
        base.write_text(json.dumps(rec, indent=2))
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "analytic_vs_ad_train_step" in proc.stdout
        # an analytic step slower than AD fails strict even vs itself
        bad = dict(rec, tail=self.TAIL.replace(
            "sharded_wavefront_train_analytic=171ms (2.3M rt/s)",
            "sharded_wavefront_train_analytic=460ms (0.9M rt/s)"))
        badp = tmp_path / "MULTICHIP_r08.json"
        badp.write_text(json.dumps(bad, indent=2))
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(badp), "--baseline", str(base),
             "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1
        assert "analytic_vs_ad_train_step" in proc.stderr


class TestLoadRecord:
    def test_unwraps_driver_wrapper(self, tmp_path):
        """The committed BENCH_r*.json form: pretty-printed {n,cmd,rc,tail,
        parsed} wrapper with the bench fields under 'parsed'."""
        mod = _load()
        p = tmp_path / "BENCH_r09.json"
        p.write_text(json.dumps(
            {"n": 9, "cmd": "python bench.py", "rc": 0, "tail": "...",
             "parsed": {"device": "cpu", "value": 42.0}},
            indent=2,
        ))
        assert mod.load_record(p) == {"device": "cpu", "value": 42.0}

    def test_reads_last_line_of_log_output(self, tmp_path):
        mod = _load()
        p = tmp_path / "fresh.json"
        p.write_text("some log line\n" + json.dumps({"value": 7.0}) + "\n")
        assert mod.load_record(p) == {"value": 7.0}

    def test_repo_baseline_is_loadable_and_comparable(self):
        """The script's primary documented flow: the auto-picked latest
        BENCH_r*.json must load and expose throughput fields compare() sees."""
        mod = _load()
        base = mod.load_record(mod.latest_baseline())
        findings = mod.compare(dict(base), base)
        assert findings and all(f["status"] != "regression" for f in findings)


class TestLatestBaseline:
    def test_picks_highest_round(self, tmp_path):
        mod = _load()
        for name in ("BENCH_r01.json", "BENCH_r05.json", "BENCH_r03_interactive.json"):
            (tmp_path / name).write_text("{}")
        assert mod.latest_baseline(tmp_path).name == "BENCH_r05.json"

    def test_repo_has_a_baseline(self):
        assert _load().latest_baseline() is not None

    def test_none_when_empty(self, tmp_path):
        assert _load().latest_baseline(tmp_path) is None


class TestCli:
    def _write(self, path, record):
        path.write_text(json.dumps(record) + "\n")
        return path

    def test_ok_exit_and_report(self, tmp_path):
        fresh = self._write(tmp_path / "fresh.json", {"device": "cpu", "value": 100.0})
        base = self._write(tmp_path / "base.json", {"device": "cpu", "value": 100.0})
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "value" in proc.stdout

    def test_warns_but_exits_zero_without_strict(self, tmp_path):
        fresh = self._write(tmp_path / "fresh.json", {"device": "cpu", "value": 10.0})
        base = self._write(tmp_path / "base.json", {"device": "cpu", "value": 100.0})
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0
        assert "WARNING" in proc.stderr

    def test_strict_exits_one_on_regression(self, tmp_path):
        fresh = self._write(tmp_path / "fresh.json", {"device": "cpu", "value": 10.0})
        base = self._write(tmp_path / "base.json", {"device": "cpu", "value": 100.0})
        proc = subprocess.run(
            [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base), "--strict"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1


@pytest.mark.slow
def test_end_to_end_against_fresh_bench(tmp_path):
    """Run the REAL bench.py (tiny shapes, deep phase off) and feed its record
    through the checker against itself (self-comparison: never a regression)
    and against a doctored 10x baseline (always a regression under --strict)."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        DDR_BENCH_N="256",
        DDR_BENCH_T="24",
        DDR_BENCH_DEEP_N="0",
        DDR_BENCH_DEEP_DEPTH="0",
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=1200, env=env, cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    record = json.loads([ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert record.get("value"), record
    # the new ratio field rides along whenever both throughputs measured
    if record.get("grad_value"):
        assert record.get("grad_over_forward_ratio")
    # every measured phase must carry a non-null peak even on CPU (the
    # compiled program's memory_analysis envelope fills what memory_stats
    # cannot — BENCH_r05's peak_hbm_gb: null regression class)
    for key, peak_key in (
        ("value", "peak_hbm_gb"),
        ("grad_value", "grad_peak_hbm_gb"),
        ("deep_value", "deep_peak_hbm_gb"),
        ("deep_grad_value", "deep_grad_peak_hbm_gb"),
        ("train_value", "train_peak_hbm_gb"),
    ):
        if record.get(key) is not None:
            assert record.get(peak_key) is not None, (peak_key, record)
    # the probe-timeout and kernel/dtype axes are always recorded
    assert record.get("probe_timeout_s") is not None
    assert record.get("probe_timeout_s") <= 900
    assert record.get("kernel") == "auto"
    assert record.get("compute_dtype") == "fp32"
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(record) + "\n")

    ok = subprocess.run(
        [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(fresh), "--strict"],
        capture_output=True, text=True, timeout=60,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr

    doctored = dict(record, value=record["value"] * 10)
    base = tmp_path / "base.json"
    base.write_text(json.dumps(doctored) + "\n")
    bad = subprocess.run(
        [sys.executable, str(SCRIPT), str(fresh), "--baseline", str(base), "--strict"],
        capture_output=True, text=True, timeout=60,
    )
    assert bad.returncode == 1
    assert "WARNING" in bad.stderr


class TestChaosRecords:
    """``ddr chaos`` gating: recovery time / resume-fidelity deltas warn on
    GROWTH, post-restart attainment on DROP, and chaos records compare against
    the CHAOS_* history (by mtime), never a bench round or loadtest record."""

    def _chaos(self, **over):
        rec = {
            "kind": "chaos", "mode": "serve", "device": "cpu",
            "recovery_s": 5.0, "mean_recovery_s": 4.5,
            "error_rate": 0.3, "shed_rate": 0.0,
            "post_restart_attainment": 1.0, "throughput_rps": 3.5,
        }
        rec.update(over)
        return rec

    def test_is_chaos_record(self):
        mod = _load()
        assert mod.is_chaos_record({"kind": "chaos"})
        assert not mod.is_chaos_record({"kind": "loadtest"})
        assert not mod.is_chaos_record({"value": 1.0})

    def test_recovery_growth_flags(self):
        mod = _load()
        by_key = {
            f["key"]: f
            for f in mod.compare(
                self._chaos(recovery_s=10.0, mean_recovery_s=9.0), self._chaos(),
                threshold=0.2,
            )
        }
        assert by_key["recovery_s"]["status"] == "regression"
        assert by_key["mean_recovery_s"]["status"] == "regression"

    def test_faster_recovery_is_ok(self):
        mod = _load()
        by_key = {
            f["key"]: f
            for f in mod.compare(self._chaos(recovery_s=2.0), self._chaos())
        }
        assert by_key["recovery_s"]["status"] == "ok"

    def test_post_restart_attainment_drop_flags(self):
        mod = _load()
        by_key = {
            f["key"]: f
            for f in mod.compare(
                self._chaos(post_restart_attainment=0.5), self._chaos(), threshold=0.2
            )
        }
        assert by_key["post_restart_attainment"]["status"] == "regression"

    def test_train_mode_fidelity_deltas_flag_on_growth(self):
        mod = _load()
        fresh = {"kind": "chaos", "device": "cpu", "loss_delta": 0.5,
                 "params_max_abs_delta": 0.2}
        base = {"kind": "chaos", "device": "cpu", "loss_delta": 0.0001,
                "params_max_abs_delta": 0.0001}
        by_key = {f["key"]: f for f in mod.compare(fresh, base, threshold=0.2)}
        assert by_key["loss_delta"]["status"] == "regression"
        assert by_key["params_max_abs_delta"]["status"] == "regression"

    def test_device_mismatch_downgrades(self):
        mod = _load()
        findings = mod.compare(
            self._chaos(device="cpu", recovery_s=50.0), self._chaos(device="tpu")
        )
        assert all(f["status"] in ("info", "ok") for f in findings)

    def test_chaos_baseline_selected_by_mtime_within_chaos_history(self, tmp_path):
        import os as _os

        mod = _load()
        old = tmp_path / "CHAOS_old.json"
        new = tmp_path / "CHAOS_zz_newer.json"
        bench = tmp_path / "BENCH_r99.json"
        loadtest = tmp_path / "LOADTEST_x.json"
        for p in (old, new, bench, loadtest):
            p.write_text("{}")
        _os.utime(old, (1_000_000, 1_000_000))
        _os.utime(new, (2_000_000, 2_000_000))
        assert mod.latest_baseline(tmp_path, pattern="CHAOS_*.json") == new
        # the fresh record never baselines itself
        assert mod.latest_baseline(
            tmp_path, pattern="CHAOS_*.json", exclude=new
        ) == old

    def test_cli_gates_chaos_record_end_to_end(self, tmp_path, capsys):
        import json as _json

        mod = _load()
        base = self._chaos()
        fresh = self._chaos(recovery_s=20.0, post_restart_attainment=0.4)
        base_p = tmp_path / "CHAOS_base.json"
        fresh_p = tmp_path / "CHAOS_fresh.json"
        base_p.write_text(_json.dumps(base))
        fresh_p.write_text(_json.dumps(fresh))
        rc = mod.main([str(fresh_p), "--baseline", str(base_p), "--strict"])
        err = capsys.readouterr().err
        assert rc == 1
        assert "recovery_s" in err and "post_restart_attainment" in err
        # self-compare passes clean
        assert mod.main([str(base_p), "--baseline", str(base_p), "--strict"]) == 0

    def test_chaos_baseline_pairs_by_mode(self, tmp_path):
        import json as _json
        import os as _os

        mod = _load()
        serve_rec = tmp_path / "CHAOS_a_serve.json"
        train_rec = tmp_path / "CHAOS_b_train.json"
        serve_rec.write_text(_json.dumps(self._chaos(mode="serve")))
        train_rec.write_text(_json.dumps({"kind": "chaos", "mode": "train"}))
        _os.utime(serve_rec, (1_000_000, 1_000_000))
        _os.utime(train_rec, (2_000_000, 2_000_000))  # newest overall
        # a fresh SERVE record must skip the newer train record
        assert mod.latest_chaos_baseline(tmp_path, mode="serve") == serve_rec
        assert mod.latest_chaos_baseline(tmp_path, mode="train") == train_rec
        assert mod.latest_chaos_baseline(tmp_path, mode="bogus") is None
        # and mode=None degrades to plain newest
        assert mod.latest_chaos_baseline(tmp_path, mode=None) == train_rec

    def test_chaos_baseline_pairs_by_reshard(self, tmp_path):
        """An elastic mesh-change drill pays a new-mesh recompile on every
        resume — its recovery_s must only gate against other reshard drills,
        and plain train drills against plain ones."""
        import json as _json
        import os as _os

        mod = _load()
        plain = tmp_path / "CHAOS_plain.json"
        resh = tmp_path / "CHAOS_resh.json"
        plain.write_text(_json.dumps({"kind": "chaos", "mode": "train",
                                      "reshard": None}))
        resh.write_text(_json.dumps({"kind": "chaos", "mode": "train",
                                     "reshard": "4:2"}))
        _os.utime(plain, (1_000_000, 1_000_000))
        _os.utime(resh, (2_000_000, 2_000_000))  # newest overall
        assert mod.latest_chaos_baseline(
            tmp_path, mode="train", reshard=False
        ) == plain
        assert mod.latest_chaos_baseline(
            tmp_path, mode="train", reshard=True
        ) == resh
        # unspecified reshard keeps the old behavior (plain newest of mode)
        assert mod.latest_chaos_baseline(tmp_path, mode="train") == resh


class TestFleetChaosPairing:
    """A 2-replica router drill's recovery_s is re-admission latency while the
    survivor keeps serving — a different quantity from single-replica restart
    latency, so fleet records must only gate against fleet records."""

    def test_chaos_baseline_pairs_by_fleet(self, tmp_path):
        mod = _load()
        plain = tmp_path / "CHAOS_plain_serve.json"
        fleet = tmp_path / "CHAOS_fleet_serve.json"
        plain.write_text(json.dumps({"kind": "chaos", "mode": "serve"}))
        fleet.write_text(json.dumps(
            {"kind": "chaos", "mode": "serve", "fleet": True}
        ))
        os.utime(plain, (1_000_000, 1_000_000))
        os.utime(fleet, (2_000_000, 2_000_000))  # newest overall
        assert mod.latest_chaos_baseline(
            tmp_path, mode="serve", fleet=False
        ) == plain
        assert mod.latest_chaos_baseline(
            tmp_path, mode="serve", fleet=True
        ) == fleet
        # unspecified keeps the old behavior: plain newest of the mode
        assert mod.latest_chaos_baseline(tmp_path, mode="serve") == fleet

    def test_repo_fleet_record_is_loadable(self):
        rec = json.loads((REPO / "CHAOS_r04_serve_fleet.json").read_text())
        assert rec["kind"] == "chaos" and rec["fleet"] is True
        assert rec["passed"] is True
        assert rec["federation_saw_dead"] is True

    def test_loadtest_baseline_pairs_by_fleet(self, tmp_path):
        """--fleet N records' throughput is a group aggregate: single-service
        records never gate against them (and vice versa)."""
        mod = _load()
        single = tmp_path / "LOADTEST_single.json"
        fleet = tmp_path / "LOADTEST_fleet2.json"
        single.write_text(json.dumps({"kind": "loadtest", "p50_ms": 10.0}))
        fleet.write_text(json.dumps(
            {"kind": "loadtest", "p50_ms": 10.0, "fleet": 2}
        ))
        os.utime(single, (1_000_000, 1_000_000))
        os.utime(fleet, (2_000_000, 2_000_000))  # newest overall
        assert mod.latest_loadtest_baseline(tmp_path, fleet=False) == single
        assert mod.latest_loadtest_baseline(tmp_path, fleet=True) == fleet
        assert mod.latest_loadtest_baseline(tmp_path) == fleet  # plain newest
        # the fresh record never self-selects
        assert mod.latest_loadtest_baseline(
            tmp_path, exclude=fleet, fleet=True
        ) is None
