"""``ddr loadtest`` tests.

Fast tests pin the report arithmetic and both generator shapes against a fake
driver (no service, no jax); the slow test runs the real ``--synthetic``
in-process smoke end to end and feeds its ``LOADTEST_*.json`` through
``check_bench_regression.py``.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from ddr_tpu.scripts.loadtest import (
    Outcome,
    build_report,
    main,
    render_summary,
    run_closed_loop,
    run_open_loop,
)

REPO = Path(__file__).resolve().parents[2]


def _ok(latency=0.02, queue=0.004, execute=0.012):
    return Outcome("ok", latency, queue, execute)


class TestBuildReport:
    def test_counts_rates_and_quantiles(self):
        outcomes = (
            [_ok(0.010 + i * 0.001) for i in range(6)]
            + [Outcome("rejected", 0.001)]
            + [Outcome("shed:deadline", 0.5), Outcome("shed:queue-full", 0.002)]
            + [Outcome("error:RuntimeError", 0.1)]
        )
        rep = build_report(outcomes, wall_s=2.0, offered=10)
        assert rep["kind"] == "loadtest" and rep["schema_version"] == 1
        assert rep["requests"] == 10 and rep["ok"] == 6
        assert rep["rejected"] == 1 and rep["shed"] == 2 and rep["errors"] == 1
        assert rep["sheds_by_reason"] == {"deadline": 1, "queue-full": 1}
        assert rep["shed_rate"] == 0.2
        assert rep["reject_rate"] == 0.1
        assert rep["error_rate"] == 0.1
        assert rep["throughput_rps"] == 3.0  # 6 ok / 2 s
        assert rep["offered_rps"] == 5.0
        # latency quantiles are over OK requests only, in milliseconds
        assert rep["p50_ms"] == pytest.approx(13.0, abs=1.5)
        assert rep["p99_ms"] == pytest.approx(15.0, abs=0.5)
        assert rep["queue_p50_ms"] == pytest.approx(4.0)
        assert rep["execute_p99_ms"] == pytest.approx(12.0)

    def test_empty_run_has_null_quantiles_and_zero_rates(self):
        rep = build_report([], wall_s=1.0, offered=0)
        assert rep["requests"] == 0
        for key in ("p50_ms", "p99_ms", "queue_p50_ms", "execute_p99_ms"):
            assert rep[key] is None
        assert rep["shed_rate"] == 0.0 and rep["throughput_rps"] == 0.0
        assert rep["slo_attainment"] is None

    def test_batch_occupancy_from_stats_delta(self):
        before = {"queue": {"served": 10, "batches": 5}}
        after = {
            "queue": {"served": 26, "batches": 9},
            "config": {"max_batch": 4},
        }
        rep = build_report(
            [_ok()], wall_s=1.0, offered=1,
            stats_before=before, stats_after=after,
        )
        assert rep["mean_batch_size"] == 4.0  # (26-10)/(9-5)
        assert rep["mean_batch_occupancy"] == 1.0

    def test_occupancy_none_without_stats(self):
        rep = build_report([_ok()], wall_s=1.0, offered=1)
        assert rep["mean_batch_size"] is None
        assert rep["mean_batch_occupancy"] is None

    def test_slo_prefers_server_tracker(self):
        after = {
            "slo": {
                "target": 0.99,
                "lifetime": {"good": 97, "total": 100, "attainment": 0.97},
                "windows": {"60s": {"burn_rate": 3.0}, "300s": {"burn_rate": 1.0}},
            }
        }
        rep = build_report(
            [_ok()] * 3, wall_s=1.0, offered=3, stats_after=after
        )
        assert rep["slo_target"] == 0.99
        assert rep["slo_attainment"] == 0.97  # the server saw the run
        assert rep["slo_burn_rates"] == {"60s": 3.0, "300s": 1.0}

    def test_slo_attainment_is_the_delta_over_the_run(self):
        """Against a long-lived server, prior traffic (and the priming
        request) must not pollute the measured run's attainment."""
        before = {"slo": {"lifetime": {"good": 50, "total": 100}}}
        after = {"slo": {
            "target": 0.99,
            "lifetime": {"good": 70, "total": 120, "attainment": 70 / 120},
        }}
        rep = build_report(
            [_ok()] * 20, wall_s=1.0, offered=20,
            stats_before=before, stats_after=after,
        )
        # this run: (70-50)/(120-100) = 100%, NOT the lifetime 58%
        assert rep["slo_attainment"] == 1.0

    def test_slo_falls_back_to_client_good_fraction(self):
        outcomes = [_ok()] * 3 + [Outcome("shed:deadline", 0.5)]
        rep = build_report(outcomes, wall_s=1.0, offered=4)
        assert rep["slo_attainment"] == 0.75

    def test_meta_kwargs_ride_the_record(self):
        rep = build_report([_ok()], 1.0, 1, mode="open", device="cpu", seed=7)
        assert rep["mode"] == "open" and rep["device"] == "cpu" and rep["seed"] == 7

    def test_render_summary_smoke(self):
        rep = build_report(
            [_ok()] * 3 + [Outcome("rejected", 0.001)], wall_s=1.0, offered=4,
            mode="open", target="synthetic",
        )
        text = render_summary(rep)
        assert "latency" in text and "queue" in text and "execute" in text
        assert "rejected 1" in text
        assert "slo" in text


class TestGenerators:
    def test_closed_loop_counts_and_unique_indices(self):
        seen: list[int] = []
        lock = threading.Lock()

        def fire(i: int) -> Outcome:
            with lock:
                seen.append(i)
            time.sleep(0.002)
            return _ok()

        outcomes, wall, offered = run_closed_loop(fire, clients=3, duration_s=0.15)
        assert offered == len(outcomes) == len(seen)
        assert len(set(seen)) == len(seen)  # every request got its own index
        assert wall >= 0.15

    def test_open_loop_offered_matches_fired_and_drains(self):
        fired: list[int] = []
        lock = threading.Lock()

        def fire(i: int) -> Outcome:
            with lock:
                fired.append(i)
            time.sleep(0.005)
            return _ok()

        outcomes, wall, offered = run_open_loop(
            fire, rps=150.0, duration_s=0.25, seed=3, max_inflight=8
        )
        assert offered > 10  # Poisson at 150rps over 250ms
        # the pool drained: every offered arrival completed and was recorded
        assert len(outcomes) == len(fired) == offered
        assert wall >= 0.25

    def test_open_loop_counts_client_side_wait_into_latency(self):
        """Past --max-inflight the clock keeps running from the SCHEDULED
        arrival — a backed-up client must not hide server slowness
        (coordinated omission)."""

        def slow_fire(i: int) -> Outcome:
            time.sleep(0.05)
            return _ok(latency=0.05)

        # 1 worker, ~20 arrivals in 100ms, each served in 50ms: the backlog
        # wait dwarfs the 50ms service time for later requests
        outcomes, _, offered = run_open_loop(
            slow_fire, rps=200.0, duration_s=0.1, seed=1, max_inflight=1
        )
        assert offered >= 5
        assert max(o.latency_s for o in outcomes) > 0.15

    def test_open_loop_is_seed_deterministic_in_offer_count(self):
        def fire(i: int) -> Outcome:
            return _ok()

        _, _, a = run_open_loop(fire, rps=300.0, duration_s=0.2, seed=11)
        _, _, b = run_open_loop(fire, rps=300.0, duration_s=0.2, seed=11)
        # identical expovariate streams -> identical arrival schedules
        assert a == b

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError, match="rps"):
            run_open_loop(lambda i: _ok(), rps=0.0, duration_s=1.0)
        with pytest.raises(ValueError, match="clients"):
            run_closed_loop(lambda i: _ok(), clients=0, duration_s=1.0)


class TestCli:
    def test_help_exits_zero(self):
        assert main(["--help"]) == 0

    def test_ddr_cli_dispatches_loadtest(self):
        from ddr_tpu.cli import main as ddr_main

        assert ddr_main(["loadtest", "--help"]) == 0


@pytest.mark.slow
def test_synthetic_loadtest_end_to_end(tmp_path, monkeypatch):
    """The acceptance path: `ddr loadtest --synthetic` over a ~2s open-loop
    run writes a LOADTEST_*.json with non-null p50/p99/attainment and a
    queue/execute split, feeds the live registry's new instruments, and the
    regression gate accepts + self-compares the record."""
    monkeypatch.delenv("DDR_METRICS_DIR", raising=False)
    rc = main([
        "--synthetic", "--n", "64", "--horizon", "8",
        "--rps", "30", "--duration", "2", "--max-inflight", "16",
        "--out", str(tmp_path), "--label", "smoke",
    ])
    assert rc == 0
    report_path = tmp_path / "LOADTEST_smoke.json"
    assert report_path.exists()
    rep = json.loads(report_path.read_text())

    assert rep["kind"] == "loadtest"
    assert rep["requests"] > 10 and rep["ok"] > 0
    for key in ("p50_ms", "p99_ms", "queue_p50_ms", "queue_p99_ms",
                "execute_p50_ms", "execute_p99_ms"):
        assert rep[key] is not None and rep[key] >= 0.0, key
    assert rep["p50_ms"] <= rep["p99_ms"]
    assert rep["slo_attainment"] is not None
    assert rep["slo_target"] is not None
    assert isinstance(rep["sheds_by_reason"], dict)
    assert rep["mean_batch_size"] is not None  # occupancy came from /v1/stats

    # the run log's run_end carries the serve/SLO rollup (the service closes
    # INSIDE the telemetry context), so summarize can replay the objective
    run_log = tmp_path / "run_log.loadtest.jsonl"
    assert run_log.exists()
    events = [json.loads(ln) for ln in run_log.read_text().splitlines() if ln]
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    serve_rollup = run_end["summary"]["serve"]
    assert serve_rollup["slo"]["target"] is not None
    assert serve_rollup["queue"]["served"] > 0

    # the run fed the live request-tracing + SLO instruments
    from ddr_tpu.observability import get_registry
    from ddr_tpu.observability.prometheus import render_text

    txt = render_text(get_registry())
    assert "ddr_serve_queue_seconds_count" in txt
    assert "ddr_serve_execute_seconds_count" in txt
    assert "ddr_slo_burn_rate" in txt

    # the regression gate accepts the record and self-compares clean
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_bench_regression.py"),
         str(report_path), "--baseline", str(report_path), "--strict"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "throughput_rps" in proc.stdout


class TestPriorityMix:
    def test_parse_normalizes_weights(self):
        from ddr_tpu.scripts.loadtest import parse_priority_mix

        mix = parse_priority_mix("interactive=3,bulk=1")
        assert mix == [("interactive", 0.75), ("bulk", 0.25)]
        # bare class names weigh 1.0 each
        assert parse_priority_mix("batch,bulk") == [("batch", 0.5), ("bulk", 0.5)]
        assert parse_priority_mix(None) is None
        assert parse_priority_mix("") is None

    def test_parse_rejects_bad_specs(self):
        from ddr_tpu.scripts.loadtest import parse_priority_mix

        with pytest.raises(ValueError, match="unknown priority"):
            parse_priority_mix("vip=1")
        with pytest.raises(ValueError, match="weight"):
            parse_priority_mix("batch=heavy")
        with pytest.raises(ValueError, match=">= 0"):
            parse_priority_mix("batch=-1")
        with pytest.raises(ValueError, match="zero"):
            parse_priority_mix("batch=0,bulk=0")

    def test_priority_for_is_deterministic_and_covers_mix(self):
        from ddr_tpu.scripts.loadtest import parse_priority_mix, priority_for

        mix = parse_priority_mix("interactive=0.5,bulk=0.5")
        picks = [priority_for(i, mix, seed=7) for i in range(64)]
        assert picks == [priority_for(i, mix, seed=7) for i in range(64)]
        assert set(picks) == {"interactive", "bulk"}  # both classes fired
        assert priority_for(0, None) is None

    def test_report_gains_by_priority_slice(self):
        outcomes = [
            Outcome("ok", 0.010, priority="interactive"),
            Outcome("ok", 0.020, priority="interactive"),
            Outcome("ok", 0.050, priority="bulk"),
            Outcome("shed:queue-full", 0.001, priority="bulk"),
            Outcome("rejected", 0.001, priority="bulk"),
        ]
        rep = build_report(outcomes, wall_s=1.0, offered=5)
        by = rep["by_priority"]
        assert by["interactive"]["requests"] == 2
        assert by["interactive"]["dropped"] == 0
        assert by["bulk"] == {
            "requests": 3, "ok": 1, "dropped": 2,
            "p50_ms": pytest.approx(50.0), "p95_ms": pytest.approx(50.0),
            "p99_ms": pytest.approx(50.0),
        }
        # sheds concentrate in the lowest class — visible in the summary
        assert "class    bulk: 3 requests" in render_summary(rep)
        # classless runs keep the old report shape
        assert "by_priority" not in build_report(
            [_ok()], wall_s=1.0, offered=1
        )


class TestFleetDriver:
    """--fleet plumbing against a fake group — the real 2-replica path runs
    in tests/fleet/; here we pin the Outcome mapping and the stats rollup."""

    class _FakeNet:
        forcing = None
        horizon = 8

    class _FakeSvc:
        class serve_cfg:
            deadline_s = 30.0

        def networks(self):
            return {"default": TestFleetDriver._FakeNet()}

    class _FakeReplica:
        def __init__(self, queue):
            self.service = TestFleetDriver._FakeSvc()
            self._queue = queue

        def stats(self):
            return {"queue": self._queue, "config": {"max_batch": 4}}

    class _FakeGroup:
        def __init__(self):
            self.replicas = [
                TestFleetDriver._FakeReplica({"served": 10, "batches": 5}),
                TestFleetDriver._FakeReplica({"served": 6, "batches": 2}),
            ]
            self.calls = []
            self.raise_unroutable = False

        def forecast(self, **kw):
            from ddr_tpu.fleet.router import NoHealthyReplicaError

            if self.raise_unroutable:
                raise NoHealthyReplicaError("all dead")
            self.calls.append(kw)
            return {"queue_s": 0.001, "execute_s": 0.004}

        def ensemble(self, **kw):
            self.calls.append(kw)
            return {}

    def _driver(self, group, **kw):
        from ddr_tpu.scripts.loadtest import FleetDriver

        return FleetDriver(group, **kw)

    def test_ok_outcome_and_request_shape(self):
        group = self._FakeGroup()
        out = self._driver(group).fire(3)
        assert out.status == "ok"
        assert out.queue_s == 0.001 and out.execute_s == 0.004
        assert group.calls[0]["request_id"] == "lt-3"
        assert group.calls[0]["network"] == "default"

    def test_unroutable_group_is_an_error_datapoint(self):
        group = self._FakeGroup()
        group.raise_unroutable = True
        assert self._driver(group).fire(0).status == "error:unroutable"

    def test_ensemble_requests_ride_the_group(self):
        group = self._FakeGroup()
        assert self._driver(group, ensemble=4).fire(0).status == "ok"
        assert group.calls[0]["members"] == 4

    def test_stats_sum_queues_across_replicas(self):
        stats = self._driver(self._FakeGroup()).stats()
        assert stats["queue"] == {"served": 16, "batches": 7}
        assert stats["config"]["max_batch"] == 4
        assert stats["replicas"] == 2

    def test_fleet_record_carries_fleet_meta(self):
        rep = build_report([_ok()], wall_s=1.0, offered=1, fleet=2,
                           target="fleet:2")
        assert rep["fleet"] == 2 and rep["target"] == "fleet:2"
