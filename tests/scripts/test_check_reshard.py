"""scripts/check_reshard.py: the cross-mesh checkpoint smoke gate must pass on
a clean tree (so elastic-resume bit-rot fails tier-1 fast) and actually catch
breakage."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]
SCRIPT = REPO / "scripts" / "check_reshard.py"


def test_repo_reshard_smokes_clean():
    """THE CI gate: save on a 2-device virtual cpu mesh, reshard-load on one
    device, every leaf bitwise equal."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bitwise equal" in proc.stdout


def test_gate_fails_on_broken_sharding_module(tmp_path):
    """A tree whose sharding module cannot import must fail the gate — copy
    the script next to a stub package with a broken parallel.sharding."""
    pkg = tmp_path / "ddr_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "ddr_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "sharding.py").write_text("raise RuntimeError('bit-rot')\n")
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_reshard.py").write_text(SCRIPT.read_text())
    proc = subprocess.run(
        [sys.executable, str(scripts / "check_reshard.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
    assert "import failed" in proc.stderr
