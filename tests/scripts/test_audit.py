"""`ddr audit`: synthetic localization, dtype-diff attribution, log replay.

The acceptance property (synthetic mode localizes an injected per-reach
anomaly to the correct band and reach), report serialization (audit.json +
audit.md), the CLI exit contract, and replay aggregation over a crafted run
log with band-carrying health events + skill/drift events.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from ddr_tpu.scripts.audit import (
    dtype_diff_audit,
    main,
    replay_audit,
    synthetic_audit,
)


class TestSyntheticAudit:
    @pytest.fixture(scope="class")
    def report(self):
        return synthetic_audit(n=96, t_hours=48, bands=6, top_k=5, seed=0)

    def test_localizes_injected_anomaly(self, report):
        assert report["hit_band"], report["localized"]
        assert report["hit_reach"], report["localized"]
        assert report["hit"]

    def test_report_structure(self, report):
        assert report["mode"] == "synthetic"
        assert len(report["localized"]["band_divergence"]) == report["bands"]
        assert report["injected"]["band"] == report["localized"]["worst_band"]
        # the in-program band health rode both routes
        assert len(report["health_clean"]["band_residual"]) == report["bands"]
        assert len(report["health_perturbed"]["worst_idx"]) == 5
        json.dumps(report)  # JSON-serializable end to end

    def test_explicit_reach(self):
        r = synthetic_audit(
            n=64, t_hours=48, bands=4, top_k=4, seed=1, perturb_reach=10
        )
        assert r["injected"]["reach"] == 10
        assert r["hit_reach"]


class TestDtypeDiff:
    def test_report(self):
        r = dtype_diff_audit(n=64, t_hours=48, bands=4, top_k=4, seed=0)
        assert r["mode"] == "dtype-diff"
        assert len(r["band_ulp_mean"]) == r["bands"]
        assert len(r["worst_reaches"]) == 4
        # healthy fp32-vs-bf16 divergence is small but nonzero
        assert 0 < max(r["band_ulp_max"]) < 1e4
        assert r["health_bf16"]["band_ulp_drift"] is not None
        json.dumps(r)


class TestReplayAudit:
    def _write_log(self, tmp_path):
        events = [
            {"event": "run_start", "t": 0.0, "wall": 1.0, "host": 0, "seq": 0,
             "cmd": "train"},
            {"event": "health", "t": 1.0, "wall": 2.0, "host": 0, "seq": 1,
             "reasons": ["non-finite"], "nonfinite": 4, "q_min": 0.0,
             "q_max": 9.9, "mass_residual": 0.5, "consecutive": 1,
             "worst_band": 2, "band_nonfinite": [0, 0, 4, 0],
             "band_residual": [0.1, 0.2, 8.5, 0.3],
             "band_q_max": [1.0, 2.0, 9.9, 3.0], "worst_idx": [17, 4, 9]},
            {"event": "health", "t": 2.0, "wall": 3.0, "host": 0, "seq": 2,
             "reasons": ["non-finite"], "nonfinite": 2, "q_min": 0.0,
             "q_max": 5.0, "mass_residual": 0.4, "consecutive": 2,
             "worst_band": 2, "band_nonfinite": [0, 0, 2, 0],
             "band_residual": [0.1, 0.2, 5.0, 0.3],
             "band_q_max": [1.0, 2.0, 5.0, 3.0], "worst_idx": [17, 9]},
            {"event": "skill", "t": 3.0, "wall": 4.0, "host": 0, "seq": 3,
             "gauges": 3, "scored": 3,
             "nse": {"median": 0.7, "p10": -0.2, "p90": 0.9,
                     "frac_positive": 0.66},
             "kge": {"median": 0.6, "p10": 0.1},
             "pbias": {"median_abs": 12.0, "p90_abs": 40.0},
             "worst": [{"gauge": "g7", "nse": -0.2, "kge": 0.1, "pbias": 55.0}]},
            {"event": "drift", "t": 4.0, "wall": 5.0, "host": 0, "seq": 4,
             "epoch": 1, "reasons": [],
             "fields": {"n": {"quantiles": [0.02, 0.05, 0.1], "drift": 0.03,
                              "oob": 0, "nonfinite": 0, "n": 96}}},
        ]
        path = tmp_path / "run_log.train.jsonl"
        path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        return path

    def test_replay_aggregates(self, tmp_path):
        r = replay_audit(self._write_log(tmp_path))
        assert r["health_violations"] == 2
        assert r["worst_bands"][0]["band"] == 2
        assert r["worst_bands"][0]["nonfinite"] == 4
        assert r["worst_reaches"][0] == {"reach": 17, "flagged": 2}
        assert r["skill"]["worst"][0]["gauge"] == "g7"
        assert r["drift"]["fields"]["n"]["drift"] == 0.03
        json.dumps(r)

    def test_replay_cli_writes_reports(self, tmp_path):
        log = self._write_log(tmp_path)
        out = tmp_path / "report"
        rc = main([str(log), "--out", str(out)])
        assert rc == 0
        report = json.loads((out / "audit.json").read_text())
        assert report["mode"] == "replay"
        md = (out / "audit.md").read_text()
        assert "Worst bands" in md and "Worst gauges" in md


class TestCli:
    def test_synthetic_cli_exit_zero_and_reports(self, tmp_path):
        rc = main([
            "--synthetic", "--n", "64", "--t-hours", "48", "--bands", "4",
            "--topk", "4", "--seed", "0", "--out", str(tmp_path),
        ])
        assert rc == 0
        report = json.loads((tmp_path / "audit.json").read_text())
        assert report["hit"]
        assert "LOCALIZED" in (tmp_path / "audit.md").read_text()

    def test_dtype_diff_requires_synthetic(self, tmp_path, capsys):
        assert main(["--dtype-diff", "--out", str(tmp_path)]) == 2

    def test_no_args_prints_help(self):
        assert main([]) == 2

    def test_audit_event_emitted_under_metrics_dir(self, tmp_path, monkeypatch):
        metrics_dir = tmp_path / "metrics"
        monkeypatch.setenv("DDR_METRICS_DIR", str(metrics_dir))
        rc = main([
            "--synthetic", "--n", "64", "--t-hours", "48", "--bands", "4",
            "--seed", "0", "--out", str(tmp_path / "report"),
        ])
        assert rc == 0
        log = metrics_dir / "run_log.audit.jsonl"
        events = [json.loads(line) for line in log.read_text().splitlines()]
        audit = [e for e in events if e["event"] == "audit"]
        assert len(audit) == 1 and audit[0]["mode"] == "synthetic"
        assert audit[0]["hit"] is True
