"""End-to-end script tests on the tiny on-disk MERIT fabric and the synthetic basin —
the whole train/test/route/summed-q-prime surface without external data (the
reference's strategy, tests/benchmarks/conftest.py:44-98)."""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pytest
import yaml

from ddr_tpu.geodatazoo.merit import Merit
from ddr_tpu.io import zarrlite


def _synthetic_cfg_dict(tmp_path, **exp):
    return {
        "name": "synthetic_run",
        "geodataset": "synthetic",
        "mode": "training",
        "kan": {"input_var_names": [f"a{i}" for i in range(10)]},
        "experiment": {
            "start_time": "1981/10/01",
            "end_time": "1981/10/20",
            "rho": 8,
            "batch_size": 2,
            "epochs": 1,
            "warmup": 1,
            "learning_rate": {1: 0.01},
            **exp,
        },
        "params": {"save_path": str(tmp_path)},
    }


class TestTrainScript:
    def test_train_on_synthetic(self, tmp_path):
        from ddr_tpu.scripts.train import train
        from ddr_tpu.validation.configs import Config

        cfg = Config(**_synthetic_cfg_dict(tmp_path))
        params, opt_state = train(cfg, max_batches=2)
        assert params is not None
        ckpts = list((tmp_path / "saved_models").glob("*.pkl"))
        assert ckpts, "no checkpoint written"
        plots = list((tmp_path / "plots").glob("*.png"))
        assert plots, "no validation plot written"

    def test_train_on_merit_fixture(self, merit_cfg):
        from ddr_tpu.scripts.train import train

        dataset = Merit(merit_cfg)
        params, _ = train(merit_cfg, dataset=dataset, max_batches=1)
        assert params is not None

    def test_train_resume_skips_minibatches(self, tmp_path):
        from ddr_tpu.scripts.train import train
        from ddr_tpu.training import latest_checkpoint, load_state
        from ddr_tpu.validation.configs import Config

        cfg = Config(**_synthetic_cfg_dict(tmp_path))
        train(cfg, max_batches=1)
        ckpt = latest_checkpoint(tmp_path / "saved_models")
        blob = load_state(ckpt)
        assert blob["epoch"] == 1 and blob["mini_batch"] == 0
        cfg2 = Config(**_synthetic_cfg_dict(tmp_path))
        cfg2.experiment.checkpoint = ckpt
        params, _ = train(cfg2, max_batches=1)
        assert params is not None

    @pytest.mark.slow
    def test_train_resume_from_orbax_checkpoint(self, tmp_path):
        """The orbax directory form must be drop-in for experiment.checkpoint:
        params restore structurally, and the optax state is re-restored with
        its template so the optimizer consumes it directly."""
        from ddr_tpu.scripts.train import train
        from ddr_tpu.training import latest_checkpoint, load_state, save_state_orbax
        from ddr_tpu.validation.configs import Config

        cfg = Config(**_synthetic_cfg_dict(tmp_path))
        train(cfg, max_batches=1)
        blob = load_state(latest_checkpoint(tmp_path / "saved_models"))
        ob = save_state_orbax(
            tmp_path / "saved_models", "orbax_resume",
            epoch=blob["epoch"], mini_batch=blob["mini_batch"],
            params=blob["params"], opt_state=blob["opt_state"],
            rng_state=blob.get("rng_state"), arch=blob.get("arch"),
        )
        cfg2 = Config(**_synthetic_cfg_dict(tmp_path))
        cfg2.experiment.checkpoint = ob
        params, _ = train(cfg2, max_batches=1)
        assert params is not None


class TestTestScript:
    def test_test_on_merit_fixture(self, merit_cfg, tmp_path):
        from ddr_tpu.scripts.test import test as run_test

        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "testing"
        cfg.experiment.rho = None
        cfg.experiment.batch_size = 8  # days per chunk
        cfg.params.save_path = tmp_path
        metrics = run_test(cfg)
        out = zarrlite.open_group(tmp_path / "model_test.zarr")
        pred = out["predictions"].read()
        assert pred.shape[0] == 2  # two non-headwater gauges
        assert np.isfinite(pred).all()
        assert len(metrics.nse) == 2

    def test_carry_state_continuity(self, merit_cfg, tmp_path):
        """Chunked sequential inference must match one-shot inference."""
        from ddr_tpu.scripts.test import test as run_test

        base = merit_cfg.model_copy(deep=True)
        base.mode = "testing"
        base.experiment.rho = None
        base.params.save_path = tmp_path / "oneshot"
        base.experiment.batch_size = 50  # single chunk covers all 20 days
        run_test(base)

        chunked = merit_cfg.model_copy(deep=True)
        chunked.mode = "testing"
        chunked.experiment.rho = None
        chunked.params.save_path = tmp_path / "chunked"
        chunked.experiment.batch_size = 5
        run_test(chunked)

        a = zarrlite.open_group(tmp_path / "oneshot" / "model_test.zarr")["predictions"].read()
        b = zarrlite.open_group(tmp_path / "chunked" / "model_test.zarr")["predictions"].read()
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-4)


class TestRouterScript:
    def test_route_all_segments(self, merit_cfg, tmp_path):
        from ddr_tpu.scripts.router import route_domain

        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "routing"
        cfg.experiment.rho = None
        cfg.experiment.batch_size = 10
        cfg.data_sources.gages = None
        cfg.data_sources.gages_adjacency = None
        cfg.params.save_path = tmp_path
        discharge = route_domain(cfg)
        assert discharge.shape[0] == 10  # full domain
        assert np.isfinite(discharge).all()
        out = zarrlite.open_group(tmp_path / "chrout.zarr")
        assert out["discharge"].read().shape == discharge.shape

    def test_route_target_catchments(self, merit_cfg, tmp_path):
        from ddr_tpu.scripts.router import route_domain
        from tests.geodatazoo.conftest import COMIDS

        cfg = merit_cfg.model_copy(deep=True)
        cfg.mode = "routing"
        cfg.experiment.rho = None
        cfg.experiment.batch_size = 10
        cfg.data_sources.target_catchments = [str(COMIDS[4])]
        cfg.params.save_path = tmp_path
        discharge = route_domain(cfg)
        assert discharge.shape[0] == 5  # closure of reach 4


class TestSummedQPrime:
    def test_baseline(self, merit_cfg, tmp_path):
        from ddr_tpu.scripts.summed_q_prime import eval_q_prime

        cfg = merit_cfg.model_copy(deep=True)
        cfg.params.save_path = tmp_path
        metrics = eval_q_prime(cfg)
        assert (tmp_path / "summed_q_prime_summary.json").exists()
        assert (tmp_path / "summed_q_prime_metrics.csv").exists()
        out = zarrlite.open_group(tmp_path / "summed_q_prime.zarr")
        assert out["predictions"].read().shape[0] == len(metrics.nse)


class TestTrainAndTest:
    @pytest.mark.slow
    def test_synthetic_train_and_test(self, tmp_path):
        from ddr_tpu.scripts.train_and_test import train_and_test
        from ddr_tpu.validation.configs import Config

        d = _synthetic_cfg_dict(
            tmp_path,
            epochs=1,
            test_start_time="1981/10/01",
            test_end_time="1981/10/20",
            batch_size=4,
        )
        cfg = Config(**d)
        train_and_test(cfg)
        assert (tmp_path / "model_test.zarr").exists()


class TestCli:
    def test_dispatch_and_help(self, capsys):
        from ddr_tpu.cli import main

        assert main([]) == 0
        assert "train" in capsys.readouterr().out
        assert main(["bogus"]) == 2

    def test_cli_train_synthetic(self, tmp_path):
        from ddr_tpu.cli import main

        d = _synthetic_cfg_dict(tmp_path, epochs=1, batch_size=4)
        cfg_path = tmp_path / "config.yaml"
        cfg_path.write_text(yaml.safe_dump(d))
        assert main(["train", str(cfg_path)]) == 0
