"""Config-reference generator tests (reference scripts/gen_config_docs.py:1-122)."""

from __future__ import annotations

from ddr_tpu.scripts.gen_config_docs import generate, main


def test_generate_covers_all_models():
    md = generate()
    for section in (
        "Config",
        "DataSources",
        "Params",
        "Kan",
        "ExperimentConfig",
        "BmiInitConfig",
        "BenchmarkConfig",
        "LTIRouteConfig",
    ):
        assert f"## `{section}`" in md


def test_required_and_defaults_marked():
    md = generate()
    assert "**required**" in md  # name, kan, ...
    assert "`tpu`" in md  # device default


def test_table_rows_have_exactly_four_cells():
    for line in generate().splitlines():
        if line.startswith("| `"):
            assert line.count("|") - line.count("\\|") == 5, line


def test_no_duplicate_sections():
    md = generate()
    headers = [ln for ln in md.splitlines() if ln.startswith("## ")]
    assert len(headers) == len(set(headers))


def test_enum_values_inlined():
    md = generate()
    assert "'merit'" in md and "'training'" in md


def test_main_writes_file(tmp_path):
    out = tmp_path / "ref.md"
    assert main([str(out)]) == 0
    assert out.read_text().startswith("# Configuration reference")


def test_factory_defaults_not_marked_required():
    """default_factory fields must show their materialized value, not **required**
    (regression: grid_range/learnable_parameters were mislabeled)."""
    from ddr_tpu.scripts.gen_config_docs import generate

    text = generate()
    kan_section = text.split("## `Kan`")[1].split("## ")[0]
    assert "[-2.0, 2.0]" in kan_section
    assert '["n", "q_spatial"]' in kan_section
    # Genuinely required fields keep the marker.
    assert "**required**" in kan_section  # input_var_names
