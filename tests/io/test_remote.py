"""Remote icechunk/S3 store backend (ddr_tpu.io.remote): the xarray-convention
adapter against local stand-in groups, the s3:// registration seam, and the
zero-data-layer-change contract (StreamflowReader over a mocked s3 store) —
reference read_ic, /root/reference/src/ddr/io/readers.py:413-443."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.io import remote, stores, zarrlite
from ddr_tpu.io.remote import (
    XarrayConventionGroup,
    _decode_cf_time,
    open_icechunk_group,
    parse_s3_uri,
)


@pytest.fixture(autouse=True)
def _clean_s3_backend():
    """Each test starts and ends with no s3 backend registered (the module
    auto-registers on first s3:// resolution)."""
    stores.unregister_store_backend("s3")
    yield
    stores.unregister_store_backend("s3")


class TestParseS3Uri:
    def test_bucket_and_prefix(self):
        assert parse_s3_uri("s3://mybucket/path/to/store") == ("mybucket", "path/to/store")
        assert parse_s3_uri("s3://bucket") == ("bucket", "")

    def test_rejects_non_s3(self):
        with pytest.raises(ValueError, match="not an s3"):
            parse_s3_uri("gs://bucket/x")
        with pytest.raises(ValueError, match="no bucket"):
            parse_s3_uri("s3:///x")


class TestDecodeCfTime:
    def test_days_since(self):
        t = _decode_cf_time(np.arange(3), "days since 1980-01-01")
        assert t[0] == pd.Timestamp("1980-01-01")
        assert (t[1] - t[0]).days == 1

    def test_hours_since(self):
        t = _decode_cf_time(np.arange(4), "hours since 1990-06-01 00:00:00")
        assert t[0] == pd.Timestamp("1990-06-01")
        assert (t[1] - t[0]).total_seconds() == 3600

    def test_datetime64_passthrough(self):
        vals = np.array(["2000-01-01", "2000-01-02"], dtype="datetime64[ns]")
        t = _decode_cf_time(vals, None)
        assert t[0] == pd.Timestamp("2000-01-01")

    def test_numeric_without_units_raises(self):
        with pytest.raises(ValueError, match="units"):
            _decode_cf_time(np.arange(3), None)
        with pytest.raises(ValueError, match="unsupported CF"):
            _decode_cf_time(np.arange(3), "fortnights since 1980-01-01")


def _xarray_style_store(path, n_ids=5, n_days=10, transposed=False, hourly=False):
    """A local group laid out exactly as xarray's zarr encoding writes the
    reference's icechunk datasets: coordinate arrays + CF time + per-variable
    _ARRAY_DIMENSIONS, and NO HydroStore attrs."""
    g = zarrlite.create_group(path)
    ids = np.arange(100, 100 + n_ids, dtype=np.int64)
    g.create_array("divide_id", ids, attributes={"_ARRAY_DIMENSIONS": ["divide_id"]})
    n_t = n_days * (24 if hourly else 1)
    units = "hours since 1982-03-01" if hourly else "days since 1982-03-01"
    g.create_array(
        "time", np.arange(n_t, dtype=np.int64),
        attributes={"units": units, "calendar": "standard", "_ARRAY_DIMENSIONS": ["time"]},
    )
    rng = np.random.default_rng(0)
    qr = rng.uniform(0.1, 5.0, (n_ids, n_t)).astype(np.float32)
    if transposed:
        g.create_array(
            "Qr", qr.T, attributes={"_ARRAY_DIMENSIONS": ["time", "divide_id"]}
        )
    else:
        g.create_array(
            "Qr", qr, attributes={"_ARRAY_DIMENSIONS": ["divide_id", "time"]}
        )
    return ids, qr


class TestXarrayConventionGroup:
    def test_synthesizes_hydro_attrs(self, tmp_path):
        ids, qr = _xarray_style_store(tmp_path / "ic")
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        assert adapted.attrs["ids"] == list(ids)
        assert adapted.attrs["start_date"] == "1982/03/01"
        assert adapted.attrs["freq"] == "D"
        assert adapted.attrs["id_dim"] == "divide_id"
        # coords hidden from variable iteration
        assert list(adapted.keys()) == ["Qr"]
        assert "time" in adapted  # but still addressable

    def test_hourly_freq_detected(self, tmp_path):
        _xarray_style_store(tmp_path / "ic", hourly=True)
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        assert adapted.attrs["freq"] == "h"

    def test_transposed_variable_reoriented(self, tmp_path):
        ids, qr = _xarray_style_store(tmp_path / "ic", transposed=True)
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        arr = adapted["Qr"]
        assert arr.shape == qr.shape  # (ids, time) again
        np.testing.assert_array_equal(np.asarray(arr), qr)

    def test_transposed_array_numpy2_copy_kwarg(self, tmp_path):
        """NumPy 2 calls __array__(dtype, copy=...); a 1-arg signature raises
        TypeError there (advisor r5). Both copy flavors must materialize."""
        ids, qr = _xarray_style_store(tmp_path / "ic", transposed=True)
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        arr = adapted["Qr"]
        # np.asarray(..., copy=...) only forwards on NumPy 2; call directly so
        # the contract is pinned on NumPy 1 environments too
        np.testing.assert_array_equal(arr.__array__(copy=False), qr)
        np.testing.assert_array_equal(arr.__array__(copy=True), qr)
        out = arr.__array__(dtype=np.float64, copy=None)
        assert out.dtype == np.float64
        np.testing.assert_allclose(out, qr)

    def test_self_dimensioned_coordinates_hidden_from_keys(self, tmp_path):
        """xarray coordinate variables are exactly the arrays named after their
        own dimension; any such 1-D array must vanish from keys() like the
        id/time coords (advisor r5) — while 1-D DATA variables over the id dim
        stay visible."""
        _xarray_style_store(tmp_path / "ic")
        g = zarrlite.open_group(tmp_path / "ic")
        g.create_array(
            "ensemble", np.arange(3), attributes={"_ARRAY_DIMENSIONS": ["ensemble"]}
        )
        g.create_array(
            "lat", np.linspace(30, 40, 5),
            attributes={"_ARRAY_DIMENSIONS": ["divide_id"]},
        )
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        assert sorted(adapted.keys()) == ["Qr", "lat"]
        assert "ensemble" in adapted  # hidden from iteration, still addressable

    def test_rejects_non_uniform_time_axis(self, tmp_path):
        """freq must come from the WHOLE axis: a daily store with a gap would
        otherwise be stamped 'D' and silently mis-index every window past the
        gap (advisor r5)."""
        g = zarrlite.create_group(tmp_path / "gap")
        g.create_array("divide_id", np.arange(3, dtype=np.int64))
        g.create_array(
            "time", np.array([0, 1, 2, 5, 6], dtype=np.int64),
            attributes={"units": "days since 1980-01-01"},
        )
        with pytest.raises(ValueError, match="not uniform"):
            XarrayConventionGroup(zarrlite.open_group(tmp_path / "gap"))

    def test_rejects_sub_daily_non_hourly_cadence(self, tmp_path):
        """A 6-hourly store must refuse, not silently mislabel as daily."""
        g = zarrlite.create_group(tmp_path / "ic6h")
        g.create_array("divide_id", np.arange(3, dtype=np.int64))
        g.create_array(
            "time", np.arange(0, 48, 6, dtype=np.int64),
            attributes={"units": "hours since 1980-01-01"},
        )
        with pytest.raises(ValueError, match="cadence"):
            XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic6h"))

    def test_rejects_non_hydrology_group(self, tmp_path):
        g = zarrlite.create_group(tmp_path / "x")
        g.create_array("stuff", np.ones(3))
        with pytest.raises(ValueError, match="id coordinate"):
            XarrayConventionGroup(zarrlite.open_group(tmp_path / "x"))

    def test_hydro_store_reads_adapter(self, tmp_path):
        """HydroStore consumes the adapted group with no special-casing."""
        ids, qr = _xarray_style_store(tmp_path / "ic")
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        hs = stores.HydroStore(adapted)
        assert hs.start_date == pd.Timestamp("1982-03-01")
        assert not hs.is_hourly
        sel = hs.select("Qr", np.array([1, 3]), np.array([0, 2, 4]))
        np.testing.assert_array_equal(sel, qr[[1, 3]][:, [0, 2, 4]])


class TestS3Registration:
    def test_unregistered_s3_names_missing_dependency(self):
        """Without icechunk installed, an s3:// URI must fail fast with the
        dependency named (auto-registration reaches the import guard)."""
        with pytest.raises(RuntimeError, match="icechunk"):
            stores.open_hydro_store("s3://bucket/store")

    def test_mocked_backend_is_config_only(self, tmp_path):
        """enable_remote_stores with an injected session: the reference's
        s3:// config values work through the NORMAL facade path."""
        ids, qr = _xarray_style_store(tmp_path / "ic")
        opened_uris = []

        def fake_session(uri):
            opened_uris.append(uri)
            return zarrlite.open_group(tmp_path / "ic")

        remote.enable_remote_stores(
            opener=lambda uri: open_icechunk_group(uri, _session_store_opener=fake_session)
        )
        hs = stores.open_hydro_store("s3://mrms/streamflow_store")
        assert opened_uris == ["s3://mrms/streamflow_store"]
        assert hs.ids == list(ids)
        np.testing.assert_array_equal(
            hs.select("Qr", np.arange(len(ids)), np.arange(qr.shape[1])), qr
        )

    def test_streamflow_reader_end_to_end_over_s3(self, tmp_path):
        """The zero-data-layer-change contract: StreamflowReader with an s3://
        streamflow source produces the (T, N) lateral inflows for a batch."""
        from ddr_tpu.geodatazoo.dataclasses import Dates, RoutingData
        from ddr_tpu.io.readers import StreamflowReader

        ids, qr = _xarray_style_store(tmp_path / "ic", n_ids=6, n_days=40)

        remote.enable_remote_stores(
            opener=lambda uri: open_icechunk_group(
                uri,
                _session_store_opener=lambda u: zarrlite.open_group(tmp_path / "ic"),
            )
        )

        class _Cfg:
            class data_sources:
                streamflow = "s3://bucket/qr"
                is_hourly = False

            s3_region = "us-east-2"

        reader = StreamflowReader(_Cfg)
        dates = Dates(start_time="1982/03/05", end_time="1982/03/12", rho=None)
        rd = RoutingData(
            n_segments=3, divide_ids=np.array([101, 104, 9999]), dates=dates
        )
        out = reader(routing_dataclass=rd)
        n_hours = len(dates.batch_hourly_time_range)
        assert out.shape == (n_hours, 3)
        # daily store upsampled x24; missing divide 9999 filled with 0.001
        np.testing.assert_allclose(out[0, 0], qr[1, 4])  # id 101 = row 1, day 4
        np.testing.assert_allclose(out[:, 2], 0.001)

    def test_s3_region_reaches_backend(self, monkeypatch):
        """cfg.s3_region must reach the default opener AT OPEN TIME (reference
        read_ic's region argument) — regardless of which store auto-registered
        the backend first."""
        from ddr_tpu.io.readers import _honor_s3_region

        monkeypatch.setattr(remote, "_DEFAULT_REGION", "us-east-2")

        class _Cfg:
            s3_region = "eu-west-1"

        _honor_s3_region(_Cfg, "s3://bucket/x")
        assert remote._DEFAULT_REGION == "eu-west-1"
        # local paths leave it untouched
        _honor_s3_region(type("C", (), {"s3_region": "ap-south-1"}), "/local/path")
        assert remote._DEFAULT_REGION == "eu-west-1"

    def test_load_config_sets_default_region(self, tmp_path, monkeypatch):
        from ddr_tpu.validation.configs import load_config

        monkeypatch.setattr(remote, "_DEFAULT_REGION", "us-east-2")
        load_config(
            base={
                "name": "r",
                "geodataset": "synthetic",
                "mode": "training",
                "kan": {"input_var_names": ["a"]},
                "s3_region": "us-west-2",
                "params": {"save_path": str(tmp_path)},
            },
            save_config=False,
        )
        assert remote._DEFAULT_REGION == "us-west-2"


class TestReadRetry:
    """Bounded retry around remote array reads (DDR_IO_RETRIES /
    DDR_IO_RETRY_BACKOFF_S) with the `data.remote_read` fault site firing
    before every attempt — an injected crash is the deterministic stand-in
    for the transient connection reset / 5xx / timeout the loop absorbs."""

    @pytest.fixture(autouse=True)
    def _disarm_faults(self, monkeypatch):
        from ddr_tpu.observability import faults

        monkeypatch.setenv("DDR_IO_RETRY_BACKOFF_S", "0.0")  # instant retries
        yield
        faults.configure(None)

    def test_transient_faults_absorbed_within_budget(self):
        from ddr_tpu.observability import faults

        faults.configure("crash@data.remote_read:n=2")
        calls = []
        out = remote.read_with_retry(lambda: calls.append(1) or 42, what="x")
        assert out == 42
        # the fault fires BEFORE the read: two crashed attempts never reached
        # the store, the third read it once
        assert len(calls) == 1

    def test_retry_budget_exhausts_and_reraises(self, monkeypatch):
        from ddr_tpu.observability import faults

        monkeypatch.setenv("DDR_IO_RETRIES", "1")
        faults.configure("crash@data.remote_read")  # every attempt fails
        with pytest.raises(faults.InjectedFault):
            remote.read_with_retry(lambda: 42, what="x")

    def test_non_transient_raises_immediately(self):
        calls = []

        def read():
            calls.append(1)
            raise KeyError("no such variable")

        with pytest.raises(KeyError):
            remote.read_with_retry(read, what="x")
        assert len(calls) == 1  # a deterministic failure is never retried

    def test_transient_classification(self):
        from ddr_tpu.observability.faults import InjectedFault

        assert remote._is_transient(ConnectionError("reset"))
        assert remote._is_transient(TimeoutError())
        assert remote._is_transient(InjectedFault("data.remote_read", "x"))
        assert remote._is_transient(Exception("503 Service Unavailable"))
        assert remote._is_transient(Exception("read timed out"))

        class Http(Exception):
            status = 502

        assert remote._is_transient(Http("bad gateway upstream"))
        assert not remote._is_transient(Exception("missing variable Qr"))
        assert not remote._is_transient(ValueError("unsupported CF time units"))

    def test_env_knobs_and_malformed_fallback(self, monkeypatch):
        monkeypatch.setenv("DDR_IO_RETRIES", "5")
        monkeypatch.setenv("DDR_IO_RETRY_BACKOFF_S", "0.25")
        assert remote._retry_config() == (5, 0.25)
        monkeypatch.setenv("DDR_IO_RETRIES", "lots")
        monkeypatch.setenv("DDR_IO_RETRY_BACKOFF_S", "fast")
        assert remote._retry_config() == (3, 0.1)  # defaults, not a crash

    def test_adapter_reads_ride_the_retry_loop(self, tmp_path):
        """End-to-end: one injected transient failure per read path (id
        coordinate, time coordinate, transposed variable) and the adapter
        still materializes everything."""
        from ddr_tpu.observability import faults

        ids, qr = _xarray_style_store(tmp_path / "ic", transposed=True)
        faults.configure("crash@data.remote_read:n=1")
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "ic"))
        assert adapted.attrs["ids"] == list(ids)
        faults.configure("crash@data.remote_read:n=1")
        np.testing.assert_array_equal(np.asarray(adapted["Qr"]), qr)


class TestTimeOrigin:
    def test_daily_off_midnight_raises(self, tmp_path):
        """A daily store starting off-midnight would silently floor every
        whole-day offset — refuse like the cadence check does."""
        g = zarrlite.create_group(tmp_path / "offmid")
        g.create_array("divide_id", np.arange(3, dtype=np.int64))
        g.create_array(
            "time", np.arange(0, 72, 24, dtype=np.int64),
            attributes={"units": "hours since 1980-01-01 13:00"},
        )
        with pytest.raises(ValueError, match="off-midnight"):
            XarrayConventionGroup(zarrlite.open_group(tmp_path / "offmid"))

    def test_hourly_off_midnight_keeps_full_timestamp(self, tmp_path):
        """An hourly store legitimately starting at 13:00 must carry the full
        timestamp (date truncation would read every window 13 hours early)."""
        g = zarrlite.create_group(tmp_path / "h13")
        g.create_array("divide_id", np.arange(2, dtype=np.int64))
        g.create_array(
            "time", np.arange(48, dtype=np.int64),
            attributes={"units": "hours since 1990-06-01 13:00"},
        )
        adapted = XarrayConventionGroup(zarrlite.open_group(tmp_path / "h13"))
        assert adapted.attrs["freq"] == "h"
        hs = stores.HydroStore(adapted)
        assert hs.start_date == pd.Timestamp("1990-06-01 13:00")
