"""Gage-reference column derivation (ABS_DIFF / DA_VALID / FLOW_SCALE), mirroring
/root/reference/tests/references/test_build_gage_references.py."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from ddr_tpu.io.readers import compute_flow_scale_factor, derive_gage_reference_columns


def _table(**cols):
    base = {
        "STAID": ["00000001"] * len(next(iter(cols.values()))),
    }
    base.update(cols)
    return pd.DataFrame(base)


class TestAbsDiff:
    def test_computed(self):
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=[100.0, 4.0], COMID_DRAIN_SQKM=[105.0, 8.0],
                   COMID_UNITAREA_SQKM=[50.0, 50.0])
        )
        np.testing.assert_array_almost_equal(out["ABS_DIFF"], [5.0, 4.0])

    def test_symmetric(self):
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=[100.0, 110.0], COMID_DRAIN_SQKM=[110.0, 100.0],
                   COMID_UNITAREA_SQKM=[50.0, 50.0])
        )
        np.testing.assert_array_almost_equal(out["ABS_DIFF"], [10.0, 10.0])

    def test_input_not_mutated(self):
        df = _table(DRAIN_SQKM=[100.0], COMID_DRAIN_SQKM=[105.0], COMID_UNITAREA_SQKM=[50.0])
        derive_gage_reference_columns(df)
        assert "ABS_DIFF" not in df.columns

    def test_missing_column_raises(self):
        with pytest.raises(KeyError, match="COMID_UNITAREA_SQKM"):
            derive_gage_reference_columns(
                _table(DRAIN_SQKM=[1.0], COMID_DRAIN_SQKM=[1.0])
            )


class TestDaValid:
    def _da_valid(self, abs_pairs):
        drain = [100.0] * len(abs_pairs)
        comid = [100.0 + d for d, _ in abs_pairs]
        unit = [u for _, u in abs_pairs]
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=drain, COMID_DRAIN_SQKM=comid, COMID_UNITAREA_SQKM=unit)
        )
        return out["DA_VALID"].tolist()

    def test_valid_when_within_unit_area(self):
        assert self._da_valid([(5.0, 10.0), (50.0, 50.0)]) == [True, True]

    def test_invalid_when_exceeds_threshold(self):
        assert self._da_valid([(150.0, 30.0)]) == [False]

    def test_small_unit_area_uses_100km_floor(self):
        # 60 <= max(30, 100) = 100 -> valid
        assert self._da_valid([(60.0, 30.0)]) == [True]

    def test_large_unit_area_uses_actual_value(self):
        # 150 <= max(200, 100) = 200 -> valid
        assert self._da_valid([(150.0, 200.0)]) == [True]


class TestFlowScale:
    def test_no_scaling_when_gage_downstream(self):
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=[200.0], COMID_DRAIN_SQKM=[180.0], COMID_UNITAREA_SQKM=[50.0])
        )
        assert out["FLOW_SCALE"].iloc[0] == 1.0

    def test_scaling_when_gage_upstream(self):
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=[80.0], COMID_DRAIN_SQKM=[100.0], COMID_UNITAREA_SQKM=[50.0])
        )
        assert out["FLOW_SCALE"].iloc[0] == pytest.approx((50.0 - 20.0) / 50.0)

    def test_no_scaling_when_mismatch_exceeds_unit_area(self):
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=[10.0], COMID_DRAIN_SQKM=[100.0], COMID_UNITAREA_SQKM=[50.0])
        )
        assert out["FLOW_SCALE"].iloc[0] == 1.0

    def test_matches_scalar_path(self):
        """The vectorized derivation agrees with compute_flow_scale_factor (the
        runtime fallback used when the CSV lacks FLOW_SCALE)."""
        rng = np.random.default_rng(0)
        drain = rng.uniform(10, 300, 50)
        comid = rng.uniform(10, 300, 50)
        unit = rng.uniform(20, 120, 50)
        out = derive_gage_reference_columns(
            _table(DRAIN_SQKM=drain, COMID_DRAIN_SQKM=comid, COMID_UNITAREA_SQKM=unit)
        )
        scalar = [
            compute_flow_scale_factor(d, c, u) for d, c, u in zip(drain, comid, unit)
        ]
        np.testing.assert_allclose(out["FLOW_SCALE"], scalar, rtol=1e-12)

    def test_round_trip_through_filters(self):
        """Derived columns drive the training-time filters end to end."""
        from ddr_tpu.io.readers import filter_gages_by_da_valid

        df = derive_gage_reference_columns(
            pd.DataFrame(
                {
                    "STAID": ["00000001", "00000002"],
                    "DRAIN_SQKM": [100.0, 100.0],
                    "COMID_DRAIN_SQKM": [105.0, 400.0],
                    "COMID_UNITAREA_SQKM": [50.0, 50.0],
                }
            )
        )
        gage_dict = {c: df[c].tolist() for c in df.columns}
        kept, dropped = filter_gages_by_da_valid(
            np.array(["00000001", "00000002"]), gage_dict
        )
        assert kept.tolist() == ["00000001"]
        assert dropped == 1
