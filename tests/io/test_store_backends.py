"""Remote-store seam: scheme registry + GroupLike protocol (VERDICT missing #6).

An icechunk/S3 backend must be addable without touching the data layer: these
tests register a purely in-memory backend implementing only the GroupLike surface
and drive the full HydroStore/AttributeStore facades through it, and pin the
fail-fast message for unregistered schemes."""

from __future__ import annotations

from contextlib import contextmanager

import numpy as np
import pytest

from ddr_tpu.io.stores import (
    AttributeStore,
    GroupLike,
    HydroStore,
    open_attribute_store,
    open_hydro_store,
    register_store_backend,
    unregister_store_backend,
    write_hydro_store,
)


@contextmanager
def temp_backend(scheme, opener):
    """Register a backend for the block; never leaks into _STORE_BACKENDS."""
    register_store_backend(scheme, opener)
    try:
        yield
    finally:
        unregister_store_backend(scheme)


class _MemArray:
    """Minimal array-like: only what the facades touch (shape + read)."""

    def __init__(self, data):
        self.data = np.asarray(data)

    @property
    def shape(self):
        return self.data.shape

    def read(self):
        return self.data


class _MemGroup:
    """Minimal GroupLike with no zarrlite ancestry at all."""

    def __init__(self, attrs, arrays):
        self.attrs = attrs
        self._arrays = {k: _MemArray(v) for k, v in arrays.items()}

    def __getitem__(self, name):
        return self._arrays[name]

    def __contains__(self, name):
        return name in self._arrays

    def keys(self):
        return iter(self._arrays)


@pytest.fixture()
def mem_backend():
    opened = []

    def opener(uri):
        opened.append(uri)
        return _MemGroup(
            attrs={"start_date": "1981/10/01", "freq": "h", "ids": ["cat-1", "cat-2"]},
            arrays={"Qr": np.arange(12, dtype=np.float32).reshape(2, 6)},
        )

    register_store_backend("mems", opener)
    yield opened
    unregister_store_backend("mems")


class TestBackendRegistry:
    def test_registered_scheme_serves_hydro_store(self, mem_backend):
        store = open_hydro_store("mems://bucket/run-42")
        assert mem_backend == ["mems://bucket/run-42"]
        assert isinstance(store, HydroStore)
        assert store.ids == ["cat-1", "cat-2"]
        assert store.is_hourly
        assert store.n_time("Qr") == 6
        np.testing.assert_array_equal(
            store.select("Qr", np.array([1]), np.array([0, 2])), [[6.0, 8.0]]
        )

    def test_registered_scheme_serves_attribute_store(self):
        opener = lambda uri: _MemGroup(
            attrs={"ids": ["a", "b", "c"]},
            arrays={"slope": np.array([1.0, 2.0, 3.0]), "area": np.ones(3)},
        )
        with temp_backend("memattr", opener):
            store = open_attribute_store("memattr://x")
            assert isinstance(store, AttributeStore)
            assert sorted(store.attribute_names) == ["area", "slope"]
            np.testing.assert_array_equal(
                store.matrix(["slope"]), np.array([[1.0, 2.0, 3.0]], np.float32)
            )

    def test_unregistered_scheme_names_the_seam(self):
        # non-s3 schemes fail with the registration seam named; s3 now
        # auto-registers the icechunk backend and fails on the missing
        # dependency instead (tests/io/test_remote.py covers that path)
        with pytest.raises(ValueError, match="register_store_backend"):
            open_hydro_store("gs://bucket/repo")
        with pytest.raises(ValueError, match="no egress"):
            open_attribute_store("gs://bucket/attrs")
        from ddr_tpu.io.stores import unregister_store_backend as _unreg

        try:
            with pytest.raises(RuntimeError, match="icechunk"):
                open_hydro_store("s3://bucket/repo")
        finally:
            _unreg("s3")  # drop the auto-registered backend for test isolation

    def test_scheme_is_case_insensitive(self, mem_backend):
        register_store_backend("MEMS", lambda uri: pytest.fail("should reuse lowercase"))
        unregister_store_backend("MEMS")  # removed the lowercase entry
        with pytest.raises(ValueError, match="register_store_backend"):
            open_hydro_store("mems://gone")

    def test_file_scheme_maps_to_local_path(self, tmp_path):
        write_hydro_store(
            tmp_path / "st", ["g1"], "1981/10/01", "D", {"Qr": np.ones((1, 4))}
        )
        store = open_hydro_store(f"file://{tmp_path / 'st'}")
        assert store.ids == ["g1"]

    def test_local_paths_bypass_registry(self, tmp_path, mem_backend):
        write_hydro_store(
            tmp_path / "local", ["g1"], "1981/10/01", "D", {"Qr": np.ones((1, 4))}
        )
        store = open_hydro_store(tmp_path / "local")
        assert mem_backend == []  # no backend consulted

    def test_zarrlite_group_satisfies_protocol(self, tmp_path):
        from ddr_tpu.io import zarrlite

        group = zarrlite.create_group(tmp_path / "g")
        assert isinstance(group, GroupLike)
        assert isinstance(_MemGroup({}, {}), GroupLike)


class _ArrayOnly:
    """zarr-python-style array: shape + __array__, no .read()."""

    def __init__(self, data):
        self._d = np.asarray(data)

    @property
    def shape(self):
        return self._d.shape

    def __array__(self, dtype=None):
        return self._d.astype(dtype) if dtype else self._d


class TestZarrPythonStyleArrays:
    def test_facades_accept_array_without_read(self):
        class G:
            attrs = {"start_date": "1981/10/01", "freq": "D", "ids": ["x", "y"]}

            def __getitem__(self, k):
                return _ArrayOnly(np.arange(6, dtype=np.float32).reshape(2, 3))

            def __contains__(self, k):
                return k == "Qr"

            def keys(self):
                return iter(["Qr"])

        with temp_backend("zp", lambda uri: G()):
            store = open_hydro_store("zp://x")
            assert store.n_time("Qr") == 3
            np.testing.assert_array_equal(
                store.select("Qr", np.array([0, 1]), np.array([2])), [[2.0], [5.0]]
            )

    def test_attribute_store_accepts_array_without_read(self):
        class G:
            attrs = {"ids": ["a", "b"]}

            def __getitem__(self, k):
                return _ArrayOnly(np.array([1.0, 2.0]))

            def __contains__(self, k):
                return True

            def keys(self):
                return iter(["slope"])

        with temp_backend("zpa", lambda uri: G()):
            store = open_attribute_store("zpa://x")
            assert store.attribute_names == ["slope"]
            np.testing.assert_array_equal(store.as_mapping()["slope"], [1.0, 2.0])


class TestFileUriParsing:
    def test_file_uri_with_remote_host_rejected(self):
        with pytest.raises(ValueError, match="remote host"):
            open_hydro_store("file://example.com/data/store")

    def test_file_uri_three_slash_absolute(self, tmp_path):
        write_hydro_store(
            tmp_path / "abs", ["g"], "1981/10/01", "D", {"Qr": np.ones((1, 2))}
        )
        assert open_hydro_store(f"file://{tmp_path / 'abs'}").ids == ["g"]

    def test_percent_encoded_file_uri_decodes(self, tmp_path):
        store_dir = tmp_path / "my store"
        write_hydro_store(
            store_dir, ["g"], "1981/10/01", "D", {"Qr": np.ones((1, 2))}
        )
        uri = "file://" + str(store_dir).replace(" ", "%20")
        assert open_hydro_store(uri).ids == ["g"]
