"""zarrlite round-trip + zarr v3 on-disk format conformance tests."""

import json

import numpy as np
import pytest

from ddr_tpu.io import zarrlite


def test_array_roundtrip_dtypes(tmp_path):
    g = zarrlite.create_group(tmp_path / "store.zarr")
    rng = np.random.default_rng(0)
    cases = {
        "i32": rng.integers(-1000, 1000, 257).astype(np.int32),
        "i64": rng.integers(-(2**40), 2**40, 64),
        "u8": rng.integers(0, 255, 100).astype(np.uint8),
        "f32": rng.normal(size=(33, 7)).astype(np.float32),
        "f64": rng.normal(size=500),
        "bool": rng.random(77) > 0.5,
    }
    for name, data in cases.items():
        g.create_array(name, data)
    g2 = zarrlite.open_group(tmp_path / "store.zarr")
    for name, data in cases.items():
        out = g2[name].read()
        assert out.dtype == data.dtype
        np.testing.assert_array_equal(out, data)


def test_multichunk_and_edge_chunks(tmp_path):
    g = zarrlite.create_group(tmp_path / "s")
    data = np.arange(1000, dtype=np.float32).reshape(50, 20)
    g.create_array("x", data, chunks=(7, 9))
    out = zarrlite.open_group(tmp_path / "s")["x"].read()
    np.testing.assert_array_equal(out, data)


def test_uncompressed_and_nan_fill(tmp_path):
    g = zarrlite.create_group(tmp_path / "s")
    data = np.array([1.0, np.nan, np.inf, -np.inf])
    g.create_array("x", data, compress=False, fill_value=np.nan)
    arr = zarrlite.open_group(tmp_path / "s")["x"]
    assert np.isnan(arr.fill_value)
    out = arr.read()
    assert out[0] == 1.0 and np.isnan(out[1]) and np.isposinf(out[2]) and np.isneginf(out[3])


def test_attrs_persist_and_nested_groups(tmp_path):
    g = zarrlite.create_group(tmp_path / "s")
    g.attrs["format"] = "COO"
    g.attrs.update({"shape": [5, 5]})
    sub = g.create_group("gauge_01")
    sub.create_array("values", np.ones(3, dtype=np.uint8))
    sub.attrs["gage_idx"] = 4

    g2 = zarrlite.open_group(tmp_path / "s")
    assert g2.attrs["format"] == "COO"
    assert g2.attrs["shape"] == [5, 5]
    assert "gauge_01" in g2
    assert g2["gauge_01"].attrs["gage_idx"] == 4
    assert dict(g2["gauge_01"].arrays())["values"].read().sum() == 3
    assert [k for k, _ in g2.groups()] == ["gauge_01"]


def test_on_disk_layout_is_zarr_v3(tmp_path):
    """The written metadata documents must be valid zarr v3 core spec."""
    g = zarrlite.create_group(tmp_path / "s")
    g.create_array("x", np.arange(10, dtype=np.int32))
    root_meta = json.loads((tmp_path / "s" / "zarr.json").read_text())
    assert root_meta == {"zarr_format": 3, "node_type": "group", "attributes": {}}
    arr_meta = json.loads((tmp_path / "s" / "x" / "zarr.json").read_text())
    assert arr_meta["zarr_format"] == 3
    assert arr_meta["node_type"] == "array"
    assert arr_meta["data_type"] == "int32"
    assert arr_meta["chunk_grid"]["name"] == "regular"
    assert arr_meta["codecs"][0]["name"] == "bytes"
    assert (tmp_path / "s" / "x" / "c" / "0").exists()


def test_scalar_array(tmp_path):
    g = zarrlite.create_group(tmp_path / "s")
    g.create_array("v", np.float64(3.5))
    assert zarrlite.open_group(tmp_path / "s")["v"].read() == 3.5


def test_open_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        zarrlite.open_group(tmp_path / "nope")
    g = zarrlite.create_group(tmp_path / "s")
    with pytest.raises(KeyError):
        g["missing"]


def test_zero_length_array(tmp_path):
    """Single-catchment gauge subsets have zero-nnz adjacencies (empty index arrays)."""
    g = zarrlite.create_group(tmp_path / "s")
    g.create_array("empty", np.array([], dtype=np.int32))
    out = zarrlite.open_group(tmp_path / "s")["empty"].read()
    assert out.shape == (0,) and out.dtype == np.int32


def test_attrs_delete_and_pop_persist(tmp_path):
    g = zarrlite.create_group(tmp_path / "s")
    g.attrs["x"] = 1
    g.attrs["y"] = 2
    del g.attrs["x"]
    assert g.attrs.pop("y") == 2
    g.attrs.setdefault("z", 3)
    g2 = zarrlite.open_group(tmp_path / "s")
    assert dict(g2.attrs) == {"z": 3}


def test_create_group_wipes_stale_children(tmp_path):
    """Rebuilding a store in place must not leave removed members resolvable."""
    g = zarrlite.create_group(tmp_path / "s")
    g.create_group("old_gauge").create_array("values", np.ones(2, dtype=np.uint8))
    g.create_array("old_array", np.ones(3))
    g2 = zarrlite.create_group(tmp_path / "s")
    assert "old_gauge" not in g2 and "old_array" not in g2
    assert list(g2.keys()) == []


def test_create_group_refuses_non_store_dir(tmp_path):
    d = tmp_path / "notastore"
    d.mkdir()
    (d / "data.txt").write_text("hello")
    with pytest.raises(FileExistsError):
        zarrlite.create_group(d)
    assert (d / "data.txt").exists()
