"""io.readers tests (reference tests/io coverage: gage CSV, filters, flow scaling,
streamflow/observation readers over stores built in tmp dirs)."""

import numpy as np
import pytest
from scipy import sparse

from ddr_tpu.engine.core import coo_to_zarr_group
from ddr_tpu.geodatazoo.dataclasses import Dates
from ddr_tpu.io import zarrlite
from ddr_tpu.io.readers import (
    ObservationSet,
    StreamflowReader,
    USGSObservationReader,
    build_flow_scale_tensor,
    compute_flow_scale_factor,
    convert_ft3_s_to_m3_s,
    fill_nans,
    filter_gages_by_area_threshold,
    filter_gages_by_da_valid,
    filter_headwater_gages,
    naninfmean,
    read_coo,
    read_gage_info,
    read_zarr,
)
from ddr_tpu.io.stores import write_hydro_store


@pytest.fixture
def gage_csv(tmp_path):
    p = tmp_path / "gages.csv"
    p.write_text(
        "STAID,STANAME,DRAIN_SQKM,LAT_GAGE,LNG_GAGE,ABS_DIFF,DA_VALID,COMID\n"
        "1013500,STATION A,2252.7,47.23,-68.58,10.0,True,7100001\n"
        "01014000,STATION B,3186.8,47.11,-68.64,80.0,False,7100002\n"
        "01015800,STATION C,773.0,46.52,-68.37,5.0,True,7100003\n"
    )
    return p


class TestGageInfo:
    def test_read_pads_staid(self, gage_csv):
        d = read_gage_info(gage_csv)
        assert d["STAID"] == ["01013500", "01014000", "01015800"]
        assert d["DRAIN_SQKM"][0] == 2252.7
        assert d["COMID"] == [7100001, 7100002, 7100003]

    def test_missing_required_column_raises(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("STAID,STANAME\n123,x\n")
        with pytest.raises(KeyError, match="missing"):
            read_gage_info(p)

    def test_staname_backfilled_from_staid(self, tmp_path):
        p = tmp_path / "g.csv"
        p.write_text("STAID,DRAIN_SQKM,LAT_GAGE,LNG_GAGE\n99,1.0,0.0,0.0\n")
        d = read_gage_info(p)
        # Backfill happens before STAID padding (reference readers.py:125-131).
        assert d["STANAME"] == ["99"]
        assert d["STAID"] == ["00000099"]

    def test_missing_file(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_gage_info(tmp_path / "nope.csv")


class TestFilters:
    def test_area_threshold(self, gage_csv):
        d = read_gage_info(gage_csv)
        ids = np.array(d["STAID"])
        kept, removed = filter_gages_by_area_threshold(ids, d, threshold=50.0)
        assert list(kept) == ["01013500", "01015800"] and removed == 1
        with pytest.raises(KeyError):
            filter_gages_by_area_threshold(ids, {"STAID": []}, 50.0)

    def test_da_valid(self, gage_csv):
        d = read_gage_info(gage_csv)
        ids = np.array(d["STAID"])
        kept, removed = filter_gages_by_da_valid(ids, d)
        assert list(kept) == ["01013500", "01015800"] and removed == 1

    def test_headwater(self, tmp_path):
        root = zarrlite.create_group(tmp_path / "gages.zarr")
        chain = sparse.coo_matrix(
            (np.ones(2, dtype=np.uint8), ([1, 2], [0, 1])), shape=(3, 3)
        )
        empty = sparse.coo_matrix((1, 1), dtype=np.uint8)
        coo_to_zarr_group(root, "A", chain, [1, 2, 3], "merit")
        coo_to_zarr_group(root, "B", empty, [9], "merit")
        ids = np.array(["A", "B", "C"])
        kept, removed = filter_headwater_gages(ids, zarrlite.open_group(tmp_path / "gages.zarr"))
        assert list(kept) == ["A"] and removed == 2


class TestFlowScale:
    def test_factor_cases(self):
        assert compute_flow_scale_factor(100.0, 80.0, 50.0) == 1.0  # gage >= comid
        assert compute_flow_scale_factor(np.nan, 80.0, 50.0) == 1.0
        assert compute_flow_scale_factor(100.0, 120.0, 0.0) == 1.0
        assert compute_flow_scale_factor(100.0, 200.0, 50.0) == 1.0  # diff >= unit area
        np.testing.assert_allclose(compute_flow_scale_factor(100.0, 120.0, 50.0), 30.0 / 50.0)

    def test_tensor_fast_path_and_fallback(self):
        gd = {
            "STAID": ["00000001", "00000002"],
            "DRAIN_SQKM": [100.0, 100.0],
            "FLOW_SCALE": [0.25, np.nan],
        }
        fs = build_flow_scale_tensor(["1", "2"], gd, [0, 3], 5)
        np.testing.assert_allclose(fs, [0.25, 1, 1, 1, 1])

        gd2 = {
            "STAID": ["00000001"],
            "DRAIN_SQKM": [100.0],
            "COMID_DRAIN_SQKM": [120.0],
            "COMID_UNITAREA_SQKM": [50.0],
        }
        fs2 = build_flow_scale_tensor(["1"], gd2, [2], 4)
        np.testing.assert_allclose(fs2, [1, 1, 0.6, 1])

    def test_tensor_graceful_skip(self):
        fs = build_flow_scale_tensor(["1"], {"STAID": ["00000001"]}, [0], 2)
        np.testing.assert_allclose(fs, [1, 1])


class TestNaNUtils:
    def test_naninfmean(self):
        assert naninfmean(np.array([1.0, np.nan, np.inf, 3.0])) == 2.0
        assert np.isnan(naninfmean(np.array([np.nan, np.inf])))

    def test_fill_nans_global_and_rowwise(self):
        a = np.array([[1.0, np.nan], [3.0, 5.0]])
        np.testing.assert_allclose(fill_nans(a), [[1.0, 3.0], [3.0, 5.0]])
        np.testing.assert_allclose(
            fill_nans(a, row_means=np.array([10.0, 20.0])), [[1.0, 10.0], [3.0, 5.0]]
        )

    def test_units(self):
        np.testing.assert_allclose(convert_ft3_s_to_m3_s(np.array([1.0])), [0.0283168])


class _Cfg:
    """Minimal config stand-in for reader construction."""

    class _DS:
        def __init__(self, streamflow=None, observations=None, gages=None, is_hourly=False):
            self.streamflow = streamflow
            self.observations = observations
            self.gages = gages
            self.is_hourly = is_hourly

    def __init__(self, **kw):
        self.data_sources = self._DS(**kw)


class TestStreamflowReader:
    def _dates(self):
        return Dates(start_time="1981/02/01", end_time="1981/02/04")

    def test_daily_store_repeats_24(self, tmp_path):
        qr = np.arange(20.0).reshape(2, 10)  # 2 divides x 10 days from 1981/02/01
        write_hydro_store(tmp_path / "qr.zarr", ids=[101, 202], start_date="1981/02/01",
                          freq="D", variables={"Qr": qr})
        reader = StreamflowReader(_Cfg(streamflow=tmp_path / "qr.zarr"))

        class RD:
            divide_ids = [101, 202]
            dates = self._dates()

        out = reader(routing_dataclass=RD())
        assert out.shape == (len(RD.dates.batch_hourly_time_range), 2)
        np.testing.assert_allclose(out[:24, 0], 0.0)  # day 0 value repeated
        np.testing.assert_allclose(out[24:48, 0], 1.0)

    def test_missing_divide_filled(self, tmp_path):
        write_hydro_store(tmp_path / "qr.zarr", ids=[101], start_date="1981/02/01",
                          freq="D", variables={"Qr": np.ones((1, 10))})
        reader = StreamflowReader(_Cfg(streamflow=tmp_path / "qr.zarr"))

        class RD:
            divide_ids = [101, 999]
            dates = self._dates()

        out = reader(routing_dataclass=RD())
        np.testing.assert_allclose(out[:, 1], 0.001)
        np.testing.assert_allclose(out[:, 0], 1.0)

    def test_hourly_store_direct(self, tmp_path):
        T = 10 * 24
        qr = np.tile(np.arange(T, dtype=float), (1, 1))
        write_hydro_store(tmp_path / "qr.zarr", ids=[7], start_date="1981/02/01",
                          freq="h", variables={"Qr": qr})
        reader = StreamflowReader(_Cfg(streamflow=tmp_path / "qr.zarr"))

        class RD:
            divide_ids = [7]
            dates = self._dates()

        out = reader(routing_dataclass=RD())
        np.testing.assert_allclose(out[:, 0], np.arange(len(RD.dates.batch_hourly_time_range)))

    def test_out_of_coverage_asserts(self, tmp_path):
        write_hydro_store(tmp_path / "qr.zarr", ids=[101], start_date="1981/03/01",
                          freq="D", variables={"Qr": np.ones((1, 5))})
        reader = StreamflowReader(_Cfg(streamflow=tmp_path / "qr.zarr"))

        class RD:
            divide_ids = [101]
            dates = self._dates()  # starts 1981/02/01, before store start

        with pytest.raises(AssertionError, match="negative"):
            reader(routing_dataclass=RD())


class TestUSGSObservationReader:
    def test_read_data(self, tmp_path, gage_csv):
        ids = ["01013500", "01014000", "01015800"]
        flow = np.arange(30.0).reshape(3, 10)
        write_hydro_store(tmp_path / "obs.zarr", ids=ids, start_date="1981/02/01",
                          freq="D", variables={"streamflow": flow}, id_dim="gage_id")
        cfg = _Cfg(observations=tmp_path / "obs.zarr", gages=gage_csv)
        reader = USGSObservationReader(cfg)
        dates = Dates(start_time="1981/02/02", end_time="1981/02/05")
        obs = reader.read_data(dates)
        assert isinstance(obs, ObservationSet)
        assert obs.streamflow.shape == (3, 4)
        np.testing.assert_allclose(obs.streamflow[0], [1, 2, 3, 4])

    def test_requires_gages(self, tmp_path):
        write_hydro_store(tmp_path / "obs.zarr", ids=["x"], start_date="1981/02/01",
                          freq="D", variables={"streamflow": np.ones((1, 3))})
        with pytest.raises(ValueError, match="gages"):
            USGSObservationReader(_Cfg(observations=tmp_path / "obs.zarr"))


def test_read_coo_and_read_zarr(tmp_path):
    root = zarrlite.create_group(tmp_path / "g.zarr")
    coo = sparse.coo_matrix((np.ones(1, dtype=np.uint8), ([1], [0])), shape=(2, 2))
    coo_to_zarr_group(root, "01", coo, [5, 6], "merit", gage_idx=0)
    loaded, grp = read_coo(tmp_path / "g.zarr", "01")
    np.testing.assert_array_equal(loaded.toarray(), coo.toarray())
    assert grp.attrs["gage_idx"] == 0
    with pytest.raises(KeyError, match="Cannot find key"):
        read_coo(tmp_path / "g.zarr", "nope")
    with pytest.raises(FileNotFoundError):
        read_zarr(tmp_path / "missing.zarr")
    assert "01" in read_zarr(tmp_path / "g.zarr")


def test_observation_reader_out_of_coverage_asserts(tmp_path, gage_csv):
    ids = ["01013500", "01014000", "01015800"]
    write_hydro_store(tmp_path / "obs.zarr", ids=ids, start_date="1981/02/03",
                      freq="D", variables={"streamflow": np.ones((3, 5))}, id_dim="gage_id")
    reader = USGSObservationReader(_Cfg(observations=tmp_path / "obs.zarr", gages=gage_csv))
    with pytest.raises(AssertionError, match="negative"):
        reader.read_data(Dates(start_time="1981/02/01", end_time="1981/02/04"))
    with pytest.raises(AssertionError, match="exceeds"):
        reader.read_data(Dates(start_time="1981/02/05", end_time="1981/02/12"))
