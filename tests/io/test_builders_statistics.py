"""io.builders + io.statistics tests."""

import json

import numpy as np
import pytest
from scipy import sparse

from ddr_tpu.engine.core import coo_to_zarr_group
from ddr_tpu.geodatazoo.dataclasses import Dates
from ddr_tpu.io import zarrlite
from ddr_tpu.io.builders import (
    construct_network_matrix,
    create_hydrofabric_observations,
    upstream_closure,
)
from ddr_tpu.io.readers import ObservationSet
from ddr_tpu.io.statistics import compute_statistics, set_statistics


def _subsets(tmp_path):
    """Two gauges over a 6-node CONUS matrix with overlapping subsets."""
    root = zarrlite.create_group(tmp_path / "gages.zarr")
    a = sparse.coo_matrix((np.ones(2), ([2, 4], [0, 2])), shape=(6, 6))
    b = sparse.coo_matrix((np.ones(2), ([2, 5], [0, 2])), shape=(6, 6))
    coo_to_zarr_group(root, "A", a, [1, 2, 3, 4, 5, 6], "merit", gage_catchment=4, gage_idx=4)
    coo_to_zarr_group(root, "B", b, [1, 2, 3, 4, 5, 6], "merit", gage_catchment=5, gage_idx=5)
    return zarrlite.open_group(tmp_path / "gages.zarr")


class TestConstructNetworkMatrix:
    def test_union_dedupes(self, tmp_path):
        subsets = _subsets(tmp_path)
        coo, idx, wb = construct_network_matrix(["A", "B"], subsets)
        assert coo.shape == (6, 6)
        edges = set(zip(coo.row.tolist(), coo.col.tolist()))
        assert edges == {(2, 0), (4, 2), (5, 2)}  # (2,0) deduped across A and B
        assert idx == [4, 5] and wb == [4, 5]

    def test_missing_gauge_skipped(self, tmp_path):
        subsets = _subsets(tmp_path)
        coo, idx, _ = construct_network_matrix(["A", "ZZZ"], subsets)
        assert idx == [4]

    def test_all_missing_raises(self, tmp_path):
        subsets = _subsets(tmp_path)
        with pytest.raises(KeyError):
            construct_network_matrix(["Y", "Z"], subsets)


def test_create_hydrofabric_observations():
    dates = Dates(start_time="1981/02/01", end_time="1981/02/10")
    dates.set_date_range(np.arange(2, 5))
    full = ObservationSet(
        ["g1", "g2"], dates.daily_time_range, np.arange(20.0).reshape(2, 10)
    )
    out = create_hydrofabric_observations(dates, np.array(["g2"]), full)
    np.testing.assert_allclose(out.streamflow, [[12.0, 13.0, 14.0]])


class TestUpstreamClosure:
    def test_y_network(self):
        # 0,1 -> 2 -> 3; 4 isolated
        rows = np.array([2, 2, 3])
        cols = np.array([0, 1, 2])
        np.testing.assert_array_equal(upstream_closure(rows, cols, 5, [3]), [0, 1, 2, 3])
        np.testing.assert_array_equal(upstream_closure(rows, cols, 5, [2]), [0, 1, 2])
        np.testing.assert_array_equal(upstream_closure(rows, cols, 5, [4]), [4])

    def test_no_edges(self):
        out = upstream_closure(np.array([]), np.array([]), 3, [1])
        np.testing.assert_array_equal(out, [1])


class _Cfg:
    class _DS:
        def __init__(self, attributes, statistics):
            self.attributes = attributes
            self.statistics = statistics

    def __init__(self, attributes, statistics):
        self.data_sources = self._DS(attributes, statistics)
        self.geodataset = "merit"


class TestStatistics:
    def test_compute(self):
        stats = compute_statistics({"a": np.array([1.0, np.nan, 3.0])})
        assert stats["a"]["min"] == 1.0 and stats["a"]["max"] == 3.0
        assert stats["a"]["mean"] == 2.0

    def test_cache_roundtrip(self, tmp_path):
        cfg = _Cfg(attributes="/fake/path/attrs.zarr", statistics=tmp_path)
        attrs = {"slope": np.array([0.1, 0.2, 0.3]), "area": np.array([5.0, 10.0, 15.0])}
        df1 = set_statistics(cfg, attrs)
        cache = tmp_path / "merit_attribute_statistics_attrs.zarr.json"
        assert cache.exists()
        # Second call must read the cache, not recompute: poison the input.
        df2 = set_statistics(cfg, {"slope": np.array([999.0]), "area": np.array([999.0])})
        assert df1["slope"]["mean"] == df2["slope"]["mean"]
        payload = json.loads(cache.read_text())
        assert set(payload["slope"]) == {"min", "max", "mean", "std", "p10", "p90"}


def test_construct_network_matrix_partial_attrs_stay_aligned(tmp_path):
    """A subset missing gage_catchment must not shift the idx/catchment pairing."""
    root = zarrlite.create_group(tmp_path / "g.zarr")
    a = sparse.coo_matrix((np.ones(1), ([1], [0])), shape=(4, 4))
    coo_to_zarr_group(root, "X", a, [1, 2, 3, 4], "merit", gage_idx=1)  # no catchment
    coo_to_zarr_group(root, "Y", a, [1, 2, 3, 4], "merit", gage_catchment=9, gage_idx=2)
    _, idx, wb = construct_network_matrix(["X", "Y"], zarrlite.open_group(tmp_path / "g.zarr"))
    assert idx == [2] and wb == [9]
