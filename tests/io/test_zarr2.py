"""zarr v2 read backend: fixtures are hand-built from the v2 storage spec (JSON
metadata + dot-keyed zlib chunks), NOT written by any code in this repo — so the
GroupLike protocol is finally exercised by an implementation that wasn't
developed alongside its own writer (VERDICT round-2 item 9)."""

import json
import zlib

import numpy as np
import pytest

from ddr_tpu.io import zarr2
from ddr_tpu.io.stores import (
    open_hydro_store,
    read_array,
    unregister_store_backend,
)


def _write_v2_array(path, data, chunks, compressor={"id": "zlib", "level": 1},
                    order="C", fill_value=0.0, drop_chunks=()):
    """Spec-derived writer: .zarray JSON + dot-keyed (optionally zlib) chunks."""
    path.mkdir(parents=True)
    meta = {
        "zarr_format": 2,
        "shape": list(data.shape),
        "chunks": list(chunks),
        "dtype": data.dtype.str,
        "compressor": compressor,
        "fill_value": fill_value,
        "order": order,
        "filters": None,
    }
    (path / ".zarray").write_text(json.dumps(meta))
    grid = [max(1, -(-s // c)) for s, c in zip(data.shape, chunks)]
    import itertools

    for idx in itertools.product(*(range(g) for g in grid)):
        if idx in drop_chunks:
            continue
        # full-size chunk buffer, edge chunks padded with fill (per spec)
        chunk = np.full(chunks, fill_value, dtype=data.dtype)
        sel = tuple(slice(i * c, min((i + 1) * c, s)) for i, c, s in zip(idx, chunks, data.shape))
        trim = tuple(slice(0, sl.stop - sl.start) for sl in sel)
        chunk[trim] = data[sel]
        raw = chunk.tobytes(order=order)
        if compressor is not None:
            raw = zlib.compress(raw)
        (path / ".".join(map(str, idx))).write_bytes(raw)


def _write_v2_group(path, attrs):
    path.mkdir(parents=True, exist_ok=True)
    (path / ".zgroup").write_text(json.dumps({"zarr_format": 2}))
    (path / ".zattrs").write_text(json.dumps(attrs))


@pytest.fixture
def v2_store(tmp_path):
    root = tmp_path / "legacy.zarr"
    rng = np.random.default_rng(0)
    qr = rng.uniform(0, 5, (6, 50)).astype(np.float32)
    _write_v2_group(root, {
        "start_date": "1990/01/01", "freq": "D",
        "ids": ["cat-1", "cat-2", "cat-3", "cat-4", "cat-5", "cat-6"],
    })
    _write_v2_array(root / "Qr", qr, chunks=(4, 16))  # uneven edge chunks
    return root, qr


def test_reads_hand_built_v2_store(v2_store):
    root, qr = v2_store
    g = zarr2.open_group(root)
    assert g.attrs["freq"] == "D"
    assert "Qr" in g and list(g.keys()) == ["Qr"]
    np.testing.assert_array_equal(g["Qr"].read(), qr)
    np.testing.assert_array_equal(np.asarray(g["Qr"]), qr)  # __array__ protocol


def test_hydro_store_facade_over_v2(v2_store):
    """open_hydro_store sniffs .zgroup and serves the SAME facade API as v3."""
    root, qr = v2_store
    store = open_hydro_store(root)
    assert store.ids[0] == "cat-1" and not store.is_hourly
    sel = store.select("Qr", np.array([1, 3]), np.arange(10, 20))
    np.testing.assert_array_equal(sel, qr[[1, 3]][:, 10:20])


def test_scheme_registration_dispatch(v2_store):
    root, qr = v2_store
    zarr2.register("zarr2")
    try:
        store = open_hydro_store(f"zarr2://{root}")
        np.testing.assert_array_equal(read_array(store["Qr"]), qr)
    finally:
        unregister_store_backend("zarr2")


def test_missing_chunk_is_fill_value(tmp_path):
    data = np.arange(32, dtype=np.float64).reshape(4, 8)
    root = tmp_path / "s.zarr"
    _write_v2_group(root, {"ids": []})
    _write_v2_array(root / "x", data, chunks=(2, 4), fill_value=-9.0, drop_chunks=((1, 1),))
    got = zarr2.open_group(root)["x"].read()
    expect = data.copy()
    expect[2:4, 4:8] = -9.0
    np.testing.assert_array_equal(got, expect)


def test_uncompressed_fortran_order_and_int_dtype(tmp_path):
    data = np.arange(24, dtype=np.int32).reshape(6, 4)
    root = tmp_path / "s.zarr"
    _write_v2_group(root, {"ids": []})
    _write_v2_array(root / "x", data, chunks=(6, 4), compressor=None, order="F")
    np.testing.assert_array_equal(zarr2.open_group(root)["x"].read(), data)


def test_nested_subgroup(tmp_path):
    root = tmp_path / "s.zarr"
    _write_v2_group(root, {"ids": []})
    _write_v2_group(root / "sub", {"tag": 7})
    _write_v2_array(root / "sub" / "y", np.ones(5, dtype=np.float32), chunks=(3,))
    g = zarr2.open_group(root)
    assert g["sub"].attrs["tag"] == 7
    np.testing.assert_array_equal(g["sub"]["y"].read(), np.ones(5, np.float32))


def test_unsupported_features_named(tmp_path):
    root = tmp_path / "s.zarr"
    _write_v2_group(root, {"ids": []})
    _write_v2_array(root / "x", np.ones((2, 2), np.float32), chunks=(2, 2),
                    compressor={"id": "blosc", "cname": "lz4"})
    with pytest.raises(ValueError, match="blosc"):
        zarr2.open_group(root)["x"].read()
    bad = tmp_path / "v3ish"
    bad.mkdir()
    with pytest.raises(FileNotFoundError, match="zgroup"):
        zarr2.open_group(bad)


def test_slash_dimension_separator(tmp_path):
    """zarr >= 2.8 nested stores: dimension_separator '/' -> chunk files at
    nested paths; silently-all-fill reads here were a review-caught bug."""
    data = np.arange(12, dtype=np.float32).reshape(3, 4)
    root = tmp_path / "s.zarr"
    _write_v2_group(root, {"ids": []})
    arr = root / "x"
    arr.mkdir()
    meta = {
        "zarr_format": 2, "shape": [3, 4], "chunks": [2, 2], "dtype": "<f4",
        "compressor": None, "fill_value": 0.0, "order": "C", "filters": None,
        "dimension_separator": "/",
    }
    (arr / ".zarray").write_text(json.dumps(meta))
    import itertools

    for i, j in itertools.product(range(2), range(2)):
        chunk = np.zeros((2, 2), np.float32)
        r0, r1 = i * 2, min((i + 1) * 2, 3)
        c0, c1 = j * 2, min((j + 1) * 2, 4)
        chunk[: r1 - r0, : c1 - c0] = data[r0:r1, c0:c1]
        d = arr / str(i)
        d.mkdir(exist_ok=True)
        (d / str(j)).write_bytes(chunk.tobytes())
    np.testing.assert_array_equal(zarr2.open_group(root)["x"].read(), data)


def test_unknown_separator_raises(tmp_path):
    root = tmp_path / "s.zarr"
    _write_v2_group(root, {"ids": []})
    arr = root / "x"
    arr.mkdir()
    (arr / ".zarray").write_text(json.dumps({
        "zarr_format": 2, "shape": [2], "chunks": [2], "dtype": "<f4",
        "compressor": None, "fill_value": 0.0, "order": "C", "filters": None,
        "dimension_separator": ":",
    }))
    with pytest.raises(ValueError, match="dimension_separator"):
        zarr2.open_group(root)["x"]


def test_file_uri_opens_v2_store(v2_store):
    root, qr = v2_store
    store = open_hydro_store(f"file://{root}")
    np.testing.assert_array_equal(read_array(store["Qr"]), qr)
