"""Daily-aggregation window alignment, quantified (VERDICT weak #7).

Both aggregators share the reference's exact trim formula
(``[13+tau : -11+tau]``, /root/reference/src/ddr/scripts_utils.py:18-42) and are
compared against observation days ``1..D-2`` (the reference's ``obs[:, 1:-1]``).
These tests pin (a) that the two in-repo implementations agree with each other,
(b) the shape contract, and (c) that the alignment has measurable teeth: on an
autocorrelated daily signal, the aligned comparison scores median NSE ~0.98 (not
1.0 — the 13+tau=16h trim intentionally blends (1/3) of calendar day d with (2/3)
of day d+1, the reference's timezone offset), while a one-day misalignment drops
it to ~0.93 (early) / ~0.83 (late). A windowing regression would trip this gap."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ddr_tpu.scripts_utils import compute_daily_runoff
from ddr_tpu.training import daily_from_hourly
from ddr_tpu.validation.metrics import Metrics

TAU = 3


def _median_nse(pred_dg: np.ndarray, target_dg: np.ndarray) -> float:
    return float(np.nanmedian(Metrics(pred=pred_dg.T, target=target_dg.T).nse))


def _make(seed=0, n_days=40, n_gauges=5):
    rng = np.random.default_rng(seed)
    truth = np.cumsum(rng.normal(size=(n_days, n_gauges)), axis=0) + 20.0
    hourly = np.repeat(truth[: n_days - 1], 24, axis=0).astype(np.float32)  # (D-1)*24
    return truth, hourly


class TestWindowContract:
    def test_shape_is_d_minus_2_days(self):
        truth, hourly = _make()
        daily = np.asarray(daily_from_hourly(jnp.asarray(hourly), TAU))
        assert daily.shape == (truth.shape[0] - 2, truth.shape[1])

    def test_training_and_script_paths_agree(self):
        """daily_from_hourly (jit path, (T, G)) == compute_daily_runoff ((G, T))."""
        _, hourly = _make(seed=1)
        a = np.asarray(daily_from_hourly(jnp.asarray(hourly), TAU))
        b = compute_daily_runoff(hourly.T, TAU).T
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_tau_shifts_the_window(self):
        _, hourly = _make(seed=2)
        a = np.asarray(daily_from_hourly(jnp.asarray(hourly), 0))
        b = np.asarray(daily_from_hourly(jnp.asarray(hourly), 6))
        assert a.shape == b.shape
        assert not np.allclose(a, b)


class TestAlignmentHasTeeth:
    def test_aligned_days_score_highest_nse(self):
        truth, hourly = _make()
        daily = np.asarray(daily_from_hourly(jnp.asarray(hourly), TAU))
        obs = truth[1:-1]  # the reference's obs[:, 1:-1] target days
        aligned = _median_nse(daily, obs)
        early = _median_nse(daily, truth[0:-2])
        late = _median_nse(daily[:-1], truth[2:-1])
        assert aligned > 0.95, aligned
        assert aligned > early + 0.02, (aligned, early)
        assert aligned > late + 0.05, (aligned, late)

    def test_timezone_blend_coefficients(self):
        """At tau=3 the trim starts at hour 16, so daily block d is exactly
        (1/3) * calendar day d + (2/3) * day d+1 — the documented blend."""
        n_days, g = 10, 3
        truth = np.random.default_rng(3).normal(size=(n_days, g)) + 20.0
        hourly = np.repeat(truth[: n_days - 1], 24, axis=0).astype(np.float32)
        daily = np.asarray(daily_from_hourly(jnp.asarray(hourly), TAU))
        want = (1.0 / 3.0) * truth[:-2] + (2.0 / 3.0) * truth[1:-1]
        np.testing.assert_allclose(daily, want, rtol=1e-5)
