"""Throughput counter + profiler trace context tests (SURVEY.md §5: the aux
observability subsystem the reference lacks; its analog is wall-clock brackets,
/root/reference/scripts/train.py:174,196-197)."""

from __future__ import annotations

import time

import pytest

from ddr_tpu.profiling import Throughput, profile_dir_from_env, trace


class TestThroughput:
    def test_record_math(self):
        tp = Throughput()
        rate = tp.record(n_reaches=100, n_timesteps=24, seconds=2.0)
        assert rate == pytest.approx(1200.0)
        tp.record(n_reaches=100, n_timesteps=24, seconds=1.0)
        assert tp.last_rate == pytest.approx(2400.0)
        assert tp.rate == pytest.approx(4800 / 3.0)
        assert tp.batches == 2

    def test_batch_context_times_body(self):
        tp = Throughput()
        with tp.batch(n_reaches=10, n_timesteps=10):
            time.sleep(0.01)
        assert tp.batches == 1
        assert 0 < tp.last_rate < 100 / 0.009

    def test_empty_counter_is_quiet(self):
        tp = Throughput()
        assert tp.rate == 0.0
        tp.log_summary()  # no batches: no-op, no division by zero

    def test_format_mentions_unit(self):
        tp = Throughput(label="x")
        tp.record(10, 10, 1.0)
        assert "reach-timesteps/s" in tp.format()


class TestTrace:
    def test_noop_without_dir(self, monkeypatch):
        monkeypatch.delenv("DDR_PROFILE_DIR", raising=False)
        assert profile_dir_from_env() is None
        with trace():  # must not require jax or write anything
            pass

    def test_env_var_activates(self, monkeypatch, tmp_path):
        monkeypatch.setenv("DDR_PROFILE_DIR", str(tmp_path / "prof"))
        assert profile_dir_from_env() == str(tmp_path / "prof")

    @pytest.mark.slow
    def test_trace_writes_profile(self, tmp_path):
        import jax.numpy as jnp

        with trace(str(tmp_path)):
            jnp.arange(8).sum().block_until_ready()
        assert any(tmp_path.rglob("*"))  # trace artifacts written
