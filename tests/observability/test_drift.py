"""DriftTracker: parameter-field distribution snapshots + watchdog coupling.

Quantile/OOB/non-finite summaries, the reference-snapshot drift index, env
thresholds (DDR_HEALTH_MAX_PARAM_DRIFT / _MAX_PARAM_OOB), `drift` event
emission, registry gauges, and the flag() path into HealthWatchdog
degradation.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from ddr_tpu.observability.drift import DRIFT_QUANTILES, DriftTracker, drift_index
from ddr_tpu.observability.events import Recorder, activate, deactivate
from ddr_tpu.observability.health import HealthConfig, HealthWatchdog
from ddr_tpu.observability.registry import MetricsRegistry


def _fields(seed=0, n=200, lo=0.02, hi=0.2):
    rng = np.random.default_rng(seed)
    return {"n": rng.uniform(lo, hi, n)}


def _tracker(config=None, watchdog=None, registry=None):
    return DriftTracker(
        {"n": (0.01, 0.3), "q_spatial": (0.0, 1.0)},
        config=config or HealthConfig(),
        registry=registry or MetricsRegistry(),
        watchdog=watchdog,
    )


class TestSummaries:
    def test_first_observe_is_reference(self):
        tr = _tracker()
        assert tr.observe(_fields(), epoch=1) == []
        st = tr.status()
        f = st["fields"]["n"]
        assert len(f["quantiles"]) == len(DRIFT_QUANTILES)
        assert f["oob"] == 0 and f["nonfinite"] == 0
        assert "drift" not in f  # no reference existed when field 1 summarized

    def test_second_observe_reports_drift(self):
        tr = _tracker()
        tr.observe(_fields(), epoch=1)
        tr.observe(_fields(), epoch=2)  # identical distribution
        assert tr.status()["fields"]["n"]["drift"] == pytest.approx(0.0, abs=1e-9)
        tr.observe({"n": _fields()["n"] + 0.05}, epoch=3)
        d = tr.status()["fields"]["n"]["drift"]
        # shifted by ~0.05 on a ~0.18-wide reference span
        assert 0.2 < d < 0.4

    def test_oob_and_nonfinite_counts(self):
        tr = _tracker()
        vals = np.array([0.05, 0.5, -0.2, np.nan, np.inf, 0.1])
        tr.observe({"n": vals})
        f = tr.status()["fields"]["n"]
        assert f["oob"] == 2  # 0.5 and -0.2 outside [0.01, 0.3]
        assert f["nonfinite"] == 2

    def test_unknown_field_skips_oob(self):
        tr = _tracker()
        tr.observe({"mystery": np.array([1e9, -1e9])})
        assert "oob" not in tr.status()["fields"]["mystery"]

    def test_set_reference_explicit(self):
        tr = _tracker()
        tr.set_reference(_fields())
        reasons = tr.observe({"n": _fields()["n"] + 10.0})
        assert tr.status()["fields"]["n"]["drift"] > 10


class TestDriftIndex:
    def test_zero_for_identical(self):
        q = np.linspace(0.0, 1.0, 9)
        assert drift_index(q, q) == 0.0

    def test_unit_for_own_width_shift(self):
        q = np.linspace(0.0, 1.0, 9)
        assert drift_index(q + 1.0, q) == pytest.approx(1.0)

    def test_degenerate_reference_span(self):
        q = np.full(9, 2.0)
        assert np.isfinite(drift_index(q + 1.0, q))


class TestThresholdsAndWatchdog:
    def test_violations_flag_watchdog(self):
        reg = MetricsRegistry()
        cfg = HealthConfig(max_param_drift=0.1, bad_batches=2)
        wd = HealthWatchdog(cfg, registry=reg)
        tr = _tracker(config=cfg, watchdog=wd, registry=reg)
        tr.observe(_fields(), epoch=1)
        r1 = tr.observe({"n": _fields()["n"] + 1.0}, epoch=2)
        assert r1 == ["param-drift"]
        assert not wd.degraded  # bad_batches=2: one violation isn't enough
        tr.observe({"n": _fields()["n"] + 2.0}, epoch=3)
        assert wd.degraded
        assert wd.status()["last_reasons"] == ["param-drift"]

    def test_healthy_batches_do_not_clear_flagged_streak(self):
        """The contract the flag counter exists for: healthy SOLVE batches
        land between epoch-end drift checks by construction — they must not
        reset a drifting-parameters streak, and a clean drift check must."""
        import jax.numpy as jnp

        from ddr_tpu.observability.health import HealthStats

        reg = MetricsRegistry()
        cfg = HealthConfig(max_param_drift=0.1, bad_batches=2)
        wd = HealthWatchdog(cfg, registry=reg)
        tr = _tracker(config=cfg, watchdog=wd, registry=reg)
        healthy = HealthStats(
            nonfinite=jnp.asarray(0, jnp.int32), q_min=jnp.asarray(0.1),
            q_max=jnp.asarray(1.0), mass_residual=jnp.asarray(0.0),
        )
        tr.observe(_fields(), epoch=1)  # reference
        tr.observe({"n": _fields()["n"] + 1.0}, epoch=2)  # drift 1
        wd.observe(healthy)  # a healthy solve batch in epoch 3...
        wd.observe(healthy)
        tr.observe({"n": _fields()["n"] + 2.0}, epoch=3)  # drift 2
        assert wd.degraded, "healthy batches cleared the drift streak"
        # a recovered snapshot clears it
        tr.observe(_fields(), epoch=4)
        assert not wd.degraded
        assert wd.status()["consecutive_flagged"] == 0

    def test_oob_threshold(self):
        cfg = HealthConfig(max_param_oob=0)
        tr = _tracker(config=cfg)
        vals = _fields()["n"].copy()
        vals[0] = 5.0
        assert tr.observe({"n": vals}) == ["param-oob"]

    def test_nonfinite_always_violates(self):
        tr = _tracker()
        vals = _fields()["n"].copy()
        vals[0] = np.nan
        assert tr.observe({"n": vals}) == ["param-nonfinite"]

    def test_env_knobs(self):
        cfg = HealthConfig.from_env({
            "DDR_HEALTH_MAX_PARAM_DRIFT": "0.25",
            "DDR_HEALTH_MAX_PARAM_OOB": "3",
            "DDR_HEALTH_BANDS": "16",
            "DDR_HEALTH_TOPK": "4",
        })
        assert cfg.max_param_drift == 0.25
        assert cfg.max_param_oob == 3
        assert cfg.bands == 16 and cfg.top_k == 4


class TestEventsAndMetrics:
    def test_drift_event_emitted(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        try:
            tr = _tracker()
            tr.observe(_fields(), epoch=1)
            tr.observe({"n": _fields()["n"] + 0.05}, epoch=2)
        finally:
            deactivate(rec)
            rec.close()
        events = [
            json.loads(line)
            for line in (tmp_path / "log.jsonl").read_text().splitlines()
        ]
        drifts = [e for e in events if e["event"] == "drift"]
        assert len(drifts) == 2
        assert drifts[1]["epoch"] == 2
        assert drifts[1]["fields"]["n"]["drift"] is not None
        assert drifts[1]["reasons"] == []

    def test_gauges_mirrored(self):
        reg = MetricsRegistry()
        tr = _tracker(registry=reg)
        tr.observe(_fields())
        tr.observe({"n": _fields()["n"] + 0.05})
        g = reg.get("ddr_param_drift")
        assert g.value(param="n") > 0
        assert reg.get("ddr_param_oob").value(param="n") == 0

    def test_status_counters(self):
        cfg = HealthConfig(max_param_oob=0)
        tr = _tracker(config=cfg)
        tr.observe(_fields())
        vals = _fields()["n"].copy()
        vals[0] = 5.0
        tr.observe({"n": vals})
        st = tr.status()
        assert st["observations"] == 2 and st["violations"] == 1
