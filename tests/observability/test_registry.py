"""Metrics registry + Prometheus exposition tests: instrument semantics,
label/series bookkeeping, text-format rendering, the event tee, and the
background exporter on a real ephemeral port."""

from __future__ import annotations

import urllib.error
import urllib.request

import pytest

from ddr_tpu.observability.prometheus import (
    declare_serve_metrics,
    event_tee,
    render_text,
    start_exporter,
    stop_exporter,
)
from ddr_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
    set_registry,
)


@pytest.fixture(autouse=True)
def _isolated_registry():
    """Every test gets a fresh process-default registry (and no leaked
    exporter)."""
    set_registry(MetricsRegistry(const_labels={"host": 0}))
    yield get_registry()
    stop_exporter()
    set_registry(None)


class TestInstruments:
    def test_counter_inc_and_labels(self):
        r = MetricsRegistry()
        c = r.counter("ddr_things_total", "things", labels=("kind",))
        c.inc(kind="a")
        c.inc(2, kind="a")
        c.inc(kind="b")
        assert c.value(kind="a") == 3
        assert c.value(kind="b") == 1
        assert c.value(kind="never") == 0

    def test_counter_cannot_decrease(self):
        c = MetricsRegistry().counter("c_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc(self):
        g = MetricsRegistry().gauge("g")
        g.set(5)
        g.inc(-2)
        assert g.value() == 3

    def test_histogram_buckets_cumulative(self):
        h = MetricsRegistry().histogram("h", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        state = h.series()[()]
        assert state["buckets"] == [1, 1, 1]  # per-bucket raw counts incl +Inf
        assert state["count"] == 3
        assert state["sum"] == pytest.approx(2.55)

    def test_histogram_observe_on_bound_is_le(self):
        h = MetricsRegistry().histogram("h", buckets=(1.0,))
        h.observe(1.0)  # le="1.0" is inclusive (Prometheus semantics)
        assert h.series()[()]["buckets"] == [1, 0]

    def test_get_or_create_is_idempotent_and_type_checked(self):
        r = MetricsRegistry()
        c1 = r.counter("x_total", labels=("a",))
        assert r.counter("x_total", labels=("a",)) is c1
        with pytest.raises(ValueError):
            r.gauge("x_total")
        with pytest.raises(ValueError):
            r.counter("x_total", labels=("b",))

    def test_invalid_names_rejected(self):
        r = MetricsRegistry()
        with pytest.raises(ValueError):
            r.counter("bad name")
        with pytest.raises(ValueError):
            r.counter("ok_total", labels=("bad-label",))


class TestRenderText:
    def test_counter_and_gauge_lines(self):
        r = MetricsRegistry(const_labels={"host": 1})
        r.counter("ddr_a_total", "a help", labels=("k",)).inc(k="x")
        r.gauge("ddr_b").set(2.5)
        txt = render_text(r)
        assert "# HELP ddr_a_total a help" in txt
        assert "# TYPE ddr_a_total counter" in txt
        assert 'ddr_a_total{host="1",k="x"} 1' in txt
        assert "# TYPE ddr_b gauge" in txt
        assert 'ddr_b{host="1"} 2.5' in txt
        assert txt.endswith("\n")

    def test_histogram_exposition_shape(self):
        r = MetricsRegistry()
        h = r.histogram("ddr_lat_seconds", "lat", buckets=(0.01, 0.1))
        h.observe(0.05)
        h.observe(0.05)
        txt = render_text(r)
        assert 'ddr_lat_seconds_bucket{le="0.01"} 0' in txt
        assert 'ddr_lat_seconds_bucket{le="0.1"} 2' in txt
        assert 'ddr_lat_seconds_bucket{le="+Inf"} 2' in txt
        assert "ddr_lat_seconds_sum 0.1" in txt
        assert "ddr_lat_seconds_count 2" in txt

    def test_label_value_escaping(self):
        r = MetricsRegistry()
        r.counter("e_total", labels=("p",)).inc(p='a"b\\c\nd')
        txt = render_text(r)
        assert 'p="a\\"b\\\\c\\nd"' in txt

    def test_declared_but_empty_metrics_still_typed(self):
        r = declare_serve_metrics(MetricsRegistry())
        txt = render_text(r)
        # names are visible from the first scrape, before any traffic
        assert "# TYPE ddr_request_latency_seconds histogram" in txt
        assert "ddr_health_status 1" in txt  # initialized healthy
        assert "ddr_queue_depth 0" in txt


class TestEventTee:
    def test_serve_events_update_instruments(self):
        r = declare_serve_metrics(MetricsRegistry())
        event_tee({"event": "serve_request", "status": "ok", "network": "n",
                   "model": "m", "latency_s": 0.02}, r)
        event_tee({"event": "serve_request", "status": "shed:deadline",
                   "network": "n", "model": "m", "latency_s": 0.5}, r)
        event_tee({"event": "serve_batch", "network": "n", "model": "m",
                   "size": 3, "occupancy": 0.75, "seconds": 0.01,
                   "queue_depth": 7}, r)
        event_tee({"event": "serve_shed", "reason": "deadline"}, r)
        event_tee({"event": "compile", "engine": "wavefront"}, r)
        assert r.get("ddr_requests_total").value(
            status="ok", network="n", model="m") == 1
        # latency histogram only counts served requests
        assert r.get("ddr_request_latency_seconds").series()[("n", "m")]["count"] == 1
        assert r.get("ddr_queue_depth").value() == 7
        assert r.get("ddr_sheds_total").value(reason="deadline") == 1
        assert r.get("ddr_compiles_total").value(engine="wavefront") == 1
        assert r.get("ddr_events_total").value(event="serve_request") == 2

    def test_step_and_health_events(self):
        r = MetricsRegistry()
        event_tee({"event": "step", "engine": "single", "seconds": 0.2,
                   "loss": 1.5}, r)
        event_tee({"event": "health", "reasons": ["non-finite", "grad-norm"]}, r)
        assert r.get("ddr_steps_total").value(engine="single") == 1
        assert r.get("ddr_loss").value() == 1.5
        assert r.get("ddr_health_violations_total").value(reason="non-finite") == 1
        assert r.get("ddr_health_violations_total").value(reason="grad-norm") == 1

    def test_unknown_event_only_counts_generically(self):
        r = MetricsRegistry()
        event_tee({"event": "totally_new"}, r)  # must not raise
        assert r.get("ddr_events_total").value(event="totally_new") == 1

    def test_recorder_activation_installs_tee(self, tmp_path):
        from ddr_tpu.observability import Recorder, activate, deactivate

        rec = Recorder(tmp_path / "log.jsonl")
        try:
            activate(rec)
            rec.emit("step", engine="single", seconds=0.1, loss=2.0)
        finally:
            deactivate(rec)
            rec.close()
        assert get_registry().get("ddr_steps_total").value(engine="single") == 1
        # re-activation must not double-install the hook
        rec2 = Recorder(tmp_path / "log2.jsonl")
        try:
            activate(rec2)
            activate(rec2)
            rec2.emit("step", engine="single", seconds=0.1, loss=2.0)
        finally:
            deactivate(rec2)
            rec2.close()
        assert get_registry().get("ddr_steps_total").value(engine="single") == 2


class TestServeTracingExposition:
    """Exposition correctness of the request-tracing + SLO instruments:
    the tee mapping, label-value escaping through to the text format,
    histogram bucket cumulativeness, and gauge staleness after unload."""

    def _request(self, r, status="ok", **extra):
        event_tee({"event": "serve_request", "status": status, "network": "n",
                   "model": "m", "latency_s": 0.05, **extra}, r)

    def test_tee_splits_queue_and_execute(self):
        r = declare_serve_metrics(MetricsRegistry())
        self._request(r, queue_s=0.004, execute_s=0.02)
        # a shed still queued: its wait is observed, execution never happened
        self._request(r, status="shed:deadline", queue_s=0.5)
        # a queue-full rejection never queued: neither phase observed
        self._request(r, status="shed:queue-full")
        q = r.get("ddr_serve_queue_seconds").series()[("n", "m")]
        e = r.get("ddr_serve_execute_seconds").series()[("n", "m")]
        assert q["count"] == 2 and q["sum"] == pytest.approx(0.504)
        assert e["count"] == 1 and e["sum"] == pytest.approx(0.02)

    def test_slo_event_counts_alert_transitions(self):
        r = declare_serve_metrics(MetricsRegistry())
        event_tee({"event": "slo", "state": "firing", "window": "60s",
                   "burn_rate": 20.0}, r)
        event_tee({"event": "slo", "state": "resolved", "window": "60s"}, r)
        c = r.get("ddr_slo_alerts_total")
        assert c.value(state="firing") == 1
        assert c.value(state="resolved") == 1

    def test_new_instrument_label_escaping_in_exposition(self):
        """Model/network names with quotes, backslashes, and newlines must
        render escaped (a raw newline in a label value corrupts the whole
        scrape, not just one series)."""
        r = declare_serve_metrics(MetricsRegistry())
        nasty_net, nasty_model = 'basin "A"\\v1', "kan\nnightly"
        event_tee({"event": "serve_request", "status": "ok",
                   "network": nasty_net, "model": nasty_model,
                   "latency_s": 0.05, "queue_s": 0.004, "execute_s": 0.02}, r)
        txt = render_text(r)
        # label pairs render sorted by name: model before network
        esc = 'model="kan\\nnightly",network="basin \\"A\\"\\\\v1"'
        assert f"ddr_serve_queue_seconds_count{{{esc}}} 1" in txt
        assert f"ddr_serve_execute_seconds_count{{{esc}}} 1" in txt
        assert "\nkan" not in txt  # the raw newline never reaches the wire

    def test_new_histograms_buckets_cumulative_in_exposition(self):
        r = declare_serve_metrics(MetricsRegistry())
        for queue_s in (0.0004, 0.004, 0.04, 9.0):
            event_tee({"event": "serve_request", "status": "ok", "network": "n",
                       "model": "m", "latency_s": 0.05, "queue_s": queue_s,
                       "execute_s": 0.01}, r)
        txt = render_text(r)
        counts = []
        for line in txt.splitlines():
            if line.startswith("ddr_serve_queue_seconds_bucket"):
                counts.append(int(line.rsplit(" ", 1)[1]))
        assert counts, "queue histogram missing from exposition"
        assert counts == sorted(counts)  # le-buckets are CUMULATIVE
        assert counts[-1] == 4  # +Inf sees every observation
        assert counts[0] < 4  # 9s lives above the finite buckets
        assert "ddr_serve_queue_seconds_count" in txt
        assert "# TYPE ddr_serve_execute_seconds histogram" in txt
        assert "# TYPE ddr_slo_burn_rate gauge" in txt

    def test_gauge_series_removal_for_unloaded_entities(self):
        """ddr_model_version{model=...} must stop exporting after an unload —
        a stale version gauge reads as 'still serving'."""
        r = declare_serve_metrics(MetricsRegistry())
        g = r.get("ddr_model_version")
        g.set(3, model="keep")
        g.set(7, model="gone")
        assert 'model="gone"' in render_text(r)
        assert g.remove(model="gone") is True
        txt = render_text(r)
        assert 'model="gone"' not in txt
        assert 'ddr_model_version{model="keep"} 3' in txt
        assert g.remove(model="gone") is False  # idempotent no-op

    def test_slo_gauges_render_with_window_labels(self):
        r = declare_serve_metrics(MetricsRegistry())
        r.get("ddr_slo_attainment").set(0.995)
        for window, burn in (("60s", 2.5), ("300s", 0.5)):
            r.get("ddr_slo_burn_rate").set(burn, window=window)
        txt = render_text(r)
        assert "ddr_slo_attainment 0.995" in txt
        assert 'ddr_slo_burn_rate{window="60s"} 2.5' in txt
        assert 'ddr_slo_burn_rate{window="300s"} 0.5' in txt


class TestExporter:
    def test_scrape_over_http(self):
        get_registry().counter("ddr_scrape_me_total").inc()
        server = start_exporter(port=0)
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.status == 200
            assert "version=0.0.4" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert 'ddr_scrape_me_total{host="0"} 1' in body

    def test_second_start_returns_same_server(self):
        s1 = start_exporter(port=0)
        s2 = start_exporter(port=0)
        assert s1 is s2

    def test_env_start_and_malformed_port(self, monkeypatch):
        from ddr_tpu.observability.prometheus import maybe_start_exporter_from_env

        monkeypatch.delenv("DDR_PROM_PORT", raising=False)
        assert maybe_start_exporter_from_env() is None
        monkeypatch.setenv("DDR_PROM_PORT", "not-a-port")
        assert maybe_start_exporter_from_env() is None
        monkeypatch.setenv("DDR_PROM_PORT", "0")
        server = maybe_start_exporter_from_env()
        assert server is not None
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.status == 200

    def test_unknown_path_404(self):
        server = start_exporter(port=0)
        url = server.url.replace("/metrics", "/nope")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url, timeout=10)
        assert exc.value.code == 404
