"""SkillTracker: streaming per-gauge NSE/KGE/percent-bias.

Hand-computed references on tiny series (the module's formulas must match the
textbook definitions and the offline Metrics battery), streaming equivalence
(two observes == one concatenated observe), degenerate-gauge NaN contracts,
the bounded `skill` event payload, and — the cardinality-hygiene satellite —
an exposition test proving the per-gauge worst-K Prometheus series count
stays bounded (with `_Instrument.remove()` cleanup) under gauge churn.
"""

from __future__ import annotations

import numpy as np
import pytest

from ddr_tpu.observability.events import Recorder, activate, deactivate
from ddr_tpu.observability.prometheus import render_text
from ddr_tpu.observability.registry import MetricsRegistry
from ddr_tpu.observability.skill import (
    SkillConfig,
    SkillTracker,
    gauge_skill_from_sums,
)


def _tracker(top_k=3, registry=None, **kw):
    return SkillTracker(
        SkillConfig(top_k=top_k, **kw), registry=registry or MetricsRegistry()
    )


def _col(x):
    return np.asarray(x, dtype=np.float64)[:, None]


class TestHandComputed:
    def test_perfect_prediction(self):
        tr = _tracker()
        obs = _col([1.0, 2.0, 3.0, 4.0])
        tr.observe(obs, obs, ["g"])
        r = tr.results()["g"]
        assert r["nse"] == pytest.approx(1.0)
        assert r["kge"] == pytest.approx(1.0)
        assert r["pbias"] == pytest.approx(0.0)

    def test_constant_offset(self):
        # pred = obs + 1: SSE = 4, ovar = 5 -> NSE = 0.2; r = 1, alpha = 1,
        # beta = 3.5/2.5 = 1.4 -> KGE = 0.6; pbias = 100 * 4 / 10 = 40
        tr = _tracker()
        tr.observe(_col([2.0, 3.0, 4.0, 5.0]), _col([1.0, 2.0, 3.0, 4.0]), ["g"])
        r = tr.results()["g"]
        assert r["nse"] == pytest.approx(0.2)
        assert r["kge"] == pytest.approx(0.6)
        assert r["pbias"] == pytest.approx(40.0)

    def test_mean_prediction_is_zero_nse(self):
        obs = _col([1.0, 2.0, 3.0])
        tr = _tracker()
        tr.observe(np.full_like(obs, 2.0), obs, ["g"])
        assert tr.results()["g"]["nse"] == pytest.approx(0.0)

    def test_matches_offline_metrics_battery(self):
        # the validation battery computes the same NSE/KGE definitions
        from ddr_tpu.validation.metrics import Metrics

        rng = np.random.default_rng(0)
        obs = rng.uniform(0.5, 3.0, (20, 4))
        pred = obs + rng.normal(scale=0.3, size=obs.shape)
        m = Metrics(pred=pred.T, target=obs.T)
        tr = _tracker()
        tr.observe(pred, obs, [f"g{i}" for i in range(4)])
        res = tr.results()
        for i in range(4):
            assert res[f"g{i}"]["nse"] == pytest.approx(float(m.nse[i]), rel=1e-9)
            assert res[f"g{i}"]["kge"] == pytest.approx(float(m.kge[i]), rel=1e-9)
            assert res[f"g{i}"]["pbias"] == pytest.approx(float(m.pbias[i]), rel=1e-9)


class TestStreaming:
    def test_two_observes_equal_one(self):
        rng = np.random.default_rng(1)
        obs = rng.uniform(0.5, 3.0, (12, 3))
        pred = obs + rng.normal(scale=0.2, size=obs.shape)
        ids = ["a", "b", "c"]
        one = _tracker()
        one.observe(pred, obs, ids)
        two = _tracker()
        two.observe(pred[:5], obs[:5], ids)
        two.observe(pred[5:], obs[5:], ids)
        for g in ids:
            assert two.results()[g]["nse"] == pytest.approx(
                one.results()[g]["nse"], rel=1e-12
            )

    def test_nan_masking(self):
        obs = _col([1.0, np.nan, 3.0, 4.0, 5.0])
        pred = _col([1.5, 2.0, np.nan, 4.5, 5.5])
        tr = _tracker()
        tr.observe(pred, obs, ["g"])
        # only rows 0, 3, 4 are valid pairs
        assert tr.results()["g"]["n"] == 3

    def test_new_gauges_join_midstream(self):
        tr = _tracker()
        tr.observe(_col([1.0, 2.0, 3.0]), _col([1.0, 2.0, 3.0]), ["a"])
        pred = np.column_stack([[1.0, 2.0, 3.0], [9.0, 9.0, 9.0]])
        obs = np.column_stack([[1.0, 2.0, 3.0], [1.0, 2.0, 3.0]])
        tr.observe(pred, obs, ["a", "b"])
        res = tr.results()
        assert res["a"]["nse"] == pytest.approx(1.0)
        assert res["b"]["n"] == 3


class TestDegenerate:
    def test_too_few_samples_is_nan(self):
        tr = _tracker()
        tr.observe(_col([1.0]), _col([1.0]), ["g"])
        assert tr.results()["g"]["nse"] is None

    def test_constant_obs_nse_nan(self):
        tr = _tracker()
        tr.observe(_col([1.0, 2.0, 3.0]), _col([2.0, 2.0, 2.0]), ["g"])
        r = tr.results()["g"]
        assert r["nse"] is None  # ovar == 0
        assert r["pbias"] is not None

    def test_disabled_is_noop(self):
        tr = SkillTracker(
            SkillConfig(enabled=False), registry=MetricsRegistry()
        )
        assert tr.observe(_col([1.0, 2.0]), _col([1.0, 2.0]), ["g"]) is None
        assert tr.status()["observations"] == 0


class TestEventsAndSummary:
    def test_skill_event_payload_bounded(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        try:
            tr = _tracker(top_k=2)
            rng = np.random.default_rng(2)
            obs = rng.uniform(0.5, 3.0, (10, 30))
            pred = obs + rng.normal(scale=0.5, size=obs.shape)
            summary = tr.observe(
                pred, obs, [f"g{i}" for i in range(30)], epoch=1, batch=0
            )
        finally:
            deactivate(rec)
            rec.close()
        assert summary["gauges"] == 30
        assert len(summary["worst"]) <= 2  # bounded worst set, never 30
        assert summary["nse"]["median"] is not None
        import json

        events = [
            json.loads(line)
            for line in (tmp_path / "log.jsonl").read_text().splitlines()
        ]
        skill = [e for e in events if e["event"] == "skill"]
        assert len(skill) == 1
        assert skill[0]["epoch"] == 1
        assert "worst" in skill[0] and len(skill[0]["worst"]) <= 2
        # the full per-gauge vector never rides the event
        assert "nse_values" not in skill[0]

    def test_worst_ordering(self):
        tr = _tracker(top_k=2)
        obs = np.tile(_col([1.0, 2.0, 3.0, 4.0]), (1, 3))
        pred = obs.copy()
        pred[:, 1] += 5.0  # bad
        pred[:, 2] += 1.0  # mediocre
        s = tr.observe(pred, obs, ["good", "bad", "mid"])
        assert [w["gauge"] for w in s["worst"]] == ["bad", "mid"]

    def test_status_rollup(self):
        tr = _tracker()
        tr.observe(_col([1.0, 2.0, 3.0]), _col([1.0, 2.0, 3.0]), ["g"])
        st = tr.status()
        assert st["observations"] == 1 and st["gauges"] == 1
        assert st["nse"]["median"] == pytest.approx(1.0)


class TestCardinalityHygiene:
    def test_worst_series_bounded_under_churn(self):
        """The satellite contract: per-gauge Prometheus series are capped at
        worst-K; a gauge leaving the worst set has its series REMOVED."""
        reg = MetricsRegistry()
        tr = _tracker(top_k=2, registry=reg)
        obs = _col([1.0, 2.0, 3.0, 4.0])
        rng = np.random.default_rng(3)
        # 20 rounds, each making a DIFFERENT pair of gauges the worst
        for round_ in range(20):
            bad_a, bad_b = f"g{round_}", f"g{round_ + 100}"
            pred = np.column_stack([
                obs[:, 0] + 10.0 + round_,  # fresh worst gauge
                obs[:, 0] + 5.0,
                obs[:, 0],
            ])
            o3 = np.tile(obs, (1, 3))
            tr.observe(pred, o3, [bad_a, bad_b, f"ok{round_}"])
        metric = reg.get("ddr_skill_worst_nse")
        assert len(metric.series()) <= 2, "worst-K series cap violated"
        text = render_text(reg)
        worst_lines = [
            line for line in text.splitlines()
            if line.startswith("ddr_skill_worst_nse{")
        ]
        assert len(worst_lines) <= 2
        # distributions still flow into the bounded-bucket histograms
        assert "ddr_skill_nse_bucket" in text

    def test_histograms_have_fixed_buckets(self):
        reg = MetricsRegistry()
        tr = _tracker(registry=reg)
        tr.observe(_col([1.0, 2.0, 3.0]), _col([1.0, 2.0, 3.0]), ["g"])
        hist = reg.get("ddr_skill_nse")
        from ddr_tpu.observability.skill import SKILL_BUCKETS

        assert hist.buckets == tuple(sorted(SKILL_BUCKETS))


class TestConfig:
    def test_from_env(self):
        cfg = SkillConfig.from_env(
            {"DDR_SKILL_TOPK": "4", "DDR_SKILL_MIN_SAMPLES": "3",
             "DDR_SKILL_ENABLED": "1"}
        )
        assert cfg.top_k == 4 and cfg.min_samples == 3 and cfg.enabled

    def test_env_disable(self):
        assert not SkillConfig.from_env({"DDR_SKILL_ENABLED": "off"}).enabled

    def test_bad_values_raise(self):
        with pytest.raises(ValueError):
            SkillConfig(top_k=-1)
        with pytest.raises(ValueError):
            SkillConfig(min_samples=1)
        with pytest.raises(ValueError):
            SkillConfig.from_env({"DDR_SKILL_TOPK": "lots"})

    def test_shape_mismatch_raises(self):
        tr = _tracker()
        with pytest.raises(ValueError):
            tr.observe(np.zeros((3, 2)), np.zeros((3, 2)), ["only-one"])


class TestSums:
    def test_gauge_skill_from_sums_direct(self):
        # sums for pred=[2,3,4,5] vs obs=[1,2,3,4]
        sums = np.array([[4.0, 14.0, 10.0, 54.0, 30.0, 40.0, 4.0]])
        out = gauge_skill_from_sums(sums)
        assert out["nse"][0] == pytest.approx(0.2)
        assert out["kge"][0] == pytest.approx(0.6)
        assert out["pbias"][0] == pytest.approx(40.0)


class TestMerge:
    def test_merge_equals_combined_stream(self):
        """merge(other) is lossless: the folded tracker's per-gauge results
        equal one tracker that saw both streams, including partially
        overlapping gauge sets."""
        rng = np.random.default_rng(5)
        a, b, both = _tracker(), _tracker(), _tracker()
        for tr_part, gauges in ((a, ["g0", "g1"]), (b, ["g1", "g2"])):
            pred = rng.gamma(2.0, 1.0, size=(6, 2))
            obs = rng.gamma(2.0, 1.0, size=(6, 2))
            tr_part.observe(pred, obs, gauges)
            both.observe(pred, obs, gauges)
        a.merge(b)
        ra, rb = a.results(), both.results()
        assert set(ra) == set(rb) == {"g0", "g1", "g2"}
        for g in rb:
            for k in ("nse", "kge", "pbias"):
                assert ra[g][k] == pytest.approx(rb[g][k], abs=1e-12)
        assert a.status()["samples"] == both.status()["samples"]

    def test_merge_self_raises(self):
        tr = _tracker()
        with pytest.raises(ValueError, match="itself"):
            tr.merge(tr)

    def test_merge_empty_other_is_noop(self):
        tr = _tracker()
        tr.observe(_col([1.0, 2.0]), _col([1.0, 2.0]), ["g"])
        before = tr.results()["g"]["nse"]
        tr.merge(_tracker())
        assert tr.results()["g"]["nse"] == before
