"""`ddr metrics summarize`: the Skill and Spatial-health sections, and the
serving `/v1/stats` worst-gauge (spatial) slice."""

from __future__ import annotations

import json

import numpy as np
import pytest

from ddr_tpu.observability.metrics_cli import main


def _write(path, events):
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    return path


def _base(seq=0):
    return {"t": float(seq), "wall": 100.0 + seq, "host": 0, "pid": 1, "seq": seq}


class TestSkillSection:
    def test_renders_last_skill_event(self, tmp_path, capsys):
        events = [
            {"event": "run_start", "cmd": "train", **_base(0)},
            {"event": "skill", **_base(1), "gauges": 12, "scored": 10,
             "nse": {"median": 0.1, "p10": -1.0, "p90": 0.5,
                     "frac_positive": 0.5},
             "kge": {"median": 0.2, "p10": -0.5},
             "pbias": {"median_abs": 30.0, "p90_abs": 80.0},
             "worst": [{"gauge": "early", "nse": -2.0, "kge": -1.0,
                        "pbias": 90.0}]},
            {"event": "skill", **_base(2), "gauges": 12, "scored": 11,
             "nse": {"median": 0.62, "p10": -0.1, "p90": 0.9,
                     "frac_positive": 0.8},
             "kge": {"median": 0.55, "p10": 0.0},
             "pbias": {"median_abs": 11.0, "p90_abs": 35.0},
             "worst": [{"gauge": "06191500", "nse": -0.31, "kge": 0.05,
                        "pbias": 44.0}]},
        ]
        p = _write(tmp_path / "run_log.train.jsonl", events)
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "skill    : 11/12 gauges scored" in out
        assert "NSE median 0.620" in out
        assert "worst gauges (by NSE):" in out
        assert "06191500" in out
        assert "early" not in out  # cumulative stream: last event wins

    def test_no_section_without_skill(self, tmp_path, capsys):
        p = _write(tmp_path / "run_log.train.jsonl",
                   [{"event": "run_start", "cmd": "train", **_base(0)}])
        assert main(["summarize", str(p)]) == 0
        assert "skill    :" not in capsys.readouterr().out


class TestSpatialSection:
    def test_worst_bands_and_drift_render(self, tmp_path, capsys):
        events = [
            {"event": "run_start", "cmd": "train", **_base(0)},
            {"event": "health", **_base(1), "reasons": ["non-finite"],
             "nonfinite": 4, "q_min": 0.0, "q_max": 9.0, "mass_residual": 1.0,
             "consecutive": 1, "worst_band": 2,
             "band_nonfinite": [0, 0, 4, 0],
             "band_residual": [0.1, 0.2, 7.5, 0.3],
             "band_q_max": [1.0, 2.0, 9.0, 3.0],
             "worst_idx": [17, 4]},
            {"event": "drift", **_base(2), "epoch": 1, "reasons": [],
             "fields": {"n": {"quantiles": [0.02, 0.1, 0.2], "drift": 0.04,
                              "oob": 1, "nonfinite": 0, "n": 64}}},
        ]
        p = _write(tmp_path / "run_log.train.jsonl", events)
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "spatial  : 1 violating batches carried band attribution" in out
        assert "worst bands" in out and "band2" in out
        assert "worst reaches: 17 (x1)" in out
        assert "drift    : 1 snapshots (0 violating)" in out
        assert "n drift 0.0400 oob 1" in out

    def test_plain_health_events_skip_spatial(self, tmp_path, capsys):
        events = [
            {"event": "run_start", "cmd": "train", **_base(0)},
            {"event": "health", **_base(1), "reasons": ["non-finite"],
             "nonfinite": 1, "q_min": 0.0, "q_max": 2.0,
             "mass_residual": 0.1, "consecutive": 1},
        ]
        p = _write(tmp_path / "run_log.train.jsonl", events)
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "health   : 1 violating batches" in out
        assert "spatial  :" not in out


class TestWatchdogSpatialSlice:
    def test_observe_remembers_spatial_even_when_healthy(self):
        import jax.numpy as jnp

        from ddr_tpu.observability.health import (
            HealthConfig,
            HealthStats,
            HealthWatchdog,
        )
        from ddr_tpu.observability.registry import MetricsRegistry

        wd = HealthWatchdog(HealthConfig(), registry=MetricsRegistry())
        stats = HealthStats(
            nonfinite=jnp.asarray(0, jnp.int32),
            q_min=jnp.asarray(0.1),
            q_max=jnp.asarray(2.0),
            mass_residual=jnp.asarray(0.5),
            band_nonfinite=jnp.asarray([0, 0], jnp.int32),
            band_q_min=jnp.asarray([0.1, 0.2]),
            band_q_max=jnp.asarray([2.0, 1.0]),
            band_residual=jnp.asarray([0.5, 3.0]),
            worst_idx=jnp.asarray([7, 3], jnp.int32),
            worst_score=jnp.asarray([2.0, 1.0]),
        )
        assert wd.observe(stats) == []  # healthy
        spatial = wd.status()["spatial"]
        assert spatial["worst_band"] == 1  # largest |residual|
        assert spatial["worst_idx"] == [7, 3]

    def test_flag_feeds_counters(self):
        from ddr_tpu.observability.health import HealthConfig, HealthWatchdog
        from ddr_tpu.observability.registry import MetricsRegistry

        wd = HealthWatchdog(HealthConfig(bad_batches=1), registry=MetricsRegistry())
        assert wd.flag(["param-drift"], epoch=3) == ["param-drift"]
        assert wd.degraded
        assert wd.status()["violations"] == 1
        # flag with nothing is a no-op
        assert wd.flag([]) == []


class TestTrainLoopWiring:
    def test_train_emits_skill_drift_and_band_health(self, tmp_path, monkeypatch):
        """e2e: a tiny synthetic single-device train run streams `skill`
        events per batch, one `drift` event per epoch, carries the band
        knobs into its ONE compiled step (no recompiles on the repeat
        epoch), and rolls everything up in run_end."""
        from ddr_tpu.observability import run_telemetry
        from ddr_tpu.scripts.train import train
        from ddr_tpu.validation.configs import Config

        monkeypatch.setenv("DDR_HEALTH_BANDS", "4")
        monkeypatch.setenv("DDR_HEALTH_TOPK", "3")
        monkeypatch.delenv("DDR_METRICS_DIR", raising=False)
        cfg = Config(
            name="spatial_e2e",
            geodataset="synthetic",
            mode="training",
            kan={"input_var_names": [f"a{i}" for i in range(10)]},
            experiment={
                "start_time": "1981/10/01",
                "end_time": "1981/10/10",
                "rho": 4,
                "batch_size": 2,
                "epochs": 2,
                "warmup": 1,
                "learning_rate": {1: 0.01},
                "shuffle": False,
            },
            params={"save_path": str(tmp_path)},
        )
        with run_telemetry(cfg, "train"):
            train(cfg, max_batches=4)
        events = [
            json.loads(line)
            for line in (tmp_path / "run_log.train.jsonl").read_text().splitlines()
        ]
        by_type: dict[str, list] = {}
        for e in events:
            by_type.setdefault(e["event"], []).append(e)
        skill = by_type.get("skill", [])
        assert len(skill) == 4  # one per batch
        assert skill[-1]["gauges"] >= 1
        assert skill[-1]["nse"]["median"] is not None
        drifts = by_type.get("drift", [])
        # one per COMPLETED epoch (max_batches cuts epoch 2 short mid-loop)
        assert len(drifts) == 1
        assert drifts[0]["epoch"] == 1
        assert set(drifts[0]["fields"]) >= {"n", "q_spatial"}
        end = by_type["run_end"][-1]
        assert end["status"] == "ok"
        assert end["summary"]["skill"]["observations"] == 4
        assert end["summary"]["drift"]["observations"] == 1
        # band health rode the one compiled step: same program count as the
        # aggregate-health baseline (epoch 2 repeats epoch 1's topologies)
        assert end["summary"]["compile"]["single"]["misses"] <= 2
