"""Fault-injection layer: the DDR_FAULTS grammar, deterministic matching,
the three actions, telemetry, and the zero-cost-when-off contract."""

from __future__ import annotations

import json
import time

import pytest

from ddr_tpu.observability import faults
from ddr_tpu.observability.events import Recorder, activate, deactivate
from ddr_tpu.observability.faults import (
    FaultPlan,
    InjectedFault,
    parse_faults,
)


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process plan empty (other suites must never see
    a leaked fault plan)."""
    yield
    faults.configure(None)


class TestGrammar:
    def test_issue_example_parses(self):
        acts = parse_faults(
            "crash@step=37;slow@data.load:p=0.1,ms=500;corrupt@checkpoint.write:n=1"
        )
        assert [a.describe() for a in acts] == [
            {"action": "crash", "site": "device.step", "at": 37},
            {"action": "slow", "site": "data.load", "p": 0.1, "ms": 500.0},
            {"action": "corrupt", "site": "checkpoint.write", "n": 1},
        ]

    def test_site_suffix_aliases(self):
        for token, site in (
            ("step", "device.step"),
            ("write", "checkpoint.write"),
            ("load", "data.load"),
            ("execute", "serve.execute"),
            ("reload", "registry.reload"),
            ("device.step", "device.step"),
        ):
            (a,) = parse_faults(f"crash@{token}")
            assert a.site == site

    def test_empty_clauses_and_whitespace(self):
        acts = parse_faults(" crash@step=1 ; ; slow@load:ms=5 ;")
        assert len(acts) == 2

    @pytest.mark.parametrize(
        "spec",
        [
            "crash@nowhere",  # unknown site
            "explode@step",  # unknown action
            "crash@step:bogus=1",  # unknown parameter
            "crashstep",  # no @
            "crash@step:p",  # parameter without =
            "crash@step:p=2.0",  # probability out of range
            "corrupt@device.step",  # no byte payload at that site to flip
            "corrupt@serve.execute",
        ],
    )
    def test_typos_raise(self, spec):
        with pytest.raises(ValueError):
            parse_faults(spec)

    def test_probability_seed_is_stable_across_processes(self):
        """The p= firing pattern must replay identically in a fresh
        interpreter (digest-seeded RNG, not PYTHONHASHSEED-salted tuples)."""
        import subprocess
        import sys
        from pathlib import Path

        code = (
            "from ddr_tpu.observability.faults import FaultPlan, parse_faults\n"
            "plan = FaultPlan(parse_faults('corrupt@checkpoint.write:p=0.5', seed=7))\n"
            "p = plan.point('checkpoint.write')\n"
            "data = b'z' * 100\n"
            "print(''.join('1' if p(data=data) != data else '0' for _ in range(24)))\n"
        )
        runs = {
            subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=120,
                env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
                     "PYTHONPATH": str(Path(__file__).resolve().parents[2]),
                     "PYTHONHASHSEED": str(h)},
            ).stdout.strip()
            for h in (0, 1)
        }
        assert len(runs) == 1 and runs.pop()

    def test_at_param_equals_shorthand(self):
        (a,) = parse_faults("crash@device.step:at=3")
        (b,) = parse_faults("crash@step=3")
        assert a.at == b.at == 3


class TestMatching:
    def test_at_matches_ctx_step(self):
        plan = FaultPlan(parse_faults("crash@step=2"))
        p = plan.point("device.step")
        p(step=0)
        p(step=1)
        with pytest.raises(InjectedFault) as e:
            p(step=2)
        assert e.value.site == "device.step"
        p(step=3)  # only the exact step fires

    def test_at_falls_back_to_invocation_counter(self):
        plan = FaultPlan(parse_faults("crash@checkpoint.write:at=1"))
        p = plan.point("checkpoint.write")
        p()  # invocation 0
        with pytest.raises(InjectedFault):
            p()  # invocation 1

    def test_n_limits_firings(self):
        plan = FaultPlan(parse_faults("corrupt@checkpoint.write:n=1"))
        p = plan.point("checkpoint.write")
        data = b"a" * 200
        assert p(data=data) != data
        assert p(data=data) == data  # budget spent

    def test_probability_is_seeded_and_deterministic(self):
        def firing_pattern(seed):
            plan = FaultPlan(parse_faults("corrupt@checkpoint.write:p=0.5", seed=seed))
            p = plan.point("checkpoint.write")
            data = b"z" * 100
            return [p(data=data) != data for _ in range(32)]

        a, b = firing_pattern(7), firing_pattern(7)
        assert a == b  # same seed -> same faults
        assert any(a) and not all(a)  # p=0.5 actually mixes
        assert firing_pattern(8) != a  # another seed -> another pattern

    def test_unarmed_site_resolves_to_none(self):
        plan = FaultPlan(parse_faults("crash@step=0"))
        assert plan.point("serve.execute") is None
        assert plan.point("device.step") is not None
        with pytest.raises(ValueError):
            plan.point("not.a.site")


class TestActions:
    def test_slow_sleeps(self):
        plan = FaultPlan(parse_faults("slow@data.load:ms=60"))
        p = plan.point("data.load")
        t0 = time.perf_counter()
        p()
        assert time.perf_counter() - t0 >= 0.05

    def test_corrupt_flips_bits_same_length(self):
        plan = FaultPlan(parse_faults("corrupt@checkpoint.write"))
        mutated = plan.point("checkpoint.write")(data=b"\x00" * 500)
        assert len(mutated) == 500
        assert mutated != b"\x00" * 500

    def test_corrupt_without_payload_is_noop(self):
        plan = FaultPlan(parse_faults("corrupt@checkpoint.write"))
        assert plan.point("checkpoint.write")() is None

    def test_crash_evaluated_after_slow(self):
        plan = FaultPlan(parse_faults("slow@step:ms=30;crash@step"))
        p = plan.point("device.step")
        t0 = time.perf_counter()
        with pytest.raises(InjectedFault):
            p()
        assert time.perf_counter() - t0 >= 0.02  # the delay still happened


class TestNanAction:
    """The ``nan`` action: parse-time site strictness, poisoned-copy
    semantics, and the wants_array fast-path contract."""

    @pytest.mark.parametrize("site", faults.NAN_SITES)
    def test_parses_at_every_nan_site(self, site):
        (a,) = parse_faults(f"nan@{site}:n=1")
        assert a.action == "nan" and a.site == site

    @pytest.mark.parametrize(
        "spec",
        [
            "nan@checkpoint.write",  # byte payload, not a float ndarray
            "nan@data.load",  # no payload at all
            "nan@serve.execute",
            "nan@registry.reload",
        ],
    )
    def test_nan_outside_array_sites_raises(self, spec):
        """A nan clause at a payload-free site would fire, log — and change
        nothing: exactly the silently-inert plan parse-time strictness
        exists to prevent."""
        with pytest.raises(ValueError):
            parse_faults(spec)

    def test_poisons_a_copy_never_in_place(self):
        np = pytest.importorskip("numpy")
        plan = FaultPlan(parse_faults("nan@device.step=0:n=1"))
        p = plan.point("device.step")
        q0 = np.ones((4, 5), dtype=np.float32)
        q1 = p(q0, step=0)
        assert q1 is not q0
        assert np.all(np.isfinite(q0))  # caller's array untouched
        assert np.sum(~np.isfinite(q1)) > 0

    def test_non_contiguous_input_still_poisoned(self):
        """Regression guard for the copy-then-flat poisoning: a strided view
        (a transposed forcing tile) must come back poisoned too."""
        np = pytest.importorskip("numpy")
        plan = FaultPlan(parse_faults("nan@data.forcings:n=1"))
        q = np.arange(24, dtype=np.float32).reshape(4, 6).T
        out = plan.point("data.forcings")(q)
        assert out.shape == q.shape
        assert np.sum(~np.isfinite(out)) > 0

    def test_wants_array_only_for_nan_clauses(self):
        """Call sites materialize a host copy only when a nan clause is
        armed — a crash/slow plan must keep the hot path payload-free."""
        nan_point = FaultPlan(parse_faults("nan@device.step")).point("device.step")
        crash_point = FaultPlan(parse_faults("crash@device.step=99")).point(
            "device.step"
        )
        assert nan_point.wants_array is True
        assert crash_point.wants_array is False

    def test_unmatched_step_returns_input_unchanged(self):
        """Identity is the armed-but-not-firing signal (`q1 is q0`): the
        train loop uses it to skip re-device-putting the payload."""
        np = pytest.importorskip("numpy")
        plan = FaultPlan(parse_faults("nan@device.step=7:n=1"))
        p = plan.point("device.step")
        q0 = np.ones(3, dtype=np.float32)
        assert p(q0, step=3) is q0


class TestProcessPlan:
    def test_configure_and_fault_site(self):
        faults.configure("crash@serve.execute:n=1")
        p = faults.fault_site("serve.execute")
        assert p is not None
        with pytest.raises(InjectedFault):
            p()
        assert faults.fault_site("device.step") is None
        faults.configure(None)
        assert faults.fault_site("serve.execute") is None

    def test_active_plan_reads_env_once(self, monkeypatch):
        monkeypatch.setenv("DDR_FAULTS", "crash@step=0")
        monkeypatch.setenv("DDR_FAULTS_SEED", "3")
        faults._PLAN = None  # force a re-read of the environment
        try:
            assert faults.fault_site("device.step") is not None
            monkeypatch.setenv("DDR_FAULTS", "")
            # cached: the plan does not flip mid-process
            assert faults.fault_site("device.step") is not None
        finally:
            faults.configure(None)

    def test_maybe_inject_passthrough_when_unarmed(self):
        faults.configure(None)
        assert faults.maybe_inject("checkpoint.write", data=b"abc") == b"abc"

    def test_firing_emits_fault_event(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        try:
            faults.configure("corrupt@checkpoint.write:n=1")
            faults.maybe_inject("checkpoint.write", data=b"x" * 64, path="ckpt.pkl")
        finally:
            deactivate(rec)
            rec.close()
        events = [
            json.loads(line) for line in (tmp_path / "log.jsonl").read_text().splitlines()
        ]
        fault_events = [e for e in events if e["event"] == "fault"]
        assert len(fault_events) == 1
        (ev,) = fault_events
        assert ev["action"] == "corrupt"
        assert ev["site"] == "checkpoint.write"
        assert ev["path"] == "ckpt.pkl"
