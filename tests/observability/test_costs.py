"""ProgramCard / collective_counts tests: CPU-built cards for the route, full
VJP, and train-step programs (non-zero FLOPs, non-null peak memory, zero
collectives on one device, JSON round-trip), the collective-instruction
counter against a genuinely sharded program (the multichip dryrun's GSPMD
route probe, in miniature), and the CompileTracker card wiring."""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.geodatazoo.synthetic import make_basin, observe
from ddr_tpu.observability import CompileTracker, Recorder, activate, deactivate
from ddr_tpu.observability.costs import (
    COLLECTIVE_OPS,
    ProgramCard,
    build_card,
    card_from_compiled,
    cards_enabled,
    collective_counts,
)
from ddr_tpu.validation.configs import Config


def _read(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


@pytest.fixture()
def rec(tmp_path):
    r = Recorder(tmp_path / "log.jsonl")
    activate(r)
    yield r
    deactivate(r)
    r.close()


def _problem(n=64, n_days=3):
    cfg = Config(
        name="costs_test",
        geodataset="synthetic",
        mode="training",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={"start_time": "1981/10/01", "end_time": "1981/10/08",
                    "rho": n_days, "warmup": 1},
        params={"save_path": "/tmp"},
    )
    basin = observe(make_basin(n_segments=n, n_gauges=4, n_days=n_days, seed=0), cfg)
    return cfg, basin


class TestCollectiveCounts:
    def test_counts_instructions_not_value_names(self):
        # %all-reduce.3 is a value NAME; only the opcode position counts
        hlo = (
            "%all-reduce.3 = f32[4]{0} all-reduce(f32[4]{0} %p), to_apply=%add\n"
            "%x = f32[4]{0} add(%all-reduce.3, %all-reduce.3)\n"
        )
        counts = collective_counts(hlo)
        assert counts["all-reduce"] == 1
        assert sum(counts.values()) == 1

    def test_async_pair_counts_once(self):
        hlo = (
            "%ag = (f32[2], f32[4]) all-gather-start(f32[2] %p), dimensions={0}\n"
            "%done = f32[4] all-gather-done((f32[2], f32[4]) %ag)\n"
        )
        assert collective_counts(hlo)["all-gather"] == 1

    def test_every_probed_op_reported(self):
        counts = collective_counts("no collectives here")
        assert set(counts) == set(COLLECTIVE_OPS)
        assert all(v == 0 for v in counts.values())

    def test_sharded_program_counts_collectives(self):
        """The dryrun expectation in miniature: a cross-device reduction under
        a mesh must show at least one all-reduce in the compiled HLO, and the
        helper must accept the Compiled handle directly."""
        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices (host mesh)")
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()), ("x",))
        fn = jax.jit(lambda a: (a * 2).sum(), out_shardings=NamedSharding(mesh, P()))
        a = jax.device_put(jnp.ones((8, 16)), NamedSharding(mesh, P("x")))
        compiled = fn.lower(a).compile()
        counts = collective_counts(compiled)
        assert counts["all-reduce"] >= 1
        # and the card carries the same mix
        card = card_from_compiled(compiled, name="sharded-sum")
        assert card.collectives == counts


class TestProgramCard:
    def test_route_vjp_train_step_cards(self):
        """CPU cards for the three production programs: non-zero FLOPs,
        non-null peak memory, stable zero collectives on one device."""
        from ddr_tpu.routing.mc import Bounds, route
        from ddr_tpu.routing.model import prepare_batch
        from ddr_tpu.scripts.common import build_kan
        from ddr_tpu.training import make_batch_train_step, make_optimizer

        cfg, basin = _problem()
        rd = basin.routing_data
        p = cfg.params
        bounds = Bounds.from_config(p.attribute_minimums)
        network, channels, gauges = prepare_batch(rd, p.attribute_minimums["slope"])
        params = {k: jnp.asarray(v, jnp.float32) for k, v in basin.true_params.items()}
        q_prime = jnp.asarray(basin.q_prime)

        fwd = jax.jit(
            lambda sp, qp: route(network, channels, sp, qp, gauges=gauges,
                                 bounds=bounds).runoff
        )
        vjp = jax.jit(jax.value_and_grad(
            lambda sp: route(network, channels, sp, q_prime, gauges=gauges,
                             bounds=bounds).runoff.mean()
        ))
        kan_model, kan_params = build_kan(cfg)
        optimizer = make_optimizer(1e-3)
        step = make_batch_train_step(
            kan_model, bounds, p.parameter_ranges, p.log_space_parameters,
            p.defaults, tau=p.tau, warmup=1, optimizer=optimizer,
        )
        attrs = jnp.asarray(rd.normalized_spatial_attributes)
        obs = jnp.asarray(basin.obs_daily)
        mask = jnp.ones_like(obs, dtype=bool)

        cards = {}
        cards["route"], compiled = build_card(fwd, params, q_prime, name="forward-route")
        # the returned executable runs (the one the compile paid for)
        out = compiled(params, q_prime)
        assert np.isfinite(np.asarray(out)).all()
        cards["vjp"], _ = build_card(vjp, params, name="full-vjp")
        cards["step"], _ = build_card(
            step, kan_params, optimizer.init(kan_params), network, channels,
            gauges, attrs, q_prime, obs, mask, name="train-step",
        )
        for name, card in cards.items():
            assert card.flops and card.flops > 0, name
            assert card.peak_bytes is not None and card.peak_bytes > 0, name
            assert set(card.collectives) == set(COLLECTIVE_OPS), name
            assert card.n_collectives == 0, name  # one device: no collectives
            assert card.input_specs, name
            assert card.compile_seconds is not None, name
        # VJP moves at least as many bytes as the forward route
        assert cards["vjp"].bytes_accessed >= cards["route"].bytes_accessed
        # the train step donates params/opt_state; the route donates nothing
        assert any(cards["step"].donated)
        assert not any(cards["route"].donated)
        assert cards["route"].arithmetic_intensity > 0

    def test_json_round_trip(self):
        card = ProgramCard(
            name="x", engine="single", platform="cpu", flops=12.0,
            bytes_accessed=48.0, peak_bytes=1024,
            collectives={"all-reduce": 2}, input_specs=("f32[4]",),
            donated=(True,), compile_seconds=0.5,
        )
        rt = ProgramCard.from_dict(json.loads(json.dumps(card.to_dict())))
        assert rt == card
        # derived fields survive in the dict form (events are grep-able)
        d = card.to_dict()
        assert d["arithmetic_intensity"] == pytest.approx(0.25)
        assert d["n_collectives"] == 2

    def test_from_dict_ignores_unknown_keys(self):
        assert ProgramCard.from_dict({"name": "y", "bogus": 1}).name == "y"

    def test_brief_is_compact(self):
        card = ProgramCard(name="x", flops=10.0, bytes_accessed=5.0)
        brief = card.brief()
        assert brief["arithmetic_intensity"] == 2.0
        assert "input_specs" not in brief


class TestCardsEnabled:
    def test_default_on_and_opt_out(self, monkeypatch):
        monkeypatch.delenv("DDR_PROGRAM_CARDS", raising=False)
        assert cards_enabled()
        monkeypatch.setenv("DDR_PROGRAM_CARDS", "0")
        assert not cards_enabled()
        monkeypatch.setenv("DDR_PROGRAM_CARDS", "false")
        assert not cards_enabled()
        monkeypatch.setenv("DDR_PROGRAM_CARDS", "1")
        assert cards_enabled()


class TestTrackerWiring:
    def test_miss_with_card_emits_program_card(self, rec):
        t = CompileTracker()
        card = ProgramCard(name="train-step", engine="gspmd", flops=7.0)
        t.miss("gspmd", key="abc123", seconds=0.5, card=card)
        events = _read(rec.path)
        kinds = [e["event"] for e in events]
        assert kinds == ["compile", "program_card"]
        pc = events[1]
        assert pc["key"] == "abc123"
        assert pc["name"] == "train-step"
        assert pc["flops"] == 7.0

    def test_track_jit_invokes_builder_only_on_miss(self, rec):
        class _Fake:
            size = 0

            def _cache_size(self):
                return self.size

        calls = []

        def builder():
            calls.append(1)
            return ProgramCard(name="p", engine="single")

        fn = _Fake()
        t = CompileTracker()
        fn.size = 1
        t.track_jit("single", fn, key="k1", card_builder=builder)  # miss
        t.track_jit("single", fn, key="k1", card_builder=builder)  # hit
        assert len(calls) == 1
        assert [e["event"] for e in _read(rec.path)] == ["compile", "program_card"]

    def test_track_jit_respects_opt_out(self, rec, monkeypatch):
        monkeypatch.setenv("DDR_PROGRAM_CARDS", "0")

        class _Fake:
            def _cache_size(self):
                return 1

        t = CompileTracker()
        t.track_jit("single", _Fake(), key="k",
                     card_builder=lambda: ProgramCard(name="p"))
        # the compile event still fires; the card build is skipped
        assert [e["event"] for e in _read(rec.path)] == ["compile"]

    def test_raising_builder_never_fatal(self, rec):
        class _Fake:
            def _cache_size(self):
                return 1

        def bad():
            raise RuntimeError("boom")

        t = CompileTracker()
        t.track_jit("single", _Fake(), key="k", card_builder=bad)
        assert [e["event"] for e in _read(rec.path)] == ["compile"]
