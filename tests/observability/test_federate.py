"""Federation tests: replica-spec parsing, label injection, the cardinality
cap, and the end-to-end path — two LIVE synthetic replicas scraped into one
fleet exposition by ``ddr obs federate``."""

from __future__ import annotations

import socket
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ddr_tpu.observability.federate import (
    DEFAULT_MAX_SERIES,
    federate_text,
    inject_label,
    max_series_from_env,
    parse_replicas,
    replicas_from_env,
)
from ddr_tpu.observability.prometheus import CONTENT_TYPE, render_text
from ddr_tpu.observability.registry import MetricsRegistry


class TestParseReplicas:
    def test_label_url_pairs(self):
        got = parse_replicas("a=http://h1:9100/metrics, b=https://h2/m")
        assert got == [
            ("a", "http://h1:9100/metrics"),
            ("b", "https://h2/m"),
        ]

    def test_bare_authority_gets_scheme_path_and_label(self):
        assert parse_replicas("h1:9100") == [("h1:9100", "http://h1:9100/metrics")]

    def test_bare_url_keeps_its_path(self):
        assert parse_replicas("http://h1:9100/custom") == [
            ("h1:9100", "http://h1:9100/custom")
        ]

    def test_empty_entries_skipped_and_labels_sanitized(self):
        got = parse_replicas(',,a"b\\c=h:1,')
        assert got == [("abc", "http://h:1/metrics")]

    def test_empty_spec(self):
        assert parse_replicas("") == []

    def test_env_default(self, monkeypatch):
        monkeypatch.delenv("DDR_FEDERATE_REPLICAS", raising=False)
        assert replicas_from_env() == []
        monkeypatch.setenv("DDR_FEDERATE_REPLICAS", "a=h:1,b=h:2")
        assert [lab for lab, _ in replicas_from_env()] == ["a", "b"]


class TestMaxSeries:
    def test_default_and_valid(self, monkeypatch):
        monkeypatch.delenv("DDR_FEDERATE_MAX_SERIES", raising=False)
        assert max_series_from_env() == DEFAULT_MAX_SERIES
        monkeypatch.setenv("DDR_FEDERATE_MAX_SERIES", "17")
        assert max_series_from_env() == 17

    @pytest.mark.parametrize("bad", ["banana", "", "0", "-5"])
    def test_malformed_or_nonpositive_falls_back(self, monkeypatch, bad):
        monkeypatch.setenv("DDR_FEDERATE_MAX_SERIES", bad)
        assert max_series_from_env() == DEFAULT_MAX_SERIES


class TestInjectLabel:
    def test_unlabeled_sample(self):
        assert inject_label("up 1", "replica", "a") == 'up{replica="a"} 1'

    def test_labeled_sample_prepends(self):
        got = inject_label('m{x="1"} 2 123', "replica", "a")
        assert got == 'm{replica="a",x="1"} 2 123'

    def test_value_is_escaped(self):
        got = inject_label("up 1", "replica", 'we"ird\\lab')
        assert got == 'up{replica="we\\"ird\\\\lab"} 1'

    def test_garbage_is_none(self):
        assert inject_label("# HELP up help", "replica", "a") is None
        assert inject_label("not a sample line at all!", "replica", "a") is None


def _registry(name_prefix: str, n: int = 1) -> MetricsRegistry:
    reg = MetricsRegistry(const_labels={"host": 0})
    for i in range(n):
        reg.counter(f"{name_prefix}_total_{i}", help="synthetic").inc(i + 1)
    return reg


class TestFederateText:
    def test_local_registry_folds_in_without_network(self):
        reg = _registry("ddr_local")
        text = federate_text([], local=("self", reg))
        assert 'ddr_federate_up{replica="self"} 1' in text
        assert "ddr_federate_dropped_series 0" in text
        assert 'ddr_local_total_0{replica="self",host="0"} 1' in text

    def test_dead_replica_is_up_zero_not_fatal(self):
        # a port that was bound then closed: connection refused, fast
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        text = federate_text(
            [("dead", f"http://127.0.0.1:{port}/metrics")], timeout=0.5
        )
        assert 'ddr_federate_up{replica="dead"} 0' in text

    def test_cap_drops_overflow_and_reports(self):
        reg = _registry("ddr_cap", n=5)
        text = federate_text([], max_series=2, local=("self", reg))
        samples = [
            ln for ln in text.splitlines()
            if ln.startswith("ddr_cap_total_") and not ln.startswith("#")
        ]
        assert len(samples) == 2
        assert "ddr_federate_dropped_series 3" in text
        # liveness never counts against the cap
        assert 'ddr_federate_up{replica="self"} 1' in text

    def test_histogram_children_stay_under_family_header(self):
        reg = MetricsRegistry()
        reg.histogram("ddr_lat_seconds", buckets=(0.1, 1.0)).observe(0.5)
        text = federate_text([], local=("self", reg))
        lines = text.splitlines()
        type_at = lines.index("# TYPE ddr_lat_seconds histogram")
        assert lines.count("# TYPE ddr_lat_seconds histogram") == 1
        # bucket/sum/count samples follow their single family header
        children = [ln for ln in lines if ln.startswith("ddr_lat_seconds_")]
        assert len(children) == 5  # 2 buckets + +Inf + _sum + _count
        assert all(lines.index(ln) > type_at for ln in children)
        assert reg.series_count() == 5  # what the cap counts for this registry


def _serve_registry(reg: MetricsRegistry) -> ThreadingHTTPServer:
    """A live replica: one ThreadingHTTPServer whose every GET answers with
    the registry's current exposition."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # noqa: A002 - http.server API
            pass

        def do_GET(self):  # noqa: N802 - http.server API
            body = render_text(reg).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestLiveFleet:
    """Two live synthetic replicas -> one fleet page, via both consumption
    paths: ``ddr obs federate --once`` and the standing aggregator."""

    @pytest.fixture
    def fleet(self):
        srvs = [_serve_registry(_registry(f"ddr_rep{i}")) for i in range(2)]
        urls = [f"http://127.0.0.1:{s.server_address[1]}/metrics" for s in srvs]
        yield urls
        for s in srvs:
            s.shutdown()
            s.server_close()

    def test_obs_federate_once_merges_both_replicas(self, fleet, capsys):
        from ddr_tpu.observability.obs_cli import main

        rc = main(
            ["federate", "--replicas", f"a={fleet[0]},b={fleet[1]}", "--once"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert 'ddr_federate_up{replica="a"} 1' in out
        assert 'ddr_federate_up{replica="b"} 1' in out
        assert 'ddr_rep0_total_0{replica="a",host="0"} 1' in out
        assert 'ddr_rep1_total_0{replica="b",host="0"} 1' in out

    def test_standing_aggregator_scrapes_on_demand(self, fleet):
        from ddr_tpu.observability.obs_cli import serve_federation

        agg = serve_federation(
            parse_replicas(f"a={fleet[0]},b={fleet[1]}"), host="127.0.0.1", port=0
        )
        try:
            with urllib.request.urlopen(agg.url, timeout=10) as resp:
                assert resp.status == 200
                assert "version=0.0.4" in resp.headers["Content-Type"]
                body = resp.read().decode()
            assert 'ddr_federate_up{replica="a"} 1' in body
            assert 'ddr_federate_up{replica="b"} 1' in body
            assert 'ddr_rep0_total_0{replica="a"' in body
        finally:
            agg.shutdown()
            agg.server_close()

    def test_no_targets_is_an_error(self, monkeypatch, capsys):
        from ddr_tpu.observability.obs_cli import main

        monkeypatch.delenv("DDR_FEDERATE_REPLICAS", raising=False)
        assert main(["federate", "--once"]) == 2
        assert "no federation targets" in capsys.readouterr().err
