"""Span nesting / trace re-entrancy tests (incl. the regression for
double-started profiler traces and exception safety)."""

from __future__ import annotations

import json
import threading
from contextlib import contextmanager

import pytest

from ddr_tpu.observability import (
    Recorder,
    activate,
    deactivate,
    profile_dir_from_env,
    span,
    spanned,
    trace,
    trace_active,
)
from ddr_tpu.observability.spans import _stack


def _read(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


@pytest.fixture()
def rec(tmp_path):
    r = Recorder(tmp_path / "log.jsonl")
    activate(r)
    yield r
    deactivate(r)
    r.close()


class TestSpanNesting:
    def test_paths_nest(self, rec):
        with span("outer"):
            with span("inner"):
                pass
            with span("inner2"):
                pass
        names = [e["name"] for e in _read(rec.path) if e["event"] == "span"]
        # children close (and emit) before their parent
        assert names == ["outer/inner", "outer/inner2", "outer"]

    def test_span_without_recorder_is_noop(self):
        deactivate()
        with span("lonely"):
            pass  # must not raise, nothing to write to

    def test_exception_unwinds_stack_and_still_records(self, rec):
        with pytest.raises(ValueError):
            with span("outer"):
                with span("bad"):
                    raise ValueError("boom")
        assert _stack() == []  # fully unwound
        names = [e["name"] for e in _read(rec.path) if e["event"] == "span"]
        assert names == ["outer/bad", "outer"]  # both timed despite the raise
        with span("after"):
            pass
        assert _read(rec.path)[-1]["name"] == "after"  # no stale prefix

    def test_spanned_decorator(self, rec):
        @spanned("fn")
        def f(x):
            return x + 1

        assert f(1) == 2
        assert [e["name"] for e in _read(rec.path) if e["event"] == "span"] == ["fn"]

    def test_thread_local_stacks(self, rec):
        paths = []
        orig = rec.record_span
        rec.record_span = lambda p, s, **kw: (paths.append(p), orig(p, s, **kw))

        def worker():
            with span("thread-span"):
                pass

        with span("main-span"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        # the worker's span must NOT nest under the main thread's open span
        assert "thread-span" in paths and "main-span/thread-span" not in paths

    def test_span_inside_jit_traces_once_per_compile(self, rec):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            with span("jit-body"):
                return x * 2

        f(jnp.arange(4))
        f(jnp.arange(4))  # cache hit: no re-trace, no second span
        names = [e["name"] for e in _read(rec.path) if e["event"] == "span"]
        assert names.count("jit-body") == 1


class _CountingTrace:
    """Stand-in for jax.profiler.trace that counts starts/stops."""

    def __init__(self):
        self.starts = 0
        self.stops = 0

    @contextmanager
    def __call__(self, log_dir):
        self.starts += 1
        try:
            yield
        finally:
            self.stops += 1


class TestTraceReentrancy:
    def test_noop_without_dir(self, monkeypatch):
        monkeypatch.delenv("DDR_PROFILE_DIR", raising=False)
        assert profile_dir_from_env() is None
        with trace():
            assert not trace_active()

    def test_nested_trace_starts_profiler_once(self, tmp_path, monkeypatch):
        import jax

        counter = _CountingTrace()
        monkeypatch.setattr(jax.profiler, "trace", counter)
        with trace(str(tmp_path)):
            assert trace_active()
            with trace(str(tmp_path)):  # re-entrant: must NOT double-start
                assert trace_active()
            with trace():  # dir-less nested call: also a no-op
                assert trace_active()
            assert counter.starts == 1
        assert counter.starts == 1 and counter.stops == 1
        assert not trace_active()

    def test_exception_stops_profiler_and_resets_state(self, tmp_path, monkeypatch):
        import jax

        counter = _CountingTrace()
        monkeypatch.setattr(jax.profiler, "trace", counter)
        with pytest.raises(RuntimeError):
            with trace(str(tmp_path)):
                raise RuntimeError("boom")
        assert counter.stops == 1
        assert not trace_active()
        # and a fresh trace can start again afterwards
        with trace(str(tmp_path)):
            pass
        assert counter.starts == 2 and counter.stops == 2

    def test_span_opens_trace_annotation_only_when_tracing(self, tmp_path, monkeypatch, rec):
        import jax

        entered = []

        class _Annot:
            def __init__(self, name):
                self.name = name

            def __enter__(self):
                entered.append(self.name)

            def __exit__(self, *exc):
                return False

        monkeypatch.setattr(jax.profiler, "trace", _CountingTrace())
        monkeypatch.setattr(jax.profiler, "TraceAnnotation", _Annot)
        with span("outside"):
            pass
        assert entered == []
        with trace(str(tmp_path)):
            with span("inside"):
                pass
        assert entered == ["inside"]
