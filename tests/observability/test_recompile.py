"""CompileTracker tests: hit/miss counters, compile events on misses, jit
cache-growth detection, and the ParallelTrainer built-step LRU integration
(cached vs uncached step build)."""

from __future__ import annotations

import json

import jax
import pytest

from ddr_tpu.observability import CompileTracker, Recorder, activate, deactivate


def _read(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


@pytest.fixture()
def rec(tmp_path):
    r = Recorder(tmp_path / "log.jsonl")
    activate(r)
    yield r
    deactivate(r)
    r.close()


class TestCompileTracker:
    def test_miss_emits_compile_event(self, rec):
        t = CompileTracker()
        t.miss("stacked-sharded", key="deadbeef", seconds=1.25, cache_entries=1)
        t.hit("stacked-sharded", key="deadbeef")
        assert t.counts("stacked-sharded") == (1, 1)
        events = [e for e in _read(rec.path) if e["event"] == "compile"]
        assert len(events) == 1  # hits never emit
        ev = events[0]
        assert ev["engine"] == "stacked-sharded"
        assert ev["key"] == "deadbeef"
        assert ev["build_seconds"] == pytest.approx(1.25)
        assert ev["cache_entries"] == 1

    def test_counts_aggregate_across_engines(self):
        t = CompileTracker()
        t.miss("a")
        t.hit("a")
        t.miss("b")
        assert t.counts() == (1, 2)
        snap = t.snapshot()
        assert snap["a"] == {"hits": 1, "misses": 1, "build_seconds": 0.0}
        assert snap["b"]["misses"] == 1

    def test_track_jit_counts_growth_as_miss(self, rec):
        class _Fake:
            def __init__(self):
                self.size = 0

            def _cache_size(self):
                return self.size

        fn = _Fake()
        t = CompileTracker()
        fn.size = 1
        t.track_jit("single", fn, key="k1")  # first sighting: miss
        t.track_jit("single", fn, key="k1")  # steady: hit
        fn.size = 2
        t.track_jit("single", fn, key="k2")  # growth: miss
        assert t.counts("single") == (1, 2)
        keys = [e["key"] for e in _read(rec.path) if e["event"] == "compile"]
        assert keys == ["k1", "k2"]

    def test_track_jit_tolerates_unsupported_fn(self):
        t = CompileTracker()
        t.track_jit("single", lambda: None)  # no _cache_size: silent no-op
        assert t.counts("single") == (0, 0)

    def test_track_jit_on_real_jit(self):
        import jax.numpy as jnp

        fn = jax.jit(lambda x: x + 1)
        t = CompileTracker()
        fn(jnp.arange(3))
        t.track_jit("single", fn)
        fn(jnp.arange(3))  # same shape: cache hit
        t.track_jit("single", fn)
        fn(jnp.arange(5))  # new shape: recompile
        t.track_jit("single", fn)
        hits, misses = t.counts("single")
        if hits == 0 and misses == 0:
            pytest.skip("this jax version exposes no _cache_size")
        assert (hits, misses) == (1, 2)


class TestTrainerStepCache:
    """The trainer's built-step LRU: a repeated batch topology is a hit (no
    compile event); a fresh one is a miss with the topology hash."""

    def _trainer(self, tmp_path):
        from ddr_tpu.scripts.common import build_kan
        from ddr_tpu.parallel.train import ParallelTrainer
        from ddr_tpu.training import make_optimizer
        from ddr_tpu.validation.configs import Config

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        cfg = Config(
            name="obs_run",
            geodataset="synthetic",
            mode="training",
            device="cpu:8",
            kan={"input_var_names": [f"a{i}" for i in range(10)]},
            experiment={
                "start_time": "1981/10/01",
                "end_time": "1981/10/20",
                "rho": 8,
                "batch_size": 2,
                "epochs": 1,
                "warmup": 1,
                "learning_rate": {1: 0.01},
                "parallel": "stacked-sharded",
            },
            params={"save_path": str(tmp_path)},
        )
        kan_model, _ = build_kan(cfg)
        return ParallelTrainer(cfg, kan_model, make_optimizer(1e-3))

    def test_repeat_topology_is_cached(self, tmp_path, rec):
        import numpy as np

        from ddr_tpu.geodatazoo.synthetic import make_basin

        par = self._trainer(tmp_path)
        basin = make_basin(n_segments=33, n_gauges=2, n_days=3, seed=5)
        rd = basin.routing_data
        q_prime = np.asarray(basin.q_prime, dtype=np.float32)

        prep1 = par.prepare(rd, q_prime)
        assert par.compile_tracker.counts("stacked-sharded") == (0, 1)
        prep2 = par.prepare(rd, q_prime)  # same topology: LRU hit, no rebuild
        assert par.compile_tracker.counts("stacked-sharded") == (1, 1)
        assert prep1.step_fn is prep2.step_fn
        assert prep1.topo_key == prep2.topo_key

        compile_events = [e for e in _read(rec.path) if e["event"] == "compile"]
        assert len(compile_events) == 1
        assert compile_events[0]["key"] == prep1.topo_key
        assert compile_events[0]["engine"] == "stacked-sharded"
        # prepare() is span-traced
        assert any(
            e["event"] == "span" and e["name"].startswith("prepare")
            for e in _read(rec.path)
        )

    def test_new_topology_is_a_second_miss(self, tmp_path, rec):
        import numpy as np

        from ddr_tpu.geodatazoo.synthetic import make_basin

        par = self._trainer(tmp_path)
        for seed, n in ((5, 33), (6, 41)):
            basin = make_basin(n_segments=n, n_gauges=2, n_days=3, seed=seed)
            par.prepare(basin.routing_data, np.asarray(basin.q_prime, dtype=np.float32))
        assert par.compile_tracker.counts("stacked-sharded") == (0, 2)
        keys = {e["key"] for e in _read(rec.path) if e["event"] == "compile"}
        assert len(keys) == 2
