"""Numerical-health watchdog tests: on-device stat computation (standalone,
inside jit, riding route() and the train step), env-config parsing, and the
host-side threshold/consecutive/degraded state machine with its telemetry."""

from __future__ import annotations

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddr_tpu.observability import Recorder, activate, deactivate
from ddr_tpu.observability.health import (
    HealthConfig,
    HealthStats,
    HealthWatchdog,
    compute_health,
)
from ddr_tpu.observability.registry import MetricsRegistry, set_registry


@pytest.fixture(autouse=True)
def _isolated_registry():
    set_registry(MetricsRegistry(const_labels={"host": 0}))
    yield
    set_registry(None)


class TestComputeHealth:
    def test_clean_batch(self):
        q = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        h = compute_health(q, q)
        assert int(h.nonfinite) == 0
        assert float(h.q_min) == 1.0 and float(h.q_max) == 4.0
        # runoff == inflow => residual ~ 0
        assert float(h.mass_residual) == pytest.approx(0.0, abs=1e-5)
        assert h.grad_norm is None

    def test_counts_nonfinite_in_all_inputs(self):
        runoff = jnp.asarray([[1.0, jnp.nan]])
        qp = jnp.asarray([[jnp.inf, 1.0]])
        fd = jnp.asarray([jnp.nan])
        h = compute_health(runoff, qp, final_discharge=fd)
        assert int(h.nonfinite) == 3
        # min/max over the FINITE entries only
        assert float(h.q_min) == 1.0 and float(h.q_max) == 1.0
        assert math.isfinite(float(h.mass_residual))

    def test_row_mask_makes_stats_occupancy_independent(self):
        """Pad rows of a serving batch slot must not leak into the stats: one
        live row in a B=4 slot and the same row alone must agree exactly."""
        live = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])[None]  # (1, T, G)
        pad = jnp.full((3, 2, 2), 7.0)  # routed pad rows: nonzero discharge
        batch = jnp.concatenate([live, pad])
        qp_live = jnp.asarray([[0.5, 0.5], [0.5, 0.5]])[None]
        qp = jnp.concatenate([qp_live, jnp.zeros((3, 2, 2))])
        mask = jnp.arange(4) < 1
        h_masked = compute_health(batch, qp, row_mask=mask)
        h_alone = compute_health(live, qp_live)
        for field in ("nonfinite", "q_min", "q_max", "mass_residual"):
            assert float(getattr(h_masked, field)) == pytest.approx(
                float(getattr(h_alone, field))
            ), field
        # without the mask, pad rows dominate q_max and skew the residual
        h_unmasked = compute_health(batch, qp)
        assert float(h_unmasked.q_max) == 7.0
        assert float(h_unmasked.mass_residual) != pytest.approx(
            float(h_alone.mass_residual)
        )
        # NaNs hiding in PAD rows are ignored; NaNs in LIVE rows still count
        nan_pad = batch.at[2, 0, 0].set(jnp.nan)
        assert int(compute_health(nan_pad, qp, row_mask=mask).nonfinite) == 0
        nan_live = batch.at[0, 0, 0].set(jnp.nan)
        assert int(compute_health(nan_live, qp, row_mask=mask).nonfinite) == 1

    def test_compute_health_host_matches_device(self):
        from ddr_tpu.observability.health import compute_health_host

        runoff = np.array([[1.0, np.nan], [2.0, 3.0]], dtype=np.float32)
        qp = np.array([[0.5, np.inf], [0.5, 0.5]], dtype=np.float32)
        h_np = compute_health_host(runoff, qp)
        h_dev = compute_health(jnp.asarray(runoff), jnp.asarray(qp))
        assert int(h_np.nonfinite) == int(h_dev.nonfinite) == 2
        assert float(h_np.q_min) == float(h_dev.q_min)
        assert float(h_np.q_max) == float(h_dev.q_max)
        assert float(h_np.mass_residual) == pytest.approx(
            float(h_dev.mass_residual), rel=1e-6
        )

    def test_inside_jit_is_a_pytree(self):
        h = jax.jit(lambda q: compute_health(q, q))(jnp.ones((3, 4)))
        assert isinstance(h, HealthStats)
        leaves = jax.tree_util.tree_leaves(h)
        assert all(leaf.shape == () for leaf in leaves)

    def test_route_collect_health_rides_result(self):
        from ddr_tpu.geodatazoo.synthetic import make_basin
        from ddr_tpu.routing.mc import route
        from ddr_tpu.routing.model import prepare_batch

        basin = make_basin(n_segments=16, n_gauges=2, n_days=2, seed=0)
        network, channels, gauges = prepare_batch(basin.routing_data, 1e-4)
        params = {
            "n": jnp.full(16, 0.03),
            "q_spatial": jnp.full(16, 0.5),
            "p_spatial": jnp.full(16, 21.0),
        }
        qp = jnp.asarray(basin.q_prime[:12])
        res = route(network, channels, params, qp, gauges=gauges)
        assert res.health is None  # default: exactly the old result
        res_h = route(network, channels, params, qp, gauges=gauges, collect_health=True)
        assert int(res_h.health.nonfinite) == 0
        np.testing.assert_allclose(
            np.asarray(res.runoff), np.asarray(res_h.runoff)
        )  # health is observational only
        bad = qp.at[0, 3].set(jnp.nan)
        res_bad = route(network, channels, params, bad, gauges=gauges, collect_health=True)
        assert int(res_bad.health.nonfinite) > 0

    def test_train_step_returns_health_with_grad_norm(self, tmp_path):
        from ddr_tpu.geodatazoo.synthetic import make_basin, observe
        from ddr_tpu.routing.mc import Bounds
        from ddr_tpu.routing.model import prepare_batch
        from ddr_tpu.scripts.common import build_kan
        from ddr_tpu.training import make_batch_train_step, make_optimizer
        from tests.serving.conftest import make_cfg

        cfg = make_cfg(tmp_path, mode="training")
        kan_model, params = build_kan(cfg)
        basin = observe(make_basin(n_segments=16, n_gauges=2, n_days=4, seed=0), cfg)
        rd = basin.routing_data
        optimizer = make_optimizer(1e-3)
        opt_state = optimizer.init(params)
        step = make_batch_train_step(
            kan_model, Bounds(), cfg.params.parameter_ranges,
            cfg.params.log_space_parameters, cfg.params.defaults,
            tau=cfg.params.tau, warmup=0, optimizer=optimizer,
            collect_health=True,
        )
        network, channels, gauges = prepare_batch(rd, 1e-4)
        obs_daily = jnp.asarray(basin.obs_daily)
        params, opt_state, loss, daily, health = step(
            params, opt_state, network, channels, gauges,
            jnp.asarray(rd.normalized_spatial_attributes),
            jnp.asarray(basin.q_prime), obs_daily,
            jnp.ones_like(obs_daily, dtype=bool),
        )
        assert isinstance(health, HealthStats)
        assert int(health.nonfinite) == 0
        gn = float(health.grad_norm)
        assert math.isfinite(gn) and gn >= 0


class TestHealthConfig:
    def test_defaults_only_flag_nonfinite(self):
        cfg = HealthConfig()
        assert cfg.enabled and cfg.max_nonfinite == 0
        assert cfg.max_discharge == math.inf and cfg.max_residual == math.inf

    def test_env_parsing(self):
        cfg = HealthConfig.from_env({
            "DDR_HEALTH_ENABLED": "0",
            "DDR_HEALTH_MAX_NONFINITE": "5",
            "DDR_HEALTH_MAX_DISCHARGE": "1e6",
            "DDR_HEALTH_MAX_GRAD_NORM": "100",
            "DDR_HEALTH_BAD_BATCHES": "7",
        })
        assert not cfg.enabled
        assert cfg.max_nonfinite == 5
        assert cfg.max_discharge == 1e6
        assert cfg.max_grad_norm == 100
        assert cfg.bad_batches == 7

    def test_overrides_beat_env(self):
        cfg = HealthConfig.from_env({"DDR_HEALTH_BAD_BATCHES": "7"}, bad_batches=2)
        assert cfg.bad_batches == 2

    def test_bad_values_raise(self):
        with pytest.raises(ValueError):
            HealthConfig(bad_batches=0)
        with pytest.raises(ValueError):
            HealthConfig.from_env({"DDR_HEALTH_MAX_NONFINITE": "many"})


def _stats(nonfinite=0, q_min=0.1, q_max=10.0, residual=0.0, grad_norm=None):
    return HealthStats(
        nonfinite=np.int32(nonfinite), q_min=np.float32(q_min),
        q_max=np.float32(q_max), mass_residual=np.float32(residual),
        grad_norm=None if grad_norm is None else np.float32(grad_norm),
    )


class TestWatchdog:
    def test_healthy_batches_keep_gauge_up(self):
        w = HealthWatchdog(HealthConfig())
        assert w.observe(_stats()) == []
        assert not w.degraded and w.consecutive_bad == 0
        assert w.status()["batches"] == 1

    def test_each_violation_kind(self):
        cfg = HealthConfig(max_discharge=100.0, max_residual=10.0, max_grad_norm=1.0)
        w = HealthWatchdog(cfg)
        assert w.check(_stats(nonfinite=1)) == ["non-finite"]
        assert w.check(_stats(q_max=1e4)) == ["discharge-max"]
        assert w.check(_stats(residual=-50.0)) == ["mass-residual"]
        assert w.check(_stats(grad_norm=5.0)) == ["grad-norm"]
        # a NaN grad norm is unhealthy even with the threshold off
        w_inf = HealthWatchdog(HealthConfig())
        assert w_inf.check(_stats(grad_norm=math.nan)) == ["grad-norm"]

    def test_consecutive_degraded_and_recovery(self):
        w = HealthWatchdog(HealthConfig(bad_batches=2))
        w.observe(_stats(nonfinite=1))
        assert not w.degraded
        w.observe(_stats(nonfinite=1))
        assert w.degraded
        w.observe(_stats())  # one healthy batch clears it
        assert not w.degraded and w.consecutive_bad == 0

    def test_disabled_observes_nothing(self):
        w = HealthWatchdog(HealthConfig(enabled=False))
        assert w.observe(_stats(nonfinite=99)) == []
        assert w.status()["batches"] == 0

    def test_reset_streaks_clears_degraded_but_keeps_totals(self):
        """Checkpoint restore / reshard / recovery: the restored state is a
        different trajectory — the streak resets, the lifetime totals don't
        (a resumed run must not flip /readyz 503 on a healthy first batch)."""
        w = HealthWatchdog(HealthConfig(bad_batches=2))
        w.observe(_stats(nonfinite=1))
        w.observe(_stats(nonfinite=1))
        assert w.degraded
        w.reset_streaks()
        assert not w.degraded and w.consecutive_bad == 0
        status = w.status()
        assert status["batches"] == 2 and status["violations"] == 2
        assert status["last_reasons"] == []

    def test_one_event_per_violating_batch(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        try:
            w = HealthWatchdog(HealthConfig())
            w.observe(_stats())  # healthy: no event
            w.observe(_stats(nonfinite=2), epoch=1, batch=4)
        finally:
            deactivate(rec)
            rec.close()
        events = [json.loads(line) for line in (tmp_path / "log.jsonl").read_text().splitlines()]
        health = [e for e in events if e["event"] == "health"]
        assert len(health) == 1
        (ev,) = health
        assert ev["reasons"] == ["non-finite"]
        assert ev["nonfinite"] == 2 and ev["epoch"] == 1 and ev["batch"] == 4
        assert ev["consecutive"] == 1

    def test_gauge_and_counter_flip(self):
        from ddr_tpu.observability.registry import get_registry

        w = HealthWatchdog(HealthConfig())
        g = get_registry().get("ddr_health_status")
        assert g.value() == 1.0
        w.observe(_stats(nonfinite=1))
        assert g.value() == 0.0
        assert get_registry().get("ddr_health_violations_total").value(
            reason="non-finite") == 1
        w.observe(_stats())
        assert g.value() == 1.0


class TestStaleness:
    """The wall-clock stall check: a watchdog that stops seeing batches goes
    stale -> degraded (a hung collective's signature), and a single observe
    clears it."""

    def test_stale_off_by_default(self):
        w = HealthWatchdog(HealthConfig())
        assert not w.stale
        assert w.staleness_s >= 0.0
        assert w.status()["stale"] is False

    def test_stale_after_silence_and_cleared_by_observe(self):
        import time as _time

        w = HealthWatchdog(HealthConfig(max_stall_s=0.05))
        _time.sleep(0.1)
        assert w.stale
        assert w.degraded  # staleness degrades even with zero violations
        status = w.status()
        assert status["stale"] is True and status["degraded"] is True
        assert status["staleness_s"] >= 0.05
        w.observe(_stats())  # one healthy batch clears it
        assert not w.stale and not w.degraded

    def test_disabled_watchdog_never_goes_stale(self):
        import time as _time

        w = HealthWatchdog(HealthConfig(enabled=False, max_stall_s=0.01))
        _time.sleep(0.03)
        assert not w.stale

    def test_from_env_and_validation(self, monkeypatch):
        monkeypatch.setenv("DDR_HEALTH_MAX_STALL_S", "12.5")
        assert HealthConfig.from_env().max_stall_s == 12.5
        import pytest as _pytest

        with _pytest.raises(ValueError, match="max_stall_s"):
            HealthConfig(max_stall_s=0.0)

    def test_serving_readyz_degrades_on_stale(self):
        """The serving layer reads watchdog.degraded for /readyz — staleness
        must flow through the same property."""
        import time as _time

        w = HealthWatchdog(HealthConfig(max_stall_s=0.04, bad_batches=3))
        w.observe(_stats())
        assert not w.degraded
        _time.sleep(0.08)
        assert w.degraded
