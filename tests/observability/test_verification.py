"""The forecast verification plane: streaming CRPS / Brier / rank scorers
and the forecast–observation ledger.

Hand-computed references on tiny ensembles (the closed-form fair-CRPS cases,
ties-low ranks, Murphy's Brier identity), streaming-vs-offline equivalence to
1e-9 over multi-update random data, lead-bin boundary routing, climatology
priors-only threshold resolution, ledger join semantics (duplicates,
out-of-order, eviction), the bounded ``verify`` event, and the worst-K
exposition cardinality under gauge churn.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from ddr_tpu.observability.events import Recorder, activate, deactivate
from ddr_tpu.observability.prometheus import render_text
from ddr_tpu.observability.registry import MetricsRegistry
from ddr_tpu.observability.verification import (
    ForecastLedger,
    VerificationScorer,
    VerifyConfig,
    brier_score,
    crps_ensemble,
    lead_bin_index,
    lead_bin_labels,
    parse_thresholds,
    rank_of_obs,
)


def _scorer(registry=None, **kw):
    kw.setdefault("thresholds", ("1.0",))
    return VerificationScorer(
        VerifyConfig(**kw), registry=registry or MetricsRegistry()
    )


def _crps_brute(members, obs, fair=True):
    """O(E²) textbook estimator: mean|x−y| − Σ_{i,j}|x_i−x_j| / (2D)."""
    m = np.asarray(members, dtype=np.float64)
    E = m.shape[0]
    term1 = np.mean(np.abs(m - np.asarray(obs, dtype=np.float64)[None]), axis=0)
    if E == 1:
        return term1
    pair = np.abs(m[:, None] - m[None, :]).sum(axis=(0, 1))
    denom = E * (E - 1) if fair else E * E
    return term1 - pair / (2.0 * denom)


class TestReferenceScorers:
    def test_closed_form_two_members(self):
        # members {0, 2}, obs 1: term1 = 1, pair term = 2/D.
        # standard D=4 -> 1 - 0.5 = 0.5; fair D=2 -> 1 - 1 = 0.0
        m = np.array([[0.0], [2.0]])
        o = np.array([1.0])
        assert crps_ensemble(m, o, fair=False)[0] == pytest.approx(0.5, abs=1e-12)
        assert crps_ensemble(m, o, fair=True)[0] == pytest.approx(0.0, abs=1e-12)

    def test_single_member_is_mae(self):
        m = np.array([[3.0, -1.0]])
        o = np.array([1.0, 1.0])
        for fair in (True, False):
            np.testing.assert_allclose(
                crps_ensemble(m, o, fair=fair), [2.0, 2.0], atol=1e-12
            )

    def test_matches_brute_force_pairwise(self):
        rng = np.random.default_rng(7)
        m = rng.gamma(2.0, 1.5, size=(9, 40))
        o = rng.gamma(2.0, 1.5, size=40)
        for fair in (True, False):
            np.testing.assert_allclose(
                crps_ensemble(m, o, fair=fair), _crps_brute(m, o, fair=fair),
                atol=1e-9,
            )

    def test_perfect_sharp_ensemble_scores_zero(self):
        o = np.array([2.5, 0.1])
        m = np.tile(o, (4, 1))
        assert crps_ensemble(m, o, fair=True) == pytest.approx([0.0, 0.0])

    def test_rank_of_obs_ties_low(self):
        m = np.array([[1.0, 1.0], [2.0, 1.0], [3.0, 1.0]])
        # obs 2.5: two members below -> rank 2; obs 1.0 ties all -> rank 0
        np.testing.assert_array_equal(
            rank_of_obs(m, np.array([2.5, 1.0])), [2, 0]
        )

    def test_lead_bin_boundaries(self):
        edges = (6.0, 24.0, 72.0)
        assert lead_bin_labels(edges) == ("0-6h", "6-24h", "24-72h", "72h+")
        leads = np.array([0.0, 5.999, 6.0, 23.9, 24.0, 71.9, 72.0, 500.0])
        # a lead exactly AT an edge opens the next bin (half-open upper bounds)
        np.testing.assert_array_equal(
            lead_bin_index(leads, edges), [0, 0, 1, 1, 2, 2, 3, 3]
        )

    def test_parse_thresholds(self):
        assert parse_thresholds("p90, 2.5") == (
            ("p90", "pct", 90.0), ("2.5", "abs", 2.5)
        )
        with pytest.raises(ValueError, match="bad threshold token"):
            parse_thresholds("flood")
        with pytest.raises(ValueError, match="must be in"):
            parse_thresholds("p100")
        with pytest.raises(ValueError, match="finite"):
            parse_thresholds("-1.0")
        with pytest.raises(ValueError, match="duplicate"):
            parse_thresholds("p90,p90")


class TestVerifyConfig:
    def test_env_and_override_precedence(self):
        env = {
            "DDR_VERIFY_THRESHOLDS": "p75,3.0",
            "DDR_VERIFY_LEAD_BINS": "12,48",
            "DDR_VERIFY_TOPK": "3",
            "DDR_VERIFY_ENABLED": "0",
        }
        cfg = VerifyConfig.from_env(environ=env, top_k=5)
        assert cfg.thresholds == ("p75", "3.0")
        assert cfg.lead_bins_h == (12.0, 48.0)
        assert cfg.top_k == 5  # explicit override beats env
        assert cfg.enabled is False

    def test_validation(self):
        with pytest.raises(ValueError, match="strictly increasing"):
            VerifyConfig(lead_bins_h=(24.0, 6.0))
        with pytest.raises(ValueError, match="ledger_cap"):
            VerifyConfig(ledger_cap=0)
        with pytest.raises(ValueError, match="min_clim"):
            VerifyConfig(clim_samples=4, min_clim=8)
        with pytest.raises(ValueError, match="bad threshold token"):
            VerifyConfig(thresholds=("flood",))
        with pytest.raises(ValueError, match="bad DDR_VERIFY_LEAD_BINS"):
            VerifyConfig.from_env(environ={"DDR_VERIFY_LEAD_BINS": "6,x"})


class TestStreamingScorer:
    def test_streaming_matches_offline_to_1e9(self):
        """Many small updates == one offline pass: the raw running sums
        reproduce the reference estimators exactly (1e-9), independent of
        the 6-decimal rounding the bounded event payload applies."""
        rng = np.random.default_rng(3)
        sc = _scorer(thresholds=("1.0",), lead_bins_h=(6.0, 24.0))
        E, chunks, S = 5, 7, 12
        all_m, all_o = [], []
        for _ in range(chunks):
            m = rng.gamma(2.0, 1.0, size=(E, S))
            o = rng.gamma(2.0, 1.0, size=S)
            lead = rng.uniform(0.0, 48.0, size=S)
            sc.update_samples(m, o, lead, [f"g{i % 3}" for i in range(S)])
            all_m.append(m)
            all_o.append(o)
        m = np.concatenate(all_m, axis=1)
        o = np.concatenate(all_o)
        n = sc._bin_sums[:, 0].sum()
        assert n == m.shape[1]
        ref_crps = crps_ensemble(m, o, fair=True)
        assert sc._bin_sums[:, 1].sum() / n == pytest.approx(
            ref_crps.mean(), abs=1e-9
        )
        acc = sc._brier["1.0"]
        p = (m > 1.0).mean(axis=0)
        ob = (o > 1.0).astype(float)
        assert acc["sse"].sum() / acc["n"].sum() == pytest.approx(
            brier_score(p, ob), abs=1e-9
        )
        # spread–skill from the same sums: fair member variance over mean RMSE
        ens_var = m.var(axis=0, ddof=1) * (E + 1.0) / E
        rmse = np.sqrt(np.mean((m.mean(axis=0) - o) ** 2))
        assert sc.summary()["spread_skill"] == pytest.approx(
            np.sqrt(ens_var.mean()) / rmse, abs=1e-4
        )

    def test_murphy_identity_with_one_p_per_bin(self):
        """With every probability bin holding a single distinct forecast p,
        the binned decomposition is exact: BS = REL − RES + UNC."""
        sc = _scorer(thresholds=("1.0",), min_samples=1)
        E = 10
        # p = k/10 for k=0..9 -> ten distinct bins; obs alternates outcome
        for k in range(10):
            members = np.array([2.0] * k + [0.0] * (E - k), dtype=float)
            obs = 3.0 if k % 2 else 0.5  # exceeds threshold on odd k
            sc.update_samples(members[:, None], [obs], [1.0], [f"g{k}"])
        t = sc.summary()["thresholds"]["1.0"]
        assert t["n"] == 10
        assert t["brier"] == pytest.approx(
            t["reliability"] - t["resolution"] + t["uncertainty"], abs=3e-6
        )
        assert t["base_rate"] == pytest.approx(0.5)

    def test_rank_histogram_and_flatness(self):
        sc = _scorer()
        m = np.array([[0.0, 0.0, 0.0], [1.0, 1.0, 1.0]])  # E=2
        # obs below both / between / above both -> ranks 0, 1, 2
        sc.update_samples(m, [-1.0, 0.5, 2.0], [1.0, 1.0, 1.0], list("abc"))
        rh = sc.summary()["rank_histogram"]
        assert rh["members"] == 2
        assert rh["counts"] == [1, 1, 1]
        assert rh["flatness"] == pytest.approx(0.0)  # perfectly flat

    def test_lead_bin_routing(self):
        sc = _scorer(lead_bins_h=(6.0, 24.0))
        m = np.zeros((2, 3))
        sc.update_samples(m, [0.0, 0.0, 0.0], [5.9, 6.0, 24.0], list("xyz"))
        by = sc.summary()["by_lead"]
        assert [by[k]["n"] for k in ("0-6h", "6-24h", "24h+")] == [1, 1, 1]

    def test_nonfinite_samples_counted_and_skipped(self):
        sc = _scorer()
        m = np.array([[1.0, np.nan, 1.0], [2.0, 2.0, 2.0]])
        obs = [1.5, 1.5, np.inf]
        assert sc.update_samples(m, obs, [1.0] * 3, list("abc")) == 1
        s = sc.summary()
        assert s["samples"] == 1 and s["nonfinite_samples"] == 2

    def test_update_flattens_like_update_samples(self):
        rng = np.random.default_rng(11)
        E, T, G = 3, 4, 2
        m = rng.gamma(2.0, 1.0, size=(E, T, G))
        o = rng.gamma(2.0, 1.0, size=(T, G))
        lead = np.arange(1.0, T + 1)
        a = _scorer()
        a.update(m, o, lead, ["g0", "g1"])
        b = _scorer()
        b.update_samples(
            m.reshape(E, T * G), o.reshape(T * G), np.repeat(lead, G),
            [g for _ in range(T) for g in ("g0", "g1")],
        )
        np.testing.assert_allclose(a._bin_sums, b._bin_sums, atol=1e-12)
        assert a.summary() == b.summary()

    def test_climatology_thresholds_are_priors_only(self):
        """A pNN threshold resolves from observations STRICTLY BEFORE the
        scored batch: the first batch (no priors) contributes no Brier
        samples, the second scores against the first batch's percentile."""
        sc = _scorer(thresholds=("p50",), clim_samples=8, min_clim=2)
        m = np.zeros((1, 4))
        first = [1.0, 2.0, 3.0, 4.0]
        sc.update_samples(m, first, [1.0] * 4, ["g"] * 4)
        assert sc.summary()["thresholds"]["p50"]["n"] == 0  # no priors yet
        second = [10.0, 0.0, 10.0, 0.0]
        sc.update_samples(m, second, [1.0] * 4, ["g"] * 4)
        t = sc.summary()["thresholds"]["p50"]
        assert t["n"] == 4
        # threshold = median of FIRST batch only (2.5): p=0 members, obs
        # exceeds twice -> BS = 0.5, base rate 0.5
        assert t["base_rate"] == pytest.approx(0.5)
        assert t["brier"] == pytest.approx(0.5)

    def test_worst_gauges_floor_and_order(self):
        sc = _scorer(min_samples=2, top_k=2)
        m = np.array([[0.0]])
        for gauge, err, times in (("a", 5.0, 2), ("b", 1.0, 2), ("c", 9.0, 1)):
            for _ in range(times):
                sc.update_samples(m, [err], [1.0], [gauge])
        worst = sc.worst_gauges()
        # c is worst but below the sample floor; order is mean-CRPS descending
        assert [w["gauge"] for w in worst] == ["a", "b"]
        assert worst[0]["crps"] == pytest.approx(5.0)

    def test_worst_k_exposition_cardinality_under_churn(self):
        reg = MetricsRegistry()
        sc = _scorer(registry=reg, min_samples=1, top_k=3)
        m = np.array([[0.0]])
        for wave in range(6):
            gauges = [f"g{wave}_{i}" for i in range(4)]
            errs = [float(10 + wave + i) for i in range(4)]
            sc.update_samples(
                np.tile(m, (1, 4)), errs, [1.0] * 4, gauges
            )
        text = render_text(reg)
        rows = [
            ln for ln in text.splitlines()
            if ln.startswith("ddr_verify_worst_crps{")
        ]
        assert len(rows) == 3  # capped at top_k; stale gauges removed

    def test_disabled_scorer_is_inert(self):
        sc = _scorer(enabled=False)
        assert sc.update_samples(np.zeros((1, 2)), [1.0, 1.0], [1.0, 1.0],
                                 ["a", "b"]) == 0
        assert sc.status()["samples"] == 0


class TestForecastLedger:
    def _ledger(self, **kw):
        kw.setdefault("thresholds", ("1.0",))
        return ForecastLedger(VerifyConfig(**kw), registry=MetricsRegistry())

    def test_join_scores_reference_crps(self):
        led = self._ledger()
        members = np.array(
            [[[0.0, 1.0]], [[2.0, 3.0]]]  # (E=2, T=1, G=2)
        )
        led.record_forecast("net", "m", "r1", 100, [101], ["a", "b"], members)
        out = led.observe("net", {"a": [(101, 1.0)], "b": [(101, 2.0)]})
        assert out["matched"] == 2 and out["unmatched"] == 0
        ref = crps_ensemble(members[:, 0, :], np.array([1.0, 2.0]), fair=True)
        assert led.scorer.summary()["crps"] == pytest.approx(
            ref.mean(), abs=1e-6
        )
        # lead = valid - issue = 1h -> first bin
        assert led.scorer.summary()["by_lead"]["0-6h"]["n"] == 2

    def test_duplicate_and_unmatched_accounting(self):
        led = self._ledger()
        led.record_forecast(
            "net", "m", "r1", 0, [1], ["a"], np.zeros((1, 1, 1))
        )
        assert led.observe("net", {"a": [(1, 0.5)]})["matched"] == 1
        again = led.observe("net", {"a": [(1, 0.5)], "b": [(1, 0.5)]})
        assert again["matched"] == 0
        assert again["duplicates"] == 1  # recently matched key re-observed
        assert again["unmatched"] == 1  # nothing ever pending for gauge b
        assert led.scorer.status()["samples"] == 1  # never rescored
        st = led.status()
        assert st["duplicate_obs"] == 1 and st["unmatched_obs"] == 1

    def test_out_of_order_joins(self):
        """Observations arrive latest-valid-hour first; every pending cell
        still matches, each at its own lead time."""
        led = self._ledger()
        led.record_forecast(
            "net", "m", "r1", 0, [1, 2, 3], ["a"], np.zeros((2, 3, 1))
        )
        assert led.observe("net", {"a": [(3, 0.0)]})["matched"] == 1
        assert led.observe("net", {"a": [(2, 0.0), (1, 0.0)]})["matched"] == 2
        assert led.scorer.status()["samples"] == 3

    def test_multiple_forecasts_one_observation(self):
        """Overlapping issues (a 1-member and a 3-member forecast claiming
        the same valid hour) both score on the single observation."""
        led = self._ledger()
        led.record_forecast("net", "m", "r1", 0, [2], ["a"], np.zeros((1, 1, 1)))
        led.record_forecast("net", "m", "r2", 1, [2], ["a"], np.ones((3, 1, 1)))
        out = led.observe("net", {"a": [(2, 0.5)]})
        assert out["matched"] == 2
        # E=1 at lead 2h scored MAE 0.5; E=3 at lead 1h
        assert led.scorer.status()["samples"] == 2

    def test_deterministic_oldest_eviction(self):
        led = self._ledger(ledger_cap=3)
        led.record_forecast(
            "net", "m", "r1", 0, [1, 2, 3, 4, 5], ["a"],
            np.zeros((1, 5, 1)),
        )
        assert led.status()["evicted"] == 2  # hours 1 and 2 dropped
        assert led.observe("net", {"a": [(1, 0.0), (2, 0.0)]})["unmatched"] == 2
        assert led.observe("net", {"a": [(3, 0.0)]})["matched"] == 1

    def test_http_list_form_and_validation(self):
        led = self._ledger()
        led.record_forecast(
            "net", "m", "r1", 0, [1, 2], ["a"], np.zeros((1, 2, 1))
        )
        out = led.observe(
            "net", [{"gauge": "a", "times": [1, 2], "values": [0.1, 0.2]}]
        )
        assert out["matched"] == 2
        with pytest.raises(ValueError, match="times"):
            led.observe("net", [{"gauge": "a", "times": [1], "values": []}])

    def test_two_t_g_member_layout_accepted(self):
        led = self._ledger()
        led.record_forecast(  # (T, G) single-forecast shorthand -> (1, T, G)
            "net", "m", "r1", 0, [1], ["a", "b"], np.array([[0.5, 1.5]])
        )
        assert led.observe(
            "net", {"a": [(1, 0.5)], "b": [(1, 1.5)]}
        )["matched"] == 2
        assert led.scorer.summary()["crps"] == pytest.approx(0.0)

    def test_one_bounded_verify_event_per_join(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        try:
            led = self._ledger()
            led.record_forecast(
                "net", "m", "r1", 0, [1], ["a"], np.zeros((2, 1, 1))
            )
            led.observe("net", {"a": [(1, 0.2)]}, source="test")
            led.observe("net", {"a": [(1, 0.2)]})  # all-duplicate join
        finally:
            deactivate(rec)
            rec.close()
        events = [
            json.loads(ln)
            for ln in (tmp_path / "log.jsonl").read_text().splitlines()
            if '"verify"' in ln
        ]
        events = [e for e in events if e.get("event") == "verify"]
        assert len(events) == 2  # exactly one per observe() call
        first = events[0]
        assert first["matched"] == 1 and first["source"] == "test"
        assert first["crps"] is not None
        assert set(first["by_lead"]) <= set(lead_bin_labels(
            VerifyConfig().lead_bins_h
        ))
        assert len(json.dumps(first)) < 4096  # bounded payload
        assert events[1]["duplicates"] == 1

    def test_status_rollup_shape(self):
        led = self._ledger()
        led.record_forecast(
            "net", "m", "r1", 0, [1, 2], ["a"], np.zeros((1, 2, 1))
        )
        st = led.status()
        assert st["forecasts"] == 1 and st["cells_pending"] == 2
        assert st["scorer"]["enabled"] is True
        assert st["scorer"]["samples"] == 0
