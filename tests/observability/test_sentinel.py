"""observability.sentinel: streaming change-point detection (EWMA + CUSUM
with warmup and hysteresis), the overlap-aware bottleneck classifier, and the
Sentinel's bounded anomaly emission — the contracts docs/observability.md
"Performance sentinel & bottleneck attribution" promises."""

from __future__ import annotations

import json

import pytest

from ddr_tpu.observability.events import EVENT_TYPES, run_telemetry
from ddr_tpu.observability.prometheus import event_tee
from ddr_tpu.observability.registry import MetricsRegistry
from ddr_tpu.observability.sentinel import (
    BOTTLENECK_CLASSES,
    BottleneckAttributor,
    EwmaCusumDetector,
    Sentinel,
    SentinelConfig,
    attribute_steps,
    classify_step,
    render_attribution,
)

#: A config tuned so the fixtures below are deterministic: short warmup,
#: unsmoothed residuals, tight threshold.
CFG = SentinelConfig(
    warmup=10, ewma_alpha=1.0, cusum_k=0.5, cusum_h=5.0, hysteresis=3,
    min_sigma_frac=0.1,
)


def _feed(det, values, start=0):
    """Feed a value sequence; return the transitions [(step, state), ...]."""
    out = []
    for i, v in enumerate(values, start=start):
        tr = det.observe(v, step=i)
        if tr is not None:
            out.append((i, tr))
    return out


class TestDetectorFixtures:
    def test_warmup_is_silent_even_on_wild_samples(self):
        det = EwmaCusumDetector("x", CFG)
        # anything goes during calibration — it IS the baseline
        assert _feed(det, [1.0, 100.0, 1.0, 50.0, 1.0, 1.0, 2.0, 1.0, 1.0]) == []
        assert det.snapshot()["warming_up"] is True

    def test_step_change_fires_exactly_once_per_episode(self):
        det = EwmaCusumDetector("x", CFG)
        values = [1.0] * 10 + [10.0] * 20  # calibrate on 1.0, then a level shift
        transitions = _feed(det, values)
        assert [t["state"] for _, t in transitions] == ["firing"]
        step, t = transitions[0]
        assert t["side"] == "high"
        assert t["baseline"] == pytest.approx(1.0)
        assert t["observed"] == pytest.approx(10.0)
        # onset is the first shifted sample, which precedes the crossing
        assert t["onset_step"] == 10
        assert t["onset_step"] <= step
        assert det.firing and det.episodes == 1

    def test_drop_fires_low_side_only_with_low_direction(self):
        det = EwmaCusumDetector("throughput", CFG, direction="low")
        # throughput collapse fires...
        drops = _feed(det, [100.0] * 10 + [10.0] * 10)
        assert [t["state"] for _, t in drops] == ["firing"]
        assert drops[0][1]["side"] == "low"
        # ...but a throughput IMPROVEMENT on a fresh detector never does
        det2 = EwmaCusumDetector("throughput", CFG, direction="low")
        assert _feed(det2, [100.0] * 10 + [1000.0] * 30) == []

    def test_ramp_fires_once_and_resolves_after_hysteresis(self):
        det = EwmaCusumDetector("x", CFG)
        ramp = [float(v) for v in range(10)]  # noisy-ish rising warmup
        values = ramp + [20.0, 30.0, 40.0, 50.0, 60.0, 70.0, 80.0]
        transitions = _feed(det, values)
        assert [t["state"] for _, t in transitions] == ["firing"]
        # back in band: needs `hysteresis` consecutive calm samples
        base = det.config.hysteresis
        back = _feed(det, [4.5] * (base + 1), start=100)
        assert [t["state"] for _, t in back] == ["resolved"]
        assert not det.firing
        # a second excursion is a NEW episode (fires again, episodes == 2)
        again = _feed(det, [80.0] * 10, start=200)
        assert [t["state"] for _, t in again] == ["firing"]
        assert det.episodes == 2

    def test_hysteresis_no_flap_on_boundary_oscillation(self):
        det = EwmaCusumDetector("x", CFG)
        _feed(det, [1.0] * 10 + [50.0] * 5)  # now firing
        assert det.firing
        # oscillate: calm, calm, SPIKE, calm, calm, SPIKE ... — the in-band
        # run never reaches `hysteresis`, so no resolved/firing flapping
        osc = [1.0, 1.0, 50.0] * 6
        assert _feed(det, osc, start=50) == []
        assert det.firing

    def test_near_constant_warmup_gets_sigma_floor(self):
        det = EwmaCusumDetector("x", CFG)
        _feed(det, [2.0] * 10)  # zero variance; floor = 0.1 * 2.0
        assert det.snapshot()["sigma"] == pytest.approx(0.2)
        # jitter inside the floor never fires
        assert _feed(det, [2.01, 1.99, 2.02, 1.98] * 10, start=10) == []

    def test_nonfinite_and_garbage_samples_are_dropped(self):
        det = EwmaCusumDetector("x", CFG)
        for bad in (float("nan"), float("inf"), "bogus", None):
            assert det.observe(bad) is None
        assert det.snapshot()["samples"] == 0


class TestClassifier:
    def test_overlap_aware_device_bound_despite_big_host_buckets(self):
        # prefetch overlap: host buckets are LARGE but the device was kept
        # busy — loop wall barely exceeds device time
        phases = {"data_load": 0.09, "host_prep": 0.05, "device_step": 0.10}
        assert classify_step(phases, loop_s=0.11) == "device_bound"

    def test_idle_loop_attributes_to_largest_host_bucket(self):
        phases = {"data_load": 0.08, "host_prep": 0.01, "device_step": 0.02}
        assert classify_step(phases, loop_s=0.10) == "data_bound"
        phases = {"data_load": 0.01, "eval": 0.08, "device_step": 0.02}
        assert classify_step(phases, loop_s=0.10) == "host_bound"
        phases = {"checkpoint": 0.2, "device_step": 0.02}
        assert classify_step(phases, loop_s=0.25) == "checkpoint_bound"

    def test_without_loop_s_largest_bucket_wins_device_on_ties(self):
        assert classify_step({"data_load": 0.2, "device_step": 0.1}) == "data_bound"
        assert classify_step({"data_load": 0.1, "device_step": 0.1}) == "device_bound"
        assert classify_step({}) == "unknown"
        assert classify_step(None) == "unknown"

    def test_idle_frac_threshold_is_respected(self):
        phases = {"data_load": 0.05, "device_step": 0.06}
        # idle = 0.04 of 0.10 loop: bound by the knob
        assert classify_step(phases, loop_s=0.10, idle_frac=0.5) == "device_bound"
        assert classify_step(phases, loop_s=0.10, idle_frac=0.25) == "data_bound"


class TestAttributor:
    def test_modal_verdict_with_stage_seconds_and_overlap(self):
        attr = BottleneckAttributor()
        for _ in range(7):
            attr.add({"data_load": 0.08, "device_step": 0.02}, loop_s=0.1)
        for _ in range(3):
            attr.add({"data_load": 0.001, "device_step": 0.098}, loop_s=0.1)
        s = attr.summary()
        assert s["steps"] == 10
        assert s["classes"] == {"data_bound": 7, "device_bound": 3}
        assert s["verdict"] == "data_bound"
        assert s["stage_seconds"]["data_load"] == pytest.approx(0.563)
        assert s["overlap"]["steps"] == 10
        assert s["overlap"]["busy_frac"] == pytest.approx(0.434)
        assert any("prefetch_ahead" in r for r in s["recommendations"])

    def test_verdict_tiebreak_prefers_actionable_class(self):
        attr = BottleneckAttributor()
        attr.add({"data_load": 0.2, "device_step": 0.01}, loop_s=0.25)
        attr.add({"data_load": 0.001, "device_step": 0.24}, loop_s=0.25)
        # 1-1 tie: data_bound (earlier in BOTTLENECK_CLASSES) wins
        assert BOTTLENECK_CLASSES.index("data_bound") < BOTTLENECK_CLASSES.index(
            "device_bound"
        )
        assert attr.summary()["verdict"] == "data_bound"

    def test_unknown_only_when_nothing_classified(self):
        attr = BottleneckAttributor()
        attr.add({}, None)
        assert attr.summary()["verdict"] == "unknown"
        attr.add({"device_step": 0.1}, loop_s=0.1)
        assert attr.summary()["verdict"] == "device_bound"

    def test_attribute_steps_replays_events_and_renders(self):
        events = [
            {"event": "step", "phases": {"data_load": 0.09, "device_step": 0.01},
             "loop_s": 0.1},
            {"event": "step", "no_phases_here": True},
        ] * 3
        result = attribute_steps(events)
        assert result["steps"] == 3  # phase-less events skipped
        text = render_attribution(result)
        assert "pipeline verdict : data_bound" in text
        assert "steps classified : 3" in text
        assert "device busy" in text
        assert "  - raise experiment.prefetch_ahead" in text


class TestSentinel:
    def test_anomaly_event_reaches_run_log_with_scope(self, tmp_path):
        with run_telemetry(None, "sentinel_test", base_dir=str(tmp_path)):
            s = Sentinel(CFG, scope="train", registry=MetricsRegistry())
            for i in range(10):
                s.observe("data_load", 0.01, step=i)
            for i in range(10, 20):
                s.observe("data_load", 0.5, step=i)
            assert s.active() == ["data_load"]
        log = next(tmp_path.glob("run_log.*.jsonl"))
        events = [json.loads(ln) for ln in log.read_text().splitlines()]
        anomalies = [e for e in events if e["event"] == "anomaly"]
        assert len(anomalies) == 1
        a = anomalies[0]
        assert a["signal"] == "data_load" and a["state"] == "firing"
        assert a["scope"] == "train" and a["onset_step"] == 10

    def test_max_events_budget_suppresses_log_but_not_gauges(self):
        reg = MetricsRegistry()
        cfg = SentinelConfig(
            warmup=2, ewma_alpha=1.0, cusum_h=2.0, hysteresis=1, max_events=1
        )
        emitted = []
        s = Sentinel(cfg, registry=reg, emit=lambda ev, **kw: emitted.append(kw))
        for i in range(2):
            s.observe("a", 1.0, step=i)
            s.observe("b", 1.0, step=i)
        s.observe("a", 100.0, step=2)  # episode 1: within budget
        s.observe("b", 100.0, step=2)  # episode 2: over budget
        assert len(emitted) == 1 and emitted[0]["signal"] == "a"
        st = s.status()
        assert st["events"] == 1 and st["suppressed"] == 1
        assert sorted(st["active"]) == ["a", "b"]
        # the over-budget transition still reached the registry directly
        assert reg.get("ddr_anomaly_active").value(signal="b") == 1.0
        assert reg.get("ddr_anomalies_total").value(signal="b") == 1.0

    def test_observe_step_feeds_phases_cadence_and_compile_deltas(self):
        s = Sentinel(
            SentinelConfig(warmup=5, ewma_alpha=1.0, cusum_h=3.0, hysteresis=1),
            registry=MetricsRegistry(),
            emit=lambda ev, **kw: None,
        )
        for i in range(1, 7):
            s.observe_step(
                i, phases={"data_load": 0.01, "device_step": 0.02},
                loop_s=0.022, seconds=0.02, rate=100.0, compiles=3,
            )
        snap = s.status()["signals"]
        # compile_rate sees DELTAS of the cumulative count: constant 3 -> 0.0
        assert snap["compile_rate"]["samples"] == 5
        assert {"data_load", "device_step", "step_seconds", "throughput"} <= set(snap)
        # a late recompile storm fires the compile_rate detector
        out = []
        for i in range(7, 15):
            out += s.observe_step(i, compiles=3 + (i - 6) * 4)
        assert any(t["signal"] == "compile_rate" for t in out)
        assert s.pipeline_summary()["verdict"] == "device_bound"

    def test_disabled_sentinel_is_inert(self):
        s = Sentinel(SentinelConfig(enabled=False), registry=MetricsRegistry())
        for i in range(50):
            assert s.observe("x", 1000.0 * (i % 2), step=i) is None
        assert s.observe_step(1, phases={"data_load": 9.9}) == []
        assert s.status()["signals"] == {}


class TestConfigAndTee:
    def test_from_env_precedence_and_falsey(self):
        cfg = SentinelConfig.from_env(environ={
            "DDR_SENTINEL_WARMUP": "7",
            "DDR_SENTINEL_CUSUM_H": "3.5",
            "DDR_SENTINEL_ENABLED": "off",
            "DDR_SENTINEL_FLAG_WATCHDOG": "1",
        }, cusum_h=9.0)
        assert cfg.warmup == 7
        assert cfg.cusum_h == 9.0  # explicit override beats env
        assert cfg.enabled is False and cfg.flag_watchdog is True

    def test_from_env_rejects_garbage_and_bad_ranges(self):
        with pytest.raises(ValueError, match="DDR_SENTINEL_WARMUP"):
            SentinelConfig.from_env(environ={"DDR_SENTINEL_WARMUP": "soon"})
        with pytest.raises(ValueError, match="warmup"):
            SentinelConfig(warmup=1)
        with pytest.raises(ValueError, match="idle_frac"):
            SentinelConfig(idle_frac=1.0)
        with pytest.raises(ValueError, match="ewma_alpha"):
            SentinelConfig(ewma_alpha=0.0)

    def test_anomaly_is_a_schema_event_type(self):
        assert "anomaly" in EVENT_TYPES

    def test_tee_counts_episodes_and_tracks_active_gauge(self):
        r = MetricsRegistry()
        fire = {"event": "anomaly", "signal": "data_load", "state": "firing"}
        event_tee(fire, r)
        event_tee(fire, r)
        event_tee({"event": "anomaly", "signal": "data_load",
                   "state": "resolved"}, r)
        assert r.get("ddr_anomalies_total").value(signal="data_load") == 2.0
        assert r.get("ddr_anomaly_active").value(signal="data_load") == 0.0

    def test_tee_heartbeat_prefetch_depth_gauge(self):
        r = MetricsRegistry()
        event_tee({"event": "heartbeat", "prefetch_depth": 3}, r)
        assert r.get("ddr_prefetch_depth").value() == 3.0
        event_tee({"event": "heartbeat"}, r)  # no depth: gauge untouched
        assert r.get("ddr_prefetch_depth").value() == 3.0
