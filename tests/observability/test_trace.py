"""Trace-context tests: id minting/derivation, the DDR_TRACE master switch,
the thread-local ambient stack, and the deterministic multi-host step scheme."""

from __future__ import annotations

import threading

import pytest

from ddr_tpu.observability.trace import (
    SpanContext,
    adopt_trace_id,
    context,
    current,
    derive_id,
    new_span_id,
    new_trace_id,
    pop,
    push,
    run_trace_seed,
    step_context,
    trace_enabled,
)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("DDR_TRACE", raising=False)
    monkeypatch.delenv("DDR_RUN_ID", raising=False)


class TestSwitch:
    def test_default_on(self):
        assert trace_enabled() is True

    @pytest.mark.parametrize("off", ["0", "false", "no", "off", " OFF ", "No"])
    def test_off_spellings(self, monkeypatch, off):
        monkeypatch.setenv("DDR_TRACE", off)
        assert trace_enabled() is False

    @pytest.mark.parametrize("on", ["1", "true", "yes", "on", "anything"])
    def test_on_spellings(self, monkeypatch, on):
        monkeypatch.setenv("DDR_TRACE", on)
        assert trace_enabled() is True


class TestIds:
    def test_mint_shapes(self):
        tid, sid = new_trace_id(), new_span_id()
        assert len(tid) == 16 and len(sid) == 12
        int(tid, 16), int(sid, 16)  # hex or raise
        assert new_trace_id() != tid  # random, not sticky

    def test_derive_is_deterministic_and_part_sensitive(self):
        a = derive_id("step", "run-1", 7)
        assert a == derive_id("step", "run-1", 7)
        assert a != derive_id("step", "run-1", 8)
        assert a != derive_id("step", "run-2", 7)
        assert len(a) == 16 and len(derive_id("x", length=12)) == 12

    def test_adopt_sanitizes_caps_and_mints(self):
        assert adopt_trace_id("edge-abc") == "edge-abc"
        # control chars and whitespace are stripped, the rest survives
        assert adopt_trace_id("ok\tid\x01junk") == "okidjunk"
        assert len(adopt_trace_id("x" * 200)) == 64
        # nothing usable -> a fresh mint
        assert len(adopt_trace_id(None)) == 16
        assert len(adopt_trace_id("\x01\x02")) == 16


class TestSpanContext:
    def test_child_keeps_trace_and_links_parent(self):
        root = SpanContext("t" * 16, "s" * 12)
        kid = root.child()
        assert kid.trace_id == root.trace_id
        assert kid.parent_id == root.span_id
        assert kid.span_id != root.span_id and len(kid.span_id) == 12
        named = root.child(span_id="abc123")
        assert named.span_id == "abc123"

    def test_ids_omits_absent_parent(self):
        root = SpanContext("t" * 16, "s" * 12)
        assert root.ids() == {"trace_id": "t" * 16, "span_id": "s" * 12}
        kid = root.child(span_id="k" * 12)
        assert kid.ids()["parent_id"] == root.span_id


class TestAmbientStack:
    def test_push_pop_and_context_manager(self):
        assert current() is None
        a = SpanContext(new_trace_id(), new_span_id())
        push(a)
        try:
            assert current() is a
            with context(a.child()) as b:
                assert current() is b and b.parent_id == a.span_id
            assert current() is a
        finally:
            pop()
        assert current() is None
        pop()  # underflow is a no-op, not an error

    def test_context_none_is_noop(self):
        with context(None) as got:
            assert got is None and current() is None

    def test_stack_is_thread_local(self):
        push(SpanContext(new_trace_id(), new_span_id()))
        try:
            seen: list = []
            t = threading.Thread(target=lambda: seen.append(current()))
            t.start()
            t.join()
            assert seen == [None]  # the other thread sees its own empty stack
        finally:
            pop()


class TestStepScheme:
    def test_seed_precedence(self, monkeypatch):
        class P:
            save_path = "/runs/x"

        class Cfg:
            name = "basin"
            params = P()

        assert run_trace_seed(None) == "run"
        assert run_trace_seed(Cfg()) == "basin:/runs/x"
        monkeypatch.setenv("DDR_RUN_ID", "launcher-7")
        assert run_trace_seed(Cfg()) == "launcher-7"  # env wins over config

    def test_hosts_agree_without_collectives(self):
        # two "hosts" derive the same step context from the shared seed alone
        a = step_context("basin:/runs/x", "3:12")
        b = step_context("basin:/runs/x", "3:12")
        assert a == b
        assert a.parent_id is None  # the step IS the trace root
        assert step_context("basin:/runs/x", "3:13").trace_id != a.trace_id

    def test_none_when_disabled(self, monkeypatch):
        monkeypatch.setenv("DDR_TRACE", "0")
        assert step_context("seed", 1) is None
