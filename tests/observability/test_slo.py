"""SLO layer tests: SloConfig validation + env parsing, SloTracker
sliding-window attainment/burn-rate arithmetic and alert edge detection
(injected clocks — no sleeps), and the offline event replay."""

from __future__ import annotations

import threading

import pytest

from ddr_tpu.observability.slo import (
    SloConfig,
    SloTracker,
    attainment_from_events,
    window_label,
)


class TestSloConfig:
    def test_defaults(self):
        cfg = SloConfig()
        assert cfg.enabled and cfg.target == 0.99
        assert cfg.windows == (60.0, 300.0, 3600.0)
        assert cfg.fast_window == 60.0 and cfg.slo_window == 3600.0

    def test_windows_sorted_and_deduped(self):
        cfg = SloConfig(windows=(300, 60, 300.0))
        assert cfg.windows == (60.0, 300.0)

    def test_validation(self):
        with pytest.raises(ValueError, match="target"):
            SloConfig(target=1.0)
        with pytest.raises(ValueError, match="target"):
            SloConfig(target=0.0)
        with pytest.raises(ValueError, match="latency_s"):
            SloConfig(latency_s=0)
        with pytest.raises(ValueError, match="windows"):
            SloConfig(windows=())
        with pytest.raises(ValueError, match="windows"):
            SloConfig(windows=(60.0, -1.0))
        with pytest.raises(ValueError, match="alert_burn_rate"):
            SloConfig(alert_burn_rate=0)
        with pytest.raises(ValueError, match="alert_min_samples"):
            SloConfig(alert_min_samples=0)

    def test_from_env_order_defaults_env_overrides(self):
        env = {
            "DDR_SLO_TARGET": "0.95",
            "DDR_SLO_LATENCY_MS": "250",
            "DDR_SLO_WINDOWS": "30,600",
            "DDR_SLO_ALERT_BURN": "6",
            "DDR_SLO_ALERT_MIN_SAMPLES": "3",
        }
        cfg = SloConfig.from_env(env)
        assert cfg.target == 0.95
        assert cfg.latency_s == pytest.approx(0.25)  # ms env -> seconds
        assert cfg.windows == (30.0, 600.0)
        assert cfg.alert_burn_rate == 6.0 and cfg.alert_min_samples == 3
        # explicit kwargs beat the environment
        assert SloConfig.from_env(env, target=0.9).target == 0.9

    def test_from_env_enabled_switch(self):
        assert SloConfig.from_env({"DDR_SLO_ENABLED": "off"}).enabled is False
        assert SloConfig.from_env({"DDR_SLO_ENABLED": "1"}).enabled is True
        assert SloConfig.from_env({}).enabled is True

    def test_from_env_bad_values_raise(self):
        with pytest.raises(ValueError, match="DDR_SLO_TARGET"):
            SloConfig.from_env({"DDR_SLO_TARGET": "ninety-nine"})
        with pytest.raises(ValueError, match="DDR_SLO_WINDOWS"):
            SloConfig.from_env({"DDR_SLO_WINDOWS": "60,abc"})

    def test_window_label_round_trip(self):
        from ddr_tpu.observability.slo import parse_window_label

        assert window_label(60.0) == "60s"
        assert window_label(0.5) == "0.5s"
        assert parse_window_label("60s") == 60.0
        assert parse_window_label("0.5s") == 0.5
        assert parse_window_label("not-a-window") is None


def _tracker(**kw) -> SloTracker:
    kw.setdefault("target", 0.99)
    kw.setdefault("windows", (10.0, 100.0))
    return SloTracker(SloConfig(**kw))


class TestSloTracker:
    def test_empty_tracker_reads_none(self):
        t = _tracker()
        assert t.attainment(now=1000.0) is None
        assert t.burn_rate(10.0, now=1000.0) is None
        assert set(t.burn_rates(now=1000.0)) == {"10s", "100s"}

    def test_attainment_and_burn_per_window(self):
        t = _tracker()
        # 50 old observations, all good; 10 recent, half bad
        for i in range(50):
            t.observe(True, now=1000.0 + i * 0.1)
        for i in range(10):
            t.observe(i % 2 == 0, now=1050.0 + i * 0.1)
        now = 1052.0
        # the 10s window sees only the recent half-bad stretch
        assert t.attainment(10.0, now=now) == pytest.approx(0.5)
        # the 100s window sees everything: 55/60 good
        assert t.attainment(100.0, now=now) == pytest.approx(55 / 60)
        assert t.burn_rate(10.0, now=now) == pytest.approx(0.5 / 0.01)
        rates = t.burn_rates(now=now)
        assert rates["10s"] == pytest.approx(50.0)
        assert rates["100s"] == pytest.approx((5 / 60) / 0.01)

    def test_observe_reports_bucket_rollover(self):
        """observe() returns True exactly when it opens a new time bucket —
        the cadence the serving layer uses to gate its O(buckets) gauge
        mirroring off the per-request path."""
        t = _tracker()
        assert t.observe(True, now=100.0) is True
        # same bucket: no rollover
        assert t.observe(True, now=100.0 + t._bucket_s / 2) is False
        assert t.observe(False, now=100.0 + t._bucket_s * 1.5) is True

    def test_all_good_burns_zero(self):
        t = _tracker()
        for i in range(20):
            t.observe(True, now=500.0 + i)
        assert t.burn_rate(100.0, now=520.0) == 0.0

    def test_memory_is_bounded_by_window(self):
        t = _tracker(windows=(1.0, 10.0))
        for i in range(10_000):
            t.observe(True, now=100.0 + i * 0.01)  # 100s of traffic
        # pruning keeps only ~slo_window/bucket buckets, not 10k entries
        assert len(t._buckets) <= int(10.0 / t._bucket_s) + 2

    def test_status_shape(self):
        t = _tracker(windows=(10.0,))
        t.observe(True, now=100.0)
        t.observe(False, now=100.5)
        s = t.status(now=101.0)
        assert s["target"] == 0.99
        assert s["lifetime"] == {"good": 1, "total": 2, "attainment": 0.5}
        assert s["windows"]["10s"]["total"] == 2
        assert s["windows"]["10s"]["attainment"] == 0.5
        assert s["windows"]["10s"]["burn_rate"] == pytest.approx(50.0)
        assert s["alerting"] is False

    def test_thread_safety_smoke(self):
        t = _tracker()
        errs: list[Exception] = []

        def hammer():
            try:
                for i in range(500):
                    t.observe(i % 3 != 0)
                    t.attainment()
                    t.burn_rates()
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs
        assert t.status()["lifetime"]["total"] == 2000


class TestAlertEdge:
    def test_fires_once_then_resolves_once(self):
        t = _tracker(
            target=0.99, windows=(10.0, 100.0),
            alert_burn_rate=14.0, alert_min_samples=5,
        )
        # 10 bad requests: burn 100x >> 14x
        for i in range(10):
            t.observe(False, now=200.0 + i * 0.1)
        edge = t.check_alert(now=201.5)
        assert edge is not None and edge["state"] == "firing"
        assert edge["window"] == "10s"
        assert edge["burn_rate"] == pytest.approx(100.0)
        assert edge["target"] == 0.99
        assert t.alerting
        # no repeat while still burning
        assert t.check_alert(now=201.6) is None
        # traffic turns good; once the bad stretch ages out, one resolved edge
        for i in range(20):
            t.observe(True, now=215.0 + i * 0.1)
        edge = t.check_alert(now=218.0)
        assert edge is not None and edge["state"] == "resolved"
        assert not t.alerting
        assert t.check_alert(now=218.1) is None

    def test_min_samples_gate(self):
        t = _tracker(windows=(10.0,), alert_min_samples=10)
        for i in range(3):
            t.observe(False, now=300.0 + i * 0.1)  # 100% bad but only 3 samples
        assert t.check_alert(now=301.0) is None
        assert not t.alerting

    def test_empty_window_resolves(self):
        t = _tracker(windows=(10.0,), alert_min_samples=2)
        for i in range(5):
            t.observe(False, now=400.0 + i * 0.1)
        assert t.check_alert(now=401.0)["state"] == "firing"
        # idle long enough that the fast window is empty
        edge = t.check_alert(now=500.0)
        assert edge is not None and edge["state"] == "resolved"
        assert edge["burn_rate"] is None and edge["attainment"] is None


class TestAttainmentFromEvents:
    def _ev(self, wall, status="ok", slo_ok=None):
        e = {"event": "serve_request", "wall": wall, "status": status}
        if slo_ok is not None:
            e["slo_ok"] = slo_ok
        return e

    def test_none_without_usable_events(self):
        assert attainment_from_events([]) is None
        assert attainment_from_events([{"event": "step", "wall": 1.0}]) is None
        # a serve_request without a wall clock can't be windowed
        assert attainment_from_events([{"event": "serve_request"}]) is None

    def test_slo_ok_field_wins_over_status(self):
        # served ok but LATE: slo_ok=False must count as budget spend
        events = [self._ev(100.0, "ok", slo_ok=False), self._ev(100.1, "ok")]
        agg = attainment_from_events(events, windows=(60.0,))
        assert agg["good"] == 1 and agg["total"] == 2
        assert agg["attainment"] == 0.5

    def test_status_fallback_for_pre_tracing_logs(self):
        events = [
            self._ev(100.0, "ok"),
            self._ev(100.1, "shed:deadline"),
            self._ev(100.2, "error:RuntimeError"),
        ]
        agg = attainment_from_events(events, windows=(60.0,))
        assert agg["good"] == 1 and agg["total"] == 3

    def test_windows_trail_last_event(self):
        events = [self._ev(0.0, "shed:queue-full")] + [
            self._ev(1000.0 + i, "ok") for i in range(5)
        ]
        agg = attainment_from_events(events, windows=(30.0, 2000.0), target=0.9)
        assert agg["windows"]["30s"] == {
            "attainment": 1.0, "total": 5, "burn_rate": 0.0,
        }
        w = agg["windows"]["2000s"]
        assert w["total"] == 6 and w["attainment"] == pytest.approx(5 / 6)
        assert w["burn_rate"] == pytest.approx((1 / 6) / 0.1)
        assert agg["target"] == 0.9
        assert agg["burn_rate"] == pytest.approx((1 / 6) / 0.1)

    def test_no_burn_without_target(self):
        agg = attainment_from_events([self._ev(1.0)], windows=(60.0,))
        assert "burn_rate" not in agg
        assert "burn_rate" not in agg["windows"]["60s"]
