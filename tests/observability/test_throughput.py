"""Throughput counter regressions: the zero-duration clamp (no more inf rates
in JSONL aggregation) and the shim's continued API compatibility."""

from __future__ import annotations

import math

import pytest

from ddr_tpu.observability import MIN_BATCH_SECONDS, Throughput


class TestZeroDurationClamp:
    def test_zero_seconds_clamps_finite_with_warning(self, caplog):
        tp = Throughput(label="t")
        with caplog.at_level("WARNING"):
            rate = tp.record(n_reaches=100, n_timesteps=24, seconds=0.0)
        assert math.isfinite(rate) and rate > 0
        assert rate == pytest.approx(100 * 24 / MIN_BATCH_SECONDS)
        assert "clamp" in caplog.text
        assert tp.last_seconds == MIN_BATCH_SECONDS
        assert math.isfinite(tp.rate)

    def test_negative_and_nan_also_clamp(self):
        tp = Throughput()
        assert math.isfinite(tp.record(10, 10, -1.0))
        assert math.isfinite(tp.record(10, 10, float("nan")))
        assert tp.total_seconds == pytest.approx(2 * MIN_BATCH_SECONDS)

    def test_normal_durations_unchanged(self, caplog):
        tp = Throughput()
        with caplog.at_level("WARNING"):
            rate = tp.record(100, 24, 2.0)
        assert rate == pytest.approx(1200.0)
        assert tp.last_seconds == 2.0
        assert "clamp" not in caplog.text

    def test_last_seconds_tracks_batch_context(self):
        import time

        tp = Throughput()
        with tp.batch(10, 10):
            time.sleep(0.005)
        assert tp.last_seconds >= 0.005


class TestProfilingShim:
    def test_shim_reexports(self):
        from ddr_tpu import profiling
        from ddr_tpu.observability import throughput as obs_tp

        assert profiling.Throughput is obs_tp.Throughput
        from ddr_tpu.observability.spans import trace as obs_trace

        assert profiling.trace is obs_trace
        assert callable(profiling.profile_dir_from_env)
