"""Recorder / JSONL event tests: round-trip, envelope invariants,
primary-process-only main-log writes under a faked 2-process layout, heartbeat
payloads, and the run_telemetry lifecycle."""

from __future__ import annotations

import json

import numpy as np
import pytest

from ddr_tpu.observability import (
    EVENT_TYPES,
    Recorder,
    activate,
    deactivate,
    device_memory_stats,
    emit_heartbeat,
    get_recorder,
    run_telemetry,
)


def _read(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


class TestRecorderRoundTrip:
    def test_events_round_trip_with_envelope(self, tmp_path):
        p = tmp_path / "log.jsonl"
        rec = Recorder(p, host=0, n_hosts=1, tags={"run": "x"})
        rec.emit("run_start", name="r")
        rec.emit("step", epoch=1, batch=0, loss=np.float32(1.5), seconds=0.25)
        rec.emit("compile", engine="gspmd", key="abc")
        rec.close()
        events = _read(p)
        assert [e["event"] for e in events] == ["run_start", "step", "compile", "run_end"]
        for e in events:
            assert {"event", "t", "wall", "host", "pid", "seq"} <= set(e)
            assert e["host"] == 0
            assert e["tags"] == {"run": "x"}
        # numpy payloads serialize as plain JSON numbers
        assert events[1]["loss"] == pytest.approx(1.5)
        # seq strictly increasing, t monotone non-decreasing
        assert [e["seq"] for e in events] == list(range(len(events)))
        ts = [e["t"] for e in events]
        assert ts == sorted(ts)

    def test_run_end_carries_summary(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl", host=0)
        rec.emit("step", loss=1.0)
        rec.record_span("train/step", 0.5)
        rec.merge_summary("compile", {"gspmd": {"hits": 3, "misses": 1}})
        rec.close(status="ok")
        end = _read(tmp_path / "log.jsonl")[-1]
        assert end["event"] == "run_end"
        assert end["status"] == "ok"
        assert end["summary"]["events"]["step"] == 1
        assert end["summary"]["spans"]["train/step"]["count"] == 1
        assert end["summary"]["compile"]["gspmd"]["misses"] == 1

    def test_close_is_idempotent_and_emits_nothing_after(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        rec.close()
        rec.close()
        rec.emit("step", loss=1.0)  # dropped, not an error
        assert [e["event"] for e in _read(tmp_path / "log.jsonl")] == ["run_end"]

    def test_unknown_event_type_warns_but_writes(self, tmp_path, caplog):
        rec = Recorder(tmp_path / "log.jsonl")
        with caplog.at_level("WARNING"):
            rec.emit("bogus", x=1)
        assert "bogus" in caplog.text
        assert _read(tmp_path / "log.jsonl")[0]["event"] == "bogus"
        rec.close()

    def test_event_vocabulary_is_closed(self):
        assert set(EVENT_TYPES) == {
            "run_start", "step", "eval", "compile", "heartbeat", "span", "run_end",
            "serve_request", "serve_batch", "serve_shed", "health", "program_card",
            "slo", "fault", "preempt", "chaos", "skill", "drift", "audit", "reshard",
            "tune", "recovery", "data_anomaly", "canary", "verify", "anomaly",
        }


class TestFlushBatching:
    """DDR_METRICS_FLUSH_EVERY: batch flushes for high-rate emitters; close()
    always drains."""

    def test_default_flushes_every_line(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        rec.emit("step", loss=1.0)
        # visible to a concurrent reader immediately (the PR-1 behavior)
        assert len(_read(tmp_path / "log.jsonl")) == 1
        rec.close()

    def test_batched_flush_defers_then_drains(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl", flush_every=3)
        rec.emit("step", loss=1.0)
        rec.emit("step", loss=2.0)
        assert _read(tmp_path / "log.jsonl") == []  # still buffered
        rec.emit("step", loss=3.0)  # third event hits the cadence
        assert len(_read(tmp_path / "log.jsonl")) == 3
        rec.emit("step", loss=4.0)  # buffered again...
        rec.close()  # ...but close flushes regardless (run_end included)
        events = _read(tmp_path / "log.jsonl")
        assert [e["event"] for e in events] == ["step"] * 4 + ["run_end"]

    def test_env_cadence_and_malformed_fallback(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DDR_METRICS_FLUSH_EVERY", "2")
        rec = Recorder(tmp_path / "a.jsonl")
        rec.emit("step", loss=1.0)
        assert _read(tmp_path / "a.jsonl") == []
        rec.emit("step", loss=2.0)
        assert len(_read(tmp_path / "a.jsonl")) == 2
        rec.close()
        monkeypatch.setenv("DDR_METRICS_FLUSH_EVERY", "lots")
        rec2 = Recorder(tmp_path / "b.jsonl")  # falls back to 1, no raise
        rec2.emit("step", loss=1.0)
        assert len(_read(tmp_path / "b.jsonl")) == 1
        rec2.close()


class TestEmitHooks:
    def test_hooks_see_full_record_and_never_break_emit(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        seen = []
        rec.add_hook(seen.append)
        rec.add_hook(seen.append)  # idempotent: same callable installs once

        def boom(record):
            raise RuntimeError("hook bug")

        rec.add_hook(boom)
        rec.emit("step", loss=1.0)
        rec.close()
        # emit survived the raising hook and the good hook saw the envelope
        steps = [r for r in seen if r["event"] == "step"]
        assert len(steps) == 1
        assert steps[0]["loss"] == 1.0 and "seq" in steps[0]
        assert len(_read(tmp_path / "log.jsonl")) == 2  # step + run_end


class TestPrimaryProcessWrites:
    """Main log from the primary process only; other hosts get sidecars."""

    def test_host0_owns_main_log(self, tmp_path):
        rec = Recorder.open_run(tmp_path, cmd="train", host=0, n_hosts=2)
        assert rec.path == tmp_path / "run_log.train.jsonl"
        rec.close()

    def test_secondary_host_writes_sidecar(self, tmp_path):
        rec = Recorder.open_run(tmp_path, cmd="train", host=1, n_hosts=2)
        assert rec.path == tmp_path / "run_log.train.host1.jsonl"
        rec.emit("heartbeat", step=3)
        rec.close()
        # the main log was never touched by the non-primary process
        assert not (tmp_path / "run_log.train.jsonl").exists()
        assert _read(rec.path)[0]["host"] == 1

    def test_faked_two_process_layout_resolves_sidecar(self, tmp_path, monkeypatch):
        """Under a faked jax 2-process layout, the non-primary recorder picks
        its sidecar automatically (via scripts.common.is_primary_process)."""
        import jax

        monkeypatch.setattr(jax, "process_index", lambda: 1)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        rec = Recorder.open_run(tmp_path, cmd="train")
        assert rec.host == 1 and rec.n_hosts == 2
        assert rec.path.name == "run_log.train.host1.jsonl"
        rec.close()
        rec0 = Recorder.open_run(tmp_path, cmd="train", host=0, n_hosts=2)
        assert rec0.path.name == "run_log.train.jsonl"
        rec0.close()


class TestHeartbeat:
    def test_emit_heartbeat_includes_devices(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        emit_heartbeat(rec, epoch=2, batch=5, step=7)
        rec.close()
        hb = _read(tmp_path / "log.jsonl")[0]
        assert hb["event"] == "heartbeat"
        assert hb["step"] == 7
        assert isinstance(hb["devices"], list)

    def test_device_memory_stats_shape(self):
        import jax  # noqa: F401  — ensures the lazy jax path is exercised

        stats = device_memory_stats(max_devices=2)
        assert isinstance(stats, list) and len(stats) <= 2
        for entry in stats:
            assert "id" in entry and "platform" in entry

    def test_device_memory_stats_cpu_backend_partial_no_raise(self):
        """On a CPU backend memory_stats() is unsupported: every local device
        must still yield an id/platform entry, byte fields simply absent."""
        import jax

        stats = device_memory_stats()
        assert stats, "an initialized backend must report its devices"
        assert len(stats) == min(len(jax.local_devices()), 8)
        for entry in stats:
            assert entry["platform"] == jax.local_devices()[0].platform
            assert isinstance(entry["id"], int)
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                assert k not in entry or isinstance(entry[k], int)

    def test_device_memory_stats_without_jax_is_empty(self, monkeypatch):
        import sys

        monkeypatch.setitem(sys.modules, "jax", None)  # "never imported"
        assert device_memory_stats() == []

    def test_device_memory_stats_backend_failure_is_empty(self, monkeypatch):
        import sys

        class _BrokenJax:
            def local_devices(self):
                raise RuntimeError("backend exploded")

        monkeypatch.setitem(sys.modules, "jax", _BrokenJax())
        assert device_memory_stats() == []

    def test_no_active_recorder_is_silent(self):
        deactivate()
        emit_heartbeat(step=1)  # must not raise


class _Params:
    def __init__(self, save_path):
        self.save_path = save_path


class _Cfg:
    def __init__(self, save_path):
        self.name = "telem_run"
        self.mode = "training"
        self.device = "cpu:8"
        self.params = _Params(save_path)
        self.experiment = type("E", (), {"parallel": "auto", "epochs": 2, "batch_size": 4, "warmup": 1})()


class TestRunTelemetry:
    def test_lifecycle_and_activation(self, tmp_path):
        cfg = _Cfg(str(tmp_path))
        assert get_recorder() is None
        with run_telemetry(cfg, "train") as rec:
            assert get_recorder() is rec
            rec.emit("step", loss=0.5)
        assert get_recorder() is None
        events = _read(tmp_path / "run_log.train.jsonl")
        assert [e["event"] for e in events] == ["run_start", "step", "run_end"]
        start = events[0]
        assert start["name"] == "telem_run"
        assert start["parallel"] == "auto" and start["epochs"] == 2
        assert events[-1]["status"] == "ok"

    def test_metrics_dir_env_overrides_save_path(self, tmp_path, monkeypatch):
        override = tmp_path / "elsewhere"
        monkeypatch.setenv("DDR_METRICS_DIR", str(override))
        with run_telemetry(_Cfg(str(tmp_path / "save")), "train"):
            pass
        assert (override / "run_log.train.jsonl").exists()
        assert not (tmp_path / "save").exists()

    def test_exception_recorded_and_reraised(self, tmp_path):
        with pytest.raises(RuntimeError):
            with run_telemetry(_Cfg(str(tmp_path)), "train"):
                raise RuntimeError("boom")
        end = _read(tmp_path / "run_log.train.jsonl")[-1]
        assert end["status"] == "error:RuntimeError"
        assert get_recorder() is None

    def test_interrupt_status(self, tmp_path):
        with pytest.raises(KeyboardInterrupt):
            with run_telemetry(_Cfg(str(tmp_path)), "train"):
                raise KeyboardInterrupt
        assert _read(tmp_path / "run_log.train.jsonl")[-1]["status"] == "interrupted"

    def test_no_dir_no_cfg_disables(self, monkeypatch):
        monkeypatch.delenv("DDR_METRICS_DIR", raising=False)
        with run_telemetry(None, "train") as rec:
            assert rec is None
        assert get_recorder() is None


@pytest.fixture(autouse=True)
def _clean_active_recorder():
    """Never leak an active recorder between tests."""
    yield
    deactivate()
    assert get_recorder() is None
