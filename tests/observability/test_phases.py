"""PhaseTimer / summarize_phases tests: per-step dicts, run totals, shares,
and the Prometheus tee's ddr_phase_seconds histogram."""

from __future__ import annotations

import threading

import pytest

from ddr_tpu.observability.phases import STEP_PHASES, PhaseTimer, summarize_phases
from ddr_tpu.observability.prometheus import event_tee
from ddr_tpu.observability.registry import MetricsRegistry


class TestPhaseTimer:
    def test_per_step_dict_and_totals(self):
        t = PhaseTimer()
        step = {}
        with t.phase("data_load", into=step):
            pass
        with t.phase("device_step", into=step):
            pass
        assert set(step) == {"data_load", "device_step"}
        assert all(v >= 0 for v in step.values())
        totals = t.totals()
        assert totals["data_load"]["count"] == 1
        assert totals["device_step"]["count"] == 1

    def test_repeated_phase_accumulates_into_step_dict(self):
        t = PhaseTimer()
        step = {}
        for _ in range(3):
            with t.phase("eval", into=step):
                pass
        assert t.totals()["eval"]["count"] == 3
        assert len(step) == 1  # one accumulated entry, not three

    def test_exception_safe(self):
        t = PhaseTimer()
        step = {}
        with pytest.raises(ValueError):
            with t.phase("checkpoint", into=step):
                raise ValueError("x")
        assert "checkpoint" in step
        assert t.totals()["checkpoint"]["count"] == 1

    def test_thread_safety(self):
        """The prefetch thread times data_load while the main thread times
        device_step — totals must not lose updates."""
        t = PhaseTimer()

        def worker(name):
            for _ in range(50):
                with t.phase(name):
                    pass

        threads = [threading.Thread(target=worker, args=(n,)) for n in STEP_PHASES]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        totals = t.totals()
        assert all(totals[n]["count"] == 50 for n in STEP_PHASES)

    def test_summary_shares_sum_to_one(self):
        t = PhaseTimer()
        with t.phase("data_load"):
            pass
        with t.phase("device_step"):
            pass
        shares = t.summary()["shares"]
        assert sum(shares.values()) == pytest.approx(1.0, abs=1e-3)


class TestSummarizePhases:
    def test_aggregates_step_events(self):
        events = [
            {"phases": {"device_step": 3.0, "eval": 1.0}},
            {"phases": {"device_step": 1.0}},
            {"no_phases": True},
            {"phases": {"device_step": "bogus"}},  # malformed value dropped
        ]
        agg = summarize_phases(events)
        assert agg["device_step"]["seconds"] == pytest.approx(4.0)
        assert agg["device_step"]["count"] == 2
        assert agg["device_step"]["share"] == pytest.approx(0.8)
        assert agg["eval"]["share"] == pytest.approx(0.2)
        # sorted by total time, biggest first
        assert list(agg) == ["device_step", "eval"]

    def test_empty(self):
        assert summarize_phases([]) == {}

    def test_overlap_entry_from_loop_s(self):
        """Steps carrying ``loop_s`` (schema v5) gain the reserved
        ``_overlap`` rollup — device busy fraction of the loop wall — which
        phase shares alone cannot express."""
        events = [
            {"phases": {"device_step": 0.08, "data_load": 0.05}, "loop_s": 0.1},
            {"phases": {"device_step": 0.02}, "loop_s": 0.1},
            {"phases": {"device_step": 1.0}},  # no loop_s: excluded from overlap
        ]
        agg = summarize_phases(events)
        ov = agg["_overlap"]
        assert ov["count"] == 2
        assert ov["loop_s"] == pytest.approx(0.2)
        assert ov["device_s"] == pytest.approx(0.1)
        assert ov["busy_frac"] == pytest.approx(0.5)
        assert ov["idle_s"] == pytest.approx(0.1)
        # phase rows are unaffected by the reserved entry
        assert agg["device_step"]["count"] == 3

    def test_no_overlap_entry_without_loop_s(self):
        agg = summarize_phases([{"phases": {"device_step": 1.0}}])
        assert "_overlap" not in agg


class TestPrometheusTee:
    def test_step_phases_feed_histogram(self):
        r = MetricsRegistry()
        event_tee(
            {"event": "step", "engine": "single", "seconds": 1.0,
             "phases": {"device_step": 0.9, "eval": 0.1, "bad": None}},
            r,
        )
        hist = r.get("ddr_phase_seconds")
        assert hist is not None
        series = hist.series()
        assert ("device_step",) in series
        assert series[("device_step",)]["count"] == 1
        assert ("eval",) in series
        assert ("bad",) not in series  # unparseable values are skipped

    def test_step_without_phases_declares_nothing(self):
        r = MetricsRegistry()
        event_tee({"event": "step", "engine": "single", "seconds": 1.0}, r)
        assert r.get("ddr_phase_seconds") is None
