"""Recovery supervisor + forcing validator: the escalation-ladder matrix,
bounded budgets, the two-phase decide/record protocol, and the data-side
policy machine — the host-side contracts self-healing training rests on."""

from __future__ import annotations

import json

import numpy as np
import pytest

from ddr_tpu.observability.events import Recorder, activate, deactivate
from ddr_tpu.observability.recovery import (
    RECOVERY_STAGES,
    REROUTE_REASONS,
    ForcingValidator,
    RecoveryConfig,
    RecoveryGiveUp,
    RecoverySupervisor,
)


def _sup(**overrides) -> RecoverySupervisor:
    return RecoverySupervisor(RecoveryConfig(enabled=True, **overrides))


class TestRecoveryConfig:
    def test_defaults_are_off(self):
        """Recovery snapshots state before every step — a deliberate opt-in,
        never ambient."""
        assert RecoveryConfig().enabled is False
        assert RecoveryConfig.from_env(environ={}).enabled is False

    def test_from_env_reads_every_knob(self):
        cfg = RecoveryConfig.from_env(environ={
            "DDR_RECOVERY_ENABLED": "1",
            "DDR_RECOVERY_MAX_SKIPS": "7",
            "DDR_RECOVERY_MAX_REROUTES": "5",
            "DDR_RECOVERY_MAX_ROLLBACKS": "2",
            "DDR_RECOVERY_LR_BACKOFF": "0.25",
        })
        assert cfg == RecoveryConfig(
            enabled=True, max_skips=7, max_reroutes=5, max_rollbacks=2,
            lr_backoff=0.25,
        )

    def test_overrides_beat_env(self):
        cfg = RecoveryConfig.from_env(
            environ={"DDR_RECOVERY_MAX_SKIPS": "7"}, max_skips=1
        )
        assert cfg.max_skips == 1

    @pytest.mark.parametrize(
        "kwargs",
        [
            {"max_skips": -1},
            {"max_reroutes": -1},
            {"max_rollbacks": -1},
            {"lr_backoff": 0.0},
            {"lr_backoff": 1.5},
        ],
    )
    def test_bad_values_raise(self, kwargs):
        with pytest.raises(ValueError):
            RecoveryConfig(**kwargs)

    def test_bad_env_value_raises_with_var_name(self):
        with pytest.raises(ValueError, match="DDR_RECOVERY_MAX_SKIPS"):
            RecoveryConfig.from_env(environ={"DDR_RECOVERY_MAX_SKIPS": "many"})


class TestLadderOrder:
    """decide() walks DOWN the ladder, never up, and each rung has a gate."""

    def test_bf16_reasons_reroute_first(self):
        sup = _sup()
        for reason in REROUTE_REASONS:
            assert sup.decide([reason], fp32_available=True) == "fp32-reroute"
        assert sup.decide(list(REROUTE_REASONS), fp32_available=True) == "fp32-reroute"

    def test_mixed_reasons_never_reroute(self):
        """A batch that is ALSO non-finite has poisoned state — re-running it
        in fp32 reproduces the poison, so the ladder goes straight to skip."""
        sup = _sup()
        assert sup.decide(
            ["bf16-overflow", "non-finite"], fp32_available=True
        ) == "skip"

    def test_no_fp32_twin_means_no_reroute(self):
        sup = _sup()
        assert sup.decide(["bf16-overflow"], fp32_available=False) == "skip"

    def test_skip_budget_exhausted_falls_to_rollback(self):
        sup = _sup(max_skips=1)
        assert sup.decide(["non-finite"]) == "skip"
        sup.record("skip", ["non-finite"], epoch=1, batch=0)
        assert sup.decide(["non-finite"], rollback_available=True) == "rollback"

    def test_rollback_needs_a_pinned_checkpoint(self):
        sup = _sup(max_skips=0)
        assert sup.decide(["non-finite"], rollback_available=False) == "give-up"

    def test_full_escalation_sequence(self):
        """The whole ladder, one violating batch at a time: reroute x2,
        skip x1, rollback x1, then give-up — each committed stage closes
        its own rung."""
        sup = _sup(max_skips=1, max_reroutes=2, max_rollbacks=1)
        seen = []
        for _ in range(5):
            stage = sup.decide(
                ["bf16-overflow"], fp32_available=True, rollback_available=True
            )
            seen.append(stage)
            sup.record(stage, ["bf16-overflow"], epoch=1, batch=len(seen))
        assert seen == ["fp32-reroute", "fp32-reroute", "skip", "rollback", "give-up"]
        assert seen[-1] == RECOVERY_STAGES[-1]

    def test_decide_is_pure(self):
        """decide() spends nothing — only record() commits a budget."""
        sup = _sup(max_skips=1)
        for _ in range(5):
            assert sup.decide(["non-finite"]) == "skip"
        assert sup.count("skip") == 0


class TestRecord:
    def test_unknown_stage_raises(self):
        with pytest.raises(ValueError):
            _sup().record("retry-harder", ["non-finite"])

    def test_skip_quarantines_batch_identity(self):
        sup = _sup()
        sup.record("skip", ["non-finite"], epoch=2, batch=5, step=13)
        assert sup.summary()["quarantined"] == [{"epoch": 2, "batch": 5}]

    def test_quarantine_ledger_is_bounded(self):
        sup = _sup(max_skips=10_000)
        for i in range(RecoverySupervisor.MAX_QUARANTINE + 10):
            sup.record("skip", ["non-finite"], epoch=1, batch=i)
        assert len(sup.summary()["quarantined"]) == RecoverySupervisor.MAX_QUARANTINE
        assert sup.count("skip") == RecoverySupervisor.MAX_QUARANTINE + 10

    def test_emits_recovery_event(self, tmp_path):
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        try:
            _sup().record(
                "rollback", ["grad-norm"], epoch=3, batch=1,
                checkpoint="chaos-pinned", lr_backoff=0.5,
            )
        finally:
            deactivate(rec)
            rec.close()
        events = [json.loads(ln) for ln in
                  (tmp_path / "log.jsonl").read_text().splitlines()]
        (ev,) = [e for e in events if e["event"] == "recovery"]
        assert ev["stage"] == "rollback"
        assert ev["reasons"] == ["grad-norm"]
        assert ev["checkpoint"] == "chaos-pinned"
        assert ev["lr_backoff"] == 0.5

    def test_recoveries_totals_and_summary(self):
        sup = _sup()
        sup.record("skip", ["non-finite"], epoch=1, batch=0)
        sup.record("fp32-reroute", ["ulp-drift"], epoch=1, batch=1)
        assert sup.recoveries == 2
        assert sup.summary()["counts"]["skip"] == 1
        assert sup.summary()["enabled"] is True

    def test_give_up_is_a_distinct_type(self):
        """Callers must be able to tell a deliberate state-preserving stop
        from a crash (the CLI maps it to its own exit code)."""
        assert issubclass(RecoveryGiveUp, RuntimeError)
        assert RecoveryGiveUp is not RuntimeError


class TestForcingValidator:
    def test_off_policy_scans_nothing(self):
        v = ForcingValidator("off")
        assert not v.enabled
        assert v.scan(np.full(8, np.nan)) is None

    def test_env_policy_and_typo_rejected(self, monkeypatch):
        monkeypatch.setenv("DDR_DATA_VALIDATE", "warn")
        assert ForcingValidator().policy == "warn"
        with pytest.raises(ValueError, match="DDR_DATA_VALIDATE"):
            ForcingValidator("quarantine-ish")

    def test_clean_batch_is_none(self):
        v = ForcingValidator("warn")
        assert v.scan(np.ones((4, 6), dtype=np.float32)) is None
        assert v.summary()["batches"] == 1
        assert v.summary()["anomalies"] == 0

    def test_nonfinite_and_range_counted_separately(self):
        v = ForcingValidator("warn")
        q = np.ones(10, dtype=np.float32)
        q[0] = np.nan
        q[1] = np.inf  # counts as non-finite, NOT out-of-range
        q[2] = -5.0  # below MIN_RUNOFF
        q[3] = 1e9  # above MAX_RUNOFF
        anomaly = v.scan(q, epoch=1, batch=4)
        assert anomaly["nonfinite"] == 2
        assert anomaly["out_of_range"] == 2
        assert anomaly["size"] == 10
        assert anomaly["batch"] == 4

    def test_note_returns_policy_verdict(self):
        warn, quarantine = ForcingValidator("warn"), ForcingValidator("quarantine")
        a = {"nonfinite": 1, "out_of_range": 0, "size": 4, "policy": "warn"}
        assert warn.note(a) == "warn"
        assert quarantine.note(dict(a, policy="quarantine")) == "quarantine"
        assert quarantine.summary()["quarantined"] == 1
        assert warn.summary()["quarantined"] == 0

    def test_events_are_bounded(self, tmp_path):
        """MAX_EVENTS data_anomaly emissions, then suppression — the rollup
        still counts every finding."""
        rec = Recorder(tmp_path / "log.jsonl")
        activate(rec)
        v = ForcingValidator("warn")
        try:
            for i in range(ForcingValidator.MAX_EVENTS + 5):
                v.note({"nonfinite": 1, "out_of_range": 0, "size": 4,
                        "policy": "warn", "batch": i})
        finally:
            deactivate(rec)
            rec.close()
        events = [json.loads(ln) for ln in
                  (tmp_path / "log.jsonl").read_text().splitlines()]
        assert (
            len([e for e in events if e["event"] == "data_anomaly"])
            == ForcingValidator.MAX_EVENTS
        )
        assert v.summary()["events_suppressed"] == 5
