"""End-to-end acceptance: a CPU-mesh parallel `ddr train` dry-run writes a run
log with run_start / step (finite rate) / compile (topology hash) / heartbeat /
run_end; `ddr metrics summarize` renders it; a repeated-topology second epoch
recompiles nothing on the LRU-cached engines."""

from __future__ import annotations

import json
import math

import jax
import pytest

from ddr_tpu.observability import run_telemetry
from ddr_tpu.validation.configs import Config

N_DEV = 8


def _cfg(tmp_path, **exp):
    return Config(
        name="telem_e2e",
        geodataset="synthetic",
        mode="training",
        device=f"cpu:{N_DEV}",
        kan={"input_var_names": [f"a{i}" for i in range(10)]},
        experiment={
            "start_time": "1981/10/01",
            "end_time": "1981/10/20",
            "rho": 8,
            "batch_size": 2,
            "epochs": 2,
            "warmup": 1,
            "learning_rate": {1: 0.01},
            "shuffle": False,  # identical batches across epochs: epoch 2 must be all cache hits
            **exp,
        },
        params={"save_path": str(tmp_path)},
    )


@pytest.mark.slow
def test_train_dry_run_produces_complete_run_log(tmp_path, monkeypatch):
    from ddr_tpu.scripts.train import train

    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    monkeypatch.setenv("DDR_HEARTBEAT_EVERY", "1")
    # gspmd: the one engine exercising the shared-jit compile-cache tracking
    # (the shard_map engines' LRU tracking is pinned in test_recompile.py)
    cfg = _cfg(tmp_path, parallel="gspmd")
    with run_telemetry(cfg, "train"):
        train(cfg, max_batches=4)  # 2 epochs x 2 batches, same topologies

    log_path = tmp_path / "run_log.train.jsonl"
    events = [json.loads(line) for line in log_path.read_text().splitlines()]
    by_type: dict[str, list] = {}
    for e in events:
        by_type.setdefault(e["event"], []).append(e)

    assert len(by_type["run_start"]) == 1
    steps = by_type["step"]
    assert len(steps) == 4
    for s in steps:
        assert math.isfinite(float(s["reach_timesteps_per_sec"]))
        assert s["engine"] == "gspmd"
    compiles = by_type["compile"]
    assert len(compiles) >= 1
    # every compile event names the batch topology (sha1 hex)
    assert all(isinstance(c["key"], str) and len(c["key"]) == 40 for c in compiles)
    assert by_type["heartbeat"], "heartbeats missing"
    end = by_type["run_end"][-1]
    assert end["status"] == "ok"

    # Repeated-topology epoch 2 (shuffle=False): ZERO recompiles — all misses
    # land in epoch 1 (≤ 2 batches), and every epoch-2 step is a cache hit.
    # Tracing is ON here (DDR_TRACE defaults on), so this same bound is the
    # zero-new-jit-cache-entries proof for trace propagation.
    compile_summary = end["summary"]["compile"]["gspmd"]
    assert compile_summary["misses"] == len(compiles) <= 2
    assert compile_summary["hits"] == len(steps) - compile_summary["misses"] >= 2

    # Trace propagation: every step is its own trace root with deterministic
    # ids (any host of this run would stamp the same), and the phase spans
    # emitted inside the step link back to it as children.
    from ddr_tpu.observability.trace import run_trace_seed, step_context

    seed = run_trace_seed(cfg)
    step_ids = set()
    for s in steps:
        want = step_context(seed, f"{s['epoch']}:{s['batch']}")
        assert s["trace_id"] == want.trace_id and s["span_id"] == want.span_id
        assert "parent_id" not in s  # the step IS the trace root
        step_ids.add(s["trace_id"])
    assert len(step_ids) == 4
    child_spans = [
        e for e in by_type.get("span", []) if e.get("trace_id") in step_ids
    ]
    assert child_spans, "no phase spans joined their step's trace"
    # every child's parent resolves within its own trace (root or sibling)
    known: dict[str, set] = {s["trace_id"]: {s["span_id"]} for s in steps}
    for c in child_spans:
        known[c["trace_id"]].add(c["span_id"])
    assert all(c["parent_id"] in known[c["trace_id"]] for c in child_spans)

    # And the CLI renders it without error.
    from ddr_tpu.observability.metrics_cli import main as metrics_main

    assert metrics_main(["summarize", str(log_path)]) == 0
    assert metrics_main(["tail", str(log_path)]) == 0


@pytest.mark.slow
def test_eval_events_from_test_pipeline(tmp_path):
    """`ddr test`-path evaluation emits eval events with finite rates."""
    from ddr_tpu.scripts.test import test as run_test

    cfg = _cfg(tmp_path, parallel="none")
    cfg.mode = "testing"
    with run_telemetry(cfg, "test"):
        run_test(cfg)
    events = [
        json.loads(line)
        for line in (tmp_path / "run_log.test.jsonl").read_text().splitlines()
    ]
    evals = [e for e in events if e["event"] == "eval"]
    assert evals
    assert all(math.isfinite(float(e["reach_timesteps_per_sec"])) for e in evals)
    assert events[-1]["event"] == "run_end" and events[-1]["status"] == "ok"
