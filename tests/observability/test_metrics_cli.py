"""``ddr metrics`` CLI tests: summarize/tail on a golden run log, multi-host
directory merging, corrupt-line tolerance, and help/exit-code smoke checks
(incl. ``bench.py --help``)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from ddr_tpu.observability import metrics_dir_from_env
from ddr_tpu.observability.metrics_cli import load_events, main

REPO = Path(__file__).resolve().parents[2]


def _write_golden(path: Path) -> Path:
    """A small but complete run log: every event type, two engines, a loss
    curve, heartbeats from two hosts (sidecar merged separately)."""
    events = [
        {"event": "run_start", "t": 0.0, "wall": 100.0, "host": 0, "pid": 1, "seq": 0,
         "cmd": "train", "name": "golden", "device": "cpu:8", "parallel": "auto",
         "epochs": 2, "n_hosts": 1},
        {"event": "compile", "t": 0.5, "wall": 100.5, "host": 0, "pid": 1, "seq": 1,
         "engine": "stacked-sharded", "key": "aaa111", "build_seconds": 1.5,
         "cache_entries": 1, "hits": 0, "misses": 1},
        {"event": "span", "t": 0.6, "wall": 100.6, "host": 0, "pid": 1, "seq": 2,
         "name": "prepare", "seconds": 0.4},
        {"event": "heartbeat", "t": 1.2, "wall": 101.2, "host": 0, "pid": 1, "seq": 3,
         "step": 1, "devices": [{"id": 0, "platform": "cpu"}]},
        {"event": "run_end", "t": 9.0, "wall": 109.0, "host": 0, "pid": 1, "seq": 100,
         "status": "ok", "duration_s": 9.0,
         "summary": {"events": {"step": 4}, "spans": {},
                     "compile": {"stacked-sharded": {"hits": 3, "misses": 1,
                                                     "build_seconds": 1.5}}}},
    ]
    for i in range(4):
        events.insert(3 + i, {
            "event": "step", "t": 1.0 + i, "wall": 101.0 + i, "host": 0, "pid": 1,
            "seq": 4 + i, "epoch": 1 + i // 2, "batch": i % 2,
            "loss": 2.0 / (i + 1), "n_reaches": 33, "n_timesteps": 96,
            "seconds": 0.5, "reach_timesteps_per_sec": 6336.0,
            "engine": "stacked-sharded",
        })
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    return path


class TestLoadEvents:
    def test_single_file(self, tmp_path):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        events, bad = load_events(p)
        assert bad == 0 and len(events) == 9

    def test_corrupt_lines_skipped(self, tmp_path):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        with p.open("a") as f:
            f.write('{"event": "step", "t":\n')  # killed mid-write
            f.write("not json at all\n")
        events, bad = load_events(p)
        assert bad == 2 and len(events) == 9

    def test_directory_merges_host_sidecars(self, tmp_path):
        _write_golden(tmp_path / "run_log.train.jsonl")
        sidecar = {"event": "heartbeat", "t": 1.3, "wall": 101.3, "host": 1,
                   "pid": 2, "seq": 0, "step": 1, "devices": []}
        (tmp_path / "run_log.train.host1.jsonl").write_text(json.dumps(sidecar) + "\n")
        events, _ = load_events(tmp_path)
        assert len(events) == 10
        assert {e.get("host") for e in events} == {0, 1}
        walls = [e["wall"] for e in events]
        assert walls == sorted(walls)  # merged in wall order

    def test_directory_with_corrupt_lines_in_main_and_sidecar(self, tmp_path):
        """The multi-host crash case in one read: a directory whose main log
        AND host sidecars both carry torn/garbage lines must merge the valid
        events in wall order and count every corrupt line, never raise."""
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        with p.open("a") as f:
            f.write('{"event": "step", "t":\n')  # main log torn mid-write
        sidecar = tmp_path / "run_log.train.host1.jsonl"
        good = {"event": "heartbeat", "t": 1.3, "wall": 101.3, "host": 1,
                "pid": 2, "seq": 0, "step": 1, "devices": []}
        sidecar.write_text(
            json.dumps(good) + "\n"
            + "garbage not json\n"
            + '["a", "json", "array", "not", "an", "event"]\n'
            + "\n"  # blank lines are skipped silently, not corrupt
        )
        events, bad = load_events(tmp_path)
        assert bad == 3
        assert len(events) == 10  # 9 golden + the sidecar heartbeat
        assert {e.get("host") for e in events} == {0, 1}
        walls = [e["wall"] for e in events]
        assert walls == sorted(walls)

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_events(tmp_path / "nope.jsonl")
        with pytest.raises(FileNotFoundError):
            load_events(tmp_path)  # empty dir: no .jsonl inside


class TestSummarize:
    def test_golden_log_renders(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "name=golden" in out
        assert "status   : ok" in out
        assert "steps    : 4" in out
        assert "reach-timesteps/s" in out
        assert "stacked-sharded" in out
        # hits come from the run_end summary rollup
        assert "loss" in out and "0.5" in out
        assert "heartbeats" in out
        assert "prepare" in out  # span table

    def test_health_section_renders(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        with p.open("a") as f:
            for i, consec in enumerate((1, 2)):
                f.write(json.dumps({
                    "event": "health", "t": 2.0 + i, "wall": 102.0 + i,
                    "host": 0, "pid": 1, "seq": 50 + i,
                    "reasons": ["non-finite"], "nonfinite": 3 + i,
                    "q_min": 1e-4, "q_max": 125.0, "mass_residual": 2.5,
                    "grad_norm": 7.0, "consecutive": consec,
                }) + "\n")
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "health   : 2 violating batches — non-finite 2" in out
        assert "worst nonfinite 4" in out
        assert "max discharge 125" in out
        assert "max grad-norm 7" in out
        assert "last consecutive run 2" in out

    def test_no_health_section_without_events(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        assert main(["summarize", str(p)]) == 0
        assert "health   :" not in capsys.readouterr().out

    def test_where_time_went_renders(self, tmp_path, capsys):
        """Step events carrying `phases` dicts aggregate into the per-phase
        percentage table (biggest bucket first)."""
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        events = [json.loads(l) for l in p.read_text().splitlines()]
        for e in events:
            if e["event"] == "step":
                e["phases"] = {"device_step": 0.4, "eval": 0.1}
        p.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "where time went" in out
        assert "device_step  80.0%" in out
        assert "eval         20.0%" in out

    def test_no_phase_section_without_phase_dicts(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        assert main(["summarize", str(p)]) == 0
        assert "where time went" not in capsys.readouterr().out

    def test_overlap_line_renders_with_loop_s(self, tmp_path, capsys):
        """Steps carrying `loop_s` (schema v5) add the overlap-efficiency
        line under the phase table."""
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        events = [json.loads(l) for l in p.read_text().splitlines()]
        for e in events:
            if e["event"] == "step":
                e["phases"] = {"device_step": 0.4, "eval": 0.1}
                e["loop_s"] = 0.5
        p.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "overlap  : device busy 80.0% of loop wall" in out
        assert "_overlap" not in out  # reserved key never rendered as a phase

    def test_anomaly_section_and_pipeline_verdict_render(self, tmp_path, capsys):
        """`anomaly` events tabulate (signal/state/baseline→observed/onset)
        and the run_end summary's sentinel rollup prints the pipeline
        verdict + recommendations."""
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        events = [json.loads(l) for l in p.read_text().splitlines()]
        for e in events:
            if e["event"] == "run_end":
                e.setdefault("summary", {})["pipeline"] = {
                    "steps": 4, "classes": {"data_bound": 3, "device_bound": 1},
                    "verdict": "data_bound",
                    "overlap": {"steps": 4, "loop_s": 2.0, "device_s": 0.5,
                                "busy_frac": 0.25, "idle_s": 1.5},
                    "recommendations": ["raise experiment.prefetch_ahead"],
                }
        p.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        with p.open("a") as f:
            f.write(json.dumps({
                "event": "anomaly", "t": 2.0, "wall": 102.0, "host": 0,
                "pid": 1, "seq": 60, "signal": "data_load", "scope": "train",
                "state": "firing", "side": "high", "baseline": 0.01,
                "observed": 0.21, "sigma": 0.002, "onset_step": 12,
                "step": 14, "episodes": 1,
            }) + "\n")
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "anomalies: 1 episode(s), 1 transition(s)" in out
        assert "data_load" in out and "firing" in out
        assert "pipeline verdict: data_bound  (data_bound=3  device_bound=1)" in out
        assert "device busy 25.0% of loop wall" in out
        assert "- raise experiment.prefetch_ahead" in out

    def test_no_anomaly_section_without_events(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "anomalies:" not in out
        assert "pipeline verdict" not in out

    def test_program_cost_table_renders(self, tmp_path, capsys):
        """program_card events render one row per distinct program; a re-emit
        for the same (name, engine, key) doesn't duplicate the row."""
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        card = {
            "event": "program_card", "t": 0.7, "wall": 100.7, "host": 0,
            "pid": 1, "seq": 40, "name": "train-step", "engine": "stacked-sharded",
            "key": "aaa111", "flops": 1.5e9, "bytes_accessed": 3.0e8,
            "arithmetic_intensity": 5.0, "peak_bytes": 512 * 2**20,
            "n_collectives": 4, "collectives": {"all-reduce": 4},
            "compile_seconds": 12.5,
        }
        with p.open("a") as f:
            f.write(json.dumps(card) + "\n")
            f.write(json.dumps({**card, "seq": 41}) + "\n")  # re-emit, same program
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "programs : 2 card events, 1 distinct programs" in out
        assert "train-step" in out
        assert "aaa111" in out  # topology-key short form distinguishes programs
        assert "512.0" in out  # peak MB
        assert "12.50" in out  # compile_s

    def test_multi_host_dir(self, tmp_path, capsys):
        _write_golden(tmp_path / "run_log.train.jsonl")
        (tmp_path / "run_log.train.host1.jsonl").write_text(
            json.dumps({"event": "heartbeat", "t": 1.0, "wall": 101.0, "host": 1,
                        "pid": 2, "seq": 0, "step": 1, "devices": []}) + "\n"
        )
        assert main(["summarize", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "hosts: 2" in out
        assert "host1" in out


class TestTail:
    def test_tail_last_n(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        assert main(["tail", str(p), "-n", "2"]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 2
        assert "run_end" in lines[-1]
        assert "status=ok" in lines[-1]


class TestFollow:
    """`ddr metrics tail --follow`: the poll loop is driven deterministically
    by monkeypatching its sleep to mutate the log between polls."""

    def _run_follow(self, monkeypatch, path, actions, n=20, max_polls=None):
        """Run follow() with `actions[i]` executed at the i-th poll sleep."""
        import io

        from ddr_tpu.observability import metrics_cli

        calls = [0]

        def scripted_sleep(_secs):
            i = calls[0]
            calls[0] += 1
            if i < len(actions):
                actions[i]()

        monkeypatch.setattr(metrics_cli.time, "sleep", scripted_sleep)
        out = io.StringIO()
        rc = metrics_cli.follow(
            path, n=n, interval=0.0, out=out,
            max_polls=len(actions) if max_polls is None else max_polls,
        )
        return rc, out.getvalue()

    def _append(self, path, *lines):
        def do():
            with path.open("a") as f:
                for ln in lines:
                    f.write(ln)
        return do

    def test_prints_existing_then_new_events(self, tmp_path, monkeypatch):
        p = _write_golden(tmp_path / "run_log.serve.jsonl")
        new = {"event": "serve_request", "t": 10.0, "wall": 110.0, "host": 0,
               "pid": 1, "seq": 101, "status": "ok", "latency_s": 0.02}
        rc, out = self._run_follow(
            monkeypatch, p,
            [self._append(p, json.dumps(new) + "\n"), lambda: None],
        )
        assert rc == 0
        lines = out.strip().splitlines()
        assert "run_end" in lines[-2]  # the existing tail came first
        assert "serve_request" in lines[-1] and "status=ok" in lines[-1]

    def test_corrupt_and_blank_lines_skipped(self, tmp_path, monkeypatch):
        p = _write_golden(tmp_path / "run_log.serve.jsonl")
        good = {"event": "heartbeat", "t": 11.0, "wall": 111.0, "host": 0,
                "pid": 1, "seq": 102, "step": 9, "devices": []}
        rc, out = self._run_follow(
            monkeypatch, p,
            [self._append(
                p, "garbage not json\n", "\n", json.dumps(good) + "\n"
            )],
        )
        assert rc == 0
        assert "garbage" not in out
        assert "heartbeat" in out.strip().splitlines()[-1]

    def test_partial_line_waits_for_its_newline(self, tmp_path, monkeypatch):
        """A torn write renders once completed — exactly once, never as two
        half events."""
        p = _write_golden(tmp_path / "run_log.serve.jsonl")
        ev = json.dumps({"event": "serve_request", "t": 12.0, "wall": 112.0,
                         "host": 0, "pid": 1, "seq": 103, "status": "ok"})
        rc, out = self._run_follow(
            monkeypatch, p,
            [self._append(p, ev[:20]), self._append(p, ev[20:] + "\n")],
        )
        assert rc == 0
        assert out.count("serve_request") == 1

    def test_truncation_restarts_from_top(self, tmp_path, monkeypatch):
        p = _write_golden(tmp_path / "run_log.serve.jsonl")
        fresh = {"event": "run_start", "t": 0.0, "wall": 200.0, "host": 0,
                 "pid": 2, "seq": 0, "cmd": "serve", "name": "second-run"}

        def recreate():
            p.write_text(json.dumps(fresh) + "\n")

        rc, out = self._run_follow(monkeypatch, p, [recreate])
        assert rc == 0
        # the recreated file's content is the new run, from its first byte
        assert "second-run" in out.strip().splitlines()[-1]

    def test_recreation_to_a_larger_file_restarts_from_top(
        self, tmp_path, monkeypatch
    ):
        """A new run reusing the log name can outgrow the old read offset
        between polls — recreation is detected by inode, not size."""
        p = tmp_path / "run_log.serve.jsonl"
        p.write_text(json.dumps(
            {"event": "run_start", "t": 0.0, "wall": 100.0, "host": 0,
             "pid": 1, "seq": 0, "cmd": "serve", "name": "first"}) + "\n")

        def recreate_bigger():
            p.unlink()  # new inode
            events = [{"event": "run_start", "t": 0.0, "wall": 200.0,
                       "host": 0, "pid": 2, "seq": 0, "cmd": "serve",
                       "name": "second-bigger"}]
            events += [{"event": "heartbeat", "t": 1.0 + i, "wall": 201.0 + i,
                        "host": 0, "pid": 2, "seq": 1 + i, "step": i,
                        "devices": []} for i in range(8)]
            p.write_text("\n".join(json.dumps(e) for e in events) + "\n")

        rc, out = self._run_follow(monkeypatch, p, [recreate_bigger])
        assert rc == 0
        # the new run's FIRST event (before the old offset) was not skipped
        assert "name=second-bigger" in out
        assert out.count("heartbeat") == 8

    def test_directory_interleaves_host_sidecars(self, tmp_path, monkeypatch):
        """Following a run DIRECTORY merges the primary log and every
        per-host sidecar live, host<K>-prefixed, in wall-clock order."""
        prim = tmp_path / "run_log.train.jsonl"
        prim.write_text(json.dumps(
            {"event": "run_start", "t": 0.0, "wall": 100.0, "host": 0,
             "pid": 1, "seq": 0, "cmd": "train", "name": "fleet"}) + "\n")
        side = tmp_path / "run_log.train.host1.jsonl"
        side.write_text(json.dumps(
            {"event": "heartbeat", "t": 0.5, "wall": 100.5, "host": 1,
             "pid": 2, "seq": 0, "step": 0, "devices": []}) + "\n")
        later = {"event": "heartbeat", "t": 2.0, "wall": 102.0, "host": 1,
                 "pid": 2, "seq": 1, "step": 1, "devices": []}
        earlier = {"event": "step", "t": 1.5, "wall": 101.5, "host": 0,
                   "pid": 1, "seq": 1, "i": 0, "seconds": 0.1}

        def append_both():
            # written sidecar-first: the printed order must follow wall, not
            # file enumeration
            with side.open("a") as f:
                f.write(json.dumps(later) + "\n")
            with prim.open("a") as f:
                f.write(json.dumps(earlier) + "\n")

        rc, out = self._run_follow(monkeypatch, tmp_path, [append_both])
        assert rc == 0
        lines = out.strip().splitlines()
        assert "following" in lines[0]
        assert any(ln.startswith("host0| ") and "run_start" in ln for ln in lines)
        assert any(ln.startswith("host1| ") and "heartbeat" in ln for ln in lines)
        # wall order across files: host0's t=1.5 step before host1's t=2.0 beat
        assert lines[-2].startswith("host0| ") and "step" in lines[-2]
        assert lines[-1].startswith("host1| ") and "step=1" in lines[-1]

    def test_directory_picks_up_sidecar_created_mid_run(
        self, tmp_path, monkeypatch
    ):
        prim = tmp_path / "run_log.train.jsonl"
        prim.write_text(json.dumps(
            {"event": "run_start", "t": 0.0, "wall": 100.0, "host": 0,
             "pid": 1, "seq": 0, "cmd": "train", "name": "fleet"}) + "\n")
        side = tmp_path / "run_log.train.host3.jsonl"

        def create_sidecar():
            side.write_text(json.dumps(
                {"event": "heartbeat", "t": 1.0, "wall": 101.0, "host": 3,
                 "pid": 2, "seq": 0, "step": 0, "devices": []}) + "\n")

        rc, out = self._run_follow(
            monkeypatch, tmp_path, [create_sidecar, lambda: None]
        )
        assert rc == 0
        # the new sidecar's FIRST event is printed, from its first byte
        assert any(
            ln.startswith("host3| ") and "heartbeat" in ln
            for ln in out.strip().splitlines()
        )

    def test_ctrl_c_exits_zero(self, tmp_path, monkeypatch):
        p = _write_golden(tmp_path / "run_log.serve.jsonl")

        def interrupt():
            raise KeyboardInterrupt

        rc, _ = self._run_follow(monkeypatch, p, [interrupt], max_polls=99)
        assert rc == 0

    def test_cli_wiring_and_missing_file(self, tmp_path):
        assert main(["tail", str(tmp_path / "nope.jsonl"), "--follow"]) == 1
        assert main(["tail", "--help"]) == 0  # --follow/-i documented


class TestSloSummarize:
    def _append_serve(self, path, n_ok=3, n_bad=1, slo_events=False):
        with path.open("a") as f:
            seq = 200
            for i in range(n_ok):
                f.write(json.dumps({
                    "event": "serve_request", "t": 5.0 + i, "wall": 105.0 + i,
                    "host": 0, "pid": 1, "seq": seq, "status": "ok",
                    "latency_s": 0.02, "queue_s": 0.004, "execute_s": 0.012,
                    "slo_ok": True}) + "\n")
                seq += 1
            for i in range(n_bad):
                f.write(json.dumps({
                    "event": "serve_request", "t": 8.0 + i, "wall": 108.0 + i,
                    "host": 0, "pid": 1, "seq": seq,
                    "status": "shed:deadline", "latency_s": 0.5,
                    "queue_s": 0.5, "slo_ok": False}) + "\n")
                seq += 1
            if slo_events:
                f.write(json.dumps({
                    "event": "slo", "t": 8.5, "wall": 108.5, "host": 0,
                    "pid": 1, "seq": seq, "state": "firing", "window": "60s",
                    "burn_rate": 25.0, "attainment": 0.75,
                    "target": 0.99}) + "\n")

    def test_slo_section_renders_attainment(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.serve.jsonl")
        self._append_serve(p)
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "slo      : attainment 75.00% (3/4 good)" in out
        # the lifecycle decomposition line rides the serving section; queue
        # waits INCLUDE sheds (the 500ms deadline victim dominates p99, same
        # as the live ddr_serve_queue_seconds histogram would show)
        assert "queue p50" in out and "execute p50" in out
        assert "queue p50 4.0ms p99 500.0ms" in out

    def test_slo_alert_transitions_render(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.serve.jsonl")
        self._append_serve(p, slo_events=True)
        assert main(["summarize", str(p)]) == 0
        out = capsys.readouterr().out
        assert "1 burn-rate alert transitions (1 firing)" in out
        assert "last: firing burn 25.0x over 60s" in out

    def test_no_slo_section_without_serve_events(self, tmp_path, capsys):
        p = _write_golden(tmp_path / "run_log.train.jsonl")
        assert main(["summarize", str(p)]) == 0
        assert "slo      :" not in capsys.readouterr().out


class TestExitCodes:
    def test_help_exits_zero(self):
        assert main(["--help"]) == 0
        assert main(["summarize", "--help"]) == 0

    def test_no_command_is_usage_error(self):
        assert main([]) == 2

    def test_missing_log_is_error(self, tmp_path):
        assert main(["summarize", str(tmp_path / "nope.jsonl")]) == 1

    def test_ddr_cli_dispatches_metrics(self):
        from ddr_tpu.cli import main as ddr_main

        assert ddr_main(["metrics", "--help"]) == 0


class TestBenchSmoke:
    def test_bench_help_exits_zero(self):
        """`bench.py --help` must print usage and exit 0 WITHOUT running the
        benchmark (and without importing jax in the parent)."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--help"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0
        assert "usage" in proc.stdout.lower()
        assert "DDR_METRICS_DIR" in proc.stdout

    def test_metrics_dir_env_helper(self, monkeypatch):
        monkeypatch.delenv("DDR_METRICS_DIR", raising=False)
        assert metrics_dir_from_env() is None
        monkeypatch.setenv("DDR_METRICS_DIR", "/tmp/x")
        assert metrics_dir_from_env() == "/tmp/x"


class TestStallDetection:
    """Stall detection: summarize's post-hoc check and follow's live watch
    both flag a run whose event stream went quiet past N x its cadence."""

    def _steps(self, walls, host=0):
        return [
            {"event": "step", "t": w - 100.0, "wall": w, "host": host, "pid": 1,
             "seq": i, "epoch": 1, "batch": i, "loss": 1.0,
             "reach_timesteps_per_sec": 10.0, "seconds": 0.5}
            for i, w in enumerate(walls)
        ]

    def test_detect_stalls_flags_quiet_host(self):
        from ddr_tpu.observability.metrics_cli import detect_stalls

        events = self._steps([100.0, 102.0, 104.0, 106.0])
        findings = detect_stalls(events, now=200.0, factor=5.0)
        assert len(findings) == 1
        (f,) = findings
        assert f["host"] == 0 and f["last_event"] == "step"
        assert f["cadence_s"] == 2.0 and f["age_s"] == 94.0
        # a healthy run (age within factor x cadence) stays quiet
        assert detect_stalls(events, now=112.0, factor=5.0) == []

    def test_run_end_means_finished_not_stalled(self):
        from ddr_tpu.observability.metrics_cli import detect_stalls

        events = self._steps([100.0, 102.0])
        events.append({"event": "run_end", "wall": 103.0, "host": 0, "status": "ok"})
        assert detect_stalls(events, now=10_000.0) == []

    def test_single_event_has_no_cadence_to_judge(self):
        from ddr_tpu.observability.metrics_cli import detect_stalls

        assert detect_stalls(self._steps([100.0]), now=10_000.0) == []

    def test_per_host_flagging(self):
        from ddr_tpu.observability.metrics_cli import detect_stalls

        events = self._steps([100.0, 102.0, 198.0, 199.8], host=0)
        events += self._steps([100.0, 102.0, 104.0], host=1)
        findings = detect_stalls(events, now=200.0, factor=5.0)
        assert [f["host"] for f in findings] == [1]  # host0 is current

    def test_heartbeats_count_as_liveness(self):
        from ddr_tpu.observability.metrics_cli import detect_stalls

        events = [
            {"event": "heartbeat", "wall": w, "host": 0, "step": i}
            for i, w in enumerate([100.0, 110.0, 120.0])
        ]
        findings = detect_stalls(events, now=500.0, factor=5.0)
        assert len(findings) == 1 and findings[0]["last_event"] == "heartbeat"

    def test_summarize_prints_stall_line(self, tmp_path):
        import io

        from ddr_tpu.observability.metrics_cli import summarize

        events = [{"event": "run_start", "wall": 99.0, "host": 0, "cmd": "train"}]
        events += self._steps([100.0, 101.0, 102.0, 103.0])
        out = io.StringIO()
        summarize(events, out=out, now=163.0)
        text = out.getvalue()
        assert "STALL?" in text and "host0" in text and "cadence" in text
        # with run_end present the same events summarize quietly
        out2 = io.StringIO()
        summarize(
            events + [{"event": "run_end", "wall": 104.0, "host": 0,
                       "status": "ok", "duration_s": 5.0}],
            out=out2, now=163.0,
        )
        assert "STALL?" not in out2.getvalue()

    def test_summarize_cli_stall_factor_flag(self, tmp_path):
        p = tmp_path / "run_log.train.jsonl"
        lines = [json.dumps(e) for e in self._steps([100.0, 101.0, 102.0])]
        p.write_text("\n".join(lines) + "\n")
        # enormous factor: even an ancient log is "current"
        assert main(["summarize", str(p), "--stall-factor", "1e18"]) == 0

    def test_follow_warns_once_on_silence_and_rearms(self, tmp_path, monkeypatch):
        import io

        from ddr_tpu.observability import metrics_cli

        p = tmp_path / "run_log.train.jsonl"
        p.write_text("")
        clock = {"t": 1000.0}
        monkeypatch.setattr(metrics_cli.time, "monotonic", lambda: clock["t"])
        polls = {"n": 0}

        def fake_sleep(_s):
            # advance the fake clock 1s per poll; append one event on the
            # first three polls (cadence ~1s), then go silent
            polls["n"] += 1
            clock["t"] += 1.0
            if polls["n"] <= 3:
                ev = {"event": "step", "t": polls["n"], "wall": polls["n"],
                      "host": 0, "pid": 1, "seq": polls["n"], "loss": 1.0}
                with p.open("a") as f:
                    f.write(json.dumps(ev) + "\n")

        monkeypatch.setattr(metrics_cli.time, "sleep", fake_sleep)
        out = io.StringIO()
        metrics_cli.follow(p, out=out, max_polls=12, stall_factor=3.0)
        text = out.getvalue()
        assert text.count("STALL?") == 1  # warned once, not every poll
        assert "cadence" in text
